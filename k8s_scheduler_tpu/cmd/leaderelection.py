"""Leader election for active/standby HA (SURVEY.md §5.3).

The reference family elects a leader through apiserver Lease objects
(`leaderElection` in KubeSchedulerConfiguration): the active scheduler
renews a lease; standbys watch it and take over when it expires. Without an
apiserver, the shim's equivalent coordination point is a lease FILE on
shared storage: fcntl byte-range locks give the atomic acquire, and a
heartbeat timestamp written under the lock gives standbys the expiry
signal. The scheduler itself stays stateless either way — a standby that
takes over rebuilds all state from the agent's re-list (§5.3), so
correctness never depends on the lease (at worst two actives emit
conflicting bindings briefly; the cluster store's optimistic concurrency —
or the agent applying one — arbitrates, as upstream).
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import threading
import time as _time
from typing import Callable

log = logging.getLogger("k8s_scheduler_tpu.cmd")


class FileLease:
    """flock-based lease with heartbeat renewal.

    acquire() blocks until leadership is won (or `timeout` elapses). The
    holder renews by rewriting the heartbeat every `renew_seconds`; a
    holder that stops renewing (crash, hang) loses the flock when its
    process dies, letting a standby in immediately — the heartbeat is
    advisory metadata for observability, the kernel lock is the truth.
    """

    # POSIX record locks are per-process (two locks in one process never
    # conflict) and are dropped when the process closes ANY fd for the
    # file. This registry restores flock-like semantics inside a process:
    # try_acquire of an already-held path fails, and holder() reads through
    # the holder's own fd instead of open()+close()-ing a second one (which
    # would silently release the lock).
    _held_lock = threading.Lock()
    _held: dict[str, "FileLease"] = {}

    def __init__(
        self,
        path: str,
        identity: str = "",
        renew_seconds: float = 2.0,
    ) -> None:
        self.path = path
        self.identity = identity or f"pid-{os.getpid()}"
        self.renew_seconds = renew_seconds
        self._fd: int | None = None
        self._stop = threading.Event()
        self._renewer: threading.Thread | None = None

    def _key(self) -> str:
        return os.path.realpath(self.path)

    # ---- acquisition -----------------------------------------------------

    def try_acquire(self) -> bool:
        with FileLease._held_lock:
            if self._key() in FileLease._held:
                return False
            fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
            try:
                # POSIX byte-range lock (not flock): NFS and other shared
                # filesystems propagate these, so the election holds
                # across hosts — the deployment the module exists for
                fcntl.lockf(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                return False
            self._fd = fd
            FileLease._held[self._key()] = self
        self._write_heartbeat()
        return True

    def acquire(self, timeout: float | None = None,
                poll_seconds: float = 0.5) -> bool:
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and _time.monotonic() >= deadline:
                return False
            _time.sleep(poll_seconds)

    def start_renewing(self) -> None:
        self._renewer = threading.Thread(
            target=self._renew_loop, name="lease-renewer", daemon=True
        )
        self._renewer.start()

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.renew_seconds):
            self._write_heartbeat()

    def _write_heartbeat(self) -> None:
        if self._fd is None:
            return
        payload = json.dumps(
            {
                "holderIdentity": self.identity,
                "renewTime": _time.time(),
                "leaseDurationSeconds": self.renew_seconds * 3,
            }
        ).encode()
        os.lseek(self._fd, 0, os.SEEK_SET)
        os.truncate(self._fd, 0)
        os.write(self._fd, payload)

    # ---- observation (standbys / operators) ------------------------------

    def holder(self) -> dict | None:
        """Read the advisory heartbeat (None if no lease file/content)."""
        try:
            with FileLease._held_lock:
                held = FileLease._held.get(self._key())
                if held is not None and held._fd is not None:
                    # this process holds the lock: read through the
                    # holder's fd — opening+closing another fd for the
                    # file would drop the POSIX lock
                    data = os.pread(self._fd or held._fd, 65536, 0)
                    return json.loads(data) if data else None
            with open(self.path, "rb") as f:
                data = f.read()
            return json.loads(data) if data else None
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def is_leader(self) -> bool:
        return self._fd is not None

    def lease_age_seconds(self) -> float:
        """Seconds since the advisory heartbeat was last renewed (0.0
        when no heartbeat is readable). For the holder this tracks its
        own renew cadence; for a standby it grows past
        leaseDurationSeconds when the active stops renewing — the
        failover signal the scheduler_leader_lease_age_seconds gauge
        exports."""
        info = self.holder()
        if not info or "renewTime" not in info:
            return 0.0
        return max(0.0, _time.time() - float(info["renewTime"]))

    def describe(self) -> dict:
        """Lease identity/age view for /healthz and dashboards."""
        info = self.holder() or {}
        return {
            "leader": self.is_leader(),
            "holder": info.get("holderIdentity", ""),
            "age_s": round(self.lease_age_seconds(), 3),
            "lease_duration_s": info.get("leaseDurationSeconds"),
            "path": self.path,
        }

    def release(self) -> None:
        self._stop.set()
        if self._renewer is not None:
            # shutdown join (the CompileWarmer drain-exit discipline,
            # schedlint TR003): the renewer wakes from its stop-Event
            # wait immediately, so 5s only ever elapses when a
            # heartbeat write is wedged on dead shared storage — then
            # say so instead of silently dropping the thread
            self._renewer.join(timeout=5)
            if self._renewer.is_alive():
                log.warning(
                    "lease renewer failed to exit within 5s of "
                    "release() (heartbeat write wedged?); abandoning "
                    "the daemon thread — the kernel lock below is "
                    "still released"
                )
            self._renewer = None
        with FileLease._held_lock:
            if self._fd is not None:
                fcntl.lockf(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
                self._fd = None
                if FileLease._held.get(self._key()) is self:
                    del FileLease._held[self._key()]


def run_with_leader_election(
    lease: FileLease,
    run: Callable[[], None],
    on_started_leading: Callable[[], None] | None = None,
) -> None:
    """Block until leadership, then run (upstream leaderElection.Run)."""
    lease.acquire()
    lease.start_renewing()
    if on_started_leading is not None:
        on_started_leading()
    try:
        run()
    finally:
        lease.release()
