"""Process entry: flags, config load, serving, leader election.

The analogue of the reference's `NewSchedulerCommand`/`Run` (SURVEY.md §2
C1, §3.1): parse flags, load the KubeSchedulerConfiguration-shaped YAML,
start the health/metrics HTTP endpoints, optionally win a leader lease,
then run the gRPC shim that the cluster agent talks to.

    python -m k8s_scheduler_tpu \
        --config scheduler.yaml --address 127.0.0.1:50051 --http-port 10251
"""

from __future__ import annotations

import argparse
import signal
import threading

from ..config import SchedulerConfiguration, load_config
from .httpserver import start_http_server, stop_http_server
from .leaderelection import FileLease


def new_scheduler_command() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="k8s-scheduler-tpu",
        description="TPU-native scheduling service (kube-scheduler-"
        "compatible semantics; snapshot in, bindings out over gRPC)",
    )
    ap.add_argument(
        "--config", default="", help="KubeSchedulerConfiguration-style YAML"
    )
    ap.add_argument(
        "--address", default="127.0.0.1:50051", help="gRPC bind address"
    )
    ap.add_argument(
        "--http-port", type=int, default=10251,
        help="/healthz + /metrics port (0 = ephemeral, -1 = disabled)",
    )
    ap.add_argument(
        "--http-host", default="127.0.0.1", help="/healthz + /metrics host"
    )
    ap.add_argument(
        "--leader-elect", action="store_true",
        help="block on the lease file until elected (active/standby HA)",
    )
    ap.add_argument(
        "--leader-elect-lease-file", default="/tmp/k8s-scheduler-tpu.lease",
        help="shared lease file used for election",
    )
    ap.add_argument(
        "--profile-every", type=int, default=0,
        help="every N cycles, run the per-plugin profiling pass (0 = off)",
    )
    ap.add_argument(
        "--forced-sync", action="store_true",
        help="block every cycle dispatch to completion (disables the "
        "split-phase serving pipeline's overlap; for debugging and "
        "latency measurement — results are identical either way)",
    )
    ap.add_argument(
        "--flight-record-n", type=int, default=-1,
        help="cycle flight-recorder ring capacity (per-cycle phase "
        "records behind /debug/flightrecorder, /debug/trace and the "
        "derived pipeline gauges); 0 disables, -1 = keep config "
        "flightRecorderSize (default 512)",
    )
    ap.add_argument(
        "--trace-dir", default="",
        help="on shutdown, dump the flight recorder's full ring as a "
        "Chrome-trace/Perfetto JSON into this directory (live download: "
        "/debug/trace?last=N)",
    )
    ap.add_argument(
        "--health-max-cycle-age", type=float, default=-1.0,
        help="/healthz reports 503 when no scheduling cycle completed "
        "within this many seconds (staleness from the flight recorder; "
        "0 disables, -1 = keep config healthMaxCycleAge)",
    )
    ap.add_argument(
        "--pad-ma", type=int, default=0,
        help="pre-size the sticky per-pod affinity-term pad (MA) so a "
        "mid-serving arrival of a many-term pod cannot flip the packed "
        "regime (overrides config padMa; 0 = keep config)",
    )
    ap.add_argument(
        "--pad-mc", type=int, default=0,
        help="pre-size the sticky per-pod topology-spread-constraint pad "
        "(MC) the same way (overrides config padMc; 0 = keep config)",
    )
    ap.add_argument(
        "--multi-cycle-k", type=int, default=0,
        help="multi-cycle on-device serving: coalesce up to K arrival "
        "groups into one device dispatch running K scheduling cycles in "
        "a device-resident loop (amortizes the dispatch round trip "
        "K-fold for small-delta cycles; config multiCycleK; 1 disables, "
        "0 = keep config). Workloads outside the exactness envelope "
        "fall back to sequential single-cycle dispatches",
    )
    ap.add_argument(
        "--multi-cycle-max-wait-ms", type=float, default=-1.0,
        help="latency bound on the multi-cycle coalescing buffer: a "
        "delta group is never held back longer than this many ms "
        "waiting for the batch to fill (config multiCycleMaxWaitMs; "
        "-1 = keep config)",
    )
    ap.add_argument(
        "--slo-p99-ms", type=float, default=-1.0,
        help="latency SLO objective: at most 1%% of cycles in the "
        "sloWindowCycles window may exceed this many milliseconds of "
        "cycle wall time; drives scheduler_slo_burn_rate{window}, "
        "scheduler_slo_budget_remaining and the /healthz degraded flag "
        "(config sloP99Ms; 0 disables, -1 = keep config)",
    )
    ap.add_argument(
        "--pad-hysteresis-pct", type=float, default=-1.0,
        help="regime hysteresis: a shrinking pod/node count only steps "
        "the pad bucket DOWN when it leaves at least this many percent "
        "of headroom inside the smaller bucket, so an oscillating "
        "workload holds the larger (already-compiled) regime instead "
        "of flip-flopping (config padHysteresisPct; 0 disables, "
        "-1 = keep config)",
    )
    ap.add_argument(
        "--compile-cache-dir", default="",
        help="persistent compiled-program cache directory (config "
        "compileCacheDir): AOT-compiled executables keyed by pad "
        "regime + profile + program kind + jaxlib/backend fingerprint, "
        "so a warm restart compiles zero programs for previously-seen "
        "regimes. Empty = <stateDir>/compile_cache when --state-dir is "
        "set, else disabled; 'off' disables even with a state dir",
    )
    ap.add_argument(
        "--shard-devices", type=int, default=-1,
        help="shard the device-resident carry over a 1-D pods mesh of "
        "this many local devices (config shardDevices); placements "
        "stay bit-identical to the single-device run (shard-invariant "
        "tie-breaking). 0/1 = single device, -1 = keep config",
    )
    ap.add_argument(
        "--speculative-compile", type=int, default=-1, choices=(-1, 0, 1),
        help="background pre-compilation of the adjacent pad regime on "
        "a warm thread when demand drifts toward a bucket boundary "
        "(config speculativeCompile; 1 on, 0 off, -1 = keep config)",
    )
    ap.add_argument(
        "--speculative-dispatch", type=int, default=-1, choices=(-1, 0, 1),
        help="depth-2 speculative dispatch pipelining: while multi-cycle "
        "batch k is on device, dispatch batch k+1 against the predicted "
        "post-k carry; adopted on a predicate match, abandoned and "
        "re-dispatched on a mismatch — results are bit-identical either "
        "way. Forced off under --forced-sync and at/below the ladder's "
        "sequential rung (config speculativeDispatch; 1 on, 0 off, "
        "-1 = keep config)",
    )
    ap.add_argument(
        "--incremental-encode", type=int, default=-1, choices=(-1, 0, 1),
        help="admission-time incremental encode: parse each buffered pod "
        "into staged row data at multi-cycle buffer time (the ack "
        "path's shadow) so the flush encode is an O(dirty) finalize "
        "over pre-parsed rows; falls back to a full rebuild on "
        "interning-table growth or a pad-regime flip, bit-identical "
        "either way (config incrementalEncode; 1 on, 0 off, "
        "-1 = keep config)",
    )
    ap.add_argument(
        "--dispatch-deadline-ms", type=float, default=-1.0,
        help="dispatch watchdog: bound on the blocking per-cycle "
        "decision fetch in milliseconds — on expiry the fetch is "
        "abandoned, the cycle's pods requeue, and the degradation "
        "ladder steps down a rung (config dispatchDeadlineMs; "
        "0 disables, -1 = keep config)",
    )
    ap.add_argument(
        "--degrade-promote-cycles", type=int, default=0,
        help="degradation ladder: consecutive clean cycles before the "
        "ladder steps one rung back up toward normal (config "
        "degradePromoteCycles; 0 = keep config)",
    )
    ap.add_argument(
        "--fault-spec", default="",
        help="fault injection plan, e.g. 'fetch_hang@cycle=40:ms=5000' "
        "(config faultSpec; env SCHED_FAULTS also read when both are "
        "empty) — soaks/benches/tests only, never production",
    )
    ap.add_argument(
        "--submit-addr", default="",
        help="submission front door: serve the admission-controlled "
        "Submit/NodeChurn RPCs on this extra gRPC address (own accept "
        "queue + worker pool) and run the internal serve loop — "
        "arrivals coalesce straight into the multi-cycle batcher "
        "instead of waiting for agent-driven Cycle RPCs. Accepted "
        "pods are journaled through the WAL before the ack returns "
        "when --state-dir is set. Empty = front door disabled",
    )
    ap.add_argument(
        "--admission-queue-depth", type=int, default=-1,
        help="bound on the admission queue (pending pods + coalescing "
        "buffers): a Submit that would push the depth past this is "
        "shed with RESOURCE_EXHAUSTED + retry-after instead of "
        "buffered (config admissionQueueDepth; 0 = unbounded, "
        "-1 = keep config)",
    )
    ap.add_argument(
        "--state-dir", default="",
        help="durable scheduler state: write-ahead journal + snapshots "
        "of the queue/cache live here (config stateDir). A process "
        "starting against a non-empty dir — e.g. a standby that just "
        "won the lease — restores the exact pre-crash state before its "
        "first cycle. Empty = durability disabled",
    )
    ap.add_argument(
        "--snapshot-interval", type=float, default=-1.0,
        help="seconds between journal-compacting snapshots (config "
        "snapshotInterval; 0 = journal only, -1 = keep config)",
    )
    ap.add_argument(
        "--trace-sample-rate", type=float, default=-1.0,
        help="pod-lifecycle tracing: head-sampling probability for "
        "submissions arriving without a traceparent (deterministic "
        "per pod uid; an explicit traceparent always samples). Spans "
        "serve at /debug/traces and join /debug/explain (config "
        "traceSampleRate, default 1/64; 0 disables tracing, "
        "-1 = keep config)",
    )
    ap.add_argument(
        "--trace-export-dir", default="",
        help="on shutdown, dump the span ring as OTLP-JSON "
        "(spans-NNNNNN.json) into this directory for external "
        "ingestion; repeated runs append the next file and the "
        "directory is size-rotated (oldest dumps deleted past 64 MB). "
        "Empty = no OTLP export (spans still serve at /debug/traces)",
    )
    ap.add_argument(
        "--metrics-history-samples", type=int, default=-1,
        help="watchtower: per-series raw ring capacity of the "
        "in-process metrics history TSDB; arming it also evaluates "
        "the built-in alert rule pack and serves "
        "/debug/metrics/history, /debug/alerts and /debug/dashboard "
        "(config metricsHistorySamples, default 512; 0 disables the "
        "watchtower, -1 = keep config)",
    )
    ap.add_argument(
        "--alert-rules-file", default="",
        help="extra alert/recording rules (YAML/JSON list, the "
        "metrics/rules.py shape) appended to the built-in pack "
        "(config alertRulesFile; empty = built-ins only)",
    )
    ap.add_argument(
        "--blackbox-retention", type=int, default=-1,
        help="crash black box: post-mortem bundles kept under "
        "<stateDir>/blackbox/ — dumped on SIGTERM, degrade-to-"
        "stateless, watchdog aborts and serve-loop faults; read them "
        "with scripts/blackbox_read.py (config blackboxRetention, "
        "default 8; 0 disables, -1 = keep config; needs --state-dir)",
    )
    return ap


def main(argv: list[str] | None = None) -> int:
    args = new_scheduler_command().parse_args(argv)
    config = (
        load_config(args.config) if args.config else SchedulerConfiguration()
    )
    if args.pad_ma:
        config.pad_ma = args.pad_ma
    if args.pad_mc:
        config.pad_mc = args.pad_mc
    if args.forced_sync:
        config.forced_sync = True
    if args.flight_record_n >= 0:
        config.flight_recorder_size = args.flight_record_n
    if args.health_max_cycle_age >= 0:
        config.health_max_cycle_age_seconds = args.health_max_cycle_age
    if args.slo_p99_ms >= 0:
        config.slo_p99_ms = args.slo_p99_ms
    if args.multi_cycle_k > 0:
        config.multi_cycle_k = args.multi_cycle_k
    if args.multi_cycle_max_wait_ms >= 0:
        config.multi_cycle_max_wait_ms = args.multi_cycle_max_wait_ms
    if args.pad_hysteresis_pct >= 0:
        config.pad_hysteresis_pct = args.pad_hysteresis_pct
    if args.compile_cache_dir:
        config.compile_cache_dir = args.compile_cache_dir
    if args.shard_devices >= 0:
        config.shard_devices = args.shard_devices
    if args.speculative_compile >= 0:
        config.speculative_compile = bool(args.speculative_compile)
    if args.speculative_dispatch >= 0:
        config.speculative_dispatch = bool(args.speculative_dispatch)
    if args.incremental_encode >= 0:
        config.incremental_encode = bool(args.incremental_encode)
    if args.dispatch_deadline_ms >= 0:
        config.dispatch_deadline_ms = args.dispatch_deadline_ms
    if args.degrade_promote_cycles > 0:
        config.degrade_promote_cycles = args.degrade_promote_cycles
    if args.fault_spec:
        config.fault_spec = args.fault_spec
    if args.admission_queue_depth >= 0:
        config.admission_queue_depth = args.admission_queue_depth
    if args.state_dir:
        config.state_dir = args.state_dir
    if args.snapshot_interval >= 0:
        config.snapshot_interval_seconds = args.snapshot_interval
    if args.trace_sample_rate >= 0:
        config.trace_sample_rate = args.trace_sample_rate
    if args.metrics_history_samples >= 0:
        config.metrics_history_samples = args.metrics_history_samples
    if args.alert_rules_file:
        config.alert_rules_file = args.alert_rules_file
    if args.blackbox_retention >= 0:
        config.blackbox_retention = args.blackbox_retention
    if (
        config.health_max_cycle_age_seconds > 0
        and config.flight_recorder_size <= 0
    ):
        # contradictory config: the staleness deadline reads the flight
        # recorder's last-cycle age — with the recorder disabled it
        # would be silently inert and /healthz would report 200 while
        # wedged, the exact failure the deadline exists to catch
        raise SystemExit(
            "--health-max-cycle-age/healthMaxCycleAge requires the "
            "flight recorder (--flight-record-n/flightRecorderSize > 0)"
        )

    # multi-host (DCN) runtime: a no-op unless the launcher set the JAX
    # coordinator env vars (parallel/mesh.py initialize_distributed)
    from ..parallel.mesh import initialize_distributed
    from ..utils.compilation_cache import enable_compilation_cache

    # persistent XLA cache: a restarted (or failed-over) scheduler reuses
    # compiled cycle programs instead of paying the 100s+ first compile
    enable_compilation_cache()

    initialize_distributed()

    # the shim owns the Scheduler; import deferred so --help stays instant
    from ..service.server import serve

    lease = None
    if args.leader_elect:
        lease = FileLease(args.leader_elect_lease_file)
        print(
            f"waiting for leader lease {args.leader_elect_lease_file} ...",
            flush=True,
        )
        lease.acquire()
        lease.start_renewing()
        print("became leader", flush=True)

    # serve the PROCESS-WIDE registry: process-level counters that never
    # reach a Scheduler handle (program retry strikes from _Resilient)
    # must appear on /metrics. Library/test constructions get a fresh
    # registry by default — only the CLI opts into the global one.
    from ..metrics.metrics import global_metrics

    gm = global_metrics()

    # build identity: one constant-1 gauge stamped at startup so
    # dashboards can correlate latency shifts with binary/runtime
    # changes (bench headlines carry the same fingerprint)
    from ..metrics.metrics import build_fingerprint

    fp = build_fingerprint()
    gm.set_build_info(fp)
    print(
        "build: "
        + " ".join(f"{k}={v}" for k, v in sorted(fp.items())),
        flush=True,
    )

    # leader gauges evaluate at scrape so a failover is visible the
    # moment it happens, not at the next heartbeat write
    gm.leader_state.set_function(
        lambda: 1.0 if (lease.is_leader() if lease else True) else 0.0
    )
    gm.leader_lease_age.set_function(
        lambda: lease.lease_age_seconds() if lease else 0.0
    )

    # durable state: created AFTER the lease is won — a standby must not
    # touch (or journal into) the shared state dir while the active owns
    # it. Scheduler.__init__ restores snapshot+tail before its first
    # cycle, so a takeover resumes with the dead active's exact queue/
    # cache state instead of an empty rebuild.
    state = None
    if config.state_dir:
        from ..state import DurableState

        state = DurableState(
            config.state_dir,
            snapshot_interval_seconds=config.snapshot_interval_seconds,
            metrics=gm,
        )

    server, service, port = serve(
        args.address,
        config=config,
        profile_every=args.profile_every,
        metrics=gm,
        state=state,
    )
    print(f"scheduler shim listening on port {port}", flush=True)

    # submission front door: the admission-controlled Submit/NodeChurn
    # RPCs on their own address (own accept queue + worker pool, so a
    # flood of submissions cannot starve the agent channel) plus the
    # internal serve loop — with a network feed there is no agent to
    # drive Cycle, so the scheduler runs its own ScheduleOne loop,
    # serialized against any stray Cycle RPC by the service cycle lock.
    front_door = None
    submit_server = None
    spans_recorder = None
    if args.submit_addr:
        from concurrent import futures as _futures

        import grpc as _grpc

        from ..service.admission import self_confirming_front_door
        from ..service.server import add_to_server

        # pod-lifecycle tracing: armed BEFORE the front door starts so
        # the very first submission can be sampled. Only the front-door
        # path mints trace contexts (Submit is where a pod's lifecycle
        # begins), so agent-driven runs skip the armed cost entirely.
        if config.trace_sample_rate > 0:
            from ..core import spans as _spans

            spans_recorder = _spans.arm(
                rate=config.trace_sample_rate,
                counter=(
                    lambda name: gm.trace_spans.labels(name=name).inc()
                ),
            )
            print(
                "tracing armed: sample rate "
                f"{config.trace_sample_rate:g} "
                "(/debug/traces, /debug/explain)",
                flush=True,
            )

        admission = service.enable_front_door()
        submit_server = _grpc.server(
            _futures.ThreadPoolExecutor(max_workers=8),
            options=(("grpc.so_reuseport", 0),),
        )
        add_to_server(service, submit_server)
        sport = submit_server.add_insecure_port(args.submit_addr)
        if sport == 0 and not args.submit_addr.rstrip().endswith(":0"):
            raise OSError(
                f"failed to bind submit address {args.submit_addr!r}"
            )
        submit_server.start()
        # self-confirming: the local loop is the binder of record (no
        # agent fetches bindings in this mode) — without post-cycle
        # confirmation every assumed bind would TTL-expire and re-bind
        front_door = self_confirming_front_door(service, admission)
        front_door.start()
        print(
            f"front door: submissions on port {sport} "
            f"(admission depth {admission.depth_bound})",
            flush=True,
        )

    if state is not None:
        r = state.last_restore
        print(
            "durable state: restored "
            f"snapshot={r.get('snapshot')} "
            f"replayed={r.get('records_replayed')} records "
            f"pending={r.get('pending')} cache={r.get('cache')}",
            flush=True,
        )

    # health is no longer a static closure: staleness comes from the
    # flight recorder, so a scheduler that stopped completing cycles
    # (wedged device, deadlocked loop) flips /healthz to 503 instead of
    # reporting healthy forever
    from .httpserver import staleness_healthz

    recorder = service.scheduler.flight
    observer = service.scheduler.observer
    healthz = staleness_healthz(
        lambda: {
            "bootId": service.boot_id,
            "leader": lease.is_leader() if lease else True,
            # lease identity + heartbeat age so probes/dashboards see
            # WHO leads and how fresh the lease is, not just a boolean
            **({"lease": lease.describe()} if lease else {}),
            "pending": service.scheduler.queue.pending_counts(),
        },
        recorder,
        config.health_max_cycle_age_seconds,
        observer=observer,
        ladder=service.scheduler.ladder,
        admission=service.admission,
    )

    # the watchtower (metrics history + alert rules): armed only by
    # the CLI, like tracing — library/test constructions pay one
    # module-flag check at the flight-recorder hook and nothing else
    tsdb_store = None
    alert_engine = None
    if config.metrics_history_samples > 0:
        from ..metrics import tsdb as _tsdb
        from ..metrics.rules import (
            RuleEngine,
            builtin_rules,
            load_rules_file,
        )

        tsdb_store = _tsdb.arm(
            raw_cap=config.metrics_history_samples
        )
        rules = builtin_rules()
        if config.alert_rules_file:
            rules += load_rules_file(config.alert_rules_file)
        alert_engine = RuleEngine(
            rules,
            tsdb_store,
            observer=observer,
            events=service.scheduler.events,
            metrics=gm,
        )
        tsdb_store.engine = alert_engine
        if recorder is not None:
            recorder.observers.append(tsdb_store.observe_record)
        tsdb_store.start_ticker(
            gm.registry, interval_s=config.metrics_ticker_seconds
        )
        print(
            "watchtower armed: "
            f"{len(rules)} rules, history {config.metrics_history_samples} "
            f"raw samples/series, ticker {config.metrics_ticker_seconds:g}s "
            "(/debug/metrics/history, /debug/alerts, /debug/dashboard)",
            flush=True,
        )

    # crash black box: bundles dump at the moment of the trigger
    # (degrade-to-stateless, watchdog abort, serve-loop fault), not at
    # exit — a later kill -9 still finds the bundle on disk
    blackbox_box = None
    if config.state_dir and config.blackbox_retention > 0:
        import os as _os

        from ..core import blackbox as _bb
        from ..config.types import to_dict as _config_to_dict

        blackbox_box = _bb.arm(_bb.BlackBox(
            _os.path.join(config.state_dir, "blackbox"),
            retention=config.blackbox_retention,
            config=_config_to_dict(config),
            recorder=recorder,
            observer=observer,
            spans_recorder=spans_recorder,
            tsdb=tsdb_store,
            engine=alert_engine,
            ladder=service.scheduler.ladder,
            fault_plan=getattr(service.scheduler, "_fault_plan", None),
            events=service.scheduler.events,
        ))
        print(
            f"black box armed: {blackbox_box.directory} "
            f"(retention {blackbox_box.retention})",
            flush=True,
        )

    http_server = None
    if args.http_port >= 0:
        http_server = start_http_server(
            service.scheduler.metrics,
            port=args.http_port,
            host=args.http_host,
            healthz=healthz,
            recorder=recorder,
            pod_timeline=service.scheduler.pod_timeline,
            state=state,
            observer=observer,
            admission=service.admission,
            spans_recorder=spans_recorder,
            tsdb=tsdb_store,
            alerts=alert_engine,
            dashboard=config.debug_dashboard,
        )
        print(
            "serving /healthz /metrics on port "
            f"{http_server.server_address[1]}",
            flush=True,
        )

    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    try:
        stop.wait()
    finally:
        if blackbox_box is not None:
            # FIRST in shutdown: the sigterm bundle captures the rings
            # before the drains below start mutating them
            from ..core import blackbox as _bb

            bpath = _bb.trigger("sigterm", "clean shutdown")
            if bpath:
                print(f"black box dumped: {bpath}", flush=True)
        if front_door is not None:
            # graceful drain BEFORE anything seals: admission closes
            # (late submits answer UNAVAILABLE "draining"), buffered
            # multi-cycle groups flush, the active tier empties — no
            # pod stranded between ack and dispatch — then the loop
            # thread joins
            drained = front_door.stop()
            print(
                f"front door drained: {drained} "
                f"(cycles {front_door.cycles})",
                flush=True,
            )
        if submit_server is not None:
            submit_server.stop(grace=1.0)
        server.stop(grace=2.0)
        if http_server is not None:
            # shutdown + JOIN + close, not a bare shutdown(): the serve
            # thread must be drained before the lease release below
            # hands the socket's port story to a successor
            stop_http_server(http_server)
        if state is not None:
            # seal the journal: a final clean-shutdown snapshot (same
            # pattern as the --trace-dir dump below) so the next start
            # — or the standby about to win the lease — restores from
            # one file with an empty tail. Guarded: a failing seal
            # (disk full) must not abort the rest of shutdown — the
            # journal tail already written is the fallback.
            try:
                state.seal()
                print(
                    "durable state sealed: "
                    f"{state.last_snapshot.get('path')}",
                    flush=True,
                )
            except Exception as e:
                print(f"durable state seal FAILED: {e}", flush=True)
        if args.trace_dir and recorder is not None:
            # post-mortem trace: the full ring as one Perfetto-loadable
            # file (same payload as /debug/trace, taken at shutdown)
            import json
            import os
            import time as _t

            from ..core.flight_recorder import to_chrome_trace

            os.makedirs(args.trace_dir, exist_ok=True)
            path = os.path.join(
                args.trace_dir, f"scheduler-trace-{int(_t.time())}.json"
            )
            with open(path, "w") as f:
                json.dump(
                    to_chrome_trace(
                        recorder.snapshot(),
                        epoch=recorder.epoch,
                        # pod-trace tracks merged into the cycle lanes
                        # when tracing was armed this run
                        spans=(
                            spans_recorder.snapshot()
                            if spans_recorder is not None
                            else None
                        ),
                    ),
                    f,
                )
            print(f"flight-recorder trace written to {path}", flush=True)
        if spans_recorder is not None:
            from ..core import spans as _spans

            if args.trace_export_dir:
                # post-mortem OTLP dump (same pattern as --trace-dir):
                # guarded — a failing export must not abort shutdown
                try:
                    opath = _spans.export_otlp_dir(
                        spans_recorder, args.trace_export_dir
                    )
                    if opath:
                        print(
                            f"OTLP span export written to {opath}",
                            flush=True,
                        )
                except Exception as e:  # schedlint: disable=RB001 -- best-effort shutdown dump
                    print(f"OTLP span export FAILED: {e}", flush=True)
            _spans.disarm()
        if tsdb_store is not None:
            # stops the ticker thread and detaches the cycle hook's
            # flag; the store object itself stays readable (the sigterm
            # bundle above already captured it)
            from ..metrics import tsdb as _tsdb

            _tsdb.disarm()
        if blackbox_box is not None:
            from ..core import blackbox as _bb

            _bb.disarm()
        if lease is not None:
            lease.release()
    return 0
