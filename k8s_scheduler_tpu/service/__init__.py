from .client import SchedulerAgent, SchedulerClient
from .server import SchedulerService, add_to_server, serve

__all__ = [
    "SchedulerAgent",
    "SchedulerClient",
    "SchedulerService",
    "add_to_server",
    "serve",
]
