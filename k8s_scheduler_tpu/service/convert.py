"""proto <-> models.api converters for the gRPC shim.

Quantities travel as Kubernetes Quantity strings and are normalized here
(cpu -> millicores, bytes elsewhere) exactly like the JSON constructors in
models/api.py — the two wire formats are interchangeable."""

from __future__ import annotations

from ..models import api
from . import scheduler_pb2 as pb


# ---- proto -> api ----------------------------------------------------------


def _req_from(r: pb.LabelSelectorRequirement) -> api.NodeSelectorRequirement:
    return api.NodeSelectorRequirement(r.key, r.operator, tuple(r.values))


def _term_from(t: pb.NodeSelectorTerm) -> api.NodeSelectorTerm:
    return api.NodeSelectorTerm(
        match_expressions=tuple(_req_from(e) for e in t.match_expressions),
        match_fields=tuple(_req_from(e) for e in t.match_fields),
    )


def _selector_from(s: pb.LabelSelector) -> api.LabelSelector:
    return api.LabelSelector(
        match_labels=dict(s.match_labels),
        match_expressions=tuple(_req_from(e) for e in s.match_expressions),
    )


def _aff_term_from(t: pb.PodAffinityTerm) -> api.PodAffinityTerm:
    return api.PodAffinityTerm(
        label_selector=_selector_from(t.label_selector),
        topology_key=t.topology_key,
        namespaces=tuple(t.namespaces),
    )


def _pod_aff_from(p: pb.PodAffinity, cls):
    return cls(
        required=tuple(_aff_term_from(t) for t in p.required),
        preferred=tuple(
            api.WeightedPodAffinityTerm(w.weight, _aff_term_from(w.term))
            for w in p.preferred
        ),
    )


def affinity_from(a: pb.Affinity) -> api.Affinity | None:
    has_na = a.HasField("node_affinity")
    has_pa = a.HasField("pod_affinity")
    has_pan = a.HasField("pod_anti_affinity")
    if not (has_na or has_pa or has_pan):
        return None
    na = None
    if has_na:
        na = api.NodeAffinity(
            required=tuple(_term_from(t) for t in a.node_affinity.required),
            preferred=tuple(
                api.PreferredSchedulingTerm(p.weight, _term_from(p.preference))
                for p in a.node_affinity.preferred
            ),
        )
    pa = _pod_aff_from(a.pod_affinity, api.PodAffinity) if has_pa else None
    pan = (
        _pod_aff_from(a.pod_anti_affinity, api.PodAntiAffinity)
        if has_pan
        else None
    )
    return api.Affinity(node_affinity=na, pod_affinity=pa, pod_anti_affinity=pan)


def meta_from(m: pb.ObjectMeta) -> api.ObjectMeta:
    return api.ObjectMeta(
        name=m.name,
        namespace=m.namespace or "default",
        uid=m.uid,
        labels=dict(m.labels),
        annotations=dict(m.annotations),
        creation_timestamp=m.creation_timestamp,
    )


def pod_from(p: pb.Pod) -> api.Pod:
    s = p.spec
    containers = tuple(
        api.Container.make(
            c.name or "main",
            c.image,
            dict(c.requests),
            tuple(
                api.ContainerPort(
                    container_port=cp.container_port,
                    host_port=cp.host_port,
                    protocol=cp.protocol or "TCP",
                    host_ip=cp.host_ip,
                )
                for cp in c.ports
            ),
        )
        for c in s.containers
    )
    return api.Pod(
        metadata=meta_from(p.metadata),
        spec=api.PodSpec(
            containers=containers,
            node_name=s.node_name,
            node_selector=dict(s.node_selector),
            affinity=affinity_from(s.affinity) if s.HasField("affinity") else None,
            tolerations=tuple(
                api.Toleration(t.key, t.operator or "Equal", t.value, t.effect)
                for t in s.tolerations
            ),
            topology_spread_constraints=tuple(
                api.TopologySpreadConstraint(
                    max_skew=c.max_skew,
                    topology_key=c.topology_key,
                    when_unsatisfiable=c.when_unsatisfiable,
                    label_selector=_selector_from(c.label_selector),
                )
                for c in s.topology_spread_constraints
            ),
            priority=s.priority,
            priority_class_name=s.priority_class_name,
            preemption_policy=s.preemption_policy or "PreemptLowerPriority",
            scheduler_name=s.scheduler_name or "default-scheduler",
            overhead=api._req_to_internal(dict(s.overhead)),
            pod_group=s.pod_group,
            volumes=tuple(s.volumes),
        ),
        nominated_node_name=p.nominated_node_name,
    )


def pvc_from(c: pb.PersistentVolumeClaim) -> api.PersistentVolumeClaim:
    return api.PersistentVolumeClaim(
        name=c.name,
        namespace=c.namespace or "default",
        storage_class=c.storage_class,
        request=c.request,
        volume_name=c.volume_name,
    )


def pvc_to(c: api.PersistentVolumeClaim) -> pb.PersistentVolumeClaim:
    return pb.PersistentVolumeClaim(
        name=c.name,
        namespace=c.namespace,
        storage_class=c.storage_class,
        request=c.request,
        volume_name=c.volume_name,
    )


def pv_from(v: pb.PersistentVolume) -> api.PersistentVolume:
    return api.PersistentVolume(
        name=v.name,
        capacity=v.capacity,
        storage_class=v.storage_class,
        node_affinity=tuple(_term_from(t) for t in v.node_affinity),
        claim_ref=v.claim_ref,
    )


def pv_to(v: api.PersistentVolume) -> pb.PersistentVolume:
    return pb.PersistentVolume(
        name=v.name,
        capacity=v.capacity,
        storage_class=v.storage_class,
        node_affinity=[_term_to(t) for t in v.node_affinity],
        claim_ref=v.claim_ref,
    )


def storage_class_from(s: pb.StorageClass) -> api.StorageClass:
    return api.StorageClass(
        name=s.name,
        volume_binding_mode=s.volume_binding_mode or api.VOLUME_BINDING_IMMEDIATE,
        provisioner=s.provisioner,
        allowed_topologies=tuple(_term_from(t) for t in s.allowed_topologies),
    )


def pdb_from(p: pb.PodDisruptionBudget) -> api.PodDisruptionBudget:
    return api.PodDisruptionBudget(
        name=p.name,
        namespace=p.namespace or "default",
        selector=_selector_from(p.selector),
        disruptions_allowed=p.disruptions_allowed,
    )


def pdb_to(p: api.PodDisruptionBudget) -> pb.PodDisruptionBudget:
    return pb.PodDisruptionBudget(
        name=p.name,
        namespace=p.namespace,
        selector=_selector_to(p.selector),
        disruptions_allowed=p.disruptions_allowed,
    )


def storage_class_to(s: api.StorageClass) -> pb.StorageClass:
    return pb.StorageClass(
        name=s.name,
        volume_binding_mode=s.volume_binding_mode,
        provisioner=s.provisioner,
        allowed_topologies=[_term_to(t) for t in s.allowed_topologies],
    )


def node_from(n: pb.Node) -> api.Node:
    return api.Node(
        metadata=meta_from(n.metadata),
        spec=api.NodeSpec(
            taints=tuple(
                api.Taint(t.key, t.value, t.effect or api.NO_SCHEDULE)
                for t in n.spec.taints
            ),
            unschedulable=n.spec.unschedulable,
        ),
        status=api.NodeStatus(
            allocatable=api._req_to_internal(dict(n.status.allocatable)),
            images=tuple(
                api.ContainerImage(tuple(i.names), i.size_bytes)
                for i in n.status.images
            ),
        ),
    )


# ---- api -> proto (the client agent's side) --------------------------------


def _req_to(r: api.NodeSelectorRequirement) -> pb.LabelSelectorRequirement:
    return pb.LabelSelectorRequirement(
        key=r.key, operator=r.operator, values=list(r.values)
    )


def _term_to(t: api.NodeSelectorTerm) -> pb.NodeSelectorTerm:
    return pb.NodeSelectorTerm(
        match_expressions=[_req_to(e) for e in t.match_expressions],
        match_fields=[_req_to(e) for e in t.match_fields],
    )


def _selector_to(s: api.LabelSelector) -> pb.LabelSelector:
    return pb.LabelSelector(
        match_labels=dict(s.match_labels),
        match_expressions=[_req_to(e) for e in s.match_expressions],
    )


def _aff_term_to(t: api.PodAffinityTerm) -> pb.PodAffinityTerm:
    return pb.PodAffinityTerm(
        label_selector=_selector_to(t.label_selector),
        topology_key=t.topology_key,
        namespaces=list(t.namespaces),
    )


def _pod_aff_to(p) -> pb.PodAffinity:
    return pb.PodAffinity(
        required=[_aff_term_to(t) for t in p.required],
        preferred=[
            pb.WeightedPodAffinityTerm(weight=w.weight, term=_aff_term_to(w.term))
            for w in p.preferred
        ],
    )


def affinity_to(a: api.Affinity | None) -> pb.Affinity | None:
    if a is None:
        return None
    out = pb.Affinity()
    if a.node_affinity is not None:
        out.node_affinity.CopyFrom(
            pb.NodeAffinity(
                required=[_term_to(t) for t in a.node_affinity.required],
                preferred=[
                    pb.PreferredSchedulingTerm(
                        weight=p.weight, preference=_term_to(p.preference)
                    )
                    for p in a.node_affinity.preferred
                ],
            )
        )
    if a.pod_affinity is not None:
        out.pod_affinity.CopyFrom(_pod_aff_to(a.pod_affinity))
    if a.pod_anti_affinity is not None:
        out.pod_anti_affinity.CopyFrom(_pod_aff_to(a.pod_anti_affinity))
    return out


def meta_to(m: api.ObjectMeta) -> pb.ObjectMeta:
    return pb.ObjectMeta(
        name=m.name,
        namespace=m.namespace,
        uid=m.uid,
        labels=dict(m.labels),
        annotations=dict(m.annotations),
        creation_timestamp=m.creation_timestamp,
    )


def _qty(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else str(v)


def _requests_to(requests: dict[str, float]) -> dict[str, str]:
    # internal units back to Quantity strings; format_millis keeps
    # sub-millicore cpu exact ("500u" survives the round-trip)
    from ..utils.quantity import format_millis

    return {
        name: (format_millis(v) if name == api.CPU else _qty(v))
        for name, v in requests.items()
    }


def pod_to(p: api.Pod) -> pb.Pod:
    s = p.spec
    msg = pb.Pod(
        metadata=meta_to(p.metadata),
        spec=pb.PodSpec(
            containers=[
                pb.Container(
                    name=c.name,
                    image=c.image,
                    requests=_requests_to(c.requests),
                    ports=[
                        pb.ContainerPort(
                            container_port=cp.container_port,
                            host_port=cp.host_port,
                            protocol=cp.protocol,
                            host_ip=cp.host_ip,
                        )
                        for cp in c.ports
                    ],
                )
                for c in s.containers
            ],
            node_name=s.node_name,
            node_selector=dict(s.node_selector),
            tolerations=[
                pb.Toleration(
                    key=t.key, operator=t.operator, value=t.value, effect=t.effect
                )
                for t in s.tolerations
            ],
            topology_spread_constraints=[
                pb.TopologySpreadConstraint(
                    max_skew=c.max_skew,
                    topology_key=c.topology_key,
                    when_unsatisfiable=c.when_unsatisfiable,
                    label_selector=_selector_to(c.label_selector),
                )
                for c in s.topology_spread_constraints
            ],
            priority=s.priority,
            priority_class_name=s.priority_class_name,
            preemption_policy=s.preemption_policy,
            scheduler_name=s.scheduler_name,
            overhead=_requests_to(s.overhead),
            pod_group=s.pod_group,
            volumes=list(s.volumes),
        ),
        nominated_node_name=p.nominated_node_name,
    )
    aff = affinity_to(s.affinity)
    if aff is not None:
        msg.spec.affinity.CopyFrom(aff)
    return msg


def node_to(n: api.Node) -> pb.Node:
    return pb.Node(
        metadata=meta_to(n.metadata),
        spec=pb.NodeSpec(
            taints=[
                pb.Taint(key=t.key, value=t.value, effect=t.effect)
                for t in n.spec.taints
            ],
            unschedulable=n.spec.unschedulable,
        ),
        status=pb.NodeStatus(
            allocatable=_requests_to(n.status.allocatable),
            images=[
                pb.ContainerImage(names=list(i.names), size_bytes=i.size_bytes)
                for i in n.status.images
            ],
        ),
    )
