"""gRPC shim server: snapshot deltas in, bindings out.

The cluster-integration boundary from SURVEY.md §7 step 7 / §5.8: where the
reference talks HTTPS watch/Binding-POST to the API server itself, the TPU
scheduler runs behind this service and a thin agent (client.py) owns the
cluster store conversation. Per the north star, one `Cycle` RPC returns
pod->node bindings for the WHOLE pending set.

Bind dispatch is optimistic (upstream assume-then-bind-async): a binding
returned from `Cycle` is assumed in the cache; the agent reports failed
Binding POSTs in its next `Update(bind_failures=[...])`, which forgets the
assumption and requeues with backoff. If the confirmation never arrives the
assumed-pod TTL expires and the pod is requeued (no double-bind either way
— fault tests in tests/test_service.py).

The driver underneath runs the split-phase serving pipeline
(core/pipeline.py): inside `Cycle`, the response's `bindings` are
collected from the winner bind loop, which blocks only on the slimmed
decision fetch — preemption nominations, evictions, and FailedScheduling
events ride the deferred programs that resolve while winners bind, so a
mostly-schedulable cycle's bindings are never gated on diagnostics.
`forced_sync` (config `forcedSync` or the serve() argument) restores
strictly sequential execution for tests and latency measurement.

The grpc servicer/stub glue is hand-written (the image has protoc for
messages but no grpc_python_plugin); method handler wiring mirrors what
grpc_tools would generate.
"""

from __future__ import annotations

import threading
import uuid
from concurrent import futures

import grpc

from ..config import SchedulerConfiguration
from ..core.scheduler import Scheduler
from ..metrics import SchedulerMetrics
from ..models.api import PodGroup
from . import convert
from . import scheduler_pb2 as pb

SERVICE_NAME = "k8sschedtpu.Scheduler"


class SchedulerService:
    """Implements the four RPCs against one host-side Scheduler."""

    def __init__(self, config: SchedulerConfiguration | None = None,
                 scheduler: Scheduler | None = None,
                 profile_every: int = 0,
                 metrics: SchedulerMetrics | None = None,
                 forced_sync: bool | None = None,
                 state=None) -> None:
        # the injectable binder collects into the in-progress response;
        # one cycle at a time (serialized by _cycle_lock)
        self._bindings: list[pb.Binding] = []
        self.scheduler = scheduler or Scheduler(
            config=config, binder=self._collect_binding, metrics=metrics,
            forced_sync=forced_sync, state=state,
        )
        if scheduler is not None:
            scheduler.binder = self._collect_binding
            if metrics is not None:
                # rebind like the binder above: an injected scheduler must
                # still report into the registry the caller will serve
                scheduler.metrics = metrics
        self._cycle_lock = threading.Lock()
        self._uid_index: dict[str, object] = {}  # uid -> last seen Pod
        # incarnation id: a restarted shim at the same address must be
        # distinguishable from the one the agent fed state to (§5.3)
        self.boot_id = uuid.uuid4().hex
        # every N Cycle RPCs, run the per-plugin profiling pass so the
        # plugin-latency histograms stay populated in steady serving
        self.profile_every = int(profile_every)
        self._cycle_count = 0
        # submission front door (service/admission.py): None until
        # enable_front_door() — the Submit/NodeChurn RPCs answer
        # FAILED_PRECONDITION while disabled
        self.admission = None

    def enable_front_door(self, **kwargs):
        """Attach an AdmissionController (idempotent) so the Submit /
        NodeChurn RPCs serve; returns the controller. The CLI calls
        this when --submit-addr is given."""
        if self.admission is None:
            from .admission import AdmissionController

            self.admission = AdmissionController(
                self.scheduler, **kwargs
            )
        return self.admission

    def run_local_cycle(self):
        """One scheduling cycle on the FRONT-DOOR serve loop,
        serialized against agent-driven Cycle RPCs by the same lock.
        Bindings are applied host-side (assume + events) exactly as in
        Cycle; the response-collection list is discarded — there is no
        RPC response to carry it."""
        with self._cycle_lock:
            self._bindings = []
            stats = self.scheduler.schedule_cycle()
            self._bindings = []
            return stats

    def _collect_binding(self, pod, node_name: str) -> None:
        self._bindings.append(
            pb.Binding(
                pod_uid=pod.uid,
                pod_name=pod.name,
                pod_namespace=pod.namespace,
                node_name=node_name,
            )
        )

    # ---- RPCs ------------------------------------------------------------

    def Update(self, request: pb.UpdateRequest, context) -> pb.UpdateResponse:
        s = self.scheduler
        for n in request.node_adds:
            s.on_node_add(convert.node_from(n))
        for n in request.node_updates:
            s.on_node_update(convert.node_from(n))
        for name in request.node_deletes:
            s.on_node_delete(name)
        for g in request.pod_groups:
            s.add_pod_group(PodGroup(g.name, g.min_member))
        for ev in request.pod_adds:
            pod = convert.pod_from(ev.pod)
            self._uid_index[pod.uid] = pod
            s.on_pod_add(pod, node_name=ev.bound_node)
        for ev in request.pod_updates:
            pod = convert.pod_from(ev.pod)
            self._uid_index[pod.uid] = pod
            s.on_pod_update(pod, node_name=ev.bound_node)
        for uid in request.pod_deletes:
            self._uid_index.pop(uid, None)
            s.on_pod_delete(uid)
        for uid in request.bind_failures:
            # agent's Binding POST failed: forget + backoff (upstream
            # handleBindingCycleError)
            s.cache.forget(uid)
            pod = self._uid_index.get(uid)
            if pod is not None:
                s.queue.requeue_backoff(pod)
        for c in request.pvc_upserts:
            s.on_pvc_upsert(convert.pvc_from(c))
        for key in request.pvc_deletes:
            s.on_pvc_delete(key)
        for v in request.pv_upserts:
            s.on_pv_upsert(convert.pv_from(v))
        for name in request.pv_deletes:
            s.on_pv_delete(name)
        for sc in request.storage_class_upserts:
            s.on_storage_class_upsert(convert.storage_class_from(sc))
        for name in request.storage_class_deletes:
            s.on_storage_class_delete(name)
        for pdb in request.pdb_upserts:
            s.on_pdb_upsert(convert.pdb_from(pdb))
        for key in request.pdb_deletes:
            s.on_pdb_delete(key)
        return pb.UpdateResponse(boot_id=self.boot_id)

    def Cycle(self, request: pb.CycleRequest, context) -> pb.CycleResponse:
        with self._cycle_lock:
            self._bindings = []
            s = self.scheduler
            stats = s.schedule_cycle()
            self._cycle_count += 1
            if self.profile_every and self._cycle_count % self.profile_every == 0:
                s.profile_cycle()
            resp = pb.CycleResponse(
                boot_id=self.boot_id,
                bindings=list(self._bindings),
                stats=pb.CycleStats(
                    attempted=stats.attempted,
                    scheduled=stats.scheduled,
                    unschedulable=stats.unschedulable,
                    bind_errors=stats.bind_errors,
                    preemptors=stats.preemptors,
                    victims=stats.victims,
                    gang_dropped=stats.gang_dropped,
                    cycle_seconds=stats.cycle_seconds,
                ),
            )
            # nominations + evictions were applied to host state by the
            # driver; surface them from its per-cycle decision log
            for pod, node in s.last_nominations:
                resp.nominations.append(
                    pb.Nomination(pod_uid=pod.uid, node_name=node)
                )
            for pod, node in s.last_evictions:
                resp.evictions.append(
                    pb.Eviction(
                        pod_uid=pod.uid, pod_name=pod.name, node_name=node
                    )
                )
            # drain Scheduled/FailedScheduling/Preempted events so the
            # agent can post them as real Kubernetes Events
            for ev in s.events.drain():
                resp.events.append(
                    pb.Event(
                        type=ev.type,
                        reason=ev.reason,
                        pod_uid=ev.pod_uid,
                        pod_name=ev.pod_name,
                        message=ev.message,
                    )
                )
            return resp

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        return pb.HealthResponse(ok=True, status="ok", boot_id=self.boot_id)

    def Metrics(self, request: pb.MetricsRequest, context) -> pb.MetricsResponse:
        return pb.MetricsResponse(
            prometheus_text=self.scheduler.metrics.expose()
        )

    def Inspect(self, request: pb.InspectRequest, context) -> pb.InspectResponse:
        """Flight-recorder introspection over the agent's channel: the
        same payloads the /debug HTTP endpoints serve (cycle records,
        Perfetto trace, per-pod timeline), JSON-encoded."""
        import json

        fr = self.scheduler.flight
        kind = request.kind or "flightrecorder"
        last = request.last if request.last > 0 else 128
        # kind="pod" stays available with the recorder disabled — the
        # timeline join degrades to the events-ring half, exactly like
        # the /debug/pods HTTP endpoint
        if fr is None and kind in ("flightrecorder", "trace"):
            return pb.InspectResponse(
                ok=False, error="flight recorder disabled "
                "(flightRecorderSize: 0)",
            )
        if kind == "flightrecorder":
            payload = {
                "cycles": fr.to_dicts(last=last),
                "derived": fr.derived(last=last),
            }
        elif kind == "trace":
            from ..core.flight_recorder import to_chrome_trace

            payload = to_chrome_trace(
                fr.snapshot(last=last), epoch=fr.epoch
            )
        elif kind == "pod":
            payload = self.scheduler.pod_timeline(request.pod_uid)
            if payload is None:
                return pb.InspectResponse(
                    ok=False,
                    error=f"pod {request.pod_uid!r} not seen",
                )
        else:
            return pb.InspectResponse(
                ok=False,
                error=f"unknown kind {kind!r} "
                "(flightrecorder | trace | pod)",
            )
        return pb.InspectResponse(
            ok=True, json=json.dumps(payload).encode()
        )

    # ---- the submission front door (service/admission.py) ---------------

    def Submit(self, request: pb.SubmitRequest, context) -> pb.SubmitResponse:
        """Admission-controlled pod intake: whole-request accept or
        reject. Shed answers RESOURCE_EXHAUSTED with a retry-after-ms
        trailing-metadata hint; an OK ack means every pod was journaled
        through the WAL (group fsync) first — `durable` reports
        whether that barrier actually held (no state dir = false).

        Trace context (core/spans) rides gRPC metadata, not the proto:
        a W3C `traceparent` invocation-metadata entry joins the
        submission's spans to the caller's trace, and the ack's
        trailing metadata echoes the effective traceparent back (the
        caller's own, or the head-sampled root the scheduler minted)."""
        adm = self.admission
        if adm is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "front door disabled (start with --submit-addr or "
                "enable_front_door())",
            )
        try:
            pods = [convert.pod_from(p) for p in request.pods]
        except (ValueError, KeyError, TypeError) as e:
            # the proto contract: malformed pods answer
            # INVALID_ARGUMENT (an unparseable quantity here would
            # otherwise surface as UNKNOWN, which retrying clients
            # treat as transient and hammer forever)
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"unparseable pod in submission: {e}",
            )
        traceparent = ""
        for key, value in context.invocation_metadata() or ():
            if key == "traceparent":
                traceparent = value
                break
        res = adm.submit(pods, traceparent=traceparent)
        if res.invalid:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, res.reason
            )
        if res.reason == "draining":
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "front door draining (shutdown in progress)",
            )
        if res.shed:
            context.set_trailing_metadata(
                (("retry-after-ms", f"{res.retry_after_ms:g}"),)
            )
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"admission shed: {res.reason}",
            )
        if res.traceparent:
            context.set_trailing_metadata(
                (("traceparent", res.traceparent),)
            )
        return pb.SubmitResponse(
            boot_id=self.boot_id,
            accepted=res.accepted,
            durable=res.durable,
            queue_depth=res.queue_depth,
        )

    def NodeChurn(
        self, request: pb.NodeChurnRequest, context
    ) -> pb.NodeChurnResponse:
        adm = self.admission
        if adm is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "front door disabled (start with --submit-addr or "
                "enable_front_door())",
            )
        from .admission import AdmissionClosed

        try:
            adds = [convert.node_from(n) for n in request.adds]
            updates = [convert.node_from(n) for n in request.updates]
        except (ValueError, KeyError, TypeError) as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"unparseable node in churn request: {e}",
            )
        try:
            durable = adm.node_churn(
                adds=adds,
                updates=updates,
                deletes=list(request.deletes),
            )
        except AdmissionClosed:
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "front door draining (shutdown in progress)",
            )
        return pb.NodeChurnResponse(
            boot_id=self.boot_id, durable=durable
        )


_RPCS = {
    "Update": (pb.UpdateRequest, pb.UpdateResponse),
    "Cycle": (pb.CycleRequest, pb.CycleResponse),
    "Health": (pb.HealthRequest, pb.HealthResponse),
    "Metrics": (pb.MetricsRequest, pb.MetricsResponse),
    "Inspect": (pb.InspectRequest, pb.InspectResponse),
    "Submit": (pb.SubmitRequest, pb.SubmitResponse),
    "NodeChurn": (pb.NodeChurnRequest, pb.NodeChurnResponse),
}


def add_to_server(servicer: SchedulerService, server: grpc.Server) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        for name, (req, resp) in _RPCS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


def serve(
    address: str = "127.0.0.1:50051",
    config: SchedulerConfiguration | None = None,
    max_workers: int = 4,
    profile_every: int = 0,
    metrics: SchedulerMetrics | None = None,
    forced_sync: bool | None = None,
    state=None,  # state.DurableState | None (restore-then-journal)
) -> tuple[grpc.Server, SchedulerService, int]:
    """Start the shim; returns (server, servicer, bound_port)."""
    service = SchedulerService(
        config=config, profile_every=profile_every, metrics=metrics,
        forced_sync=forced_sync, state=state,
    )
    # no SO_REUSEPORT: a second shim on the same address must fail loudly,
    # not silently split the accept queue with the first
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=(("grpc.so_reuseport", 0),),
    )
    add_to_server(service, server)
    port = server.add_insecure_port(address)
    if port == 0 and not address.rstrip().endswith(":0"):
        # grpc signals bind failure by returning port 0; only an explicit
        # ":0" (ephemeral) request may legitimately come back remapped
        server.stop(grace=0)
        raise OSError(f"failed to bind gRPC address {address!r}")
    server.start()
    return server, service, port


def main() -> None:  # pragma: no cover - exercised via the CLI
    import argparse

    ap = argparse.ArgumentParser(description="TPU scheduler gRPC shim")
    ap.add_argument("--address", default="127.0.0.1:50051")
    args = ap.parse_args()
    server, _, port = serve(args.address)
    print(f"scheduler shim listening on port {port}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":  # pragma: no cover
    main()
