"""The submission front door: admission control, WAL-before-ack, drain.

ROADMAP item 1's serving edge. Everything below the queue is fast
(multi-cycle batching, depth-2 speculation), shard-exact, and
chaos-hardened — this module is where live traffic meets it. Two pieces:

- `AdmissionController` — the admission layer behind the Submit /
  NodeChurn RPCs (service/server.py) and the debug server's thin
  `POST /submit` path (cmd/httpserver.py). A submission is accepted
  ATOMICALLY or rejected whole:

  * **invalid** (missing uid/name, duplicate uid — within the request,
    still pending from an earlier accept, or already assumed/bound in
    the cache: a retry whose ack was lost after the bind must not
    re-admit the pod) — INVALID_ARGUMENT; nothing enqueued, nothing
    journaled.
  * **shed** — explicit backpressure, RESOURCE_EXHAUSTED with a
    retry-after hint, when admitting the request would push the
    admission queue (pending pods across all tiers + pods coalescing in
    the multi-cycle buffers) past `admissionQueueDepth`, when the SLO
    fast-burn gauge fires (core/observe.SloEngine.degraded), or when
    the degradation ladder sits below rung 0. Overload degrades to
    shedding — never to unbounded memory, never to silent latency.
  * **accepted** — every pod is enqueued through the scheduler's
    informer path (`on_pod_add` -> `queue.add`, which journals `q.add`
    through the PR 3 WAL) and then, when a state dir is configured, the
    ack WAITS on the journal's group-commit fsync barrier
    (`DurableState.ack_barrier`) before returning. An acked submission
    is durable by contract: a kill -9 one instant after the ack
    replays the pod from the WAL. Concurrent submitters share one
    fsync per writer batch — the ack path rides the group commit, it
    never adds fsyncs to the bind path.

  Accepted pods are timestamped; `Scheduler._bind` closes the window
  via `note_bind`, and the per-cycle worst submit->bind latency rides
  the flight record as the `submit_bind` phase (observe.PHASES), so
  the streaming p99 gauges track the end-to-end SLO the open-loop
  load harness (scripts/loadgen.py) measures from outside.

- `FrontDoor` — the `ScheduleOne` loop for network-fed serving: a
  thread driving `schedule_cycle()` continuously (the agent-driven
  `Cycle` RPC has no caller when arrivals come over the wire). Its
  `stop()` is the graceful-drain contract: admission closes (late
  submits get UNAVAILABLE "draining"), the loop keeps cycling until
  the active tier and every multi-cycle coalescing buffer are empty —
  no pod stranded between ack and dispatch — and only then does the
  caller seal durable state.

Thread model: `submit`/`node_churn` run on gRPC/HTTP worker threads;
`note_bind`/`take_bind_latency_ms`/`queue_depth` run on the serve
loop. Every shared structure is guarded by the controller's one lock;
the queue/cache take their own locks exactly as they do for informer
callbacks today.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time as _time

from ..core import blackbox as _blackbox
from ..core import spans as _spans

log = logging.getLogger(__name__)

# accepted-but-unbound timestamps kept at most this many deep: a pod
# parked unschedulable for hours should age out of the latency join
# (its eventual submit->bind sample would only poison the histogram)
_MAX_TRACKED = 262_144

# per-uid admission history (shed / invalid / accepted / bound) kept
# for /debug/explain — bounded LRU on uid, bounded events per uid
_MAX_HISTORY_UIDS = 4096
_MAX_HISTORY_EVENTS = 32


@dataclasses.dataclass
class SubmitResult:
    """Outcome of one submission request (whole-request semantics)."""

    accepted: int = 0
    shed: int = 0
    invalid: tuple[str, ...] = ()  # offending uids (or "" for no-uid)
    reason: str = ""  # shed/invalid/draining detail
    retry_after_ms: float = 0.0  # > 0 on shed
    durable: bool = False  # the WAL ack barrier held
    queue_depth: int = 0  # admission queue depth after the request
    # trace context echoed back to the submitter (W3C traceparent):
    # the caller's own header when one was supplied, else the first
    # sampled pod's locally minted root context, "" when tracing is
    # unarmed or nothing sampled — rides the gRPC trailing metadata
    # and the HTTP response header
    traceparent: str = ""

    @property
    def ok(self) -> bool:
        return not self.shed and not self.invalid and not self.reason


class AdmissionController:
    def __init__(
        self,
        scheduler,
        queue_depth: int | None = None,  # None = config
        retry_after_ms: float | None = None,  # None = config
        max_tracked: int = _MAX_TRACKED,
        tenants=None,  # tenancy.TenantRegistry | None
    ) -> None:
        self.scheduler = scheduler
        cfg = scheduler.config
        # multi-tenant mode: a Submit carries its tenant in the pod
        # namespace. Admission validates the tenant exists and is
        # active (invalid otherwise — nothing journaled), and the shed
        # predicate consults THAT tenant's accepted-unbound depth
        # against its quota and weighted-fair share of the global
        # bound, so one flooding tenant backpressures itself instead
        # of starving the fleet's front door.
        self.tenants = tenants
        # uid -> tenant id for accepted-unbound pods; the per-tenant
        # depth is its value multiset (kept as a counter dict)
        self._tenant_of: dict[str, str] = {}
        self._tenant_depth: dict[str, int] = {}
        self.depth_bound = int(
            cfg.admission_queue_depth if queue_depth is None
            else queue_depth
        )
        self.retry_after_ms = float(
            cfg.admission_retry_after_ms if retry_after_ms is None
            else retry_after_ms
        )
        self._lock = threading.Lock()
        # uid -> accept time (scheduler clock) for accepted, still
        # unbound pods; ordered so overflow evicts the oldest
        self._accept_t: collections.OrderedDict[str, float] = (
            collections.OrderedDict()
        )
        self._max_tracked = max_tracked
        # uid -> [admission events] for /debug/explain (shed/invalid
        # attempts, the accept, the bind) — LRU-bounded both ways
        self._history: collections.OrderedDict[str, list] = (
            collections.OrderedDict()
        )
        self._bind_lat_ms = 0.0  # worst since last take (per record)
        self._closed = False
        self.accepted_total = 0
        self.shed_total = 0
        self.invalid_total = 0
        self.last_shed_reason = ""
        # the durable-state handle bound ONCE here (it is fixed for the
        # scheduler's lifetime): the ack-barrier path must not chase
        # `self.scheduler.state` per submit — and the name `state`
        # collides with the device keepers' `state` methods in the
        # name-based callgraph, which would smear the HTTP role across
        # the dispatch path (schedlint TR001 false positives)
        self._durable = scheduler.state
        # the scheduler consults this at bind/record time
        scheduler.admission = self

    # ---- depth ------------------------------------------------------------

    def queue_depth(self) -> int:
        """Pending pods across all queue tiers plus pods buffered in
        the multi-cycle coalescing groups (popped but not dispatched).
        Approximate by design — the serve loop mutates the buffers
        concurrently — which is fine for a shed bound: the queue's own
        lock makes each component read consistent, and the bound is a
        memory guard, not an exactness contract."""
        s = self.scheduler
        n = len(s.queue)
        for bufs in s._mc_groups.values():
            for _t, group in bufs:
                n += len(group)
        return n

    # ---- admission history (the /debug/explain join) ----------------------

    def _note_history(self, uids, kind: str, **detail) -> None:
        """Append one admission event per uid (callers hold the lock).
        Tracing-independent: the shed/retry history is part of the
        explain contract whether or not spans are armed."""
        wall = _time.time()
        for uid in uids:
            if not uid:
                continue
            events = self._history.get(uid)
            if events is None:
                events = []
                self._history[uid] = events
                while len(self._history) > _MAX_HISTORY_UIDS:
                    self._history.popitem(last=False)
            else:
                self._history.move_to_end(uid)
            events.append({"wall": wall, "kind": kind, **detail})
            if len(events) > _MAX_HISTORY_EVENTS:
                del events[: len(events) - _MAX_HISTORY_EVENTS]

    def history_for(self, uid: str) -> list:
        """This uid's admission history, oldest first (empty when the
        uid was never seen or aged out of the LRU)."""
        with self._lock:
            events = self._history.get(uid)
            return [dict(e) for e in events] if events else []

    # ---- submission -------------------------------------------------------

    def submit(self, pods, traceparent: str = "") -> SubmitResult:
        t0 = _time.perf_counter()
        m = self.scheduler.metrics
        if self._closed:
            return SubmitResult(
                shed=len(pods), reason="draining",
                retry_after_ms=self.retry_after_ms,
                queue_depth=self.queue_depth(),
                traceparent=traceparent,
            )
        # validation first: an invalid request must journal NOTHING
        bad: list[str] = []
        seen: set[str] = set()
        for p in pods:
            uid = getattr(p, "uid", "")
            if not uid or not p.name:
                bad.append(uid or "")
            elif uid in seen:
                bad.append(uid)
            seen.add(uid)
        if bad:
            with self._lock:
                self.invalid_total += len(pods)
                self._note_history(bad, "invalid", reason="malformed")
            m.admission_total.labels(outcome="invalid").inc(len(pods))
            return SubmitResult(
                invalid=tuple(bad),
                reason=f"invalid pods: {bad[:4]!r}",
                queue_depth=self.queue_depth(),
                traceparent=traceparent,
            )
        # a uid the cache already knows (assumed or bound) is a
        # duplicate too: a client retrying a Submit whose ack was lost
        # AFTER the pod bound must not re-admit it — note_bind has
        # already dropped it from _accept_t, and re-queueing a bound
        # pod double-schedules it. Checked OUTSIDE the admission lock
        # (cache takes its own lock; nesting it under ours would
        # invert against the bind path's note_bind).
        cache = self.scheduler.cache
        known = [u for u in seen if cache.has_pod(u)]
        if known:
            with self._lock:
                self.invalid_total += len(pods)
                self._note_history(
                    known, "invalid", reason="already bound"
                )
            m.admission_total.labels(outcome="invalid").inc(len(pods))
            return SubmitResult(
                invalid=tuple(known),
                reason=f"uids already bound: {known[:4]!r}",
                queue_depth=self.queue_depth(),
                traceparent=traceparent,
            )
        # tenant validity: an unknown or suspended tenant is INVALID
        # (a caller bug or a deliberate lockout), not backpressure —
        # nothing journaled, no retry-after
        if self.tenants is not None:
            bad_t: list[str] = []
            t_reason = ""
            for p in pods:
                t = self.tenants.get(p.namespace)
                if t is None:
                    bad_t.append(p.uid)
                    t_reason = t_reason or (
                        f"unknown tenant {p.namespace!r}"
                    )
                elif t.lifecycle != "active":
                    bad_t.append(p.uid)
                    t_reason = t_reason or (
                        f"tenant {p.namespace!r} suspended"
                    )
            if bad_t:
                with self._lock:
                    self.invalid_total += len(pods)
                    self._note_history(
                        bad_t, "invalid", reason=t_reason
                    )
                m.admission_total.labels(outcome="invalid").inc(
                    len(pods)
                )
                return SubmitResult(
                    invalid=tuple(bad_t),
                    reason=t_reason,
                    queue_depth=self.queue_depth(),
                    traceparent=traceparent,
                )
        t_valid = _time.perf_counter()
        ctxs: list = []  # (uid, TraceContext) for sampled pods
        with self._lock:
            if self._closed:
                return SubmitResult(
                    shed=len(pods), reason="draining",
                    retry_after_ms=self.retry_after_ms,
                    queue_depth=self.queue_depth(),
                    traceparent=traceparent,
                )
            # a uid still pending from an earlier accepted submission
            # is a duplicate, not an update — re-queueing it would
            # reset its attempt bookkeeping and could double-bind
            dup = [u for u in seen if u in self._accept_t]
            if dup:
                self.invalid_total += len(pods)
                self._note_history(
                    dup, "invalid", reason="already pending"
                )
                m.admission_total.labels(outcome="invalid").inc(
                    len(pods)
                )
                return SubmitResult(
                    invalid=tuple(dup),
                    reason=f"uids already pending: {dup[:4]!r}",
                    queue_depth=self.queue_depth(),
                    traceparent=traceparent,
                )
            depth = self.queue_depth()
            reason = self._shed_reason(depth, len(pods))
            if not reason and self.tenants is not None:
                reason = self._tenant_shed_reason(depth, pods)
            if reason:
                self.shed_total += len(pods)
                self.last_shed_reason = reason
                self._note_history(
                    seen, "shed", reason=reason,
                    retry_after_ms=self.retry_after_ms,
                )
                m.admission_total.labels(outcome="shed").inc(len(pods))
                return SubmitResult(
                    shed=len(pods), reason=reason,
                    retry_after_ms=self.retry_after_ms,
                    queue_depth=depth,
                    traceparent=traceparent,
                )
            # accept: enqueue through the informer path — queue.add
            # journals q.add with the same codec/clock discipline every
            # other mutator uses, so replay and the standby-takeover
            # digest machinery need nothing new for submitted pods
            now = self.scheduler._now()
            for p in pods:
                # bind the trace context BEFORE the enqueue: the serve
                # loop can pop and flush the pod the instant queue.add
                # releases, and its mc.buffer_wait/dispatch spans join
                # the trace by uid lookup
                if _spans.ARMED:
                    c = _spans.register(
                        p.uid, traceparent,
                        tenant=(
                            p.namespace
                            if self.tenants is not None else ""
                        ),
                    )
                    if c is not None:
                        ctxs.append((p.uid, c))
                self.scheduler.on_pod_add(p)
                self._accept_t[p.uid] = now
                if self.tenants is not None:
                    tid = p.namespace
                    self._tenant_of[p.uid] = tid
                    self._tenant_depth[tid] = (
                        self._tenant_depth.get(tid, 0) + 1
                    )
            while len(self._accept_t) > self._max_tracked:
                old_uid, _t = self._accept_t.popitem(last=False)
                self._tenant_untrack(old_uid)
            self.accepted_total += len(pods)
            self._note_history(seen, "accepted", depth=depth)
            depth += len(pods)
        m.admission_total.labels(outcome="accepted").inc(len(pods))
        m.admission_queue_depth.set(depth)
        # WAL-before-ack, OUTSIDE the admission lock: the barrier is
        # the group-commit fsync every concurrent submitter shares —
        # serializing it under the lock would turn group commit back
        # into one fsync per request
        durable = False
        t_ack0 = _time.perf_counter()
        flush_seq = -1
        if self._durable is not None:
            durable = self._durable.ack_barrier()
            if ctxs:
                flush_seq = self._durable.flush_seq()
        m.submit_ack.observe(_time.perf_counter() - t0)
        tp = traceparent
        if ctxs:
            # one span triple per sampled pod, stamped from the shared
            # request timestamps: validate (request entry -> dup checks
            # done), journal (the informer-path enqueue, which stamped
            # itself inside the lock window), ack.barrier (the shared
            # group-commit fsync wait — every submitter's span carries
            # the flush seq it rode)
            t_ack1 = _time.perf_counter()
            for uid, c in ctxs:
                _spans.record_span(
                    "submit.validate", c, t0, t_valid, uid=uid
                )
                _spans.record_span(
                    "submit.journal", c, t_valid, t_ack0, uid=uid
                )
                if self._durable is not None:
                    _spans.record_span(
                        "ack.barrier", c, t_ack0, t_ack1, uid=uid,
                        flush_seq=flush_seq, durable=durable,
                    )
            if not tp:
                tp = ctxs[0][1].traceparent()
        return SubmitResult(
            accepted=len(pods), durable=durable, queue_depth=depth,
            traceparent=tp,
        )

    def _shed_reason(self, depth: int, incoming: int) -> str:
        """The backpressure predicate (callers hold the lock)."""
        if self.depth_bound > 0 and depth + incoming > self.depth_bound:
            return (
                f"admission queue full ({depth}+{incoming} > "
                f"{self.depth_bound})"
            )
        reason = ""
        obs = self.scheduler.observer
        ladder = self.scheduler.ladder
        if obs is not None and obs.slo.degraded():
            reason = (
                "SLO fast-burn "
                f"({obs.slo.burn_rate('fast'):.1f}x sustainable)"
            )
        elif ladder.rung > 0:
            from ..core.degrade import RUNGS

            # RUNGS[rung], not ladder.status(): this predicate runs
            # under the admission lock on the ack path — it must stay
            # a pure read of plain attributes
            reason = (
                f"degradation ladder at rung {ladder.rung} "
                f"({RUNGS[ladder.rung]})"
            )
        if reason:
            # half-open, not closed: while degraded the effective
            # bound shrinks to a probe trickle instead of zero. Both
            # recovery signals are TRAFFIC-DRIVEN (ladder promotion
            # counts clean DISPATCHING cycles; the SLO windows advance
            # one entry per attempted cycle) — shedding everything
            # while degraded would freeze the very evidence recovery
            # needs, and one watchdog expiry would pin the door shut
            # for good. The flood still sheds; the trickle heals.
            trickle = (
                max(self.depth_bound // 8, 16)
                if self.depth_bound > 0 else 64
            )
            if depth + incoming > trickle:
                return reason
        return ""

    def _tenant_shed_reason(self, depth: int, pods) -> str:
        """Per-tenant backpressure (callers hold the lock; global shed
        already passed). Two predicates, both scoped to the submitting
        tenant so the reason names who to back off and why:

        - **quota**: the tenant's accepted-unbound depth may not exceed
          its configured ceiling (0 = unlimited). Absolute — fires at
          any fleet load.
        - **weighted-fair share**: under global pressure (the fleet
          past half its depth bound), a tenant may not hold more than
          `depth_bound * weight / total_active_weight` of the
          admission queue. A flooding tenant saturates its share and
          sheds; a light tenant's submissions keep landing — the
          admission-side half of the starved-tenant story (the arena's
          anomaly is the schedule-side half). Idle fleets skip the
          share cap so a lone tenant stays work-conserving."""
        tn = self.tenants
        m = self.scheduler.metrics
        by: dict[str, int] = {}
        for p in pods:
            by[p.namespace] = by.get(p.namespace, 0) + 1
        pressured = (
            self.depth_bound > 0
            and depth + len(pods) > self.depth_bound // 2
        )
        for tid in sorted(by):
            t = tn.get(tid)
            if t is None:
                continue  # tenant deleted after validation: not shed
            n = by[tid]
            tdepth = self._tenant_depth.get(tid, 0)
            if t.quota > 0 and tdepth + n > t.quota:
                m.tenancy_events.labels(event="quota_shed").inc()
                return (
                    f"tenant {tid} quota exceeded "
                    f"({tdepth}+{n} > {t.quota})"
                )
            if pressured:
                share = max(
                    int(self.depth_bound * t.weight / tn.total_weight()),
                    1,
                )
                if tdepth + n > share:
                    m.tenancy_events.labels(event="fair_shed").inc()
                    return (
                        f"tenant {tid} over weighted-fair share "
                        f"({tdepth}+{n} > {share} of "
                        f"{self.depth_bound})"
                    )
        return ""

    def _tenant_untrack(self, uid: str) -> None:
        """Drop one uid from the per-tenant depth accounting (callers
        hold the lock): bind, delete, or LRU eviction."""
        tid = self._tenant_of.pop(uid, None)
        if tid is None:
            return
        left = self._tenant_depth.get(tid, 0) - 1
        if left > 0:
            self._tenant_depth[tid] = left
        else:
            self._tenant_depth.pop(tid, None)

    def tenant_depth(self, tenant_id: str) -> int:
        """Accepted-unbound pods this controller tracks for a tenant
        (the quota/fair-share denominator) — /debug surface."""
        with self._lock:
            return self._tenant_depth.get(tenant_id, 0)

    # ---- node churn -------------------------------------------------------

    def node_churn(self, adds=(), updates=(), deletes=()) -> bool:
        """Apply node churn through the informer path (journaled via
        the cache's c.add_node/c.update_node/c.remove_node records) and
        hold the same ack barrier. Node churn is never shed — dropping
        cluster state is strictly worse than any queue depth — but a
        draining front door refuses it (AdmissionClosed -> UNAVAILABLE:
        the state is about to seal)."""
        if self._closed:
            raise AdmissionClosed("front door draining")
        s = self.scheduler
        for nd in adds:
            s.on_node_add(nd)
        for nd in updates:
            s.on_node_update(nd)
        for name in deletes:
            s.on_node_delete(name)
        if self._durable is not None:
            return self._durable.ack_barrier()
        return False

    # ---- serve-loop side --------------------------------------------------

    def note_bind(self, uid: str) -> None:
        """Called by Scheduler._bind for every successful bind: closes
        the submit->bind window for front-door pods (a uid this
        controller never accepted is a no-op). Must never raise — it
        sits on the bind path."""
        with self._lock:
            t0 = self._accept_t.pop(uid, None)
            if t0 is None:
                return
            self._tenant_untrack(uid)
            lat_ms = max(self.scheduler._now() - t0, 0.0) * 1e3
            if lat_ms > self._bind_lat_ms:
                self._bind_lat_ms = lat_ms
            if uid in self._history:
                self._note_history(
                    (uid,), "bound", latency_ms=round(lat_ms, 3)
                )

    def note_delete(self, uid: str) -> None:
        """Called by Scheduler.on_pod_delete: a pod deleted before it
        bound leaves the accepted-pending set, so a re-created pod
        reusing the uid can be admitted again (without this the uid
        would answer 'already pending' until the LRU happened to evict
        it). Must never raise — it sits on the informer path."""
        with self._lock:
            self._accept_t.pop(uid, None)
            self._tenant_untrack(uid)
        # a deleted pod's trace is over — drop its live context (the
        # recorded spans stay in the ring for /debug queries)
        if _spans.ARMED:
            _spans.release(uid)

    def take_bind_latency_ms(self) -> float:
        """Worst submit->bind latency among binds since the last take
        (consumed by Scheduler._commit_record into the `submit_bind`
        flight-record phase); 0.0 when no front-door pod bound."""
        with self._lock:
            v = self._bind_lat_ms
            self._bind_lat_ms = 0.0
        return v

    # ---- lifecycle / status ----------------------------------------------

    def close(self) -> None:
        """Stop admitting (drain begins): every later submit answers
        'draining' (UNAVAILABLE), node churn raises AdmissionClosed."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def overloaded(self) -> str:
        """Non-empty reason while the front door would shed RIGHT NOW
        — surfaced as `degraded: true` in /healthz during a burst.
        Deliberately lock-free: the predicate reads plain attributes
        plus the queue's own lock, and a probe must never queue behind
        a submit's fsync barrier (the depth it reports is a snapshot
        either way)."""
        return self._shed_reason(self.queue_depth(), 1)

    def status(self) -> dict:
        with self._lock:
            return {
                "queue_depth": self.queue_depth(),
                "depth_bound": self.depth_bound,
                "accepted_total": self.accepted_total,
                "shed_total": self.shed_total,
                "invalid_total": self.invalid_total,
                "pending_accepted": len(self._accept_t),
                "last_shed_reason": self.last_shed_reason,
                "closed": self._closed,
                "tenant_depths": dict(self._tenant_depth),
            }


class AdmissionClosed(RuntimeError):
    """Raised by node_churn on a draining front door."""


class FrontDoor:
    """The serve loop for network-fed arrivals, with graceful drain.

    `cycle_fn` defaults to the scheduler's `schedule_cycle`; the CLI
    passes `SchedulerService.run_local_cycle` so a stray agent-driven
    Cycle RPC serializes against the loop instead of racing it."""

    def __init__(
        self,
        admission: AdmissionController,
        cycle_fn=None,
        idle_sleep: float = 0.005,
        post_cycle=None,
    ) -> None:
        self.admission = admission
        self.scheduler = admission.scheduler
        self._cycle_fn = cycle_fn or self.scheduler.schedule_cycle
        self._idle_sleep = idle_sleep
        # runs on the loop thread after every cycle — the in-process
        # drives (bench config 9, loadgen, soak overload) use it to
        # play the informer back (bind confirmations), which a real
        # deployment's agent does via Update; without confirmation an
        # assumed pod expires on the 30 s TTL and re-binds
        self._post_cycle = post_cycle
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._thread: threading.Thread | None = None
        self.cycles = 0
        self.cycle_failures = 0
        self._failure_backoff = 0.5

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("front door already started")
        self._thread = threading.Thread(
            target=self._run, name="front-door-serve", daemon=True
        )
        self._thread.start()

    def _buffered(self) -> bool:
        s = self.scheduler
        return any(s._mc_groups.values())

    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            # fail SHUT: if the loop ever exits without a completed
            # drain or an explicit stop() (a BaseException, a logic
            # error), the door must not keep acking durable pods into
            # a serve loop that no longer exists
            if not self._stop.is_set() and not self._drained.is_set():
                log.error(
                    "front-door serve loop exited abnormally — "
                    "closing admission (acked pods stay journaled "
                    "and dispatch on restart)"
                )
                self.admission.close()

    def _run_loop(self) -> None:
        s = self.scheduler
        while not self._stop.is_set():
            try:
                stats = self._cycle_fn()
                self.cycles += 1
                if self._post_cycle is not None:
                    self._post_cycle()
            except Exception:
                # a host-side bug escaping schedule_cycle (device
                # failures are consumed by the watchdog + ladder) must
                # not silently kill the serve thread while admission
                # keeps acking: log, count, back off, keep serving —
                # accepted pods are journaled and stay dispatchable
                # the moment the fault clears
                self.cycle_failures += 1
                log.exception(
                    "front-door cycle failed (%d so far) — backing "
                    "off %.1fs and continuing",
                    self.cycle_failures, self._failure_backoff,
                )
                # unhandled serve-loop exception = black-box trigger
                # (throttled inside; the loop is about to keep running,
                # so the bundle must capture the rings now)
                _blackbox.trigger(
                    "serve_loop",
                    f"cycle_failures={self.cycle_failures}",
                )
                self._stop.wait(self._failure_backoff)
                continue
            if self._draining.is_set():
                # drain condition: nothing ready AND nothing coalescing
                # (backoff/unschedulable pods are durable in the sealed
                # state and legitimately outlive the drain — they are
                # parked, not stranded between ack and dispatch)
                if (
                    s.queue.pending_counts().get("active", 0) == 0
                    and not self._buffered()
                ):
                    self._drained.set()
                    return
                continue  # drain at full cadence, no idle sleep
            if stats.attempted == 0 and not self._buffered():
                self._stop.wait(self._idle_sleep)

    def begin_drain(self) -> None:
        """Stop admission and switch the loop into drain mode."""
        self.admission.close()
        self._draining.set()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> bool:
        """Graceful shutdown: close admission, flush every buffered
        group, stop the loop, join the thread. Returns True when the
        drain completed (False = timeout; the journal tail still holds
        every acked pod, so nothing is lost either way)."""
        drained = True
        if drain and self._thread is not None:
            self.begin_drain()
            drained = self._drained.wait(timeout)
            if not drained:
                log.warning(
                    "front door drain did not complete within %.1fs "
                    "(active=%d, buffered=%s) — stopping anyway; the "
                    "journal tail covers the remainder",
                    timeout,
                    self.scheduler.queue.pending_counts().get(
                        "active", 0
                    ),
                    self._buffered(),
                )
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=max(timeout, 5.0))
            if thread.is_alive():
                log.error(
                    "front-door serve thread failed to exit; leaving "
                    "it daemon (a wedged dispatch is bounded by the "
                    "watchdog, not this join)"
                )
            self._thread = None
        return drained


def self_confirming_front_door(service, admission) -> FrontDoor:
    """FrontDoor for agentless CLI serving (`--submit-addr`): the local
    loop is the binder of record — `run_local_cycle` has no RPC
    response to carry bindings to an agent, and no API server echoes
    them back — so an assumed bind would otherwise expire on the cache
    TTL and re-bind forever. Chains the service's response-collecting
    binder with a confirm queue the loop plays back post-cycle through
    the informer path (the same contract an agent's Update confirmation
    provides); the confirmed bind is journaled, so a failover restores
    it bound instead of re-schedulable."""
    confirm_q: collections.deque = collections.deque()
    sched = service.scheduler
    svc_binder = sched.binder

    def binder(pod, node_name):
        svc_binder(pod, node_name)
        confirm_q.append((pod, node_name))

    sched.binder = binder

    def confirm():
        while confirm_q:
            p, n = confirm_q.popleft()
            sched.on_pod_add(p, n)

    return FrontDoor(
        admission, cycle_fn=service.run_local_cycle, post_cycle=confirm
    )
