"""Client agent for the gRPC shim.

`SchedulerClient` is the raw stub (hand-written; no grpc_python_plugin in
the image). `SchedulerAgent` is the cluster-side logic the reference keeps
in-process: it mirrors the informer stream to the shim, carries bindings
back, and — because the shim is stateless like upstream's scheduler
(SURVEY.md §5.3) — recovers from a shim restart by re-listing everything it
knows. A binding the agent fails to apply is reported as a bind_failure so
the shim forgets the assumption and backs the pod off.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Iterator

import grpc

from ..models.api import Node, Pod, PodGroup
from . import convert
from . import scheduler_pb2 as pb
from .server import SERVICE_NAME


class SchedulerClient:
    """Thin typed stub over a grpc channel."""

    def __init__(self, target: str, channel: grpc.Channel | None = None) -> None:
        self.channel = channel or grpc.insecure_channel(target)
        # effective W3C traceparent from the last submit's trailing
        # metadata ("" until a traced submit acks)
        self.last_traceparent = ""
        mk = self.channel.unary_unary
        self._update = mk(
            f"/{SERVICE_NAME}/Update",
            request_serializer=pb.UpdateRequest.SerializeToString,
            response_deserializer=pb.UpdateResponse.FromString,
        )
        self._cycle = mk(
            f"/{SERVICE_NAME}/Cycle",
            request_serializer=pb.CycleRequest.SerializeToString,
            response_deserializer=pb.CycleResponse.FromString,
        )
        self._health = mk(
            f"/{SERVICE_NAME}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )
        self._metrics = mk(
            f"/{SERVICE_NAME}/Metrics",
            request_serializer=pb.MetricsRequest.SerializeToString,
            response_deserializer=pb.MetricsResponse.FromString,
        )
        self._inspect = mk(
            f"/{SERVICE_NAME}/Inspect",
            request_serializer=pb.InspectRequest.SerializeToString,
            response_deserializer=pb.InspectResponse.FromString,
        )
        self._submit = mk(
            f"/{SERVICE_NAME}/Submit",
            request_serializer=pb.SubmitRequest.SerializeToString,
            response_deserializer=pb.SubmitResponse.FromString,
        )
        self._node_churn = mk(
            f"/{SERVICE_NAME}/NodeChurn",
            request_serializer=pb.NodeChurnRequest.SerializeToString,
            response_deserializer=pb.NodeChurnResponse.FromString,
        )

    def update(self, request: pb.UpdateRequest, timeout: float = 10.0):
        return self._update(request, timeout=timeout)

    def cycle(self, timeout: float = 120.0) -> pb.CycleResponse:
        return self._cycle(pb.CycleRequest(), timeout=timeout)

    def health(self, timeout: float = 5.0) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=timeout)

    def metrics_text(self, timeout: float = 10.0) -> bytes:
        return self._metrics(pb.MetricsRequest(), timeout=timeout).prometheus_text

    def inspect(
        self,
        kind: str = "flightrecorder",
        last: int = 0,
        pod_uid: str = "",
        timeout: float = 10.0,
    ) -> dict:
        """Pull flight-recorder data (cycle records / Perfetto trace /
        per-pod timeline) decoded from the JSON payload; raises
        RuntimeError when the server reports an inspection error."""
        import json

        resp = self._inspect(
            pb.InspectRequest(kind=kind, last=last, pod_uid=pod_uid),
            timeout=timeout,
        )
        if not resp.ok:
            raise RuntimeError(f"Inspect({kind!r}): {resp.error}")
        return json.loads(resp.json.decode())

    def submit(
        self, pods, timeout: float = 30.0, traceparent: str = "",
    ) -> pb.SubmitResponse:
        """Submit pending pods through the admission front door.
        `pods` are models.api.Pod objects. Raises grpc.RpcError with
        RESOURCE_EXHAUSTED on shed (retry-after hint in the trailing
        metadata key "retry-after-ms"), INVALID_ARGUMENT on malformed
        pods, UNAVAILABLE while the server drains.

        `traceparent` (W3C) joins the submission's trace spans to the
        caller's trace; either way the server's effective traceparent
        (the caller's, or a head-sampled root it minted) comes back in
        the trailing metadata and lands in `self.last_traceparent`
        ("" when tracing is unarmed or the pod was not sampled)."""
        request = pb.SubmitRequest(
            pods=[convert.pod_to(p) for p in pods]
        )
        metadata = (
            (("traceparent", traceparent),) if traceparent else None
        )
        resp, call = self._submit.with_call(
            request, timeout=timeout, metadata=metadata
        )
        self.last_traceparent = ""
        for key, value in call.trailing_metadata() or ():
            if key == "traceparent":
                self.last_traceparent = value
                break
        return resp

    def node_churn(
        self, adds=(), updates=(), deletes=(), timeout: float = 30.0
    ) -> pb.NodeChurnResponse:
        """Node churn through the front door (journaled before ack;
        never shed)."""
        return self._node_churn(
            pb.NodeChurnRequest(
                adds=[convert.node_to(n) for n in adds],
                updates=[convert.node_to(n) for n in updates],
                deletes=list(deletes),
            ),
            timeout=timeout,
        )

    def close(self) -> None:
        self.channel.close()


# bind_applier(pod_uid, pod_name, namespace, node_name) -> None; raise = failed
BindApplier = Callable[[str, str, str, str], None]


class SchedulerAgent:
    """Mirrors cluster objects into the shim and applies its decisions.

    Keeps a local store of every live object so a full re-list can be
    replayed after the shim restarts (same recovery the reference gets from
    client-go informers re-listing into a fresh scheduler process)."""

    def __init__(self, client: SchedulerClient, bind_applier: BindApplier,
                 evict_applier: Callable[[str, str], None] | None = None,
                 event_applier: Callable[["pb.Event"], None] | None = None) -> None:
        self.client = client
        self.bind_applier = bind_applier
        self.evict_applier = evict_applier or (lambda uid, node: None)
        # posts each drained scheduler event as a Kubernetes Event
        self.event_applier = event_applier or (lambda ev: None)
        # informer-side mirror of the cluster view, NOT WAL-tracked
        # state (the server's cache._nodes is the durable copy)
        self._node_mirror: dict[str, Node] = {}
        self._pods: dict[str, tuple[Pod, str]] = {}  # uid -> (pod, bound_node)
        self._groups: dict[str, PodGroup] = {}
        self._pvcs: dict[str, object] = {}
        self._pvs: dict[str, object] = {}
        self._classes: dict[str, object] = {}
        self._pdbs: dict[str, object] = {}
        self._pending_failures: list[str] = []
        self._boot_id: str | None = None  # shim incarnation last fed state
        self._batch: pb.UpdateRequest | None = None  # open batched() request

    # ---- informer-side entry points -------------------------------------

    def upsert_node(self, node: Node) -> None:
        known = node.name in self._node_mirror
        self._node_mirror[node.name] = node
        self._send(
            pb.UpdateRequest(
                **{
                    ("node_updates" if known else "node_adds"): [
                        convert.node_to(node)
                    ]
                }
            )
        )

    def delete_node(self, name: str) -> None:
        self._node_mirror.pop(name, None)
        self._send(pb.UpdateRequest(node_deletes=[name]))

    def upsert_pod(self, pod: Pod, bound_node: str = "") -> None:
        known = pod.uid in self._pods
        self._pods[pod.uid] = (pod, bound_node)
        ev = pb.PodEvent(pod=convert.pod_to(pod), bound_node=bound_node)
        self._send(
            pb.UpdateRequest(
                **{("pod_updates" if known else "pod_adds"): [ev]}
            )
        )

    def delete_pod(self, uid: str) -> None:
        self._pods.pop(uid, None)
        self._send(pb.UpdateRequest(pod_deletes=[uid]))

    def add_pod_group(self, group: PodGroup) -> None:
        self._groups[group.name] = group
        self._send(
            pb.UpdateRequest(
                pod_groups=[pb.PodGroup(name=group.name,
                                        min_member=group.min_member)]
            )
        )

    # ---- volume objects (VolumeBinding inputs) ---------------------------

    def upsert_pvc(self, pvc) -> None:
        self._pvcs[pvc.key] = pvc
        self._send(pb.UpdateRequest(pvc_upserts=[convert.pvc_to(pvc)]))

    def delete_pvc(self, key: str) -> None:
        self._pvcs.pop(key, None)
        self._send(pb.UpdateRequest(pvc_deletes=[key]))

    def upsert_pv(self, pv) -> None:
        self._pvs[pv.name] = pv
        self._send(pb.UpdateRequest(pv_upserts=[convert.pv_to(pv)]))

    def delete_pv(self, name: str) -> None:
        self._pvs.pop(name, None)
        self._send(pb.UpdateRequest(pv_deletes=[name]))

    def upsert_storage_class(self, sc) -> None:
        self._classes[sc.name] = sc
        self._send(
            pb.UpdateRequest(storage_class_upserts=[convert.storage_class_to(sc)])
        )

    def delete_storage_class(self, name: str) -> None:
        self._classes.pop(name, None)
        self._send(pb.UpdateRequest(storage_class_deletes=[name]))

    def upsert_pdb(self, pdb) -> None:
        self._pdbs[pdb.key] = pdb
        self._send(pb.UpdateRequest(pdb_upserts=[convert.pdb_to(pdb)]))

    def delete_pdb(self, key: str) -> None:
        self._pdbs.pop(key, None)
        self._send(pb.UpdateRequest(pdb_deletes=[key]))

    # ---- the cycle -------------------------------------------------------

    def run_cycle(self) -> pb.CycleResponse:
        """Flush failures, run one cycle, apply bindings/evictions."""
        if self._pending_failures:
            self._send(pb.UpdateRequest(bind_failures=self._pending_failures))
            self._pending_failures = []
        resp = self._with_recovery(self.client.cycle)
        if self._boot_changed(resp.boot_id):
            # the shim restarted since we fed it state and the cycle ran
            # against an empty cache — replay everything and re-run
            self.relist()
            resp = self._with_recovery(self.client.cycle)
        confirmed = pb.UpdateRequest()
        for b in resp.bindings:
            try:
                self.bind_applier(
                    b.pod_uid, b.pod_name, b.pod_namespace, b.node_name
                )
            except Exception:
                self._pending_failures.append(b.pod_uid)
                continue
            pod, _ = self._pods.get(b.pod_uid, (None, ""))
            if pod is not None:
                self._pods[b.pod_uid] = (pod, b.node_name)
                confirmed.pod_updates.append(
                    pb.PodEvent(pod=convert.pod_to(pod), bound_node=b.node_name)
                )
        for ev in resp.evictions:
            self.evict_applier(ev.pod_uid, ev.node_name)
        for ev in resp.events:
            self.event_applier(ev)
        if confirmed.pod_updates:
            self._send(confirmed)
        return resp

    # ---- transport + recovery -------------------------------------------

    def _boot_changed(self, boot_id: str) -> bool:
        """Track the shim incarnation; True when a restart was detected
        (a restarted shim at the same address answers RPCs normally but
        holds empty state — the boot_id is the only tell)."""
        if self._boot_id == boot_id:
            return False
        first = self._boot_id is None
        self._boot_id = boot_id
        return not first

    @contextlib.contextmanager
    def batched(self) -> Iterator[None]:
        """Coalesce every upsert/delete inside the block into ONE Update
        RPC — the informer re-list path would otherwise pay one round-trip
        per object (10k pods = 10k RPCs). Nesting reuses the open batch."""
        if self._batch is not None:
            yield
            return
        self._batch = pb.UpdateRequest()
        try:
            yield
            batch, self._batch = self._batch, None
            if batch.SerializeToString():
                self._send(batch)
        finally:
            self._batch = None

    def _send(self, request: pb.UpdateRequest) -> None:
        if self._batch is not None:
            self._batch.MergeFrom(request)
            return
        resp = self._with_recovery(lambda: self.client.update(request))
        if self._boot_changed(resp.boot_id):
            # state before this delta is gone: replay everything (the delta
            # itself was applied to the fresh shim, and relist re-sends the
            # full store including it, which is idempotent)
            self.relist()

    def _with_recovery(self, call):
        try:
            return call()
        except grpc.RpcError as e:
            if e.code() not in (
                grpc.StatusCode.UNAVAILABLE,
                grpc.StatusCode.DEADLINE_EXCEEDED,
            ):
                raise
            # shim restarted (or hiccuped): replay the full state, retry once
            self.relist()
            return call()

    def relist(self) -> None:
        """Replay everything we know into a (possibly fresh) shim."""
        req = pb.UpdateRequest()
        for node in self._node_mirror.values():
            req.node_adds.append(convert.node_to(node))
        for g in self._groups.values():
            req.pod_groups.append(
                pb.PodGroup(name=g.name, min_member=g.min_member)
            )
        for pod, bound in self._pods.values():
            req.pod_adds.append(
                pb.PodEvent(pod=convert.pod_to(pod), bound_node=bound)
            )
        for pvc in self._pvcs.values():
            req.pvc_upserts.append(convert.pvc_to(pvc))
        for pv in self._pvs.values():
            req.pv_upserts.append(convert.pv_to(pv))
        for sc in self._classes.values():
            req.storage_class_upserts.append(convert.storage_class_to(sc))
        for pdb in self._pdbs.values():
            req.pdb_upserts.append(convert.pdb_to(pdb))
        resp = self.client.update(req)
        self._boot_id = resp.boot_id
