"""The scheduling cycle: one jitted program, pending pods in, bindings out.

TPU-native replacement for the reference's `ScheduleOne` hot loop
(SURVEY.md §3.2; expected `schedule_one.go` / `core/generic_scheduler.go`
[UNVERIFIED], mount empty). Where the reference runs, per pod:

    RunPreFilterPlugins -> RunFilterPlugins (16 goroutines over nodes)
    -> RunScorePlugins -> selectHost -> cache.AssumePod

this program computes, per cycle, for the WHOLE pending set:

    CycleContext precomputes (PreFilter analogue, batched)
    -> framework static masks/scores ([P, N], commitment-independent)
    -> greedy sequential-commit scan (dynamic residue: resource fit,
       running domain counts) -> assignment [P]

The framework (framework/runtime.py) decides which plugins contribute;
`build_cycle_fn` bakes one Framework into one compiled program."""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.interfaces import CycleContext
from ..framework.runtime import Framework
from ..models.encoding import ClusterSnapshot
from ..parallel.mesh import mesh_pin
from ..ops import commit as commit_ops
from ..ops import rounds as rounds_ops
from ..ops import volumes as volumes_ops
from . import faults as _faults


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleResult:
    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-cycle
    unschedulable: jnp.ndarray  # bool [P] valid pod that found no node
    gang_dropped: jnp.ndarray  # bool [P] placed, then unwound (group failed)
    # NOTE: the PostFilter candidate gate is no longer a cycle output —
    # the preemption program computes its own per-candidate static gate
    # (all static filters EXCEPT NodePorts, whose existing-pod conflicts
    # eviction can free) and checks every evictable constraint per victim
    # prefix itself (ops/preemption.py).
    reject_counts: jnp.ndarray  # i32 [P, F] nodes first-rejected per filter
    # (static + dynamic attribution summed; columns = Framework.filter_names)
    # — feeds FailedScheduling events and requeue queueing hints
    pv_claimed: jnp.ndarray  # bool [V] static PVs claimed by this cycle's
    # placements (all-False when VolumeBinding carries no state). The
    # diagnosis program consumes the ENGINE's actual bitmap — a batched
    # replay could reconstruct different claims when a pod was revoked
    # and re-accepted across rounds.
    rounds_used: jnp.ndarray  # i32 [] commit rounds consumed (0 in scan mode)
    accepted_per_round: jnp.ndarray  # i32 [max_rounds] acceptance counts
    # per commit round (zeros in scan mode) — convergence diagnostics
    diag_per_round: jnp.ndarray  # i32 [max_rounds, 3] (live claims,
    # capacity rejections, guard rejections) per round, summed over passes


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleDecision:
    """The latency-critical subset of a cycle's outputs: exactly what the
    driver must have in hand before bindings can go out, and nothing
    else. `build_cycle_fn(outputs="latency")` returns this instead of
    CycleResult — reject attribution, per-round convergence diagnostics,
    and the PV claim bitmap are then never computed on the decision
    path (XLA dead-code-eliminates their kernels from the compiled
    program); FailedScheduling attribution comes from the separate
    diagnosis program (build_diagnosis_fn), off-path."""

    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-cycle (the carry)
    unschedulable: jnp.ndarray  # bool [P] valid pod that found no node
    gang_dropped: jnp.ndarray  # bool [P] placed, then unwound


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MultiCycleResult:
    """Stacked decisions of one multi-cycle dispatch (K inner cycles,
    build_packed_multicycle_fn). Rows past `cycles_run` were never
    executed (early exit on drain) and carry the init fill (-1 / False /
    zeros)."""

    assignment: jnp.ndarray  # i32 [K, P] node index or -1
    unschedulable: jnp.ndarray  # bool [K, P]
    gang_dropped: jnp.ndarray  # bool [K, P]
    attempted: jnp.ndarray  # bool [K, P] inner cycle i's pod_valid — the
    # host maps row i's leading slots back onto delta group i's pods
    node_requested: jnp.ndarray  # f32 [K, N, R] POST-inner-cycle state:
    # row i feeds that inner cycle's deferred diagnosis/preemption
    # programs (device-resident; never part of the slimmed fetch)
    cycles_run: jnp.ndarray  # i32 [] inner cycles actually executed
    # the loop's FINAL carry, exposed so a depth-2 speculative batch can
    # chain device-to-device (ServingPipeline.dispatch_multi carry0=…):
    # the continuation program consumes these without a host round trip
    carry_node_requested: jnp.ndarray  # f32 [N, R] post-batch capacity
    carry_gplaced: jnp.ndarray  # i32 [G] per-group members placed by
    # this batch (continuation batches add it to their own carry)


def multicycle_unsupported_reason(snap: ClusterSnapshot) -> str | None:
    """Why this snapshot is outside the multi-cycle envelope (None = in).

    The device-resident K-cycle loop carries exactly two pieces of
    cross-cycle state: `node_requested` and the per-group placed-member
    counts. That is EXACT — bit-identical to K sequential dispatches
    with host bind-folding between them — precisely when no enabled
    capability reads any other existing-pod-derived state. Capabilities
    that do (and therefore fall back to sequential single-cycle
    dispatches, scheduler-side):

    - inter-pod affinity / topology spread: a bind changes the
      matched-existing tables and domain counts the next cycle reads;
    - volumes: a bind claims PVs the next cycle's VolumeBinding state
      must see;
    - host ports: a bind occupies ports in the node port bitmap;
    - extenders: verdicts are consulted per host cycle, not per inner
      device cycle.

    The flags are per-SNAPSHOT capabilities (what the pending/existing
    pods actually carry), not per-config — a default plugin set serving
    an affinity-free workload stays in the envelope."""
    if snap.has_extender:
        return "extender"
    if snap.has_inter_pod_affinity:
        return "inter_pod_affinity"
    if snap.has_topology_spread:
        return "topology_spread"
    if snap.has_volumes:
        return "volumes"
    # host ports: only PENDING pods that actually request a port can
    # occupy one — port-free binds leave the node port bitmaps
    # untouched, so a port-free pending set stays exact regardless of
    # what existing pods hold. (num_distinct_ports is a sticky padded
    # dictionary size with a nonzero floor — useless as a signal.)
    # pod_port_ids is an ARRAY field: concrete on the host-side
    # snapshots this gate runs on, a tracer inside the compiled loop —
    # where the host has already gated, so the check is skipped.
    ports = snap.pod_port_ids
    if isinstance(ports, np.ndarray) and bool((ports >= 0).any()):
        return "host_ports"
    return None


def sampling_mask(snap: ClusterSnapshot, pct: int) -> jnp.ndarray:
    """percentageOfNodesToScore: restrict each pod to a rotating window of
    candidate nodes (bool [P, N]).

    Upstream numFeasibleNodesToFind semantics: clusters of <100 nodes (or
    pct >= 100) consider everything; otherwise the candidate count is
    numAllNodes * pct / 100 (adaptive pct = 50 - numAllNodes/125, floor 5,
    when the knob is 0), floored at 100 nodes. Upstream stops SCANNING
    after finding that many feasible nodes from a rotating start index;
    the batched analogue samples that many CANDIDATE nodes per pod from a
    deterministic per-pod rotation — a documented deviation (data-
    dependent early exit is anti-TPU), strictly more selective, and the
    sample rotates with the pod's queue rank exactly so different pods
    spread load over different nodes."""
    n = snap.num_nodes.astype(jnp.int32)  # real node count (traced)
    if pct >= 100:
        return jnp.ones((snap.P, snap.N), bool)
    if pct <= 0:
        adaptive = jnp.maximum(50 - n // 125, 5)
    else:
        adaptive = jnp.int32(pct)
    k = jnp.maximum(n * adaptive // 100, 100)  # min-feasible floor
    # rotate per pod rank AND per cycle: a pod whose feasible nodes fall
    # outside this cycle's window gets a different window next cycle, so
    # sampling delays but never permanently starves (upstream's rotating
    # global scan index has the same property)
    off = (
        snap.pod_order.astype(jnp.int32) * 75347
        + snap.cycle_index.astype(jnp.int32) * 31337
    ) % jnp.maximum(n, 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (snap.P, snap.N), 1)
    win = (col - off[:, None]) % jnp.maximum(n, 1)
    # clusters under the floor consider every node (win < k always)
    return win < k


def _unique(fn, base: str, disc: str = ""):
    """Give each built program a DETERMINISTIC distinctive __name__ (and
    therefore HLO module name): stable across process restarts (the
    name feeds the persistent compilation-cache key, so a process
    counter would force full recompiles after every restart), yet
    distinct between builders with different inputs (a discriminator
    hash) — two in-process jits with byte-identical programs are the
    trigger for the executable-cache corruption _Resilient heals."""
    if disc:
        import hashlib

        base = f"{base}_{hashlib.sha1(disc.encode()).hexdigest()[:8]}"
    fn.__name__ = base
    fn.__qualname__ = base
    return fn


# runtime executable-cache corruption signatures (see _Resilient)
_CORRUPT_MARKERS = (
    "compiled program expected",   # supplied N buffers, expected N+1
    "buffer with incompatible size",  # stale entry from another regime
    "Executable expected parameter",
)

# rig wedge signatures (round 5): after an E/MPN-regime flip, the second
# invocation of the second-regime preemption executable raises this and
# the process's backend SESSION is wedged — every later device op,
# including plain device_put, fails; clear_cache + retrace does NOT heal
# it (verified on-rig), so retrying would only burn ~100 s retraces
# before the inevitable raise. _Resilient records the strike and raises
# IMMEDIATELY: a process restart with the warm persistent compilation
# cache (~1-7 s) is the recovery, per the stateless design. Avoidance:
# pre-size the sticky E and MPN pads (SnapshotEncoder(pad_existing=...,
# pad_pods_per_node=...)) so bind-folding never flips the regime
# mid-serving. Marker = common substring of the observed formats
# ('INVALID_ARGUMENT: TPU backend error (InvalidArgument)').
_WEDGE_MARKERS = (
    "TPU backend error",
)

# tunneled-rig transport flake signatures: the compile/execute RPC dies
# mid-flight (BENCH_r03's `remote_compile: read body: response body
# closed`). Nothing device-side is corrupted — the request never
# completed — so a plain re-invoke (no clear_cache) recovers; matched
# case-insensitively and kept narrow so real errors re-raise.
_TRANSPORT_MARKERS = (
    "remote_compile",
    "remote_execute",
    "response body closed",
    "read body",
    "connection reset",
    "broken pipe",
    "connection refused",
    "unexpected eof",
)


def is_transport_error(e: BaseException) -> bool:
    """True when `e` looks like a tunnel/RPC transport flake (retryable
    without clearing compiled state) rather than a program error."""
    msg = str(e).lower()
    return any(m in msg for m in _TRANSPORT_MARKERS)


def classify_failure(e: BaseException) -> str:
    """Failure class of a device/dispatch error, by the SAME marker
    precedence `_Resilient` recovers with: transport (flake, cache
    preserved) before corrupt (clear_cache heals) before wedge (process
    restart heals). Feeds `scheduler_fetch_failures_total{class}` and
    the degradation ladder's transition reasons."""
    msg = str(e)
    if is_transport_error(e):
        return "transport"
    if any(m in msg for m in _CORRUPT_MARKERS):
        return "corrupt"
    if any(m in msg for m in _WEDGE_MARKERS):
        return "wedge"
    return "other"


# per-process strike log: (program name, kind) -> count. Mirrored into
# the prometheus counter (scheduler_program_retry_strikes_total) so
# operators can see how often serving pays a retry; kept as a plain
# dict too so tests and the bench can read it without a registry scrape.
RESILIENT_STRIKES: dict[tuple[str, str], int] = {}


def _record_strike(program: str, kind: str) -> None:
    key = (program, kind)
    RESILIENT_STRIKES[key] = RESILIENT_STRIKES.get(key, 0) + 1
    try:
        from ..metrics.metrics import global_metrics

        global_metrics().program_retry_strikes.labels(
            program=program, kind=kind
        ).inc()
    except Exception:  # schedlint: disable=RB001 -- deliberately silent:
        # the strike itself IS the trace (RESILIENT_STRIKES + the
        # caller's retry log); a broken metrics registry must not break
        # the serving path it observes
        pass


class _Resilient:
    """Retry wrapper for the built jitted programs.

    Two observed failure classes, both recoverable because the programs
    are pure:

    - executable-cache corruption (jax 0.9 + the platform plugin): a
      jit's SECOND call can execute a corrupted/mismatched cached
      executable — 'Execution supplied N buffers but compiled program
      expected N+1' or 'Executable expected parameter I of size X but
      got buffer with incompatible size Y' — with identical avals and
      no retrace. `clear_cache()` + re-trace recovers (verified by
      targeted reproduction); the corruption can strike the retry too,
      so up to three attempts.
    - transport flakes through the tunnel (`remote_compile: response
      body closed` killed round 3's official bench): the RPC died
      mid-flight, nothing is corrupted; re-invoke WITHOUT clearing the
      cache after a short backoff.

    Every retry is recorded in RESILIENT_STRIKES and the
    scheduler_program_retry_strikes_total metric (kind =
    executable_cache | transport). Anything else re-raises.

    An AOT-compiled executable (core/compile_cache.py: loaded from the
    persistent cache or compiled up front) can be installed via
    `install_aot`; calls whose argument avals match run it directly —
    the jit path stays as the fallback for any other call shape (e.g.
    the preemption program fed a CycleDecision by the multi-cycle path
    where the single-cycle path feeds a CycleResult) and as the
    executable-cache-corruption recovery."""

    def __init__(self, fn):
        self._fn = fn
        self._aot = None

    def install_aot(self, compiled) -> None:
        """Serve through an AOT executable for matching-aval calls."""
        self._aot = compiled

    def __call__(self, *a, **k):
        # classify by MESSAGE, not exception type: a transport flake can
        # surface as a wrapped ValueError and a corruption marker can ride
        # a non-ValueError (advisor r4) — one except block, two recoveries
        for attempt in range(3):
            try:
                if _faults.ARMED:
                    # fault injection (core/faults.py `device_error`):
                    # raises with a real marker signature INSIDE the
                    # try, so the injected fault walks the exact
                    # transport/corrupt/wedge recovery below
                    _faults.raise_device_error()
                aot = self._aot
                if aot is not None:
                    try:
                        return aot(*a, **k)
                    except TypeError:
                        # aval/convention mismatch for THIS call shape
                        # (a second legitimate signature of the same
                        # program): fall through to the jit path, which
                        # traces and caches that variant. The AOT
                        # executable stays installed for matching calls.
                        pass
                return self._fn(*a, **k)
            except Exception as e:
                msg = str(e)
                if attempt == 2:
                    raise
                # transport FIRST: a proxied RPC error can embed remote
                # text matching a corrupt marker; the flake recovery
                # (backoff, cache preserved) is right for that case and
                # clear_cache would pay a needless ~100s retrace
                if is_transport_error(e):
                    _record_strike(self._fn.__name__, "transport")
                    import time

                    time.sleep(0.5 * (attempt + 1))
                elif any(m in msg for m in _CORRUPT_MARKERS):
                    # corrupt BEFORE wedge: the wedge marker is a broad
                    # substring ('TPU backend error') that can wrap an
                    # INVALID_ARGUMENT-carried corruption message, and the
                    # healable clear_cache+retry recovery must win when
                    # both match (ADVICE r5)
                    _record_strike(self._fn.__name__, "executable_cache")
                    # a corrupted executable may BE the AOT one: drop it
                    # so the retry re-traces through the cleared jit
                    self._aot = None
                    self._fn.clear_cache()
                elif any(m in msg for m in _WEDGE_MARKERS):
                    # not healable in-process (see _WEDGE_MARKERS):
                    # strike for observability, fail fast for the
                    # restart-based recovery
                    _record_strike(self._fn.__name__, "backend_wedge")
                    raise
                else:
                    raise

    def lower(self, *a, **k):
        return self._fn.lower(*a, **k)

    def clear_cache(self):
        return self._fn.clear_cache()

    def _cache_size(self):
        return self._fn._cache_size()


def _jit(fn, base: str, disc: str = "", **jit_kw):
    return _Resilient(jax.jit(_unique(fn, base, disc), **jit_kw))


def _mesh_desc(mesh) -> str:
    """Deterministic mesh descriptor for program names and cache keys:
    sharded and unsharded builds of one regime are different executables
    and must never share a name (or a persistent-cache entry)."""
    if mesh is None:
        return "none"
    return ",".join(
        f"{axis}{size}" for axis, size in mesh.shape.items()
    )


def _constrain_carry(carry: dict, mesh) -> dict:
    """Pin the carry tables onto the mesh: sbase [P, N] sharded on
    ('pods', 'nodes'-when-divisible); matched-pending [S, P] pinned
    REPLICATED — it is bool (S*P bytes, ~5 MB at the audit shape), and
    letting it shard makes every per-round affinity/spread state
    contraction over the pods axis a cross-device partial sum that XLA
    then all-reduces at [S, N]/[S, D] width (measured 58 MB/cycle at
    the audit shape, dwarfing the 43 MB baseline the diet attacks).
    Identity without a mesh — the single-device path compiles
    byte-identical programs."""
    if mesh is None:
        return carry
    return {
        "sbase": mesh_pin(carry["sbase"], mesh, ("pods", "nodes")),
        "mp": mesh_pin(carry["mp"], mesh, (None, None)),
    }


def _fw_disc(fw: Framework | None) -> str:
    """Deterministic framework discriminator for program names: plugin
    names, score weights, AND per-plugin config args (two profiles with
    the same plugin set but different args compile different programs
    and must not share a name)."""
    if fw is None:
        return "defaultfw"

    def pa(p):
        return f"{p.name}({sorted(p.args.items())!r})"

    return ",".join(
        [pa(f) for f in fw.filters]
        + [f"{pa(s)}:{w}" for s, w in fw.scores]
        + [pa(p) for p in fw.post_filters]
    )


def _make_pv_choice_fn(ctx: CycleContext):
    """The rounds engine's static-PV guard hook: chosen PV per
    (claimant, volume slot) against the live claim bitmap in the
    VolumeBinding extra state. None when the snapshot has no volumes."""
    if not ctx.snap.has_volumes:
        return None

    def pv_choice_fn(vsnap, node_of, live, ext_state):
        claimed = ext_state.get("VolumeBinding")
        MVol = vsnap.pod_vol_mode.shape[1]
        B = node_of.shape[0]
        if claimed is None:  # plugin disabled in this profile
            return jnp.full((B, MVol), -1, jnp.int32)
        # contention-free fold-pass simulation (SDR-safe choice, intra-
        # pod distinctness) so the guard key predicts fold_pv_claims
        return volumes_ops.chosen_pv_slots(
            vsnap, ctx.expr_node_mask, claimed, node_of, live
        )

    return pv_choice_fn


def _pv_claimed_of(snap: ClusterSnapshot, extra) -> jnp.ndarray:
    """The VolumeBinding claim bitmap out of a commit engine's final
    extra state (all-False when the plugin carries no state)."""
    pv = extra.get("VolumeBinding") if isinstance(extra, dict) else None
    if pv is None:
        return jnp.zeros((snap.pv_avail.shape[0],), bool)
    return pv


def _pv_claimed_after_unwind(snap, ctx, extra, assignment, dropped):
    """pv_claimed for CycleResult, with gang-unwound pods' static-PV
    claims released (ADVICE r3 #2: the engine folded claims for pods
    _gang_unwind later dropped, and the diagnosis program would treat
    those PVs as unavailable, misattributing VolumeBinding rejections).

    When any pod was dropped, the bitmap is refolded rank-ordered over
    the SURVIVING accepted set from empty. Residual inaccuracy (reason
    strings only, placements unaffected): the replay can pick different
    PVs than the engine's incremental in-round claims — e.g. a survivor
    who really bound via dynamic provisioning can be re-assigned the
    unwound pod's freed static PV, or two same-class survivors can swap
    identities. Exactness would need per-pod chosen-PV tracking through
    the engines' extra state; the refold keeps the claimed COUNT per
    (class, topology) pool right for survivors, which is what the
    diagnosis program's VolumeBinding attribution keys on. lax.cond
    skips the refold entirely in the no-drop common case."""
    pv = _pv_claimed_of(snap, extra)
    if not isinstance(extra, dict) or "VolumeBinding" not in extra:
        return pv
    if not snap.has_volumes:
        return pv

    def refold(_):
        accepted = snap.pod_valid & (assignment >= 0)  # post-unwind
        return volumes_ops.fold_pv_claims(
            snap, ctx.expr_node_mask, jnp.zeros_like(pv), accepted,
            jnp.maximum(assignment, 0),
            snap.pod_order.astype(jnp.int32),
        )

    return jax.lax.cond(
        jnp.any(dropped), refold, lambda _: pv, None
    )



def _gang_unwind(snap: ClusterSnapshot, result):
    """All-or-nothing gang rollback (Coscheduling analogue, SURVEY.md §2
    C14): groups whose placed-this-cycle count plus already-running
    members stays below minMember get every this-cycle placement
    unwound. Returns (result, dropped bool [P])."""
    placed = snap.pod_valid & (result.assignment >= 0)
    G = snap.group_min_member.shape[0]
    gid = jnp.clip(snap.pod_group, 0, G - 1)
    in_group = snap.pod_group >= 0
    # minMember counts this cycle's placements PLUS members already
    # running (a gang member retried alone after a bind error must not
    # be unwound while its siblings run)
    counts = snap.group_existing_count + jnp.zeros(G, jnp.int32).at[
        gid
    ].add(jnp.where(in_group & placed, 1, 0))
    # minMember defaults to 0 for undeclared groups -> never fails
    fail = counts < snap.group_min_member
    dropped = in_group & fail[gid] & placed
    result = commit_ops.unwind_assignments(
        result, dropped, snap.pod_requested
    )
    return result, dropped


def _make_cycle_body(
    fw: Framework,
    gang_scheduling: bool,
    commit_mode: str,
    max_rounds: int,
    percentage_of_nodes_to_score: int,
    rounds_kw: dict | None,
    outputs: str,
):
    """The UNJITTED cycle body shared by every cycle builder: one
    snapshot in, CycleResult/CycleDecision out. `build_cycle_fn` wraps
    it in a jit; `build_packed_multicycle_fn` re-invokes it K times
    inside a device-resident loop (one trace, K iterations). Extracted
    so the multi-cycle loop executes the EXACT op chain of a single
    dispatch — the bit-identical equivalence contract
    (tests/test_multicycle.py) rests on this sharing."""
    lean = outputs == "latency"

    def cycle(snap: ClusterSnapshot, stable=None) -> CycleResult:
        ctx = CycleContext(snap)
        if stable is not None:
            # device-resident precomputes derived from the STABLE side of
            # the snapshot (existing pods / nodes / dedup tables), built
            # once per stable regime by build_stable_state_fn — seeding
            # the context cache makes XLA drop the in-cycle recompute
            ctx._cache.update(stable)
        if lean:
            # same mask/score op chain as fw.static (bit-identical
            # outputs), minus the per-filter first-rejector attribution
            smask, sscore = fw.static_lean(ctx)
            srejects = None
        else:
            smask, sscore, srejects = fw.static(ctx)
        if snap.has_extender:
            # HTTP-extender Filter/Prioritize verdicts, computed host-side
            # before the cycle (upstream runs extenders after in-tree
            # filters; rejections are attributed to the base mask)
            smask = smask & snap.pod_extender_mask
            sscore = sscore + snap.pod_extender_score
        smask_all_nodes = smask  # pre-sampling (preemption gate base)
        if percentage_of_nodes_to_score < 100:
            # 0 = adaptive percentage, like upstream's default; the <100-
            # node floor inside sampling_mask keeps small clusters exact
            smask = smask & sampling_mask(snap, percentage_of_nodes_to_score)
        if snap.has_inter_pod_affinity or snap.has_topology_spread:
            # materialize the shared match tables at CYCLE scope: the scan
            # body would otherwise compute-and-cache them inside its own
            # trace, and the post-commit gate pass reading the cache would
            # see an escaped inner tracer
            ctx.matched_pending
        extra = fw.extra_init(ctx)

        if commit_mode == "rounds":
            # the rounds engine re-invokes the plugin kernels on COMPACTED
            # pod views (a ClusterSnapshot gathered at the active ids); a
            # view context shares the full context's node-side precomputes
            # and swaps in the view's matched-pending columns
            def view_ctx(vsnap, vmp):
                vctx = CycleContext(vsnap)
                vctx._cache.update(ctx._cache)
                vctx._cache["matched_pending"] = vmp
                return vctx

            def dyn_batched_view_fn(vsnap, vmp, node_req, ext, vsmask):
                return fw.dyn_batched(view_ctx(vsnap, vmp), node_req, ext,
                                      vsmask)

            def update_batched_view_fn(vsnap, vmp, ext, accepted, node_of):
                return fw.extra_update_batched(
                    view_ctx(vsnap, vmp), ext, accepted, node_of
                )

            rres = rounds_ops.rounds_commit(
                snap=snap,
                static_mask=smask,
                static_score=sscore,
                m_pending=ctx.matched_pending,
                dyn_batched_view_fn=dyn_batched_view_fn,
                update_batched_view_fn=update_batched_view_fn,
                extra=extra,
                max_rounds=max_rounds,
                score_anchor_fn=lambda nr: fw.score_anchor(ctx, nr),
                pv_choice_fn=_make_pv_choice_fn(ctx),
                **(rounds_kw or {}),
            )
            # Final-state work (dynamic reject attribution + the NodePorts
            # part of the preemption gate) only matters for pods that never
            # placed — computed on a COMPACTED view instead of a full
            # [P, N] dyn pass. PREEMPTION-ELIGIBLE unplaced pods fill the
            # window first (by rank), so the window can never be exhausted
            # by preemptionPolicy:Never pods ahead of eligible preemptors
            # (the window is >= the preemption budget, so every pod the
            # PostFilter would consider gets real gate rows); other
            # unplaced pods follow and get attribution on a best-effort
            # basis — beyond the window: empty gate rows and zero dyn
            # attribution, retried next cycle. The latency program skips
            # all of it (the diagnosis program owns attribution there).
            if lean:
                dyn_aux = jnp.zeros(
                    (snap.P, len(fw.filters)), jnp.int32
                )
            else:
                unplaced = snap.pod_valid & (rres.assignment < 0)
                B_attr = rounds_ops.compact_window(snap.P)
                rank32 = snap.pod_order.astype(jnp.int32)
                ucan = unplaced & snap.pod_can_preempt
                ukey = jnp.where(
                    ucan, rank32,
                    jnp.where(unplaced, rank32 + jnp.int32(1 << 24),
                              jnp.int32(2**31 - 1)),
                )
                ugid = jnp.argsort(ukey)[:B_attr].astype(jnp.int32)
                uact = unplaced[ugid]
                uvsnap = rounds_ops._pod_view(snap, ugid)
                uvmp = ctx.matched_pending[:, ugid]
                uvsmask = smask[ugid]
                _um, _us, upf = dyn_batched_view_fn(
                    uvsnap, uvmp, rres.node_requested, rres.extra, uvsmask
                )
                urejects = fw.attribute_rejects(uvsmask, upf, rows=uact)
                dyn_aux = (
                    jnp.zeros((snap.P, len(fw.filters)), jnp.int32)
                    .at[ugid]
                    .add(jnp.where(uact[:, None], urejects, 0))
                )
            result = commit_ops.CommitResult(
                assignment=rres.assignment,
                node_requested=rres.node_requested,
                extra=rres.extra,
                dyn_aux=dyn_aux,
            )
            rounds_used = rres.rounds_used
            accepted_per_round = rres.accepted_per_round
            diag_per_round = rres.diag_per_round
        else:
            def dyn_fn(p, node_req, ext, static_row):
                out = fw.dyn(ctx, p, node_req, ext, static_row)
                # latency program: drop the per-step reject attribution
                # (the scan then stacks a scalar zero instead of [F]
                # counts, and XLA removes the attribution kernels)
                return out[:2] if lean else out

            def update_fn(ext, p, node, ok):
                return fw.extra_update(ctx, ext, p, node, ok)

            rounds_used = jnp.int32(0)
            accepted_per_round = jnp.zeros((max_rounds,), jnp.int32)
            diag_per_round = jnp.zeros((max_rounds, 3), jnp.int32)
            order = jnp.argsort(snap.pod_order)
            result = commit_ops.greedy_commit(
                order=order,
                static_mask=smask,
                static_score=sscore,
                pod_requested=snap.pod_requested,
                pod_valid=snap.pod_valid,
                pod_nominated=snap.pod_nominated,
                node_allocatable=snap.node_allocatable,
                node_requested=snap.node_requested,
                dyn_fn=dyn_fn,
                extra=extra,
                update_fn=update_fn,
            )
        dropped = jnp.zeros_like(snap.pod_valid)
        if gang_scheduling:
            result, dropped = _gang_unwind(snap, result)
        unsched = snap.pod_valid & (result.assignment < 0)

        if lean:
            return CycleDecision(
                result.assignment, result.node_requested, unsched, dropped
            )
        return CycleResult(
            result.assignment, result.node_requested, unsched, dropped,
            srejects + result.dyn_aux,
            _pv_claimed_after_unwind(
                snap, ctx, result.extra, result.assignment, dropped
            ),
            rounds_used, accepted_per_round, diag_per_round,
        )

    return cycle


def build_cycle_fn(
    framework: Framework | None = None,
    gang_scheduling: bool = True,
    commit_mode: str = "scan",
    max_rounds: int = 64,
    percentage_of_nodes_to_score: int = 0,  # 0 = adaptive (upstream default)
    rounds_kw: dict | None = None,  # compact/passes/shortlist overrides
    outputs: str = "full",  # "full" -> CycleResult, "latency" ->
    # CycleDecision: only the decision carry is computed; reject
    # attribution / per-round diagnostics / pv_claimed move off the
    # decision path (build_diagnosis_fn is the deferred companion)
) -> Callable[[ClusterSnapshot], CycleResult]:
    """Compile the cycle for a framework (default: the default plugin set).
    The returned callable is jitted; snapshots with identical padded shapes
    reuse the compiled program.

    `outputs` selects the split-phase axis: "full" returns the classic
    CycleResult (diagnostic outputs fused into the decision program);
    "latency" returns a CycleDecision whose compiled program contains ONLY
    the work needed to decide placements — the parity contract (enforced
    by tests/test_pipeline.py) is that its assignment/node_requested/
    unschedulable/gang_dropped are bit-identical to the monolithic
    program's in both commit modes.

    `commit_mode` selects the in-cycle commitment engine:
      - "scan": the strict sequential scan (ops/commit.py) — exact
        one-pod-at-a-time ScheduleOne semantics, one lax.scan step per
        pod. Best for small pending sets and for differential parity.
      - "rounds": the round-based batched commit (ops/rounds.py) — a few
        MXU-wide rounds instead of P sequential steps; the production
        mode at 10k-pod scale (~1000x faster on TPU; see ops/rounds.py
        for the documented semantics contract).

    With `gang_scheduling` (the Coscheduling plugin analogue, SURVEY.md §2
    C14), pods carrying a pod-group whose placed-member count stays below
    the group's minMember are rolled back after the commit scan — the
    all-or-nothing semantics upstream gets from Permit-and-wait, here a
    single batched unwind. minMember counts pods placed THIS cycle;
    already-running members are bound facts, not waiters."""
    fw = framework or Framework.from_config()
    if commit_mode not in ("scan", "rounds"):
        raise ValueError(f"unknown commit_mode {commit_mode!r}")
    if outputs not in ("full", "latency"):
        raise ValueError(f"unknown outputs {outputs!r}")
    if commit_mode == "rounds":
        fw.check_batched_parity()
    cycle = _make_cycle_body(
        fw, gang_scheduling, commit_mode, max_rounds,
        percentage_of_nodes_to_score, rounds_kw, outputs,
    )
    return _jit(
        cycle, "cycle",
        disc=(
            f"{commit_mode}|{gang_scheduling}|{max_rounds}|"
            f"{percentage_of_nodes_to_score}|{outputs}|"
            f"{sorted((rounds_kw or {}).items())!r}|{_fw_disc(fw)}"
        ),
    )


def build_packed_cycle_fn(spec, **kw):
    """Packed-input variant of build_cycle_fn: takes the (u32, u8) buffers
    of models.packing.pack instead of a ClusterSnapshot. On the tunneled
    TPU rig, feeding a program ~80 freshly-assembled arrays costs a large
    per-buffer first-use overhead every cycle; two packed buffers make it
    negligible. The unpack is static slices + bitcasts, fused by XLA.

    The returned callable takes an optional third argument: the output of
    build_stable_state_fn (device-resident precomputes for the stable
    side), which removes the per-cycle recompute of existing-pod match
    tables / initial affinity state / node expression masks."""
    from ..models import packing

    cycle = build_cycle_fn(**kw)

    def packed(wbuf, bbuf, stable=None):
        return cycle(packing.unpack(wbuf, bbuf, spec), stable)

    scalars = {k: v for k, v in kw.items() if k != "framework"}
    return _jit(
        packed, "packed_cycle",
        disc=(
            repr(spec.key()) + repr(sorted(scalars.items()))
            + _fw_disc(kw.get("framework"))
        ),
    )


def build_arena_cycle_fn(spec, **kw):
    """The MULTI-TENANT arena program: a vmapped build_packed_cycle_fn.
    Takes STACKED packed buffers (u32 [T, W], u8 [T, B]) — one row per
    virtual cluster, all sharing one pad regime (`spec`) — and returns a
    CycleResult whose every field carries a leading tenant axis. One
    compiled program, one compile-cache entry, schedules every tenant in
    the stack per dispatch; tenant count T is baked into the trace, so
    the arena packer (tenancy/arena.py) pads T to pow2 buckets to keep
    the set of executables small and churn-stable.

    The per-row op chain is the EXACT `_make_cycle_body` chain of a
    single packed dispatch — the per-tenant bit-equality contract
    (tests/test_tenancy.py: packed N-tenant run == N sequential
    single-tenant runs) rests on vmap's batching rules preserving each
    row's reduction/sort/scan structure. Zero-filled pad rows unpack to
    all-invalid snapshots and decide nothing; callers discard them.

    `stable` precomputes are not supported here: they are per-tenant
    state and stacking them would tie every tenant's stable regime to
    the bucket's — the small-snapshot arena regime recomputes them
    in-trace instead."""
    from ..models import packing

    fw = kw.get("framework") or Framework.from_config()
    commit_mode = kw.get("commit_mode", "scan")
    if commit_mode == "rounds":
        fw.check_batched_parity()
    cycle = _make_cycle_body(
        fw,
        kw.get("gang_scheduling", True),
        commit_mode,
        kw.get("max_rounds", 64),
        kw.get("percentage_of_nodes_to_score", 0),
        kw.get("rounds_kw"),
        kw.get("outputs", "full"),
    )

    def row(wbuf, bbuf):
        return cycle(packing.unpack(wbuf, bbuf, spec), None)

    def arena(wbufs, bbufs):
        return jax.vmap(row)(wbufs, bbufs)

    scalars = {k: v for k, v in kw.items() if k != "framework"}
    return _jit(
        arena, "arena_cycle",
        disc=(
            repr(spec.key()) + repr(sorted(scalars.items()))
            + _fw_disc(kw.get("framework"))
        ),
    )


def build_packed_multicycle_fn(
    spec,
    framework: Framework | None = None,
    k: int = 4,
    gang_scheduling: bool = True,
    commit_mode: str = "rounds",
    max_rounds: int = 64,
    percentage_of_nodes_to_score: int = 0,
    rounds_kw: dict | None = None,
    carry_in: bool = False,
):
    """The MULTI-CYCLE serving program: up to `k` scheduling cycles per
    dispatch inside a device-resident `lax.while_loop`, amortizing the
    ~100 ms remote-compile tunnel round trip K-fold for small-delta
    cycles (ROADMAP item 1 — `tunnel_rt / K` instead of `tunnel_rt`).

    Inputs: `(wbufs u32 [K, W], bbufs u8 [K, B], stable, n_cycles i32)`
    — a stacked per-cycle delta feed: row i is the packed snapshot the
    host would have dispatched as cycle i (its own pending group, ranks,
    cycle_index), all encoded against the PRE-batch cache state. The
    loop threads the post-cycle carry the host fold would have produced:

      - `node_requested` — inner cycle i+1 schedules against cycle i's
        post-commit capacity, overriding the (stale) snapshot field;
      - per-group placed counts — folded into `group_existing_count` so
        a gang spanning inner cycles still reaches minMember.

    Within the supported envelope (`multicycle_unsupported_reason` —
    no inter-pod affinity / topology spread / volumes / host ports /
    extenders) these two are the ONLY existing-pod-derived state the
    cycle body reads, so the loop is bit-identical to K sequential
    single-cycle dispatches with host bind-folding between them
    (tests/test_multicycle.py asserts exactly that). The inner body IS
    the single-dispatch body (`_make_cycle_body`, outputs="latency"),
    traced once.

    Early exit: the loop stops at `n_cycles` or as soon as every
    remaining row carries zero valid pods (the pending set drained), so
    a short batch never pays the full K iterations. `cycles_run`
    reports how many rows are real.

    There is no clock under jit, so per-inner-cycle device time cannot
    be stamped on device; the host apportions the measured batch window
    by per-cycle attempted-pod counts (core/scheduler.py) — the
    `device_share` phase in core/observe.PHASES.

    `carry_in=True` builds the CONTINUATION variant (depth-2
    speculative dispatch, ServingPipeline.dispatch_multi carry0=…):
    the callable takes two extra arguments `(node_req0 f32 [N, R],
    gplaced0 i32 [G])` — a predecessor batch's `carry_node_requested` /
    `carry_gplaced` outputs, still device-resident — and seeds the loop
    carry from them instead of the stale snapshot fields. Chaining
    batch B onto batch A this way is bit-identical to one combined
    [A;B] batch (and therefore, inside the envelope, to sequential
    dispatches with host folding), which is exactly what makes
    adoption of a speculative batch correctness-free."""
    from ..models import packing

    fw = framework or Framework.from_config()
    if commit_mode not in ("scan", "rounds"):
        raise ValueError(f"unknown commit_mode {commit_mode!r}")
    if k < 1:
        raise ValueError(f"multi-cycle k must be >= 1, got {k}")
    if commit_mode == "rounds":
        fw.check_batched_parity()
    body = _make_cycle_body(
        fw, gang_scheduling, commit_mode, max_rounds,
        percentage_of_nodes_to_score, rounds_kw, outputs="latency",
    )
    # pod_valid's static location in the packed bool buffer: the
    # early-exit drain check reads the stacked validity rows directly
    # instead of unpacking every snapshot up front
    pv_off = pv_p = None
    for name, shape, off in spec.bools:
        if name == "pod_valid":
            pv_off, pv_p = off, int(shape[0])
    if pv_off is None:  # pragma: no cover — every spec carries pod_valid
        raise ValueError("spec has no pod_valid field")

    def multicycle(wbufs, bbufs, stable, n_cycles, *carry0):
        snap0 = packing.unpack(wbufs[0], bbufs[0], spec)
        reason = multicycle_unsupported_reason(snap0)
        if reason is not None:
            # trace-time guard: the scheduler/bench gate BEFORE building
            # this program; reaching here is a driver bug, and a traced
            # wrong answer would be far worse than a loud build failure
            raise ValueError(
                f"multi-cycle loop unsupported for this snapshot: "
                f"{reason} (carry would go stale across inner cycles)"
            )
        P = snap0.P
        N, R = snap0.node_requested.shape
        G = snap0.group_min_member.shape[0]
        # suffix counts of valid pods per row: remaining[i] == 0 means
        # rows i.. are all empty — the drain early-exit
        pv = (bbufs[:, pv_off:pv_off + pv_p] != 0)  # [K, P]
        counts = jnp.sum(pv, axis=1, dtype=jnp.int32)  # [K]
        remaining = jnp.concatenate(
            [jnp.cumsum(counts[::-1])[::-1], jnp.zeros((1,), jnp.int32)]
        )  # [K+1]

        def body_fn(carry):
            (i, node_req, gplaced, a_out, u_out, d_out, act_out,
             nr_out) = carry
            w = jax.lax.dynamic_index_in_dim(wbufs, i, keepdims=False)
            b = jax.lax.dynamic_index_in_dim(bbufs, i, keepdims=False)
            snap = packing.unpack(w, b, spec)
            snap = dataclasses.replace(
                snap,
                node_requested=node_req,
                group_existing_count=snap.group_existing_count + gplaced,
            )
            dec = body(snap, stable)
            placed = snap.pod_valid & (dec.assignment >= 0)
            gid = jnp.clip(snap.pod_group, 0, G - 1)
            in_group = snap.pod_group >= 0
            gplaced = gplaced + jnp.zeros((G,), jnp.int32).at[gid].add(
                jnp.where(in_group & placed, 1, 0)
            )
            a_out = a_out.at[i].set(
                jnp.where(snap.pod_valid, dec.assignment, -1)
            )
            u_out = u_out.at[i].set(dec.unschedulable)
            d_out = d_out.at[i].set(dec.gang_dropped)
            act_out = act_out.at[i].set(snap.pod_valid)
            nr_out = nr_out.at[i].set(dec.node_requested)
            return (i + 1, dec.node_requested, gplaced, a_out, u_out,
                    d_out, act_out, nr_out)

        def cond_fn(carry):
            i = carry[0]
            return (i < jnp.minimum(n_cycles, k)) & (
                remaining[jnp.clip(i, 0, k)] > 0
            )

        if carry_in:
            # continuation batch: seed the carry from the predecessor
            # batch's device-resident final carry instead of the (stale)
            # snapshot fields — the rows were encoded against the SAME
            # pre-predecessor cache state, so this is the identical
            # dataflow a combined [A;B] batch would thread internally
            node_req0, gplaced0 = carry0
            node_req0 = node_req0.astype(jnp.float32)
            gplaced0 = gplaced0.astype(jnp.int32)
        else:
            node_req0 = snap0.node_requested
            gplaced0 = jnp.zeros((G,), jnp.int32)
        init = (
            jnp.int32(0),
            node_req0,
            gplaced0,
            jnp.full((k, P), -1, jnp.int32),
            jnp.zeros((k, P), bool),
            jnp.zeros((k, P), bool),
            jnp.zeros((k, P), bool),
            jnp.zeros((k, N, R), jnp.float32),
        )
        i, nr_fin, gp_fin, a_out, u_out, d_out, act_out, nr_out = (
            jax.lax.while_loop(cond_fn, body_fn, init)
        )
        return MultiCycleResult(
            assignment=a_out,
            unschedulable=u_out,
            gang_dropped=d_out,
            attempted=act_out,
            node_requested=nr_out,
            cycles_run=i,
            carry_node_requested=nr_fin,
            # a continuation's gplaced carry already contains the
            # predecessor's counts; report only THIS batch's delta so
            # chains of any depth add deltas, never double-count
            carry_gplaced=gp_fin - gplaced0,
        )

    return _jit(
        multicycle, "multicycle",
        disc=(
            f"k{k}|{commit_mode}|{gang_scheduling}|{max_rounds}|"
            f"{percentage_of_nodes_to_score}|"
            f"{sorted((rounds_kw or {}).items())!r}|carry{int(carry_in)}|"
            + repr(spec.key()) + _fw_disc(fw)
        ),
    )


def build_stable_state_fn(spec):
    """Compile the stable-side precompute program: (wbuf, bbuf) -> dict of
    device arrays valid for as long as the encoder's stable side (nodes,
    existing pods, grow-only dedup tables) is unchanged — the host reruns
    it only when the encoder's stable key changes. Its outputs feed the
    packed cycle's optional `stable` argument; entries the enabled plugin
    set never reads are dead-code-eliminated there (this program itself
    gates only on the snapshot's capability flags)."""
    from ..models import packing

    def stable(wbuf, bbuf):
        snap = packing.unpack(wbuf, bbuf, spec)
        ctx = CycleContext(snap)
        out = {"expr_node_mask": ctx.expr_node_mask}
        if snap.has_inter_pod_affinity or snap.has_topology_spread:
            out["matched_existing"] = ctx.matched_existing
            out["initial_affinity_state"] = ctx.initial_affinity_state()
        return out

    return _jit(stable, "stable_state", disc=repr(spec.key()))


def build_carry_fns(spec, framework: Framework | None = None, mesh=None):
    """Device-resident static-phase carry: the [P, N] combined static
    base (score where feasible, NEG_INF where not) and the [S, P]
    matched-pending table persist on device ACROSS cycles, and each cycle
    only recomputes the rows whose pod object changed (the encoder's
    delta path already tracks exactly that set).

    Validity: both tables depend only on pod rows x node-side tables x
    interning dictionaries — NOT on existing-pod state — so they stay
    correct across cycles in real serving; any node/dict/stable change
    runs the encoder's full path, and the host rebuilds the carry with
    carry_init. Returns (carry_init, carry_update_for_bucket) where the
    latter memoizes one jitted update program per dirty-count bucket."""
    import functools

    from ..models import packing
    from ..ops import interpod as interpod_ops

    fw = framework or Framework.from_config()

    def _static_base(ctx):
        mask, score = fw.static_lean(ctx)
        return jnp.where(
            mask, jnp.clip(score, -1e6, 1e6), rounds_ops.NEG_INF
        )

    def carry_init(wbuf, bbuf, stable):
        snap = packing.unpack(wbuf, bbuf, spec)
        ctx = CycleContext(snap)
        ctx._cache.update(stable)
        return _constrain_carry({
            "sbase": _static_base(ctx),
            "mp": ctx.matched_pending,
        }, mesh)

    carry_init = _jit(
        carry_init, "carry_init",
        disc=repr(spec.key()) + _fw_disc(fw) + _mesh_desc(mesh),
    )

    update_memo: dict[int, Callable] = {}

    def carry_update_for_bucket(n_bucket: int):
        hit = update_memo.get(n_bucket)
        if hit is None:

            def carry_update(wbuf, bbuf, stable, carry, dirty):
                # dirty: i32 [n_bucket] slot ids; pad entries repeat a
                # real slot (identical rewrite, harmless)
                snap = packing.unpack(wbuf, bbuf, spec)
                vsnap = rounds_ops._pod_view(snap, dirty)
                vctx = CycleContext(vsnap)
                vctx._cache.update(stable)
                rows = _static_base(vctx)  # [Bd, N]
                cols = interpod_ops.matched_pending(vsnap)  # [S, Bd]
                return _constrain_carry({
                    "sbase": carry["sbase"].at[dirty].set(rows),
                    "mp": carry["mp"].at[:, dirty].set(cols),
                }, mesh)

            # NOT donated: the _Resilient retry re-invokes with the
            # original arguments, and a donated carry consumed by a
            # failed first call would make the recovery path itself
            # crash; the un-aliased copy costs ~0.3ms of HBM traffic
            carry_update = _jit(
                carry_update, "carry_update",
                disc=f"{n_bucket}|" + repr(spec.key()) + _fw_disc(fw)
                + _mesh_desc(mesh),
            )
            update_memo[n_bucket] = carry_update
            hit = carry_update
        return hit

    return carry_init, carry_update_for_bucket


class CarryKeeper:
    """Host-side carry maintenance shared by the bench and the serving
    scheduler: one FIXED dirty-bucket size (so exactly one update program
    compiles, warmable up front), full rebuild via carry_init whenever
    the regime key changes, the encode was full, or the dirty set
    exceeds the bucket."""

    def __init__(self, spec, framework: Framework | None = None,
                 mesh=None):
        import numpy as np

        self._np = np
        self.spec = spec
        self.ci, self._cu = build_carry_fns(spec, framework, mesh=mesh)
        P = None
        for name, _dt, shape, _off in spec.words:
            if name == "pod_priority":
                P = shape[0]
                break
        self.P = P
        self.bucket = min(P, 1 << (max(256, P // 4) - 1).bit_length())
        self.key = None
        self.carry = None

    def warm(self, wbuf, bbuf, stable):
        """Compile both carry programs outside any timed window."""
        c = self.ci(wbuf, bbuf, stable)
        idx = self._np.zeros(self.bucket, self._np.int32)
        self._cu(self.bucket)(wbuf, bbuf, stable, c, idx)
        self.key = None  # force a clean rebuild on first real use

    def state(self, wbuf, bbuf, stable, dirty, regime_key, pin=None):
        """`pin` keeps a strong ref to whatever object(s) the regime key
        embeds raw id()s of (the encoder's stable dict) — while pinned,
        CPython cannot recycle the address into a false key match."""
        np = self._np
        self._pin = pin
        if (
            self.key != regime_key
            or dirty is None
            or len(dirty) > self.bucket
        ):
            self.carry = self.ci(wbuf, bbuf, stable)
            self.key = regime_key
        elif len(dirty):
            idx = np.full(self.bucket, dirty[0], np.int32)
            idx[: len(dirty)] = dirty
            self.carry = self._cu(self.bucket)(
                wbuf, bbuf, stable, self.carry, idx
            )
        return self.carry


class ExtenderVerdictKeeper:
    """Device-resident HTTP-extender verdict carry (VERDICT r4 item 7).

    Holds the Filter/Prioritize verdict arrays (emask bool [P, N],
    escore f32 [P, N]) on device across cycles and re-consults the
    webhooks only for CHANGED pod slots (the encoder's dirty set) — the
    behavior `Extender.carry_verdicts` opts into (the operator asserts
    verdicts are deterministic per (pod, node set); stateful extenders
    must keep the default full path, which re-consults every pod every
    cycle). Padding matches the fallback path exactly: mask True and
    score 0 beyond the real pod/node counts. A regime-key change (node
    set / packed regime) or an over-bucket dirty set triggers a full
    webhook sweep. Per-slot error messages are carried alongside the
    verdicts (a carried row's error stays attached to its pod)."""

    def __init__(self, spec):
        import numpy as np

        self._np = np
        P = N = None
        for name, _dt, shape, _off in spec.words:
            if name == "pod_priority":
                P = shape[0]
            elif name == "node_taintset":
                N = shape[0]
        self.P, self.N = P, N
        self.bucket = min(P, 1 << (max(256, P // 4) - 1).bit_length())
        self.key = None
        self.emask = self.escore = None
        self.errors: dict[int, str] = {}
        self._upd = _jit(
            lambda em, es, idx, mr, sr: (
                em.at[idx].set(mr), es.at[idx].set(sr)
            ),
            "extender_verdict_update",
            disc=f"{self.bucket}|{P}x{N}",
        )

    def _rows(self, extenders, pods, nodes):
        from ..framework.host import run_extender_prepass

        np = self._np
        m, s, errs = run_extender_prepass(extenders, pods, nodes)
        n_real = len(nodes)
        mrows = np.ones((len(pods), self.N), bool)
        srows = np.zeros((len(pods), self.N), np.float32)
        if m is not None:
            mrows[:, :n_real] = m
            srows[:, :n_real] = s
        return mrows, srows, errs

    def state(self, extenders, pending, nodes, dirty, regime_key):
        import jax

        np = self._np
        full = (
            self.key != regime_key
            or self.emask is None
            or dirty is None
            or len(dirty) > self.bucket
        )
        if full:
            mrows, srows, errs = self._rows(extenders, pending, nodes)
            em = np.ones((self.P, self.N), bool)
            es = np.zeros((self.P, self.N), np.float32)
            em[: len(pending)] = mrows
            es[: len(pending)] = srows
            self.emask = jax.device_put(em)
            self.escore = jax.device_put(es)
            self.errors = dict(errs)
            self.key = regime_key
            return self.emask, self.escore
        # changed slots PLUS every slot with a carried error: a transient
        # webhook failure must be retried each cycle (the pod is requeued
        # with backoff), not carried forever as an all-False row
        rows_idx = sorted(
            {int(i) for i in dirty if i < len(pending)}
            | {i for i in self.errors if i < len(pending)}
        )
        if rows_idx:
            mrows, srows, errs = self._rows(
                extenders, [pending[i] for i in rows_idx], nodes
            )
            for i in rows_idx:
                self.errors.pop(i, None)
            for j, msg in errs.items():
                self.errors[rows_idx[j]] = msg
            k = len(rows_idx)
            idx = np.full(self.bucket, rows_idx[0], np.int32)
            idx[:k] = rows_idx
            mb = np.broadcast_to(
                mrows[:1], (self.bucket, self.N)
            ).copy()
            sb = np.zeros((self.bucket, self.N), np.float32)
            mb[:k] = mrows
            sb[:k] = srows
            sb[k:] = srows[0]  # idempotent: pad rows repeat row 0
            self.emask, self.escore = self._upd(
                self.emask, self.escore, idx, mb, sb
            )
        return self.emask, self.escore


def build_packed_cycle_carry_fn(
    spec,
    framework: Framework | None = None,
    gang_scheduling: bool = True,
    max_rounds: int = 64,
    percentage_of_nodes_to_score: int = 0,
    rounds_kw: dict | None = None,  # compact/passes/passes_round0 overrides
    extender_args: bool = False,  # cycle takes device-resident extender
    # verdict arrays (emask bool [P,N], escore f32 [P,N]) as two extra
    # arguments — the extender-verdict carry (PERF.md): verdict rows
    # persist on device across cycles, only changed pods re-consult the
    # webhook, and extender deployments keep the latency path
    mesh=None,  # jax.sharding.Mesh | None: multi-chip serving. The
    # carry arrives sharded (build_carry_fns(mesh=...)), the rounds
    # engine pins its compacted views onto the mesh (the collective-
    # payload diet), and the program name/cache key carry the mesh
    # descriptor so sharded and unsharded builds never alias.
):
    """The LATENCY-PATH cycle: packed buffers in, carry (see
    build_carry_fns) in, decisions out. Differences from build_cycle_fn:

      - the static [P, N] base and matched-pending arrive precomputed in
        the carry (delta-maintained across cycles) instead of being
        rebuilt per cycle;
      - no per-filter reject attribution and no final-state dynamic
        attribution pass — FailedScheduling diagnosis moved OFF the
        decision path into build_diagnosis_fn, which the driver runs
        asynchronously after bindings go out (reject_counts is zeros
        here);
      - no preemption gate output: the preemption program computes its
        own per-candidate static gate (_preemption_gate_rows) and
        checks what eviction can actually free itself.

    Rounds commit only (the scan engine keeps the classic path)."""
    from ..models import packing

    fw = framework or Framework.from_config()
    fw.check_batched_parity()

    def cycle(wbuf, bbuf, stable, carry, emask=None, escore=None
              ) -> CycleResult:
        snap = packing.unpack(wbuf, bbuf, spec)
        ctx = CycleContext(snap)
        ctx._cache.update(stable)
        ctx._cache["matched_pending"] = carry["mp"]
        sbase_all = carry["sbase"]
        if extender_args:
            # merge exactly like the fallback path merges the snapshot's
            # extender fields (rejections land in the base mask)
            sbase_all = jnp.where(
                emask, sbase_all + escore, rounds_ops.NEG_INF
            )
        elif snap.has_extender:
            sbase_all = jnp.where(
                snap.pod_extender_mask,
                sbase_all + snap.pod_extender_score,
                rounds_ops.NEG_INF,
            )
        sbase = sbase_all
        if percentage_of_nodes_to_score < 100:
            sbase = jnp.where(
                sampling_mask(snap, percentage_of_nodes_to_score),
                sbase_all,
                rounds_ops.NEG_INF,
            )
        extra = fw.extra_init(ctx)

        def view_ctx(vsnap, vmp):
            vctx = CycleContext(vsnap)
            vctx._cache.update(ctx._cache)
            vctx._cache["matched_pending"] = vmp
            return vctx

        rres = rounds_ops.rounds_commit(
            snap=snap,
            sbase=sbase,
            m_pending=carry["mp"],
            dyn_batched_view_fn=lambda vs, vmp, nr, ex, vsm: fw.dyn_batched(
                view_ctx(vs, vmp), nr, ex, vsm
            ),
            update_batched_view_fn=lambda vs, vmp, ex, acc, nod: (
                fw.extra_update_batched(view_ctx(vs, vmp), ex, acc, nod)
            ),
            extra=extra,
            max_rounds=max_rounds,
            score_anchor_fn=lambda nr: fw.score_anchor(ctx, nr),
            pv_choice_fn=_make_pv_choice_fn(ctx),
            mesh=mesh,
            **(rounds_kw or {}),
        )
        result = commit_ops.CommitResult(
            assignment=rres.assignment,
            node_requested=rres.node_requested,
            extra=rres.extra,
            dyn_aux=jnp.zeros((snap.P, len(fw.filters)), jnp.int32),
        )
        dropped = jnp.zeros_like(snap.pod_valid)
        if gang_scheduling:
            result, dropped = _gang_unwind(snap, result)
        unsched = snap.pod_valid & (result.assignment < 0)
        return CycleResult(
            result.assignment, result.node_requested, unsched, dropped,
            result.dyn_aux,
            _pv_claimed_after_unwind(
                snap, ctx, rres.extra, result.assignment, dropped
            ),
            rres.rounds_used, rres.accepted_per_round, rres.diag_per_round,
        )

    return _jit(
        cycle, "carry_cycle",
        disc=(
            f"{gang_scheduling}|{percentage_of_nodes_to_score}|"
            f"{max_rounds}|ext{int(extender_args)}|"
            f"{sorted((rounds_kw or {}).items())!r}|"
            f"mesh{_mesh_desc(mesh)}|"
            + repr(spec.key()) + _fw_disc(fw)
        ),
    )


def build_diagnosis_fn(spec, framework: Framework | None = None,
                       window: int = 2048, extender_args: bool = False,
                       donate: bool = False):
    """The DIAGNOSIS program: full FailedScheduling attribution for every
    unplaced pod, computed off the decision path (VERDICT r2 item 5 —
    no pod ever gets blank reasons, regardless of how many are
    unschedulable).

    (wbuf, bbuf, stable, assignment, node_requested) -> i32 [P, F]
    first-rejector counts (static + dynamic-vs-final-state), rows
    nonzero only for valid unplaced pods. Iterates rank-ordered windows
    of `window` pods under lax.while_loop, so cost scales with the
    number of unplaced pods, not with P."""
    from ..models import packing
    from ..ops import rounds as r_ops

    fw = framework or Framework.from_config()
    F = len(fw.filters)

    def diagnose(wbuf, bbuf, stable, assignment, node_requested,
                 pv_claimed=None, emask=None):
        snap = packing.unpack(wbuf, bbuf, spec)
        P = snap.P
        B = min(window, P)
        ctx = CycleContext(snap)
        ctx._cache.update(stable)
        mp = ctx.matched_pending
        extra = fw.extra_init(ctx)
        placed = snap.pod_valid & (assignment >= 0)
        extra = fw.extra_update_batched(
            ctx, extra, placed, jnp.where(placed, assignment, 0)
        )
        if pv_claimed is not None and "VolumeBinding" in extra:
            # use the ENGINE's actual claim bitmap: a batched replay can
            # reconstruct different claims when a pod was revoked and
            # re-accepted across rounds (CycleResult.pv_claimed)
            extra = dict(extra)
            extra["VolumeBinding"] = pv_claimed
        unplaced = snap.pod_valid & (assignment < 0)
        n_un = jnp.sum(unplaced, dtype=jnp.int32)
        order = jnp.argsort(
            jnp.where(unplaced, snap.pod_order.astype(jnp.int32),
                      jnp.int32(2**31 - 1))
        ).astype(jnp.int32)

        def body(carry):
            rej, w = carry
            start = jnp.minimum(w * B, P - B)
            ids = jax.lax.dynamic_slice(order, (start,), (B,))
            act = unplaced[ids]
            vsnap = r_ops._pod_view(snap, ids)
            vctx = CycleContext(vsnap)
            vctx._cache.update(ctx._cache)
            vctx._cache["matched_pending"] = mp[:, ids]
            base = jnp.broadcast_to(
                snap.node_valid[None, :], (B, snap.N)
            )
            if extender_args:
                # extender rejections land in the base mask, exactly as
                # the fallback cycle merges them pre-attribution
                base = base & emask[ids]
            per_static = [f.static_mask(vctx) for f in fw.filters]
            srej = fw.attribute_rejects(base, per_static, rows=act)
            smask_v = base
            for m in per_static:
                if m is not None:
                    smask_v = smask_v & m
            _m, _s, per_dyn = fw.dyn_batched(
                vctx, node_requested, extra, smask_v
            )
            drej = fw.attribute_rejects(smask_v, per_dyn, rows=act)
            # windows can overlap at the tail (dynamic_slice clamps);
            # values are per-pod deterministic, so max() is idempotent
            rej = rej.at[ids].max(
                jnp.where(act[:, None], srej + drej, 0)
            )
            return rej, w + 1

        def cond(carry):
            _, w = carry
            return w * B < n_un

        rej, _ = jax.lax.while_loop(
            cond, body,
            (jnp.zeros((P, F), jnp.int32), jnp.int32(0)),
        )
        return rej

    # `donate` hands the packed input buffers to XLA for reuse (the
    # diagnosis program is the slot's LAST consumer in the pipeline, so
    # the arena recycles without waiting for Python refcounts). Donated
    # buffers cannot feed a _Resilient re-invoke — donation is for
    # drivers that prefer arena reuse over the executable-cache retry.
    kw = {"donate_argnums": (0, 1)} if donate else {}
    return _jit(
        diagnose, "diagnose",
        disc=(
            f"{window}|ext{int(extender_args)}|don{int(donate)}|"
            + repr(spec.key()) + _fw_disc(fw)
        ),
        **kw,
    )


def _preemption_gate_rows(fw: Framework, ctx: CycleContext):
    """Per-candidate static gate for preemption: every static filter
    EXCEPT NodePorts (conflicts with existing pods' ports are exactly
    what eviction can free; the what-if kernel checks them per victim
    prefix). Returns gate_rows(ids i32 [C]) -> bool [C, N]."""

    def gate_rows(ids):
        snap = ctx.snap
        vsnap = rounds_ops._pod_view(snap, ids)
        vctx = CycleContext(vsnap)
        vctx._cache.update(ctx._cache)
        base = jnp.broadcast_to(
            snap.node_valid[None, :], (ids.shape[0], snap.N)
        )
        for f in fw.filters:
            if f.name == "NodePorts":
                continue
            m = f.static_mask(vctx)
            if m is not None:
                base = base & m
        return base

    return gate_rows


def build_packed_preemption_fn(spec, framework: Framework | None = None):
    """Packed-input variant of build_preemption_fn (same motivation).
    Accepts the optional device-resident stable dict: the what-if kernel
    reads the matched-existing/affinity-state tables, and seeding them
    avoids an in-program recompute of the stable side."""
    from ..models import packing

    fw = framework or Framework.from_config()
    if not fw.post_filters:
        return None

    def packed(wbuf, bbuf, result, stable=None):
        snap = packing.unpack(wbuf, bbuf, spec)
        ctx = CycleContext(snap)
        if stable is not None:
            ctx._cache.update(stable)
        return fw.post_filter(
            ctx,
            result.assignment,
            result.node_requested,
            _preemption_gate_rows(fw, ctx),
            excluded=result.gang_dropped,
        )

    return _jit(
        packed, "packed_preempt",
        disc=repr(spec.key()) + _fw_disc(fw),
    )


def build_preemption_fn(framework: Framework | None = None):
    """Compile the PostFilter (preemption) pass: called with the cycle's
    output when unschedulable pods remain. Kept as a separate jitted
    program so the hot cycle pays nothing when every pod places —
    the analogue of RunPostFilterPlugins only running on failure
    (SURVEY.md §3.4). Returns None when no PostFilter plugin is enabled."""
    fw = framework or Framework.from_config()
    if not fw.post_filters:
        return None

    def post_filter(snap: ClusterSnapshot, result: CycleResult):
        ctx = CycleContext(snap)
        return fw.post_filter(
            ctx,
            result.assignment,
            result.node_requested,
            _preemption_gate_rows(fw, ctx),
            excluded=result.gang_dropped,
        )

    return _jit(post_filter, "post_filter", disc=_fw_disc(fw))
