"""The scheduling cycle: one jitted program, pending pods in, bindings out.

TPU-native replacement for the reference's `ScheduleOne` hot loop
(SURVEY.md §3.2; expected `schedule_one.go` / `core/generic_scheduler.go`
[UNVERIFIED], mount empty). Where the reference runs, per pod:

    RunPreFilterPlugins -> RunFilterPlugins (16 goroutines over nodes)
    -> RunScorePlugins -> selectHost -> cache.AssumePod

this program computes, per cycle, for the WHOLE pending set:

    CycleContext precomputes (PreFilter analogue, batched)
    -> framework static masks/scores ([P, N], commitment-independent)
    -> greedy sequential-commit scan (dynamic residue: resource fit,
       running domain counts) -> assignment [P]

The framework (framework/runtime.py) decides which plugins contribute;
`build_cycle_fn` bakes one Framework into one compiled program."""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from ..framework.interfaces import CycleContext
from ..framework.runtime import Framework
from ..models.encoding import ClusterSnapshot
from ..ops import commit as commit_ops
from ..ops import rounds as rounds_ops


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleResult:
    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-cycle
    unschedulable: jnp.ndarray  # bool [P] valid pod that found no node
    gang_dropped: jnp.ndarray  # bool [P] placed, then unwound (group failed)
    preempt_gate: jnp.ndarray  # bool [P, N]: the PostFilter candidate
    # mask — static feasibility (WITHOUT the node-sampling window;
    # preemption considers every node, as upstream findCandidates does)
    # AND the NodePorts dynamic mask against the FINAL post-commit state.
    # Ports gate because a port claimed by a this-cycle winner cannot be
    # freed by evicting existing pods — nominating there wastes the
    # eviction. Affinity/spread dynamic masks deliberately do NOT gate:
    # evicting matching victims lowers the domain counts, so those
    # constraints can genuinely clear by the next cycle.
    reject_counts: jnp.ndarray  # i32 [P, F] nodes first-rejected per filter
    # (static + dynamic attribution summed; columns = Framework.filter_names)
    # — feeds FailedScheduling events and requeue queueing hints
    rounds_used: jnp.ndarray  # i32 [] commit rounds consumed (0 in scan mode)
    accepted_per_round: jnp.ndarray  # i32 [max_rounds] acceptance counts
    # per commit round (zeros in scan mode) — convergence diagnostics
    diag_per_round: jnp.ndarray  # i32 [max_rounds, 3] (live claims,
    # capacity rejections, guard rejections) per round, summed over passes


def sampling_mask(snap: ClusterSnapshot, pct: int) -> jnp.ndarray:
    """percentageOfNodesToScore: restrict each pod to a rotating window of
    candidate nodes (bool [P, N]).

    Upstream numFeasibleNodesToFind semantics: clusters of <100 nodes (or
    pct >= 100) consider everything; otherwise the candidate count is
    numAllNodes * pct / 100 (adaptive pct = 50 - numAllNodes/125, floor 5,
    when the knob is 0), floored at 100 nodes. Upstream stops SCANNING
    after finding that many feasible nodes from a rotating start index;
    the batched analogue samples that many CANDIDATE nodes per pod from a
    deterministic per-pod rotation — a documented deviation (data-
    dependent early exit is anti-TPU), strictly more selective, and the
    sample rotates with the pod's queue rank exactly so different pods
    spread load over different nodes."""
    n = snap.num_nodes.astype(jnp.int32)  # real node count (traced)
    if pct >= 100:
        return jnp.ones((snap.P, snap.N), bool)
    if pct <= 0:
        adaptive = jnp.maximum(50 - n // 125, 5)
    else:
        adaptive = jnp.int32(pct)
    k = jnp.maximum(n * adaptive // 100, 100)  # min-feasible floor
    # rotate per pod rank AND per cycle: a pod whose feasible nodes fall
    # outside this cycle's window gets a different window next cycle, so
    # sampling delays but never permanently starves (upstream's rotating
    # global scan index has the same property)
    off = (
        snap.pod_order.astype(jnp.int32) * 75347
        + snap.cycle_index.astype(jnp.int32) * 31337
    ) % jnp.maximum(n, 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (snap.P, snap.N), 1)
    win = (col - off[:, None]) % jnp.maximum(n, 1)
    # clusters under the floor consider every node (win < k always)
    return win < k


def build_cycle_fn(
    framework: Framework | None = None,
    gang_scheduling: bool = True,
    commit_mode: str = "scan",
    max_rounds: int = 64,
    percentage_of_nodes_to_score: int = 0,  # 0 = adaptive (upstream default)
) -> Callable[[ClusterSnapshot], CycleResult]:
    """Compile the cycle for a framework (default: the default plugin set).
    The returned callable is jitted; snapshots with identical padded shapes
    reuse the compiled program.

    `commit_mode` selects the in-cycle commitment engine:
      - "scan": the strict sequential scan (ops/commit.py) — exact
        one-pod-at-a-time ScheduleOne semantics, one lax.scan step per
        pod. Best for small pending sets and for differential parity.
      - "rounds": the round-based batched commit (ops/rounds.py) — a few
        MXU-wide rounds instead of P sequential steps; the production
        mode at 10k-pod scale (~1000x faster on TPU; see ops/rounds.py
        for the documented semantics contract).

    With `gang_scheduling` (the Coscheduling plugin analogue, SURVEY.md §2
    C14), pods carrying a pod-group whose placed-member count stays below
    the group's minMember are rolled back after the commit scan — the
    all-or-nothing semantics upstream gets from Permit-and-wait, here a
    single batched unwind. minMember counts pods placed THIS cycle;
    already-running members are bound facts, not waiters."""
    fw = framework or Framework.from_config()
    if commit_mode not in ("scan", "rounds"):
        raise ValueError(f"unknown commit_mode {commit_mode!r}")
    if commit_mode == "rounds":
        fw.check_batched_parity()

    @jax.jit
    def cycle(snap: ClusterSnapshot, stable=None) -> CycleResult:
        ctx = CycleContext(snap)
        if stable is not None:
            # device-resident precomputes derived from the STABLE side of
            # the snapshot (existing pods / nodes / dedup tables), built
            # once per stable regime by build_stable_state_fn — seeding
            # the context cache makes XLA drop the in-cycle recompute
            ctx._cache.update(stable)
        smask, sscore, srejects = fw.static(ctx)
        if snap.has_extender:
            # HTTP-extender Filter/Prioritize verdicts, computed host-side
            # before the cycle (upstream runs extenders after in-tree
            # filters; rejections are attributed to the base mask)
            smask = smask & snap.pod_extender_mask
            sscore = sscore + snap.pod_extender_score
        smask_all_nodes = smask  # pre-sampling (preemption gate base)
        if percentage_of_nodes_to_score < 100:
            # 0 = adaptive percentage, like upstream's default; the <100-
            # node floor inside sampling_mask keeps small clusters exact
            smask = smask & sampling_mask(snap, percentage_of_nodes_to_score)
        if snap.has_inter_pod_affinity or snap.has_topology_spread:
            # materialize the shared match tables at CYCLE scope: the scan
            # body would otherwise compute-and-cache them inside its own
            # trace, and the post-commit gate pass reading the cache would
            # see an escaped inner tracer
            ctx.matched_pending
        extra = fw.extra_init(ctx)

        if commit_mode == "rounds":
            # the rounds engine re-invokes the plugin kernels on COMPACTED
            # pod views (a ClusterSnapshot gathered at the active ids); a
            # view context shares the full context's node-side precomputes
            # and swaps in the view's matched-pending columns
            def view_ctx(vsnap, vmp):
                vctx = CycleContext(vsnap)
                vctx._cache.update(ctx._cache)
                vctx._cache["matched_pending"] = vmp
                return vctx

            def dyn_batched_view_fn(vsnap, vmp, node_req, ext, vsmask):
                return fw.dyn_batched(view_ctx(vsnap, vmp), node_req, ext,
                                      vsmask)

            def update_batched_view_fn(vsnap, vmp, ext, accepted, node_of):
                return fw.extra_update_batched(
                    view_ctx(vsnap, vmp), ext, accepted, node_of
                )

            rres = rounds_ops.rounds_commit(
                snap=snap,
                static_mask=smask,
                static_score=sscore,
                m_pending=ctx.matched_pending,
                dyn_batched_view_fn=dyn_batched_view_fn,
                update_batched_view_fn=update_batched_view_fn,
                extra=extra,
                max_rounds=max_rounds,
                score_anchor_fn=lambda nr: fw.score_anchor(ctx, nr),
            )
            # Final-state work (dynamic reject attribution + the NodePorts
            # part of the preemption gate) only matters for pods that never
            # placed — computed on a COMPACTED view instead of a full
            # [P, N] dyn pass. PREEMPTION-ELIGIBLE unplaced pods fill the
            # window first (by rank), so the window can never be exhausted
            # by preemptionPolicy:Never pods ahead of eligible preemptors
            # (the window is >= the preemption budget, so every pod the
            # PostFilter would consider gets real gate rows); other
            # unplaced pods follow and get attribution on a best-effort
            # basis — beyond the window: empty gate rows and zero dyn
            # attribution, retried next cycle.
            unplaced = snap.pod_valid & (rres.assignment < 0)
            B_attr = rounds_ops.compact_window(snap.P)
            rank32 = snap.pod_order.astype(jnp.int32)
            ucan = unplaced & snap.pod_can_preempt
            ukey = jnp.where(
                ucan, rank32,
                jnp.where(unplaced, rank32 + jnp.int32(1 << 24),
                          jnp.int32(2**31 - 1)),
            )
            ugid = jnp.argsort(ukey)[:B_attr].astype(jnp.int32)
            uact = unplaced[ugid]
            uvsnap = rounds_ops._pod_view(snap, ugid)
            uvmp = ctx.matched_pending[:, ugid]
            uvsmask = smask[ugid]
            _um, _us, upf = dyn_batched_view_fn(
                uvsnap, uvmp, rres.node_requested, rres.extra, uvsmask
            )
            urejects = fw.attribute_rejects(uvsmask, upf, rows=uact)
            dyn_aux = (
                jnp.zeros((snap.P, len(fw.filters)), jnp.int32)
                .at[ugid]
                .add(jnp.where(uact[:, None], urejects, 0))
            )
            result = commit_ops.CommitResult(
                assignment=rres.assignment,
                node_requested=rres.node_requested,
                extra=rres.extra,
                dyn_aux=dyn_aux,
            )
            rounds_used = rres.rounds_used
            accepted_per_round = rres.accepted_per_round
            diag_per_round = rres.diag_per_round
        else:
            def dyn_fn(p, node_req, ext, static_row):
                return fw.dyn(ctx, p, node_req, ext, static_row)

            def update_fn(ext, p, node, ok):
                return fw.extra_update(ctx, ext, p, node, ok)

            rounds_used = jnp.int32(0)
            accepted_per_round = jnp.zeros((max_rounds,), jnp.int32)
            diag_per_round = jnp.zeros((max_rounds, 3), jnp.int32)
            order = jnp.argsort(snap.pod_order)
            result = commit_ops.greedy_commit(
                order=order,
                static_mask=smask,
                static_score=sscore,
                pod_requested=snap.pod_requested,
                pod_valid=snap.pod_valid,
                pod_nominated=snap.pod_nominated,
                node_allocatable=snap.node_allocatable,
                node_requested=snap.node_requested,
                dyn_fn=dyn_fn,
                extra=extra,
                update_fn=update_fn,
            )
        dropped = jnp.zeros_like(snap.pod_valid)
        if gang_scheduling:
            placed = snap.pod_valid & (result.assignment >= 0)
            G = snap.group_min_member.shape[0]
            gid = jnp.clip(snap.pod_group, 0, G - 1)
            in_group = snap.pod_group >= 0
            # minMember counts this cycle's placements PLUS members already
            # running (a gang member retried alone after a bind error must
            # not be unwound while its siblings run)
            counts = snap.group_existing_count + jnp.zeros(G, jnp.int32).at[
                gid
            ].add(jnp.where(in_group & placed, 1, 0))
            # minMember defaults to 0 for undeclared groups -> never fails
            fail = counts < snap.group_min_member
            dropped = in_group & fail[gid] & placed
            result = commit_ops.unwind_assignments(
                result, dropped, snap.pod_requested
            )
        unsched = snap.pod_valid & (result.assignment < 0)

        # PostFilter candidate gate (see CycleResult.preempt_gate): static
        # without sampling, plus the final-state NodePorts dynamic mask.
        # Rounds mode builds gate rows from the compacted unplaced view
        # (placed pods are never preemption candidates, so their rows are
        # simply False); scan mode pays one batched pass — it targets
        # small pending sets.
        if commit_mode == "rounds":
            grows = smask_all_nodes[ugid]
            for f, m in zip(fw.filters, upf):
                if m is not None and f.name == "NodePorts":
                    grows = grows & m
            gate = (
                jnp.zeros((snap.P, snap.N), bool)
                .at[ugid]
                .max(grows & uact[:, None])
            )
        else:
            _m, _s, per_filter_final = fw.dyn_batched(
                ctx, result.node_requested, result.extra, smask
            )
            gate = smask_all_nodes
            for f, m in zip(fw.filters, per_filter_final):
                if m is not None and f.name == "NodePorts":
                    gate = gate & m

        return CycleResult(
            result.assignment, result.node_requested, unsched, dropped, gate,
            srejects + result.dyn_aux, rounds_used, accepted_per_round,
            diag_per_round,
        )

    return cycle


def build_packed_cycle_fn(spec, **kw):
    """Packed-input variant of build_cycle_fn: takes the (u32, u8) buffers
    of models.packing.pack instead of a ClusterSnapshot. On the tunneled
    TPU rig, feeding a program ~80 freshly-assembled arrays costs a large
    per-buffer first-use overhead every cycle; two packed buffers make it
    negligible. The unpack is static slices + bitcasts, fused by XLA.

    The returned callable takes an optional third argument: the output of
    build_stable_state_fn (device-resident precomputes for the stable
    side), which removes the per-cycle recompute of existing-pod match
    tables / initial affinity state / node expression masks."""
    from ..models import packing

    cycle = build_cycle_fn(**kw)

    @jax.jit
    def packed(wbuf, bbuf, stable=None):
        return cycle(packing.unpack(wbuf, bbuf, spec), stable)

    return packed


def build_stable_state_fn(spec):
    """Compile the stable-side precompute program: (wbuf, bbuf) -> dict of
    device arrays valid for as long as the encoder's stable side (nodes,
    existing pods, grow-only dedup tables) is unchanged — the host reruns
    it only when the encoder's stable key changes. Its outputs feed the
    packed cycle's optional `stable` argument; entries the enabled plugin
    set never reads are dead-code-eliminated there (this program itself
    gates only on the snapshot's capability flags)."""
    from ..models import packing

    @jax.jit
    def stable(wbuf, bbuf):
        snap = packing.unpack(wbuf, bbuf, spec)
        ctx = CycleContext(snap)
        out = {"expr_node_mask": ctx.expr_node_mask}
        if snap.has_inter_pod_affinity or snap.has_topology_spread:
            out["matched_existing"] = ctx.matched_existing
            out["initial_affinity_state"] = ctx.initial_affinity_state()
        return out

    return stable


def build_packed_preemption_fn(spec, framework: Framework | None = None):
    """Packed-input variant of build_preemption_fn (same motivation)."""
    from ..models import packing

    pre = build_preemption_fn(framework)
    if pre is None:
        return None

    @jax.jit
    def packed(wbuf, bbuf, result):
        return pre(packing.unpack(wbuf, bbuf, spec), result)

    return packed


def build_preemption_fn(framework: Framework | None = None):
    """Compile the PostFilter (preemption) pass: called with the cycle's
    output when unschedulable pods remain. Kept as a separate jitted
    program so the hot cycle pays nothing when every pod places —
    the analogue of RunPostFilterPlugins only running on failure
    (SURVEY.md §3.4). Returns None when no PostFilter plugin is enabled."""
    fw = framework or Framework.from_config()
    if not fw.post_filters:
        return None

    @jax.jit
    def post_filter(snap: ClusterSnapshot, result: CycleResult):
        ctx = CycleContext(snap)
        return fw.post_filter(
            ctx,
            result.assignment,
            result.node_requested,
            result.preempt_gate,
            excluded=result.gang_dropped,
        )

    return post_filter
