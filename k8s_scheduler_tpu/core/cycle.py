"""The scheduling cycle: one jitted program, pending pods in, bindings out.

TPU-native replacement for the reference's `ScheduleOne` hot loop
(SURVEY.md §3.2; expected `schedule_one.go` / `core/generic_scheduler.go`
[UNVERIFIED], mount empty). Where the reference runs, per pod:

    RunPreFilterPlugins -> RunFilterPlugins (16 goroutines over nodes)
    -> RunScorePlugins -> selectHost -> cache.AssumePod

this program computes, per cycle, for the WHOLE pending set:

    CycleContext precomputes (PreFilter analogue, batched)
    -> framework static masks/scores ([P, N], commitment-independent)
    -> greedy sequential-commit scan (dynamic residue: resource fit,
       running domain counts) -> assignment [P]

The framework (framework/runtime.py) decides which plugins contribute;
`build_cycle_fn` bakes one Framework into one compiled program."""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from ..framework.interfaces import CycleContext
from ..framework.runtime import Framework
from ..models.encoding import ClusterSnapshot
from ..ops import commit as commit_ops


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleResult:
    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-cycle
    unschedulable: jnp.ndarray  # bool [P] valid pod that found no node


def build_cycle_fn(
    framework: Framework | None = None,
) -> Callable[[ClusterSnapshot], CycleResult]:
    """Compile the cycle for a framework (default: the default plugin set).
    The returned callable is jitted; snapshots with identical padded shapes
    reuse the compiled program."""
    fw = framework or Framework.from_config()

    @jax.jit
    def cycle(snap: ClusterSnapshot) -> CycleResult:
        ctx = CycleContext(snap)
        smask, sscore = fw.static(ctx)
        extra = fw.extra_init(ctx)

        def dyn_fn(p, node_req, ext, static_row):
            return fw.dyn(ctx, p, node_req, ext, static_row)

        def update_fn(ext, p, node, ok):
            return fw.extra_update(ctx, ext, p, node, ok)

        order = jnp.argsort(snap.pod_order)
        result = commit_ops.greedy_commit(
            order=order,
            static_mask=smask,
            static_score=sscore,
            pod_requested=snap.pod_requested,
            pod_valid=snap.pod_valid,
            pod_nominated=snap.pod_nominated,
            node_allocatable=snap.node_allocatable,
            node_requested=snap.node_requested,
            dyn_fn=dyn_fn,
            extra=extra,
            update_fn=update_fn,
        )
        unsched = snap.pod_valid & (result.assignment < 0)
        return CycleResult(result.assignment, result.node_requested, unsched)

    return cycle
