"""The scheduling cycle: one jitted program, pending pods in, bindings out.

This is the TPU-native replacement for the reference's `ScheduleOne` hot
loop (SURVEY.md §3.2; expected `schedule_one.go` / `core/generic_scheduler.go`
[UNVERIFIED], mount empty). Where the reference runs, per pod:

    RunPreFilterPlugins -> RunFilterPlugins (16 goroutines over nodes)
    -> RunScorePlugins -> selectHost -> cache.AssumePod

this program computes, per cycle, for the WHOLE pending set:

    static masks/scores (batched [P, N], everything independent of in-cycle
    commitments) -> greedy sequential-commit scan (the dynamic residue:
    resource fit + running-state scores) -> assignment [P]

The minimal slice wires NodeResourcesFit + LeastRequested +
BalancedAllocation + NodeName/validity masks; further Filter/Score plugins
contribute additional static masks/scores or dynamic hooks (see
framework/runtime.py for how the plugin registry assembles them).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..models.encoding import ClusterSnapshot
from ..ops import commit as commit_ops
from ..ops import resources as res_ops


@dataclasses.dataclass(frozen=True)
class CycleOptions:
    """Static knobs baked into the compiled cycle (a change recompiles).

    Score weights follow the upstream default-plugin weights; resources
    participating in scoring default to cpu+memory like upstream
    `defaultRequestedRatioResources`."""

    least_requested_weight: float = 1.0
    balanced_allocation_weight: float = 1.0
    score_resources: tuple[str, ...] = ("cpu", "memory")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleResult:
    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-cycle
    unschedulable: jnp.ndarray  # bool [P] valid pod that found no node


def _score_resource_weights(snap: ClusterSnapshot, options: CycleOptions) -> np.ndarray:
    w = np.zeros(len(snap.resource_names), np.float32)
    for r in options.score_resources:
        if r in snap.resource_names:
            w[snap.resource_names.index(r)] = 1.0
    return w


def static_mask_basic(snap: ClusterSnapshot) -> jnp.ndarray:
    """Masks independent of both in-cycle commitments and label machinery:
    node validity (padding), NodeUnschedulable, NodeName pin."""
    P, N = snap.pod_requested.shape[0], snap.node_allocatable.shape[0]
    mask = jnp.broadcast_to(
        snap.node_valid[None, :] & ~snap.node_unschedulable[None, :], (P, N)
    )
    # NodeName plugin: a pinned pod may only land on its named node
    # (pod_node_name -2 = named node unknown -> infeasible everywhere).
    pinned = snap.pod_node_name[:, None]  # [P, 1]
    node_ids = jnp.arange(N, dtype=jnp.int32)[None, :]
    mask = jnp.where(pinned >= 0, mask & (node_ids == pinned), mask)
    mask = jnp.where(pinned == -2, False, mask)
    return mask


def build_cycle_fn(
    options: CycleOptions = CycleOptions(),
) -> Callable[[ClusterSnapshot], CycleResult]:
    """Compile the minimal-slice cycle. The returned callable is jitted;
    snapshots with identical padded shapes reuse the compiled program."""

    @jax.jit
    def cycle(snap: ClusterSnapshot) -> CycleResult:
        res_w = jnp.asarray(_score_resource_weights(snap, options))
        smask = static_mask_basic(snap)
        sscore = jnp.zeros_like(smask, jnp.float32)

        def dyn_fn(p, node_req, _extra):
            req = snap.pod_requested[p]
            m = res_ops.fit_mask_single(req, snap.node_allocatable, node_req)
            s = options.least_requested_weight * res_ops.least_requested_score(
                req, snap.node_allocatable, node_req, res_w
            ) + options.balanced_allocation_weight * res_ops.balanced_allocation_score(
                req, snap.node_allocatable, node_req, res_w
            )
            return m, s

        order = jnp.argsort(snap.pod_order)
        result = commit_ops.greedy_commit(
            order=order,
            static_mask=smask,
            static_score=sscore,
            pod_requested=snap.pod_requested,
            pod_valid=snap.pod_valid,
            pod_nominated=snap.pod_nominated,
            node_allocatable=snap.node_allocatable,
            node_requested=snap.node_requested,
            dyn_fn=dyn_fn,
        )
        unsched = snap.pod_valid & (result.assignment < 0)
        return CycleResult(result.assignment, result.node_requested, unsched)

    return cycle
