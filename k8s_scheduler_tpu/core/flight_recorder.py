"""Cycle flight recorder: bounded per-cycle phase marks + pod timelines.

Production serving needs to answer "which phase ate the cycle and which
plugin rejected this pod" continuously, without stopping the scheduler
and without reconstructing it from three independent probe runs. The
Prometheus histograms aggregate away the per-cycle structure; this module
keeps the structure:

- `FlightRecorder` — a bounded ring of `CycleRecord`s. The scheduling
  loop stamps each cycle with host-side `perf_counter` marks (encode,
  dispatch, decision fetch, winner binds, postfilter, deferred-diagnosis
  resolution) plus counts (pods, binds, preemptions, queue depths, retry
  strikes, fetch bytes, pipeline slot). Writer cost is a handful of dict
  writes and ONE list-slot store per cycle — no locks on the writer side;
  publication is a seqlock-style monotonically increasing commit count
  (`_commits`), which readers check around their ring copy and retry
  until no commit tore the window.
- `PodTimelines` — a bounded (LRU) per-pod event log:
  queued -> attempts[{cycle, result, first-rejecting plugin}] ->
  bound / evicted. Fed by the scheduler's informer handlers and the
  winner/loser loops; joined with the events ring at query time
  (Scheduler.pod_timeline).
- `to_chrome_trace` — reconstructs the split-phase pipeline's overlapped
  lanes (host encode/bind vs in-flight device cycle vs deferred
  diagnosis) as a Chrome-trace/Perfetto JSON from the REAL serving
  timestamps, so pipeline overlap is visible from production, not probe
  medians. Download via `/debug/trace?last=N`, open in ui.perfetto.dev.

Single-writer contract: records are started and committed by the
scheduling loop only (one thread). Pod-timeline notes may arrive from
informer threads and take a small lock. The module is stdlib-only (no
jax/numpy) so tools and tests can import it without a backend.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time as _time
from typing import Any, Callable, Iterable

# chrome-trace lane (tid) layout: one process, three threads. Perfetto
# renders each tid as its own track, so the overlap between the host
# lane and the device/diagnosis lanes is visible directly.
LANE_HOST = 1  # encode, dispatch call, winner binds, loser requeue
LANE_DEVICE = 2  # dispatched cycle program -> slimmed decision fetch
LANE_DIAG = 3  # deferred FailedScheduling attribution (diag lag)

LANE_NAMES = {
    LANE_HOST: "host (encode/bind)",
    LANE_DEVICE: "device cycle (in flight)",
    LANE_DIAG: "deferred diagnosis",
}

# Where each attribution phase (core/observe.PHASES) renders in the
# chrome-trace export: phase -> (lane tid, slice name). Phases that ride
# inside a parent slice (fold inside encode, compile inside the flip
# cycle's dispatch) map to that parent. `to_chrome_trace` reads its lane
# ids from here, and schedlint's ID005 check enforces that this mapping,
# observe.PHASES, the scheduler_cycle_phase_seconds docstring entry, and
# the README phase table never drift apart.
TRACE_LANE_FOR_PHASE = {
    "total": (LANE_HOST, "cycle[seq]"),
    "encode": (LANE_HOST, "encode"),
    "fold": (LANE_HOST, "encode"),
    "dispatch": (LANE_HOST, "dispatch"),
    "compile": (LANE_HOST, "dispatch"),
    "decision_fetch": (LANE_HOST, "decision_wait"),
    "bind": (LANE_HOST, "bind winners"),
    "postfilter": (LANE_HOST, "postfilter"),
    "device": (LANE_DEVICE, "device cycle[seq]"),
    "diag_lag": (LANE_DIAG, "diag lag[seq]"),
    # multi-cycle batched decomposition: an inner cycle's host-side
    # coalescing wait renders on the host lane (it precedes the batch's
    # encode), its apportioned device share inside the batch's device
    # slice (the host cannot see per-inner-cycle device boundaries)
    "batch_wait": (LANE_HOST, "batch wait"),
    "device_share": (LANE_DEVICE, "device cycle[seq]"),
    # streamed decision fetch: batch flush -> first inner cycle's
    # decision row landed; renders inside the batch's device slice
    # (the window ends where row 0's transfer completes)
    "first_bind": (LANE_DEVICE, "device cycle[seq]"),
    # front door: admission accept -> bind, a host-observed end-to-end
    # window; renders on the host lane (it ends in the bind loop)
    "submit_bind": (LANE_HOST, "bind winners"),
    # admission-time incremental encode: the ingest share was paid
    # before the flush cycle started, but it is host encode work, so
    # both halves render inside the flush cycle's encode slice
    "encode_ingest": (LANE_HOST, "encode"),
    "encode_finalize": (LANE_HOST, "encode"),
}


@dataclasses.dataclass
class CycleRecord:
    """One scheduling cycle's flight data (one per profile per cycle).

    `marks` hold ABSOLUTE recorder-clock times (perf_counter seconds)
    for phase boundaries; `phases` hold derived millisecond durations
    (the ServingPipeline stage report plus scheduler-side phases);
    `counts` hold integers (pods, binds, queue depths, fetch bytes...).
    Records are immutable once committed — the ring replaces slots, it
    never mutates them."""

    seq: int
    profile: str
    t_start: float  # recorder clock (perf_counter)
    wall_start: float  # time.time() anchor for log cross-referencing
    slot: int = -1  # pipeline upload slot id
    forced_sync: bool = False
    t_end: float = 0.0
    marks: dict[str, float] = dataclasses.field(default_factory=dict)
    phases: dict[str, float] = dataclasses.field(default_factory=dict)
    counts: dict[str, int] = dataclasses.field(default_factory=dict)
    # padded-shape signature of the cycle's packed regime, as a sorted
    # tuple of (dim, size) pairs (models/packing.shape_signature): the
    # observer diffs consecutive signatures to attribute WHICH pad
    # dimension (E/MPN/MA/MC/P/N) flipped on a recompile anomaly
    sig: tuple | None = None
    # where this cycle's (re)built programs came from, stamped only on
    # regime-flip cycles: "cold" (full XLA compile on the serve path),
    # "cache" (loaded from the persistent executable cache), or
    # "speculative" (the warm thread pre-built the regime before the
    # flip). The observer surfaces it in /debug/anomalies recompile
    # events so operators can tell a cache miss from a win.
    compile_source: str = ""
    # depth-2 speculative dispatch outcome, stamped on the record of
    # the batch a speculation rode (one sample per speculation):
    # "adopted" | "abandoned" | "none" (speculation considered but not
    # dispatched — e.g. spec mismatch), "" = no speculation involved.
    # Feeds the observer's speculation_thrash abandon-rate EWMA.
    speculation: str = ""
    # trace ids of the sampled pods this cycle served (core/spans):
    # the exemplar join from a flight record back to its pod traces —
    # span attrs carry the cycle `seq` for the reverse direction.
    # Stamped only when tracing is armed AND a sampled pod rode the
    # cycle; empty tuple otherwise (and omitted from to_dict).
    trace_ids: tuple = ()
    # virtual cluster this cycle scheduled for (tenancy/): stamped by
    # _commit_record when the scheduler runs tenant-scoped (the
    # sequential per-tenant reference path); "" = single-tenant, and
    # omitted from to_dict. Arena-mode attribution rides the tenancy
    # metrics + span attrs instead — one record per tenant would undo
    # the batching the arena exists for.
    tenant: str = ""

    def mark(self, name: str, t: float) -> None:
        self.marks[name] = t

    def to_dict(self, epoch: float = 0.0) -> dict[str, Any]:
        """JSON-ready dict; mark times rebased to `epoch` (seconds)."""
        return {
            "seq": self.seq,
            "profile": self.profile,
            "slot": self.slot,
            "forced_sync": self.forced_sync,
            "t_start_s": round(self.t_start - epoch, 6),
            "t_end_s": round(self.t_end - epoch, 6),
            "wall_start": self.wall_start,
            "marks_s": {
                k: round(v - epoch, 6) for k, v in self.marks.items()
            },
            "phases_ms": {k: round(v, 4) for k, v in self.phases.items()},
            "counts": dict(self.counts),
            **(
                {"sig": {k: v for k, v in self.sig}}
                if self.sig is not None else {}
            ),
            **(
                {"compile_source": self.compile_source}
                if self.compile_source else {}
            ),
            **(
                {"speculation": self.speculation}
                if self.speculation else {}
            ),
            **(
                {"trace_ids": list(self.trace_ids)}
                if self.trace_ids else {}
            ),
            **(
                {"tenant": self.tenant}
                if self.tenant else {}
            ),
        }


class PodTimelines:
    """Bounded per-pod scheduling history (LRU on pod uid).

    Each entry is `{"uid", "name", "events": [...]}` where every event
    carries the recorder-clock time, wall time, a kind (Queued /
    Attempt / Nominated / Bound / BindError / Unschedulable / Evicted /
    Deleted), and kind-specific detail (cycle seq, node, first-rejecting
    plugin). Thread-safe — informer handlers run on other threads than
    the scheduling loop."""

    def __init__(self, max_pods: int = 4096, max_events: int = 256):
        self._lock = threading.Lock()
        self._max_pods = max_pods
        self._max_events = max_events
        self._pods: collections.OrderedDict[str, dict] = (
            collections.OrderedDict()
        )

    def note(
        self, uid: str, name: str, kind: str, t: float, wall: float,
        **detail: Any,
    ) -> None:
        ev = {"t_s": t, "wall": wall, "kind": kind, **detail}
        with self._lock:
            entry = self._pods.get(uid)
            if entry is None:
                entry = {"uid": uid, "name": name, "events": []}
                self._pods[uid] = entry
                while len(self._pods) > self._max_pods:
                    self._pods.popitem(last=False)
            else:
                self._pods.move_to_end(uid)
                if name:
                    entry["name"] = name
            events = entry["events"]
            events.append(ev)
            if len(events) > self._max_events:
                del events[: len(events) - self._max_events]

    def get(self, uid: str) -> dict | None:
        with self._lock:
            entry = self._pods.get(uid)
            if entry is None:
                return None
            return {
                "uid": entry["uid"],
                "name": entry["name"],
                "events": [dict(e) for e in entry["events"]],
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._pods)


class FlightRecorder:
    """Bounded ring of CycleRecords + pod timelines.

    Hot-path cost: `start()` is one dataclass construction; `commit()`
    is one list-slot store plus one int publish. Readers (`snapshot`)
    copy the ring without blocking the writer and validate the copy
    against the commit count (seqlock-style): a copy a commit landed in
    is retried."""

    def __init__(
        self,
        capacity: int = 512,
        now: Callable[[], float] = _time.perf_counter,
        wall: Callable[[], float] = _time.time,
        max_pods: int = 4096,
    ) -> None:
        self.capacity = max(int(capacity), 1)
        self.now = now
        self._wall = wall
        self._ring: list[CycleRecord | None] = [None] * self.capacity
        # COMMIT count (monotonic): the seqlock generation readers check.
        # Distinct from _seq — a started-but-never-committed record
        # consumes a seq but must not inflate the committed-cycle count.
        # (A failed decision fetch the degradation ladder handled IS
        # committed, stamped counts.aborted=1 + the post-failure rung —
        # core/scheduler._cycle_failed; only failures that escape the
        # ladder leave a consumed seq behind.)
        self._commits = 0
        self._seq = 0  # next record's sequence number
        self.epoch = now()
        self.wall_epoch = wall()
        self.pods = PodTimelines(max_pods=max_pods)
        # publish-time consumers (core/observe.CycleObserver.observe):
        # called synchronously after each commit with the record. A
        # failing observer is logged once and detached — observability
        # must never take the scheduling loop down with it.
        self.observers: list[Callable[[CycleRecord], None]] = []

    # ---- writer side (scheduling loop only) ------------------------------

    def start(self, profile: str = "default-scheduler") -> CycleRecord:
        rec = CycleRecord(
            seq=self._seq,
            profile=profile,
            t_start=self.now(),
            wall_start=self._wall(),
        )
        self._seq += 1
        return rec

    def commit(self, rec: CycleRecord) -> None:
        if not rec.t_end:
            rec.t_end = self.now()
        self._ring[rec.seq % self.capacity] = rec
        # publish AFTER the slot store: a reader that observes the new
        # count is guaranteed to observe the new record (GIL-ordered)
        self._commits += 1
        for cb in list(self.observers):
            try:
                cb(rec)
            except Exception:  # noqa: BLE001 — see observers docstring
                import logging

                logging.getLogger(__name__).exception(
                    "flight-recorder observer %r failed; detaching", cb
                )
                self.observers.remove(cb)

    def pod_event(
        self, uid: str, name: str, kind: str, **detail: Any
    ) -> None:
        self.pods.note(
            uid, name, kind, self.now() - self.epoch, self._wall(),
            **detail,
        )

    # ---- reader side -----------------------------------------------------

    @property
    def cycles(self) -> int:
        """Total committed records (not capped by capacity; aborted
        starts do not count)."""
        return self._commits

    def snapshot(self, last: int | None = None) -> list[CycleRecord]:
        """Consistent copy of the most recent `last` records (oldest
        first; `last=0` is an empty window). Lock-free: the copy is
        retried until no commit landed during it (the seqlock check —
        commits are cycle-rate, the copy is microseconds, so this
        converges immediately in practice); the fallback trims to the
        newest run of seqs no commit could have torn."""
        ring: list[CycleRecord | None] = []
        for _ in range(8):
            before = self._commits
            ring = list(self._ring)  # atomic-enough slot copy under GIL
            if self._commits == before:
                break  # no commit during the copy: exactly consistent
        recs = sorted(
            (r for r in ring if r is not None), key=lambda r: r.seq
        )
        if recs:
            # fallback consistency trim: a commit mid-copy can leave a
            # stale slot (seq max-capacity) next to its replacement —
            # keep only the trailing window every slot agrees on
            recs = [
                r for r in recs
                if r.seq > recs[-1].seq - self.capacity
            ]
        if last is not None:
            n = max(int(last), 0)
            recs = recs[-n:] if n else []
        return recs

    def last_record(self) -> CycleRecord | None:
        recs = self.snapshot(last=1)
        return recs[-1] if recs else None

    def last_cycle_age_s(self) -> float:
        """Seconds since the newest committed cycle record — or since
        the recorder was created when no cycle has EVER completed, so a
        scheduler that wedged before its first cycle still ages out of
        its health deadline instead of reporting healthy forever."""
        rec = self.last_record()
        anchor = rec.t_end if rec is not None else self.epoch
        return max(0.0, self.now() - anchor)

    def to_dicts(self, last: int | None = None) -> list[dict]:
        return [r.to_dict(epoch=self.epoch) for r in self.snapshot(last)]

    def derived(self, last: int = 64) -> dict[str, float]:
        """Continuous pipeline gauges computed over the recent window —
        the production replacement for the probe's three separated runs
        (see core/profiling.overlap_from_records for the accounting)."""
        from .profiling import overlap_from_records

        recs = self.snapshot(last=last)
        out = overlap_from_records(r.phases for r in recs)
        out["cycles"] = float(self.cycles)
        out["last_cycle_age_s"] = round(self.last_cycle_age_s(), 6)
        return out


# ---- Chrome-trace / Perfetto export ------------------------------------


def _slice(
    name: str, tid: int, t0: float, t1: float, epoch: float,
    args: dict | None = None,
) -> dict:
    ev = {
        "name": name,
        "ph": "X",
        "pid": 1,
        "tid": tid,
        "ts": round((t0 - epoch) * 1e6, 3),
        "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
        "cat": "scheduler",
    }
    if args:
        ev["args"] = args
    return ev


def to_chrome_trace(
    records: Iterable[CycleRecord], epoch: float = 0.0,
    spans: Iterable | None = None,
) -> dict:
    """Chrome-trace (JSON object format) reconstruction of the serving
    pipeline's lanes from committed records. Open the serialized dict in
    ui.perfetto.dev or chrome://tracing.

    When `spans` (core/spans.Span, the same perf_counter clock as the
    cycle marks) is given, per-trace pod tracks render in a second
    process group below the cycle lanes — one Perfetto view shows a
    pod's submit→bind spans overlapping the batch that served it.

    Lane layout (one pid, three tids — see LANE_NAMES):

    - host lane: `encode` -> `dispatch` -> `decision_wait` (the one
      blocking fetch) -> `bind winners` -> `postfilter` -> `losers`;
    - device lane: one `cycle[k]` slice spanning dispatch start ->
      decision fetch end — the window the device (and the transfer) is
      working while the host is free to do other work;
    - diag lane: `diag lag` from decision-fetch end to the moment the
      deferred FailedScheduling attribution was forced.

    Under async serving the diag slice overlaps the host bind slice and
    the device slice overlaps host dispatch-adjacent work; under
    `forced_sync` every slice serializes — the visual proof either way
    comes from real serving timestamps."""
    events: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "args": {"name": "tpu-scheduler serving pipeline"},
        }
    ]
    for tid, name in LANE_NAMES.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": name},
            }
        )
        events.append(
            {
                "name": "thread_sort_index",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"sort_index": tid},
            }
        )

    for rec in records:
        m = rec.marks
        args = {
            "seq": rec.seq,
            "profile": rec.profile,
            "slot": rec.slot,
            "forced_sync": rec.forced_sync,
            **{k: v for k, v in rec.counts.items()},
        }
        t_enc0 = m.get("encode_start", rec.t_start)
        t_disp0 = m.get("dispatch_start")
        t_disp1 = m.get("dispatch_end")
        t_dec0 = m.get("decision_start")
        t_dec1 = m.get("decision_end")
        # bind work starts at apply_start when stamped (after the
        # deferred dispatches, which BLOCK under forced_sync)
        t_apply = m.get("apply_start", m.get("decision_end"))
        t_win = m.get("winners_end")
        t_post = m.get("postfilter_end")
        t_diag = m.get("diag_done")

        # whole-cycle envelope on the host lane (parent slice: children
        # below nest inside it on the same tid)
        events.append(
            _slice(
                f"cycle[{rec.seq}]", LANE_HOST, rec.t_start, rec.t_end,
                epoch, args,
            )
        )
        if t_disp0 is not None:
            events.append(
                _slice(
                    TRACE_LANE_FOR_PHASE["encode"][1],
                    TRACE_LANE_FOR_PHASE["encode"][0],
                    t_enc0, t_disp0, epoch,
                )
            )
        if t_disp0 is not None and t_disp1 is not None:
            events.append(
                _slice(
                    TRACE_LANE_FOR_PHASE["dispatch"][1],
                    TRACE_LANE_FOR_PHASE["dispatch"][0],
                    t_disp0, t_disp1, epoch,
                )
            )
        if t_dec0 is not None and t_dec1 is not None:
            events.append(
                _slice(
                    TRACE_LANE_FOR_PHASE["decision_fetch"][1],
                    TRACE_LANE_FOR_PHASE["decision_fetch"][0],
                    t_dec0, t_dec1, epoch,
                    {"fetch_bytes": rec.counts.get("fetch_bytes", 0)},
                )
            )
        if t_apply is not None and t_win is not None:
            events.append(
                _slice(
                    TRACE_LANE_FOR_PHASE["bind"][1],
                    TRACE_LANE_FOR_PHASE["bind"][0],
                    t_apply, t_win, epoch,
                )
            )
        if t_win is not None and t_post is not None:
            events.append(
                _slice(
                    TRACE_LANE_FOR_PHASE["postfilter"][1],
                    TRACE_LANE_FOR_PHASE["postfilter"][0],
                    t_win, t_post, epoch,
                )
            )
        if t_post is not None:
            events.append(
                _slice("losers", LANE_HOST, t_post, rec.t_end, epoch)
            )

        # device lane: dispatched program in flight until the slimmed
        # decision payload landed on the host
        if t_disp0 is not None and t_dec1 is not None:
            events.append(
                _slice(
                    f"device cycle[{rec.seq}] slot={rec.slot}",
                    TRACE_LANE_FOR_PHASE["device"][0],
                    t_disp0, t_dec1, epoch,
                    {"seq": rec.seq, "slot": rec.slot},
                )
            )

        # diagnosis lane: how far FailedScheduling attribution trailed
        # the binds (resolves while the host bind loop runs)
        if t_dec1 is not None and t_diag is not None and t_diag > t_dec1:
            events.append(
                _slice(
                    f"diag lag[{rec.seq}]",
                    TRACE_LANE_FOR_PHASE["diag_lag"][0],
                    t_dec1, t_diag,
                    epoch, {"seq": rec.seq},
                )
            )

    if spans is not None:
        from .spans import spans_to_chrome_events

        events.extend(spans_to_chrome_events(spans, epoch=epoch))

    return {"traceEvents": events, "displayTimeUnit": "ms"}
