"""Streaming latency attribution, anomaly sentinel, and SLO burn rate.

PR 2's flight recorder keeps the per-cycle *structure* (phase marks,
counts, pod timelines); this module is the layer that turns each record
into *answers* at publish time — the role kube-scheduler's
`scheduling_duration_seconds` phase breakdown and SLO dashboards play,
rebuilt TPU-natively on top of the recorder:

- **Phase attribution** (`phase_seconds`): every committed CycleRecord
  is decomposed into the named phase windows in `PHASES` (encode, fold,
  dispatch, device, decision_fetch, bind, postfilter, diag_lag, compile,
  total) and fed into fixed-bucket streaming histograms, exported as the
  `scheduler_cycle_phase_seconds{phase=...}` histogram family plus
  per-phase p50/p99 gauges evaluated at scrape time. The windows are
  measurement lenses, not a strict partition: `device` (dispatch return
  -> decision landed) CONTAINS `decision_fetch` (the blocking wait), and
  on this rig both embed one tunnel round-trip — which is exactly why
  the stall classes below watch them.
- **Anomaly sentinel**: EWMA + streaming-quantile baselines per phase
  classify outlier cycles into typed anomalies (`ANOMALY_CLASSES`):

  * `tunnel_stall`   — the device round-trip window stalled (the 28 s
    outlier class ROUND5.md could only count, not attribute);
  * `fetch_stall`    — the blocking decision fetch crawled while the
    round-trip window was otherwise unremarkable (slow transfer, not a
    stalled dispatch);
  * `recompile`      — the encoder's padded-shape signature flipped
    between consecutive cycles; the flipping dimensions (E/MPN/MA/MC/
    P/N, models/packing.shape_signature) are attributed by diffing, so
    "which pad regime moved" no longer needs a probe run;
  * `fold_miss`      — a warm cycle fell off the delta/fold encode path
    into a full re-encode (without a regime flip to explain it);
  * `wedge_precursor`— `_Resilient` absorbed new retry strikes this
    cycle (core/cycle.py): the strike classes that precede the rig's
    executable-cache wedge;
  * `degraded`       — a degradation-ladder rung transition
    (core/degrade.py), raised externally via `raise_anomaly` with the
    from/to rung names and the triggering reason in the detail.

  Each anomaly is a structured ring event carrying the cycle `seq`, so
  `/debug/anomalies?last=N` links straight to the flight record and the
  matching `/debug/trace` Perfetto window, and each is counted in
  `scheduler_anomalies_total{class=...}`.
- **SLO engine** (`SloEngine`): a configurable latency objective —
  config `sloP99Ms`/`sloWindowCycles`, CLI `--slo-p99-ms` — tracked as
  "at most 1% of cycles may exceed the objective" over fast/slow cycle
  windows, exported as `scheduler_slo_burn_rate{window=...}` and
  `scheduler_slo_budget_remaining`; `/healthz` reports a fast-window
  burn above `fast_burn_degraded` as `degraded: true` (the probe stays
  200 — budget burn is a paging signal, not a liveness failure).

Stdlib-only, like the recorder it consumes: tools and tests import it
without a jax backend. Thread model: `observe()` runs on the scheduling
loop (via FlightRecorder.observers at commit — a dozen histogram
increments under one small lock, microseconds next to a cycle); readers
(scrape-time gauge closures, /debug/anomalies) take the same lock.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time as _time
from typing import Any, Iterable

# The canonical phase inventory. schedlint's ID005 check enforces that
# this tuple, the flight recorder's chrome-trace lane mapping
# (flight_recorder.TRACE_LANE_FOR_PHASE), the metrics/metrics.py
# docstring entry for scheduler_cycle_phase_seconds, and the README
# phase table never drift apart.
PHASES = (
    "total",          # t_start -> t_end (the whole profile cycle)
    "encode",         # host snapshot encode, minus the fold share below
    "fold",           # incremental existing-fold inside the encode
    "dispatch",       # async program dispatch (host side)
    "device",         # dispatch returned -> decision payload landed
    "decision_fetch", # the ONE blocking device->host wait
    "bind",           # winner bind loop
    "postfilter",     # preemption force between winners and losers
    "diag_lag",       # deferred FailedScheduling attribution lag
    "compile",        # packed-program (re)build on a regime flip
    # multi-cycle batched decomposition (core/scheduler.py
    # _schedule_profile_multi): one device dispatch runs K inner cycles,
    # and each inner cycle's record carries its share of the batch —
    "batch_wait",     # how long this inner cycle's delta group waited
    # host-side for the batch to fill (bounded by multiCycleMaxWaitMs)
    "device_share",   # this inner cycle's apportioned share of the
    # batch's device window (no clock runs under jit, so the host
    # splits the measured window by per-cycle attempted-pod counts)
    "first_bind",     # streamed decision fetch: batch flush -> the
    # FIRST inner cycle's decision row landed (the latency a row-0 pod
    # actually waits before its bind; ~1 inner cycle under depth-2
    # speculative dispatch instead of the whole K-cycle batch)
    "submit_bind",    # front door (service/admission.py): admission
    # accept -> the pod's bind, end to end through the queue and the
    # coalescing buffers; stamped per cycle as the WORST such latency
    # among the cycle's binds, so the streaming p99 tracks the
    # submit->bind SLO the open-loop load harness measures externally
    # admission-time incremental encode (models/encoding.py ingest_pod
    # + the multi-cycle flush): the encode cost splits into work paid
    # in the ack path's shadow and the flush-time residue —
    "encode_ingest",  # per-group parse of buffered pods into staged
    # row data at multi-cycle buffer time (hidden behind the front
    # door's ack; stamped on the flush cycle's record)
    "encode_finalize", # the flush-critical encode remainder: folding
    # staged rows into the packed arena when the batch flushes (what
    # is left of the old O(P) rebuild)
)

ANOMALY_CLASSES = (
    "tunnel_stall",
    "fetch_stall",
    "recompile",
    "fold_miss",
    "wedge_precursor",
    # a degradation-ladder rung transition (core/degrade.py): raised
    # externally via raise_anomaly — both directions, with the from/to
    # rung names and the triggering reason in the detail
    "degraded",
    # depth-2 speculative dispatch is net-negative: the per-profile
    # abandon-rate EWMA crossed spec_thrash_threshold — every abandoned
    # speculation re-dispatches, so a thrashing workload pays the
    # speculative encode+dispatch for nothing. Raising this also holds
    # speculation off for the profile for `spec_hold_cycles` cycles
    # (the scheduler consults speculation_ok before speculating).
    "speculation_thrash",
    # a tenant with pending demand bound NOTHING for `starve_after`
    # consecutive arena cycles while other tenants bound — raised
    # externally by tenancy/arena.py (the schedule-side unfairness the
    # per-tenant bit-equality property cannot see; admission's
    # weighted-fair shed is the intake-side guard). The detail carries
    # the tenant id, its pending depth, and the streak length.
    "tenant_starved",
    # a declarative alert rule fired (metrics/rules.py RuleEngine):
    # raised externally once per firing — not per evaluation — with the
    # rule name, severity, observed value and threshold in the detail,
    # so the anomaly ring carries the alert timeline next to the raw
    # symptoms the rule aggregated over
    "alert",
)

# Fixed log-ish bucket edges (seconds) for the streaming phase
# histograms: sub-ms TPU phases up through multi-second tunnel stalls
# (the observed 28 s outlier lands in the top finite bucket).
PHASE_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def phase_seconds(rec) -> dict[str, float]:
    """Decompose one CycleRecord into `{phase: seconds}` windows.

    Only phases whose source data exists in the record are emitted (a
    cycle with no deferred diagnosis has no `diag_lag`; `compile`
    appears only on regime-flip cycles) so absent work never pollutes
    the histograms with zeros."""
    m, ph = rec.marks, rec.phases
    out: dict[str, float] = {}
    total = rec.t_end - rec.t_start
    if total > 0:
        out["total"] = total

    fold = ph.get("fold_ms", 0.0) / 1e3
    if "encode_ms" in ph:
        # the fold ran INSIDE the encode window: attribute it separately
        # and keep `encode` as the non-fold remainder
        out["encode"] = max(ph["encode_ms"] / 1e3 - fold, 0.0)
    if fold > 0.0:
        out["fold"] = fold
    if "dispatch_ms" in ph:
        out["dispatch"] = ph["dispatch_ms"] / 1e3
    if "decision_wait_ms" in ph:
        out["decision_fetch"] = ph["decision_wait_ms"] / 1e3
    d0, d1 = m.get("dispatch_end"), m.get("decision_end")
    if d0 is not None and d1 is not None and d1 >= d0:
        out["device"] = d1 - d0
    a0, a1 = m.get("apply_start"), m.get("winners_end")
    if a0 is not None and a1 is not None and a1 >= a0:
        out["bind"] = a1 - a0
    p1 = m.get("postfilter_end")
    if a1 is not None and p1 is not None and p1 >= a1:
        out["postfilter"] = p1 - a1
    if "diag_lag_ms" in ph:
        out["diag_lag"] = ph["diag_lag_ms"] / 1e3
    if "compile_ms" in ph:
        out["compile"] = ph["compile_ms"] / 1e3
    # multi-cycle batched decomposition: stamped only on inner-cycle
    # records of a multi-cycle dispatch (scheduler-side apportioning)
    if "batch_wait_ms" in ph:
        out["batch_wait"] = ph["batch_wait_ms"] / 1e3
    if "device_share_ms" in ph:
        out["device_share"] = ph["device_share_ms"] / 1e3
    if "first_bind_ms" in ph:
        out["first_bind"] = ph["first_bind_ms"] / 1e3
    if "submit_bind_ms" in ph:
        out["submit_bind"] = ph["submit_bind_ms"] / 1e3
    # admission-time incremental encode split (stamped on flush cycles
    # when incrementalEncode is on; ingest may be 0-cost on an empty
    # buffer, so gate on presence, not value)
    if "encode_ingest_ms" in ph:
        out["encode_ingest"] = ph["encode_ingest_ms"] / 1e3
    if "encode_finalize_ms" in ph:
        out["encode_finalize"] = ph["encode_finalize_ms"] / 1e3
    return out


class StreamHist:
    """Fixed-bucket streaming histogram with interpolated quantiles.

    O(len(buckets)) memory forever; `observe` is one bisect + two adds.
    Quantiles interpolate linearly inside the owning bucket — exact
    enough for p50/p99 gauges over latency-shaped data, and immune to
    the unbounded-memory failure of keeping raw samples."""

    __slots__ = ("edges", "counts", "n", "total", "max_seen")

    def __init__(self, edges: Iterable[float] = PHASE_BUCKETS_S) -> None:
        self.edges = tuple(edges)
        self.counts = [0] * (len(self.edges) + 1)
        self.n = 0
        self.total = 0.0
        self.max_seen = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.edges, v)] += 1
        self.n += 1
        self.total += v
        if v > self.max_seen:
            self.max_seen = v

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return 0.0
        target = q * self.n
        seen = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                lo = self.edges[i - 1] if i > 0 else 0.0
                hi = (
                    self.edges[i] if i < len(self.edges)
                    else max(self.max_seen, lo)
                )
                frac = (target - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.max_seen

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class PhaseBaseline:
    """EWMA mean + EWMA absolute deviation + a streaming histogram —
    the per-phase "normal" an outlier is judged against. Anomalous
    samples update the baseline winsorized BELOW the threshold that
    flagged them (at threshold/mult — see CycleObserver), so a 28 s
    stall cannot drag its own baseline up and mask the next stall."""

    __slots__ = ("hist", "ewma", "ewdev", "n", "alpha")

    def __init__(self, alpha: float = 0.05):
        self.hist = StreamHist()
        self.ewma = 0.0
        self.ewdev = 0.0
        self.n = 0
        self.alpha = alpha

    def update(self, v: float) -> None:
        self.hist.observe(v)
        if self.n == 0:
            self.ewma = v
        else:
            dev = abs(v - self.ewma)
            self.ewdev += self.alpha * (dev - self.ewdev)
            self.ewma += self.alpha * (v - self.ewma)
        self.n += 1

    def threshold(
        self, mult: float, k_dev: float, floor_s: float
    ) -> float:
        """The outlier boundary: `mult` x the larger of (EWMA + k_dev
        sigma-ish) and the streaming p99, floored at `floor_s`."""
        base = max(
            self.ewma + k_dev * self.ewdev, self.hist.quantile(0.99)
        )
        return max(floor_s, mult * base)


class SloEngine:
    """Multi-window burn-rate tracking for a cycle-latency objective.

    Objective: at most `budget_fraction` (default 1%, i.e. a p99
    objective) of cycles may exceed `p99_ms`. Burn rate over a window =
    observed violation fraction / budget fraction: 1.0 burns the budget
    exactly at the sustainable rate, N burns it N times too fast. Two
    windows — `fast` (window/16, floor 16 cycles: pages quickly) and
    `slow` (`sloWindowCycles`: the budget window itself) — the standard
    multi-window shape, with cycles as the time base because cycle rate
    IS the serving rate here."""

    def __init__(
        self,
        p99_ms: float,
        window_cycles: int = 1024,
        budget_fraction: float = 0.01,
        fast_burn_degraded: float = 6.0,
    ) -> None:
        self.p99_ms = float(p99_ms)
        self.window_cycles = max(int(window_cycles), 16)
        self.budget_fraction = budget_fraction
        self.fast_burn_degraded = fast_burn_degraded
        self.windows: dict[str, collections.deque] = {
            "fast": collections.deque(
                maxlen=max(16, self.window_cycles // 16)
            ),
            "slow": collections.deque(maxlen=self.window_cycles),
        }
        self.cycles = 0
        self.violations = 0

    @property
    def enabled(self) -> bool:
        return self.p99_ms > 0

    def note(self, total_s: float) -> bool:
        violated = self.enabled and total_s * 1e3 > self.p99_ms
        for w in self.windows.values():
            w.append(1 if violated else 0)
        self.cycles += 1
        self.violations += int(violated)
        return violated

    def burn_rate(self, window: str) -> float:
        w = self.windows[window]
        if not self.enabled or not w:
            return 0.0
        return (sum(w) / len(w)) / self.budget_fraction

    def budget_remaining(self) -> float:
        """Fraction of the slow window's violation budget left (1.0 =
        untouched; negative = overspent). Sized against the window
        CAPACITY so early violations spend the same budget they would
        in steady state."""
        if not self.enabled:
            return 1.0
        w = self.windows["slow"]
        budget = self.budget_fraction * w.maxlen
        return (budget - sum(w)) / budget

    def degraded(self) -> bool:
        return (
            self.enabled
            and self.burn_rate("fast") >= self.fast_burn_degraded
        )

    def status(self) -> dict[str, Any]:
        return {
            "p99_ms": self.p99_ms,
            "window_cycles": self.window_cycles,
            "enabled": self.enabled,
            "cycles": self.cycles,
            "violations": self.violations,
            "burn_rate": {
                name: round(self.burn_rate(name), 4)
                for name in self.windows
            },
            "budget_remaining": round(self.budget_remaining(), 4),
            "degraded": self.degraded(),
        }


class CycleObserver:
    """The streaming consumer wired into `FlightRecorder.observers`:
    every committed record is attributed, baselined, anomaly-classified,
    and SLO-accounted — within the same cycle it was published in.

    Tuning attributes (set before traffic; tests shrink the floors):
    `stall_mult` / `stall_k_dev` / `stall_floor_s` shape the outlier
    threshold (PhaseBaseline.threshold), `warmup_cycles` is how many
    samples a phase needs before it can be judged at all."""

    def __init__(
        self,
        metrics=None,
        slo_p99_ms: float = 0.0,
        slo_window_cycles: int = 1024,
        ring: int = 256,
        warmup_cycles: int = 8,
        stall_mult: float = 4.0,
        stall_k_dev: float = 6.0,
        stall_floor_s: float = 0.25,
        fast_burn_degraded: float = 6.0,
        spec_thrash_threshold: float = 0.5,
        spec_hold_cycles: int = 8,
        spec_warmup: int = 4,
    ) -> None:
        self._lock = threading.Lock()
        self.warmup_cycles = warmup_cycles
        self.stall_mult = stall_mult
        self.stall_k_dev = stall_k_dev
        self.stall_floor_s = stall_floor_s
        # speculative-dispatch thrash sentinel: per-profile EWMA of the
        # abandon rate over speculated batches. Above the threshold
        # (after spec_warmup samples) speculation is net-negative —
        # every abandon re-dispatches — so a speculation_thrash anomaly
        # fires and speculation_ok() holds the profile's speculation
        # off for the next spec_hold_cycles opportunities (the
        # scheduler wires degradePromoteCycles in here).
        self.spec_thrash_threshold = spec_thrash_threshold
        self.spec_hold_cycles = spec_hold_cycles
        self.spec_warmup = spec_warmup
        self.baselines = {p: PhaseBaseline() for p in PHASES}
        # unwinsorized per-phase histograms: the exported p50/p99
        # gauges and status() read THESE — the baselines' winsorized
        # hists exist to keep the outlier threshold honest, and would
        # report a near-normal tail during an active stall episode
        self.raw = {p: StreamHist() for p in PHASES}
        self.slo = SloEngine(
            slo_p99_ms,
            window_cycles=slo_window_cycles,
            fast_burn_degraded=fast_burn_degraded,
        )
        self.anomaly_counts = {c: 0 for c in ANOMALY_CLASSES}
        self.ring: collections.deque = collections.deque(maxlen=ring)
        self.cycles = 0
        self.epoch = 0.0  # recorder clock epoch (set by the scheduler)
        # per-profile memory: last shape signature + monotonic counters
        # (per-profile encoder full_encodes) for deltas
        self._prof: dict[str, dict[str, Any]] = {}
        # process-global monotonic counters (retry_strikes_total from
        # RESILIENT_STRIKES): every profile's record carries the same
        # sum, so the delta must be tracked once or N profiles would
        # each raise the same strike
        self._global_counts: dict[str, int] = {}
        self._metrics = metrics
        if metrics is not None:
            self._bind_metrics(metrics)

    # ---- metrics wiring --------------------------------------------------

    def _bind_metrics(self, m) -> None:
        """Register the scrape-time closures: per-phase p50/p99 (from
        the RAW streaming histograms — the winsorized baselines would
        hide the tail during a stall episode) and the SLO burn gauges
        evaluate live at scrape, not at cycle end."""
        # metrics.py keeps a LITERAL copy of PHASE_BUCKETS_S (so it
        # stays importable without the core package); retuning one
        # without the other would make the exported histogram and the
        # streaming p50/p99 gauges disagree at exactly the bucket
        # boundaries histogram_quantile interpolates over — refuse at
        # wiring time instead of drifting silently
        exported = getattr(m.cycle_phase, "_upper_bounds", None)
        if exported is not None:
            finite = tuple(
                e for e in exported if e != float("inf")
            )
            if finite != PHASE_BUCKETS_S:
                raise ValueError(
                    "scheduler_cycle_phase_seconds bucket edges "
                    f"{finite} drifted from observe.PHASE_BUCKETS_S "
                    f"{PHASE_BUCKETS_S}: retune both or neither"
                )
        for p in PHASES:
            m.cycle_phase_p50.labels(phase=p).set_function(
                lambda p=p: self.quantile(p, 0.5)
            )
            m.cycle_phase_p99.labels(phase=p).set_function(
                lambda p=p: self.quantile(p, 0.99)
            )
        for w in self.slo.windows:
            m.slo_burn_rate.labels(window=w).set_function(
                lambda w=w: self.slo_burn_rate(w)
            )
        m.slo_budget_remaining.set_function(self.slo_budget_remaining)

    # ---- the per-record hook (scheduling loop) ---------------------------

    def observe(self, rec) -> list[dict]:
        """Consume one committed CycleRecord; returns the anomalies it
        raised (also pushed onto the ring + counters + metrics)."""
        phases = phase_seconds(rec)
        return self.observe_phases(
            phases,
            counts=rec.counts,
            sig=getattr(rec, "sig", None),
            profile=rec.profile,
            seq=rec.seq,
            t_s=rec.t_end - self.epoch,
            wall=rec.wall_start,
            compile_source=getattr(rec, "compile_source", ""),
            speculation=getattr(rec, "speculation", ""),
        )

    def observe_phases(
        self,
        phases: dict[str, float],
        counts: dict[str, int] | None = None,
        sig: tuple | None = None,
        profile: str = "default-scheduler",
        seq: int = -1,
        t_s: float = 0.0,
        wall: float = 0.0,
        compile_source: str = "",
        speculation: str = "",
    ) -> list[dict]:
        """The sentinel core, usable without a CycleRecord (bench_suite
        feeds plain latency series through classify_latency_series)."""
        counts = counts or {}
        anomalies: list[dict] = []
        with self._lock:
            prof = self._prof.setdefault(
                profile, {"sig": None, "counts": {}, "cycles": 0}
            )
            first = prof["cycles"] == 0
            # per-profile demand drift baseline: an EWMA of the cycle's
            # attempted-pod count. The speculative-compile warmer
            # (core/compile_cache.py) watches it to pre-build the
            # ADJACENT pad regime before churn crosses a bucket
            # boundary — alpha 0.2 tracks a drifting arrival rate in a
            # handful of cycles without chasing single-cycle spikes.
            pods_n = counts.get("pods")
            if pods_n is not None:
                prev_d = prof.get("demand_ewma")
                prof["demand_ewma"] = (
                    float(pods_n) if prev_d is None
                    else prev_d + 0.2 * (pods_n - prev_d)
                )

            def raise_anomaly(
                cls: str, phase: str = "", value_s: float = 0.0,
                baseline_s: float = 0.0, **detail: Any,
            ) -> None:
                ev = {
                    "seq": seq,
                    "profile": profile,
                    "t_s": round(t_s, 6),
                    "wall": wall,
                    "class": cls,
                    "phase": phase,
                    "value_ms": round(value_s * 1e3, 3),
                    "baseline_ms": round(baseline_s * 1e3, 3),
                    "detail": detail,
                }
                anomalies.append(ev)
                self.ring.append(ev)
                self.anomaly_counts[cls] += 1

            # -- stall classes: judge BEFORE the update, so an outlier
            # is measured against the baseline it violated. During
            # warmup an over-threshold sample is winsorized but NOT
            # classified (too little history to page on) — feeding it
            # raw would park the p99 term at the stall value and mask
            # the whole class for the next ~100 cycles.
            stall_phase = {}
            warm_cap: dict[str, float] = {}
            for phase in ("device", "decision_fetch"):
                v = phases.get(phase)
                if v is None:
                    continue
                # no b.n == 0 special case: with no history the
                # threshold degrades to stall_floor_s, so a stall on
                # the VERY FIRST cycle (exactly when the rig is
                # startup-flaky) is still winsorized below — seeding
                # the baseline raw would park ewma and the p99 term at
                # the stall value and mask the class post-warmup
                b = self.baselines[phase]
                thr = b.threshold(
                    self.stall_mult, self.stall_k_dev,
                    self.stall_floor_s,
                )
                if v > thr:
                    if b.n >= self.warmup_cycles:
                        stall_phase[phase] = (v, thr, b)
                    else:
                        warm_cap[phase] = thr
            if "device" in stall_phase:
                v, thr, b = stall_phase["device"]
                raise_anomaly(
                    "tunnel_stall", phase="device", value_s=v,
                    baseline_s=b.ewma, threshold_ms=round(thr * 1e3, 3),
                )
            elif "decision_fetch" in stall_phase:
                # the fetch alone crawled while the round-trip window
                # stayed unremarkable: a transfer stall, not a tunnel
                # dispatch stall (precedence documented in ANOMALY
                # class docs above)
                v, thr, b = stall_phase["decision_fetch"]
                raise_anomaly(
                    "fetch_stall", phase="decision_fetch", value_s=v,
                    baseline_s=b.ewma, threshold_ms=round(thr * 1e3, 3),
                )

            # -- recompile: a genuine packed-program rebuild this cycle
            # (regime_flip is stamped only on a _packed_fns memo miss),
            # with the flipping pad dimensions attributed by diffing
            # consecutive shape signatures. A signature flip WITHOUT a
            # rebuild is a memoized regime switch — a pad flip-flop
            # riding the scheduler's _packed cache, costing no compile —
            # so it raises nothing (it would otherwise spam the ring
            # every cycle of an oscillating workload); the sig diff
            # still suppresses fold_miss below, because the shape
            # change legitimately full-encodes.
            flipped: list[str] = []
            pd: dict = {}
            nd: dict = {}
            if sig is not None:
                prev = prof["sig"]
                if prev is not None and sig != prev:
                    pd, nd = dict(prev), dict(sig)
                    flipped = sorted(
                        k for k in (set(pd) | set(nd))
                        if pd.get(k) != nd.get(k)
                    )
                prof["sig"] = sig
            if not first and counts.get("regime_flip"):
                detail: dict[str, Any] = (
                    {
                        "dims": flipped,
                        "from_sig": {k: pd.get(k) for k in flipped},
                        "to_sig": {k: nd.get(k) for k in flipped},
                    }
                    if flipped
                    # dictionary-growth recompile: spec.key() changed
                    # while every named pad size stayed identical
                    # (grow-only interning dimensions) — no signature
                    # diff to show, but the rebuild cost is just as real
                    else {"dims": [], "growth": "interning"}
                )
                if compile_source:
                    # cold | cache | speculative: a cache hit or a
                    # speculation win is a regime flip that cost ~no
                    # serve-path compile — operators triage these
                    # differently from a cold miss
                    detail["compile_source"] = compile_source
                raise_anomaly(
                    "recompile",
                    phase="compile",
                    value_s=phases.get(
                        "compile", phases.get("dispatch", 0.0)
                    ),
                    **detail,
                )

            # -- monotonic-counter deltas: full encodes (fold miss,
            # per-profile encoder) and _Resilient strikes (wedge
            # precursor, process-global)
            pc = prof["counts"]
            if "full_encodes" in counts:
                prev_v = pc.get("full_encodes")
                delta = (
                    counts["full_encodes"] - prev_v
                    if prev_v is not None else 0
                )
                pc["full_encodes"] = counts["full_encodes"]
                if (
                    delta > 0 and not first and not flipped
                    and not counts.get("regime_flip")
                    and not counts.get("multi_cycle_k")
                    and not counts.get("post_batch")
                ):
                    # a regime flip legitimately full-encodes; only an
                    # UNexplained fall off the delta path is a fold
                    # miss. regime_flip covers dictionary-growth
                    # recompiles too — spec.key() changed while the six
                    # named pad sizes stayed identical, so `flipped`
                    # alone cannot see them. multi_cycle_k marks a
                    # batched dispatch, whose K per-group encodes are
                    # full by design (the delta arena serves the
                    # single-cycle path) — explained, not a miss.
                    # post_batch marks the FIRST single-cycle dispatch
                    # after a batch, whose full encode is the batch's
                    # doing: the plain encodes left _delta_state
                    # describing the pre-batch arena
                    raise_anomaly(
                        "fold_miss",
                        phase="encode",
                        value_s=phases.get("encode", 0.0),
                        full_encodes=delta,
                    )
            if "retry_strikes_total" in counts:
                prev_v = self._global_counts.get("retry_strikes_total")
                delta = (
                    counts["retry_strikes_total"] - prev_v
                    if prev_v is not None else 0
                )
                self._global_counts["retry_strikes_total"] = counts[
                    "retry_strikes_total"
                ]
                if delta > 0:
                    raise_anomaly("wedge_precursor", strikes=delta)

            # -- speculation thrash: EWMA of the abandon rate over
            # speculated batches (one sample per speculation — the
            # scheduler stamps the outcome only on the record of the
            # batch the speculation rode). Above the threshold the
            # speculative encode+dispatch is being paid for nothing
            # (every abandon re-dispatches), so raise the anomaly and
            # hold speculation off for spec_hold_cycles opportunities;
            # the EWMA resets so post-hold evidence is judged fresh.
            if speculation in ("adopted", "abandoned"):
                x = 1.0 if speculation == "abandoned" else 0.0
                prev_e = prof.get("spec_ewma")
                prof["spec_ewma"] = (
                    x if prev_e is None else prev_e + 0.3 * (x - prev_e)
                )
                prof["spec_n"] = prof.get("spec_n", 0) + 1
                if (
                    prof["spec_n"] >= self.spec_warmup
                    and prof["spec_ewma"] > self.spec_thrash_threshold
                ):
                    raise_anomaly(
                        "speculation_thrash",
                        abandon_rate_ewma=round(prof["spec_ewma"], 4),
                        threshold=self.spec_thrash_threshold,
                        hold_cycles=self.spec_hold_cycles,
                    )
                    prof["spec_hold"] = self.spec_hold_cycles
                    prof["spec_ewma"] = 0.0
                    prof["spec_n"] = 0

            # -- feed histograms/baselines (winsorized for flagged
            # stall phases) and the SLO accounting
            for phase, v in phases.items():
                self.raw[phase].observe(v)
                cap = (
                    stall_phase[phase][1] if phase in stall_phase
                    else warm_cap.get(phase)
                )
                if cap is not None:
                    # winsorize at the PRE-multiplier base, not the
                    # threshold itself: threshold-level samples feed the
                    # p99 term, which the next threshold multiplies by
                    # stall_mult again — a run of identical stalls would
                    # background itself within a handful of cycles
                    v = min(v, cap / self.stall_mult)
                self.baselines[phase].update(v)
            if "total" in phases:
                self.slo.note(phases["total"])
            self.cycles += 1
            prof["cycles"] += 1

        m = self._metrics
        if m is not None:
            for phase, v in phases.items():
                m.cycle_phase.labels(phase=phase).observe(v)
            for ev in anomalies:
                m.anomalies.labels(ev["class"]).inc()
        return anomalies

    # ---- external anomaly sources ----------------------------------------

    def raise_anomaly(
        self,
        cls: str,
        *,
        seq: int = -1,
        profile: str = "",
        phase: str = "",
        value_s: float = 0.0,
        **detail: Any,
    ) -> dict:
        """Push one anomaly event from OUTSIDE the per-record pipeline
        (the degradation ladder's rung transitions): same ring, counts,
        and scheduler_anomalies_total accounting as record-driven
        classes, so /debug/anomalies is the one place to look."""
        if cls not in self.anomaly_counts:
            raise ValueError(
                f"unknown anomaly class {cls!r} (ANOMALY_CLASSES)"
            )
        ev = {
            "seq": seq,
            "profile": profile,
            "t_s": 0.0,
            "wall": _time.time(),
            "class": cls,
            "phase": phase,
            "value_ms": round(value_s * 1e3, 3),
            "baseline_ms": 0.0,
            "detail": dict(detail),
        }
        with self._lock:
            self.ring.append(ev)
            self.anomaly_counts[cls] += 1
        m = self._metrics
        if m is not None:
            m.anomalies.labels(cls).inc()
        return ev

    # ---- readers ---------------------------------------------------------

    def quantile(self, phase: str, q: float) -> float:
        with self._lock:
            return self.raw[phase].quantile(q)

    def demand_ewma(self, profile: str) -> float:
        """The per-profile attempted-pod EWMA (0.0 before any cycle) —
        the drift signal the speculative-compile warmer watches."""
        with self._lock:
            return float(
                self._prof.get(profile, {}).get("demand_ewma") or 0.0
            )

    def speculation_ok(self, profile: str) -> bool:
        """Consulted by the scheduler before each speculative dispatch
        opportunity (batch flush). False while a speculation_thrash
        hold is active; each consult during the hold spends one of its
        spec_hold_cycles, so speculation auto-re-enables after
        degradePromoteCycles opportunities of sequential serving."""
        with self._lock:
            prof = self._prof.get(profile)
            if prof is None:
                return True
            hold = prof.get("spec_hold", 0)
            if hold <= 0:
                return True
            prof["spec_hold"] = hold - 1
            return False

    # locked SloEngine reads: the scrape-time gauge closures must not
    # iterate the burn-window deques while the scheduling loop appends
    # (deques raise "mutated during iteration" mid-scrape)
    def slo_burn_rate(self, window: str) -> float:
        with self._lock:
            return self.slo.burn_rate(window)

    def slo_budget_remaining(self) -> float:
        with self._lock:
            return self.slo.budget_remaining()

    def anomalies(self, last: int | None = None) -> list[dict]:
        with self._lock:
            evs = list(self.ring)
        if last is not None:
            n = max(int(last), 0)
            evs = evs[-n:] if n else []
        return [dict(e) for e in evs]

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "cycles": self.cycles,
                "anomaly_counts": dict(self.anomaly_counts),
                "phase_p50_ms": {
                    p: round(self.raw[p].quantile(0.5) * 1e3, 3)
                    for p in PHASES
                    if self.raw[p].n
                },
                "phase_p99_ms": {
                    p: round(self.raw[p].quantile(0.99) * 1e3, 3)
                    for p in PHASES
                    if self.raw[p].n
                },
                "slo": self.slo.status(),  # schedlint: disable=TR004 -- by-name fallback: the callee is SloEngine.status (pure dict reads), not the listdir-ing Journal/CompileCache status the resolver also matches
            }

    def healthz_detail(self) -> dict[str, Any]:
        """The /healthz enrichment: SLO burn + degraded flag. Degraded
        is reported, not 503'd — killing the pod does not refill an
        error budget."""
        with self._lock:
            out: dict[str, Any] = {"slo": self.slo.status()}
            if self.slo.degraded():
                out["degraded"] = True
                out["degraded_reason"] = (
                    f"slo fast-burn {self.slo.burn_rate('fast'):.1f}x "
                    f">= {self.slo.fast_burn_degraded:g}x "
                    f"(objective p99 <= {self.slo.p99_ms:g} ms)"
                )
            return out


def classify_latency_series(
    samples_s: Iterable[float], **observer_kw: Any
) -> dict[str, int]:
    """Run the runtime sentinel's outlier rule over a plain forced-sync
    latency series (bench_suite's per-cycle times, where the blocking
    read IS the tunnel round-trip window) and return anomaly counts by
    class. Only the stall classes can fire on a bare series — there is
    no signature or strike stream in it — so the result is exactly the
    "which cycles stalled, by the production classifier" count the
    BENCH artifacts carry next to the raw percentiles."""
    obs = CycleObserver(metrics=None, **observer_kw)
    for i, t in enumerate(samples_s):
        obs.observe_phases(
            {"total": t, "device": t, "decision_fetch": t},
            profile="bench", seq=i,
        )
    return {
        c: n for c, n in obs.anomaly_counts.items() if n
    }
