"""The Scheduler: event handlers, cycle driver, bind/preemption plumbing.

Host-side equivalent of the reference's `Scheduler` object + `ScheduleOne`
loop (`scheduler.go`, `eventhandlers.go` — [UNVERIFIED], mount empty;
SURVEY.md §2 C2, §3.2/§3.3): informer events maintain the cache and queue;
each `schedule_cycle()` encodes the ready set into a device snapshot, runs
the fused cycle program (+ the preemption PostFilter when needed), assumes
winners, hands them to the binder, and routes losers back through
backoff/unschedulable tiers.

Where upstream runs one pod per ScheduleOne iteration with an async
bindingCycle goroutine, this driver schedules the whole ready set per
cycle and dispatches binds through an injectable `binder` callable —
synchronous by default; the gRPC service wraps it with its own transport.
Bind failures forget the assumption and requeue with backoff (upstream
handleBindingCycleError).

The device side runs through the split-phase ServingPipeline
(core/pipeline.py): the cycle program is dispatched async, the only
blocking transfer is the slimmed decision payload, winners bind before
the (deferred, overlapped) preemption/diagnosis programs are forced for
the losers, and cycle k's binds always fold into the cache before cycle
k+1's encode reads it. `forced_sync` restores sequential execution.
"""

from __future__ import annotations

import dataclasses
import logging
import threading as _threading
import time as _time
from typing import Callable

import numpy as np

from ..config import SchedulerConfiguration
from ..framework.runtime import Framework
from ..internal.cache import SchedulerCache
from ..metrics import SchedulerMetrics
from ..internal.queue import (
    EVENT_NODE_ADD,
    EVENT_NODE_DELETE,
    EVENT_NODE_UPDATE,
    EVENT_POD_ADD,
    EVENT_POD_DELETE,
    EVENT_POD_UPDATE,
    EVENT_PV_CHANGE,
    EVENT_PVC_CHANGE,
    EVENT_STORAGE_CLASS_CHANGE,
    SchedulingQueue,
)
from ..models.api import Node, Pod, PodGroup
from ..models.encoding import SnapshotEncoder
from .cycle import (
    build_cycle_fn,
    build_packed_cycle_fn,
    build_packed_preemption_fn,
    build_preemption_fn,
    build_stable_state_fn,
    classify_failure,
)
from .degrade import (
    RUNG_FORCED_SYNC,
    RUNG_RETRACE,
    RUNG_SEQUENTIAL,
    RUNG_STATELESS,
    DegradationLadder,
)
from .events import EventRecorder, failed_scheduling_message
from .flight_recorder import FlightRecorder
from . import spans as _spans
from . import blackbox as _blackbox

# binder(pod, node_name) -> None; raise to signal bind failure
Binder = Callable[[Pod, str], None]
# evictor(pod, node_name) -> None (preemption victim deletion)
Evictor = Callable[[Pod, str], None]


@dataclasses.dataclass
class CycleStats:
    attempted: int = 0
    scheduled: int = 0
    unschedulable: int = 0
    bind_errors: int = 0
    preemptors: int = 0
    victims: int = 0
    gang_dropped: int = 0
    cycle_seconds: float = 0.0


def _pad(n: int, bucket: int = 64) -> int:
    n = max(n, 1)
    return ((n + bucket - 1) // bucket) * bucket


class Scheduler:
    def __init__(
        self,
        config: SchedulerConfiguration | None = None,
        binder: Binder | None = None,
        evictor: Evictor | None = None,
        now: Callable[[], float] = _time.monotonic,
        pad_bucket: int = 64,
        metrics: SchedulerMetrics | None = None,
        events: EventRecorder | None = None,
        host_plugins: "list | None" = None,
        forced_sync: bool | None = None,  # None = config.forced_sync;
        # True blocks every pipeline dispatch to completion (strict
        # sequential execution — the tests/measurement escape hatch)
        flight_recorder: FlightRecorder | None = None,  # None = build
        # from config.flight_recorder_size (0 disables recording)
        state: "object | None" = None,  # state.DurableState | None:
        # durable queue/cache journal + snapshots; attach() below
        # restores any existing state BEFORE the first cycle (the
        # standby-takeover path) and starts journaling mutations
        tenant_id: str = "",  # non-empty when this scheduler serves ONE
        # virtual cluster (the tenancy sequential reference path):
        # stamped on every flight record so per-tenant traces, SLO burn
        # and /debug joins attribute to the right tenant
    ) -> None:
        self.tenant_id = str(tenant_id)
        self.config = config or SchedulerConfiguration()
        # one Framework per profile (SURVEY.md §2 C12 / §5.6: multiple
        # schedulers by schedulerName); pods route by
        # pod.spec.scheduler_name, unknown names are parked loudly
        names = [p.scheduler_name for p in self.config.profiles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate profile schedulerNames: {names}")
        self.frameworks = {
            n: Framework.from_config(self.config, scheduler_name=n)
            for n in names
        }
        self._profile_order = names
        # back-compat alias: the first profile (tests/tools poke at it)
        self.framework = self.frameworks[names[0]]
        self.cache = SchedulerCache(now=now)
        # default to a FRESH registry: two Schedulers in one process must
        # not cross-count (r4 regression — tests/test_reasons.py). The
        # process-level counters that cannot reach a Scheduler handle
        # (scheduler_program_retry_strikes_total from _Resilient) always
        # land in global_metrics(); the CLI passes metrics=global_metrics()
        # explicitly so the SERVED /metrics registry includes them.
        self.metrics = metrics or SchedulerMetrics()
        self.queue = SchedulingQueue(
            initial_backoff_seconds=self.config.pod_initial_backoff_seconds,
            max_backoff_seconds=self.config.pod_max_backoff_seconds,
            now=now,
            on_enqueue=lambda queue, event: self.metrics.queue_incoming.labels(
                queue=queue, event=event
            ).inc(),
        )
        self.binder = binder or (lambda pod, node: None)
        self.evictor = evictor or (lambda pod, node: None)
        # submission front door (service/admission.py): the controller
        # attaches itself here so _bind can close the submit->bind
        # window and _commit_record can stamp it on the cycle record
        self.admission = None
        # durable state (state/ package): restore-then-journal. Attach
        # happens here — after queue/cache exist, before any cycle — so
        # a standby that just won the FileLease resumes with the exact
        # backoff deadlines / attempt counts / assumed pods the dead
        # active had journaled.
        self.state = state
        if state is not None:
            state.attach(self.queue, self.cache)
            # pods that were mid-cycle when the previous leader died
            # have no outcome records — requeue them (journaled), or the
            # first pop_ready would drop them with no informer to
            # re-deliver
            self.queue.recover_in_flight()
        self.events = events or EventRecorder()
        # cycle flight recorder: per-cycle phase marks + pod timelines
        # (core/flight_recorder.py); None when disabled by config
        if flight_recorder is not None:
            self.flight: FlightRecorder | None = flight_recorder
        elif self.config.flight_recorder_size > 0:
            self.flight = FlightRecorder(
                capacity=self.config.flight_recorder_size
            )
        else:
            self.flight = None
        if self.flight is not None:
            # live staleness at scrape time (not at cycle end — a wedged
            # scheduler must show a GROWING age on /metrics)
            self.metrics.last_cycle_age.set_function(
                self.flight.last_cycle_age_s
            )
        # streaming latency attribution + anomaly sentinel + SLO engine
        # (core/observe.py): consumes every flight record at publish
        # time; None when the recorder is disabled (no records to read)
        if self.flight is not None:
            from .observe import CycleObserver

            self.observer: CycleObserver | None = CycleObserver(
                metrics=self.metrics,
                slo_p99_ms=self.config.slo_p99_ms,
                slo_window_cycles=self.config.slo_window_cycles,
                # speculation_thrash auto-disable horizon: the same
                # clean-evidence window the degradation ladder promotes
                # on (satisfies "re-enable after degradePromoteCycles")
                spec_hold_cycles=self.config.degrade_promote_cycles,
            )
            self.observer.epoch = self.flight.epoch
            self.flight.observers.append(self.observer.observe)
        else:
            self.observer = None
            if self.config.slo_p99_ms > 0:
                logging.getLogger(__name__).warning(
                    "sloP99Ms=%g is configured but the flight recorder "
                    "is disabled (flightRecorderSize=0): the observer "
                    "has no records to consume, so the SLO engine, the "
                    "anomaly sentinel, and /debug/anomalies are all "
                    "off", self.config.slo_p99_ms,
                )
        # the explicit degradation ladder (core/degrade.py): dispatch/
        # fetch failures step it down (retrace -> sequential ->
        # forced_sync -> stateless), clean cycles promote it back up.
        # Process-local by design — a standby that takes over starts at
        # the top rung on its own evidence, never inherits this one's.
        self.ladder = DegradationLadder(
            promote_after=self.config.degrade_promote_cycles,
            metrics=self.metrics,
            events=self.events,
            observer=self.observer,
            on_transition=self._on_rung_transition,
        )
        if state is not None:
            # /debug/state shows the current rung next to the journal
            state.degradation = self.ladder
        # watchdog bound on the blocking decision fetch (0 = unbounded):
        # refreshed onto each memoized pipeline at dispatch time
        self._dispatch_deadline_s = (
            max(float(self.config.dispatch_deadline_ms), 0.0) / 1e3
        )
        # fault injection (core/faults.py): armed process-globally from
        # config faultSpec / env SCHED_FAULTS — production configs leave
        # it empty and every hook stays a dead branch
        self._fault_plan = None
        self._cycle_counter = 0
        _fault_spec = self.config.fault_spec
        if not _fault_spec:
            import os as _os_f

            _fault_spec = _os_f.environ.get("SCHED_FAULTS", "")
        if _fault_spec:
            from . import faults as _faults_mod

            self._fault_plan = _faults_mod.FaultPlan.parse(_fault_spec)
            _faults_mod.arm(self._fault_plan)
            logging.getLogger(__name__).warning(
                "fault injection ARMED: %s", _fault_spec
            )
        self._now = now
        self._pad_bucket = pad_bucket
        self._profile_name = self.config.profiles[0].scheduler_name  # legacy alias
        self._groups: dict[str, PodGroup] = {}
        self._pvcs: dict[str, object] = {}  # "ns/name" -> PVC
        self._pvs: dict[str, object] = {}  # name -> PV
        self._storage_classes: dict[str, object] = {}
        self._pdbs: dict[str, object] = {}  # "ns/name" -> PDB
        # host-side extension points (Reserve/Permit/PreBind/PostBind) and
        # HTTP scheduler extenders — framework/host.py
        from ..framework.host import HTTPExtender

        self.host_plugins = list(host_plugins or [])
        self.extenders = [HTTPExtender(c) for c in self.config.extenders]
        # per-cycle decision log (consumed by the gRPC shim): what the last
        # schedule_cycle nominated (preemptors) and evicted (victims)
        self.last_nominations: list[tuple[Pod, str]] = []
        self.last_evictions: list[tuple[Pod, str]] = []
        # ONE encoder per profile for the scheduler's lifetime: interned
        # string ids and the resource-name axis stay stable across cycles
        # (the encoder's documented contract), and each profile keeps its
        # own delta arena (its pending subset is what carries over). The
        # profile's queueSort plugin owns each encoder's pod_order rank.
        from ..framework.queuesort import queue_sort_for_profile

        self._encoders = {
            n: SnapshotEncoder(
                queue_sort=queue_sort_for_profile(self.config.profile(n)),
                pad_existing=self.config.pad_existing or None,
                pad_pods_per_node=(
                    self.config.pad_pods_per_node or None
                ),
                pad_ma=self.config.pad_ma or None,
                pad_mc=self.config.pad_mc or None,
                pad_hysteresis_pct=self.config.pad_hysteresis_pct,
            )
            for n in names
        }
        self.forced_sync = (
            self.config.forced_sync if forced_sync is None else forced_sync
        )
        self._encoder = self._encoders[names[0]]
        self._cycle_kw = dict(
            gang_scheduling=self.config.gang_scheduling,
            commit_mode=self.config.commit_mode,
            percentage_of_nodes_to_score=(
                self.config.percentage_of_nodes_to_score
            ),
        )
        # the serving path runs the PACKED programs (two input buffers per
        # cycle instead of ~80 — see models/packing.py), compiled lazily
        # per packed-spec regime and memoized so regime flip-flops (pad
        # bucket changes) reuse earlier compilations
        self._packed: dict = {}
        self._dev_stable: dict = {}
        # multi-cycle serving (ROADMAP item 1): with multiCycleK > 1,
        # per-cycle arrival groups coalesce in _mc_groups until K groups
        # are buffered, an idle pop signals the arrival stream paused,
        # or the oldest group ages past multiCycleMaxWaitMs — then ONE
        # device dispatch runs all of them as inner cycles of a device-
        # resident loop (core/cycle.build_packed_multicycle_fn),
        # amortizing the dispatch round trip K-fold. _mc_fns memoizes
        # the per-regime multi-cycle + diagnosis programs; _mc_off pins
        # the profiles whose workload left the exactness envelope (the
        # encoder's capability flags are sticky/grow-only, so a profile
        # that left it never re-enters for this process's lifetime).
        self._mc_k = max(int(self.config.multi_cycle_k), 1)
        self._mc_wait_s = (
            max(float(self.config.multi_cycle_max_wait_ms), 0.0) / 1e3
        )
        self._mc_groups: dict[str, list[tuple[float, list[Pod]]]] = {
            n: [] for n in names
        }
        self._mc_fns: dict = {}
        self._mc_off: dict[str, str] = {}
        # profiles whose packed delta arena a batch dispatch left stale
        # (the K stacked snapshots take plain encode(), not
        # encode_packed(), so _delta_state still describes the
        # pre-batch arena): the NEXT single-cycle record is stamped
        # post_batch=1 so the observer can excuse its full re-encode
        # from the fold_miss anomaly
        self._mc_stale_arena: set[str] = set()
        # admission-time incremental encode (incrementalEncode): per-
        # profile accumulated ingest seconds since the last flush (the
        # staging work hidden in the buffering pop's shadow) and the
        # flush-time phase stamps awaiting inner record 0
        self._ingest_s: dict[str, float] = {}
        self._flush_phases: dict[str, dict] = {}
        if self.extenders:
            # extender verdicts are consulted per HOST cycle; inner
            # device cycles cannot re-consult a webhook, so batching is
            # off for every profile from the start
            self._mc_off = {n: "extender" for n in names}
        # regime-flip accounting for the observer: _packed_fns bumps the
        # build count on every memo miss and records how long the host-
        # side program (re)build took — the XLA compile itself rides the
        # first dispatch (or, with the compile cache enabled, the AOT
        # build inside _build_packed_entry), which the recompile anomaly
        # attributes. _last_compile_source tells the flip's cost class:
        # cold (full XLA compile), cache (persistent-cache load), or
        # speculative (the warm thread pre-built it).
        self._packed_builds = 0
        self._last_build_s = 0.0
        self._last_compile_source = "cold"
        # compile-regime management (core/compile_cache.py): persistent
        # AOT-executable cache under compileCacheDir (or the state dir's
        # compile_cache/ subtree), plus the speculative warm thread that
        # pre-builds the adjacent pad regime when the sentinel's demand
        # EWMA drifts toward a bucket boundary. _packed_lock serializes
        # the program memos against the warm thread; the serve path pays
        # one uncontended acquire per memo hit.
        self._packed_lock = _threading.Lock()
        cc_dir = self.config.compile_cache_dir
        if cc_dir.lower() in ("off", "none"):
            # explicit opt-out even with a state dir (slow shared
            # storage, poisoned-cache triage): "" means derive, not off
            cc_dir = ""
        elif not cc_dir and state is not None:
            cc_dir = getattr(state, "compile_cache_path", "")
        self._compile_cache = None
        if cc_dir:
            from .compile_cache import CompileCache

            self._compile_cache = CompileCache(
                cc_dir, metrics=self.metrics
            )
            if state is not None:
                # /debug/state shows hit/miss/entry counts next to the
                # journal the same directory tree holds
                state.compile_cache = self._compile_cache
        self._warmer = None
        if self.config.speculative_compile and self.observer is not None:
            from .compile_cache import CompileWarmer

            # lazy daemon thread: nothing starts until the first
            # speculative submit, so recorder-less or idle schedulers
            # never spawn it
            self._warmer = CompileWarmer(metrics=self.metrics)
        # multi-chip serving (shardDevices, ROADMAP item 3): the device-
        # resident carry shards over a 1-D ('pods',) mesh and the rounds
        # engine pins its compacted views onto it (the collective-
        # payload diet in ops/rounds.py). Placements are bit-identical
        # to the single-device run at any device count — the shard-
        # invariant tie-breaking contract (ops/argsel.py), promoted to
        # tier-1 by tests/test_shard_invariance.py.
        self._mesh = None
        d = int(self.config.shard_devices)
        if d > 1:
            import jax as _jax

            from ..parallel.mesh import make_mesh

            avail = len(_jax.devices())
            if d > avail:
                raise ValueError(
                    f"shardDevices={d} but only {avail} device(s) are "
                    "visible to this process"
                )
            if pad_bucket % d != 0:
                # every pod-axis pad is a multiple of the bucket, so a
                # divisor of the bucket always divides P
                raise ValueError(
                    f"shardDevices={d} must divide the pod pad bucket "
                    f"({pad_bucket}) so sharded arrays split evenly"
                )
            self._mesh = make_mesh(_jax.devices()[:d])
        self.n_devices = d if d > 1 else 1
        self.metrics.shard_devices.set(self.n_devices)
        # per-profile collective payload (bytes/cycle) of the current
        # regime's CYCLE program, probed from the compiled executable's
        # HLO at AOT-install time (parallel/audit.py — the same parser
        # scripts/audit_sharded.py gates on). 0 until a program has
        # been AOT-compiled (plain-jit builds are not probed: lowering
        # a second time just for accounting would double compile cost).
        self._collective_payload: dict[str, int] = {}
        self._shard_status = {
            "n_devices": self.n_devices,
            "mesh": (
                dict(self._mesh.shape) if self._mesh is not None else None
            ),
            "collective_payload_bytes": self._collective_payload,
        }
        if state is not None:
            # /debug/state shows the sharding layout + payload probe
            # next to the compile cache (same pin pattern)
            state.sharding = self._shard_status
        # carry mode (rounds only; extender verdicts replace snapshot
        # fields, which the arena spec does not carry): the [P,N] static
        # base + [S,P] matched-pending persist on device and are updated
        # for the encoder-reported dirty rows; FailedScheduling reasons
        # come from the separate diagnosis program, forced only when a
        # loser actually needs them (off the bind-latency path)
        # extenders keep the carry/latency path when EVERY one opts into
        # the verdict carry (carry_verdicts: the operator asserts its
        # Filter/Prioritize verdicts are deterministic per pod, so rows
        # persist on device and only changed pods re-consult the webhook)
        self._extender_carry = bool(self.extenders) and all(
            e.config.carry_verdicts for e in self.extenders
        )
        self._use_carry = self.config.commit_mode == "rounds" and (
            not self.extenders or self._extender_carry
        )
        if (
            self.config.commit_mode == "rounds"
            and self.extenders
            and not self._extender_carry
        ):
            # extenders WITHOUT carry_verdicts disable the carry/latency
            # path: their verdicts may be stateful, so every cycle must
            # re-consult every pod and pay the full static [P,N] rebuild
            # plus in-cycle attribution. Loud, because the deployments
            # that reach for extenders are often the ones that also care
            # about cycle latency (VERDICT r3 weak #6) — measured
            # ~+60 ms device + full re-encode at 10k x 5k. Deterministic
            # extenders can set carryVerdicts: true to keep the latency
            # path (PERF.md 'Extenders and the carry path').
            logging.getLogger(__name__).warning(
                "scheduler: %d HTTP extender(s) configured without "
                "carryVerdicts - the device-carry latency path is "
                "DISABLED; cycles take the full re-encode + in-cycle "
                "attribution path (see PERF.md 'Extenders and the "
                "carry path')",
                len(self.extenders),
            )
        # per-profile in-place-mutation reports (the delta arena must
        # re-read a nominated pod's slot): one set per profile, cleared
        # only by THAT profile's encode — a shared set would let profile
        # A's encode wipe ids recorded for profile B's pods
        self._nominated_mut: dict[str, set[int]] = {
            n: set() for n in names
        }
        # unpacked fallbacks, kept for tests/tools poking at the scheduler
        self._cycle = build_cycle_fn(self.framework, **self._cycle_kw)
        self._preempt = build_preemption_fn(self.framework)

    def _packed_fns(self, spec, profile: str):
        key = (spec.key(), profile)
        with self._packed_lock:
            entry = self._packed.get(key)
            if entry is not None:
                # true LRU: move-to-end on hit so the eviction below
                # drops the COLDEST regime, never the one serving now
                self._packed.pop(key)
                self._packed[key] = entry
                if entry.pop("fresh", None):
                    # first serve-path use of a speculative warm build:
                    # the flip speculation predicted just happened, and
                    # it costs ~zero compile here — stamp a regime_flip
                    # so the observer records the win
                    self._packed_builds += 1
                    self._last_build_s = 0.0
                    self._last_compile_source = "speculative"
                return entry["fns"]
        # build OUTSIDE the lock (seconds of trace/compile; the warm
        # thread must stay able to install other regimes meanwhile)
        entry = self._build_packed_entry(
            spec, profile,
            aot=self._compile_cache is not None and not self.extenders,
        )
        with self._packed_lock:
            cur = self._packed.setdefault(key, entry)
            self._packed.pop(key)
            self._packed[key] = cur  # newest position (LRU end)
            cur.pop("fresh", None)  # this cycle IS the flip; stamp once
            self._packed_builds += 1
            self._last_build_s = entry["build_s"]
            self._last_compile_source = entry["source"]
            # bounded: grow-only interning dimensions make old regimes
            # permanently dead — keep only the recent few (pad-bucket
            # flip-flops) instead of leaking compiled executables forever
            while len(self._packed) > 4 * len(self.frameworks):
                self._packed.pop(next(iter(self._packed)))
        return cur["fns"]

    def _build_packed_entry(
        self, spec, profile: str, aot: bool
    ) -> dict:
        """Construct one regime's full program set (the `_packed` memo
        entry). Pure with respect to scheduler state — safe on the
        speculative warm thread — except for the program-build metrics
        the AOT layer records. With `aot`, every program is
        ahead-of-time compiled through the persistent executable cache
        (core/compile_cache.py) and the loaded/compiled executable is
        installed on its _Resilient wrapper, so the first dispatch pays
        a call, not a compile."""
        from .pipeline import ServingPipeline

        fw = self.frameworks[profile]
        # wall measurement, NOT self._now(): the injected clock is
        # logical time (backoff/TTL) and may be frozen in tests/bench
        # drives — build_s feeds compile_ms attribution, which must be
        # the real seconds the (re)build cost
        t_build = _time.perf_counter()
        if self._use_carry:
            from .cycle import (
                CarryKeeper,
                ExtenderVerdictKeeper,
                build_diagnosis_fn,
                build_packed_cycle_carry_fn,
            )

            ext = self._extender_carry
            cyc = build_packed_cycle_carry_fn(
                spec, framework=fw,
                gang_scheduling=self._cycle_kw["gang_scheduling"],
                percentage_of_nodes_to_score=self._cycle_kw[
                    "percentage_of_nodes_to_score"
                ],
                extender_args=ext,
                mesh=self._mesh,
                # sharded builds fetch compacted rows via the one-hot
                # contraction (its psum stays mesh-local under the
                # shard_view pin); single-device keeps the row-gather
                rounds_kw=(
                    {"compact_gather": "onehot"}
                    if self._mesh is not None else None
                ),
            )
            keeper = CarryKeeper(spec, fw, mesh=self._mesh)
            diag = build_diagnosis_fn(spec, fw, extender_args=ext)
            ext_keeper = ExtenderVerdictKeeper(spec) if ext else None
        else:
            cyc = build_packed_cycle_fn(
                spec, framework=fw, **self._cycle_kw
            )
            keeper = diag = ext_keeper = None
        preempt = build_packed_preemption_fn(spec, fw)
        pipe = ServingPipeline(
            cyc,
            keeper=keeper,
            diag_fn=diag,
            preempt_fn=preempt,
            forced_sync=self.forced_sync,
            metrics=self.metrics,
            events=self.events,
            dispatch_deadline_s=self._dispatch_deadline_s,
            # depth-2 speculation keeps TWO batches in flight: the
            # third arena slot lets the next upload proceed without
            # overwriting either (the 2-slot default assumes one).
            # Only the multi-cycle path speculates, so single-cycle
            # serving keeps the tighter double-buffered arena
            slots=(
                3
                if self.config.speculative_dispatch
                and self.config.multi_cycle_k > 1
                else 2
            ),
        )
        fns = (
            cyc,
            preempt,
            build_stable_state_fn(spec),
            keeper, diag, ext_keeper, pipe,
        )
        source = "cold"
        if aot:
            src = self._aot_install(
                spec, profile,
                cyc=cyc, preempt=preempt, stable_fn=fns[2],
                keeper=keeper, diag=diag,
            )
            if src is not None:
                source = src
        return {
            "fns": fns,
            "build_s": _time.perf_counter() - t_build,
            "source": source,
        }

    def _aot_install(
        self, spec, profile: str, *, cyc, preempt, stable_fn, keeper,
        diag,
    ) -> "str | None":
        """AOT-compile this regime's programs through the persistent
        executable cache and install the executables on their
        _Resilient wrappers. Argument avals are derived from the spec
        alone (packed buffers) plus each upstream program's out_info,
        so no device work happens here. Returns "cache" when EVERY
        program loaded from disk, "cold" when any compiled here, None
        when AOT was impossible (the plain jit path remains)."""
        import jax

        from . import compile_cache as cc

        w = jax.ShapeDtypeStruct((spec.n_words,), np.uint32)
        b = jax.ShapeDtypeStruct((spec.n_bytes,), np.uint8)
        sources: list[str] = []

        def one(kind, fn, args, kwargs=None):
            if fn is None:
                return None
            compiled, source, _dt, out_sds = cc.load_or_compile(
                fn, self._compile_cache, spec, profile, kind,
                args=args, kwargs=kwargs,
            )
            if compiled is None:
                return None
            fn.install_aot(compiled)
            sources.append(source)
            if kind == "cycle":
                self._probe_payload(profile, compiled)
            return out_sds

        stable_sds = one("stable", stable_fn, (w, b))
        if stable_sds is None:
            return None
        if keeper is not None:
            carry_sds = one("carry_init", keeper.ci, (w, b, stable_sds))
            if carry_sds is None:
                return None
            out_sds = one("cycle", cyc, (w, b, stable_sds, carry_sds))
            idx_sds = jax.ShapeDtypeStruct((keeper.bucket,), np.int32)
            one(
                "carry_update", keeper._cu(keeper.bucket),
                (w, b, stable_sds, carry_sds, idx_sds),
            )
        else:
            out_sds = one("cycle", cyc, (w, b, stable_sds))
        if out_sds is not None and preempt is not None:
            one("preempt", preempt, (w, b, out_sds, stable_sds))
        if out_sds is not None and diag is not None:
            kwargs = {}
            pv = getattr(out_sds, "pv_claimed", None)
            if pv is not None:
                # matches CycleHandle.dispatch_diagnosis's convention
                kwargs["pv_claimed"] = pv
            one(
                "diag", diag,
                (w, b, stable_sds, out_sds.assignment,
                 out_sds.node_requested),
                kwargs,
            )
        if not sources:
            return None
        return "cache" if all(s == "cache" for s in sources) else "cold"

    def _probe_payload(self, profile: str, compiled) -> None:
        """Stamp this regime's per-cycle collective payload (bytes) off
        the compiled CYCLE executable's HLO — the same parser the audit
        gate uses (parallel/audit.py), so serving telemetry
        (`scheduler_collective_payload_bytes`, flight-record counts,
        /debug/state) can never disagree with scripts/audit_sharded.py
        about what a byte of collective is. Runs once per regime build,
        off the bind path (the AOT install already took seconds)."""
        try:
            from ..parallel.audit import collective_payload_bytes

            nbytes = int(collective_payload_bytes(compiled.as_text()))
        except Exception as e:
            # accounting only — a backend whose executables cannot
            # render HLO text must not lose its AOT install
            logging.getLogger(__name__).debug(
                "collective payload probe failed for %r: %s", profile, e
            )
            return
        self._collective_payload[profile] = nbytes
        self.metrics.collective_payload.labels(profile=profile).set(
            nbytes
        )

    def _maybe_speculate(self, profile: str, spec) -> None:
        """Speculative precompilation trigger, run at the tail of a
        profile's cycle (never the bind path — the dispatch, fetch, and
        bind loop are all behind us): when the sentinel's demand EWMA
        for this profile drifts within the margin of the current P pad
        bucket's boundary, derive the ADJACENT regime's spec
        (packing.respec — no re-encode) and hand its program build to
        the warm thread. A wrong prediction costs one wasted background
        build; a right one makes the flip's serve-path compile ~zero."""
        warmer = self._warmer
        obs = self.observer
        if warmer is None or obs is None:
            return
        from ..models import packing

        sig = dict(packing.shape_signature(spec))
        P = sig.get("P", 0)
        if P <= 0:
            return
        demand = obs.demand_ewma(profile)
        if demand <= 0.0:
            return
        bucket = self._pad_bucket
        targets = []
        if demand >= 0.85 * P:
            # drifting UP toward the boundary: the next bucket's regime
            targets.append(_pad(P + 1, bucket))
        down = _pad(max(int(demand), 1), bucket)
        if down < P and demand <= down * (
            1.0 - max(self.config.pad_hysteresis_pct, 10.0) / 100.0
        ):
            # drifting DOWN with enough headroom that hysteresis (or a
            # plain re-bucket) will actually step the regime down
            targets.append(down)
        for tgt in targets:
            adj = packing.respec(spec, {"P": tgt})
            if adj is None:
                continue
            key = (adj.key(), profile)
            with self._packed_lock:
                if key in self._packed:
                    continue
            warmer.enqueue_build(
                ("packed",) + key,
                lambda adj=adj, profile=profile: self._warm_regime(
                    adj, profile
                ),
            )

    def _warm_regime(self, spec, profile: str) -> None:
        """Warm-thread body: pre-build one predicted regime's programs
        into the `_packed` (and, under multi-cycle serving, `_mc_fns`)
        memos and the persistent executable cache. Installs with
        setdefault — if the serve loop flipped first and built its own
        entry, this build is discarded (the disk entries still land)."""
        key = (spec.key(), profile)
        with self._packed_lock:
            if key in self._packed:
                return
        entry = self._build_packed_entry(spec, profile, aot=True)
        entry["source"] = "speculative"
        entry["fresh"] = True
        with self._packed_lock:
            self._packed.setdefault(key, entry)
            while len(self._packed) > 4 * len(self.frameworks) + 1:
                # +1: a fresh speculative entry must not evict a live
                # regime the moment it lands, nor be evicted itself
                self._packed.pop(next(iter(self._packed)))
        if self._mc_k > 1 and profile not in self._mc_off:
            with self._packed_lock:
                if key in self._mc_fns:
                    return
            m_entry = self._build_mc_entry(spec, profile, aot=True)
            m_entry["source"] = "speculative"
            m_entry["fresh"] = True
            with self._packed_lock:
                self._mc_fns.setdefault(key, m_entry)
                while len(self._mc_fns) > 4 * len(self.frameworks) + 1:
                    self._mc_fns.pop(next(iter(self._mc_fns)))


    def _stable_state(self, spec, stable_fn, wbuf, bbuf, encoder=None):
        """Device-resident stable-side precomputes, rerun only when the
        encoder's stable side (nodes / existing pods / dedup tables) or
        the packed-spec regime changes. A miss costs one extra ASYNC
        dispatch of a ~2ms device program (cheaper than the fused
        in-cycle recompute it replaces), so even a bind-every-cycle
        workload — whose existing-pod set changes every cycle — comes out
        ahead; the memo is bounded like _packed for pad flip-flops."""
        # keyed on the encoder's stable-cache dict IDENTITY, with a strong
        # ref pinned in the entry: the encoder's _stable_key tuple contains
        # raw id()s whose objects older memo entries would not pin, so a
        # recycled address could otherwise produce a false hit on stale
        # existing-pod tables. fold_hits joins the key because the
        # incremental existing-fold mutates the st dict IN PLACE (same
        # identity, new contents) — each fold must recompute the device
        # stable precomputes.
        enc = encoder or self._encoder
        enc_st = getattr(enc, "_stable", None)
        key = (spec.key(), id(enc_st), getattr(enc, "fold_hits", 0))
        hit = self._dev_stable.get(key)
        if hit is None or hit[0] is not enc_st:
            hit = (enc_st, stable_fn(wbuf, bbuf))
            self._dev_stable[key] = hit
            while len(self._dev_stable) > 4 * len(self.frameworks):
                self._dev_stable.pop(next(iter(self._dev_stable)))
        return hit[1]

    # ---- informer-style event handlers (SURVEY.md §3.3) ------------------

    def on_pod_add(self, pod: Pod, node_name: str = "") -> None:
        if node_name:
            # observed bound: drop any stale queue entry (a late informer
            # echo after an assumption expired must not leave the pod both
            # pending and existing, which would double-schedule it)
            self.queue.delete(pod.uid)
            self.cache.add_pod(pod, node_name)
            self.queue.move_all_to_active_or_backoff(EVENT_POD_ADD)
            if self.flight is not None:
                self.flight.pod_event(
                    pod.uid, pod.name, "BoundObserved", node=node_name
                )
        else:
            self.queue.add(pod)
            if self.flight is not None:
                self.flight.pod_event(pod.uid, pod.name, "Queued")

    def on_pod_update(self, pod: Pod, node_name: str = "") -> None:
        if node_name:
            self.queue.delete(pod.uid)
            self.cache.add_pod(pod, node_name)
            self.queue.move_all_to_active_or_backoff(EVENT_POD_UPDATE)
            if self.flight is not None:
                self.flight.pod_event(
                    pod.uid, pod.name, "BoundObserved", node=node_name
                )
        else:
            self.queue.update(pod)
            if self.flight is not None:
                self.flight.pod_event(pod.uid, pod.name, "Updated")

    def on_pod_delete(self, pod_uid: str) -> None:
        self.cache.remove_pod(pod_uid)
        self.queue.delete(pod_uid)
        if self.admission is not None:
            # a pod deleted before binding must leave the front door's
            # accepted-pending set, or its uid stays "already pending"
            # forever and a re-created pod can never be admitted
            self.admission.note_delete(pod_uid)
        self.queue.move_all_to_active_or_backoff(EVENT_POD_DELETE)
        if self.flight is not None:
            self.flight.pod_event(pod_uid, "", "Deleted")

    def on_node_add(self, node: Node) -> None:
        self.cache.add_node(node)
        self.queue.move_all_to_active_or_backoff(EVENT_NODE_ADD)

    def on_node_update(self, node: Node) -> None:
        self.cache.update_node(node)
        self.queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)

    def on_node_delete(self, node_name: str) -> None:
        self.cache.remove_node(node_name)
        self.queue.move_all_to_active_or_backoff(EVENT_NODE_DELETE)

    def add_pod_group(self, group: PodGroup) -> None:
        self._groups[group.name] = group

    # ---- volume objects (VolumeBinding inputs) ---------------------------

    def on_pvc_upsert(self, pvc) -> None:
        self._pvcs[pvc.key] = pvc
        self.queue.move_all_to_active_or_backoff(EVENT_PVC_CHANGE)

    def on_pvc_delete(self, key: str) -> None:
        self._pvcs.pop(key, None)
        self.queue.move_all_to_active_or_backoff(EVENT_PVC_CHANGE)

    def on_pv_upsert(self, pv) -> None:
        self._pvs[pv.name] = pv
        self.queue.move_all_to_active_or_backoff(EVENT_PV_CHANGE)

    def on_pv_delete(self, name: str) -> None:
        self._pvs.pop(name, None)
        self.queue.move_all_to_active_or_backoff(EVENT_PV_CHANGE)

    def on_storage_class_upsert(self, sc) -> None:
        self._storage_classes[sc.name] = sc
        self.queue.move_all_to_active_or_backoff(EVENT_STORAGE_CLASS_CHANGE)

    def on_storage_class_delete(self, name: str) -> None:
        self._storage_classes.pop(name, None)
        self.queue.move_all_to_active_or_backoff(EVENT_STORAGE_CLASS_CHANGE)

    def on_pdb_upsert(self, pdb) -> None:
        self._pdbs[pdb.key] = pdb

    def on_pdb_delete(self, key: str) -> None:
        self._pdbs.pop(key, None)

    # ---- the cycle -------------------------------------------------------

    def schedule_cycle(self) -> CycleStats:
        """One batched scheduling cycle over everything ready to run.
        Pods route to their profile's framework by
        `pod.spec.scheduler_name` (upstream: multiple schedulers by
        schedulerName); profiles run in declaration order within the
        cycle, each seeing the previous profiles' assumptions."""
        from . import faults as _faults

        self._cycle_counter += 1
        self._cycle_fault = False
        t0 = self._now()
        if _faults.ARMED:
            # ambient cycle index for fault-rule windows, and the
            # clock_skew injection point (derived stats must tolerate a
            # stepping clock read)
            _faults.set_cycle(self._cycle_counter)
            t0 += _faults.skew_s()
        stats = CycleStats()
        self.last_nominations = []
        self.last_evictions = []
        for pod, node in self.cache.cleanup_expired():
            # TTL expiry used to drop the pod without a trace
            # (/debug/pods showed an assumed pod simply vanishing):
            # leave an events-ring entry + an `Expired` timeline attempt
            # explaining the requeue before backoff takes it
            self.queue.requeue_backoff(pod, event="AssumeExpired")
            self.events.assume_expired(pod, node)
            if self.flight is not None:
                self.flight.pod_event(
                    pod.uid, pod.name, "Expired", node=node
                )
        self.queue.flush_unschedulable_timeout()

        # multi-cycle batching is gated on the degradation ladder: at or
        # below the `sequential` rung every cycle dispatches alone
        mc_on = self._mc_k > 1 and self.ladder.rung < RUNG_SEQUENTIAL
        if self._mc_k > 1 and not mc_on:
            # the ladder stepped below `sequential` with groups still
            # coalescing: drain them as single-cycle dispatches BEFORE
            # this cycle's non-hold pop replaces the in-flight set (a
            # stranded buffer's pods would be neither queued nor
            # in-flight — lost)
            for name in self._profile_order:
                buf = self._mc_groups[name]
                if not buf:
                    continue
                self._mc_groups[name] = []
                for _t_enq, g in buf:
                    stats.attempted += len(g)
                    self._schedule_profile(name, g, stats, t0)
                self.queue.retire_in_flight(
                    [p.uid for _t_enq, g in buf for p in g]
                )
        mc_buffered = mc_on and any(
            self._mc_groups[n] for n in self._profile_order
        )
        # hold-pop while groups are buffered: their in-flight entries
        # (attempts counts, delete tombstones, crash recovery) must
        # survive until the batch flush applies their outcomes
        pending_all = self.queue.pop_ready(hold=mc_buffered)
        if not pending_all and not mc_buffered and stats.attempted == 0:
            # gauges must track deletions/moves that happen between
            # non-empty cycles, so update them on the empty path too
            # (attempted > 0 means the rung-gated drain above dispatched
            # — that work must flow through the full cycle epilogue)
            self._update_gauges()
            if self.state is not None:
                self.state.maybe_snapshot()
            return stats
        if pending_all:
            # += not =: the rung-gated buffer drain above may already
            # have counted its groups into this cycle's attempted
            stats.attempted += len(pending_all)
            self.metrics.cycle_pods.observe(len(pending_all))

        by_prof: dict[str, list[Pod]] = {
            n: [] for n in self._profile_order
        }
        for pod in pending_all:
            name = pod.spec.scheduler_name or self._profile_order[0]
            lst = by_prof.get(name)
            if lst is None:
                # a pod naming a scheduler this process does not serve is
                # not ours to place — park it loudly instead of silently
                # scheduling it under the wrong profile
                self.events.failed_scheduling(
                    pod,
                    f"no profile named {name!r} in this scheduler",
                )
                self.queue.requeue_unschedulable(
                    pod, reasons=("UnknownSchedulerName",)
                )
                stats.unschedulable += 1
                self.metrics.observe_attempt(
                    "unschedulable", self._now() - t0, name
                )
                continue
            lst.append(pod)

        for name in self._profile_order:
            group = by_prof[name]
            if mc_on and name not in self._mc_off:
                # multi-cycle coalescing: buffer this pop's arrival group
                # and flush K of them as ONE device dispatch. Flush when
                # the batch is full, the arrival stream paused (an empty
                # pop — holding a ready group while nothing else is
                # coming would be pure added latency), or the oldest
                # group aged past the latency bound.
                buf = self._mc_groups[name]
                if group:
                    buf.append((t0, group))
                    if self.config.incremental_encode:
                        # admission-time incremental encode: parse each
                        # newly buffered pod's arena row NOW, in the
                        # buffering pop's shadow — the first serve-
                        # thread moment after the front door acked it
                        # (the encoder is serve-thread-owned, so the
                        # ack path proper never touches it). The flush
                        # then finalizes with an O(dirty) apply.
                        self._ingest_group(name, group)
                if not buf:
                    continue
                if (
                    len(buf) >= self._mc_k
                    or not group
                    or (t0 - buf[0][0]) >= self._mc_wait_s
                ):
                    self._mc_groups[name] = []
                    if _spans.ARMED:
                        # mc.buffer_wait: admission-group enqueue ->
                        # this flush, one span per sampled pod. The
                        # wait is a scheduler-clock delta (t0/t_enq
                        # may ride an injected test clock); the span
                        # anchors its END at the recorder clock so it
                        # abuts the dispatch span that follows.
                        t_flush = _spans.now()
                        for t_enq, g in buf:
                            wait_s = max(t0 - t_enq, 0.0)
                            for p in g:
                                c = _spans.ctx_for(p.uid)
                                if c is not None:
                                    _spans.record_span(
                                        "mc.buffer_wait", c,
                                        t_flush - wait_s, t_flush,
                                        uid=p.uid, groups=len(buf),
                                    )
                    # a pod is "attempted" in the cycle whose dispatch
                    # carries it: groups popped by EARLIER buffering
                    # cycles count NOW (their buffering cycle
                    # subtracted them below), so per-cycle stats keep
                    # scheduled <= attempted and a cross-cycle
                    # sum(scheduled)/sum(attempted) rate stays honest
                    # (this cycle's own group is already counted via
                    # pending_all)
                    stats.attempted += (
                        sum(len(g) for _t, g in buf) - len(group)
                    )
                    if len(buf) == 1:
                        # a lone group gains nothing from the stacked
                        # path — keep it on the delta/carry-optimized
                        # single-cycle encode
                        self._schedule_profile(
                            name, buf[0][1], stats, t0
                        )
                    else:
                        self._schedule_profile_multi(
                            name, buf, stats, t0
                        )
                    # outcomes applied: drop the batch's pods from the
                    # in-flight set. Hold pops only ACCUMULATE, and
                    # out-of-phase profile buffers can keep every pop
                    # holding — without this, bound pods stay
                    # "recoverable" forever (unbounded growth + a
                    # takeover re-binding pods bound long ago)
                    self.queue.retire_in_flight(
                        [p.uid for _t_enq, g in buf for p in g]
                    )
                    if self.config.incremental_encode:
                        # staged rows the flush did not consume (shed /
                        # dropped pods) must not outlive their batch
                        self._encoders[name].clear_ingest()
                        self._ingest_s.pop(name, None)
                else:
                    # buffered, not dispatched: attempted at the flush
                    stats.attempted -= len(group)
            elif group:
                self._schedule_profile(name, group, stats, t0)
                if self._mc_k > 1:
                    # this profile is pinned out of batching but other
                    # profiles' buffers may be holding every pop — its
                    # outcomes are applied, so retire explicitly too
                    # (K=1 serving skips this: the non-hold pop's
                    # wholesale replacement retires, and the journal
                    # stream stays byte-identical to the seed's)
                    self.queue.retire_in_flight(
                        [p.uid for p in group]
                    )

        stats.cycle_seconds = self._now() - t0
        self.metrics.cycle_duration.labels(phase="total").observe(
            stats.cycle_seconds
        )
        if stats.attempted > 0 and not self._cycle_fault:
            # promotion bookkeeping: only cycles that actually exercised
            # the dispatch path count as evidence the fault cleared
            self.ladder.note_clean_cycle(seq=self._cycle_counter)
        self._update_gauges()
        if self.state is not None:
            # interval-gated journal compaction, deliberately AFTER
            # cycle_seconds is stamped: snapshots ride between cycles,
            # never inside the per-profile bind path
            self.state.maybe_snapshot()
        return stats

    def _schedule_profile(
        self, profile: str, pending: list[Pod], stats: CycleStats,
        t0: float,
    ) -> None:
        framework = self.frameworks[profile]
        encoder = self._encoders[profile]
        fr = self.flight
        rec = fr.start(profile) if fr is not None else None
        builds_before = self._packed_builds
        if rec is not None:
            rec.mark("encode_start", rec.t_start)
            # per-profile deltas: CycleStats accumulates across profiles
            _before = (
                stats.scheduled, stats.unschedulable, stats.bind_errors,
                stats.preemptors, stats.victims,
            )
        nodes = self.cache.nodes()
        existing = self.cache.existing_pods()
        # bucketed pod/node padding keeps jit caches warm across cycles;
        # hysteresis_pad damps the DOWN-steps (padHysteresisPct), so a
        # count oscillating around a bucket boundary holds the larger
        # already-compiled regime instead of flip-flopping
        encoder.pad_pods = encoder.hysteresis_pad(
            "P", _pad(len(pending), self._pad_bucket), len(pending)
        )
        encoder.pad_nodes = encoder.hysteresis_pad(
            "N", _pad(len(nodes), self._pad_bucket), len(nodes)
        )
        kw = dict(
            pod_groups=list(self._groups.values()),
            pvcs=list(self._pvcs.values()),
            pvs=list(self._pvs.values()),
            storage_classes=list(self._storage_classes.values()),
            pdbs=list(self._pdbs.values()),
        )
        from ..models import packing

        extender_errors: dict[int, str] = {}
        diag = None
        t_start = self._now()
        import os as _os

        do_device_put = _os.environ.get("K8S_TPU_NO_DEVICE_PUT") != "1"
        if self._use_carry:
            mut = self._nominated_mut[profile]
            wbuf, bbuf, spec, snap, dirty = encoder.encode_packed(
                nodes, pending, existing,
                mutated_ids=frozenset(mut), **kw
            )
            mut.clear()
            # ONE host->device upload per cycle (device_put copies the
            # arena synchronously); numpy args would re-upload the packed
            # buffers once per program in the chain below
            if do_device_put:
                import jax as _jax

                wbuf = _jax.device_put(wbuf)
                bbuf = _jax.device_put(bbuf)
            (
                pcycle, ppreempt, stable_fn, keeper, diag, ext_keeper,
                pipe,
            ) = self._packed_fns(spec, profile)
            try:
                stable = self._stable_state(
                    spec, stable_fn, wbuf, bbuf, encoder
                )
            except Exception as e:
                # a device failure BEFORE any bind (stable precompute):
                # step the ladder and requeue — no winner exists yet,
                # so the whole pending set retries safely
                self._cycle_failed(profile, pending, e, stats, t0, rec)
                return
            t_encode = self._now()
            self.metrics.cycle_duration.labels(phase="encode").observe(
                t_encode - t_start
            )
            ext_mask = ext_score = None
            if ext_keeper is not None:
                # extender-verdict carry: webhooks consulted only for
                # pods whose CONTENT changed (last_changed_slots — the
                # returned dirty set may be inflated by NodePorts carry
                # repair slots, which don't affect extender verdicts);
                # rows persist on device
                ext_dirty = getattr(
                    encoder, "last_changed_slots", None
                )
                if ext_dirty is None and dirty is not None:
                    ext_dirty = dirty
                ext_mask, ext_score = ext_keeper.state(
                    self.extenders, pending, nodes, ext_dirty,
                    (
                        spec.key(),
                        getattr(encoder, "_carry_key", None),
                    ),
                )
                extender_errors = {
                    i: m for i, m in ext_keeper.errors.items()
                    if i < len(pending)
                }
            # async dispatch: the carry update (keyed on _carry_key —
            # stable key MINUS existing/PDBs — plus the st dict identity;
            # a bound-pod fold mutates st IN PLACE, carry still valid)
            # and the latency cycle program go out without blocking; the
            # only synchronous read below is the slimmed decision fetch
            enc_st = getattr(encoder, "_stable", None)
            pipe.forced_sync = (
                self.forced_sync or self.ladder.rung >= RUNG_FORCED_SYNC
            )
            pipe.dispatch_deadline_s = self._dispatch_deadline_s
            pipe.note_encode(t_encode - t_start)
            try:
                handle = pipe.dispatch(
                    wbuf, bbuf, stable,
                    dirty=dirty,
                    carry_key=(
                        spec.key(), id(enc_st),
                        getattr(encoder, "_carry_key", None),
                    ),
                    pin=enc_st,
                    emask=ext_mask, escore=ext_score,
                    device_put=False,  # uploaded above (stable/carry
                    # share it)
                )
            except Exception as e:
                self._cycle_failed(profile, pending, e, stats, t0, rec)
                return
        else:
            snap = encoder.encode(nodes, pending, existing, **kw)
            if self.extenders:
                from ..framework.host import run_extender_prepass

                emask, escore, extender_errors = run_extender_prepass(
                    self.extenders, pending, nodes
                )
                if emask is not None:
                    import dataclasses as _dc

                    full_mask = np.ones((snap.P, snap.N), bool)
                    full_score = np.zeros((snap.P, snap.N), np.float32)
                    full_mask[: len(pending), : len(nodes)] = emask
                    full_score[: len(pending), : len(nodes)] = escore
                    snap = _dc.replace(
                        snap,
                        has_extender=True,
                        pod_extender_mask=full_mask,
                        pod_extender_score=full_score,
                    )
            spec = packing.make_spec(snap)
            (
                pcycle, ppreempt, stable_fn, _keeper, diag, _ek, pipe,
            ) = self._packed_fns(spec, profile)
            wbuf, bbuf = packing.pack(snap, spec)
            if do_device_put:
                import jax as _jax

                wbuf = _jax.device_put(wbuf)
                bbuf = _jax.device_put(bbuf)
            try:
                stable = self._stable_state(
                    spec, stable_fn, wbuf, bbuf, encoder
                )
            except Exception as e:
                self._cycle_failed(profile, pending, e, stats, t0, rec)
                return
            t_encode = self._now()
            self.metrics.cycle_duration.labels(phase="encode").observe(
                t_encode - t_start
            )
            pipe.forced_sync = (
                self.forced_sync or self.ladder.rung >= RUNG_FORCED_SYNC
            )
            pipe.dispatch_deadline_s = self._dispatch_deadline_s
            pipe.note_encode(t_encode - t_start)
            try:
                handle = pipe.dispatch(
                    wbuf, bbuf, stable, device_put=False
                )
            except Exception as e:
                self._cycle_failed(profile, pending, e, stats, t0, rec)
                return
        # the ONLY blocking transfer on the bind path: the slimmed
        # decision payload (i16 assignment + u8 flags per pod). A
        # failure here — deadline expiry, transport flake past the
        # retries, corrupt/wedged executable — consumes the cycle (the
        # pipeline guard released) and walks the degradation ladder;
        # every pod requeues with backoff, none was bound.
        try:
            assignment, _unsched, gang_dropped = handle.decisions()
        except Exception as e:
            self._cycle_failed(profile, pending, e, stats, t0, rec)
            return
        assignment = assignment[: len(pending)]
        gang_dropped = gang_dropped[: len(pending)]
        # accumulate like every sibling counter: in a multi-profile
        # cycle `=` would report only the LAST profile's gang drops
        profile_gang_dropped = int(gang_dropped.sum())
        stats.gang_dropped += profile_gang_dropped
        t_device = self._now()
        self.metrics.cycle_duration.labels(phase="device").observe(
            t_device - t_encode
        )
        self.metrics.decisions.inc(len(pending) * len(nodes))

        # FailedScheduling attribution: under carry mode the cycle does
        # not compute reject counts — the diagnosis program does,
        # dispatched non-blocking here and forced lazily the first time
        # a loser needs reasons (the loser pass runs AFTER winners bind,
        # so the attribution program overlaps the host bind loop)
        if diag is not None and (assignment < 0).any():
            handle.dispatch_diagnosis()
        _rej_box: list = []

        def reject_counts_fn():
            # ONE force of the whole [P, F] attribution matrix — the
            # vectorized loser fold consumes it column-wise
            if not _rej_box:
                _rej_box.append(
                    handle.reject_counts_matrix(len(pending))
                )
            return _rej_box[0]

        # preemption dispatched async too; its device time overlaps the
        # winner bind loop below and is forced only before losers are
        # processed (nominations/evictions are loser-side outputs)
        pre_handle = None
        if ppreempt is not None and (assignment < 0).any():
            self.metrics.preemption_attempts.inc()
            pre_handle = handle.dispatch_preemption()
        def force_pre():
            if pre_handle is None:
                return None, None
            return (
                np.asarray(pre_handle.nominated)[: len(pending)],
                np.asarray(pre_handle.victims)[: len(existing)],
            )

        self._apply_phase(
            profile, framework, pending, nodes, existing, assignment,
            gang_dropped, extender_errors, reject_counts_fn, force_pre,
            stats, t0, rec, t_device,
        )

        # ---- flight record: assemble + commit (one list store) ----
        if rec is not None:
            st = pipe.stage_report()
            # latency-attribution enrichment (core/observe.py reads
            # these at publish): the encoder's incremental-fold share
            # of the encode, and the program-(re)build cost when this
            # cycle flipped regimes
            extra_phases: dict = {}
            extra_counts: dict = {}
            compile_source = ""
            fold_ms = encoder.delta_profile.get("fold")
            if fold_ms:
                extra_phases["fold_ms"] = float(fold_ms)
            if self.config.incremental_encode:
                # a lone buffered group flushed through the single-
                # cycle path with staged ingest rows: its encode WAS
                # the finalize, so the ingest/finalize split lands
                # here too (the mc flush stamps via _flush_phases)
                ing_s = self._ingest_s.pop(profile, 0.0)
                if ing_s > 0.0:
                    fin_s = max(t_encode - t_start, 0.0)
                    extra_phases["encode_ingest_ms"] = ing_s * 1e3
                    extra_phases["encode_finalize_ms"] = fin_s * 1e3
                    self.metrics.encode_finalize.observe(fin_s)
            if self._packed_builds > builds_before:
                extra_phases["compile_ms"] = self._last_build_s * 1e3
                extra_counts["regime_flip"] = 1
                # cold | cache | speculative — how the flip was paid
                compile_source = self._last_compile_source
            if profile in self._mc_stale_arena:
                # first single-cycle dispatch after a batch: a full
                # re-encode here is the batch's fault (its plain
                # encodes left _delta_state stale), not a fold miss —
                # cleared now because this encode_packed reinstalled
                # the arena, so later full encodes are unexplained
                self._mc_stale_arena.discard(profile)
                extra_counts["post_batch"] = 1
            self._commit_record(
                rec, st, spec, encoder, pending, nodes, stats,
                _before, profile_gang_dropped,
                fetch_bytes=int(st.get("fetch_bytes", 0)),
                extra_phases=extra_phases, extra_counts=extra_counts,
                compile_source=compile_source,
            )
            if "diag_lag_ms" in st:
                self.metrics.diag_lag.observe(st["diag_lag_ms"] / 1e3)
        # speculative precompilation: after the cycle's work is fully
        # committed, check whether demand is drifting toward a pad
        # boundary and pre-build the adjacent regime off-thread
        self._maybe_speculate(profile, spec)

    def _mc_programs(self, spec, profile: str):
        """Memoized multi-cycle program pair for one packed regime:
        (multicycle_fn, diagnosis_fn). Counted into `_packed_builds`
        like every other program build so the observer's recompile
        anomaly attributes the one-time compile cost of a new regime's
        batch program. True LRU: a hit moves the entry to the end, so
        eviction drops the coldest regime — the seed's FIFO pop could
        evict the hottest multi-cycle regime while a cold one stayed
        (regression-tested in tests/test_compile_cache.py)."""
        key = (spec.key(), profile)
        with self._packed_lock:
            entry = self._mc_fns.get(key)
            if entry is not None:
                self._mc_fns.pop(key)
                self._mc_fns[key] = entry  # move-to-end on hit
                if entry.pop("fresh", None):
                    self._packed_builds += 1
                    self._last_build_s = 0.0
                    self._last_compile_source = "speculative"
                return entry["fns"]
        entry = self._build_mc_entry(
            spec, profile,
            aot=self._compile_cache is not None and not self.extenders,
        )
        with self._packed_lock:
            cur = self._mc_fns.setdefault(key, entry)
            self._mc_fns.pop(key)
            self._mc_fns[key] = cur
            cur.pop("fresh", None)
            self._packed_builds += 1
            self._last_build_s = entry["build_s"]
            self._last_compile_source = entry["source"]
            while len(self._mc_fns) > 4 * len(self.frameworks):
                self._mc_fns.pop(next(iter(self._mc_fns)))
        return cur["fns"]

    def _build_mc_entry(self, spec, profile: str, aot: bool) -> dict:
        """Construct one regime's multi-cycle program pair (the
        `_mc_fns` memo entry); warm-thread safe like
        _build_packed_entry."""
        from .cycle import (
            build_diagnosis_fn,
            build_packed_multicycle_fn,
        )

        t_build = _time.perf_counter()  # wall, like _build_packed_entry
        fw = self.frameworks[profile]
        mfn = build_packed_multicycle_fn(
            spec, framework=fw, k=self._mc_k, **self._cycle_kw
        )
        # the multi-cycle decisions are lean (no fused reject
        # counts), so every regime needs the separate diagnosis
        # program — including scan-mode regimes whose single-cycle
        # path runs the fused full program and has none
        mdiag = build_diagnosis_fn(spec, fw)
        # depth-2 speculation chains batch k+1 onto batch k's
        # device-resident carry through the carry_in continuation
        # variant; only built when the config can ever dispatch one
        mcont = None
        if self.config.speculative_dispatch:
            mcont = build_packed_multicycle_fn(
                spec, framework=fw, k=self._mc_k, carry_in=True,
                **self._cycle_kw,
            )
        source = "cold"
        if aot:
            src = self._aot_install_multi(
                spec, profile, mfn=mfn, mdiag=mdiag, mcont=mcont
            )
            if src is not None:
                source = src
        return {
            "fns": (mfn, mdiag, mcont),
            "build_s": _time.perf_counter() - t_build,
            "source": source,
        }

    def _aot_install_multi(
        self, spec, profile: str, *, mfn, mdiag, mcont=None
    ) -> "str | None":
        """AOT layer for the multi-cycle programs: the stacked [K, ...]
        batch loop (kind `multicycle-K` — K is static in the program),
        its per-row diagnosis companion (same key as the single-cycle
        diag when the conventions match, so the disk entry is shared),
        and — under speculativeDispatch — the carry-in continuation
        variant (kind `multicycle-cont-K`; two extra carry arguments,
        so it can never alias the plain entry)."""
        import jax

        from . import compile_cache as cc
        from .cycle import build_stable_state_fn

        w1 = jax.ShapeDtypeStruct((spec.n_words,), np.uint32)
        b1 = jax.ShapeDtypeStruct((spec.n_bytes,), np.uint8)
        wk = jax.ShapeDtypeStruct(
            (self._mc_k, spec.n_words), np.uint32
        )
        bk = jax.ShapeDtypeStruct((self._mc_k, spec.n_bytes), np.uint8)
        try:
            stable_sds = jax.eval_shape(
                build_stable_state_fn(spec), w1, b1
            )
        except Exception as e:
            logging.getLogger(__name__).warning(
                "multi-cycle AOT install skipped: stable-state avals "
                "unavailable (%s); the jit path remains", e,
            )
            return None
        n_sds = jax.ShapeDtypeStruct((), np.int32)
        sources: list[str] = []
        compiled, source, _dt, out_sds = cc.load_or_compile(
            mfn, self._compile_cache, spec, profile,
            f"multicycle-{self._mc_k}",
            args=(wk, bk, stable_sds, n_sds),
        )
        if compiled is not None:
            mfn.install_aot(compiled)
            sources.append(source)
        if mcont is not None and out_sds is not None:
            # continuation avals: the same stacked inputs plus the
            # predecessor's final carry (shapes straight off out_sds)
            nr0 = jax.ShapeDtypeStruct(
                tuple(out_sds.carry_node_requested.shape), np.float32
            )
            gp0 = jax.ShapeDtypeStruct(
                tuple(out_sds.carry_gplaced.shape), np.int32
            )
            compiled_c, source_c, _dt, _out_c = cc.load_or_compile(
                mcont, self._compile_cache, spec, profile,
                f"multicycle-cont-{self._mc_k}",
                args=(wk, bk, stable_sds, n_sds, nr0, gp0),
            )
            if compiled_c is not None:
                mcont.install_aot(compiled_c)
                sources.append(source_c)
        if out_sds is not None:
            a_row = jax.ShapeDtypeStruct(
                tuple(out_sds.assignment.shape[1:]), np.int32
            )
            nr_row = jax.ShapeDtypeStruct(
                tuple(out_sds.node_requested.shape[1:]), np.float32
            )
            compiled_d, source_d, _dt, _out = cc.load_or_compile(
                mdiag, self._compile_cache, spec, profile, "diag",
                args=(w1, b1, stable_sds, a_row, nr_row),
            )
            if compiled_d is not None:
                mdiag.install_aot(compiled_d)
                sources.append(source_d)
        if not sources:
            return None
        return "cache" if all(s == "cache" for s in sources) else "cold"

    def _ingest_group(self, profile: str, group: "list[Pod]") -> None:
        """Stage each newly buffered pod's arena row (incrementalEncode,
        models/encoding.SnapshotEncoder.ingest_pod) so the flush's per-
        group delta encode skips the parse. The staging seconds
        accumulate per profile for the flush record's encode_ingest_ms
        phase — the host encode cost hidden from the dispatch path."""
        enc = self._encoders[profile]
        t_ing = self._now()
        for p in group:
            enc.ingest_pod(p)
        ing_s = max(self._now() - t_ing, 0.0)
        self._ingest_s[profile] = self._ingest_s.get(profile, 0.0) + ing_s
        self.metrics.encode_ingest.observe(ing_s)
        if _spans.ARMED:
            # encode.ingest: this group's admission-time row staging
            # (scheduler-clock duration anchored at the recorder clock,
            # same discipline as mc.buffer_wait)
            t1 = _spans.now()
            for p in group:
                c = _spans.ctx_for(p.uid)
                if c is not None:
                    _spans.record_span(
                        "encode.ingest", c, t1 - ing_s, t1,
                        uid=p.uid, pods=len(group),
                    )

    def _schedule_profile_multi(
        self,
        profile: str,
        groups: "list[tuple[float, list[Pod]]]",
        stats: CycleStats,
        t0: float,
    ) -> None:
        """Dispatch the buffered arrival groups as a multi-cycle
        device batch (core/cycle.build_packed_multicycle_fn): group i
        becomes inner cycle i of a device-resident loop, paying one
        dispatch round trip for up to K scheduling cycles. Under
        `speculativeDispatch` the flush splits depth-2 — row 0
        dispatches alone and the rest ride its dispatch shadow as a
        speculative continuation batch (_schedule_profile_multi_spec);
        either way the decision rows stream back per inner cycle
        (_apply_mc_rows) instead of blocking on the stacked fetch.

        Semantics contract: each inner cycle's decisions are applied
        through `_apply_phase` in batch order — binds, journal records,
        events, and pod timelines land per cycle exactly as K sequential
        dispatches would, so durability does not change across the
        batch boundary. The device loop threads the post-cycle capacity
        + gang-count carry the host fold would have produced; workloads
        whose snapshots leave the exactness envelope
        (`multicycle_unsupported_reason`) fall back to sequential
        single-cycle dispatches — sticky capability reasons (affinity /
        topology spread / volumes, grow-only encoder flags) pin the
        profile out of batching for the process lifetime, while
        host_ports is per-snapshot: a later port-free batch re-enters
        the device loop."""
        fr = self.flight
        nodes = self.cache.nodes()
        existing = self.cache.existing_pods()
        kw = dict(
            pod_groups=list(self._groups.values()),
            pvcs=list(self._pvcs.values()),
            pvs=list(self._pvs.values()),
            storage_classes=list(self._storage_classes.values()),
            pdbs=list(self._pdbs.values()),
        )
        from ..models import packing
        from .cycle import multicycle_unsupported_reason

        # one spec for every row: pad to the LARGEST group so all K
        # packed snapshots stack into [K, W]/[K, B]; down-steps damped
        # by the same hysteresis as the single-cycle path
        mc_pods = max(len(g) for _, g in groups)
        encoder = self._encoders[profile]
        encoder.pad_pods = encoder.hysteresis_pad(
            "P", _pad(mc_pods, self._pad_bucket), mc_pods
        )
        encoder.pad_nodes = encoder.hysteresis_pad(
            "N", _pad(len(nodes), self._pad_bucket), len(nodes)
        )
        builds_before = self._packed_builds
        t_batch = self._now()
        t_batch_rec = fr.now() if fr is not None else 0.0
        inc = self.config.incremental_encode
        if not inc:
            # the stacked snapshots below take plain encode() — the
            # packed delta arena is bypassed and its _delta_state goes
            # stale, so the next single-cycle encode_packed may
            # legitimately fall back to a full encode (set even when
            # the envelope precheck falls back: the plain encodes have
            # run either way). Under incrementalEncode every group
            # folds through encode_packed, so the arena stays fresh.
            self._mc_stale_arena.add(profile)

        # depth-2 speculative dispatch pipelining (speculativeDispatch):
        # row 0 dispatches alone and the remaining rows ride its
        # dispatch shadow as a speculative continuation batch — first
        # bind lands after ~1 inner cycle instead of K. Forced off
        # under forcedSync, at/below the ladder's `sequential` rung,
        # and while the sentinel's speculation_thrash hold is active.
        if (
            self.config.speculative_dispatch
            and len(groups) >= 2
            and not self.forced_sync
            and self.ladder.rung < RUNG_SEQUENTIAL
            and (
                self.observer is None
                or self.observer.speculation_ok(profile)
            )
        ):
            self._schedule_profile_multi_spec(
                profile, groups, stats, t0, t_batch, t_batch_rec,
                builds_before, nodes, existing, kw,
            )
            return

        if inc:
            rows, spec, reason = self._encode_groups_packed(
                profile, encoder, groups, nodes, existing, kw
            )
            if rows is None:
                self._mc_fall_back(profile, groups, stats, t0, reason)
                return
            snaps = None
        else:
            snaps = []
            lens0 = None
            ci0 = encoder._cycle_index
            for _t_enq, g in groups:
                snaps.append(encoder.encode(nodes, g, existing, **kw))
                if lens0 is None:
                    # row 0's tables are the whole batch's stable side
                    # (_stable_state below reads wbufs[0]/bbufs[0]), so
                    # the growth watermark starts AFTER its encode —
                    # anything a later group interns past this point is
                    # invisible to the tables every inner cycle reads
                    lens0 = encoder._table_lens()
                reason = multicycle_unsupported_reason(snaps[-1])
                if reason is not None:
                    self._mc_fall_back(
                        profile, groups, stats, t0, reason
                    )
                    return
            specs = [packing.make_spec(s) for s in snaps]
            if (
                encoder._table_lens() != lens0
                or any(sp.key() != specs[0].key() for sp in specs[1:])
            ):
                # a later group grew an interning structure — either
                # past row 0's padded regime (spec keys diverge) or
                # WITHIN the padding (keys still match, but row 0's
                # stable tables lack the new entries and a later row's
                # reference to them would dangle): re-encode the whole
                # batch once against the now-grown (grow-only) tables
                # so every row shares the final spec AND row 0 carries
                # the full tables. The retry is a host-side do-over of
                # the SAME logical cycles: rewind the sampling rotation
                # so each group re-stamps the cycle_index its first
                # encode used (otherwise the retry would skew the
                # rotation vs a batch that needed only one pass)
                encoder._cycle_index = ci0
                snaps = [
                    encoder.encode(nodes, g, existing, **kw)
                    for _t_enq, g in groups
                ]
                specs = [packing.make_spec(s) for s in snaps]
                if any(sp.key() != specs[0].key() for sp in specs[1:]):
                    # cannot happen with grow-only tables; refuse to
                    # guess
                    self._mc_fall_back(profile, groups, stats, t0, None)
                    return
            spec = specs[0]
        (
            _pcycle, ppreempt, stable_fn, _keeper, _diag, _ek, pipe,
        ) = self._packed_fns(spec, profile)
        mfn, mdiag, mcont = self._mc_programs(spec, profile)
        pipe.multi_fn = mfn
        pipe.multi_diag_fn = mdiag
        pipe.multi_cont_fn = mcont

        n = len(groups)
        if inc:
            wbufs, bbufs = self._pack_stack_rows(rows, spec)
        else:
            wbufs, bbufs = self._pack_stack(snaps, spec)
        batch_pods = [p for _t_enq, g in groups for p in g]
        try:
            stable = self._stable_state(
                spec, stable_fn, wbufs[0], bbufs[0], encoder
            )
        except Exception as e:
            self._cycle_failed(profile, batch_pods, e, stats, t0, None)
            return
        t_encode = self._now()
        self.metrics.cycle_duration.labels(phase="encode").observe(
            t_encode - t_batch
        )
        if inc:
            self._stamp_finalize(
                profile, t_encode - t_batch, pods=batch_pods
            )
        pipe.forced_sync = (
            self.forced_sync or self.ladder.rung >= RUNG_FORCED_SYNC
        )
        pipe.dispatch_deadline_s = self._dispatch_deadline_s
        pipe.note_encode(t_encode - t_batch)
        # a failed batch dispatch consumes the WHOLE batch before any
        # bind: every group's pods requeue (the caller's
        # retire_in_flight after this return drops only pods the
        # requeue did not re-track)
        try:
            handle = pipe.dispatch_multi(
                wbufs, bbufs, stable, n, device_put=False
            )
        except Exception as e:
            self._cycle_failed(profile, batch_pods, e, stats, t0, None)
            return
        self.metrics.multicycle_batch.observe(n)
        applied, exc = self._apply_mc_rows(
            profile, handle, groups, spec, encoder, stats, t0, t_batch,
            t_batch_rec, nodes, existing, ppreempt, builds_before,
            batch_n=n, stamp_first_bind=True, stamp_compile=True,
        )
        self.metrics.multicycle_cycles.inc(applied)
        if exc is not None:
            # a mid-stream fetch failure: groups already applied are
            # bound and folded (exactly as sequential dispatches would
            # be); only the unapplied tail requeues through the ladder
            rest = [p for _t_enq, g in groups[applied:] for p in g]
            self._cycle_failed(profile, rest, exc, stats, t0, None)
            return
        self._maybe_speculate(profile, spec)

    def _pack_stack(self, snaps, spec):
        """Stack packed snapshot rows into the [K, W]/[K, B] multi-
        cycle arenas (zero-padded past the real rows) and device_put
        them unless K8S_TPU_NO_DEVICE_PUT=1 — the one upload
        convention every multi-cycle dispatch shape (combined batch,
        depth-2 row 0, speculative continuation) shares."""
        import os as _os

        from ..models import packing

        wbufs = np.zeros((self._mc_k, spec.n_words), np.uint32)
        bbufs = np.zeros((self._mc_k, spec.n_bytes), np.uint8)
        for i, s in enumerate(snaps):
            wbufs[i], bbufs[i] = packing.pack(s, spec)
        if _os.environ.get("K8S_TPU_NO_DEVICE_PUT") != "1":
            import jax as _jax

            wbufs = _jax.device_put(wbufs)
            bbufs = _jax.device_put(bbufs)
        return wbufs, bbufs

    def _encode_groups_packed(
        self, profile, encoder, groups, nodes, existing, kw
    ):
        """Encode a flush's groups through the packed delta arena
        (incrementalEncode): each group folds via encode_packed —
        staged ingest rows make it an O(dirty) apply — and its
        wbuf/bbuf row is copied out immediately, before the next
        group's encode rewrites the arena in place. When a later group
        grows an interning dimension, the growing group full-encodes
        against the grown tables and ONE delta re-encode pass re-rows
        the earlier groups (delta_hits, not a second round of full
        encodes — ingest already grew the tables before the flush, so
        the whole-batch double re-encode disappears). Returns
        (rows, spec, None) on success, (None, None, reason|None) to
        fall back sequential."""
        from .cycle import multicycle_unsupported_reason

        mut = frozenset(self._nominated_mut[profile])

        def one_pass():
            rows, specs = [], []
            lens0 = None
            for _t_enq, g in groups:
                f = encoder.encode_packed(
                    nodes, g, existing, mutated_ids=mut, **kw
                )
                if lens0 is None:
                    # growth watermark starts after row 0's encode:
                    # its tables are the batch's stable side, so later
                    # interning (even within the padded regime — spec
                    # keys unchanged) leaves dangling row references
                    lens0 = encoder._table_lens()
                reason = multicycle_unsupported_reason(f.snap)
                if reason is not None:
                    return None, None, reason, lens0
                rows.append((f.wbuf.copy(), f.bbuf.copy()))
                specs.append(f.spec)
            return rows, specs, None, lens0

        ci0 = encoder._cycle_index
        rows, specs, reason, lens0 = one_pass()
        if rows is None:
            return None, None, reason
        if (
            encoder._table_lens() != lens0
            or any(sp.key() != specs[0].key() for sp in specs[1:])
        ):
            # host-side do-over of the same logical cycles: rewind the
            # sampling rotation so the retry stamps the same
            # cycle_index values as the first pass
            encoder._cycle_index = ci0
            rows, specs, reason, lens0 = one_pass()
            if rows is None:
                return None, None, reason
            if (
                encoder._table_lens() != lens0
                or any(sp.key() != specs[0].key() for sp in specs[1:])
            ):
                # cannot happen with grow-only tables; refuse to guess
                return None, None, None
        self._nominated_mut[profile].clear()
        return rows, specs[0], None

    def _pack_stack_rows(self, rows, spec):
        """_pack_stack for already-packed arena rows (the
        incrementalEncode flush path): stack the copied wbuf/bbuf rows
        into the [K, W]/[K, B] multi-cycle arenas and device_put them
        under the same convention."""
        import os as _os

        wbufs = np.zeros((self._mc_k, spec.n_words), np.uint32)
        bbufs = np.zeros((self._mc_k, spec.n_bytes), np.uint8)
        for i, (wr, br) in enumerate(rows):
            wbufs[i] = wr
            bbufs[i] = br
        if _os.environ.get("K8S_TPU_NO_DEVICE_PUT") != "1":
            import jax as _jax

            wbufs = _jax.device_put(wbufs)
            bbufs = _jax.device_put(bbufs)
        return wbufs, bbufs

    def _stamp_finalize(
        self, profile: str, fin_s: float, pods=(),
    ) -> None:
        """Observe the flush's finalize window (encode_finalize
        histogram) and park the ingest/finalize phase stamps for the
        batch's inner record 0 (_apply_mc_row picks them up)."""
        fin_s = max(fin_s, 0.0)
        ing_s = self._ingest_s.pop(profile, 0.0)
        self.metrics.encode_finalize.observe(fin_s)
        self._flush_phases[profile] = {
            "encode_finalize_ms": fin_s * 1e3,
            "encode_ingest_ms": ing_s * 1e3,
        }
        if _spans.ARMED and pods:
            # flush.finalize: the O(dirty) flush apply this batch paid
            # (scheduler-clock duration, recorder-clock anchor)
            t1 = _spans.now()
            for p in pods:
                c = _spans.ctx_for(p.uid)
                if c is not None:
                    _spans.record_span(
                        "flush.finalize", c, t1 - fin_s, t1,
                        uid=p.uid,
                    )

    def _mc_fall_back(
        self, profile: str, groups, stats: CycleStats, t0: float,
        reason: "str | None",
    ) -> None:
        """Dispatch `groups` as sequential single-cycle dispatches
        because the batch left the multi-cycle exactness envelope
        (`reason`), pinning sticky capability reasons out of batching
        for the process lifetime (host_ports stays per-snapshot)."""
        log = logging.getLogger(__name__)
        if reason == "host_ports":
            # per-SNAPSHOT reason, not a sticky capability: only a
            # PENDING pod that requests a port leaves the envelope
            # (cycle.multicycle_unsupported_reason), so a later
            # port-free batch is exact again — fall back for THIS
            # batch without pinning the profile
            log.info(
                "multi-cycle batch for profile %r fell back to "
                "sequential dispatches: pending set carries host "
                "ports (batching resumes on port-free batches)",
                profile,
            )
        elif reason is not None and profile not in self._mc_off:
            # sticky encoder capability flags (affinity / topology
            # spread / volumes / extender) are grow-only: once a
            # profile's workload shows them, it never re-enters
            self._mc_off[profile] = reason
            log.warning(
                "multi-cycle serving disabled for profile %r: "
                "workload left the exactness envelope (%s); "
                "falling back to sequential single-cycle "
                "dispatches", profile, reason,
            )
        for _t_enq, g in groups:
            self._schedule_profile(profile, g, stats, t0)

    @staticmethod
    def _fold_digest(
        scheduled: int, unschedulable: int, bind_errors: int,
        victims: int,
    ) -> tuple:
        """Digest of one host fold's observable cache effects — the
        part of the post-fold state a speculative continuation batch
        conditioned on. The speculation's PREDICATE is this digest
        computed from the predecessor's device decisions (every winner
        binds, nothing else changes: zero bind errors, zero
        evictions); the fold's ACTUAL digest is computed from what the
        apply loop really did. Equal digests mean the cache mutated
        exactly as the speculative encode+carry assumed, so adoption
        is bit-identical to a sequential re-dispatch; anything else
        (a bind error, a host-plugin veto, a preemption eviction)
        abandons. A named tuple of the four counts, not a hash: on an
        abandon the log must say WHICH count diverged — that is the
        datum an operator debugging speculation_thrash needs."""
        return (
            ("scheduled", scheduled),
            ("unschedulable", unschedulable),
            ("bind_errors", bind_errors),
            ("victims", victims),
        )

    def _apply_mc_rows(
        self,
        profile: str,
        handle,
        group_slice,
        spec,
        encoder,
        stats: CycleStats,
        t0: float,
        t_batch: float,
        t_batch_rec: float,
        nodes,
        existing,
        ppreempt,
        builds_before: int,
        batch_n: int,
        stamp_first_bind: bool = False,
        stamp_compile: bool = False,
        resolve_after_first=None,
    ) -> "tuple[int, BaseException | None]":
        """STREAMED apply of one dispatched multi-cycle batch: fetch
        decision row i (`MultiCycleHandle.decisions_row`), apply group
        i through `_apply_phase`, commit its flight record — so inner
        cycle i's winners bind while rows i+1… (and, under depth-2
        speculation, the NEXT batch) are still on device, instead of
        blocking on the whole stacked fetch.

        `group_slice` is this handle's `[(t_enq, pods), …]` in row
        order. `resolve_after_first(a_row, before)` — the speculation
        predicate hook — runs after group 0's apply and returns the
        speculation tag for its record. Returns `(applied, exc)`:
        `applied` groups were fully applied; `exc` is the fetch
        failure that stopped the walk (None when every row landed —
        the caller requeues the unapplied tail). Rows the device loop
        never executed (early exit on a non-empty group: a driver
        bug) requeue loudly here with `MultiCycleUnran`."""
        fr = self.flight
        log = logging.getLogger(__name__)
        framework = self.frameworks[profile]
        pipe = handle._pipe
        st: dict = {}
        device_win_s = 0.0
        total_attempted = sum(len(g) for _t, g in group_slice) or 1
        applied = 0
        exc: "BaseException | None" = None
        for gi, (t_enq, pending) in enumerate(group_slice):
            try:
                a_full, _u_full, gd_full, att_full = (
                    handle.decisions_row(gi)
                )
            except Exception as e:  # schedlint: disable=RB001 -- not swallowed: decisions_row already attributed it (note_fetch_failure: metric + events ring) and the caller routes it through _cycle_failed's ladder step + requeue
                exc = e
                break
            if gi == 0:
                # the dispatch's stage report as of its first landed
                # row: batch-wide marks (encode/dispatch/decision
                # fetch) come from here and land only on record 0
                st = pipe.stage_report()
                device_win_s = max(
                    st.get("t_decision_end", 0.0)
                    - st.get("t_dispatch_end", 0.0),
                    0.0,
                )
                self.metrics.cycle_duration.labels(
                    phase="device"
                ).observe(device_win_s)
            if pending and not att_full[: len(pending)].any():
                # drain early-exit cannot fire on non-empty groups, so
                # an unran row is a driver bug: stop and requeue below
                break
            rec = fr.start(profile) if fr is not None else None
            _before = (
                stats.scheduled, stats.unschedulable, stats.bind_errors,
                stats.preemptors, stats.victims,
            )
            if rec is not None:
                # the record's window opens at the batch flush, not at
                # this inner cycle's apply: its `total` is the latency
                # the inner cycle's pods actually experienced
                rec.t_start = t_batch_rec
                rec.mark("encode_start", t_batch_rec)
            try:
                self._apply_mc_row(
                    profile, handle, gi, pending, a_full, gd_full,
                    spec, encoder, stats, t0, t_batch, t_batch_rec,
                    nodes, existing, ppreempt, builds_before, batch_n,
                    stamp_first_bind, stamp_compile,
                    resolve_after_first, rec, st, device_win_s,
                    total_attempted, t_enq, _before,
                )
            except Exception:  # schedlint: disable=RB001 -- not swallowed: the guard-release is the recovery (old stacked-fetch parity); the error re-raises to the cycle driver with its story intact
                # a NON-fetch failure mid-apply (a deferred diagnosis/
                # preemption force, a host-plugin bug): the stacked
                # fetch of the old path had already marked the handle
                # consumed before the apply loop, so the ordering guard
                # could never be left held — restore that property
                # before the error reaches the cycle driver, or one
                # apply-path exception would wedge the pipeline forever
                handle.fetched = True
                handle.release()
                pipe._note_inflight()
                raise
            applied += 1
        if exc is None and applied < len(group_slice):
            log.error(
                "multi-cycle dispatch ran %d of %d inner cycles; "
                "requeueing the unran groups", applied,
                len(group_slice),
            )
            # release the guard: the unran rows will never be fetched
            # (a distinct event name keeps the recovery honest — these
            # pods never reached a bind attempt; bind_errors still
            # counts them, the closest CycleStats bucket for "cycle
            # failed through no fault of the pod")
            handle.fetched = True
            handle.release()
            pipe._note_inflight()
            for _t_enq, g in group_slice[applied:]:
                for pod in g:
                    self.queue.requeue_backoff(
                        pod, event="MultiCycleUnran"
                    )
                    stats.bind_errors += 1
        return applied, exc

    def _apply_mc_row(
        self, profile, handle, gi, pending, a_full, gd_full, spec,
        encoder, stats, t0, t_batch, t_batch_rec, nodes, existing,
        ppreempt, builds_before, batch_n, stamp_first_bind,
        stamp_compile, resolve_after_first, rec, st, device_win_s,
        total_attempted, t_enq, before,
    ) -> None:
        """One inner cycle's apply + record commit (the body of
        _apply_mc_rows' walk, split out so its guard-release failure
        handling stays readable)."""
        framework = self.frameworks[profile]
        a_i = a_full[: len(pending)]
        gd_i = gd_full[: len(pending)]
        profile_gang_dropped = int(gd_i.sum())
        stats.gang_dropped += profile_gang_dropped
        self.metrics.decisions.inc(len(pending) * len(nodes))

        if (a_i < 0).any():
            handle.dispatch_diagnosis(gi)
        _rej_box: list = []

        def reject_counts_fn(
            gi=gi, pending=pending, _rej_box=_rej_box
        ):
            # ONE force of inner cycle gi's [P, F] attribution matrix
            if not _rej_box:
                _rej_box.append(
                    handle.reject_counts_matrix(gi, len(pending))
                )
            return _rej_box[0]

        pre_handle = None
        if ppreempt is not None and (a_i < 0).any():
            self.metrics.preemption_attempts.inc()
            pre_handle = handle.dispatch_preemption(gi)

        def force_pre(pre_handle=pre_handle, pending=pending):
            if pre_handle is None:
                return None, None
            return (
                np.asarray(pre_handle.nominated)[: len(pending)],
                np.asarray(pre_handle.victims)[: len(existing)],
            )

        self._apply_phase(
            profile, framework, pending, nodes, existing, a_i,
            gd_i, {}, reject_counts_fn, force_pre,
            stats, t0, rec, self._now(),
        )
        speculation = ""
        if gi == 0 and resolve_after_first is not None:
            # the speculation predicate: group 0's fold just landed —
            # adopt or abandon the in-flight continuation before any
            # record of this batch publishes
            speculation = resolve_after_first(a_i, before)

        if rec is not None:
            # batched decomposition (observe.PHASES): how long this
            # group waited for the batch to fill, and its share of
            # the batch's device window apportioned by attempted-pod
            # counts (no clock runs under jit). multi_cycle_k marks
            # this record as an inner cycle of an n-cycle batch —
            # the observer reads it to excuse the full (non-delta)
            # per-group encodes from fold_miss
            extra_phases: dict = {
                "batch_wait_ms": max(t_batch - t_enq, 0.0) * 1e3,
                "device_share_ms": (
                    device_win_s * len(pending)
                    / total_attempted * 1e3
                ),
            }
            extra_marks: dict = {}
            extra_counts: dict = {"multi_cycle_k": batch_n}
            if gi == 0:
                # incrementalEncode flush stamps (encode_ingest /
                # encode_finalize): batch-wide, so they land only on
                # the dispatch's record — same rule as the pipeline
                # marks below
                extra_phases.update(self._flush_phases.pop(profile, {}))
            if (
                gi == 0 and stamp_first_bind
                and "t_first_decision" in st
                and t_batch_rec
            ):
                # streamed-fetch headline: batch flush -> the first
                # decision row landed (both on the recorder clock)
                extra_phases["first_bind_ms"] = max(
                    st["t_first_decision"] - t_batch_rec, 0.0
                ) * 1e3
            dl = handle.diag_lag.get(gi)
            if dl is not None:
                lag_s, t_done = dl
                extra_phases["diag_lag_ms"] = lag_s * 1e3
                extra_marks["diag_done"] = t_done
                self.metrics.diag_lag.observe(lag_s)
            compile_source = ""
            if (
                gi == 0 and stamp_compile
                and self._packed_builds > builds_before
            ):
                extra_phases["compile_ms"] = (
                    self._last_build_s * 1e3
                )
                extra_counts["regime_flip"] = 1
                compile_source = self._last_compile_source
            # batch-wide pipeline marks/phases (encode, dispatch,
            # device window, decision fetch) land ONLY on inner
            # record 0 — the one representing the dispatch. Copying
            # them onto all K records would feed the streaming
            # phase histograms K observations of ONE batch window
            # (~K-fold inflated attribution) and let a single slow
            # batch raise K duplicate stall anomalies; records i>0
            # carry the apportioned decomposition instead
            # (device_share/batch_wait), same spirit as zeroing
            # their fetch_bytes
            st_i = st if gi == 0 else {"slot": st.get("slot", -1)}
            # armed-only: this inner cycle's streamed decision-row
            # window (pipeline.decisions_row stamps it per row) — the
            # decision.row span override for records of a batch
            row_window = None
            if _spans.ARMED:
                row_window = dict(
                    (ri, (rt0, rt1))
                    for ri, rt0, rt1 in st.get("decision_rows", ())
                ).get(gi)
            self._commit_record(
                rec, st_i, spec, encoder, pending, nodes, stats,
                before, profile_gang_dropped,
                fetch_bytes=(
                    int(st.get("fetch_bytes", 0)) if gi == 0 else 0
                ),
                extra_phases=extra_phases,
                extra_marks=extra_marks,
                extra_counts=extra_counts,
                compile_source=compile_source,
                speculation=speculation,
                row_window=row_window,
            )

    def _schedule_profile_multi_spec(
        self,
        profile: str,
        groups: "list[tuple[float, list[Pod]]]",
        stats: CycleStats,
        t0: float,
        t_batch: float,
        t_batch_rec: float,
        builds_before: int,
        nodes,
        existing,
        kw: dict,
    ) -> None:
        """The depth-2 speculative split of one flushed batch
        (ROADMAP item 2 / ISSUE 13 tentpole): batch A = row 0 alone,
        batch B = the remaining rows, dispatched SPECULATIVELY against
        A's predicted post-fold state while A is still on device.

        Timeline (device never idles, first bind never waits K
        cycles):

            encode row 0 -> dispatch A (1 inner cycle)
            encode rows 1..n-1          | A on device
            dispatch B (carry0 = A's    |
              device-resident carry)    |
            fetch A row 0, bind, fold   | B on device
            predicate digest match?     |
              yes -> adopt B: stream B's rows, apply (zero added
                     latency — B has been on device the whole time)
              no  -> abandon B, re-dispatch rows 1..n-1 against the
                     TRUE post-fold state (correctness never rides
                     the speculation, only latency does)

        The predicate (`_fold_digest`) covers exactly what B's encode
        + device-carry assumed about A's fold: every device winner
        binds, no bind errors, no host-plugin vetoes, no preemption
        evictions. B's rows were encoded against the same pre-batch
        cache state the combined [A;B] batch would use and chained
        through the carry_in continuation program, so adoption is
        bit-identical to the combined batch — and, inside the
        envelope, to sequential dispatches with host folding
        (tests/test_speculative.py asserts all three)."""
        from ..models import packing
        from .cycle import multicycle_unsupported_reason

        log = logging.getLogger(__name__)
        encoder = self._encoders[profile]
        n = len(groups)
        rest_groups = groups[1:]
        batch_pods = [p for _t_enq, g in groups for p in g]

        inc = self.config.incremental_encode
        mut = frozenset(self._nominated_mut[profile]) if inc else None
        if inc:
            f0 = encoder.encode_packed(
                nodes, groups[0][1], existing, mutated_ids=mut, **kw
            )
            snap0 = f0.snap
        else:
            snap0 = encoder.encode(nodes, groups[0][1], existing, **kw)
        reason = multicycle_unsupported_reason(snap0)
        if reason is not None:
            self._mc_fall_back(profile, groups, stats, t0, reason)
            return
        # growth watermark: A's stable side is row 0's tables; if B's
        # encodes below intern anything new — even within the padded
        # regime — B's rows would reference entries A's tables lack
        lens0 = encoder._table_lens()
        spec = f0.spec if inc else packing.make_spec(snap0)
        (
            _pcycle, ppreempt, stable_fn, _keeper, _diag, _ek, pipe,
        ) = self._packed_fns(spec, profile)
        mfn, mdiag, mcont = self._mc_programs(spec, profile)
        pipe.multi_fn = mfn
        pipe.multi_diag_fn = mdiag
        pipe.multi_cont_fn = mcont

        if inc:
            # the arena is rewritten by B's encodes below while A is
            # still on device: stack a copy of row 0 now
            wa, ba = self._pack_stack_rows([(f0.wbuf, f0.bbuf)], spec)
        else:
            wa, ba = self._pack_stack([snap0], spec)
        try:
            stable = self._stable_state(
                spec, stable_fn, wa[0], ba[0], encoder
            )
        except Exception as e:
            self._cycle_failed(profile, batch_pods, e, stats, t0, None)
            return
        t_encode = self._now()
        self.metrics.cycle_duration.labels(phase="encode").observe(
            t_encode - t_batch
        )
        # the speculative gate already excluded forcedSync and the
        # degraded rungs; refresh the pipeline's knobs regardless
        pipe.forced_sync = False
        pipe.dispatch_deadline_s = self._dispatch_deadline_s
        pipe.note_encode(t_encode - t_batch)
        try:
            handle_a = pipe.dispatch_multi(
                wa, ba, stable, 1, device_put=False
            )
        except Exception as e:
            self._cycle_failed(profile, batch_pods, e, stats, t0, None)
            return

        # rows 1..n-1 encode in A's dispatch shadow — the host work
        # depth-2 hides behind device time (effective cycle tends to
        # max(device_ms, encode_ms) instead of their sum)
        t_enc_b0 = self._now()
        snaps_b = []
        rows_b = []
        specs_b = []
        bad_reason: "str | None" = None
        for _t_enq, g in rest_groups:
            if inc:
                fb_ = encoder.encode_packed(
                    nodes, g, existing, mutated_ids=mut, **kw
                )
                s = fb_.snap
            else:
                s = encoder.encode(nodes, g, existing, **kw)
            bad_reason = multicycle_unsupported_reason(s)
            if bad_reason is not None:
                break
            if inc:
                rows_b.append((fb_.wbuf.copy(), fb_.bbuf.copy()))
                specs_b.append(fb_.spec)
            else:
                snaps_b.append(s)
        if inc:
            if bad_reason is None:
                # every buffered group folded with `mut` in scope; an
                # incomplete pass keeps the set so the fall-back
                # encodes still rewrite the mutated slots
                self._nominated_mut[profile].clear()
            self._stamp_finalize(
                profile,
                (t_encode - t_batch) + (self._now() - t_enc_b0),
                pods=batch_pods,
            )
        handle_b = None
        if bad_reason is None:
            if encoder._table_lens() != lens0 or any(
                (specs_b[j] if inc else packing.make_spec(s)).key()
                != spec.key()
                for j, s in enumerate(specs_b if inc else snaps_b)
            ):
                # a later group grew an interning structure — past row
                # 0's regime (carry shapes no longer line up) or within
                # its padding (B's rows reference table entries A's
                # stable side lacks) — so B cannot chain: it
                # re-dispatches after A's fold instead (counted as
                # speculation="none": nothing was ever speculated)
                log.info(
                    "speculative batch for profile %r skipped: rows "
                    "1..%d grew the interning tables past row 0's",
                    profile, n - 1,
                )
            else:
                if inc:
                    wb, bb = self._pack_stack_rows(rows_b, spec)
                else:
                    wb, bb = self._pack_stack(snaps_b, spec)
                pipe.note_encode(self._now() - t_enc_b0)
                try:
                    handle_b = pipe.dispatch_multi(
                        wb, bb, stable, n - 1, device_put=False,
                        carry0=(
                            handle_a.result.carry_node_requested,
                            handle_a.result.carry_gplaced,
                        ),
                        speculative=True,
                    )
                except Exception as e:
                    # the speculation itself failing must never fail
                    # the batch: B simply re-dispatches sequentially
                    # after A's fold
                    log.warning(
                        "speculative dispatch failed for profile %r "
                        "(%s); re-dispatching sequentially", profile, e,
                    )
                    handle_b = None

        outcome: dict = {}

        def resolve(a_row, before):
            # predicted fold: every device winner binds, nothing else
            # mutates the cache — vs what the apply loop actually did
            wins = int((a_row >= 0).sum())
            predicted = self._fold_digest(
                wins, len(a_row) - wins, 0, 0
            )
            sb, ub, bb_, _pb, vb = before
            actual = self._fold_digest(
                stats.scheduled - sb,
                stats.unschedulable - ub,
                stats.bind_errors - bb_,
                stats.victims - vb,
            )
            outcome["predicted"] = predicted
            outcome["actual"] = actual
            if handle_b is None:
                outcome["tag"] = "none"
            elif actual == predicted:
                pipe.adopt_speculative()
                outcome["tag"] = "adopted"
            else:
                pipe.abandon_speculative()
                outcome["tag"] = "abandoned"
            return outcome["tag"]

        self.metrics.multicycle_batch.observe(n)
        try:
            applied_a, exc_a = self._apply_mc_rows(
                profile, handle_a, groups[:1], spec, encoder, stats,
                t0, t_batch, t_batch_rec, nodes, existing, ppreempt,
                builds_before, batch_n=n, stamp_first_bind=True,
                stamp_compile=True, resolve_after_first=resolve,
            )
        except BaseException:  # schedlint: disable=RB001 -- not swallowed: purely a leak guard (the speculation slot must not outlive the batch) — the original error re-raises with its story intact
            # a non-fetch apply failure escaped with the speculation
            # possibly unresolved: free its slot before the error
            # reaches the cycle driver (no-op if already resolved)
            pipe.abandon_speculative()
            raise
        if exc_a is not None:
            # A's fetch failed with the speculation (if any) still in
            # flight: abandon it so its arena slot cannot leak, then
            # consume the whole batch through the ladder — nothing was
            # bound, every pod requeues
            pipe.abandon_speculative()
            self._cycle_failed(
                profile, batch_pods, exc_a, stats, t0, None
            )
            return
        if applied_a == 0:
            # row 0 never executed (driver bug; A's group was requeued
            # by _apply_mc_rows) — the speculation conditioned on a
            # fold that never happened
            pipe.abandon_speculative()
            for _t_enq, g in rest_groups:
                for pod in g:
                    self.queue.requeue_backoff(
                        pod, event="MultiCycleUnran"
                    )
                    stats.bind_errors += 1
            return

        tag = outcome.get("tag", "none")
        if tag == "adopted":
            applied_b, exc_b = self._apply_mc_rows(
                profile, handle_b, rest_groups, spec, encoder, stats,
                t0, t_batch, t_batch_rec, nodes, existing, ppreempt,
                builds_before, batch_n=n,
            )
            self.metrics.multicycle_cycles.inc(applied_a + applied_b)
            if exc_b is not None:
                rest = [
                    p for _t_enq, g in rest_groups[applied_b:]
                    for p in g
                ]
                self._cycle_failed(
                    profile, rest, exc_b, stats, t0, None
                )
                return
        else:
            self.metrics.multicycle_cycles.inc(applied_a)
            if tag == "abandoned":
                pipe.note_redispatch()
                diverged = [
                    f"{name} {pv}->{av}"
                    for (name, pv), (_n2, av) in zip(
                        outcome["predicted"], outcome["actual"]
                    )
                    if pv != av
                ]
                log.info(
                    "speculative batch abandoned for profile %r (host "
                    "fold diverged from the predicate digest: %s); "
                    "re-dispatching %d group(s) against the true "
                    "carry", profile, ", ".join(diverged),
                    len(rest_groups),
                )
            if bad_reason is not None:
                self._mc_fall_back(
                    profile, rest_groups, stats, t0, bad_reason
                )
            elif len(rest_groups) == 1:
                self._schedule_profile(
                    profile, rest_groups[0][1], stats, t0
                )
            else:
                self._schedule_profile_multi(
                    profile, rest_groups, stats, t0
                )
        self._maybe_speculate(profile, spec)

    def _commit_record(
        self,
        rec,
        st: dict,
        spec,
        encoder,
        pending: "list[Pod]",
        nodes,
        stats: CycleStats,
        before: tuple,
        gang_dropped: int,
        fetch_bytes: int,
        extra_phases: "dict | None" = None,
        extra_marks: "dict | None" = None,
        extra_counts: "dict | None" = None,
        compile_source: str = "",
        speculation: str = "",
        row_window: "tuple | None" = None,
    ) -> None:
        """Assemble + commit one cycle flight record (one list store):
        pipeline stage marks/phases, pad-regime signature, queue
        depths, and the per-profile outcome deltas. Shared by the
        single-cycle path and the multi-cycle batch path so a field
        added to one cannot silently go missing from the other; the
        paths differ only through the extra_* parameters (fold_ms /
        compile_ms / post_batch vs batch_wait / device_share /
        multi_cycle_k)."""
        from ..models import packing as _packing
        from .cycle import RESILIENT_STRIKES

        rec.slot = int(st.get("slot", -1))
        rec.forced_sync = bool(self.forced_sync)
        if self.tenant_id:
            rec.tenant = self.tenant_id
        # absolute pipeline marks (same perf_counter clock as the
        # recorder) -> trace lanes; "t_dispatch_start" -> mark
        # "dispatch_start" etc.
        for k, v in st.items():
            if k.startswith("t_"):
                rec.mark(k[2:], v)
        rec.phases.update(
            {
                k: float(v)
                for k, v in st.items()
                if k.endswith("_ms")
            }
        )
        for k, v in (extra_marks or {}).items():
            rec.mark(k, v)
        rec.phases.update(extra_phases or {})
        if self.admission is not None:
            # front door: worst admission-accept -> bind latency among
            # this record's binds (collected by _bind via note_bind);
            # absent when the record bound no front-door pods
            sb_ms = self.admission.take_bind_latency_ms()
            if sb_ms > 0.0:
                rec.phases["submit_bind_ms"] = sb_ms
        # pad-regime signature: core/observe.py diffs consecutive
        # cycles' sigs to attribute recompile dimensions
        rec.sig = _packing.shape_signature(spec)
        if compile_source:
            # regime-flip cycles only: how the (re)build was paid —
            # cold compile, persistent-cache load, or a speculation win
            rec.compile_source = compile_source
        if speculation:
            # depth-2 dispatch speculation outcome (adopted | abandoned
            # | none), one sample per speculation — feeds the
            # observer's speculation_thrash abandon-rate EWMA
            rec.speculation = speculation
        qc = self.queue.pending_counts()
        sb, ub, bb, pb, vb = before
        rec.counts.update(
            pods=len(pending),
            nodes=len(nodes),
            scheduled=stats.scheduled - sb,
            unschedulable=stats.unschedulable - ub,
            bind_errors=stats.bind_errors - bb,
            preemptors=stats.preemptors - pb,
            victims=stats.victims - vb,
            gang_dropped=gang_dropped,
            fetch_bytes=fetch_bytes,
            retry_strikes_total=sum(RESILIENT_STRIKES.values()),
            # monotonic encoder counters: the observer diffs them
            # per profile to classify fold_miss (an unexplained
            # fall off the delta/fold encode path)
            full_encodes=int(encoder.full_encodes),
            delta_hits=int(encoder.delta_hits),
            fold_hits=int(getattr(encoder, "fold_hits", 0)),
            # admission-time incremental encode: dirty slots whose
            # flush-time parse was skipped (a staged ingest row was
            # waiting) — the bench's encode_hidden evidence
            ingest_hits=int(getattr(encoder, "ingest_hits", 0)),
            queue_active=qc.get("active", 0),
            queue_backoff=qc.get("backoff", 0),
            queue_unschedulable=qc.get("unschedulable", 0),
            # current degradation rung (0 = normal): bench config 7 and
            # soak_chaos count records with rung > 0 as degraded cycles
            rung=self.ladder.rung,
            # multi-chip serving: mesh width this cycle dispatched over
            # and the regime's probed per-cycle collective payload
            # (0 = single device / no AOT probe yet)
            n_devices=self.n_devices,
            collective_payload_bytes=self._collective_payload.get(
                rec.profile, 0
            ),
            **(extra_counts or {}),
        )
        if _spans.ARMED:
            self._emit_cycle_spans(rec, pending, speculation, row_window)
        self.flight.commit(rec)

    def _emit_cycle_spans(
        self, rec, pending, speculation: str,
        row_window: "tuple | None",
    ) -> None:
        """Armed-only: emit this record's serve-side spans for every
        sampled pod it carried and stamp the record's `trace_ids`
        exemplar join. All windows come from the record's own marks
        (recorder perf_counter clock — the same base the span ring
        uses), so span slices and cycle lanes rebase identically;
        `row_window` overrides the decision window for an inner cycle
        of a multi-cycle batch (its streamed row, not the batch-wide
        fetch envelope)."""
        ctxs = []
        for p in pending:
            c = _spans.ctx_for(p.uid)
            if c is not None:
                ctxs.append((p.uid, c))
        if not ctxs:
            return
        m = rec.marks
        d0, d1 = m.get("dispatch_start"), m.get("dispatch_end")
        r0, r1 = row_window or (
            m.get("decision_start"), m.get("decision_end")
        )
        a0, a1 = m.get("apply_start"), m.get("winners_end")
        for uid, c in ctxs:
            if d0 is not None and d1 is not None:
                _spans.record_span(
                    "dispatch", c, d0, d1, uid=uid, seq=rec.seq,
                )
            if speculation in ("adopted", "abandoned"):
                # the speculative continuation this batch resolved:
                # anchor it on the dispatch window (the speculation
                # rode that dispatch's shadow)
                _spans.record_span(
                    "dispatch.speculative", c,
                    d0 if d0 is not None else rec.t_start,
                    d1 if d1 is not None else rec.t_start,
                    uid=uid, seq=rec.seq, outcome=speculation,
                )
            if r0 is not None and r1 is not None:
                _spans.record_span(
                    "decision.row", c, r0, r1, uid=uid, seq=rec.seq,
                )
            if a0 is not None and a1 is not None:
                _spans.record_span(
                    "apply.fold", c, a0, a1, uid=uid, seq=rec.seq,
                )
        rec.trace_ids = tuple(
            dict.fromkeys(c.trace_id for _u, c in ctxs)
        )

    def _cycle_failed(
        self,
        profile: str,
        pending: "list[Pod]",
        e: BaseException,
        stats: CycleStats,
        t0: float,
        rec,
    ) -> None:
        """A dispatch/fetch failure consumed the cycle BEFORE any bind:
        classify it, step the degradation ladder, requeue every pod with
        backoff, and commit an aborted flight record — the serve loop
        then continues at the new rung instead of dying (or, for a hung
        tunnel without the watchdog, hanging forever)."""
        from .pipeline import DispatchDeadlineExceeded

        cls = (
            "deadline" if isinstance(e, DispatchDeadlineExceeded)
            else classify_failure(e)
        )
        self._cycle_fault = True
        seq = rec.seq if rec is not None else -1
        logging.getLogger(__name__).error(
            "cycle dispatch failed for profile %r (%s: %s); stepping "
            "the degradation ladder and requeueing %d pods",
            profile, cls, e, len(pending),
        )
        new_rung = self.ladder.degrade(
            f"{cls}: {str(e)[:200]}", seq=seq
        )
        per_pod_s = (self._now() - t0) / max(len(pending), 1)
        for pod in pending:
            self.queue.requeue_backoff(pod, event="DispatchFailed")
            stats.bind_errors += 1
            if self.flight is not None:
                self.flight.pod_event(
                    pod.uid, pod.name, "DispatchFailed", cycle=seq,
                    failure=cls,
                )
            self.metrics.observe_attempt("error", per_pod_s, profile)
        if rec is not None:
            # an aborted record: total is real wall time (the SLO engine
            # must charge a blown deadline), device phases absent (no
            # decision landed, so the stall baselines stay clean)
            rec.counts.update(
                pods=len(pending),
                aborted=1,
                bind_errors=len(pending),
                rung=new_rung,
            )
            self.flight.commit(rec)
        if _blackbox.ARMED and cls == "deadline":
            # a watchdog-aborted dispatch is a black-box trigger: the
            # tunnel just proved it can wedge, so capture the rings
            # NOW — a later kill -9 must still find this bundle
            _blackbox.trigger(
                "watchdog", f"profile={profile} seq={seq} {e}"
            )

    def _on_rung_transition(
        self, old: int, new: int, reason: str
    ) -> None:
        """Apply a rung's side effects (runs outside the ladder lock).
        Rungs `sequential` and `forced_sync` are read at dispatch time;
        only `retrace` (clear+rebuild) and `stateless` (seal for
        failover) act here. A sticky-bottom repeat arrives as
        old == new (the ladder re-fires the hook under continued
        failure): the retrace clear runs again so no executable
        installed since the last clear survives into the next retry."""
        if new >= old and new >= RUNG_RETRACE:
            # the regime-wide clear_cache+retrace recovery: drop every
            # memoized program set (with its jit caches and installed
            # AOT executables) so the next cycle re-traces from scratch.
            # Re-applied on every further down-step — if the fault
            # persisted, a stale executable must not survive into the
            # next rung's retry.
            with self._packed_lock:
                self._packed.clear()
                self._mc_fns.clear()
            self._dev_stable.clear()
        if (
            new >= RUNG_STATELESS
            and old < RUNG_STATELESS
            and self.state is not None
        ):
            # seal-for-failover: a final snapshot + journal close means
            # the standby restores a CLEAN boundary instead of replaying
            # a tail written by a process this degraded; then detach so
            # this process's further mutations stop journaling (it is
            # stateless from here on — the documented journal-death
            # degrade, entered deliberately)
            try:
                self.state.seal()
                self.state.detach()
                logging.getLogger(__name__).warning(
                    "durable state sealed + detached for failover "
                    "(degradation rung 'stateless'): %s", reason,
                )
            except Exception:
                logging.getLogger(__name__).exception(
                    "seal-for-failover failed; continuing stateless "
                    "(journal tail on disk is the fallback)"
                )
            # durability is gone for this process either way (seal
            # succeeded and detached, or the journal died trying):
            # pin the promotion floor so the ladder never reports
            # "normal" while mutations go unjournaled — the standby
            # takeover is the recovery that clears this
            self.ladder.floor = RUNG_STATELESS
        if new >= RUNG_STATELESS and old < RUNG_STATELESS:
            # entering stateless is the "something is very wrong"
            # boundary whether or not durable state was attached:
            # dump the black box while the rings still hold the fault
            if _blackbox.ARMED:
                _blackbox.trigger("stateless", reason)

    def _apply_phase(
        self,
        profile: str,
        framework,
        pending: "list[Pod]",
        nodes,
        existing,
        assignment,
        gang_dropped,
        extender_errors: "dict[int, str]",
        reject_counts_fn,
        force_pre,
        stats: CycleStats,
        t0: float,
        rec,
        t_device: float,
    ) -> None:
        """The host APPLY phase of one cycle: winner bind loop,
        preemption force, loser requeue, victim eviction — everything
        between "decisions in hand" and "flight record assembled".
        Shared verbatim by the single-cycle path (_schedule_profile)
        and the multi-cycle batch path (_schedule_profile_multi), which
        invokes it once per INNER cycle in batch order, so binds,
        journal records, events, and timelines are applied per cycle
        exactly as sequential dispatches would — durability semantics
        do not change across the batch boundary.

        Vectorized fold: winners/losers are classified once with
        numpy, the per-plugin attribution is forced ONCE as a matrix
        (`reject_counts_fn()`), outcome metrics batch per cycle
        (observe_attempts), and every journal emission of the fold
        coalesces into ONE batch record (state.batch() — replays to
        the identical digest as N singles, so the emit-once contract
        holds at batch granularity). Per-pod calls that carry
        semantics — assume, host plugins, bind, events, timelines —
        stay per pod, in slot order, so the event and journal streams
        are bit-identical to the scalar loop's.

        `force_pre()` forces the cycle's preemption program and
        returns `(nominated[:P_real] | None, victims[:E_real] | None)`.
        """
        import contextlib

        fr = self.flight
        filter_names = framework.filter_names
        if rec is not None:
            # bind work starts here: under forced_sync the deferred
            # dispatches above BLOCKED, and the trace's bind slice must
            # not swallow that wait (the diag lane would fake overlap)
            rec.mark("apply_start", fr.now())

        # ---- apply, split-phase: winners bind FIRST (no deferred
        # output can block them), losers are processed after — their
        # inputs (preemption nominations, diagnosis reject counts) were
        # dispatched above and resolve while the bind loop runs ----
        # per-attempt latency is sampled at observation time so it includes
        # binding (upstream attempt duration = algorithm + bind)
        def per_pod_s() -> float:
            return (self._now() - t0) / max(len(pending), 1)

        # per-pod timeline notes (flight recorder): every attempt outcome
        # carries the cycle seq so timelines join back to cycle records
        def _pev(pod, kind: str, **detail) -> None:
            if fr is not None:
                fr.pod_event(
                    pod.uid, pod.name, kind, cycle=rec.seq, **detail
                )
        from ..framework.host import (
            HostPluginRejection,
            run_post_bind,
            run_reserve_permit_prebind,
            run_unreserve,
        )

        a = np.asarray(assignment[: len(pending)])
        win_idx = np.flatnonzero(a >= 0)
        lose_idx = np.flatnonzero(a < 0)
        # ONE journal group-append per cycle: every record the fold
        # emits (assume/bind/requeue/evict) buffers into a single
        # batch frame, flushed (and fsynced by the writer as one
        # payload) when the context exits
        batch_cm = (
            self.state.batch() if self.state is not None
            else contextlib.nullcontext()
        )
        with batch_cm:
            n_bound = 0
            for i in win_idx:
                i = int(i)
                pod = pending[i]
                node_name = nodes[int(a[i])].name
                try:
                    # a per-pod scheduling error (e.g. the uid raced to
                    # bound via an informer echo mid-cycle) must not
                    # kill the loop — upstream continues with the next
                    # pod
                    self.cache.assume(pod, node_name)
                except ValueError:
                    stats.bind_errors += 1
                    _pev(
                        pod, "BindError", node=node_name, stage="assume"
                    )
                    self.metrics.observe_attempt(
                        "error", per_pod_s(), profile
                    )
                    continue
                # Reserve -> Permit -> PreBind host extension points
                try:
                    run_reserve_permit_prebind(
                        self.host_plugins, pod, node_name
                    )
                except HostPluginRejection as rej:
                    self.cache.forget(pod.uid)
                    if rej.point == "PreBind":
                        # transient pre-bind failure: retry with backoff
                        self.queue.requeue_backoff(pod)
                        stats.bind_errors += 1
                        _pev(
                            pod, "BindError", node=node_name,
                            stage="PreBind", plugin=rej.plugin,
                        )
                        self.metrics.observe_attempt(
                            "error", per_pod_s(), profile
                        )
                    else:
                        # Reserve/Permit veto: unschedulable, attributed
                        # to the vetoing host plugin
                        self.events.failed_scheduling(
                            pod,
                            f"{rej.plugin} rejected at {rej.point}: "
                            f"{rej.reason}"
                        )
                        self.queue.requeue_unschedulable(
                            pod, reasons=(rej.plugin,)
                        )
                        stats.unschedulable += 1
                        _pev(
                            pod, "Rejected", node=node_name,
                            stage=rej.point, plugin=rej.plugin,
                        )
                        self.metrics.observe_attempt(
                            "unschedulable", per_pod_s(), profile
                        )
                    continue
                t_bind = self._now()
                try:
                    self._bind(pod, node_name)
                except Exception:
                    run_unreserve(self.host_plugins, pod, node_name)
                    self.cache.forget(pod.uid)
                    self.queue.requeue_backoff(pod)
                    stats.bind_errors += 1
                    _pev(pod, "BindError", node=node_name, stage="bind")
                    self.metrics.observe_attempt(
                        "error", per_pod_s(), profile
                    )
                    continue
                self.metrics.binding_duration.observe(
                    self._now() - t_bind
                )
                self.cache.finish_binding(pod.uid)
                run_post_bind(self.host_plugins, pod, node_name)
                self.events.scheduled(pod, node_name)
                _pev(pod, "Bound", node=node_name)
                stats.scheduled += 1
                self.metrics.pod_scheduling_attempts.observe(
                    self.queue.attempts_of(pod.uid)
                )
                n_bound += 1
            if n_bound:
                # the happy-path outcome batches: one counter inc + one
                # shared latency sample for the cycle's binds (error
                # paths above stay per-pod — rare, and their sample
                # time is the failure moment)
                self.metrics.observe_attempts(
                    "scheduled", per_pod_s(), profile, n_bound
                )

            # losers: force the (overlapped) preemption output now
            t_winners = self._now()
            if rec is not None:
                rec.mark("winners_end", fr.now())
            nominated, victims = force_pre()
            t_post = self._now()
            if rec is not None:
                rec.mark("postfilter_end", fr.now())
            self.metrics.cycle_duration.labels(
                phase="postfilter"
            ).observe(t_post - t_winners)

            rej_mat = None
            n_unsched = 0
            reason_incs: dict[str, int] = {}
            for i in lose_idx:
                i = int(i)
                pod = pending[i]
                if i in extender_errors:
                    # non-ignorable extender failure: retry with backoff
                    # (transient webhook errors must not park the pod)
                    self.queue.requeue_backoff(pod)
                    stats.bind_errors += 1
                    _pev(pod, "BindError", stage="extender")
                    self.metrics.observe_attempt(
                        "error", per_pod_s(), profile
                    )
                    continue
                if nominated is not None and nominated[i] >= 0:
                    pod.nominated_node_name = (
                        nodes[int(nominated[i])].name
                    )
                    _pev(pod, "Nominated", node=pod.nominated_node_name)
                    # in-place mutation: the delta encoder must re-read
                    # this pod's slot next cycle (arena contract)
                    self._nominated_mut[profile].add(id(pod))
                    self.last_nominations.append(
                        (pod, pod.nominated_node_name)
                    )
                    stats.preemptors += 1
                if gang_dropped[i]:
                    reasons = ("Coscheduling",)
                    message = (
                        f"pod group {pod.spec.pod_group!r} did not "
                        "reach minMember; all-or-nothing placement "
                        "rolled back"
                    )
                else:
                    if rej_mat is None:
                        rej_mat = reject_counts_fn()
                    per_plugin = list(zip(filter_names, rej_mat[i]))
                    reasons = tuple(
                        name for name, n in per_plugin if n > 0
                    )
                    message = failed_scheduling_message(
                        len(nodes), per_plugin
                    )
                for r in reasons:
                    reason_incs[r] = reason_incs.get(r, 0) + 1
                _pev(
                    pod, "Unschedulable",
                    plugin=reasons[0] if reasons else "",
                )
                self.events.failed_scheduling(pod, message)
                self.queue.requeue_unschedulable(pod, reasons=reasons)
                stats.unschedulable += 1
                n_unsched += 1
            for r, cnt in reason_incs.items():
                # column-batched attribution: one inc per plugin per
                # cycle instead of one per (pod, plugin)
                self.metrics.unschedulable_reasons.labels(
                    plugin=r, profile=profile
                ).inc(cnt)
            if n_unsched:
                self.metrics.observe_attempts(
                    "unschedulable", per_pod_s(), profile, n_unsched
                )

            if victims is not None and victims.any():
                # victims belong to the preemptor nominated onto their
                # node
                preemptor_by_node = {
                    node: pod.name
                    for pod, node in self.last_nominations
                }
                # armed-only: the preemptor pod (not just its name) by
                # node, so a victim's span joins the PREEMPTOR's trace
                preemptor_pod_by_node = (
                    {
                        node: pod
                        for pod, node in self.last_nominations
                    }
                    if _spans.ARMED else {}
                )
                n_vict = 0
                for e in np.flatnonzero(victims):
                    vpod, vnode = existing[int(e)]
                    t_ev0 = _spans.now() if _spans.ARMED else 0.0
                    self.evictor(vpod, vnode)
                    self.last_evictions.append((vpod, vnode))
                    _pev(
                        vpod, "Evicted", node=vnode,
                        preemptor=preemptor_by_node.get(vnode, ""),
                    )
                    self.events.preempted(
                        vpod, preemptor_by_node.get(vnode, "<pending>")
                    )
                    if _spans.ARMED:
                        pre = preemptor_pod_by_node.get(vnode)
                        c = (
                            _spans.ctx_for(pre.uid)
                            if pre is not None else None
                        )
                        if c is not None:
                            _spans.record_span(
                                "preempt.victim", c, t_ev0,
                                _spans.now(), uid=pre.uid,
                                victim=vpod.uid, node=vnode,
                                seq=rec.seq if rec is not None else -1,
                            )
                    n_vict += 1
                stats.victims += n_vict
                self.metrics.preemption_victims.observe(n_vict)

        # apply = winner bind loop + loser requeue loop (the preemption
        # force between them is the "postfilter" phase)
        self.metrics.cycle_duration.labels(phase="apply").observe(
            (t_winners - t_device) + (self._now() - t_post)
        )


    def _bind(self, pod: Pod, node_name: str) -> None:
        """Bind, delegating to the first bind-verb extender (upstream: an
        extender with a bind verb replaces the default binder)."""
        t_b0 = _spans.now() if _spans.ARMED else 0.0
        for ext in self.extenders:
            if ext.is_binder:
                ext.bind(pod, node_name)
                if self.admission is not None:
                    self.admission.note_bind(pod.uid)
                self._span_bind_confirm(pod, node_name, t_b0)
                return
        self.binder(pod, node_name)
        if self.admission is not None:
            # after the binder: a raising binder is a bind error, and
            # an errored bind must not close the submit->bind window
            self.admission.note_bind(pod.uid)
        self._span_bind_confirm(pod, node_name, t_b0)

    def _span_bind_confirm(
        self, pod: Pod, node_name: str, t_b0: float
    ) -> None:
        """Armed-only: the pod's bind.confirm span — binder call
        through note_bind, the moment its trace's submit->bind window
        closes. A raising binder never reaches here (a bind error is
        not a confirm)."""
        if _spans.ARMED:
            c = _spans.ctx_for(pod.uid)
            if c is not None:
                _spans.record_span(
                    "bind.confirm", c, t_b0, _spans.now(),
                    uid=pod.uid, node=node_name,
                )

    def _update_gauges(self) -> None:
        self.metrics.set_pending(self.queue.pending_counts())
        c = self.cache.counts()
        # upstream cache_size{type="pods"} counts every tracked pod state;
        # assumed_pods is the subset awaiting bind confirmation
        self.metrics.set_cache(
            c.get("nodes", 0),
            c.get("bound", 0) + c.get("assumed", 0),
            c.get("assumed", 0),
        )
        # flight-recorder derived gauges: the continuous overlap story
        # (scheduler_pipeline_overlap_ratio) computed from the recent
        # cycle window instead of separated probe runs
        if self.flight is not None and self.flight.cycles:
            d = self.flight.derived()
            self.metrics.pipeline_overlap.set(d["overlap_ratio"])
        if self.admission is not None:
            # the front door also sets this at submit time; the cycle
            # refresh keeps the gauge falling as the queue drains even
            # when no new submission arrives to re-stamp it
            self.metrics.admission_queue_depth.set(
                self.admission.queue_depth()
            )

    def speculation_ledger(self) -> dict:
        """Aggregate depth-2 speculation ledger: {'adopted',
        'abandoned', 'redispatched'} counts. Read from this
        scheduler's scheduler_speculation_total{outcome} counters, not
        the per-pipeline dicts — a retrace rung (or plain LRU
        eviction) drops regime pipelines along with their ledgers,
        while the metric registry survives every memo clear. Soaks and
        the fuzz differential read this to assert the speculative path
        actually exercised (and abandoned without leaking a slot)."""
        return {
            o: int(
                self.metrics.speculation.labels(outcome=o)._value.get()
            )
            for o in ("adopted", "abandoned", "redispatched")
        }

    def pod_timeline(self, uid: str) -> dict | None:
        """The per-pod scheduling timeline: the flight recorder's pod
        events (queued -> attempts -> bound/evicted) joined with
        whatever is still in the events ring (the shim drains the ring
        per Cycle, so the recorder half is the durable one). Returns
        None for a pod neither side has seen."""
        tl = (
            self.flight.pods.get(uid) if self.flight is not None else None
        )
        ring = self.events.events_for(uid)
        if tl is None and not ring:
            return None
        out = tl or {"uid": uid, "name": "", "events": []}
        # cycle attempts in order: every outcome note carries its cycle
        # seq, which joins back to /debug/flightrecorder records
        attempt_kinds = {
            "Bound", "Unschedulable", "BindError", "Rejected", "Expired",
            "DispatchFailed",
        }
        out["attempts"] = [
            {
                "cycle": e.get("cycle", -1),
                "result": e["kind"],
                **{
                    k: e[k]
                    for k in ("plugin", "node", "stage")
                    if k in e
                },
            }
            for e in out["events"]
            if e["kind"] in attempt_kinds
        ]
        terminal = [
            e for e in out["events"]
            if e["kind"] in ("Bound", "Evicted", "Deleted",
                             "BoundObserved")
        ]
        out["state"] = (
            terminal[-1]["kind"] if terminal
            else ("Unschedulable" if any(
                e["kind"] == "Unschedulable" for e in out["events"]
            ) else "Pending")
        )
        out["ring_events"] = [dataclasses.asdict(e) for e in ring]
        return out

    def profile_cycle(self, repeats: int = 3) -> dict:
        """Sampled per-plugin observability pass (SURVEY.md §5.1): times
        each enabled plugin's kernel in isolation over the CURRENT pending
        set + cluster state (queue is not drained), filling the upstream
        per-plugin/extension-point histograms. Not the hot path."""
        from .profiling import profile_plugins

        pending = list(self.queue.all_pending())
        nodes = self.cache.nodes()
        if not pending or not nodes:
            return {}
        self._encoder.pad_pods = self._encoder.hysteresis_pad(
            "P", _pad(len(pending), self._pad_bucket), len(pending)
        )
        self._encoder.pad_nodes = self._encoder.hysteresis_pad(
            "N", _pad(len(nodes), self._pad_bucket), len(nodes)
        )
        snap = self._encoder.encode(
            nodes,
            pending,
            self.cache.existing_pods(),
            pod_groups=list(self._groups.values()),
            pvcs=list(self._pvcs.values()),
            pvs=list(self._pvs.values()),
            storage_classes=list(self._storage_classes.values()),
        )
        return profile_plugins(self.framework, snap, self.metrics, repeats)

    def run(self, max_cycles: int | None = None,
            idle_sleep: float = 0.01) -> None:
        """The scheduling loop (upstream wait.UntilWithContext(ScheduleOne)).
        Runs until `max_cycles` cycles have executed (None = forever)."""
        cycles = 0
        while max_cycles is None or cycles < max_cycles:
            stats = self.schedule_cycle()
            cycles += 1
            if stats.attempted == 0 and not (
                self._mc_k > 1
                and any(self._mc_groups.values())
            ):
                # buffered groups are waiting on the NEXT pop to
                # detect a paused arrival stream (the flush trigger) —
                # sleeping here would stretch every batch by
                # idle_sleep; a truly idle loop still backs off
                _time.sleep(idle_sleep)
