"""Per-plugin profiling pass (SURVEY.md §5.1 tracing/profiling).

The production cycle fuses every plugin kernel into one XLA program, so
per-plugin latency is not separable there (upstream can time each plugin
because it dispatches callbacks eagerly). This pass re-runs each enabled
plugin's static kernel as its own jitted program, blocked to completion,
and records the upstream per-plugin histograms:

    scheduler_plugin_execution_duration_seconds{plugin,extension_point,...}
    scheduler_framework_extension_point_duration_seconds{extension_point,...}

plus a per-plugin decision-log report (feasible fraction per Filter, score
stats per Score) — the per-plugin mask statistics from SURVEY.md §5.5.

Run it sampled (Scheduler.profile_cycle, or the CLI's --profile-every
knob), never in the hot loop. For kernel-level detail beyond this, wrap any
call in `jax.profiler.trace(log_dir)` and read the trace in TensorBoard or
Perfetto; `trace_cycle` below does that for one full fused cycle.
"""

from __future__ import annotations

import time as _time
from typing import Any

import jax
import numpy as np

from ..framework.interfaces import CycleContext
from ..framework.runtime import Framework
from ..metrics import SchedulerMetrics
from ..models.encoding import ClusterSnapshot


def _time_call(fn, snap, repeats: int = 3) -> tuple[float, Any]:
    """Compile (untimed), then best-of-`repeats` wall time, result blocked."""
    out = fn(snap)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = _time.perf_counter()
        out = fn(snap)
        jax.block_until_ready(out)
        best = min(best, _time.perf_counter() - t0)
    return best, out


def profile_plugins(
    framework: Framework,
    snap: ClusterSnapshot,
    metrics: SchedulerMetrics | None = None,
    repeats: int = 3,
) -> dict[str, dict[str, Any]]:
    """Time each plugin's static kernel in isolation; returns a report
    {plugin_name: {extension_point, seconds, ...stats}} and records the
    per-plugin/per-point histograms when `metrics` is given."""
    report: dict[str, dict[str, Any]] = {}
    point_totals = {"Filter": 0.0, "Score": 0.0}
    valid = (
        np.asarray(snap.pod_valid)[:, None] & np.asarray(snap.node_valid)[None, :]
    )
    n_valid = max(valid.sum(), 1)

    for plugin in framework.filters:
        fn = jax.jit(lambda s, p=plugin: p.static_mask(CycleContext(s)))
        if fn(snap) is None:  # dynamic-only plugin (no static kernel)
            continue
        secs, mask = _time_call(fn, snap, repeats)
        feasible = float((np.asarray(mask) & valid).sum() / n_valid)
        report[f"{plugin.name}/Filter"] = {
            "extension_point": "Filter",
            "seconds": secs,
            "feasible_fraction": feasible,
        }
        point_totals["Filter"] += secs
        if metrics is not None:
            metrics.plugin_duration.labels(
                plugin=plugin.name, extension_point="Filter", status="Success"
            ).observe(secs)

    for plugin, weight in framework.scores:
        fn = jax.jit(lambda s, p=plugin: p.static_score(CycleContext(s)))
        if fn(snap) is None:
            continue
        secs, score = _time_call(fn, snap, repeats)
        sc = np.asarray(score)[valid]
        report[f"{plugin.name}/Score"] = {
            "extension_point": "Score",
            "seconds": secs,
            "weight": weight,
            "score_mean": float(sc.mean()) if sc.size else 0.0,
            "score_max": float(sc.max()) if sc.size else 0.0,
        }
        point_totals["Score"] += secs
        if metrics is not None:
            metrics.plugin_duration.labels(
                plugin=plugin.name, extension_point="Score", status="Success"
            ).observe(secs)

    if metrics is not None:
        for point, total in point_totals.items():
            if total > 0.0:
                metrics.extension_point_duration.labels(
                    extension_point=point, status="Success"
                ).observe(total)
    return report


def trace_cycle(cycle_fn, snap: ClusterSnapshot, log_dir: str):
    """One fused cycle under jax.profiler (TensorBoard/Perfetto trace)."""
    with jax.profiler.trace(log_dir):
        out = cycle_fn(snap)
        jax.block_until_ready(out.assignment)
    return out
