"""Per-plugin profiling pass (SURVEY.md §5.1 tracing/profiling).

The production cycle fuses every plugin kernel into one XLA program, so
per-plugin latency is not separable there (upstream can time each plugin
because it dispatches callbacks eagerly). This pass re-runs each enabled
plugin's static kernel as its own jitted program, blocked to completion,
and records the upstream per-plugin histograms:

    scheduler_plugin_execution_duration_seconds{plugin,extension_point,...}
    scheduler_framework_extension_point_duration_seconds{extension_point,...}

plus a per-plugin decision-log report (feasible fraction per Filter, score
stats per Score) — the per-plugin mask statistics from SURVEY.md §5.5.

Run it sampled (Scheduler.profile_cycle, or the CLI's --profile-every
knob), never in the hot loop. For kernel-level detail beyond this, wrap any
call in `jax.profiler.trace(log_dir)` and read the trace in TensorBoard or
Perfetto; `trace_cycle` below does that for one full fused cycle.
"""

from __future__ import annotations

import time as _time
from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.interfaces import CycleContext
from ..framework.runtime import Framework
from ..metrics import SchedulerMetrics
from ..models.encoding import ClusterSnapshot
from ..ops import argsel


def _time_call(fn, snap, repeats: int = 3) -> tuple[float, Any]:
    """Compile (untimed), then best-of-`repeats` wall time, result blocked."""
    out = fn(snap)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = _time.perf_counter()
        out = fn(snap)
        jax.block_until_ready(out)
        best = min(best, _time.perf_counter() - t0)
    return best, out


def profile_plugins(
    framework: Framework,
    snap: ClusterSnapshot,
    metrics: SchedulerMetrics | None = None,
    repeats: int = 3,
) -> dict[str, dict[str, Any]]:
    """Time each plugin's static kernel in isolation; returns a report
    {plugin_name: {extension_point, seconds, ...stats}} and records the
    per-plugin/per-point histograms when `metrics` is given."""
    report: dict[str, dict[str, Any]] = {}
    point_totals = {"Filter": 0.0, "Score": 0.0}
    # jitted probes are cached on the framework so repeated profiling
    # passes (--profile-every) reuse compiled programs instead of paying
    # full XLA recompilation on every pass; jax.jit itself handles shape
    # changes within one cached callable
    cache: dict[Any, Any] = framework.__dict__.setdefault("_probe_cache", {})
    valid = (
        np.asarray(snap.pod_valid)[:, None] & np.asarray(snap.node_valid)[None, :]
    )
    n_valid = max(valid.sum(), 1)

    for plugin in framework.filters:
        if ("static", plugin.name, "Filter") not in cache:
            cache[("static", plugin.name, "Filter")] = jax.jit(  # schedlint: disable=JP006 -- _probe_cache guard above: built once per plugin per process, then reused
                lambda s, p=plugin: p.static_mask(CycleContext(s))
            )
        fn = cache[("static", plugin.name, "Filter")]
        if fn(snap) is None:  # dynamic-only plugin (no static kernel)
            continue
        secs, mask = _time_call(fn, snap, repeats)
        feasible = float((np.asarray(mask) & valid).sum() / n_valid)
        report[f"{plugin.name}/Filter"] = {
            "extension_point": "Filter",
            "seconds": secs,
            "feasible_fraction": feasible,
        }
        point_totals["Filter"] += secs
        if metrics is not None:
            metrics.plugin_duration.labels(
                plugin=plugin.name, extension_point="Filter", status="Success"
            ).observe(secs)

    for plugin, weight in framework.scores:
        if ("static", plugin.name, "Score") not in cache:
            cache[("static", plugin.name, "Score")] = jax.jit(  # schedlint: disable=JP006 -- _probe_cache guard above: built once per plugin per process, then reused
                lambda s, p=plugin: p.static_score(CycleContext(s))
            )
        fn = cache[("static", plugin.name, "Score")]
        if fn(snap) is None:
            continue
        secs, score = _time_call(fn, snap, repeats)
        sc = np.asarray(score)[valid]
        report[f"{plugin.name}/Score"] = {
            "extension_point": "Score",
            "seconds": secs,
            "weight": weight,
            "score_mean": float(sc.mean()) if sc.size else 0.0,
            "score_max": float(sc.max()) if sc.size else 0.0,
        }
        point_totals["Score"] += secs
        if metrics is not None:
            metrics.plugin_duration.labels(
                plugin=plugin.name, extension_point="Score", status="Success"
            ).observe(secs)

    # ---- dynamic path: the actual hot loop -------------------------------
    # Filter/Score work that runs INSIDE the commit scan (resource fit
    # against running capacity, affinity/spread domain counts) is invisible
    # to the static timings above. Time each plugin's dyn path as its own
    # isolated scan over the full pending set — the per-cycle cost the
    # plugin adds to the fused program.
    for plugin in framework.filters:
        if ("dyn", plugin.name, "Filter") not in cache:
            cache[("dyn", plugin.name, "Filter")] = _dyn_probe(
                plugin, snap, as_score=False
            )
        fn = cache[("dyn", plugin.name, "Filter")]
        if fn is None:
            continue
        secs, _ = _time_call(fn, snap, repeats)
        report[f"{plugin.name}/Filter[dyn]"] = {
            "extension_point": "Filter",
            "seconds": secs,
        }
        point_totals["Filter"] += secs
        if metrics is not None:
            metrics.plugin_duration.labels(
                plugin=plugin.name, extension_point="Filter", status="Success"
            ).observe(secs)

    for plugin, weight in framework.scores:
        if ("dyn", plugin.name, "Score") not in cache:
            cache[("dyn", plugin.name, "Score")] = _dyn_probe(
                plugin, snap, as_score=True
            )
        fn = cache[("dyn", plugin.name, "Score")]
        if fn is None:
            continue
        secs, _ = _time_call(fn, snap, repeats)
        report[f"{plugin.name}/Score[dyn]"] = {
            "extension_point": "Score",
            "seconds": secs,
            "weight": weight,
        }
        point_totals["Score"] += secs
        if metrics is not None:
            metrics.plugin_duration.labels(
                plugin=plugin.name, extension_point="Score", status="Success"
            ).observe(secs)

    if metrics is not None:
        for point, total in point_totals.items():
            if total > 0.0:
                metrics.extension_point_duration.labels(
                    extension_point=point, status="Success"
                ).observe(total)
    return report


def _dyn_probe(plugin, snap: ClusterSnapshot, as_score: bool):
    """A jitted isolated commit-scan exercising ONE plugin's dynamic path
    (mask or score) plus its state update; None when the plugin has no such
    path. The scan mirrors greedy_commit's shape so timings are
    representative of the plugin's marginal cost in the fused cycle."""
    # a plugin with no dyn path returns None at trace time (a Python-level
    # decision, same with tracers or concrete arrays) — check eagerly
    ctx0 = CycleContext(snap)
    e0 = plugin.extra_init(ctx0)
    ext0 = {} if e0 is None else {plugin.name: e0}
    probe = (
        plugin.dyn_score(ctx0, 0, snap.node_requested, ext0,
                         jnp.broadcast_to(snap.node_valid, (snap.N,)))
        if as_score
        else plugin.dyn_mask(ctx0, 0, snap.node_requested, ext0)
    )
    if probe is None:
        return None

    def fn(snap):
        ctx = CycleContext(snap)
        e = plugin.extra_init(ctx)
        extra = {} if e is None else {plugin.name: e}
        order = jnp.argsort(snap.pod_order)

        def step(carry, rank):
            node_req, ext = carry
            p = order[rank]
            mask = jnp.broadcast_to(snap.node_valid, (snap.N,))
            score = jnp.zeros((snap.N,), jnp.float32)
            if as_score:
                score = plugin.dyn_score(ctx, p, node_req, ext, mask)
            else:
                mask = mask & plugin.dyn_mask(ctx, p, node_req, ext)
            best = argsel.argmax_first(
                jnp.where(mask, score, -1e9), axis=0
            )
            ok = mask[best] & snap.pod_valid[p]
            node_req = node_req.at[best].add(
                jnp.where(ok, snap.pod_requested[p], 0.0)
            )
            if plugin.name in ext:
                ext = {
                    plugin.name: plugin.extra_update(
                        ctx, ext[plugin.name], p, best, ok
                    )
                }
            return (node_req, ext), ()

        (node_req, _), _ = jax.lax.scan(
            step, (snap.node_requested, extra),
            jnp.arange(snap.P, dtype=jnp.int32),
        )
        return node_req

    return jax.jit(fn)


def overlap_stats(
    encode_s: float, device_s: float, pipelined_s: float
) -> dict[str, float]:
    """Split-phase overlap accounting for the serving pipeline
    (core/pipeline.py): given three independently measured medians —
    host encode alone, device cycle (dispatch + slimmed decision fetch)
    alone, and the pipelined per-cycle wall time (dispatch cycle k, then
    encode cycle k+1 on the host, then fetch k's decisions) — report how
    much of the smaller stage was hidden behind the larger one.

        hidden      = encode + device - pipelined   (>= 0)
        overlap_pct = hidden / min(encode, device) * 100

    100% means the cheaper stage ran entirely in the other's shadow (the
    pipelined cycle costs max(encode, device), not the sum); 0% means no
    overlap (fully serial — e.g. forced_sync)."""
    hidden = max(0.0, encode_s + device_s - pipelined_s)
    denom = min(encode_s, device_s)
    pct = 100.0 * hidden / denom if denom > 0 else 0.0
    return {
        "encode_ms": round(encode_s * 1e3, 3),
        "device_ms": round(device_s * 1e3, 3),
        "pipelined_ms": round(pipelined_s * 1e3, 3),
        "encode_hidden_ms": round(min(hidden, encode_s) * 1e3, 3),
        "overlap_pct": round(min(pct, 100.0), 1),
    }


def overlap_from_records(
    phase_dicts: "Iterable[dict[str, float]]",
) -> dict[str, float]:
    """Continuous overlap accounting from flight-recorder records —
    the production counterpart of `overlap_stats`, which needs three
    separated probe runs. Each input dict is a CycleRecord's `phases`
    (the ServingPipeline stage report: encode_ms, decision_wait_ms,
    encode_hidden_ms, diag_lag_ms, ...).

    `overlap_ratio` = hidden encode / total encode over the window,
    using the pipeline's conservative per-cycle estimate
    (hidden = max(0, encode - decision_wait)); 0.0 = fully serial
    (forced_sync), 1.0 = every encode ran in the device's shadow.
    Pure python — safe to call from endpoints at serving rate."""
    n = 0
    enc = hidden = wait = diag = diag_n = 0.0
    for ph in phase_dicts:
        n += 1
        e = ph.get("encode_ms", 0.0)
        w = ph.get("decision_wait_ms", 0.0)
        enc += e
        wait += w
        hidden += ph.get("encode_hidden_ms", max(0.0, e - w))
        if "diag_lag_ms" in ph:
            diag += ph["diag_lag_ms"]
            diag_n += 1
    return {
        "window": float(n),
        "encode_ms_mean": round(enc / n, 4) if n else 0.0,
        "decision_wait_ms_mean": round(wait / n, 4) if n else 0.0,
        "encode_hidden_ms_mean": round(hidden / n, 4) if n else 0.0,
        "diag_lag_ms_mean": round(diag / diag_n, 4) if diag_n else 0.0,
        "overlap_ratio": round(min(hidden / enc, 1.0), 4) if enc > 0
        else 0.0,
    }


def trace_cycle(cycle_fn, snap: ClusterSnapshot, log_dir: str):
    """One fused cycle under jax.profiler (TensorBoard/Perfetto trace)."""
    with jax.profiler.trace(log_dir):
        out = cycle_fn(snap)
        jax.block_until_ready(out.assignment)
    return out
