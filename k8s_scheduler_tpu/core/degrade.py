"""The explicit degradation ladder: every recovery mode, ordered.

The repo's failure handling used to be real but implicit — `_Resilient`
retried, a journal death silently went stateless, a wedge killed the
process and the standby took over. This module names the modes and
drives transitions between them, so a dispatch failure walks an
EXPLICIT, observable recovery ladder instead of an ad-hoc one:

    rung 0  normal       full async pipeline; `_Resilient` retries
                         absorb transient flakes invisibly
    rung 1  retrace      compiled-program memos cleared (the
                         clear_cache+retrace recovery, regime-wide)
    rung 2  sequential   multi-cycle batching off — every cycle is its
                         own dispatch (smaller blast radius per fault)
    rung 3  forced_sync  every dispatch blocks to completion (no
                         in-flight state to lose; the measurement mode,
                         now a recovery mode)
    rung 4  stateless    durable state sealed + detached for failover;
                         serving continues without durability (the
                         standby restores the sealed snapshot)

The literal `RUNGS` tuple is the inventory of record: schedlint ID007
pins the README "## Failure model & degradation ladder" rung table to
it. Each transition (both directions) is emitted as an events-ring
entry, a typed `degraded` anomaly in /debug/anomalies, the
`scheduler_degradation_rung` gauge, and a
`scheduler_degradation_transitions_total{from,to}` counter increment;
the current rung rides `/healthz` and `/debug/state`.

Degradation state is PROCESS-LOCAL, deliberately never journaled as
authoritative: a standby that takes over starts at the top rung and
walks down only on its own evidence (the fault may have died with the
old process — tests/test_state_failover.py asserts the restart-at-top
behavior). Promotion is automatic: `promote_after` consecutive clean
scheduling cycles step one rung back up, so a cleared fault recovers
the full pipeline without operator action.
"""

from __future__ import annotations

import collections
import logging
import threading
import time as _time
from typing import Callable

log = logging.getLogger("k8s_scheduler_tpu.degrade")

# Bounded transition-log depth: a long-lived process under a persistent
# fault degrades every cycle, and the ISSUE-8 list grew one dict per
# degrade forever. The ring keeps the recent window the soaks and bench
# config 7 read for MTTR; the exact lifetime counts live in the
# `degradations` / `transitions_total` counters and the
# `scheduler_degradation_transitions_total` metric, which never lose
# precision to the cap.
TRANSITIONS_CAP = 512

# The ladder, top first. Index IS the rung number; schedlint ID007 pins
# the README rung table to this literal tuple.
RUNGS = (
    "normal",
    "retrace",
    "sequential",
    "forced_sync",
    "stateless",
)

RUNG_NORMAL = 0
RUNG_RETRACE = 1
RUNG_SEQUENTIAL = 2
RUNG_FORCED_SYNC = 3
RUNG_STATELESS = 4


class DegradationLadder:
    """Rung state + transition plumbing. Thread model: `degrade` and
    `note_clean_cycle` run on the scheduling loop; readers (`/healthz`
    closures, `/debug/state`) take the same small lock. The
    `on_transition(old, new, reason)` callback runs WITHOUT the lock
    held (it clears program memos / seals state — work that must not
    nest under a status read)."""

    def __init__(
        self,
        *,
        promote_after: int = 8,
        metrics=None,  # SchedulerMetrics | None
        events=None,  # core/events.EventRecorder | None
        observer=None,  # core/observe.CycleObserver | None
        on_transition: "Callable[[int, int, str], None] | None" = None,
    ) -> None:
        self._lock = threading.Lock()
        self.promote_after = max(int(promote_after), 1)
        self.rung = RUNG_NORMAL
        # promotion floor: the ladder never promotes below this rung.
        # The scheduler pins it at RUNG_STATELESS after sealing durable
        # state away — serving at lower rungs could resume, but
        # reporting rung 0 ("normal") while every mutation since the
        # seal is unjournaled would be a lie; the standby takeover (or
        # a restart) is the recovery that clears it.
        self.floor = RUNG_NORMAL
        self.last_reason = ""
        self._clean = 0
        self._metrics = metrics
        self._events = events
        self._observer = observer
        self._on_transition = on_transition
        # transition log (soaks and bench config 7 read it for MTTR):
        # each entry carries both clocks so recovery time is measurable
        # in wall seconds. A bounded ring (ISSUE 11 satellite): a
        # process degrading every cycle for weeks must not grow one
        # dict per fault — `transitions_total` keeps the exact count.
        self.transitions: "collections.deque[dict]" = collections.deque(
            maxlen=TRANSITIONS_CAP
        )
        self.transitions_total = 0
        self.degradations = 0
        if metrics is not None:
            metrics.degradation_rung.set(0)

    # ---- transitions -----------------------------------------------------

    def degrade(self, reason: str, seq: int = -1) -> int:
        """Step one rung DOWN (toward stateless); returns the new rung.
        At the bottom rung further failures re-emit the event/anomaly
        (the operator must see continued failures) without moving — and
        RE-FIRE `on_transition` (ISSUE 11 satellite): the rung's side
        effects (the retrace memo clear) must be re-applied under
        continued failure, or a stale executable installed after the
        last clear survives into every subsequent retry."""
        with self._lock:
            old = self.rung
            new = min(old + 1, len(RUNGS) - 1)
            self.rung = new
            self.last_reason = reason
            self._clean = 0
            self.degradations += 1
        self._emit(old, new, reason, seq, down=True)
        return new

    def note_clean_cycle(self, seq: int = -1) -> None:
        """One scheduling cycle completed without a dispatch failure;
        after `promote_after` in a row, step one rung back UP — never
        below `floor` (the scheduler pins the floor at `stateless` once
        durable state is sealed away: durability cannot come back in
        this process, so the ladder must not report full recovery)."""
        with self._lock:
            if self.rung <= max(RUNG_NORMAL, self.floor):
                self._clean = 0
                return
            self._clean += 1
            if self._clean < self.promote_after:
                return
            old = self.rung
            new = old - 1
            self.rung = new
            self._clean = 0
        self._emit(
            old, new,
            f"promoted after {self.promote_after} clean cycles", seq,
            down=False,
        )

    def _emit(
        self, old: int, new: int, reason: str, seq: int, down: bool
    ) -> None:
        entry = {
            "from": old,
            "to": new,
            "from_name": RUNGS[old],
            "to_name": RUNGS[new],
            "reason": reason,
            "seq": seq,
            "t": _time.perf_counter(),
            "wall": _time.time(),
        }
        with self._lock:
            self.transitions.append(entry)
            self.transitions_total += 1
        # direction comes from the CALLER's intent, not old/new order:
        # a degrade() at the sticky bottom rung keeps old == new, and
        # inferring direction from the comparison would report those
        # continued failures as promotions
        direction = "DOWN" if down else "up"
        log.warning(
            "degradation ladder %s: rung %d (%s) -> %d (%s): %s",
            direction, old, RUNGS[old], new, RUNGS[new], reason,
        )
        m = self._metrics
        if m is not None:
            m.degradation_rung.set(new)
            if new != old:
                m.degradation_transitions.labels(
                    RUNGS[old], RUNGS[new]
                ).inc()
        ev = self._events
        if ev is not None:
            from .events import DEGRADED, PROMOTED

            ev.system(
                DEGRADED if down else PROMOTED,
                f"degradation ladder rung {old} ({RUNGS[old]}) -> "
                f"{new} ({RUNGS[new]}): {reason}",
            )
        obs = self._observer
        if obs is not None:
            obs.raise_anomaly(
                "degraded",
                seq=seq,
                from_rung=RUNGS[old],
                to_rung=RUNGS[new],
                direction="down" if down else "up",
                reason=reason[:300],
            )
        cb = self._on_transition
        # the hook fires on every rung CHANGE and on every sticky-bottom
        # degrade repeat (old == new, down): continued failure must
        # re-apply the rung's actions (retrace re-clears the program
        # memos), not only re-emit telemetry. Promotions always change
        # the rung, so `down` can't double-fire them.
        if cb is not None and (new != old or down):
            try:
                cb(old, new, reason)
            except Exception:
                # a failing rung-effect hook must not mask the original
                # fault or take the loop down — the rung number already
                # moved, which is what readers and promotion act on
                log.exception(
                    "degradation rung-transition hook failed "
                    "(%d -> %d)", old, new,
                )

    # ---- readers ---------------------------------------------------------

    def status(self) -> dict:
        """The /healthz + /debug/state payload."""
        with self._lock:
            return {
                "rung": self.rung,
                "name": RUNGS[self.rung],
                "floor": self.floor,
                "clean_cycles": self._clean,
                "promote_after": self.promote_after,
                "degradations": self.degradations,
                "last_reason": self.last_reason,
                # exact lifetime count — the ring below may have evicted
                "transitions": self.transitions_total,
                "transitions_buffered": len(self.transitions),
            }

    def transition_log(self, last: "int | None" = None) -> list[dict]:
        """Copy of the transition ring (oldest first), each entry with
        its monotonic `t` and wall timestamp — the /debug/state MTTR
        surface and the black box's ladder tail. `last` trims to the
        most recent entries; the ring itself is bounded (512)."""
        with self._lock:
            out = [dict(e) for e in self.transitions]
        return out if last is None else out[-last:]

    def recovery_episodes_ms(self) -> list[float]:
        """Wall milliseconds of each completed recovery episode (left
        rung 0 -> returned to rung 0) — the MTTR series bench config 7
        and soak_chaos report."""
        out: list[float] = []
        down_t: "float | None" = None
        # snapshot under the lock: iterating the live deque while the
        # scheduling loop appends raises (a list raced benignly here;
        # a deque does not)
        with self._lock:
            transitions = list(self.transitions)
        for e in transitions:
            if e["from"] == RUNG_NORMAL and e["to"] > RUNG_NORMAL:
                if down_t is None:
                    down_t = e["t"]
            elif e["to"] == RUNG_NORMAL and down_t is not None:
                out.append((e["t"] - down_t) * 1e3)
                down_t = None
        return out
