"""Events: the upstream event stream (`Scheduled` / `FailedScheduling` /
`Preempted`), as an injectable recorder.

The reference family posts Kubernetes Events per pod with per-plugin
failure reasons ("0/5 nodes are available: 3 Insufficient cpu, ..." —
SURVEY.md §5.5; expected upstream `EventBroadcaster` usage, [UNVERIFIED],
mount empty). There is no API server here to post to, so the recorder
keeps a bounded in-memory ring + structured logging, which doubles as the
per-cycle decision log the batched design needs; the gRPC shim drains the
ring into each CycleResponse so the cluster agent can post real Events.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
from typing import Iterable

from ..models.api import Pod

log = logging.getLogger("k8s_scheduler_tpu.events")

# Event reasons, upstream names
SCHEDULED = "Scheduled"
FAILED_SCHEDULING = "FailedScheduling"
PREEMPTED = "Preempted"
# batched-cycle addition: the assumed-pod TTL sweep used to drop pods
# silently — this reason makes the expiry explainable per pod
ASSUME_EXPIRED = "AssumeExpired"
# robustness additions: scheduler-level (pod-less) events — a consumed
# cycle's fetch failure and degradation-ladder rung transitions must
# leave an on-box trace even though no single pod owns them
FETCH_FAILED = "FetchFailed"
DEGRADED = "Degraded"
PROMOTED = "Promoted"
# watchtower additions: alert-rule transitions (metrics/rules.py) ride
# the events ring too — the literals live in rules.py so metrics/ stays
# importable without core/, and these constants keep the reason
# namespace discoverable in one place
ALERT_FIRING = "AlertFiring"
ALERT_RESOLVED = "AlertResolved"


@dataclasses.dataclass(frozen=True)
class Event:
    type: str  # "Normal" | "Warning"
    reason: str  # Scheduled | FailedScheduling | Preempted
    pod_uid: str
    pod_name: str
    message: str


def failed_scheduling_message(
    num_nodes: int, reject_counts: Iterable[tuple[str, int]]
) -> str:
    """Upstream-style diagnosis line: '0/5 nodes are available:
    3 NodeResourcesFit, 2 NodeAffinity.' — counts are nodes first-rejected
    per plugin (CycleResult.reject_counts row)."""
    parts = [f"{int(n)} {name}" for name, n in reject_counts if n > 0]
    detail = ", ".join(parts) if parts else "no nodes matched"
    return f"0/{num_nodes} nodes are available: {detail}."


class EventRecorder:
    """Bounded in-memory event ring + structured log line per event.

    Thread-safe; `events()` snapshots for tests/endpoints; the gRPC shim
    calls `drain()` per Cycle so events ride the CycleResponse."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._ring: collections.deque[Event] = collections.deque(
            maxlen=capacity
        )

    def record(self, type_: str, reason: str, pod: Pod, message: str) -> None:
        ev = Event(type_, reason, pod.uid, pod.name, message)
        with self._lock:
            self._ring.append(ev)
        log.info(
            "event", extra={"event_reason": reason, "pod": pod.name,
                            "event_message": message}
        )

    def scheduled(self, pod: Pod, node_name: str) -> None:
        self.record(
            "Normal", SCHEDULED, pod,
            f"Successfully assigned {pod.namespace}/{pod.name} to {node_name}",
        )

    def failed_scheduling(self, pod: Pod, message: str) -> None:
        self.record("Warning", FAILED_SCHEDULING, pod, message)

    def preempted(self, victim: Pod, preemptor_name: str) -> None:
        self.record(
            "Normal", PREEMPTED, victim,
            f"Preempted by pod {preemptor_name}",
        )

    def system(self, reason: str, message: str) -> None:
        """A scheduler-level event with no owning pod (fetch failures,
        degradation-ladder transitions): rides the same ring/drain path
        as pod events with an empty uid and the synthetic name
        "scheduler", so the gRPC shim forwards it like any other."""
        ev = Event("Warning", reason, "", "scheduler", message)
        with self._lock:
            self._ring.append(ev)
        log.warning(
            "event", extra={"event_reason": reason, "pod": "scheduler",
                            "event_message": message}
        )

    def assume_expired(self, pod: Pod, node_name: str) -> None:
        self.record(
            "Warning", ASSUME_EXPIRED, pod,
            f"assumed binding to {node_name} expired without bind "
            "confirmation; pod requeued with backoff",
        )

    def events(self) -> list[Event]:
        with self._lock:
            return list(self._ring)

    def events_for(self, pod_uid: str) -> list[Event]:
        """Events still in the ring for one pod — the events-ring half of
        the per-pod scheduling timeline join (Scheduler.pod_timeline).
        Empty after the gRPC shim drained the ring; the flight recorder's
        own pod timeline is the durable half."""
        with self._lock:
            return [e for e in self._ring if e.pod_uid == pod_uid]

    def drain(self) -> list[Event]:
        """Pop everything recorded so far (the gRPC shim calls this per
        Cycle response so the agent can post real Kubernetes Events)."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
