"""Pod-lifecycle tracing: span recorder, trace-context propagation.

Every other observability surface is cycle-centric (flight records,
phase histograms, the anomaly sentinel); since the front door landed,
the unit of work users experience is a POD REQUEST: Submit ->
admission -> WAL ack barrier -> mc-group buffering -> (speculative)
dispatch -> inner-cycle decision row -> bind fold -> confirm. This
module makes that whole life one trace:

- `SpanRecorder` — a bounded ring of `Span`s with the same
  seqlock-style publication discipline as the cycle flight recorder
  (core/flight_recorder.FlightRecorder): a writer's cost is the span
  construction plus ONE list-slot store; readers copy the ring without
  blocking writers, retry while a commit tears the copy, and trim to
  the trailing window no commit could have torn. Unlike the flight
  recorder, spans are written from SEVERAL threads (gRPC/HTTP submit
  workers, the serve loop, informer threads); slot sequence numbers
  come from `itertools.count` (atomic in CPython), so concurrent
  writers never race a slot index read-modify-write.
- Arming — the PR 8 fault-hook pattern (core/faults.py): a module
  global `ARMED` flag plus `arm()`/`disarm()`. Unarmed, every stamp
  site pays ONE module-attribute load and a falsy branch; armed, a
  stamp is dict stores into a Span plus the slot store. The scheduler
  never imports anything trace-specific on the unarmed path.
- Context propagation — `register(uid, traceparent)` binds a pod uid
  to a trace at admission time: an explicit W3C-style `traceparent`
  joins the caller's trace; absent one, deterministic head sampling
  (`sampled(uid)`, a uid-hash coin at the armed sample rate) decides
  per pod. The uid -> context map is the cross-thread join: spans
  emitted on the submit thread (validate/journal/ack), the serve
  thread (buffer wait, dispatch, decision row, apply fold, bind
  confirm) and anywhere else all look the context up by uid and land
  in ONE trace. `release(uid)` drops the binding at the pod's
  terminal event (bound / deleted).
- Export — `spans_to_chrome_events` renders per-trace tracks that
  `to_chrome_trace` merges into the cycle lanes (one Perfetto view
  shows a pod's spans overlapping the batch that served it), and
  `to_otlp_json` / `export_otlp_dir` produce OTLP-JSON resource spans
  for external ingestion (`--trace-export-dir`, size-rotated).

`SPAN_NAMES` below is the pinned span inventory; schedlint's ID010
check keeps it, the README "## Distributed tracing" span table, and
the metrics docstring from drifting apart. Stdlib-only (no jax /
numpy / prometheus) so the state layer, tools and tests can import it
without a backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import os
import re
import threading
import time as _time
import uuid
from typing import Any, Callable, Iterable

# The pinned span-name inventory — every stamp site emits one of
# these. Grouped by the thread that stamps them:
#   submit thread:  submit.validate (request validation + dup check),
#                   submit.journal (the informer-path enqueue, which
#                   appends q.add through the WAL), ack.barrier (the
#                   group-commit fsync wait; one span PER SUBMITTER,
#                   all joined to the shared flush seq via the
#                   `flush_seq` attr)
#   serve thread:   mc.buffer_wait (admission -> multi-cycle flush),
#                   encode.ingest (admission-time incremental row
#                   staging), flush.finalize (the O(dirty) flush
#                   apply), dispatch (device dispatch window),
#                   dispatch.speculative (the depth-2 continuation;
#                   attr `outcome`: adopted | abandoned),
#                   decision.row (the inner cycle's slimmed row
#                   transfer), apply.fold (winner bind loop ->
#                   postfilter), bind.confirm (the pod's bind),
#                   preempt.victim (an eviction this pod's nomination
#                   caused; attrs name the victim)
SPAN_NAMES = (
    "submit.validate",
    "submit.journal",
    "ack.barrier",
    "mc.buffer_wait",
    "encode.ingest",
    "flush.finalize",
    "dispatch",
    "dispatch.speculative",
    "decision.row",
    "apply.fold",
    "bind.confirm",
    "preempt.victim",
)

# default head-sampling rate (absent an explicit traceparent): 1/64
DEFAULT_SAMPLE_RATE = 1.0 / 64.0

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

# uid -> TraceContext bound at most this deep (LRU): a pod parked
# unschedulable forever must not pin its context entry
_MAX_CONTEXTS = 65_536


@dataclasses.dataclass
class Span:
    """One completed operation in a pod's trace. Times are absolute
    recorder-clock seconds (perf_counter, the same clock the flight
    recorder stamps marks with, so span slices and cycle lanes rebase
    against one epoch). Spans are immutable once recorded — the ring
    replaces slots, it never mutates them."""

    trace_id: str  # 32 hex chars (W3C trace-id)
    span_id: str  # 16 hex chars
    parent: str  # 16 hex chars, "" for a root span
    name: str  # one of SPAN_NAMES
    t0: float
    t1: float
    seq: int = -1  # recorder slot sequence (assigned by record())
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self, epoch: float = 0.0) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent": self.parent,
            "name": self.name,
            "t0_s": round(self.t0 - epoch, 6),
            "t1_s": round(self.t1 - epoch, 6),
            "dur_ms": round(max(self.t1 - self.t0, 0.0) * 1e3, 4),
            "attrs": dict(self.attrs),
        }


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A pod's binding to a trace, created at admission. `span_id` is
    the parent every span emitted for the pod names: a locally minted
    root id for head-sampled pods, the caller's span id when an
    explicit traceparent joined us to an existing trace. `tenant` is
    the pod's virtual cluster ("" in single-tenant mode): every span
    recorded under the context inherits it as a `tenant` attr, so one
    trace view shows per-tenant lanes."""

    trace_id: str
    span_id: str
    tenant: str = ""

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)


# ---- W3C traceparent helpers --------------------------------------------


def parse_traceparent(value: str) -> "tuple[str, str] | None":
    """(trace_id, parent_span_id) from a W3C traceparent header, or
    None when malformed / all-zero (the spec's invalid sentinels)."""
    m = _TRACEPARENT_RE.match((value or "").strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    # flags 01: sampled (we only hold contexts for sampled pods)
    return f"00-{trace_id}-{span_id}-01"


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return os.urandom(8).hex()


def sampled(uid: str, rate: float) -> bool:
    """Deterministic head-sampling coin: the same uid at the same rate
    always decides the same way (a retry of a shed submission keeps
    its sampling fate), and distinct uids decide independently. rate
    >= 1 samples everything, <= 0 nothing."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = hashlib.blake2b(uid.encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0**64 < rate


# ---- the span ring -------------------------------------------------------


class SpanRecorder:
    """Bounded multi-writer ring of completed spans.

    Writer cost: one Span construction + one list-slot store (the
    slot index comes from an `itertools.count`, whose `next()` is
    atomic under CPython — concurrent submit/serve/informer threads
    never race an index increment). `_commits` publishes like the
    flight recorder's seqlock generation; its increment is a benign
    multi-writer race (a lost increment can only cost a reader one
    extra retry) because the snapshot's trailing-window trim — keep
    only the newest run of seqs no commit could have torn — is the
    correctness backstop, exactly as it is for FlightRecorder."""

    def __init__(
        self,
        capacity: int = 8192,
        now: Callable[[], float] = _time.perf_counter,
        wall: Callable[[], float] = _time.time,
    ) -> None:
        self.capacity = max(int(capacity), 1)
        self.now = now
        self._ring: "list[Span | None]" = [None] * self.capacity
        self._seq = itertools.count()
        self._commits = 0
        self.epoch = now()
        self.wall_epoch = wall()

    # ---- writer side -----------------------------------------------------

    def record(
        self,
        name: str,
        ctx: TraceContext,
        t0: float,
        t1: float,
        **attrs: Any,
    ) -> Span:
        """Record one completed span under `ctx` (parent = the
        context's root/caller span id). A tenant-scoped context stamps
        its tenant on every span it records — one stamp site, so no
        emitter can forget the attribution."""
        if ctx.tenant and "tenant" not in attrs:
            attrs["tenant"] = ctx.tenant
        span = Span(
            trace_id=ctx.trace_id,
            span_id=new_span_id(),
            parent=ctx.span_id,
            name=name,
            t0=t0,
            t1=t1,
            seq=next(self._seq),
            attrs=attrs,
        )
        self._ring[span.seq % self.capacity] = span
        # publish AFTER the slot store (GIL-ordered); see class doc
        # for why the racy increment is safe here
        self._commits += 1  # schedlint: disable=TR001 -- benign seqlock-generation race: the snapshot trim is the correctness backstop
        return span

    # ---- reader side -----------------------------------------------------

    @property
    def count(self) -> int:
        """Spans recorded (approximate under concurrent writers —
        monotonic, may trail by in-flight commits)."""
        return self._commits

    def snapshot(self, last: "int | None" = None) -> "list[Span]":
        """Consistent copy of the most recent `last` spans (oldest
        first). Same discipline as FlightRecorder.snapshot: retry the
        lock-free copy while a commit lands in it, then trim to the
        trailing contiguous-capacity window."""
        ring: "list[Span | None]" = []
        for _ in range(8):
            before = self._commits
            ring = list(self._ring)
            if self._commits == before:
                break
        spans = sorted(
            (s for s in ring if s is not None), key=lambda s: s.seq
        )
        if spans:
            spans = [
                s for s in spans
                if s.seq > spans[-1].seq - self.capacity
            ]
        if last is not None:
            n = max(int(last), 0)
            spans = spans[-n:] if n else []
        return spans

    def for_trace(self, trace_id: str) -> "list[Span]":
        return [s for s in self.snapshot() if s.trace_id == trace_id]

    def for_uid(self, uid: str) -> "list[Span]":
        """Spans whose `uid` attr names the pod — the /debug join for
        pods whose context has already been released."""
        return [
            s for s in self.snapshot() if s.attrs.get("uid") == uid
        ]

    def to_dicts(self, last: "int | None" = None) -> "list[dict]":
        return [s.to_dict(epoch=self.epoch) for s in self.snapshot(last)]


# ---- module arming (the PR 8 fault-hook pattern) -------------------------

# Hot sites gate on `spans.ARMED` (one module-attribute load + branch
# unarmed); cross-package sites that must not import core (the state
# layer) reach this module through sys.modules, exactly like
# state/journal.py reaches core.faults.
ARMED = False
RECORDER: "SpanRecorder | None" = None
_RATE = DEFAULT_SAMPLE_RATE
# span-name -> count callback (the CLI wires the
# scheduler_trace_spans_total counter here; tests leave it None)
_COUNTER: "Callable[[str], None] | None" = None

_ctx_lock = threading.Lock()
_contexts: "dict[str, TraceContext]" = {}


def arm(
    recorder: "SpanRecorder | None" = None,
    rate: float = DEFAULT_SAMPLE_RATE,
    counter: "Callable[[str], None] | None" = None,
) -> SpanRecorder:
    """Install `recorder` (a fresh default-capacity one when None) as
    the process-wide span sink and flip every stamp site live."""
    global ARMED, RECORDER, _RATE, _COUNTER
    RECORDER = recorder if recorder is not None else SpanRecorder()
    _RATE = float(rate)
    _COUNTER = counter
    ARMED = True
    return RECORDER


def disarm() -> None:
    """Flip every stamp site back to the one-flag-load path and drop
    the uid -> context map (the recorder stays readable for post-hoc
    export until the next arm() replaces it)."""
    global ARMED, _COUNTER
    ARMED = False
    _COUNTER = None
    with _ctx_lock:
        _contexts.clear()


def now() -> float:
    """The armed recorder's clock (perf_counter unless a test
    injected another) — stamp sites use this so span times and
    flight-record marks share one base."""
    rec = RECORDER
    return rec.now() if rec is not None else _time.perf_counter()


# ---- context registry (the cross-thread trace join) ----------------------


def register(
    uid: str, traceparent: str = "", tenant: str = ""
) -> "TraceContext | None":
    """Bind `uid` to a trace at admission: join the caller's trace
    when `traceparent` parses, else head-sample at the armed rate.
    `tenant` names the pod's virtual cluster (multi-tenant front door;
    "" otherwise) and rides the context onto every recorded span.
    Returns the context (None = unsampled or unarmed). Idempotent for
    an already-registered uid (a duplicate submit keeps the original
    binding)."""
    if not ARMED:
        return None
    parsed = parse_traceparent(traceparent) if traceparent else None
    if parsed is None and not sampled(uid, _RATE):
        return None
    with _ctx_lock:
        ctx = _contexts.get(uid)
        if ctx is None:
            if parsed is not None:
                ctx = TraceContext(*parsed, tenant=tenant)
            else:
                ctx = TraceContext(
                    new_trace_id(), new_span_id(), tenant=tenant
                )
            _contexts[uid] = ctx
            if len(_contexts) > _MAX_CONTEXTS:
                # drop the oldest insertion (dicts iterate in order)
                _contexts.pop(next(iter(_contexts)))
    return ctx


def ctx_for(uid: str) -> "TraceContext | None":
    with _ctx_lock:
        return _contexts.get(uid)


def release(uid: str) -> None:
    """Drop the uid's trace binding at its terminal event (bound /
    deleted). Recorded spans stay in the ring; only the LIVE join is
    released."""
    with _ctx_lock:
        _contexts.pop(uid, None)


def record_span(
    name: str,
    ctx: TraceContext,
    t0: float,
    t1: float,
    **attrs: Any,
) -> None:
    """The armed stamp: one span into the module recorder. Callers
    gate on `ARMED` themselves (that IS the unarmed fast path); a
    stamp racing a concurrent disarm is dropped silently."""
    rec = RECORDER
    if rec is None:
        return
    rec.record(name, ctx, t0, t1, **attrs)
    cb = _COUNTER
    if cb is not None:
        try:
            cb(name)
        except Exception:  # schedlint: disable=RB001 -- observability counter failure must never reach a stamp site on the serve/submit path
            pass


# ---- export --------------------------------------------------------------

# chrome-trace: span tracks render in their own process group so
# Perfetto shows them under (and time-aligned with) the cycle lanes
TRACE_TRACK_PID = 2


def spans_to_chrome_events(
    spans: Iterable[Span], epoch: float = 0.0
) -> "list[dict]":
    """Chrome-trace events for per-trace tracks: one tid per trace_id
    (named by the trace's pod uids), each span an `X` slice whose args
    carry the span/parent ids and attrs — the flight-record `seq` attr
    included, which is the exemplar join back to the cycle lanes."""
    events: "list[dict]" = []
    tids: "dict[str, int]" = {}
    uids: "dict[str, set]" = {}
    tenants: "dict[str, set]" = {}
    spans = list(spans)
    for s in spans:
        tid = tids.setdefault(s.trace_id, len(tids) + 1)
        uid = s.attrs.get("uid")
        if uid:
            uids.setdefault(s.trace_id, set()).add(uid)
        tn = s.attrs.get("tenant")
        if tn:
            tenants.setdefault(s.trace_id, set()).add(tn)
    if not tids:
        return events
    events.append(
        {
            "name": "process_name",
            "ph": "M",
            "pid": TRACE_TRACK_PID,
            "args": {"name": "pod traces"},
        }
    )
    for trace_id, tid in tids.items():
        pods = ",".join(sorted(uids.get(trace_id, ()))) or "?"
        # tenant-scoped traces lead with the tenant so Perfetto's
        # track list groups one virtual cluster's lanes together
        tn = ",".join(sorted(tenants.get(trace_id, ())))
        prefix = f"tenant {tn} " if tn else ""
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": TRACE_TRACK_PID,
                "tid": tid,
                "args": {
                    "name": f"{prefix}trace {trace_id[:8]} pod={pods}"
                },
            }
        )
        events.append(
            {
                "name": "thread_sort_index",
                "ph": "M",
                "pid": TRACE_TRACK_PID,
                "tid": tid,
                "args": {"sort_index": tid},
            }
        )
    for s in spans:
        events.append(
            {
                "name": s.name,
                "ph": "X",
                "pid": TRACE_TRACK_PID,
                "tid": tids[s.trace_id],
                "ts": round((s.t0 - epoch) * 1e6, 3),
                "dur": round(max(s.t1 - s.t0, 0.0) * 1e6, 3),
                "cat": "pod-trace",
                "args": {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent": s.parent,
                    **s.attrs,
                },
            }
        )
    return events


def _otlp_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def to_otlp_json(
    spans: Iterable[Span],
    epoch: float,
    wall_epoch: float,
    service_name: str = "tpu-scheduler",
) -> dict:
    """OTLP/JSON (the OTLP file-exporter shape: one resourceSpans
    entry, spans with hex ids and unix-nano times anchored at the
    recorder's wall epoch) for external ingestion."""

    def nanos(t: float) -> str:
        return str(int((t - epoch + wall_epoch) * 1e9))

    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        {
                            "key": "service.name",
                            "value": {"stringValue": service_name},
                        }
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "k8s_scheduler_tpu.core.spans"},
                        "spans": [
                            {
                                "traceId": s.trace_id,
                                "spanId": s.span_id,
                                **(
                                    {"parentSpanId": s.parent}
                                    if s.parent else {}
                                ),
                                "name": s.name,
                                "kind": 1,  # SPAN_KIND_INTERNAL
                                "startTimeUnixNano": nanos(s.t0),
                                "endTimeUnixNano": nanos(s.t1),
                                "attributes": [
                                    {
                                        "key": k,
                                        "value": _otlp_value(v),
                                    }
                                    for k, v in s.attrs.items()
                                ],
                            }
                            for s in spans
                        ],
                    }
                ],
            }
        ]
    }


def export_otlp_dir(
    recorder: SpanRecorder,
    directory: str,
    max_bytes: int = 64 << 20,
) -> "str | None":
    """Dump the recorder's current window as one OTLP-JSON file in
    `directory` (created if needed), then rotate: oldest dumps are
    deleted until the directory's spans-*.json total is back under
    `max_bytes`. Returns the written path (None when the ring is
    empty). Called at shutdown by the CLI; safe to call repeatedly —
    each call writes the next spans-NNNNNN.json in sequence."""
    spans = recorder.snapshot()
    if not spans:
        return None
    os.makedirs(directory, exist_ok=True)
    existing = sorted(
        f for f in os.listdir(directory)
        if f.startswith("spans-") and f.endswith(".json")
    )
    nxt = 0
    if existing:
        try:
            nxt = int(existing[-1][len("spans-"):-len(".json")]) + 1
        except ValueError:
            nxt = len(existing)
    path = os.path.join(directory, f"spans-{nxt:06d}.json")
    payload = to_otlp_json(spans, recorder.epoch, recorder.wall_epoch)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    # size rotation, oldest-first, never the file just written
    files = sorted(
        f for f in os.listdir(directory)
        if f.startswith("spans-") and f.endswith(".json")
    )
    total = sum(
        os.path.getsize(os.path.join(directory, f)) for f in files
    )
    for f in files[:-1]:
        if total <= max_bytes:
            break
        fp = os.path.join(directory, f)
        total -= os.path.getsize(fp)
        os.remove(fp)
    return path
