"""Deterministic fault injection: named points on the real code paths.

Every recovery rung this repo grew — `_Resilient` retries, the guard
release on a failed fetch, journal-death stateless degrade, warm-standby
failover — was verified against faults the RIG happened to produce. This
module makes each of them reproducible on demand: a seeded `FaultPlan`
fires scripted faults at named injection points threaded through the
real serving/durability code, so `scripts/soak_chaos.py`, bench config 7
(`fault_storm`), and the tier-1 tests can PROVE each ladder rung works
instead of waiting for the tunnel to misbehave.

Injection points (`POINTS`; each hook sits on the exact code path the
real fault class strikes):

- `fetch_delay`    — sleep `ms` BEFORE the blocking decision fetch (a
  slow tunnel: latency visible to the caller, watchdog not involved);
- `fetch_hang`     — sleep `ms` INSIDE the watchdog-bounded fetch call
  (a wedged tunnel: what `dispatchDeadlineMs` exists to bound);
- `device_error`   — raise from inside `_Resilient.__call__` with a
  message carrying the real marker signatures (`kind=` transport |
  corrupt | wedge — core/cycle.py `_TRANSPORT_MARKERS` /
  `_CORRUPT_MARKERS` / `_WEDGE_MARKERS`), driving the real retry /
  clear_cache / fail-fast classification;
- `journal_enospc` — the journal writer's batch write raises ENOSPC
  (state/journal.py), driving the documented degrade-to-stateless path;
- `cache_torn`     — the compile-cache store lands a TRUNCATED entry at
  the final path, as if a rename landed without its data — the next
  load must refuse it and recompile (core/compile_cache.py);
- `cache_enospc`   — the compile-cache store raises ENOSPC (refused
  entry, serving continues on the in-process executable);
- `clock_skew`     — the scheduler's cycle-clock read jumps by `ms`
  (derived stats must tolerate a stepping clock).

Plan syntax (config `faultSpec`, CLI `--fault-spec`, env `SCHED_FAULTS`):

    fetch_hang@cycle=40:ms=5000
    seed=7;fetch_delay@cycle=3..9:ms=50:p=0.5;device_error@cycle=12:kind=wedge:n=1

Rules separated by `;` (or `,`); each is `point[@param:param:...]` with
params `cycle=<i>[..<j>]` (inclusive window; omitted = any cycle),
`ms=<float>`, `kind=<name>`, `p=<prob>`, `n=<max fires>`. A standalone
`seed=<int>` seeds the probability draws, making the whole plan
deterministic. The ambient cycle index is stamped by the scheduler
(`set_cycle`) at the top of every `schedule_cycle`; hooks on other
threads (journal writer, warm thread) see the loop's latest stamp.

Zero overhead unarmed: every hook is gated on the module flag `ARMED`
(one global load + branch); no plan object, rng, or lock is touched.
The hooks are host-side only — schedlint's trace-safety pass keeps this
module off the jit path like any other host effect.
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import random
import threading
import time as _time

log = logging.getLogger("k8s_scheduler_tpu.faults")

POINTS = (
    "fetch_delay",
    "fetch_hang",
    "device_error",
    "journal_enospc",
    "cache_torn",
    "cache_enospc",
    "clock_skew",
)

# Hot-path gate: hooks read this ONE module global and branch away when
# no plan is armed. Mutated only by arm()/disarm().
ARMED = False

_PLAN: "FaultPlan | None" = None
_CYCLE = -1  # ambient cycle index (set_cycle; -1 before the first cycle)


@dataclasses.dataclass
class FaultRule:
    point: str
    lo: "int | None" = None  # inclusive cycle window; None = any cycle
    hi: "int | None" = None
    ms: float = 0.0
    kind: str = "transport"  # device_error class
    prob: float = 1.0
    count: "int | None" = None  # max fires (None = unlimited)
    fired: int = 0

    def eligible(self, cycle: int) -> bool:
        if self.count is not None and self.fired >= self.count:
            return False
        if self.lo is not None and (cycle < self.lo or cycle > self.hi):
            return False
        return True


class FaultPlanError(ValueError):
    """Malformed fault spec — refused loudly at arm time, never at the
    moment the fault would have fired."""


class FaultPlan:
    """A parsed, seeded set of FaultRules plus the fire log (every fire
    is recorded so soaks/benches can assert the plan actually ran)."""

    def __init__(self, rules: "list[FaultRule]", seed: int = 0) -> None:
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.log: list[dict] = []

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        rules: list[FaultRule] = []
        seed = 0
        for raw in spec.replace(",", ";").split(";"):
            tok = raw.strip()
            if not tok:
                continue
            if tok.startswith("seed="):
                seed = int(tok[len("seed="):])
                continue
            point, _, params = tok.partition("@")
            point = point.strip()
            if point not in POINTS:
                raise FaultPlanError(
                    f"unknown fault point {point!r} (known: {POINTS})"
                )
            rule = FaultRule(point=point)
            for p in params.split(":"):
                p = p.strip()
                if not p:
                    continue
                k, _, v = p.partition("=")
                if not v:
                    raise FaultPlanError(
                        f"fault param {p!r} in {tok!r} needs key=value"
                    )
                if k == "cycle":
                    lo, _, hi = v.partition("..")
                    rule.lo = int(lo)
                    rule.hi = int(hi) if hi else rule.lo
                elif k == "ms":
                    rule.ms = float(v)
                elif k == "kind":
                    if v not in ("transport", "corrupt", "wedge"):
                        raise FaultPlanError(
                            f"unknown device_error kind {v!r} in {tok!r}"
                        )
                    rule.kind = v
                elif k == "p":
                    rule.prob = float(v)
                elif k == "n":
                    rule.count = int(v)
                else:
                    raise FaultPlanError(
                        f"unknown fault param {k!r} in {tok!r}"
                    )
            rules.append(rule)
        if not rules:
            raise FaultPlanError(f"fault spec {spec!r} contains no rules")
        return cls(rules, seed=seed)

    def fire(self, point: str, cycle: int) -> "FaultRule | None":
        """The first eligible rule for `point` at `cycle` (recorded in
        the fire log), or None. Probability draws come from the plan's
        seeded rng, so a plan replays identically given the same
        sequence of hook invocations."""
        with self._lock:
            for rule in self.rules:
                if rule.point != point or not rule.eligible(cycle):
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                entry = {
                    "point": point,
                    "cycle": cycle,
                    "kind": rule.kind,
                    "ms": rule.ms,
                    "wall": _time.time(),
                }
                self.log.append(entry)
                log.warning(
                    "fault injected: %s at cycle %d (%s)", point, cycle,
                    ", ".join(f"{k}={v}" for k, v in
                              (("kind", rule.kind), ("ms", rule.ms))
                              if v),
                )
                return rule
        return None

    def fired_points(self) -> "set[str]":
        with self._lock:
            return {e["point"] for e in self.log}


def arm(plan: "FaultPlan | None") -> None:
    global ARMED, _PLAN
    _PLAN = plan
    ARMED = plan is not None


def disarm() -> None:
    arm(None)


def plan() -> "FaultPlan | None":
    return _PLAN


def set_cycle(cycle: int) -> None:
    """Stamp the ambient cycle index (scheduler loop, once per cycle)."""
    global _CYCLE
    _CYCLE = cycle


def fire(point: str) -> "FaultRule | None":
    p = _PLAN
    return p.fire(point, _CYCLE) if p is not None else None


def sleep_point(point: str) -> "FaultRule | None":
    """Fire `point`; sleep its `ms` when it fired (fetch_delay/hang)."""
    r = fire(point)
    if r is not None and r.ms > 0:
        _time.sleep(r.ms / 1e3)
    return r


def raise_device_error() -> None:
    """Fire `device_error`; raise with the matching marker signature so
    the REAL classifier (`_Resilient`, `classify_failure`) routes it."""
    r = fire("device_error")
    if r is None:
        return
    from .cycle import _CORRUPT_MARKERS, _WEDGE_MARKERS

    if r.kind == "corrupt":
        raise RuntimeError(
            f"[fault-injected] Execution supplied 5 buffers but "
            f"{_CORRUPT_MARKERS[0]} 6 buffers"
        )
    if r.kind == "wedge":
        raise RuntimeError(
            f"[fault-injected] INVALID_ARGUMENT: {_WEDGE_MARKERS[0]} "
            "(InvalidArgument)"
        )
    raise RuntimeError(
        "[fault-injected] remote_execute: response body closed"
    )


def raise_enospc(point: str) -> None:
    """Fire `point`; raise ENOSPC when it fired (journal/cache stores)."""
    if fire(point) is not None:
        raise OSError(
            errno.ENOSPC, "No space left on device [fault-injected]"
        )


def torn_store() -> bool:
    """True when the compile-cache store should land a torn entry."""
    return fire("cache_torn") is not None


def skew_s() -> float:
    """Injected clock-skew offset in seconds (0.0 when nothing fired)."""
    r = fire("clock_skew")
    return (r.ms / 1e3) if r is not None else 0.0
