"""Double-buffered async serving pipeline: only decision bytes block.

The serving hot path's latency budget is dominated by work that does NOT
have to sit between "snapshot encoded" and "bindings out": FailedScheduling
attribution, per-round convergence diagnostics, the preemption what-if, and
most of the device->host transfer itself (a full CycleResult fetch moves
[P, F] reject counts and per-round tables nobody reads before binding).
`ServingPipeline` restructures one cycle as:

    encode (host)                       # caller, before dispatch()
    -> dispatch: upload into slot k%2, carry update, latency cycle program
       (all ASYNC — JAX dispatches and returns futures)
    -> caller continues host work (extender webhooks, event drain, ...)
    -> decisions(): block on ONE slimmed device->host copy — an i16 (when
       N < 2^15) assignment plus a u8 flag byte per pod, instead of the
       i32 + 2 x bool + diagnostics payload
    -> winners bind; the preemption and diagnosis programs are dispatched
       non-blocking and forced only when a loser actually needs them

Two slots double-buffer the packed input arenas: slot k's buffers stay
alive for cycle k's deferred consumers (diagnosis / preemption) while
cycle k+1 uploads into the other slot; when a slot is reused its previous
buffers are released first, so the allocator recycles the same-sized
blocks instead of growing (no per-cycle realloc). Optional donation
(`donate_diagnosis`) hands the slot's buffers to the diagnosis program
outright — the last consumer — trading the _Resilient retry of that one
program for immediate arena reuse.

Ordering contract: cycle k's binds MUST fold into the cache before cycle
k+1's *adopted* encode reads it. The pipeline enforces the observable
half — by default `dispatch()` refuses to start cycle k+1 until cycle
k's decisions were fetched (without them no bind can have been issued,
so an encode that already ran read a stale cache). Drivers that fold
nothing (pure throughput loops, probes) opt out with
`require_decision_fetch=False`.

Depth-2 speculative dispatch (`dispatch_multi(..., speculative=True)`)
is the one sanctioned relaxation: batch k+1 may be dispatched while
batch k is still in flight, encoded against the PREDICTED post-k state
(device-side carry chaining — cycle.build_packed_multicycle_fn
`carry_in`). The guard is then "binds fold before the next ADOPTED
encode": the speculative handle only becomes the current batch through
`adopt_speculative()` — called after batch k's host fold landed and
matched the speculation's predicate digest — and is otherwise abandoned
(`abandon_speculative()`) and re-dispatched against the true carry.
Correctness is never speculative, only latency is. Depth 2 needs a
THIRD arena slot (`slots=3`): the two double-buffered slots assume one
batch in flight, and with two in flight the slot-reuse release would
otherwise overwrite a batch whose decisions were never fetched —
`dispatch`/`dispatch_multi` refuse that loudly instead of corrupting
an in-flight upload.

`forced_sync=True` is the escape hatch for tests and latency measurement:
every dispatch blocks to completion before returning, restoring strict
sequential execution with identical results (the split is a scheduling
change, not a semantic one).
"""

from __future__ import annotations

import threading as _threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from . import faults as _faults
from . import spans as _spans
from .cycle import CycleDecision, _jit


class DispatchDeadlineExceeded(RuntimeError):
    """The blocking decision fetch exceeded `dispatchDeadlineMs`: the
    watchdog abandoned the wedged transfer (its worker thread keeps
    blocking harmlessly until the backend lets go) so the serve loop
    can step down the degradation ladder and requeue the cycle's pods
    instead of hanging forever. The cycle is CONSUMED — same contract
    as any other failed fetch (the ordering guard releases)."""


class _FetchWorker:
    """Deadline-bounding for a blocking call the host cannot interrupt
    (`jax.device_get` holds no Python-level cancellation point): the
    fetch runs on a reusable daemon thread while the serve loop waits
    with a timeout. On expiry the worker is considered wedged and
    abandoned — told to exit when (if ever) the fetch returns — and the
    next bounded fetch lazily starts a fresh worker. Cost when a fetch
    completes in time: one queue hand-off + one Event wait (~tens of
    microseconds), paid only when a deadline is configured."""

    def __init__(self) -> None:
        self._lock = _threading.Lock()
        self._q = None
        self._thread: "_threading.Thread | None" = None

    def _run(self, jobs) -> None:
        while True:
            fn, box, done = jobs.get()
            if fn is None:
                return  # abandoned after a deadline expiry
            try:
                box["v"] = fn()
            except BaseException as e:  # schedlint: disable=RB001 -- not swallowed: delivered whole to the waiting serve thread, which classifies + attributes it
                box["e"] = e
            finally:
                done.set()

    def run(self, fn, deadline_s: float):
        import queue as _queue

        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._q = _queue.Queue()
                self._thread = _threading.Thread(
                    target=self._run, args=(self._q,),
                    name="decision-fetch", daemon=True,
                )
                self._thread.start()
            q = self._q
            box: dict = {}
            done = _threading.Event()
            q.put((fn, box, done))
        if not done.wait(deadline_s):
            with self._lock:
                if self._q is q:
                    # tell the wedged worker to exit once the hung
                    # fetch finally returns; a fresh worker spawns on
                    # the next bounded fetch
                    q.put((None, None, None))
                    self._thread = None
                    self._q = None
            raise DispatchDeadlineExceeded(
                f"decision fetch exceeded the dispatch deadline "
                f"({deadline_s * 1e3:.0f} ms); transfer abandoned"
            )
        if "e" in box:
            raise box["e"]
        return box["v"]


def build_decision_slim_fn(num_nodes: int):
    """Jitted output-transfer slimming for the decision fetch:
    (assignment i32 [P], unschedulable bool [P], gang_dropped bool [P])
    -> (assignment i16|i32 [P], flags u8 [P]) where flags bit0 =
    unschedulable, bit1 = gang_dropped. The i16 narrowing is exact
    whenever every node index (and -1) fits, i.e. N < 2**15."""
    narrow = num_nodes < (1 << 15)

    def slim(assignment, unschedulable, gang_dropped):
        a = assignment.astype(jnp.int16) if narrow else assignment
        flags = unschedulable.astype(jnp.uint8) | (
            gang_dropped.astype(jnp.uint8) << 1
        )
        return a, flags

    return _jit(slim, "decision_slim", disc=f"narrow{int(narrow)}")


def build_multicycle_slim_fn(num_nodes: int):
    """Multi-cycle variant of the decision slimming: stacked [K, P]
    decisions in, (assignment i16|i32 [K, P], flags u8 [K, P],
    cycles_run i32) out. Flag bits: 0 = unschedulable, 1 = gang_dropped,
    2 = attempted (the pod was valid in that inner cycle — the host
    needs it to tell "not this cycle's pod" from "placed at node 0")."""
    narrow = num_nodes < (1 << 15)

    def slim(assignment, unschedulable, gang_dropped, attempted,
             cycles_run):
        a = assignment.astype(jnp.int16) if narrow else assignment
        flags = (
            unschedulable.astype(jnp.uint8)
            | (gang_dropped.astype(jnp.uint8) << 1)
            | (attempted.astype(jnp.uint8) << 2)
        )
        return a, flags, cycles_run

    return _jit(slim, "multicycle_slim", disc=f"narrow{int(narrow)}")


def build_multicycle_slim_rows_fn(num_nodes: int, k: int):
    """STREAMED variant of the multi-cycle decision slimming: the same
    i16|u8 diet, but split K ways so each inner cycle's row is its own
    fetchable device buffer — `(((a_0, flags_0), …, (a_{K-1},
    flags_{K-1})), cycles_run)` instead of one stacked [K, P] pair.
    MultiCycleHandle.decisions_row(i) then blocks on row i's transfer
    alone, so the apply loop can bind inner cycle i's winners while
    rows i+1…K-1 are still in flight (and, under depth-2 speculative
    dispatch, while the NEXT batch is still running on device). Flag
    bits match build_multicycle_slim_fn: 0 = unschedulable, 1 =
    gang_dropped, 2 = attempted."""
    narrow = num_nodes < (1 << 15)

    def slim(assignment, unschedulable, gang_dropped, attempted,
             cycles_run):
        a = assignment.astype(jnp.int16) if narrow else assignment
        flags = (
            unschedulable.astype(jnp.uint8)
            | (gang_dropped.astype(jnp.uint8) << 1)
            | (attempted.astype(jnp.uint8) << 2)
        )
        rows = tuple((a[i], flags[i]) for i in range(k))
        return rows, cycles_run

    return _jit(
        slim, "multicycle_slim_rows", disc=f"narrow{int(narrow)}|k{k}"
    )


def _cpu_safe_buffers(wbuf, bbuf):
    """Force a device copy of numpy packed buffers on the CPU backend.

    jax's CPU backend copies a jit's numpy arguments ASYNCHRONOUSLY on
    the dispatch thread (reproduced in PR 4's pure-jax repro), so a
    deferred program (diagnosis/preemption) still holding the host arena
    can race the NEXT encode's in-place rewrite and read a torn copy.
    The rig/TPU paths device_put explicitly and are unaffected; this is
    the explicit copy for drivers that skip device_put on CPU
    (K8S_TPU_NO_DEVICE_PUT=1, probes). A HOST-side np.copy is taken
    first: jax.device_put on the CPU backend may zero-copy alias an
    aligned numpy array, which would re-create exactly the aliasing
    this guard exists to break."""
    if isinstance(wbuf, np.ndarray) and jax.default_backend() == "cpu":
        return jax.device_put(wbuf.copy()), jax.device_put(bbuf.copy())
    return wbuf, bbuf


class CycleHandle:
    """One in-flight cycle: device-side futures plus the host-side fetch
    state. Created by ServingPipeline.dispatch(); the caller blocks only
    in decisions() (the slimmed fetch) — everything else resolves lazily."""

    def __init__(self, pipe, result, slim, wbuf, bbuf, stable, emask):
        self._pipe = pipe
        self.result = result  # CycleResult/CycleDecision device futures
        self._slim = slim  # (i16|i32 [P], u8 [P]) device futures
        self._wbuf = wbuf
        self._bbuf = bbuf
        self._stable = stable
        self._emask = emask
        self._decisions = None
        self._t_decisions = None
        self._diag = None
        self._pre = None
        self.fetched = False

    # ---- the one blocking fetch -----------------------------------------

    def decisions(self):
        """(assignment i32 [P], unschedulable bool [P], gang_dropped
        bool [P]) as numpy — blocks on the slimmed transfer only."""
        if self._decisions is None:
            now = self._pipe._now
            t0 = now()
            self._pipe.stats["t_decision_start"] = t0
            try:
                a, flags = self._pipe.fetch_decisions(
                    lambda: jax.device_get(self._slim)
                )
            except Exception as e:
                # a failed fetch consumes the cycle: no bind can come of
                # it, so the ordering guard must NOT hold the pipeline
                # hostage — the next dispatch proceeds against a cache
                # without this cycle's (never-issued) binds, which is
                # exactly what it would have read. Without this, one
                # transient device error would poison the memoized
                # pipeline's guard forever (permanent serving outage).
                # Attribution BEFORE the re-raise: a consumed cycle must
                # leave an on-box trace of WHY (events-ring entry +
                # scheduler_fetch_failures_total{class}).
                self._pipe.note_fetch_failure(e)
                self.fetched = True
                self.release()
                self._pipe._note_inflight()
                raise
            self._t_decisions = now()
            st = self._pipe.stats
            st["decision_wait_ms"] = (self._t_decisions - t0) * 1e3
            st["t_decision_end"] = self._t_decisions
            st["fetch_bytes"] = int(a.nbytes + flags.nbytes)
            # what the un-slimmed fetch of the same fields would move
            st["fetch_bytes_full"] = int(a.shape[0] * (4 + 1 + 1))
            self._pipe._fetch_bytes_total += st["fetch_bytes"]
            m = self._pipe._metrics
            if m is not None:
                m.cycle_duration.labels(phase="decision_fetch").observe(
                    self._t_decisions - t0
                )
                m.decision_fetch_bytes.inc(st["fetch_bytes"])
            self._decisions = (
                np.asarray(a, dtype=np.int32),
                (flags & 1) != 0,
                (flags & 2) != 0,
            )
            self.fetched = True
            self._pipe._note_inflight()
        return self._decisions

    # ---- deferred (off the bind path) -----------------------------------

    def dispatch_preemption(self):
        """Dispatch the preemption PostFilter program (non-blocking);
        returns its device-side result or None. Forcing it is the
        caller's choice — typically after winners were bound, so device
        preemption time overlaps the host bind loop."""
        if self._pre is None and self._pipe._preempt_fn is not None:
            self._pre = self._pipe._preempt_fn(
                self._wbuf, self._bbuf, self.result, self._stable
            )
        return self._pre

    def dispatch_diagnosis(self):
        """Dispatch the FailedScheduling diagnosis program (non-blocking);
        returns the device-side [P, F] handle or None when the pipeline
        has no diagnosis program."""
        if self._diag is None and self._pipe._diag_fn is not None:
            r = self.result
            # pv_claimed and emask are INDEPENDENT optionals — forwarded
            # by keyword so a latency cycle without pv_claimed still
            # carries the extender verdicts into attribution
            kw = {}
            pv = getattr(r, "pv_claimed", None)
            if pv is not None:
                kw["pv_claimed"] = pv
            if self._emask is not None:
                kw["emask"] = self._emask
            self._diag = self._pipe._diag_fn(
                self._wbuf, self._bbuf, self._stable,
                r.assignment, r.node_requested, **kw,
            )
            if self._pipe._donate_diagnosis:
                # the diagnosis program consumed (donated) the slot's
                # packed buffers — nothing may reference them again
                self._wbuf = self._bbuf = None
            if self._pipe.forced_sync:
                # strict sequential execution covers the deferred
                # programs too: block here (before the caller's bind
                # loop) and stamp availability now, so the flight
                # recorder's diag lane serializes instead of riding the
                # bind overlap
                jax.block_until_ready(self._diag)
                if self._t_decisions is not None:
                    t_done = self._pipe._now()
                    self._pipe.stats["diag_lag_ms"] = (
                        t_done - self._t_decisions
                    ) * 1e3
                    self._pipe.stats["t_diag_done"] = t_done
        return self._diag

    def reject_counts(self):
        """Force the diagnosis output (i32 [P, F]); returns None when no
        diagnosis program exists. Records the deferred-diagnosis lag —
        how long after the decision fetch the attribution became
        available (the window FailedScheduling events trail binds by)."""
        d = self.dispatch_diagnosis()
        if d is None:
            return None
        arr = np.asarray(d)
        if (
            self._t_decisions is not None
            and "t_diag_done" not in self._pipe.stats
        ):
            # first force stamps availability; a forced_sync
            # dispatch_diagnosis already did (earlier — see above)
            t_done = self._pipe._now()
            lag = (t_done - self._t_decisions) * 1e3
            self._pipe.stats["diag_lag_ms"] = lag
            self._pipe.stats["t_diag_done"] = t_done
        if self._t_decisions is not None:
            m = self._pipe._metrics
            if m is not None:
                m.cycle_duration.labels(phase="diag_lag").observe(
                    self._pipe.stats.get("diag_lag_ms", 0.0) / 1e3
                )
        return arr

    def reject_counts_matrix(self, n: int):
        """The per-plugin attribution as ONE forced [n, F] matrix: the
        vectorized apply fold reads whole columns (one counter inc per
        plugin across the cycle's losers) instead of re-entering the
        force per pod. Falls back to the fused program's in-result
        counts when no deferred diagnosis program exists."""
        rc = self.reject_counts()
        if rc is None:
            rc = np.asarray(self.result.reject_counts)
        return np.asarray(rc)[:n]

    def block(self):
        """Force everything in flight (the forced_sync escape hatch).
        Routed through the same bounded-fetch path as decisions(): at
        the ladder's forced_sync rung THIS is the serve loop's blocking
        wait, and without the watchdog a persistently hung tunnel would
        re-wedge the loop at exactly the rung meant to contain it (the
        next expiry then escalates to stateless/seal-for-failover)."""
        try:
            self._pipe.fetch_decisions(
                lambda: jax.block_until_ready((self.result, self._slim))
            )
        except Exception as e:
            # same contract as a failed decisions() fetch: the cycle is
            # consumed, the guard releases (see decisions) — and the
            # failure class is stamped before the re-raise
            self._pipe.note_fetch_failure(e)
            self.fetched = True
            self.release()
            self._pipe._note_inflight()
            raise
        return self

    def release(self):
        """Drop every device reference so the slot's arena blocks free
        (the allocator then recycles them for the next upload)."""
        self.result = self._slim = self._diag = self._pre = None
        self._wbuf = self._bbuf = self._stable = self._emask = None


class MultiCycleHandle:
    """One in-flight multi-cycle batch (K inner cycles dispatched as a
    single device program — core/cycle.build_packed_multicycle_fn).
    Mirrors CycleHandle's contract, streamed: the slimmed decision
    payload is split into per-inner-cycle fetchable rows
    (build_multicycle_slim_rows_fn), so `decisions_row(i)` blocks on
    row i's transfer alone and the apply loop binds cycle i's winners
    while later rows (and, under depth-2 speculation, the next batch)
    are still in flight. The handle counts as fetched — releasing the
    binds-fold ordering guard — once every LIVE row (`n_live`, the
    dispatched `n_cycles`) was fetched. The per-inner-cycle deferred
    programs (diagnosis, preemption) dispatch lazily against the
    stacked buffers' row i and the loop's post-cycle-i
    `node_requested`."""

    def __init__(
        self, pipe, result, slim, wbufs, bbufs, stable,
        n_live: int, speculative: bool = False,
    ):
        self._pipe = pipe
        self.result = result  # MultiCycleResult device futures
        # (((i16|i32 [P], u8 [P]) x K), i32) futures — per-row slimmed
        self._slim = slim
        self._wbufs = wbufs
        self._bbufs = bbufs
        self._stable = stable
        self.n_live = n_live
        self.speculative = speculative
        self._rows: dict[int, tuple] = {}
        self._cycles_run: "int | None" = None
        self._decisions = None
        self._t_decisions = None
        self._diag: dict[int, object] = {}
        self._pre: dict[int, object] = {}
        # inner cycle i -> (lag_s, t_done): deferred-diagnosis
        # availability, stamped at first force so the scheduler can put
        # diag_lag on inner-cycle flight records (stage_report is
        # snapshotted BEFORE the apply loop that forces these)
        self.diag_lag: dict[int, tuple[float, float]] = {}
        self.fetched = False

    def _consumed(self, e: BaseException) -> None:
        """A failed fetch consumes the batch: same contract as
        CycleHandle.decisions — the ordering guard releases, the
        failure class is stamped before the re-raise."""
        self._pipe.note_fetch_failure(e)
        self.fetched = True
        self.release()
        self._pipe._note_inflight()

    def decisions_row(self, i: int):
        """Inner cycle i's decisions as numpy — `(assignment i32 [P],
        unschedulable bool [P], gang_dropped bool [P], attempted bool
        [P])` — blocking on row i's slimmed transfer only. The first
        row fetched stamps `t_first_decision` (the scheduler's
        `first_bind` phase anchor); fetching every live row marks the
        handle consumed (ordering-guard release)."""
        hit = self._rows.get(i)
        if hit is not None:
            return hit
        now = self._pipe._now
        t0 = now()
        st = self._pipe.stats
        st.setdefault("t_decision_start", t0)
        try:
            a, flags = self._pipe.fetch_decisions(
                lambda: jax.device_get(self._slim[0][i])
            )
        except Exception as e:  # schedlint: disable=RB001 -- not swallowed: _consumed stamps the failure class (metric + events ring) before the re-raise — the consumed-cycle contract
            self._consumed(e)
            raise
        t1 = now()
        self._t_decisions = t1
        st["decision_wait_ms"] = (
            st.get("decision_wait_ms", 0.0) + (t1 - t0) * 1e3
        )
        st["t_decision_end"] = t1
        st.setdefault("t_first_decision", t1)
        if _spans.ARMED:
            # per-row decision window for the decision.row trace span
            # (scheduler._apply_mc_row reads it back by row index; a
            # plain-list key, so the stage report's t_*/"*_ms" copy
            # loops never see it and flight records stay unchanged)
            st.setdefault("decision_rows", []).append((i, t0, t1))
        nbytes = int(a.nbytes + flags.nbytes)
        st["fetch_bytes"] = st.get("fetch_bytes", 0) + nbytes
        self._pipe._fetch_bytes_total += nbytes
        m = self._pipe._metrics
        if m is not None:
            m.cycle_duration.labels(phase="decision_fetch").observe(
                t1 - t0
            )
            m.decision_fetch_bytes.inc(nbytes)
        row = (
            np.asarray(a, dtype=np.int32),
            (flags & 1) != 0,
            (flags & 2) != 0,
            (flags & 4) != 0,
        )
        self._rows[i] = row
        if len(self._rows) >= self.n_live and not self.fetched:
            self.fetched = True
            self._pipe._note_inflight()
        return row

    def cycles_run(self) -> int:
        """Inner cycles the device loop actually executed (blocks on
        the scalar transfer; ~free once the rows landed)."""
        if self._cycles_run is None:
            try:
                cr = self._pipe.fetch_decisions(
                    lambda: jax.device_get(self._slim[1])
                )
            except Exception as e:  # schedlint: disable=RB001 -- not swallowed: _consumed stamps the failure class (metric + events ring) before the re-raise
                self._consumed(e)
                raise
            self._cycles_run = int(cr)
        return self._cycles_run

    def decisions(self):
        """(assignment i32 [K, P], unschedulable bool [K, P],
        gang_dropped bool [K, P], attempted bool [K, P], cycles_run int)
        as numpy — the whole-batch fetch (every row + the scalar in one
        transfer). Kept for drivers that want the stacked shape; the
        streaming apply path uses decisions_row."""
        if self._decisions is None:
            now = self._pipe._now
            t0 = now()
            st = self._pipe.stats
            st.setdefault("t_decision_start", t0)
            try:
                rows, cycles_run = self._pipe.fetch_decisions(
                    lambda: jax.device_get(self._slim)
                )
            except Exception as e:  # schedlint: disable=RB001 -- not swallowed: _consumed stamps the failure class (metric + events ring) before the re-raise
                self._consumed(e)
                raise
            self._t_decisions = now()
            st["decision_wait_ms"] = (
                st.get("decision_wait_ms", 0.0)
                + (self._t_decisions - t0) * 1e3
            )
            st["t_decision_end"] = self._t_decisions
            st.setdefault("t_first_decision", self._t_decisions)
            nbytes = sum(
                int(r[0].nbytes + r[1].nbytes) for r in rows
            ) + 4
            a = np.stack([np.asarray(r[0], dtype=np.int32)
                          for r in rows])
            flags = np.stack([np.asarray(r[1]) for r in rows])
            st["fetch_bytes"] = st.get("fetch_bytes", 0) + nbytes
            self._pipe._fetch_bytes_total += nbytes
            m = self._pipe._metrics
            if m is not None:
                m.cycle_duration.labels(phase="decision_fetch").observe(
                    self._t_decisions - t0
                )
                m.decision_fetch_bytes.inc(nbytes)
            self._cycles_run = int(cycles_run)
            self._decisions = (
                a,
                (flags & 1) != 0,
                (flags & 2) != 0,
                (flags & 4) != 0,
                self._cycles_run,
            )
            self.fetched = True
            self._pipe._note_inflight()
        return self._decisions

    def _inner_decision(self, i: int) -> CycleDecision:
        """Inner cycle i's decision carry as the deferred programs'
        input: stacked row i plus the loop's POST-cycle-i state."""
        r = self.result
        return CycleDecision(
            assignment=r.assignment[i],
            node_requested=r.node_requested[i],
            unschedulable=r.unschedulable[i],
            gang_dropped=r.gang_dropped[i],
        )

    def dispatch_preemption(self, i: int):
        """Dispatch inner cycle i's preemption PostFilter (non-blocking);
        returns its device-side result or None. NOTE the documented
        multi-cycle deviation: candidates/victims are computed against
        the BATCH-start existing set — a pod bound by an earlier inner
        cycle is not yet evictable (it becomes so next batch)."""
        if i not in self._pre and self._pipe._preempt_fn is not None:
            self._pre[i] = self._pipe._preempt_fn(
                self._wbufs[i], self._bbufs[i],
                self._inner_decision(i), self._stable,
            )
        return self._pre.get(i)

    def dispatch_diagnosis(self, i: int):
        """Dispatch inner cycle i's FailedScheduling diagnosis program
        (non-blocking); returns the device-side [P, F] handle or None.
        Uses `pipe.multi_diag_fn` when set — the multi-cycle decisions
        are lean (no fused reject counts), so the scheduler installs a
        diagnosis program even for regimes whose single-cycle path runs
        the fused full program and needs none."""
        fn = self._pipe.multi_diag_fn or self._pipe._diag_fn
        if i not in self._diag and fn is not None:
            r = self.result
            self._diag[i] = fn(
                self._wbufs[i], self._bbufs[i], self._stable,
                r.assignment[i], r.node_requested[i],
            )
            if self._pipe.forced_sync:
                jax.block_until_ready(self._diag[i])
                self._stamp_diag_lag(i)
        return self._diag.get(i)

    def _stamp_diag_lag(self, i: int) -> None:
        if self._t_decisions is None or i in self.diag_lag:
            return
        t_done = self._pipe._now()
        lag_s = max(0.0, t_done - self._t_decisions)
        self.diag_lag[i] = (lag_s, t_done)
        m = self._pipe._metrics
        if m is not None:
            m.cycle_duration.labels(phase="diag_lag").observe(lag_s)

    def reject_counts(self, i: int):
        """Force inner cycle i's diagnosis output (i32 [P, F]); None
        when the pipeline has no diagnosis program. First force stamps
        the deferred-diagnosis lag for inner cycle i — how long after
        the batch's decision fetch the attribution became available."""
        d = self.dispatch_diagnosis(i)
        if d is None:
            return None
        arr = np.asarray(d)
        self._stamp_diag_lag(i)
        return arr

    def reject_counts_matrix(self, i: int, n: int):
        """Inner cycle i's per-plugin attribution as ONE forced [n, F]
        matrix (see CycleHandle.reject_counts_matrix — same one-force
        contract for the vectorized apply fold)."""
        return np.asarray(self.reject_counts(i))[:n]

    def block(self):
        """Force everything in flight (the forced_sync escape hatch);
        watchdog-bounded like CycleHandle.block."""
        try:
            self._pipe.fetch_decisions(
                lambda: jax.block_until_ready((self.result, self._slim))
            )
        except Exception as e:
            # consumed batch: guard releases, class stamped (see
            # CycleHandle.block)
            self._pipe.note_fetch_failure(e)
            self.fetched = True
            self.release()
            self._pipe._note_inflight()
            raise
        return self

    def release(self):
        self.result = self._slim = None
        self._wbufs = self._bbufs = self._stable = None
        self._diag = {}
        self._pre = {}
        self.diag_lag = {}


class ServingPipeline:
    """Owns the two upload slots, the in-flight handle, and the carry
    hand-off (CarryKeeper-compatible). One instance per compiled packed
    regime — the Scheduler memoizes it next to the programs.

    `cycle_fn` is any packed cycle program: carry-path
    (build_packed_cycle_carry_fn, with `keeper`), or plain packed
    (build_packed_cycle_fn, `keeper=None`). `diag_fn`/`preempt_fn` are
    the deferred companions (None disables them)."""

    def __init__(
        self,
        cycle_fn,
        *,
        keeper=None,
        diag_fn=None,
        preempt_fn=None,
        multi_fn=None,  # optional multi-cycle program
        # (build_packed_multicycle_fn) driving dispatch_multi; the
        # scheduler assigns it lazily (`pipe.multi_fn = ...`) when
        # multiCycleK > 1 and the workload is in the envelope
        forced_sync: bool = False,
        require_decision_fetch: bool = True,
        donate_diagnosis: bool = False,
        metrics=None,
        events=None,  # core/events.EventRecorder | None: fetch-failure
        # attribution stamps a system event on the ring before re-raise
        dispatch_deadline_s: float = 0.0,  # bound on the blocking
        # decision fetch (0 = unbounded); expiry raises
        # DispatchDeadlineExceeded via the _FetchWorker watchdog
        now=_time.perf_counter,
        slots: int = 2,
    ) -> None:
        if donate_diagnosis and preempt_fn is not None:
            # a donated diagnosis consumes the slot's packed buffers; a
            # preemption program dispatched after it would read freed
            # memory — refuse the combination instead of ordering traps
            raise ValueError(
                "donate_diagnosis requires preempt_fn=None "
                "(preemption reads the packed buffers after diagnosis)"
            )
        self._cycle_fn = cycle_fn
        self._keeper = keeper
        self._diag_fn = diag_fn
        self._preempt_fn = preempt_fn
        self.forced_sync = forced_sync
        self.require_decision_fetch = require_decision_fetch
        self._donate_diagnosis = donate_diagnosis
        self._metrics = metrics
        self._events = events
        self.dispatch_deadline_s = dispatch_deadline_s
        self._fetch_worker = _FetchWorker()  # no thread until first use
        self._now = now
        self._slots = [None] * max(2, slots)
        self._slim_fn = None
        self.multi_fn = multi_fn
        # multi-cycle diagnosis program (build_diagnosis_fn): the
        # scheduler installs it next to multi_fn; falls back to
        # _diag_fn (carry mode shares one) when None
        self.multi_diag_fn = None
        # continuation variant (build_packed_multicycle_fn carry_in):
        # consumes a predecessor batch's device-resident carry — the
        # program depth-2 speculative dispatches run on
        self.multi_cont_fn = None
        self._multi_slim_fn = None
        self._last = None
        # the one in-flight SPECULATIVE batch (depth 2: at most one),
        # pending adopt_speculative/abandon_speculative resolution
        self._spec: "MultiCycleHandle | None" = None
        # speculation ledger: outcomes of every speculative dispatch
        # (mirrored into scheduler_speculation_total{outcome})
        self.speculation = {
            "adopted": 0, "abandoned": 0, "redispatched": 0,
        }
        self._n = 0
        self._fetch_bytes_total = 0
        self._pending_encode_ms: float | None = None
        # per-cycle stage report (the split-phase measurement): refreshed
        # by dispatch()/decisions()/reject_counts(); encode_ms is fed by
        # the caller via note_encode()
        self.stats: dict[str, float] = {}

    @property
    def cycles(self) -> int:
        return self._n

    @property
    def fetch_bytes_total(self) -> int:
        return self._fetch_bytes_total

    def fetch_decisions(self, fn):
        """Run the one blocking device->host decision fetch with the
        fault hooks and (when `dispatch_deadline_s` > 0) the watchdog
        applied. `fetch_delay` sleeps OUTSIDE the bounded call (a slow
        tunnel: visible latency); `fetch_hang` sleeps INSIDE it (a
        wedged tunnel: exactly what the deadline bounds)."""
        if _faults.ARMED:
            _faults.sleep_point("fetch_delay")
            inner = fn

            def fn():
                _faults.sleep_point("fetch_hang")
                return inner()

        d = self.dispatch_deadline_s
        if d and d > 0:
            return self._fetch_worker.run(fn, d)
        return fn()

    def note_fetch_failure(self, e: BaseException) -> str:
        """Attribute a consumed cycle's fetch failure before it
        re-raises: `scheduler_fetch_failures_total{class}` + an
        events-ring entry. Returns the class (transport | corrupt |
        wedge | deadline | other). MUST NOT raise: it runs inside the
        failure handlers BEFORE the ordering-guard release — an
        attribution error that escaped would leave the guard held
        forever (the permanent-outage mode the release exists to
        prevent), so a broken metrics registry or events ring costs
        the trace, never the pipeline."""
        from .cycle import classify_failure

        cls = (
            "deadline" if isinstance(e, DispatchDeadlineExceeded)
            else classify_failure(e)
        )
        try:
            m = self._metrics
            if m is not None:
                m.fetch_failures.labels(cls).inc()
            ev = self._events
            if ev is not None:
                from .events import FETCH_FAILED

                ev.system(
                    FETCH_FAILED,
                    f"cycle decision fetch failed ({cls}): {e}"[:400],
                )
        except Exception:  # schedlint: disable=RB001 -- deliberately silent: the original error re-raises right after this call and carries the story; attribution must never hold the ordering guard hostage
            pass
        return cls

    def note_encode(self, seconds: float) -> None:
        """Record the host encode time of the snapshot about to be
        dispatched — feeds the overlap accounting in stage_report."""
        self._pending_encode_ms = seconds * 1e3

    def _claim_slot(self) -> int:
        """Claim the next upload slot, releasing its previous occupant's
        device references for arena reuse. Refuses to overwrite a slot
        whose batch was never fetched: under depth-2 speculation two
        batches are legitimately in flight, and silently releasing an
        unfetched handle would corrupt an in-flight upload — the
        slot-accounting invariant is that `slots >= in-flight + 1`
        (three slots for depth 2), enforced here loudly."""
        slot = self._n % len(self._slots)
        prev = self._slots[slot]
        if prev is not None:
            if not prev.fetched and self.require_decision_fetch:
                # fold-free drivers (require_decision_fetch=False) opted
                # out of the ordering guard and may legitimately leave
                # handles unfetched — they keep the silent release
                raise RuntimeError(
                    f"ServingPipeline: upload slot {slot} still holds "
                    "an unfetched in-flight batch — dispatch depth "
                    f"exceeds the {len(self._slots)}-slot arena "
                    "(speculative depth-2 needs slots=3)"
                )
            # release the old occupant's device references BEFORE
            # uploading so the allocator hands back the same-sized
            # blocks (buffered arena reuse instead of per-cycle growth)
            prev.release()
        return slot

    def _speculation_outcome(self, outcome: str) -> None:
        self.speculation[outcome] += 1
        m = self._metrics
        counter = getattr(m, "speculation", None) if m else None
        if counter is not None:
            counter.labels(outcome=outcome).inc()

    def adopt_speculative(self) -> "MultiCycleHandle":
        """The host fold of the predecessor batch matched the
        speculation's predicate: the in-flight speculative batch
        becomes the current one (zero added latency — it has been on
        device the whole time) and the ordering guard resumes guarding
        it like any adopted dispatch."""
        h = self._spec
        if h is None:
            raise RuntimeError("adopt_speculative: no speculation in flight")
        self._spec = None
        self._last = h
        # the adopted batch's dispatch marks become the current stage
        # report (its rows' fetch stats land on top as they stream in)
        self.stats = dict(getattr(h, "_stats_seed", {}))
        self._speculation_outcome("adopted")
        return h

    def abandon_speculative(self) -> None:
        """The host fold diverged from the speculation's predicate (or
        the predecessor batch failed outright): drop the in-flight
        speculative batch — its results are never observed — and free
        its arena slot. The caller re-dispatches against the true
        carry (note_redispatch) or requeues. Idempotent/no-op when no
        speculation is in flight, so failure paths can call it
        unconditionally without leaking a slot."""
        h = self._spec
        if h is None:
            return
        self._spec = None
        h.fetched = True  # consumed-without-observation: guard releases
        h.release()
        for i, s in enumerate(self._slots):
            if s is h:
                self._slots[i] = None
        self._speculation_outcome("abandoned")
        self._note_inflight()

    def note_redispatch(self) -> None:
        """Ledger mark: an abandoned speculation's groups were
        re-dispatched against the true carry."""
        self._speculation_outcome("redispatched")

    def dispatch(
        self,
        wbuf,
        bbuf,
        stable,
        *,
        dirty=None,
        carry_key=None,
        pin=None,
        emask=None,
        escore=None,
        device_put: bool = True,
    ) -> CycleHandle:
        """Upload + dispatch one cycle; returns immediately with a
        CycleHandle (unless forced_sync). Raises if the previous cycle's
        decisions were never fetched while require_decision_fetch — the
        strict-ordering guard (see module docstring)."""
        if self._spec is not None:
            raise RuntimeError(
                "ServingPipeline: dispatch with an unresolved "
                "speculative batch in flight — adopt_speculative() or "
                "abandon_speculative() first"
            )
        if (
            self.require_decision_fetch
            and self._last is not None
            and not self._last.fetched
        ):
            raise RuntimeError(
                "ServingPipeline: cycle k+1 dispatched before cycle k's "
                "decisions were fetched — binds cannot have folded before "
                "this snapshot was encoded (pass "
                "require_decision_fetch=False for fold-free loops)"
            )
        t0 = self._now()
        slot = self._claim_slot()
        if device_put:
            wbuf = jax.device_put(wbuf)
            bbuf = jax.device_put(bbuf)
        else:
            # CPU backend: numpy arena buffers must not feed async
            # dispatch directly — the deferred diagnosis/preemption
            # programs would race the next encode's arena rewrite
            # (see _cpu_safe_buffers)
            wbuf, bbuf = _cpu_safe_buffers(wbuf, bbuf)
        if self._keeper is not None:
            carry = self._keeper.state(
                wbuf, bbuf, stable, dirty, carry_key, pin=pin
            )
            if emask is not None:
                result = self._cycle_fn(
                    wbuf, bbuf, stable, carry, emask, escore
                )
            else:
                result = self._cycle_fn(wbuf, bbuf, stable, carry)
        else:
            result = self._cycle_fn(wbuf, bbuf, stable)
        if self._slim_fn is None:
            self._slim_fn = build_decision_slim_fn(
                result.node_requested.shape[0]
            )
        slim = self._slim_fn(
            result.assignment, result.unschedulable, result.gang_dropped
        )
        handle = CycleHandle(
            self, result, slim, wbuf, bbuf, stable, emask
        )
        self._slots[slot] = handle
        self._last = handle
        self._n += 1
        t1 = self._now()
        dispatch_s = t1 - t0
        # absolute marks (pipeline clock = perf_counter) feed the flight
        # recorder's per-cycle trace lanes (core/flight_recorder.py)
        self.stats = {
            "dispatch_ms": dispatch_s * 1e3,
            "slot": slot,
            "t_dispatch_start": t0,
            "t_dispatch_end": t1,
        }
        if self._pending_encode_ms is not None:
            self.stats["encode_ms"] = self._pending_encode_ms
            self._pending_encode_ms = None
        if self._metrics is not None:
            self._metrics.cycle_duration.labels(phase="dispatch").observe(
                dispatch_s
            )
        self._note_inflight()
        if self.forced_sync:
            handle.block()
            # sequential execution hides nothing: the device time sits
            # inside dispatch_ms here, so the conservative
            # encode-vs-decision-wait estimate would misread the tiny
            # post-block fetch as "encode fully hidden" — pin it to 0
            self.stats["encode_hidden_ms"] = 0.0
        return handle

    def dispatch_multi(
        self,
        wbufs,
        bbufs,
        stable,
        n_cycles: int,
        *,
        device_put: bool = True,
        carry0=None,
        speculative: bool = False,
    ) -> MultiCycleHandle:
        """Upload + dispatch one MULTI-CYCLE batch (stacked [K, ...]
        packed snapshots, one device dispatch for up to `n_cycles` inner
        cycles — see build_packed_multicycle_fn). Shares the single-
        dispatch ordering guard: a batch counts as the in-flight cycle,
        so the next dispatch (single or multi) is refused until the
        batch's decisions were fetched — binds-fold ordering holds
        across the batch boundary exactly as it does between single
        cycles.

        `speculative=True` is the depth-2 relaxation: the batch may be
        dispatched while its predecessor is still unfetched (the guard
        becomes "binds fold before the next ADOPTED encode" — module
        docstring). The handle is held aside until the caller resolves
        it via adopt_speculative()/abandon_speculative(); at most one
        speculation is in flight. `carry0 = (carry_node_requested,
        carry_gplaced)` chains the predecessor's device-resident final
        carry into this batch through `multi_cont_fn` (the carry_in
        continuation program) — no host round trip."""
        fn = self.multi_fn
        if carry0 is not None:
            fn = self.multi_cont_fn
            if fn is None:
                raise RuntimeError(
                    "ServingPipeline.dispatch_multi: carry0 given but "
                    "no continuation program (assign pipe.multi_cont_fn"
                    " = build_packed_multicycle_fn(..., carry_in=True))"
                )
        if fn is None:
            raise RuntimeError(
                "ServingPipeline.dispatch_multi: no multi-cycle program "
                "(assign pipe.multi_fn = build_packed_multicycle_fn(...))"
            )
        if self._spec is not None:
            raise RuntimeError(
                "ServingPipeline: dispatch_multi with an unresolved "
                "speculative batch in flight — adopt_speculative() or "
                "abandon_speculative() first"
            )
        if (
            not speculative
            and self.require_decision_fetch
            and self._last is not None
            and not self._last.fetched
        ):
            raise RuntimeError(
                "ServingPipeline: multi-cycle batch dispatched before "
                "the previous cycle's decisions were fetched — binds "
                "cannot have folded before this batch was encoded "
                "(speculative=True is the sanctioned depth-2 path)"
            )
        t0 = self._now()
        slot = self._claim_slot()
        if device_put:
            wbufs = jax.device_put(wbufs)
            bbufs = jax.device_put(bbufs)
        else:
            wbufs, bbufs = _cpu_safe_buffers(wbufs, bbufs)
        if carry0 is not None:
            result = fn(
                wbufs, bbufs, stable, np.int32(n_cycles), *carry0
            )
        else:
            result = fn(wbufs, bbufs, stable, np.int32(n_cycles))
        if self._multi_slim_fn is None:
            self._multi_slim_fn = build_multicycle_slim_rows_fn(
                result.node_requested.shape[1],
                result.assignment.shape[0],
            )
        slim = self._multi_slim_fn(
            result.assignment, result.unschedulable,
            result.gang_dropped, result.attempted, result.cycles_run,
        )
        handle = MultiCycleHandle(
            self, result, slim, wbufs, bbufs, stable,
            n_live=n_cycles, speculative=speculative,
        )
        self._slots[slot] = handle
        if speculative:
            self._spec = handle
        else:
            self._last = handle
        self._n += 1
        t1 = self._now()
        stats = {
            "dispatch_ms": (t1 - t0) * 1e3,
            "slot": slot,
            "multi_cycles": n_cycles,
            "t_dispatch_start": t0,
            "t_dispatch_end": t1,
        }
        if self._pending_encode_ms is not None:
            stats["encode_ms"] = self._pending_encode_ms
            self._pending_encode_ms = None
        if speculative:
            # a speculative dispatch must not clobber the in-flight
            # batch's stage report: its marks are held on the handle
            # and installed by adopt_speculative — the predecessor's
            # stats only note that a speculation was dispatched in its
            # shadow
            handle._stats_seed = stats
            self.stats["spec_dispatch_ms"] = stats["dispatch_ms"]
        else:
            self.stats = stats
        if self._metrics is not None:
            self._metrics.cycle_duration.labels(phase="dispatch").observe(
                t1 - t0
            )
        self._note_inflight()
        if self.forced_sync and not speculative:
            handle.block()
            self.stats["encode_hidden_ms"] = 0.0
        return handle

    def inflight(self) -> int:
        """Dispatched cycles whose decisions were not fetched yet (0 or
        1 under the strict-ordering guard; up to 2 while a depth-2
        speculative batch is in flight)."""
        return sum(
            1 for h in self._slots if h is not None and not h.fetched
        )

    def _note_inflight(self) -> None:
        g = getattr(self._metrics, "cycle_inflight", None)
        if g is not None:
            g.set(self.inflight())

    def stage_report(self) -> dict[str, float]:
        """Last-cycle per-stage breakdown: dispatch_ms, decision_wait_ms,
        fetch_bytes (+ the full-payload bytes it replaced), diag_lag_ms,
        encode_ms, and encode_hidden_ms — the portion of the reported
        encode that overlapped in-flight device work (encode minus the
        observed decision wait shortfall is not derivable per-cycle, so
        hidden = max(0, encode - decision_wait) is the conservative
        per-cycle estimate; the probe/bench compute the exact overlap
        from separated encode/device baselines)."""
        st = dict(self.stats)
        if "encode_hidden_ms" not in st:  # forced_sync pre-pins it to 0
            enc = st.get("encode_ms", 0.0)
            wait = st.get("decision_wait_ms", 0.0)
            st["encode_hidden_ms"] = max(0.0, enc - wait)
        return st
