"""Persistent compiled-program cache + speculative pre-compilation.

Every BENCH_r05 config pays 8.8-16.8 s of `compile_seconds` per program,
and a pad-regime flip re-pays it mid-serve (historically up to ~100 s,
or a backend wedge — ISSUE 5). Three layers attack that cost:

- **`CompileCache`** — an on-disk executable store under
  `<state-dir>/compile_cache/` (PR 3's durable-state directory; a
  standalone `compileCacheDir` works without durability). Programs are
  AOT-compiled (`fn.lower(...).compile()`) and serialized via
  `jax.experimental.serialize_executable`; entries are CRC-framed
  (magic + version + meta + payload + CRC32, written tmp+fsync+rename
  like PR 3 snapshots, so a concurrent warm-thread + serve-loop build
  of the same key leaves exactly one intact entry). A corrupt,
  truncated, or version-mismatched entry is REFUSED LOUDLY and the
  program recompiles — the cache can cost a compile, never a crash.
  Where the PJRT backend cannot serialize executables, the cache
  degrades to JAX's own persistent compilation-cache directory
  (utils/compilation_cache.py), pointed inside the same tree.

- **Cache keys** — `models/packing.shape_signature(spec)` (the named
  pad regime: every SIGNATURE_DIMS dimension) + a hash of the full
  `spec.key()` + profile + program kind (cycle / stable / preempt /
  diag / carry_init / carry_update / multicycle-K) + the program's
  deterministic build name + the jax/jaxlib/backend fingerprint. The
  literal `SIG_KEY_FIELDS`/`EXTRA_KEY_FIELDS` inventories below are
  machine-checked by schedlint ID006 against packing.SIGNATURE_DIMS and
  the README key table: a new pad dimension added without a cache-key
  field would silently alias distinct programs.

- **`CompileWarmer`** — a lazy daemon thread the scheduler feeds
  speculative build jobs (never the bind path): when the sentinel's
  per-profile demand EWMA (core/observe.py) drifts toward a pad-bucket
  boundary, the ADJACENT regime's spec is derived by `packing.respec`
  and its programs are pre-built into the scheduler's `_packed`/
  `_mc_fns` memos and this disk cache. A flip that speculation won then
  stamps `regime_flip` with `compile_ms~=0` and
  `compile_source="speculative"`.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import queue as _queue
import struct
import threading
import time as _time
import zlib
from typing import Any, Callable

from . import faults as _faults

log = logging.getLogger("k8s_scheduler_tpu.compile_cache")

_MAGIC = b"KSCC"
_VERSION = 1

# The cache-key inventory, pinned by schedlint ID006: SIG_KEY_FIELDS
# must equal the dimension names of models/packing.SIGNATURE_DIMS (a
# pad dimension without a key field would alias distinct programs into
# one entry), and every field of both tuples must appear in the README
# "## Compile-regime management" key table.
SIG_KEY_FIELDS = ("P", "N", "E", "MPN", "MA", "MC")
EXTRA_KEY_FIELDS = (
    "spec", "profile", "kind", "program", "mesh", "fingerprint",
)


def backend_fingerprint() -> str:
    """jax/jaxlib/backend identity an executable is only valid under.
    A mismatch is a MISS (the key embeds this), never a crash — a
    jaxlib upgrade or a CPU<->TPU move recompiles from scratch."""
    import jax
    import jaxlib

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # schedlint: disable=RB001 -- benign default: an
        # uninitializable backend still gets a usable fingerprint, and
        # the compile that follows will raise its own (louder) error
        kind = "unknown"
    return (
        f"jax{jax.__version__}-jaxlib{jaxlib.__version__}-"
        f"{jax.default_backend()}-{kind}"
    )


def program_name(fn) -> str:
    """The deterministic build name of a `_jit`-built program (the
    `_unique` base+discriminator-hash name — stable across restarts)."""
    inner = getattr(fn, "_fn", fn)
    return getattr(inner, "__name__", "anon")


class CacheKey:
    """One program's cache identity: the human-readable key string
    (stored inside the entry and verified on load) plus the filename
    stem (kind + a hash of the full key)."""

    __slots__ = ("text", "name")

    def __init__(self, text: str, kind: str) -> None:
        self.text = text
        digest = hashlib.sha256(text.encode()).hexdigest()[:24]
        self.name = f"{kind}-{digest}.kscc"

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"CacheKey({self.name}: {self.text})"


def cache_key(
    spec, profile: str, kind: str, program: str,
    fingerprint: str | None = None,
    mesh: str = "none",
) -> CacheKey:
    """Build the key for one (regime, profile, program kind) triple.
    Iterates the literal key-field inventories above so the key string
    and the documented key table cannot structurally diverge.

    `mesh` is the sharding descriptor of the call's argument layout
    (see `_args_mesh_desc`): an executable compiled against sharded
    buffers partitions its kernels and is NOT interchangeable with the
    single-device build of the same regime — without this field the
    two would alias one entry and a sharded load could serve the
    unsharded program (or vice versa). Mesh-closure programs (the
    carry cycle built with `mesh=`) additionally carry the mesh in
    their deterministic program NAME, so both routes stay distinct."""
    from ..models.packing import shape_signature

    sig = dict(shape_signature(spec))
    parts = [f"{d}{sig.get(d, 0)}" for d in SIG_KEY_FIELDS]
    extra = {
        "spec": hashlib.sha256(
            repr(spec.key()).encode()
        ).hexdigest()[:16],
        "profile": profile,
        "kind": kind,
        "program": program,
        "mesh": mesh,
        "fingerprint": fingerprint or backend_fingerprint(),
    }
    parts += [f"{f}={extra[f]}" for f in EXTRA_KEY_FIELDS]
    return CacheKey("|".join(parts), kind)


class CompileCache:
    """The on-disk executable store. Thread-safe: `load`/`store` may be
    called concurrently from the serve loop and the warm thread (writes
    are tmp+fsync+rename; the last same-key writer wins whole)."""

    def __init__(self, directory: str, metrics=None) -> None:
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._metrics = metrics
        self._fingerprint = backend_fingerprint()
        # in-memory tallies (the bench and /debug read these; the
        # prometheus families mirror them when metrics is wired).
        # load_seconds is a BOUNDED window — a long-lived scheduler
        # whose regime churn outruns the program memos reloads entries
        # indefinitely, and an unbounded list would grow (and be
        # re-sorted per /debug/state scrape) forever
        self.hits = 0
        self.misses = 0
        self.load_seconds: "collections.deque[float]" = (
            collections.deque(maxlen=256)
        )
        self.serialize_unsupported = False
        # fallback for backends without executable serialization: JAX's
        # own persistent compilation cache, pointed inside this tree so
        # the state-dir lifecycle covers it too. Only when the process
        # has no cache dir yet — the CLI and the test conftest configure
        # a process-wide one at startup, and re-pointing it at every
        # Scheduler construction would cold-start the shared cache.
        try:
            import jax

            if not getattr(
                jax.config, "jax_compilation_cache_dir", None
            ):
                from ..utils.compilation_cache import (
                    enable_compilation_cache,
                )

                enable_compilation_cache(os.path.join(directory, "xla"))
        except Exception as e:  # pragma: no cover — defensive
            log.warning("compile cache: XLA-dir fallback unavailable: %s", e)

    # ---- entry framing ---------------------------------------------------

    def _path(self, key: CacheKey) -> str:
        return os.path.join(self.dir, key.name)

    def load(self, key: CacheKey) -> bytes | None:
        """The validated payload for `key`, or None (miss). Any framing
        violation — truncation, bit flips, a future format version, a
        key/fingerprint mismatch — logs loudly and reports a miss; the
        caller recompiles and overwrites the bad entry."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            log.error("compile cache: cannot read %s: %s", path, e)
            return None
        head = len(_MAGIC) + 8
        if len(blob) < head + 4 or blob[: len(_MAGIC)] != _MAGIC:
            log.error(
                "compile cache: REFUSING %s: bad magic/truncated header "
                "(%d bytes) — recompiling", key.name, len(blob),
            )
            return None
        version, meta_len = struct.unpack_from("<II", blob, len(_MAGIC))
        if version != _VERSION:
            log.error(
                "compile cache: REFUSING %s: format version %d (this "
                "build writes %d) — recompiling", key.name, version,
                _VERSION,
            )
            return None
        body = blob[head:-4]
        (crc,) = struct.unpack_from("<I", blob, len(blob) - 4)
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            log.error(
                "compile cache: REFUSING %s: CRC mismatch (truncated or "
                "bit-flipped entry) — recompiling", key.name,
            )
            return None
        if meta_len > len(body):
            log.error(
                "compile cache: REFUSING %s: meta length %d exceeds "
                "body — recompiling", key.name, meta_len,
            )
            return None
        try:
            meta = json.loads(body[:meta_len].decode())
        except ValueError:
            log.error(
                "compile cache: REFUSING %s: unparseable meta — "
                "recompiling", key.name,
            )
            return None
        if meta.get("key") != key.text:
            log.error(
                "compile cache: REFUSING %s: key mismatch (hash "
                "collision or stale rename) — recompiling", key.name,
            )
            return None
        if meta.get("fingerprint") != self._fingerprint:
            # defense in depth: the fingerprint is part of the key (and
            # so of the filename), so this is a miss, not corruption
            log.warning(
                "compile cache: %s was built under %r, this process is "
                "%r — miss", key.name, meta.get("fingerprint"),
                self._fingerprint,
            )
            return None
        return body[meta_len:]

    def store(
        self, key: CacheKey, payload: bytes, build_seconds: float = 0.0
    ) -> bool:
        """Atomically write one entry: tmp file (unique per writer) +
        fsync + rename, exactly the PR 3 snapshot discipline — a torn
        write can never be observed, and concurrent same-key writers
        each land a whole entry (last rename wins)."""
        meta = json.dumps({
            "key": key.text,
            "fingerprint": self._fingerprint,
            "build_seconds": round(build_seconds, 3),
            "built_wall": _time.time(),
            "payload_bytes": len(payload),
        }).encode()
        body = meta + payload
        blob = (
            _MAGIC
            + struct.pack("<II", _VERSION, len(meta))
            + body
            + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)
        )
        tmp = os.path.join(
            self.dir,
            f".{key.name}.tmp.{os.getpid()}.{threading.get_ident()}",
        )
        try:
            if _faults.ARMED:
                # `cache_enospc` raises here (caught by the OSError
                # handler below — a refused store, never a crash);
                # `cache_torn` lands a TRUNCATED entry at the FINAL
                # path, as if a rename survived a crash its data did
                # not — load() must refuse it and recompile
                _faults.raise_enospc("cache_enospc")
                if _faults.torn_store():
                    with open(self._path(key), "wb") as f:
                        f.write(blob[: max(len(blob) // 2, 1)])
                    log.error(
                        "compile cache: fault-injected torn write of "
                        "%s", key.name,
                    )
                    return False
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(key))
            return True
        except OSError as e:
            log.error("compile cache: cannot store %s: %s", key.name, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    # ---- bookkeeping -----------------------------------------------------

    def note_hit(self, seconds: float) -> None:
        self.hits += 1
        self.load_seconds.append(seconds)
        m = self._metrics
        if m is not None:
            m.compile_cache_hits.inc()
            m.compile_cache_loads.observe(seconds)

    def note_miss(self) -> None:
        self.misses += 1
        m = self._metrics
        if m is not None:
            m.compile_cache_misses.inc()

    def note_unsupported(self, err: BaseException) -> None:
        if not self.serialize_unsupported:
            self.serialize_unsupported = True
            log.warning(
                "compile cache: this backend cannot serialize "
                "executables (%s); falling back to the JAX persistent "
                "compilation-cache directory under %s", err,
                os.path.join(self.dir, "xla"),
            )

    def status(self) -> dict:
        """The /debug/state enrichment + bench artifact fields."""
        loads = sorted(self.load_seconds)
        return {
            "dir": self.dir,
            "fingerprint": self._fingerprint,
            "hits": self.hits,
            "misses": self.misses,
            "entries": sum(
                1 for n in os.listdir(self.dir) if n.endswith(".kscc")
            ) if os.path.isdir(self.dir) else 0,
            "serialize_unsupported": self.serialize_unsupported,
            "load_p50_s": round(loads[len(loads) // 2], 4) if loads else 0.0,
            "load_max_s": round(loads[-1], 4) if loads else 0.0,
        }


# Process-level memo of loaded executables: (entry name, payload sha)
# -> Compiled. One deserialize per entry per process — repeated
# same-process deserialization of one entry is both wasted work and,
# on this jaxlib's CPU backend, occasionally fails with "Symbols not
# found" (observed on the third load of a large carry-cycle executable;
# the first load is reliable). A REAL warm restart is a new process, so
# this memo never weakens the restart story; it makes in-process
# re-opens (standby handover in one test process, bench drives) cheap
# and deterministic. Bounded FIFO — executables are small host objects
# and the live ones are pinned by the scheduler's program memos anyway.
_LOADED_LOCK = threading.Lock()
_LOADED: dict = {}
_LOADED_CAP = 64

# Serializes the jax_enable_compilation_cache toggle around native
# AOT compiles (see load_or_compile): the flag is PROCESS-GLOBAL, and
# an unsynchronized read/toggle/restore between the serve loop and the
# warm thread could let one builder compile WITH the XLA cache enabled
# (storing the symbol-less corrupt payload the bypass exists to avoid)
# and then restore a stale False, disabling the cache for the rest of
# the process.
_NATIVE_COMPILE_LOCK = threading.Lock()


def clear_loaded_memo() -> None:
    """Tests only: force the next load to really deserialize."""
    with _LOADED_LOCK:
        _LOADED.clear()


def _avals_digest(args: tuple, kwargs: dict) -> str:
    """Deterministic digest of a call convention (aval shapes/dtypes +
    pytree structure). Part of the key's `program` field: one program
    object can be called under more than one convention (the diagnosis
    program with/without `pv_claimed`; the preemption program fed a
    CycleResult vs a CycleDecision), and each convention is a distinct
    executable — sharing a key would load the wrong one."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = repr([
        (tuple(getattr(v, "shape", ()) or ()),
         str(getattr(v, "dtype", type(v).__name__)))
        for v in leaves
    ]) + repr(treedef)
    return hashlib.sha256(sig.encode()).hexdigest()[:12]


def _args_mesh_desc(args: tuple, kwargs: dict) -> str:
    """Sharding descriptor of a call's argument layout: "none" when
    every leaf is unsharded/single-device, else a short digest over the
    sorted set of (mesh shape, partition spec) pairs. Feeds the cache
    key's `mesh` field so sharded and unsharded builds of one program
    never alias a persistent entry."""
    import jax

    leaves, _treedef = jax.tree_util.tree_flatten((args, kwargs))
    parts: set[str] = set()
    for v in leaves:
        sh = getattr(v, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is None:
            continue
        try:
            shape = tuple(mesh.shape.items())
        except Exception:  # schedlint: disable=RB001 -- accounting
            # only: an exotic sharding without a dict-shaped mesh just
            # stays out of the descriptor (the program name still
            # disambiguates mesh-closure builds)
            continue
        if all(s == 1 for _a, s in shape):
            continue  # a 1-device mesh is the unsharded layout
        parts.add(f"{shape}|{getattr(sh, 'spec', None)!r}")
    if not parts:
        return "none"
    return hashlib.sha256(
        "||".join(sorted(parts)).encode()
    ).hexdigest()[:10]


def _compile_natively(low):
    """Compile a Lowered with JAX's persistent compilation cache truly
    OUT of the loop. Toggling `jax_enable_compilation_cache` alone is
    not enough: `compilation_cache.is_cache_used()` memoizes its
    decision process-globally on the FIRST compile, so in any process
    that already compiled with the cache enabled the flag is dead — and
    a compile that LOADS from that cache returns an executable whose
    serialize() emits a symbol-less payload (the corruption this whole
    path exists to avoid; only programs over the cache's
    min_compile_time ever land there, which is why exactly the largest
    program's entry went bad). `reset_cache()` drops the memo so the
    disabled flag is actually consulted; a second reset afterwards lets
    the next ordinary jit compile re-evaluate with the restored flag.
    Caller holds _NATIVE_COMPILE_LOCK."""
    import jax

    try:
        from jax._src import compilation_cache as _jcc
    except Exception:  # pragma: no cover — jax internals moved  # schedlint: disable=RB001 -- degraded-but-correct: without the internal module the flag toggle still applies
        _jcc = None
    prev = jax.config.jax_enable_compilation_cache
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        if _jcc is not None:
            try:
                _jcc.reset_cache()
            except Exception:  # pragma: no cover  # schedlint: disable=RB001 -- best-effort memo drop; the verification deserialize downstream catches a poison build
                pass
        return low.compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        if _jcc is not None:
            try:
                _jcc.reset_cache()
            except Exception:  # pragma: no cover  # schedlint: disable=RB001 -- best-effort memo drop on the restore side
                pass


def load_or_compile(
    fn,
    cache: CompileCache | None,
    spec,
    profile: str,
    kind: str,
    args: tuple = (),
    kwargs: dict | None = None,
) -> tuple[Any, str, float, Any]:
    """AOT-compile `fn` (a `_jit`-built program) for the exact
    `args`/`kwargs` avals, loading the serialized executable from
    `cache` when a valid entry exists.

    Returns `(compiled_or_None, source, seconds, out_sds)` with source
    one of "cache" (deserialized from disk), "cold" (compiled here), or
    "unsupported" (this program cannot be AOT-handled — caller keeps the
    plain jit path); `out_sds` is the output aval pytree (for chaining
    downstream programs' argument avals), or None when lowering failed.
    The in_tree/out_tree a deserialize needs are not serializable, so a
    load still TRACES the program (`fn.lower`) — sub-second — and skips
    only the XLA compile (the 8.8-16.8 s part)."""
    import jax
    from jax.experimental import serialize_executable as _se

    kwargs = kwargs or {}
    key = cache_key(
        spec, profile, kind,
        f"{program_name(fn)}+{_avals_digest(args, kwargs)}",
        mesh=_args_mesh_desc(args, kwargs),
    )
    t0 = _time.perf_counter()
    try:
        low = fn.lower(*args, **kwargs)
    except Exception as e:
        log.warning(
            "compile cache: cannot lower %s (%s); keeping the jit path",
            key.name, e,
        )
        return None, "unsupported", 0.0, None
    out_sds = jax.tree_util.tree_map(
        lambda o: jax.ShapeDtypeStruct(o.shape, o.dtype), low.out_info
    )
    payload = cache.load(key) if cache is not None else None
    if payload is not None:
        memo_key = (key.name, hashlib.sha256(payload).hexdigest())
        with _LOADED_LOCK:
            compiled = _LOADED.get(memo_key)
        if compiled is not None:
            dt = _time.perf_counter() - t0
            cache.note_hit(dt)
            return compiled, "cache", dt, out_sds
        try:
            _flat, in_tree = jax.tree_util.tree_flatten(low.args_info)
            compiled = _se.deserialize_and_load(
                payload, in_tree, low.out_tree
            )
            dt = _time.perf_counter() - t0
            cache.note_hit(dt)
            with _LOADED_LOCK:
                _LOADED[memo_key] = compiled
                while len(_LOADED) > _LOADED_CAP:
                    _LOADED.pop(next(iter(_LOADED)))
            return compiled, "cache", dt, out_sds
        except Exception as e:
            log.error(
                "compile cache: entry %s failed to deserialize (%s); "
                "recompiling", key.name, e,
            )
    will_store = cache is not None and not cache.serialize_unsupported
    try:
        if will_store:
            # compile NATIVELY, bypassing JAX's persistent XLA cache
            # for this one build: serialize() of an executable that
            # compile() loaded from that cache emits a payload missing
            # its symbol definitions ("Symbols not found" on a later
            # deserialize — reproduced: the cache-loaded build's
            # payload is ~half the size of the native one). Our own
            # entry IS the persistent layer here, so the XLA-cache
            # bypass costs one native compile exactly where we are
            # about to make it durable ourselves.
            with _NATIVE_COMPILE_LOCK:
                compiled = _compile_natively(low)
        else:
            compiled = low.compile()
    except Exception as e:
        log.warning(
            "compile cache: AOT compile of %s failed (%s); keeping the "
            "jit path", key.name, e,
        )
        return None, "unsupported", 0.0, out_sds
    dt = _time.perf_counter() - t0
    if cache is not None:
        cache.note_miss()
    if will_store:
        try:
            data, _in_tree, _out_tree = _se.serialize(compiled)
        except Exception as e:
            cache.note_unsupported(e)
            return compiled, "cold", dt, out_sds
        # verify BEFORE persisting: a payload that cannot deserialize
        # (defense in depth against serialize-of-a-cache-loaded
        # executable sneaking past _compile_natively) must never become
        # a poison entry that every later restart trips over loudly
        try:
            _flat, in_tree = jax.tree_util.tree_flatten(low.args_info)
            _se.deserialize_and_load(data, in_tree, low.out_tree)
        except Exception as e:
            log.error(
                "compile cache: NOT storing %s — freshly serialized "
                "payload failed its verification deserialize (%s); "
                "the in-process executable still serves", key.name,
                str(e)[:200],
            )
            return compiled, "cold", dt, out_sds
        if cache.store(key, data, build_seconds=dt):
            # later same-process loads of this entry reuse the
            # executable we just compiled instead of deserializing
            memo_key = (
                key.name, hashlib.sha256(data).hexdigest()
            )
            with _LOADED_LOCK:
                _LOADED[memo_key] = compiled
                while len(_LOADED) > _LOADED_CAP:
                    _LOADED.pop(next(iter(_LOADED)))
    return compiled, "cold", dt, out_sds


class CompileWarmer:
    """The speculative-precompilation thread: a queue of build thunks,
    drained by one lazy daemon thread so a build NEVER runs on the
    scheduling loop. Jobs are deduplicated by key while queued or
    running (a drifting workload re-triggers the same adjacent regime
    every cycle until it lands). Failures are logged and swallowed —
    speculation is an optimization, a bad prediction must cost nothing
    but the wasted build."""

    def __init__(self, metrics=None) -> None:
        self._metrics = metrics
        self._q: _queue.Queue = _queue.Queue()
        self._inflight: set = set()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.built = 0
        self.failed = 0

    def enqueue_build(self, key, thunk: Callable[[], None]) -> bool:
        """Enqueue one speculative build; False when the same key is
        already queued or building."""
        with self._lock:
            if self._stop.is_set() or key in self._inflight:
                return False
            self._inflight.add(key)
            # the put rides INSIDE the lock: the worker's drain-exit
            # checks queue emptiness under the same lock, so an item is
            # either visible to the exiting worker (queue non-empty ->
            # it keeps running) or enqueued after the worker cleared
            # self._thread (-> a fresh worker starts here)
            self._q.put((key, thunk))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run,
                    name="compile-warmer",
                    daemon=True,
                )
                self._thread.start()
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                key, thunk = self._q.get(timeout=5.0)
            except _queue.Empty:
                # drained: exit instead of polling forever — a process
                # that constructs many Schedulers must not accumulate
                # idle warmer threads. The next submit starts a fresh
                # worker (thread cleared under the submit lock, so no
                # enqueued job can be stranded).
                with self._lock:
                    if self._q.empty():
                        self._thread = None
                        return
                continue
            try:
                thunk()
                # counted under the submit lock: the worker respawns, so
                # a successor thread (or a reader polling built/failed
                # between respawns) must see each increment whole
                with self._lock:
                    self.built += 1
                m = self._metrics
                if m is not None:
                    m.compile_cache_speculative.inc()
            except Exception:
                with self._lock:
                    self.failed += 1
                log.exception(
                    "compile warmer: speculative build %r failed "
                    "(prediction discarded)", key,
                )
            finally:
                with self._lock:
                    self._inflight.discard(key)
                self._q.task_done()

    def idle(self) -> bool:
        with self._lock:
            return not self._inflight

    def join(self, timeout: float = 30.0) -> bool:
        """Wait for the queue to drain (tests / warm_cache.py)."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if self.idle():
                return True
            _time.sleep(0.02)
        return False

    def stop(self) -> None:
        self._stop.set()
