from .cycle import CycleResult, build_cycle_fn  # noqa: F401
