from .cycle import CycleOptions, CycleResult, build_cycle_fn  # noqa: F401
