from .cycle import (  # noqa: F401
    CycleDecision,
    CycleResult,
    build_carry_fns,
    build_cycle_fn,
    build_diagnosis_fn,
    build_packed_cycle_carry_fn,
    build_packed_cycle_fn,
    build_packed_preemption_fn,
    build_preemption_fn,
    build_stable_state_fn,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    to_chrome_trace,
)
from .observe import (  # noqa: F401
    ANOMALY_CLASSES,
    PHASES,
    CycleObserver,
    SloEngine,
    classify_latency_series,
    phase_seconds,
)
from .pipeline import ServingPipeline, build_decision_slim_fn  # noqa: F401
from .scheduler import CycleStats, Scheduler  # noqa: F401
