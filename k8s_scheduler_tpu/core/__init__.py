from .cycle import CycleResult, build_cycle_fn, build_preemption_fn  # noqa: F401
from .scheduler import CycleStats, Scheduler  # noqa: F401
