"""Crash black box: one-file post-mortem bundles for the watchtower.

A crash takes every in-memory ring down with it — flight recorder,
span ring, anomaly ring, metric history, active alerts — and a
`kill -9` doesn't even run `finally` blocks. So the black box dumps a
bundle at the moment things go WRONG, not at exit: by the time the
process dies (cleanly or not), the last bundle is already durable on
disk.

Triggers (each wired at its site, one module-flag check when unarmed —
the core/faults.py arming pattern):

- `sigterm`       — the CLI's shutdown path (cmd/main.py `finally`)
- `stateless`     — the degradation ladder entering RUNG_STATELESS
                    (Scheduler._on_rung_transition)
- `watchdog`      — a dispatch watchdog deadline abort
                    (Scheduler._cycle_failed, class "deadline")
- `serve_loop`    — an unhandled front-door serve-loop exception
                    (service/admission.FrontDoor._run_loop)

A bundle is one JSON file under `<stateDir>/blackbox/`, written
tmp + fsync + rename (the journal/snapshot atomicity discipline) so a
crash mid-dump leaves the previous bundle intact, never a torn one. It
carries: trigger metadata + build fingerprint + config, the TSDB metric
history window, flight records (+ derived stats + a pre-rendered
chrome trace for Perfetto), spans, anomalies, active/resolved alerts,
the ladder transition ring, and the fault-plan log. Retention is
bounded (`blackboxRetention` newest bundles kept, oldest deleted
first). `scripts/blackbox_read.py` pretty-prints a bundle and extracts
the Perfetto merge.

Dumps are throttled (`MIN_INTERVAL_S` per trigger kind) so a
crash-looping serve loop cannot turn the black box into a disk-filling
loop; `sigterm` is exempt (shutdown dumps exactly once and must win).
All dump paths swallow + log — the black box must never be the thing
that takes the scheduler down.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any

log = logging.getLogger(__name__)

# Module arming (core/faults.py pattern): hot trigger sites gate on one
# module-attribute load + branch; `arm(box)` installs the collector.
ARMED = False
BOX: "BlackBox | None" = None

MIN_INTERVAL_S = 30.0
DEFAULT_RETENTION = 8
MAX_DIR_BYTES = 64 << 20  # same ceiling as spans.export_otlp_dir


class BlackBox:
    """Holds references to the live observability surfaces and dumps
    them as one atomic bundle on demand. Every source is optional —
    a partially wired box dumps what it has."""

    def __init__(self, directory: str, retention: int = DEFAULT_RETENTION,
                 config: dict | None = None,
                 recorder=None, observer=None, spans_recorder=None,
                 tsdb=None, engine=None, ladder=None, fault_plan=None,
                 events=None):
        self.directory = directory
        self.retention = max(1, int(retention))
        self.config = config or {}
        self.recorder = recorder
        self.observer = observer
        self.spans_recorder = spans_recorder
        self.tsdb = tsdb
        self.engine = engine
        self.ladder = ladder
        self.fault_plan = fault_plan
        self.events = events
        self.dumps = 0
        self.last_path: str | None = None
        self._lock = threading.Lock()
        self._last_dump: dict[str, float] = {}

    # ---- bundle assembly --------------------------------------------

    def _collect(self, trigger: str, detail: str) -> dict:
        bundle: dict[str, Any] = {
            "blackbox_version": 1,
            "trigger": trigger,
            "detail": detail,
            "wall": time.time(),
            "pid": os.getpid(),
            "config": self.config,
        }
        try:
            from ..metrics.metrics import build_fingerprint
            bundle["build"] = build_fingerprint()
        except Exception:
            # schedlint: disable=RB001 -- fingerprint is best-effort
            # identity metadata; the bundle matters more.
            log.exception("blackbox: build fingerprint failed")
        rec = self.recorder
        if rec is not None:
            bundle["flight"] = {
                "records": rec.to_dicts(last=256),
                "derived": rec.derived(last=64),
                "cycles": rec.cycles,
            }
            try:
                from .flight_recorder import to_chrome_trace
                spans = (self.spans_recorder.snapshot(last=512)
                         if self.spans_recorder is not None else None)
                bundle["chrome_trace"] = to_chrome_trace(
                    rec.snapshot(last=256), epoch=rec.epoch, spans=spans)
            except Exception:
                # schedlint: disable=RB001 -- the Perfetto merge is a
                # convenience view; raw records are already in.
                log.exception("blackbox: chrome trace render failed")
        if self.spans_recorder is not None:
            bundle["spans"] = self.spans_recorder.to_dicts(last=512)
        if self.observer is not None:
            bundle["anomalies"] = {
                "events": self.observer.anomalies(last=512),
                "status": self.observer.status(),
            }
        if self.engine is not None:
            bundle["alerts"] = self.engine.status()
        if self.tsdb is not None:
            bundle["metrics_history"] = self.tsdb.snapshot_all()
        if self.ladder is not None:
            bundle["ladder"] = {
                "status": self.ladder.status(),
                "transitions": self.ladder.transition_log(),
            }
        if self.fault_plan is not None:
            bundle["faults"] = {
                "fired": sorted(self.fault_plan.fired_points()),
                "log": list(self.fault_plan.log)[-128:],
            }
        if self.events is not None:
            import dataclasses as _dc
            bundle["events"] = [_dc.asdict(e) for e in
                                self.events.events()[-128:]]
        return bundle

    # ---- atomic write + retention -----------------------------------

    def dump(self, trigger: str, detail: str = "") -> str | None:
        """Writes one bundle; returns its path or None (throttled /
        failed). Never raises."""
        now = time.time()
        with self._lock:
            last = self._last_dump.get(trigger, 0.0)
            if trigger != "sigterm" and now - last < MIN_INTERVAL_S:
                return None
            self._last_dump[trigger] = now
            try:
                return self._dump_locked(trigger, detail)
            except Exception:
                # schedlint: disable=RB001 -- the black box must never
                # take the scheduler down; a failed dump is logged and
                # the trigger site continues.
                log.exception("blackbox: dump failed (trigger=%s)", trigger)
                return None

    def _dump_locked(self, trigger: str, detail: str) -> str:
        bundle = self._collect(trigger, detail)
        os.makedirs(self.directory, exist_ok=True)
        existing = self._bundles()
        nxt = 0
        if existing:
            try:
                nxt = int(existing[-1].split("-")[1]) + 1
            except (IndexError, ValueError):
                nxt = len(existing)
        name = f"blackbox-{nxt:06d}-{trigger}.json"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.dumps += 1
        self.last_path = path
        log.warning("blackbox: dumped %s (trigger=%s%s)", path, trigger,
                    f": {detail}" if detail else "")
        self._rotate()
        return path

    def _bundles(self) -> list[str]:
        try:
            return sorted(
                f for f in os.listdir(self.directory)
                if f.startswith("blackbox-") and f.endswith(".json"))
        except OSError:
            return []

    def _rotate(self) -> None:
        files = self._bundles()
        # count retention first, then the byte ceiling; never delete
        # the bundle just written
        for f in files[:-self.retention]:
            self._unlink(f)
        files = self._bundles()
        total = 0
        sizes = {}
        for f in files:
            try:
                sizes[f] = os.path.getsize(os.path.join(self.directory, f))
            except OSError:
                sizes[f] = 0
            total += sizes[f]
        for f in files[:-1]:
            if total <= MAX_DIR_BYTES:
                break
            self._unlink(f)
            total -= sizes[f]

    def _unlink(self, name: str) -> None:
        try:
            os.unlink(os.path.join(self.directory, name))
        except OSError:
            log.warning("blackbox: rotate failed to remove %s", name)

    def status(self) -> dict:
        return {"directory": self.directory, "retention": self.retention,
                "dumps": self.dumps, "last_path": self.last_path,
                "bundles": self._bundles()}


def arm(box: BlackBox) -> BlackBox:
    global ARMED, BOX
    BOX = box
    ARMED = True
    return box


def disarm() -> None:
    global ARMED, BOX
    ARMED = False
    BOX = None


def trigger(kind: str, detail: str = "") -> "str | None":
    """The hot-site entry point: one module-flag check when unarmed."""
    if not ARMED:
        return None
    box = BOX
    if box is None:
        return None
    return box.dump(kind, detail)


def load_bundle(path: str) -> dict:
    """Reads one bundle back (scripts/blackbox_read.py round-trip)."""
    with open(path, encoding="utf-8") as f:
        return json.load(f)
