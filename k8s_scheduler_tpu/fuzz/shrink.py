"""Trace shrinking: reduce a failing trace to a minimal repro.

Greedy delta-debugging over the PLAIN-DATA trace, re-checking the
failure at every step and accepting a reduction ONLY when the failure
CLASS is preserved (`Failure.cls`) — shrinking to a *different* bug is
a rejected step, so the committed repro always reproduces the bug that
was found, not whichever one is easiest to trigger.

Stages, coarsest first (each runs to fixpoint before the next):

1. truncate cycles after the first failing cycle;
2. drop whole cycles (their events merge away; empty cycles stay as
   scheduling ticks only at the tail);
3. drop individual events (pod arrivals, churn);
4. drop initial objects (nodes, PVs, PVCs, classes, PDBs, groups);
5. simplify surviving pods one attribute at a time (affinity, anti,
   spread, tolerations, selector, volumes, priority, gang, ports);
6. drop fault-plan rules (chaos traces).

The `check` callable is injected — `run_case`-shaped for the real
harness, synthetic for the shrinker's own unit tests — and the whole
search is budgeted by `max_evals` (each eval of the real checker costs
a full replay)."""

from __future__ import annotations

import copy
from typing import Callable, Optional

from .replay import Failure
from .trace import Trace, trace_from_dict, trace_to_dict

Check = Callable[[Trace], Optional[Failure]]


class _Budget:
    def __init__(self, n: int) -> None:
        self.left = n

    def spend(self) -> bool:
        if self.left <= 0:
            return False
        self.left -= 1
        return True


def _clone(t: Trace) -> Trace:
    # dataclasses.asdict already rebuilds every nested container fresh
    return trace_from_dict(trace_to_dict(t))


def _same_class(check: Check, cand: Trace, cls: str,
                budget: _Budget) -> "Failure | None":
    if not budget.spend():
        return None
    f = check(cand)
    return f if f is not None and f.cls == cls else None


def _strip_variants(pod: dict):
    """Candidate one-attribute simplifications of a serialized pod
    (state/codec dialect), most-structure-first."""
    s = pod.get("s", {})
    for key in ("af", "tsc", "tol", "sel", "vol", "pg", "pp"):
        if key in s:
            v = copy.deepcopy(pod)
            del v["s"][key]
            yield v
    if s.get("pri"):
        v = copy.deepcopy(pod)
        del v["s"]["pri"]
        yield v
    for ci, c in enumerate(s.get("c", ())):
        if c.get("p"):
            v = copy.deepcopy(pod)
            del v["s"]["c"][ci]["p"]
            yield v


def shrink_trace(
    trace: Trace,
    failure: Failure,
    check: Check,
    *,
    max_evals: int = 250,
) -> tuple[Trace, Failure]:
    """Minimize `trace` while `check` keeps returning a failure of
    `failure.cls`. Returns (minimal trace, its failure). The input
    trace is not mutated."""
    cls = failure.cls
    budget = _Budget(max_evals)
    best = _clone(trace)
    best_failure = failure

    def accept(cand: Trace) -> bool:
        nonlocal best, best_failure
        f = _same_class(check, cand, cls, budget)
        if f is None:
            return False
        best, best_failure = cand, f
        return True

    # 1. truncate after the failing cycle (binary back-off from there)
    if failure.cycle >= 0 and failure.cycle + 1 < len(best.cycles):
        cand = _clone(best)
        cand.cycles = cand.cycles[: failure.cycle + 1]
        accept(cand)

    changed = True
    while changed and budget.left > 0:
        changed = False
        # 2. whole cycles, last to first
        for i in range(len(best.cycles) - 1, -1, -1):
            if len(best.cycles) <= 1:
                break
            cand = _clone(best)
            del cand.cycles[i]
            if accept(cand):
                changed = True
        # 3. individual events
        for ci in range(len(best.cycles) - 1, -1, -1):
            for ei in range(len(best.cycles[ci]) - 1, -1, -1):
                cand = _clone(best)
                del cand.cycles[ci][ei]
                if accept(cand):
                    changed = True
        # 4. initial objects
        for field in ("nodes", "pvs", "pvcs", "storage_classes", "pdbs",
                      "pod_groups"):
            lst = getattr(best, field)
            for i in range(len(lst) - 1, -1, -1):
                if field == "nodes" and len(lst) <= 1:
                    break
                cand = _clone(best)
                del getattr(cand, field)[i]
                if accept(cand):
                    changed = True
                    lst = getattr(best, field)
        # 5. pod simplification
        for ci in range(len(best.cycles)):
            for ei in range(len(best.cycles[ci])):
                ev = best.cycles[ci][ei]
                if "pod" not in ev:
                    continue
                for variant in _strip_variants(ev["pod"]):
                    cand = _clone(best)
                    cand.cycles[ci][ei]["pod"] = variant
                    if accept(cand):
                        changed = True
                        break
        # 6. fault rules (chaos)
        if best.fault_spec:
            rules = [r for r in best.fault_spec.split(";") if r]
            for i in range(len(rules) - 1, -1, -1):
                if rules[i].startswith("seed="):
                    continue
                cand = _clone(best)
                kept = rules[:i] + rules[i + 1:]
                if not any(
                    not r.startswith("seed=") for r in kept
                ):
                    continue  # a chaos trace needs >=1 rule
                cand.fault_spec = ";".join(kept)
                if accept(cand):
                    changed = True
                    rules = [
                        r for r in best.fault_spec.split(";") if r
                    ]
    return best, best_failure
