"""Trace replay: the live engine vs the trace-semantics oracle.

Both sides consume the SAME trace, drive the SAME host bookkeeping
classes (`SchedulingQueue`, `SchedulerCache` — deliberately shared: the
differential isolates the DECISION ENGINE, and the queue/cache are
plain Python already covered by the journal-replay exactness suite),
and advance the same fake clock. The only thing that differs is who
decides: the batched JAX programs behind `Scheduler.schedule_cycle`,
or `oracle.schedule_cycle_trace`.

Per cycle each side records (pending uids, binds, unschedulable+
reasons, nominations, evictions, gang drops, PDB overruns); after each
cycle the harness plays the informer back — bind confirmations
(`on_pod_add(pod, node)`) and eviction deletes (`on_pod_delete`) — and
ticks the clock past the max backoff, so requeued pods return
deterministically. `compare()` asserts the two streams bit-equal:
per-cycle for single-cycle serving, as flattened streams for
multi-cycle coalescing (whose ONLY legal difference is when outcomes
land, never what they are — PR 6's contract).

Standing invariants checked engine-side every cycle (chaos traces,
where faults make the queues legitimately diverge from the oracle's,
keep these as their whole contract):

- no node capacity overcommit (every resource, bound+assumed);
- gang all-or-nothing (placed members + running members >= minMember);
- zero duplicate binds (a uid binds at most once while bound);
- zero lost accepted pods at end of trace (bound, or still in a tier);
- PDB respected (per-cycle eviction count within disruptionsAllowed;
  overruns — legal only as the kernel's documented last resort — are
  recorded per cycle and must MATCH the oracle's, which re-derives the
  last-resort choice independently).

Chaos traces additionally assert the PR 8 soak invariants: the
watchdog bounds every injected hang, the ladder recovers to rung 0 on
the recovery tail, and (when a state dir is given) the journal
restores to a digest-identical queue/cache.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time as _time

from .. import oracle
from ..internal.cache import SchedulerCache
from ..internal.queue import (
    EVENT_NODE_ADD,
    EVENT_NODE_DELETE,
    EVENT_NODE_UPDATE,
    EVENT_POD_ADD,
    EVENT_POD_DELETE,
    SchedulingQueue,
)
from ..models.api import Pod
from ..ops import preemption as preemption_ops
from .trace import (
    Trace,
    materialize,
    materialize_event,
    trace_from_dict,
    trace_to_dict,
)


@dataclasses.dataclass
class Failure:
    """One check that did not hold. `cls` is the failure CLASS the
    shrinker preserves (shrink-to-a-different-bug is a rejected
    reduction); `cycle` anchors truncation; `detail` is human-readable
    and carries the first diverging payloads."""

    cls: str
    cycle: int = -1
    detail: str = ""

    def __str__(self) -> str:
        at = f" at cycle {self.cycle}" if self.cycle >= 0 else ""
        return f"{self.cls}{at}: {self.detail}"


@dataclasses.dataclass
class ReplayResult:
    records: list  # per-cycle dicts
    failures: list  # list[Failure] (invariants; chaos checks)
    binds: list  # flattened [(uid, node), ...] in bind order
    stats: dict

    def stream(self, key: str) -> list:
        return [x for r in self.records for x in r[key]]


def _require_scan_mode(cfgd: dict) -> None:
    """The differential is defined for the SCAN engine only: its
    decisions are exact vs the sequential oracle, and its reject
    attribution is at-turn (oracle.schedule_cycle_trace mirrors that).
    The rounds engine diverges by design (integer rounding, hash
    tie-break) and attributes against the final state — a rounds trace
    here would report phantom divergences, so refuse it loudly."""
    mode = cfgd.get("commit_mode", "scan")
    if mode != "scan":
        raise ValueError(
            f"fuzz replay requires commit_mode='scan', got {mode!r} "
            "(the rounds engine's legal divergences need the "
            "soak_differential-style validity/regret checks, not "
            "bit-equality)"
        )


class _Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


def _alloc_tol(used: float, alloc: float) -> bool:
    return used > alloc * (1 + 1e-5) + 1e-5


def _capacity_violations(cache: SchedulerCache) -> list[str]:
    by_node: dict[str, dict[str, float]] = {}
    for pod, node in cache.existing_pods():
        agg = by_node.setdefault(node, {})
        for r, v in pod.resource_requests().items():
            agg[r] = agg.get(r, 0.0) + v
    out = []
    nodes = {n.name: n for n in cache.nodes()}
    for name, agg in by_node.items():
        nd = nodes.get(name)
        if nd is None:
            continue  # node deleted out from under its pods (churn)
        for r, v in agg.items():
            if _alloc_tol(v, nd.status.allocatable.get(r, 0.0)):
                out.append(
                    f"node {name}: {r} overcommitted "
                    f"({v} > {nd.status.allocatable.get(r, 0.0)})"
                )
    return out


def _pdb_overruns(pdbs, evicted_pods) -> list[int]:
    """Per-PDB count of this cycle's evictions beyond its budget."""
    out = []
    for pdb in pdbs:
        n = sum(
            1 for p in evicted_pods
            if p.namespace == pdb.namespace
            and oracle.match_label_selector(pdb.selector, p.metadata.labels)
        )
        out.append(max(0, n - pdb.disruptions_allowed))
    return out


def _gang_violations(groups, existing_before, binds, all_pods) -> list[str]:
    """All-or-nothing: any group that placed >=1 member this cycle must
    reach minMember counting members already running."""
    if not groups:
        return []
    running: dict[str, int] = {}
    for pod, _n in existing_before:
        if pod.spec.pod_group:
            running[pod.spec.pod_group] = running.get(pod.spec.pod_group, 0) + 1
    placed: dict[str, int] = {}
    for uid, _node in binds:
        g = all_pods[uid].spec.pod_group if uid in all_pods else ""
        if g:
            placed[g] = placed.get(g, 0) + 1
    out = []
    for g in groups:
        got = placed.get(g.name, 0)
        if got and got + running.get(g.name, 0) < g.min_member:
            out.append(
                f"gang {g.name}: {got} placed + "
                f"{running.get(g.name, 0)} running < minMember "
                f"{g.min_member}"
            )
    return out


# --------------------------------------------------------------------------
# engine side
# --------------------------------------------------------------------------


def replay_engine(
    trace: Trace, *, state_dir: str = "", via_api: bool = False
) -> ReplayResult:
    """Drive the trace through a LIVE Scheduler — the real dispatch
    path (split-phase pipeline, multi-cycle coalescing and sharded
    serving included, per the trace config). Chaos traces arm the
    trace's FaultPlan for the duration.

    `via_api` (the ISSUE 14 `arrivals_via_api` variant) routes every
    pending-pod arrival through a REAL gRPC Submit round trip and
    every node add/update/delete through NodeChurn — localhost server,
    wire-format conversion, admission layer and all — instead of the
    direct informer calls. Deletions and bound-pod confirmations stay
    direct (they are informer traffic, not submissions), the admission
    depth bound is lifted (equality is the contract under test, not
    load shedding), and the harness still drives `schedule_cycle`
    itself so the frozen-clock cadence is identical: any stream
    difference vs the direct-enqueue engine is the API path's doing."""
    import jax as _jax

    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core import Scheduler, faults

    cfgd = trace.config
    _require_scan_mode(cfgd)
    devices = int(cfgd.get("shard_devices", 0))
    if devices > 1 and len(_jax.devices()) < devices:
        raise RuntimeError(
            f"trace wants shardDevices={devices} but only "
            f"{len(_jax.devices())} devices are visible (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "importing jax)"
        )
    cfg = SchedulerConfiguration(
        commit_mode=cfgd.get("commit_mode", "scan"),
        gang_scheduling=bool(cfgd.get("gang_scheduling", True)),
        multi_cycle_k=int(cfgd.get("multi_cycle_k", 1)),
        multi_cycle_max_wait_ms=float(
            cfgd.get("multi_cycle_max_wait_ms", 1e12)
        ),
        # depth-2 speculative dispatch (default OFF for traces: the
        # committed corpus predates the key and must replay unchanged;
        # generate_trace(speculative=True) turns the variant on)
        speculative_dispatch=bool(
            cfgd.get("speculative_dispatch", False)
        ),
        # admission-time incremental encode (default OFF for the same
        # corpus-stability reason; generate_trace(incremental=True)
        # turns the variant on)
        incremental_encode=bool(cfgd.get("incremental_encode", False)),
        shard_devices=devices,
        dispatch_deadline_ms=float(cfgd.get("dispatch_deadline_ms", 0.0)),
        degrade_promote_cycles=int(cfgd.get("degrade_promote_cycles", 2)),
        fault_spec=trace.fault_spec,
        speculative_compile=False,
        # the repo's executable cache keys on spec/profile/kind, NOT on
        # the traced HLO — a reused chaos state_dir could serve an
        # executable compiled across an engine_bug patch boundary.
        # "off" beats "": with a state dir, "" DERIVES a cache path.
        # Warmth still comes from jax's persistent compilation cache,
        # which keys on the HLO and is therefore mutation-safe.
        compile_cache_dir="off",
        state_dir=state_dir,
        snapshot_interval_seconds=0.0,
    )
    clock = _Clock()
    cycle_binds: list[tuple[Pod, str]] = []
    cycle_evicts: list[tuple[Pod, str]] = []
    state = None
    if state_dir:
        from k8s_scheduler_tpu.state import DurableState

        state = DurableState(state_dir, snapshot_interval_seconds=0)
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: cycle_binds.append((pod, node)),
        evictor=lambda pod, node: cycle_evicts.append((pod, node)),
        now=clock,
        pad_bucket=int(cfgd.get("pad_bucket", 8)),
        state=state,
    )

    popped: list[list[str]] = []
    orig_pop = sched.queue.pop_ready

    def pop_capture(hold: bool = False):
        ready = orig_pop(hold)
        popped.append([p.uid for p in ready])
        return ready

    sched.queue.pop_ready = pop_capture
    unsched_log: list[tuple[str, tuple]] = []
    orig_unsched = sched.queue.requeue_unschedulable

    def unsched_capture(pod, reasons=()):
        r = (reasons,) if isinstance(reasons, str) else tuple(reasons)
        unsched_log.append((pod.uid, r))
        return orig_unsched(pod, reasons=reasons)

    sched.queue.requeue_unschedulable = unsched_capture
    backoff_log: list[tuple[str, str]] = []
    orig_backoff = sched.queue.requeue_backoff

    def backoff_capture(pod, event="BindError"):
        backoff_log.append((pod.uid, event))
        return orig_backoff(pod, event=event)

    sched.queue.requeue_backoff = backoff_capture

    api_server = None
    api_client = None
    if via_api:
        from concurrent import futures as _futures

        import grpc as _grpc

        from ..service.client import SchedulerClient
        from ..service.server import SchedulerService, add_to_server

        svc = SchedulerService(scheduler=sched)
        # the servicer ctor rebinds the binder to its Cycle-response
        # collector; the replay's capture binder must win (Cycle is
        # never called here — the harness drives schedule_cycle
        # directly so the frozen-clock cadence matches the direct run)
        sched.binder = lambda pod, node: cycle_binds.append((pod, node))
        svc.enable_front_door(queue_depth=0)
        api_server = _grpc.server(
            _futures.ThreadPoolExecutor(max_workers=2),
            options=(("grpc.so_reuseport", 0),),
        )
        add_to_server(svc, api_server)
        api_port = api_server.add_insecure_port("127.0.0.1:0")
        api_server.start()
        api_client = SchedulerClient(f"127.0.0.1:{api_port}")

    objs = materialize(trace)
    pdbs = objs["pdbs"]
    groups = objs["pod_groups"]
    for nd in objs["nodes"]:
        sched.on_node_add(nd)
    for g in groups:
        sched.add_pod_group(g)
    for c in objs["pvcs"]:
        sched.on_pvc_upsert(c)
    for v in objs["pvs"]:
        sched.on_pv_upsert(v)
    for s in objs["storage_classes"]:
        sched.on_storage_class_upsert(s)
    for p in pdbs:
        sched.on_pdb_upsert(p)

    records: list[dict] = []
    failures: list[Failure] = []
    all_binds: list[tuple[str, str]] = []
    all_pods: dict[str, Pod] = {}
    added: set[str] = set()
    deleted: set[str] = set()
    evicted: set[str] = set()
    bound_now: set[str] = set()
    walls: dict[int, float] = {}
    try:
        for ci, events in enumerate(trace.cycles):
            for raw in events:
                ev = materialize_event(raw)
                op = ev["op"]
                if op == "add_pod":
                    all_pods[ev["pod"].uid] = ev["pod"]
                    added.add(ev["pod"].uid)
                    if api_client is not None:
                        _api_submit(
                            api_client, ev["pod"], ci, failures, sched
                        )
                    else:
                        sched.on_pod_add(ev["pod"])
                elif op == "add_bound_pod":
                    all_pods[ev["pod"].uid] = ev["pod"]
                    added.add(ev["pod"].uid)
                    bound_now.add(ev["pod"].uid)
                    sched.on_pod_add(ev["pod"], ev["bind_node"])
                elif op == "delete_pod":
                    deleted.add(ev["uid"])
                    bound_now.discard(ev["uid"])
                    sched.on_pod_delete(ev["uid"])
                elif op == "add_node":
                    if api_client is not None:
                        api_client.node_churn(adds=[ev["node"]])
                    else:
                        sched.on_node_add(ev["node"])
                elif op == "update_node":
                    if api_client is not None:
                        api_client.node_churn(updates=[ev["node"]])
                    else:
                        sched.on_node_update(ev["node"])
                elif op == "delete_node":
                    if api_client is not None:
                        api_client.node_churn(deletes=[ev["name"]])
                    else:
                        sched.on_node_delete(ev["name"])
                else:
                    raise ValueError(f"unknown trace op {op!r}")
            existing_before = sched.cache.existing_pods()
            cycle_binds.clear()
            cycle_evicts.clear()
            unsched_log.clear()
            backoff_log.clear()
            n_pops_before = len(popped)
            t_wall = _time.perf_counter()
            sched.schedule_cycle()
            walls[ci + 1] = _time.perf_counter() - t_wall

            binds = [(p.uid, n) for p, n in cycle_binds]
            for uid, node in binds:
                if uid in bound_now:
                    failures.append(Failure(
                        "invariant/duplicate_bind", ci,
                        f"{uid} bound again (-> {node}) while bound",
                    ))
                bound_now.add(uid)
            evs = [(p.uid, n) for p, n in cycle_evicts]
            noms = [(p.uid, n) for p, n in sched.last_nominations]
            pend = [u for lst in popped[n_pops_before:] for u in lst]
            records.append({
                "cycle": ci,
                "pending": pend,
                "binds": binds,
                "unschedulable": list(unsched_log),
                "nominated": noms,
                "evicted": [u for u, _n in evs],
                "gang_dropped": sorted(
                    u for u, r in unsched_log if r == ("Coscheduling",)
                ),
                "pdb_overruns": _pdb_overruns(
                    pdbs, [p for p, _n in cycle_evicts]
                ),
                "requeues": list(backoff_log),
                "rung": sched.ladder.rung,
            })
            all_binds.extend(binds)
            for msg in _capacity_violations(sched.cache):
                failures.append(Failure("invariant/capacity", ci, msg))
            for msg in _gang_violations(
                groups, existing_before, binds, all_pods
            ):
                failures.append(Failure("invariant/gang", ci, msg))

            # informer playback: bind confirmations + eviction deletes
            for pod, node in cycle_binds:
                sched.on_pod_add(pod, node)
            for pod, _node in cycle_evicts:
                evicted.add(pod.uid)
                bound_now.discard(pod.uid)
                sched.on_pod_delete(pod.uid)
            clock.tick(trace.tick_s)

        # ---- end-of-trace accounting ----
        tracked = {p.uid for p in sched.queue.all_pending()}
        tracked |= {p.uid for p, _n in sched.cache.existing_pods()}
        lost = sorted(added - deleted - evicted - tracked)
        if lost:
            failures.append(Failure(
                "invariant/lost_pods", len(trace.cycles) - 1,
                f"accepted pods neither bound nor queued: {lost[:6]}",
            ))
        if trace.chaos:
            failures.extend(_chaos_checks(trace, sched, walls, state_dir))
        stats = {
            "bound": len(all_binds),
            "added": len(added),
            "degradations": sched.ladder.degradations,
            "final_rung": sched.ladder.rung,
            "fired_points": sorted(
                faults.plan().fired_points()
            ) if faults.plan() is not None else [],
            # depth-2 speculation outcomes (all zero when the trace
            # runs without speculativeDispatch): the variant tests
            # assert the speculative path actually exercised AND that
            # no slot leaked (pipeline inflight drained)
            "speculation": sched.speculation_ledger(),
            # admission-time incremental encode ledger (all zero when
            # the trace runs without incrementalEncode): the variant
            # asserts staged rows were actually consumed at flush
            "ingest": {
                "hits": sum(
                    int(getattr(e, "ingest_hits", 0))
                    for e in sched._encoders.values()
                ),
                "misses": sum(
                    int(getattr(e, "ingest_misses", 0))
                    for e in sched._encoders.values()
                ),
            },
        }
    finally:
        from k8s_scheduler_tpu.core import faults as _faults

        _faults.disarm()
        if api_client is not None:
            with contextlib.suppress(Exception):
                api_client.close()
        if api_server is not None:
            with contextlib.suppress(Exception):
                api_server.stop(grace=0)
        if state is not None:
            with contextlib.suppress(Exception):
                state.journal.flush()
            with contextlib.suppress(Exception):
                state.journal.close()
    return ReplayResult(records, failures, all_binds, stats)


def _api_submit(client, pod, cycle: int, failures: list, sched) -> None:
    """One Submit round trip; a rejection is recorded as a failure
    (the unbounded-depth front door must accept every generated
    arrival — anything else is an API-path bug the variant exists to
    catch) and the pod falls back to direct enqueue so the stream
    comparison still runs to completion."""
    import grpc as _grpc

    try:
        client.submit([pod])
    except _grpc.RpcError as e:
        failures.append(Failure(
            "via_api/rejected", cycle,
            f"Submit({pod.uid}) -> {e.code().name}: {e.details()}",
        ))
        # keep both engines' inputs identical despite the failure
        sched.on_pod_add(pod)


def _chaos_checks(trace, sched, walls, state_dir) -> list[Failure]:
    """The PR 8 soak invariants, asserted on a chaos replay: watchdog
    bound held, ladder recovered on the tail, digest-verified restore."""
    import re

    from k8s_scheduler_tpu.core import faults

    out: list[Failure] = []
    deadline_ms = float(trace.config.get("dispatch_deadline_ms", 0.0))
    plan = faults.plan()
    hang_fired = plan is not None and "fetch_hang" in plan.fired_points()
    for m in re.finditer(
        r"fetch_hang@cycle=(\d+)[^;]*?ms=([0-9.]+)", trace.fault_spec
    ):
        cyc, hang_ms = int(m.group(1)), float(m.group(2))
        if not (hang_fired and deadline_ms and hang_ms > 2 * deadline_ms):
            continue
        # two-part watchdog proof, robust to in-cycle compile cost (a
        # retrace recovery can legally spend seconds rebuilding programs
        # in the same host cycle): (a) the loop never slept the full
        # hang; (b) the ladder recorded a deadline-classified step —
        # the watchdog, not the hang expiring, ended the fetch
        wall = walls.get(cyc, 0.0) * 1e3
        if wall >= hang_ms:
            out.append(Failure(
                "chaos/watchdog", cyc,
                f"serve loop blocked {wall:.0f}ms >= the injected "
                f"{hang_ms:.0f}ms hang (deadline {deadline_ms:.0f}ms)",
            ))
        if not any(
            e["reason"].startswith("deadline")
            for e in sched.ladder.transitions
        ):
            out.append(Failure(
                "chaos/watchdog", cyc,
                "fetch_hang fired but no deadline-classified ladder "
                "step was recorded — the watchdog never expired the "
                "fetch",
            ))
    if sched.ladder.rung != sched.ladder.floor:
        out.append(Failure(
            "chaos/ladder", len(trace.cycles) - 1,
            f"ladder never recovered: rung {sched.ladder.rung} "
            f"(floor {sched.ladder.floor}) after the recovery tail",
        ))
    if state_dir:
        from k8s_scheduler_tpu.state import DurableState, state_digest

        with contextlib.suppress(Exception):
            sched.state.journal.flush()
        live = state_digest(sched.queue, sched.cache)
        q2 = SchedulingQueue()
        c2 = SchedulerCache()
        st2 = DurableState(state_dir, snapshot_interval_seconds=0)
        try:
            st2.restore_into(q2, c2)
            restored = state_digest(q2, c2)
        finally:
            with contextlib.suppress(Exception):
                st2.journal.close()
        if restored != live:
            out.append(Failure(
                "chaos/digest", len(trace.cycles) - 1,
                "journal restore digest != live queue/cache digest",
            ))
    return out


# --------------------------------------------------------------------------
# oracle side
# --------------------------------------------------------------------------


def replay_oracle(trace: Trace) -> ReplayResult:
    """Drive the trace through the sequential oracle under IDENTICAL
    host bookkeeping: same queue/cache classes, same informer playback,
    same clock ticks — so any stream difference is the decision
    engine's."""
    _require_scan_mode(trace.config)
    clock = _Clock()
    queue = SchedulingQueue(
        initial_backoff_seconds=1.0, max_backoff_seconds=10.0, now=clock
    )
    cache = SchedulerCache(now=clock)
    objs = materialize(trace)
    pdbs = objs["pdbs"]
    groups = objs["pod_groups"]
    pvcs = {c.key: c for c in objs["pvcs"]}
    pvs = {v.name: v for v in objs["pvs"]}
    classes = {s.name: s for s in objs["storage_classes"]}
    for nd in objs["nodes"]:
        cache.add_node(nd)
    gang = bool(trace.config.get("gang_scheduling", True))

    records: list[dict] = []
    failures: list[Failure] = []
    all_binds: list[tuple[str, str]] = []
    all_pods: dict[str, Pod] = {}
    added: set[str] = set()
    deleted: set[str] = set()
    evicted: set[str] = set()

    def informer_bound(pod: Pod, node: str) -> None:
        queue.delete(pod.uid)
        cache.add_pod(pod, node)
        queue.move_all_to_active_or_backoff(EVENT_POD_ADD)

    def informer_delete(uid: str) -> None:
        cache.remove_pod(uid)
        queue.delete(uid)
        queue.move_all_to_active_or_backoff(EVENT_POD_DELETE)

    for ci, events in enumerate(trace.cycles):
        for raw in events:
            ev = materialize_event(raw)
            op = ev["op"]
            if op == "add_pod":
                all_pods[ev["pod"].uid] = ev["pod"]
                added.add(ev["pod"].uid)
                queue.add(ev["pod"])
            elif op == "add_bound_pod":
                all_pods[ev["pod"].uid] = ev["pod"]
                added.add(ev["pod"].uid)
                informer_bound(ev["pod"], ev["bind_node"])
            elif op == "delete_pod":
                deleted.add(ev["uid"])
                informer_delete(ev["uid"])
            elif op == "add_node":
                cache.add_node(ev["node"])
                queue.move_all_to_active_or_backoff(EVENT_NODE_ADD)
            elif op == "update_node":
                cache.update_node(ev["node"])
                queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)
            elif op == "delete_node":
                cache.remove_node(ev["name"])
                queue.move_all_to_active_or_backoff(EVENT_NODE_DELETE)
            else:
                raise ValueError(f"unknown trace op {op!r}")

        # the cycle, mirroring Scheduler.schedule_cycle's host order
        for pod, _node in cache.cleanup_expired():
            queue.requeue_backoff(pod, event="AssumeExpired")
        queue.flush_unschedulable_timeout()
        pending = queue.pop_ready()
        rec = {
            "cycle": ci, "pending": [p.uid for p in pending],
            "binds": [], "unschedulable": [], "nominated": [],
            "evicted": [], "gang_dropped": [], "pdb_overruns":
            [0] * len(pdbs), "requeues": [], "rung": 0,
        }
        cycle_binds: list[tuple[Pod, str]] = []
        cycle_evicts: list[Pod] = []
        if pending:
            nodes = cache.nodes()
            existing = cache.existing_pods()
            out = oracle.schedule_cycle_trace(
                nodes, pending, existing,
                pod_groups=groups, pvcs=list(pvcs.values()),
                pvs=list(pvs.values()),
                storage_classes=list(classes.values()),
                pdbs=pdbs, gang_scheduling=gang,
                budget=preemption_ops.DEFAULT_BUDGET,
                scan_budget=preemption_ops.DEFAULT_SCAN_BUDGET,
            )
            # winners bind in pending order (the engine's apply order)
            for i, pod in enumerate(pending):
                ni = out.decisions[i].node_index
                if ni < 0:
                    continue
                node = nodes[ni].name
                cache.assume(pod, node)
                cache.finish_binding(pod.uid)
                rec["binds"].append((pod.uid, node))
                cycle_binds.append((pod, node))
            nominated = {o.pod_index: o.node_index for o in out.preemptions}
            for i, pod in enumerate(pending):
                if out.decisions[i].node_index >= 0:
                    continue
                if i in nominated:
                    pod.nominated_node_name = nodes[nominated[i]].name
                    rec["nominated"].append(
                        (pod.uid, pod.nominated_node_name)
                    )
                reasons = out.reasons.get(i, ())
                rec["unschedulable"].append((pod.uid, tuple(reasons)))
                queue.requeue_unschedulable(pod, reasons=reasons)
            vict: set[int] = set()
            for o in out.preemptions:
                vict.update(o.victims)
            for e in sorted(vict):
                vpod = existing[e][0]
                rec["evicted"].append(vpod.uid)
                cycle_evicts.append(vpod)
            rec["gang_dropped"] = sorted(
                pending[i].uid for i in out.dropped
            )
            rec["pdb_overruns"] = _pdb_overruns(pdbs, cycle_evicts)
        records.append(rec)
        all_binds.extend(rec["binds"])
        for pod, node in cycle_binds:
            informer_bound(pod, node)
        for vpod in cycle_evicts:
            evicted.add(vpod.uid)
            informer_delete(vpod.uid)
        clock.tick(trace.tick_s)

    tracked = {p.uid for p in queue.all_pending()}
    tracked |= {p.uid for p, _n in cache.existing_pods()}
    lost = sorted(added - deleted - evicted - tracked)
    if lost:
        failures.append(Failure(
            "invariant/lost_pods", len(trace.cycles) - 1,
            f"oracle-side accepted pods neither bound nor queued: "
            f"{lost[:6]}",
        ))
    for msg in _capacity_violations(cache):
        failures.append(Failure(
            "invariant/capacity", len(trace.cycles) - 1,
            f"oracle-side {msg}",
        ))
    return ReplayResult(
        records, failures, all_binds, {"bound": len(all_binds)}
    )


# --------------------------------------------------------------------------
# comparison + the one-call driver
# --------------------------------------------------------------------------

_PER_CYCLE_KEYS = (
    "pending", "binds", "unschedulable", "nominated", "evicted",
    "gang_dropped", "pdb_overruns",
)


def compare(trace: Trace, eng: ReplayResult, orc: ReplayResult) -> list[Failure]:
    """Bit-equality of the two decision streams. Single-cycle serving
    compares cycle by cycle (first diverging cycle + field named);
    multi-cycle serving compares the flattened streams — coalescing
    legitimately moves WHEN outcomes land (to the flush cycle), never
    what they are or their order."""
    out: list[Failure] = []
    if int(trace.config.get("multi_cycle_k", 1)) <= 1:
        for er, orr in zip(eng.records, orc.records):
            for key in _PER_CYCLE_KEYS:
                if er[key] != orr[key]:
                    out.append(Failure(
                        f"divergence/{key}", er["cycle"],
                        f"engine={er[key]!r} oracle={orr[key]!r}",
                    ))
            if out:
                return out
        return out
    for key in ("binds", "unschedulable", "nominated", "evicted",
                "gang_dropped"):
        e, o = eng.stream(key), orc.stream(key)
        if key == "gang_dropped":
            # sorted per RECORD, and a flush record merges K inner
            # cycles — order across the merge is presentation, not
            # semantics (the ordered truth rides the unschedulable
            # stream as ("Coscheduling",) entries); compare the multiset
            e, o = sorted(e), sorted(o)
        if e != o:
            i = next(
                (j for j, (a, b) in enumerate(zip(e, o)) if a != b),
                min(len(e), len(o)),
            )
            out.append(Failure(
                f"divergence/{key}", -1,
                f"stream differs from element {i}: "
                f"engine={e[i:i+3]!r} oracle={o[i:i+3]!r} "
                f"(lengths {len(e)}/{len(o)})",
            ))
            return out
    return out


def compare_speculative(
    eng_on: ReplayResult, eng_off: ReplayResult
) -> list[Failure]:
    """Per-cycle bit-equality of the speculative engine against the
    NON-speculative engine on the same trace. This — not the oracle —
    is depth-2 speculation's contract: adoption/abandonment must not
    change WHAT is decided, WHEN it lands, or in what order (the two
    engines share the exact batching cadence, so even the cycle
    placement must match). The oracle differential is defined against
    sequential serving, where coalescing's documented legal
    batch-window shifts (an unschedulable pod's re-activation moving
    to the flush cycle) would read as divergence."""
    out: list[Failure] = []
    for er, orr in zip(eng_on.records, eng_off.records):
        for key in _PER_CYCLE_KEYS + ("requeues", "rung"):
            if er[key] != orr[key]:
                out.append(Failure(
                    f"speculation/{key}", er["cycle"],
                    f"spec-on={er[key]!r} spec-off={orr[key]!r}",
                ))
        if out:
            return out
    return out


def compare_incremental(
    eng_on: ReplayResult, eng_off: ReplayResult
) -> list[Failure]:
    """Per-cycle bit-equality of the incremental-encode engine against
    the rebuild engine on the same trace. This — not the oracle — is
    admission-time ingest's contract: staging row data at buffer time
    must not change WHAT is encoded or decided, only WHEN the parse
    cost is paid (the two engines share the exact coalescing cadence,
    so even cycle placement must match). The dispatched packed arenas
    are additionally compared byte for byte by run_case via
    _capture_arenas — the decision streams could mask a compensating
    arena difference, the arena bytes cannot."""
    out: list[Failure] = []
    for er, orr in zip(eng_on.records, eng_off.records):
        for key in _PER_CYCLE_KEYS + ("requeues", "rung"):
            if er[key] != orr[key]:
                out.append(Failure(
                    f"incremental/{key}", er["cycle"],
                    f"inc-on={er[key]!r} inc-off={orr[key]!r}",
                ))
        if out:
            return out
    return out


@contextlib.contextmanager
def _capture_arenas(out: list):
    """Record the packed-arena bytes of every dispatch (single and
    multi-cycle) issued inside the scope: `out` collects
    `(kind, words_bytes, bytes_bytes)` tuples in dispatch order, pulled
    to host before the upload so device placement cannot launder a
    difference. Class-level patch — replays are sequential, and the
    finally-restore keeps it scoped."""
    import numpy as _np

    from ..core.pipeline import ServingPipeline

    orig_d = ServingPipeline.dispatch
    orig_m = ServingPipeline.dispatch_multi

    def dispatch(self, wbuf, bbuf, *a, **kw):
        out.append((
            "1",
            _np.asarray(wbuf).tobytes(),
            _np.asarray(bbuf).tobytes(),
        ))
        return orig_d(self, wbuf, bbuf, *a, **kw)

    def dispatch_multi(self, wbufs, bbufs, *a, **kw):
        out.append((
            "K",
            _np.asarray(wbufs).tobytes(),
            _np.asarray(bbufs).tobytes(),
        ))
        return orig_m(self, wbufs, bbufs, *a, **kw)

    ServingPipeline.dispatch = dispatch
    ServingPipeline.dispatch_multi = dispatch_multi
    try:
        yield
    finally:
        ServingPipeline.dispatch = orig_d
        ServingPipeline.dispatch_multi = orig_m


def compare_via_api(
    eng_api: ReplayResult, eng_direct: ReplayResult
) -> list[Failure]:
    """Per-cycle bit-equality of the arrivals-via-API engine against
    the direct-enqueue engine on the same trace. Both engines share
    the exact coalescing cadence (same trace, same K, same frozen
    clock — the coalescing-window legalities of the PR 10 generator
    notes therefore cancel out), so even cycle placement must match:
    any difference is the Submit/NodeChurn path perturbing state —
    conversion loss, ordering drift, or admission side effects."""
    out: list[Failure] = []
    for er, orr in zip(eng_api.records, eng_direct.records):
        for key in _PER_CYCLE_KEYS + ("requeues", "rung"):
            if er[key] != orr[key]:
                out.append(Failure(
                    f"via_api/{key}", er["cycle"],
                    f"via-api={er[key]!r} direct={orr[key]!r}",
                ))
        if out:
            return out
    return out


def run_api_case(trace: Trace) -> list[Failure]:
    """The `arrivals_via_api` variant (ISSUE 14): replay the trace
    with every arrival through real Submit/NodeChurn RPCs, then again
    with direct enqueue, and require bit-equal streams. Engine bugs
    cancel out of an engine-vs-engine comparison — decision
    correctness stays the oracle differential's job; this variant
    hunts API-path bugs specifically."""
    eng_api = replay_engine(trace, via_api=True)
    failures = list(eng_api.failures)
    eng_direct = replay_engine(trace)
    failures.extend(eng_direct.failures)
    failures.extend(compare_via_api(eng_api, eng_direct))
    return failures


def _tenant_registry(trace: Trace):
    """One fresh TenantRegistry per replay side — fresh objects too
    (the engine mutates pods in place, same rule as materialize)."""
    from ..state.codec import node_from_state
    from ..tenancy import TenantRegistry

    reg = TenantRegistry()
    for tid, cfg in sorted(trace.config["tenancy"]["tenants"].items()):
        reg.create(
            tid, quota=int(cfg.get("quota", 0)),
            weight=float(cfg.get("weight", 1.0)),
        )
    for d in trace.nodes:
        n = node_from_state(d)
        reg.add_node(n.metadata.namespace, n)
    return reg


def run_tenant_case(
    trace: Trace, *, bug: "str | None" = None
) -> list[Failure]:
    """Replay one multi-tenant trace (generate_multitenant_trace)
    through the packed arena AND the per-tenant sequential reference,
    and require each tenant's decision stream bit-equal between the
    two — the isolation property: no tenant's placements may depend on
    which other tenants share its bucket. Also checks the decision
    streams never cross tenants (a decision's pod uid must carry its
    tenant's namespace). `bug="tenant_row_skew"` arms the arena's
    deliberate cross-tenant leak (rolling result rows within a bucket)
    for harness self-tests — the differential must CATCH it."""
    from ..state.codec import node_from_state, pod_from_state
    from ..tenancy import MultiTenantArena, TenantError

    kw = dict(
        commit_mode=trace.config.get("commit_mode", "scan"),
        gang_scheduling=bool(trace.config.get("gang_scheduling", True)),
    )
    regs = (_tenant_registry(trace), _tenant_registry(trace))
    packed = MultiTenantArena(regs[0], **kw)
    seq = MultiTenantArena(regs[1], sequential=True, **kw)
    if bug == "tenant_row_skew":
        packed.inject = "row_skew"
    elif bug is not None:
        raise ValueError(f"unknown tenant-case bug {bug!r}")

    failures: list[Failure] = []
    for ci, evs in enumerate(trace.cycles):
        for ev in evs:
            op = ev["op"]
            for reg in regs:
                # TenantError is a legal no-op during shrinking (the
                # event that created the target may have been dropped);
                # both sides raise identically, so skipping keeps them
                # in lockstep
                try:
                    if op == "add_pod":
                        reg.route(pod_from_state(ev["pod"]))
                    elif op == "delete_pod":
                        reg.remove_pod(ev["tenant"], ev["uid"])
                    elif op == "suspend_tenant":
                        reg.suspend(ev["tenant"])
                    elif op == "resume_tenant":
                        reg.resume(ev["tenant"])
                    elif op == "add_node":
                        n = node_from_state(ev["node"])
                        reg.add_node(n.metadata.namespace, n)
                    else:
                        raise ValueError(
                            f"unknown tenant-trace op {op!r}"
                        )
                except TenantError:
                    continue
        packed.run_cycle()
        seq.run_cycle()
        for tid, uid, _node in packed.last_decisions:
            if not uid.startswith(f"{tid}/"):
                failures.append(Failure(
                    "tenant/cross_leak", ci,
                    f"decision for tenant {tid!r} carries foreign pod "
                    f"{uid!r}",
                ))
        by_t: dict[str, list] = {}
        by_t_ref: dict[str, list] = {}
        for tid, uid, node in packed.last_decisions:
            by_t.setdefault(tid, []).append((uid, node))
        for tid, uid, node in seq.last_decisions:
            by_t_ref.setdefault(tid, []).append((uid, node))
        if by_t != by_t_ref:
            tid = next(
                t for t in sorted(set(by_t) | set(by_t_ref))
                if by_t.get(t) != by_t_ref.get(t)
            )
            failures.append(Failure(
                "tenant/decision_divergence", ci,
                f"tenant {tid!r} packed {by_t.get(tid)} != sequential "
                f"{by_t_ref.get(tid)}",
            ))
            break  # registries diverged; later cycles are noise
    return failures


def run_case(
    trace: Trace, *, state_dir: str = "", bug: "str | None" = None
) -> list[Failure]:
    """Replay one trace end to end and return every failure: engine
    invariants (+ chaos checks), oracle invariants, and — for plain
    traces — the differential divergences. `bug` injects a deliberate
    engine mutation (see `engine_bug`) for harness self-tests.

    Speculative-dispatch traces differentially compare the engine
    against ITSELF with speculation off (see compare_speculative) and
    additionally fail when the trace never actually speculated —
    a variant that silently stopped exercising the depth-2 path would
    otherwise be a permanent green. Decision correctness is still
    oracle-checked through the non-speculative variants (a shared
    engine bug cancels out of an engine-vs-engine comparison, so this
    variant hunts speculation bugs specifically).

    Incremental-encode traces likewise compare the engine against
    ITSELF with admission-time ingest off (compare_incremental), and
    additionally require the dispatched packed arenas byte-identical
    and the ingest path actually exercised (staged rows consumed at
    flush) — a variant that silently fell back to full rebuilds every
    flush would otherwise be a permanent green.

    Multi-tenant traces (config["tenancy"]) route to the arena-vs-
    sequential differential instead (run_tenant_case) — same plain-data
    trace format, same shrinker, same corpus, different oracle."""
    if trace.config.get("tenancy"):
        return run_tenant_case(trace, bug=bug)
    inc = bool(trace.config.get("incremental_encode")) and not trace.chaos
    arenas_on: list = []
    cap = _capture_arenas(arenas_on) if inc else contextlib.nullcontext()
    with engine_bug(bug), cap:
        eng = replay_engine(trace, state_dir=state_dir)
    failures = list(eng.failures)
    if trace.chaos:
        return failures
    if inc:
        off = trace_from_dict(trace_to_dict(trace))
        off.config["incremental_encode"] = False
        arenas_off: list = []
        with engine_bug(bug), _capture_arenas(arenas_off):
            eng_off = replay_engine(off)
        failures.extend(eng_off.failures)
        failures.extend(compare_incremental(eng, eng_off))
        if arenas_on != arenas_off:
            i = next(
                (j for j, (a, b) in enumerate(zip(arenas_on, arenas_off))
                 if a != b),
                min(len(arenas_on), len(arenas_off)),
            )
            failures.append(Failure(
                "incremental/arena", -1,
                f"dispatched packed arenas diverge at dispatch {i} "
                f"(counts {len(arenas_on)}/{len(arenas_off)})",
            ))
        ing = eng.stats.get("ingest", {})
        if not ing.get("hits", 0):
            failures.append(Failure(
                "incremental/never_exercised", -1,
                f"incrementalEncode trace consumed no staged row at "
                f"flush (ledger {ing})",
            ))
        return failures
    if trace.config.get("speculative_dispatch"):
        off = trace_from_dict(trace_to_dict(trace))
        off.config["speculative_dispatch"] = False
        with engine_bug(bug):
            eng_off = replay_engine(off)
        failures.extend(eng_off.failures)
        failures.extend(compare_speculative(eng, eng_off))
        led = eng.stats.get("speculation", {})
        if not (led.get("adopted", 0) + led.get("abandoned", 0)):
            failures.append(Failure(
                "speculation/never_exercised", -1,
                f"speculativeDispatch trace dispatched no speculative "
                f"batch (ledger {led})",
            ))
        return failures
    orc = replay_oracle(trace)
    failures.extend(orc.failures)
    failures.extend(compare(trace, eng, orc))
    return failures


@contextlib.contextmanager
def engine_bug(name: "str | None"):
    """Deliberately break the ENGINE (never the oracle) for harness
    self-tests: the fuzzer must CATCH a seeded bug, and the shrinker
    tests reduce a trace that fails under it.

    - `tiebreak`: mutate the shard-invariant claim-path tie-break
      (ops/argsel.argmax_first) from first-max to LAST-max — the exact
      class of silent wrongness PR 9 eliminated; every equal-score
      placement flips, the kind of bug only a differential oracle sees.

    Program memos are per-Scheduler; jax's persistent compilation
    cache keys on the traced HLO (mutation-safe); and replay_engine
    pins the repo's spec-keyed executable cache OFF (it does NOT key
    on HLO, so it could otherwise serve a stale executable across the
    patch boundary). Callers must not reuse a Scheduler across the
    boundary — run_case never does.
    """
    if name is None:
        yield
        return
    if name != "tiebreak":
        raise ValueError(f"unknown engine bug {name!r}")
    import jax
    import jax.numpy as jnp

    from ..ops import argsel

    orig = argsel.argmax_first

    def argmax_last(x, axis: int = -1):
        ax = axis if axis >= 0 else x.ndim + axis
        m = jnp.max(x, axis=ax, keepdims=True)
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, ax)
        return jnp.max(jnp.where(x == m, idx, jnp.int32(-1)), axis=ax)

    argsel.argmax_first = argmax_last
    try:
        yield
    finally:
        argsel.argmax_first = orig
