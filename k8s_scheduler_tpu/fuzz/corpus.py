"""The committed-corpus artifact format.

One JSON file per minimal repro, written by the soak's shrink-on-
failure path (scripts/fuzz_scheduler.py) and replayed by the fast tier
(tests/test_fuzz.py over `tests/corpus/`). Every artifact is stamped
with everything needed to reproduce the run from the file alone:
generator seed + kwargs, fault spec, the engine-bug name (for harness
self-test repros like the seeded tie-break mutation), and the failure
class the shrinker preserved.

Corpus contract: replayed CLEAN (no failures) against the current
engine — each file is the regression test for a bug class the
differential once caught — and replayed FAILING with the recorded
class when its `bug` mutation is re-injected (the proof the oracle
still catches that class; tests/test_fuzz.py asserts both)."""

from __future__ import annotations

import dataclasses
import json

from .replay import Failure, run_case
from .trace import Trace, trace_from_dict, trace_to_dict

ARTIFACT_VERSION = 1


def save_artifact(
    path: str,
    trace: Trace,
    failure: Failure,
    *,
    bug: "str | None" = None,
    note: str = "",
) -> None:
    with open(path, "w") as f:
        json.dump({
            "version": ARTIFACT_VERSION,
            "seed": trace.seed,
            "fault_spec": trace.fault_spec,
            "failure": dataclasses.asdict(failure),
            "bug": bug or "",
            "note": note,
            "trace": trace_to_dict(trace),
        }, f, indent=1, sort_keys=True)
        f.write("\n")


def load_artifact(path: str) -> dict:
    with open(path) as f:
        d = json.load(f)
    if int(d.get("version", 1)) != ARTIFACT_VERSION:
        raise ValueError(f"artifact version {d.get('version')!r}")
    d["trace"] = trace_from_dict(d["trace"])
    d["failure"] = Failure(**d["failure"])
    return d


def replay_artifact(path: str, *, with_bug: bool = False) -> list[Failure]:
    """Replay one corpus file. `with_bug=False` is the regression
    direction (must come back clean); `with_bug=True` re-injects the
    recorded engine mutation (must reproduce the recorded class)."""
    art = load_artifact(path)
    bug = art["bug"] or None if with_bug else None
    if with_bug and not art["bug"]:
        raise ValueError(f"{path} records no engine bug to re-inject")
    return run_case(art["trace"], bug=bug)
