"""Scenario fuzzer + trace-level differential oracle (ISSUE 11).

The correctness backstop for every scale item: a seeded generator
(`trace.py`) emits multi-cycle cluster traces — pod arrivals/deletions,
node add/drain/churn, gangs, priority bands with preemption pressure,
taints/tolerations, PV topology, zone spreads, disruption budgets —
which `replay.py` drives through BOTH the live `Scheduler` (the real
dispatch path, multi-cycle and sharded variants included) and the slow
sequential oracle extended with trace semantics
(`oracle.schedule_cycle_trace`), asserting bit-equal bind streams plus
standing per-cycle invariants. `shrink.py` reduces failing traces to
minimal repros; `corpus.py` serializes them into the committed format
`tests/corpus/` replays in the fast tier.

Entry points: `scripts/fuzz_scheduler.py` (open-ended soak + replay
CLI), `tests/test_fuzz.py` (fast differential cases, corpus replay,
shrinker units, slow smoke).
"""

from .corpus import load_artifact, replay_artifact, save_artifact  # noqa: F401
from .replay import (  # noqa: F401
    Failure,
    engine_bug,
    replay_engine,
    replay_oracle,
    run_api_case,
    run_case,
    run_tenant_case,
)
from .shrink import shrink_trace  # noqa: F401
from .trace import (  # noqa: F401
    Trace,
    generate_multitenant_trace,
    generate_trace,
    trace_from_dict,
    trace_to_dict,
)
