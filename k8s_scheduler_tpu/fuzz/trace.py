"""Cluster-trace model + seeded generator.

A trace is PLAIN DATA (JSON-able end to end): the scheduler config
knobs, the initial cluster objects, and per-cycle event lists. Both
replay sides (`replay.py`) materialize their OWN `Pod`/`Node` objects
from it — the live engine mutates pods in place (nominated_node_name),
so sharing objects across sides would leak decisions between them, and
plain data is what the shrinker (`shrink.py`) and the committed corpus
format (`corpus.py`) operate on.

Pod/node payloads reuse the journal codec (`state/codec.py`
pod_to_state / node_to_state) — one serialization dialect for the whole
repo; the volume/PDB/group objects get small local codecs in the same
style.

Every draw comes from ONE `random.Random(seed)`, so a trace is fully
reproducible from its seed + the generator kwargs — the reproducibility
stamp every failure artifact carries (see scripts/fuzz_scheduler.py).
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Any

from ..models import api
from ..models.api import (
    LabelSelector,
    PersistentVolume,
    PersistentVolumeClaim,
    PodDisruptionBudget,
    PodGroup,
    StorageClass,
)
from ..models.builders import MakeNode, MakePod
from ..state.codec import (
    _lsel_from,
    _lsel_to,
    _term_from,
    _term_to,
    node_from_state,
    node_to_state,
    pod_from_state,
    pod_to_state,
)

TRACE_VERSION = 1

ZONES = ("zone-a", "zone-b", "zone-c")
NODE_TYPES = ("general", "compute", "memory")
APPS = tuple(f"app-{i}" for i in range(8))


@dataclasses.dataclass
class Trace:
    """One reproducible scenario: config + initial objects + cycles.

    `cycles` is a list of per-cycle EVENT lists; each event is a dict
    with an `op` key (`add_pod`, `add_bound_pod`, `delete_pod`,
    `add_node`, `update_node`, `delete_node`) delivered to the informer
    handlers before that cycle's `schedule_cycle()`. `chaos` traces
    carry a `fault_spec` (core/faults.py grammar) armed on the ENGINE
    side only — they are checked against the standing invariants, not
    the oracle (faults make the two queues legitimately diverge)."""

    seed: int
    config: dict
    nodes: list  # initial nodes (codec dicts)
    pod_groups: list
    pvcs: list
    pvs: list
    storage_classes: list
    pdbs: list
    cycles: list  # list[list[event dict]]
    fault_spec: str = ""
    tick_s: float = 16.0  # > podMaxBackoffSeconds: every backoff expires
    version: int = TRACE_VERSION

    @property
    def chaos(self) -> bool:
        return bool(self.fault_spec)


# --------------------------------------------------------------------------
# (de)serialization — small codecs for the objects state/codec.py lacks
# --------------------------------------------------------------------------


def _pvc_to(c: PersistentVolumeClaim) -> dict:
    return {
        "n": c.name, "ns": c.namespace, "sc": c.storage_class,
        "req": c.request, "vn": c.volume_name,
    }


def _pvc_from(d: dict) -> PersistentVolumeClaim:
    return PersistentVolumeClaim(
        d["n"], namespace=d.get("ns", "default"),
        storage_class=d.get("sc", ""), request=float(d.get("req", 0.0)),
        volume_name=d.get("vn", ""),
    )


def _pv_to(v: PersistentVolume) -> dict:
    return {
        "n": v.name, "cap": v.capacity, "sc": v.storage_class,
        "na": [_term_to(t) for t in v.node_affinity],
        "cr": v.claim_ref,
    }


def _pv_from(d: dict) -> PersistentVolume:
    return PersistentVolume(
        d["n"], capacity=float(d.get("cap", 0.0)),
        storage_class=d.get("sc", ""),
        node_affinity=tuple(_term_from(t) for t in d.get("na", ())),
        claim_ref=d.get("cr", ""),
    )


def _sc_to(s: StorageClass) -> dict:
    return {
        "n": s.name, "m": s.volume_binding_mode, "p": s.provisioner,
        "at": [_term_to(t) for t in s.allowed_topologies],
    }


def _sc_from(d: dict) -> StorageClass:
    return StorageClass(
        d["n"], volume_binding_mode=d.get("m", api.VOLUME_BINDING_IMMEDIATE),
        provisioner=bool(d.get("p", True)),
        allowed_topologies=tuple(_term_from(t) for t in d.get("at", ())),
    )


def _pdb_to(p: PodDisruptionBudget) -> dict:
    return {
        "n": p.name, "ns": p.namespace, "s": _lsel_to(p.selector),
        "da": p.disruptions_allowed,
    }


def _pdb_from(d: dict) -> PodDisruptionBudget:
    return PodDisruptionBudget(
        d["n"], namespace=d.get("ns", "default"),
        selector=_lsel_from(d.get("s", {})),
        disruptions_allowed=int(d.get("da", 0)),
    )


def trace_to_dict(t: Trace) -> dict:
    return dataclasses.asdict(t)


def trace_from_dict(d: dict) -> Trace:
    if int(d.get("version", 1)) != TRACE_VERSION:
        raise ValueError(
            f"trace version {d.get('version')!r} != {TRACE_VERSION}"
        )
    return Trace(**{
        f.name: d[f.name]
        for f in dataclasses.fields(Trace)
        if f.name in d
    })


def save_trace(path: str, t: Trace) -> None:
    with open(path, "w") as f:
        json.dump(trace_to_dict(t), f, indent=1, sort_keys=True)


def load_trace(path: str) -> Trace:
    with open(path) as f:
        return trace_from_dict(json.load(f))


def materialize(t: Trace) -> dict:
    """Fresh API objects for ONE replay side (never share across
    sides: the engine mutates pods in place)."""
    return {
        "nodes": [node_from_state(d) for d in t.nodes],
        "pod_groups": [PodGroup(g["n"], int(g["mm"])) for g in t.pod_groups],
        "pvcs": [_pvc_from(d) for d in t.pvcs],
        "pvs": [_pv_from(d) for d in t.pvs],
        "storage_classes": [_sc_from(d) for d in t.storage_classes],
        "pdbs": [_pdb_from(d) for d in t.pdbs],
    }


def materialize_event(ev: dict) -> dict:
    """Decode one event's payload into fresh objects."""
    out: dict[str, Any] = {"op": ev["op"]}
    if "pod" in ev:
        out["pod"] = pod_from_state(ev["pod"])
    if "node" in ev:
        out["node"] = node_from_state(ev["node"])
    for k in ("uid", "name", "bind_node"):
        if k in ev:
            out[k] = ev[k]
    return out


# --------------------------------------------------------------------------
# the generator
# --------------------------------------------------------------------------


def _gen_node(rng: random.Random, name: str, *, uniform: bool,
              taint_p: float) -> dict:
    if uniform:
        cpu, mem = 8, 16
    else:
        cpu = rng.choice((4, 8))
        mem = rng.choice((8, 16))
    b = MakeNode(name).capacity(
        {"cpu": str(cpu), "memory": f"{mem}Gi", "pods": 110}
    ).labels({
        "topology.kubernetes.io/zone": rng.choice(ZONES),
        "node-type": rng.choice(NODE_TYPES),
    })
    if rng.random() < taint_p:
        b.taint("dedicated", "special")
    return node_to_state(b.obj())


def _gen_pod(
    rng: random.Random,
    name: str,
    created: float,
    *,
    groups: list,
    claims: list,
    churn_ok: bool,
    heavy: bool = False,
    flat_priority: bool = False,
    envelope_only: bool = False,
) -> dict:
    app = rng.choice(APPS)
    if heavy:
        cpu_m = rng.choice((2000, 3000, 4000))
    else:
        cpu_m = rng.choice((250, 500, 1000))
    if flat_priority:
        # uniform priorities make preemption structurally impossible
        # (no victim can rank below a preemptor) — multi-cycle traces
        # need that, see generate_trace
        pri = 0
    else:
        pri = rng.choice((0, 0, 5, 10)) if not heavy else 100
    b = (
        MakePod(name)
        .req({"cpu": f"{cpu_m}m", "memory": f"{rng.choice((256, 512))}Mi"})
        .labels({"app": app})
        .priority(pri)
        .created(created)
    )
    if rng.random() < 0.30:
        b.node_selector({"node-type": rng.choice(NODE_TYPES)})
    if rng.random() < 0.30:
        b.toleration("dedicated", "special", "NoSchedule")
    # envelope_only (speculative depth-2 traces): the capability draws
    # are still consumed — the stamp's spec flag must not shift the rng
    # stream — but the envelope-leaving features (affinity / spread /
    # volumes / host ports, cycle.multicycle_unsupported_reason) are
    # not applied, so the trace actually exercises the device loop the
    # variant pipelines instead of pinning the profile out of batching
    # on its first affinity pod. Plain multi-cycle traces keep drawing
    # them: the envelope-exit fallback is itself a fuzzed path.
    if rng.random() < 0.25 and not envelope_only:
        b.pod_affinity("topology.kubernetes.io/zone", {"app": app})
    if rng.random() < 0.25 and not envelope_only:
        b.pod_affinity("kubernetes.io/hostname", {"app": app}, anti=True)
    if rng.random() < 0.20 and not envelope_only:
        b.spread(rng.choice((1, 2)), "topology.kubernetes.io/zone",
                 {"app": app},
                 when_unsatisfiable=rng.choice(
                     (api.DO_NOT_SCHEDULE, api.SCHEDULE_ANYWAY)))
    if churn_ok and rng.random() < 0.08:
        b.host_port(8000 + rng.randrange(4))
    if groups and rng.random() < 0.30:
        b.group(rng.choice(groups)["n"])
    if claims and rng.random() < 0.5 and not envelope_only:
        b.volume(claims.pop(0)["n"])
    if rng.random() < 0.08:
        b.preemption_policy("Never")
    return pod_to_state(b.obj())


def _gen_tenant_node(rng: random.Random, tenant: str, name: str) -> dict:
    n = MakeNode(name).capacity(
        {"cpu": str(rng.choice((4, 8))),
         "memory": f"{rng.choice((8, 16))}Gi", "pods": 110}
    ).labels({
        "topology.kubernetes.io/zone": rng.choice(ZONES),
        "node-type": rng.choice(NODE_TYPES),
    }).obj()
    # virtual clusters own their nodes: tenant identity rides the
    # namespace, uid stays namespace-qualified like every object
    n.metadata.namespace = tenant
    n.metadata.uid = f"{tenant}/{name}"
    return node_to_state(n)


def _gen_tenant_pod(rng: random.Random, tenant: str, name: str,
                    created: float) -> dict:
    """Deliberately inside the shared-shape envelope: requests, labels
    and selectors from the SAME vocabulary every tenant draws from, no
    affinity/volumes/gangs — tenant workloads must quantize into a
    small set of PackSpec keys for the arena to stack them, and the
    leak-injection self-test needs >= 2 tenants per bucket to have a
    row to roll."""
    b = (
        MakePod(name, namespace=tenant)
        .req({"cpu": f"{rng.choice((250, 500, 1000))}m",
              "memory": f"{rng.choice((256, 512))}Mi"})
        .labels({"app": rng.choice(APPS)})
        .created(created)
    )
    if rng.random() < 0.25:
        b.node_selector({"node-type": rng.choice(NODE_TYPES)})
    return pod_to_state(b.obj())


def generate_multitenant_trace(
    seed: int, *, tenants: "int | None" = None
) -> Trace:
    """Multi-tenant arena scenario: N virtual clusters, each with its
    own namespaced nodes and pod arrivals, plus tenant lifecycle churn
    (suspend/resume, pod deletes). Replayed by `replay.run_tenant_case`
    — the packed arena against the per-tenant sequential reference,
    per-tenant decision streams bit-equal — NOT by the single-cluster
    engine/oracle differential (`config["tenancy"]` is the routing
    flag run_case dispatches on). Every tenant draws the same node
    count and the same pod vocabulary so shapes quantize into shared
    PackSpec keys; the same seed + kwargs reproduce the same trace."""
    rng = random.Random(seed)
    n_t = tenants if tenants is not None else rng.randint(2, 4)
    tids = [f"team-{i}" for i in range(n_t)]
    n_nodes = rng.randint(2, 6)  # one draw: same N pad bucket fleet-wide
    nodes = [
        _gen_tenant_node(rng, tid, f"{tid}-n{i}")
        for tid in tids
        for i in range(n_nodes)
    ]
    tenancy = {
        tid: {"quota": 0, "weight": rng.choice((1.0, 1.0, 2.0))}
        for tid in tids
    }

    n_cycles = rng.randint(3, 6)
    cycles: list[list[dict]] = []
    live: dict[str, list[str]] = {tid: [] for tid in tids}
    suspended: set[str] = set()
    uid_counter = 0
    created = 0.0
    for _c in range(n_cycles):
        evs: list[dict] = []
        for tid in tids:
            if tid in suspended:
                continue
            for _ in range(rng.randint(0, 3)):
                name = f"p{uid_counter}"
                uid_counter += 1
                evs.append({
                    "op": "add_pod",
                    "pod": _gen_tenant_pod(rng, tid, name, created),
                })
                created += 1.0
                live[tid].append(f"{tid}/{name}")
        r = rng.random()
        if r < 0.15 and len(tids) - len(suspended) > 1:
            tid = rng.choice([t for t in tids if t not in suspended])
            suspended.add(tid)
            evs.append({"op": "suspend_tenant", "tenant": tid})
        elif r < 0.25 and suspended:
            tid = rng.choice(sorted(suspended))
            suspended.discard(tid)
            evs.append({"op": "resume_tenant", "tenant": tid})
        elif r < 0.35:
            all_live = [(t, u) for t in tids for u in live[t]]
            if all_live:
                tid, u = all_live[rng.randrange(len(all_live))]
                live[tid].remove(u)
                evs.append({"op": "delete_pod", "tenant": tid, "uid": u})
        cycles.append(evs)
    cycles.extend([[], []])  # drain ticks: losers get their next cycle

    config = {
        "commit_mode": "scan",
        "gang_scheduling": True,
        "tenancy": {"tenants": tenancy},
    }
    return Trace(
        seed=seed, config=config, nodes=nodes, pod_groups=[], pvcs=[],
        pvs=[], storage_classes=[], pdbs=[], cycles=cycles, tick_s=0.0,
    )


def generate_trace(
    seed: int,
    *,
    devices: int = 1,
    chaos: bool = False,
    multi_cycle: "bool | None" = None,
    speculative: bool = False,
    incremental: bool = False,
) -> Trace:
    """One random scenario. `devices` > 1 turns on sharded serving
    (`shardDevices`; placements must stay bit-identical — PR 9's
    contract). `multi_cycle` forces the K=4 coalescing path (None =
    seeded coin); multi-cycle traces are ARRIVALS-ONLY, FROZEN-CLOCK
    (tick_s=0), and PREEMPTION-FREE (uniform priorities, so no victim
    can ever rank below a preemptor) — churn between buffered groups,
    backoff retries whose re-activation shifts to the flush cycle, and
    eviction informer echoes that land after the flush instead of
    between inner cycles are all legitimate semantic differences of
    the batch window, not engine bugs, so the generator keeps those
    traces inside the exactness envelope the PR 6 equivalence suite
    defines (whose own drive freezes the clock for the same reason).
    `chaos` fuses a random `FaultPlan` over the trace (engine side
    only) and appends a recovery tail so the ladder invariants are
    decidable. `speculative` turns on the depth-2 speculative dispatch
    variant (speculativeDispatch; forces the K=4 coalescing path it
    pipelines) — a pure config switch drawing nothing from the rng, so
    a stamp's spec=<0|1> reproduces the identical trace either way.
    `incremental` turns on admission-time incremental encode
    (incrementalEncode; forces the K=4 coalescing path it feeds) —
    like `speculative`, a pure config switch drawing nothing from the
    rng, so a stamp's inc=<0|1> reproduces the identical trace."""
    rng = random.Random(seed)
    # the coin is drawn UNCONDITIONALLY so an explicit multi_cycle flag
    # (replaying a FUZZ-FAIL stamp's mc=<0|1>) consumes the same rng
    # stream as the seeded coin did — the stamp must reproduce the
    # identical trace, not a shifted one
    mc_coin = rng.random() < 0.25
    if speculative or incremental:
        multi_cycle = True
    elif multi_cycle is None:
        multi_cycle = mc_coin
    churn_ok = not multi_cycle
    uniform = rng.random() < 0.5  # identical nodes -> score ties abound
    n_nodes = rng.randint(4, 10)
    nodes = [
        _gen_node(rng, f"n{i}", uniform=uniform, taint_p=0.2)
        for i in range(n_nodes)
    ]

    pod_groups = []
    if rng.random() < 0.4:
        pod_groups = [
            {"n": f"job-{g}", "mm": rng.randint(2, 3)}
            for g in range(rng.randint(1, 2))
        ]

    pvcs, pvs, classes = [], [], []
    claims: list = []
    if rng.random() < 0.35:
        GiB = 2 ** 30
        classes = [_sc_to(StorageClass(
            "local", api.VOLUME_BINDING_WAIT, provisioner=False,
        ))]
        n_pv = rng.randint(2, 5)
        for v in range(n_pv):
            na = ()
            if rng.random() < 0.5:  # PV topology: zone-pinned volumes
                na = (api.NodeSelectorTerm((api.NodeSelectorRequirement(
                    "topology.kubernetes.io/zone", api.OP_IN,
                    (rng.choice(ZONES),),
                ),)),)
            pvs.append(_pv_to(PersistentVolume(
                f"pv-{v}", capacity=10 * GiB, storage_class="local",
                node_affinity=na,
            )))
        for j in range(rng.randint(2, n_pv + 2)):
            c = PersistentVolumeClaim(
                f"claim-{j}", storage_class="local", request=5 * GiB
            )
            pvcs.append(_pvc_to(c))
            claims.append({"n": c.name})

    pdbs = []
    if rng.random() < 0.4:
        for i in range(rng.randint(1, 2)):
            pdbs.append(_pdb_to(PodDisruptionBudget(
                f"pdb-{i}",
                selector=LabelSelector(
                    match_labels={"app": rng.choice(APPS)}
                ),
                disruptions_allowed=rng.randint(0, 2),
            )))

    n_cycles = rng.randint(5, 9)
    cycles: list[list[dict]] = []
    uid_counter = 0
    live_uids: list[str] = []  # added, not yet deleted (pending or bound)
    churn_nodes: list[str] = []  # nodes added mid-trace (delete targets)
    created = 0.0

    # cycle 0 pre-load: a low-priority existing workload occupying
    # capacity, so high-priority arrivals exercise real preemption
    # pressure (they must fit where placed: <=2 small pods per node)
    ev0: list[dict] = []
    n_exist = rng.randint(0, 2 * n_nodes)
    for i in range(n_exist):
        p = (
            MakePod(f"run{seed % 1000}-{i}")
            .req({"cpu": "500m", "memory": "256Mi"})
            .labels({"app": rng.choice(APPS)})
            .priority(0)
            .created(created)
        )
        created += 1.0
        ev0.append({
            "op": "add_bound_pod",
            "pod": pod_to_state(p.obj()),
            "bind_node": f"n{i % n_nodes}",
        })
    cycles.append(ev0)

    for _c in range(n_cycles):
        evs: list[dict] = []
        n_heavy = 1 if (churn_ok and rng.random() < 0.3) else 0
        n_arrive = rng.randint(1, 5)
        for ai in range(n_arrive + n_heavy):
            heavy = n_heavy > 0 and ai == n_arrive  # last arrival
            name = f"f{seed % 1000}-p{uid_counter}"
            uid_counter += 1
            evs.append({
                "op": "add_pod",
                "pod": _gen_pod(
                    rng, name, created, groups=pod_groups,
                    claims=claims, churn_ok=churn_ok, heavy=heavy,
                    flat_priority=multi_cycle,
                    # envelope_only for the same reason as speculative:
                    # the incremental variant tests the coalescing
                    # flush's encode, so the trace must actually stay
                    # on the multi-cycle path
                    envelope_only=speculative or incremental,
                ),
            })
            created += 1.0
            live_uids.append(f"default/{name}")
        if churn_ok:
            if live_uids and rng.random() < 0.3:
                u = live_uids.pop(rng.randrange(len(live_uids)))
                evs.append({"op": "delete_pod", "uid": u})
            r = rng.random()
            if r < 0.10:
                nm = f"nx{uid_counter}"
                evs.append({
                    "op": "add_node",
                    "node": _gen_node(rng, nm, uniform=uniform,
                                      taint_p=0.2),
                })
                churn_nodes.append(nm)
            elif r < 0.18:
                # drain: re-deliver an initial node as unschedulable
                nd = node_from_state(rng.choice(nodes))
                nd.spec.unschedulable = True
                evs.append({"op": "update_node",
                            "node": node_to_state(nd)})
            elif r < 0.24 and churn_nodes:
                evs.append({
                    "op": "delete_node",
                    "name": churn_nodes.pop(
                        rng.randrange(len(churn_nodes))
                    ),
                })
        cycles.append(evs)

    # drain tail: empty pops flush any coalescing buffer; under chaos a
    # recovery tail with trivial arrivals (promotion only counts cycles
    # that exercised the dispatch path) lets the ladder walk back to 0
    fault_spec = ""
    if chaos:
        rules = []
        fault_cycles = sorted(
            rng.sample(range(3, 3 + n_cycles), k=min(3, n_cycles))
        )
        points = rng.sample(
            ["fetch_delay", "fetch_hang", "device_error", "clock_skew"],
            k=len(fault_cycles),
        )
        for cyc, point in zip(fault_cycles, points):
            if point == "fetch_delay":
                rules.append(f"fetch_delay@cycle={cyc}:ms={rng.choice((60, 120))}:n=1")
            elif point == "fetch_hang":
                # far past the deadline AND past any plausible compile:
                # the watchdog check (_chaos_checks) requires the hang
                # cycle's wall to stay strictly UNDER the full ms plus
                # a deadline-classified ladder step, and early-trace
                # cycles legitimately pay seconds of XLA compile before
                # the bounded fetch — ms must dominate that budget
                rules.append(f"fetch_hang@cycle={cyc}:ms=15000:n=1")
            elif point == "device_error":
                kind = rng.choice(("transport", "corrupt", "wedge"))
                rules.append(f"device_error@cycle={cyc}:kind={kind}:n=1")
            else:
                rules.append(f"clock_skew@cycle={cyc}:ms={rng.choice((100, 400))}:n=1")
        fault_spec = f"seed={seed};" + ";".join(rules)
        for i in range(14):
            name = f"f{seed % 1000}-tail{i}"
            p = (MakePod(name).req({"cpu": "250m", "memory": "128Mi"})
                 .labels({"app": "app-0"}).created(created))
            created += 1.0
            live_uids.append(f"default/{name}")
            cycles.append([{"op": "add_pod", "pod": pod_to_state(p.obj())}])
    cycles.extend([[], []])

    config = {
        "commit_mode": "scan",
        "gang_scheduling": True,
        "multi_cycle_k": 4 if multi_cycle else 1,
        # never the flush trigger: the ticking trace clock would trip a
        # real-units bound every cycle — batches flush on K or idle pops
        "multi_cycle_max_wait_ms": 1e12,
        "shard_devices": devices if devices > 1 else 0,
        # depth-2 speculative dispatch pipelining over the coalesced
        # batches: the differential asserts the adopted/abandoned/
        # re-dispatched streams stay bit-equal to the oracle's
        "speculative_dispatch": bool(speculative),
        # admission-time incremental encode over the coalesced batches:
        # the differential asserts the packed arenas stay byte-identical
        # and the decision/journal/event streams bit-equal to the
        # rebuild engine's
        "incremental_encode": bool(incremental),
        "pad_bucket": 8,
        "dispatch_deadline_ms": 300.0 if chaos else 0.0,
        "degrade_promote_cycles": 2,
    }
    return Trace(
        seed=seed, config=config, nodes=nodes, pod_groups=pod_groups,
        pvcs=pvcs, pvs=pvs, storage_classes=classes, pdbs=pdbs,
        cycles=cycles, fault_spec=fault_spec,
        # frozen clock under coalescing: backoff re-activation times
        # shift to the flush cycle, a legal batch-window difference the
        # differential must not read as divergence
        tick_s=0.0 if multi_cycle else 16.0,
    )
