"""Snapshot encoding: typed Pod/Node objects -> structure-of-arrays tensors.

This is the TPU-native replacement for the reference's `SchedulerCache`
snapshot (`internal/cache/snapshot.go`, `framework/types.go` NodeInfo —
[UNVERIFIED] locations, mount empty; SURVEY.md §2 C4/C5): instead of a list
of per-node `NodeInfo` structs walked by goroutines, the cluster state is a
set of padded, integer-interned device arrays that one jitted program
consumes.

Encoding strategy (SURVEY.md §7 step 1 + "hard parts" (c)):

- **Interning.** Every string (label keys/values, taint keys, namespaces,
  image names, topology keys) becomes an int32 id via `StringInterner`.
- **Dedup + gather.** Pod-side structures that repeat across pods (node
  affinity requirements, toleration sets, label selectors, image sets) are
  deduplicated into small tables; each pod stores table indices. Kernels
  evaluate the small table against all nodes/pods, then a gather expands to
  the pods axis — O(distinct x N) instead of O(P x N x terms).
- **Padding.** Every ragged axis is padded to a bucketed size with -1
  sentinels so shapes are static across cycles and jit caches stay warm.
- **Label expressions** (`In/NotIn/Exists/DoesNotExist/Gt/Lt`) become rows
  of one expression table usable against node labels and pod labels alike;
  `matchFields` (metadata.name) rows resolve to node-index sets at encode
  time (FIELD_IN).

Namespace scoping of pod-affinity selectors is encoded as an extra implicit
expression on a reserved label key (`__namespace__`), which is injected into
every pod's encoded label list.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, NamedTuple, Sequence

import numpy as np

from . import api
from .api import (
    Affinity,
    LabelSelector,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinityTerm,
)

# Operator codes for the expression table.
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OP_FIELD_IN = 6  # matchFields metadata.name: values are node indices
OP_IMPOSSIBLE = 7  # never matches (malformed requirement, upstream no-match)

_OP_CODE = {
    api.OP_IN: OP_IN,
    api.OP_NOT_IN: OP_NOT_IN,
    api.OP_EXISTS: OP_EXISTS,
    api.OP_DOES_NOT_EXIST: OP_DOES_NOT_EXIST,
    api.OP_GT: OP_GT,
    api.OP_LT: OP_LT,
}

# Taint effect codes.
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
_EFFECT_CODE = {
    api.NO_SCHEDULE: EFFECT_NO_SCHEDULE,
    api.PREFER_NO_SCHEDULE: EFFECT_PREFER_NO_SCHEDULE,
    api.NO_EXECUTE: EFFECT_NO_EXECUTE,
}

TOL_OP_EQUAL = 0
TOL_OP_EXISTS = 1

WHEN_DO_NOT_SCHEDULE = 0
WHEN_SCHEDULE_ANYWAY = 1

NAMESPACE_KEY = "__namespace__"
_EMPTY_I32 = np.empty(0, np.int32)
_EMPTY_F32 = np.empty(0, np.float32)


class EncodedFrame(NamedTuple):
    """encode_packed's result: the arena buffers + spec + a snapshot view
    whose fields alias them, plus which pod slots this encode rewrote.
    `dirty` is None after a full (re)build — every row changed — and an
    i32 slot-id array after a delta encode (consumers maintaining device-
    resident per-row state, e.g. the static carry, update those rows)."""

    wbuf: np.ndarray
    bbuf: np.ndarray
    spec: Any
    snap: "ClusterSnapshot"
    dirty: np.ndarray | None


def _i32(xs) -> np.ndarray:
    return np.array(xs, np.int32) if xs else _EMPTY_I32


def _f32(xs) -> np.ndarray:
    return np.array(xs, np.float32) if xs else _EMPTY_F32


HOSTNAME_LABEL = "kubernetes.io/hostname"


class StringInterner:
    """str -> dense int32 id. id 0 is reserved for "" (absent)."""

    def __init__(self) -> None:
        self._ids: dict[str, int] = {"": 0}
        self._strs: list[str] = [""]

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
        return i

    def lookup(self, i: int) -> str:
        return self._strs[i]

    def get(self, s: str) -> int:
        """Like intern but -1 for unknown (no table growth)."""
        return self._ids.get(s, -1)

    def __len__(self) -> int:
        return len(self._strs)


class _InternTable:
    """Dedup table: hashable row -> dense index, rows in insertion order.
    Every pod-side structure that repeats across pods (requirements,
    toleration sets, selectors, image sets...) goes through one of these."""

    def __init__(self) -> None:
        self.index: dict = {}
        self.rows: list = []

    def intern(self, row) -> int:
        i = self.index.get(row)
        if i is None:
            i = len(self.rows)
            self.index[row] = i
            self.rows.append(row)
        return i

    def __len__(self) -> int:
        return len(self.rows)


def _pad_dim(n: int, bucket: int = 8, minimum: int = 1) -> int:
    """Round up to a bucket multiple so shapes are stable across cycles."""
    n = max(n, minimum)
    return ((n + bucket - 1) // bucket) * bucket


def _pow2_bucket(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two (jit-cache-friendly P/N padding)."""
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


def _num_or_nan(s: str) -> float:
    try:
        return float(s)
    except ValueError:
        return float("nan")


@dataclass
class ClusterSnapshot:
    """The device-consumable cluster state. All arrays are numpy on the host;
    `jax.device_put` (or simply passing into a jitted function) moves them.

    Axis glossary: N nodes, P pending pods, E existing (assigned/assumed)
    pods, R resources, Ex label expressions, Rq node-affinity requirement
    sets, Pf preferred-node-affinity sets, Tl toleration sets, Ts taint
    sets, S pod label selectors, D flat topology domains, K topology keys,
    I distinct images, Is distinct image sets, G pod groups, MPN max pods
    per node (preemption table).
    """

    # --- names (static aux data, baked into the compiled program) ---
    resource_names: tuple[str, ...]
    topology_keys: tuple[str, ...]  # interned topology key strings, order = K axis
    # padded count of distinct pending host ports (Q axis of the scan's
    # port-claim bitmap; static because it is a shape, bucketed by 4)
    num_distinct_ports: int
    # capability flags (static): when False, the corresponding plugin
    # contributes nothing and its whole kernel is never traced — a cluster
    # without affinity pays zero for the affinity machinery
    has_inter_pod_affinity: bool
    has_topology_spread: bool
    has_volumes: bool
    # some pod really mounts >= 2 PVCs: gates the multi-volume joint-
    # admission machinery (Hall subset matmuls, claim-order permutation)
    # — MVol is a sticky PAD dim (bucket 2), so the dim alone would run
    # that machinery as guaranteed identity work on 1-PVC clusters
    has_multi_volume: bool

    # --- real (unpadded) counts: 0-d arrays, NOT static — a changed pod
    # count must not recompile the cycle (only padded shapes are static) ---
    num_nodes: np.ndarray
    num_pending: np.ndarray
    num_existing: np.ndarray
    num_domains: np.ndarray
    # monotone per-encoder cycle counter (0-d i32): rotates the node-
    # sampling windows across cycles so percentageOfNodesToScore can never
    # permanently starve a pod whose feasible nodes sit outside one window
    cycle_index: np.ndarray

    # --- nodes [N...] ---
    node_allocatable: np.ndarray  # f32 [N, R]
    node_requested: np.ndarray  # f32 [N, R] aggregated from existing pods
    node_unschedulable: np.ndarray  # bool [N]
    node_taintset: np.ndarray  # i32 [N] -> Ts
    node_label_keys: np.ndarray  # i32 [N, ML]
    node_label_vals: np.ndarray  # i32 [N, ML]
    node_label_num: np.ndarray  # f32 [N, ML] numeric parse of value (nan if not)
    node_domains: np.ndarray  # i32 [N, K] flat domain id (-1 = key absent)
    node_images: np.ndarray  # bool [N, I]
    node_used_ports: np.ndarray  # i32 [N, MPorts] encoded host ports (-1 pad)
    node_valid: np.ndarray  # bool [N] (padding rows are False)

    # --- label expression table [Ex...] ---
    ex_key: np.ndarray  # i32 [Ex]
    ex_op: np.ndarray  # i32 [Ex]
    ex_vals: np.ndarray  # i32 [Ex, MV] (-1 pad); node indices for FIELD_IN
    ex_num: np.ndarray  # f32 [Ex] numeric bound for Gt/Lt

    # --- node-affinity requirement sets (OR over terms of AND over exprs) ---
    rq_exprs: np.ndarray  # i32 [Rq, MT, ME] (-1 pad)

    # --- preferred node affinity [Pf...] (flat weighted AND-terms) ---
    pf_exprs: np.ndarray  # i32 [Pf, MPT, ME]
    pf_weight: np.ndarray  # f32 [Pf, MPT] (0 pad)

    # --- toleration / taint set tables ---
    tl_key: np.ndarray  # i32 [Tl, MTl] (-1 = empty key i.e. match-any + Exists)
    tl_op: np.ndarray  # i32 [Tl, MTl]
    tl_val: np.ndarray  # i32 [Tl, MTl]
    tl_effect: np.ndarray  # i32 [Tl, MTl] (-1 = all effects)
    tl_valid: np.ndarray  # bool [Tl, MTl]
    ts_key: np.ndarray  # i32 [Ts, MTt]
    ts_val: np.ndarray  # i32 [Ts, MTt]
    ts_effect: np.ndarray  # i32 [Ts, MTt]
    ts_valid: np.ndarray  # bool [Ts, MTt]

    # --- pod label selectors [S...] (AND of exprs, incl. namespace expr) ---
    sel_exprs: np.ndarray  # i32 [S, MSE] (-1 pad)

    # --- pending pods [P...] ---
    pod_requested: np.ndarray  # f32 [P, R]
    pod_priority: np.ndarray  # i32 [P]
    pod_order: np.ndarray  # i32 [P] rank by (priority desc, creation ts asc)
    pod_node_name: np.ndarray  # i32 [P] node index pin (-1 none)
    pod_nominated: np.ndarray  # i32 [P] node index (-1 none)
    pod_req_id: np.ndarray  # i32 [P] -> Rq (node affinity required; -1 none)
    pod_sel_req_id: np.ndarray  # i32 [P] -> Rq (nodeSelector; -1 none)
    pod_pref_id: np.ndarray  # i32 [P] -> Pf (-1 none)
    pod_tolset: np.ndarray  # i32 [P] -> Tl
    pod_label_keys: np.ndarray  # i32 [P, MPL]
    pod_label_vals: np.ndarray  # i32 [P, MPL]
    pod_ports: np.ndarray  # i32 [P, MPorts] encoded host ports (-1 pad)
    # same ports as indices into the distinct pending-port axis Q — the
    # commit scan tracks intra-batch port claims as a [N, Q] bitmap
    pod_port_ids: np.ndarray  # i32 [P, MPorts] -> Q (-1 pad)
    pod_aff_terms: np.ndarray  # i32 [P, MA, 2] (sel, topo-key idx) (-1 pad)
    pod_anti_terms: np.ndarray  # i32 [P, MA, 2]
    pod_pref_aff: np.ndarray  # i32 [P, MA, 2] preferred affinity terms
    pod_pref_aff_w: np.ndarray  # f32 [P, MA] weights (anti encoded as negative)
    pod_tsc: np.ndarray  # i32 [P, MC, 3] (topo-key idx, sel, when) (-1 pad)
    pod_tsc_skew: np.ndarray  # i32 [P, MC] max_skew (0 pad)
    pod_group: np.ndarray  # i32 [P] -> G (-1 none)
    pod_imageset: np.ndarray  # i32 [P] -> Is
    pod_can_preempt: np.ndarray  # bool [P] (preemptionPolicy != Never)
    pod_valid: np.ndarray  # bool [P]

    # --- volumes (VolumeBinding): per-pod PVC constraints [P, MVol] and
    # the PV table [V]. mode: -1 pad, 0 bound (vol_req = PV node-affinity
    # requirement id), 1 unbound WaitForFirstConsumer (vol_class/vol_size
    # select static PV candidates; vol_req = dynamic-provisioning
    # allowed-topology requirement id, -1 = anywhere, -2 = no dynamic),
    # 2 impossible (missing PVC / unbound Immediate) ---
    pod_vol_mode: np.ndarray  # i32 [P, MVol]
    pod_vol_req: np.ndarray  # i32 [P, MVol]
    pod_vol_class: np.ndarray  # i32 [P, MVol] interned class name
    pod_vol_size: np.ndarray  # f32 [P, MVol]
    pv_req_id: np.ndarray  # i32 [V] node-affinity requirement (-1 = any)
    pv_class: np.ndarray  # i32 [V] interned class name
    pv_capacity: np.ndarray  # f32 [V]
    pv_avail: np.ndarray  # bool [V] unclaimed

    # --- pod groups [G] ---
    group_min_member: np.ndarray  # i32 [G]
    group_existing_count: np.ndarray  # i32 [G] members already running

    # --- image sets ---
    imgset_sizes: np.ndarray  # f32 [Is, I] size in bytes of image i if in set

    # --- existing pods [E...] ---
    exist_node: np.ndarray  # i32 [E] node index
    exist_priority: np.ndarray  # i32 [E]
    exist_start: np.ndarray  # f32 [E] creation timestamp (victim tie-break)
    exist_pdb: np.ndarray  # i32 [E, MB] selecting PDB ids (-1 pad)
    exist_requested: np.ndarray  # f32 [E, R]
    exist_label_keys: np.ndarray  # i32 [E, MPL]
    exist_label_vals: np.ndarray  # i32 [E, MPL]
    exist_ports: np.ndarray  # i32 [E, MEP] their host ports (-1 pad) —
    # preemption's what-if needs per-victim ports, not just the per-node
    # aggregate, to know whether evicting a prefix frees a port
    exist_anti_terms: np.ndarray  # i32 [E, MA, 2] their required anti-affinity
    exist_pref_aff: np.ndarray  # i32 [E, MA, 2] their preferred (anti) affinity
    exist_pref_aff_w: np.ndarray  # f32 [E, MA] (anti negative)
    exist_valid: np.ndarray  # bool [E]

    # --- per-node existing-pod table for preemption [N, MPN] ---
    # indices into E, sorted ascending by priority (victims are prefixes)
    node_pods: np.ndarray  # i32 [N, MPN] (-1 pad)

    # --- topology domains ---
    domain_key: np.ndarray  # i32 [D] which topology-key axis each domain is under
    # number of nodes per domain (for spread normalization)
    domain_node_count: np.ndarray  # f32 [D]

    # --- PodDisruptionBudgets [GP] (preemption consumes them) ---
    pdb_allowed: np.ndarray  # i32 [GP] status.disruptionsAllowed

    # --- HTTP-extender verdicts (host-computed AFTER encode via
    # dataclasses.replace; None and never traced unless `has_extender`) ---
    has_extender: bool = False
    pod_extender_mask: np.ndarray = None  # bool [P, N]
    pod_extender_score: np.ndarray = None  # f32 [P, N] weighted

    @property
    def P(self) -> int:
        return self.pod_requested.shape[0]

    @property
    def N(self) -> int:
        return self.node_allocatable.shape[0]

    @property
    def E(self) -> int:
        return self.exist_node.shape[0]

    @property
    def R(self) -> int:
        return len(self.resource_names)

    def array_fields(self) -> dict[str, np.ndarray]:
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if isinstance(getattr(self, f.name), np.ndarray)
        }


# Register as a jax pytree with the non-array fields as static aux data, so
# a ClusterSnapshot can be passed straight into jitted kernels.
def _register_pytree() -> None:
    import jax

    data = [f.name for f in dataclasses.fields(ClusterSnapshot)
            if f.type == "np.ndarray"]
    meta = [f.name for f in dataclasses.fields(ClusterSnapshot)
            if f.type != "np.ndarray"]
    jax.tree_util.register_dataclass(
        ClusterSnapshot, data_fields=data, meta_fields=meta
    )


_register_pytree()


class SnapshotEncoder:
    """Builds `ClusterSnapshot`s. Holds interners AND the derived intern
    tables (expressions, selectors, tolerations, taints, requirement sets,
    image sets, groups, topology keys, domains) so every id is stable
    across cycles — which lets per-object encoded rows be CACHED: a pod or
    node object seen before costs one dict lookup plus array writes
    instead of re-running the compile pipeline. Steady-state re-encodes
    (same cluster, fresh pending set) are dominated by row assembly, not
    Python compilation."""

    def __init__(
        self,
        resource_names: Sequence[str] = api.DEFAULT_RESOURCES,
        pad_pods: int | None = None,
        pad_nodes: int | None = None,
        queue_sort=None,  # QueueSortPlugin; None = PrioritySort
        pad_existing: int | None = None,  # pre-size the sticky E pad: a
        # deployment that folds bindings into the existing set should set
        # this to its expected steady-state existing count so the E
        # regime (and the ~100 s cold recompile a regime flip costs)
        # never changes mid-serving
        pad_pods_per_node: int | None = None,  # pre-size the sticky MPN
        # (victim-table) pad the same way: bind-folds deepen hot nodes'
        # pod lists, and an MPN flip is a full regime change too. NOTE
        # the preemption what-if tables scale with MPN — size to the
        # realistic hot-node depth, not the worst case
        pad_ma: int | None = None,  # pre-size the sticky MA pad (max
        # (anti-)affinity/preferred terms per pod axis): MA buckets by 2,
        # so a mid-serving arrival of a 3-4-term pod flips the regime
        # (full ~100 s recompile) unless pre-sized — set to the largest
        # term count the workload can carry (ADVICE r5)
        pad_mc: int | None = None,  # pre-size the sticky MC pad
        # (topology-spread constraints per pod) the same way
        pad_hysteresis_pct: float = 0.0,  # down-step margin for the
        # P/N pad buckets (config padHysteresisPct): a shrinking real
        # count only steps the pad DOWN when it leaves at least this
        # many percent of headroom inside the smaller bucket, so a
        # workload oscillating around a bucket boundary holds the
        # larger regime instead of flip-flopping (each flip risks a
        # full recompile). 0 disables (classic immediate down-step).
    ) -> None:
        self.strings = StringInterner()
        self.resource_names = list(resource_names)
        self.pad_pods = pad_pods
        self.pad_nodes = pad_nodes
        self.pad_existing = pad_existing
        self.pad_pods_per_node = pad_pods_per_node
        self.pad_ma = pad_ma
        self.pad_mc = pad_mc
        self.pad_hysteresis_pct = float(pad_hysteresis_pct)
        # last pad actually used per hysteresis dimension ("P"/"N")
        self._held_pads: dict[str, int] = {}
        # the profile's queueSort plugin (SURVEY §2 C11): owns the
        # pod_order rank both encode paths bake into the snapshot
        if queue_sort is None:
            from ..framework.queuesort import PrioritySort

            queue_sort = PrioritySort()
        self.queue_sort = queue_sort
        # persistent intern tables (grow-only; ids stable across encodes)
        self._exprs_t = _InternTable()  # rows: (key, op, vals, num)
        self._reqs_t = _InternTable()  # rows: tuple of terms (expr-id tuples)
        self._prefs_t = _InternTable()  # rows: tuple of (exprs, weight)
        self._tols_t = _InternTable()  # rows: sorted (key, op, val, effect)
        self._taints_t = _InternTable()  # rows: sorted (key, val, effect)
        self._sels_t = _InternTable()  # rows: tuple of expr ids
        self._imgsets_t = _InternTable()  # rows: sorted image ids
        self._image_ids: dict[str, int] = {}
        self._image_sizes: dict[int, float] = {}
        self._group_ids: dict[str, int] = {}
        self._topo_keys: list[str] = [HOSTNAME_LABEL]
        # index mirrors of the list-shaped tables (shared with the
        # native pod_row builder; kept in sync wherever the list grows)
        self._topo_idx: dict[str, int] = {HOSTNAME_LABEL: 0}
        self._rn_idx: dict[str, int] = {
            n: i for i, n in enumerate(self.resource_names)
        }
        self._domain_map: dict[tuple[int, int], int] = {}
        # per-object row caches, keyed by id(); the tuple holds a strong
        # reference so a live entry's id can never be reused. matchFields
        # expressions bake node INDICES in, so entries carrying them are
        # tagged with the node epoch and recompiled when the node set maps
        # differently.
        self._pod_cache: dict[int, tuple[Any, dict]] = {}
        self._node_cache: dict[int, tuple[Any, dict]] = {}
        self._node_epoch = 0
        self._node_names: tuple[str, ...] = ()
        self._cycle_index = 0  # bumped per encode (sampling rotation)
        # sticky (grow-only) pending-side pad dims and capability flags:
        # without them a pod with the cycle's longest label list LEAVING
        # would shrink a padded dim, change the packed spec, and force a
        # full recompile — the exact regime churn the pad bucketing exists
        # to avoid. Padding rows are semantically inert, so growing-only is
        # safe; it also makes the delta path (encode_packed) applicable.
        self._sticky_dims: dict[str, int] = {}
        self._sticky_flags: dict[str, bool] = {}
        # state for the delta fast path; see encode_packed
        self._delta_state: dict | None = None
        self._arena_spec = None
        # observability: how many encode_packed calls hit the delta path
        self.delta_hits = 0
        self.full_encodes = 0
        # per-segment ms of the LAST delta encode (see _encode_delta)
        self.delta_profile: dict[str, float] = {}
        # admission-time incremental encode (ingest/finalize split, PR 16):
        # rows parsed ahead of the flush, keyed by id(pod) with a strong
        # pod ref pinned so the id cannot be recycled. `ingest_hits` counts
        # dirty slots whose flush-time parse was skipped because a staged
        # row was waiting; `ingest_misses` counts ingest_pod calls that
        # could not stage (no delta state yet, or no rowdata closure).
        self._staged: dict[int, tuple[Any, dict]] = {}
        self._staged_grew = False  # ingest grew an interning table
        self.ingest_hits = 0
        self.ingest_misses = 0
        self._ingest_ms = 0.0  # accumulated staging ms since last flush

    def hysteresis_pad(self, dim: str, candidate: int, real: int) -> int:
        """Regime hysteresis for the externally-bucketed P/N pads: the
        pad a caller should actually use for this encode, given the
        bucket-rounded `candidate` and the `real` count behind it.

        Up-steps are immediate (the candidate no longer fits the held
        regime). A DOWN-step is taken only when the real count leaves at
        least `pad_hysteresis_pct` percent of headroom inside the
        smaller bucket — a count hovering just under the boundary keeps
        the larger (already-compiled) regime, so an oscillating
        workload costs zero regime flips instead of one per crossing.
        With the knob at 0 this is the identity on `candidate`."""
        held = self._held_pads.get(dim, 0)
        pct = self.pad_hysteresis_pct
        if (
            candidate >= held
            or pct <= 0.0
            or real <= candidate * (1.0 - pct / 100.0)
        ):
            self._held_pads[dim] = candidate
            return candidate
        return held

    def _stick(self, key: str, val: int) -> int:
        cur = self._sticky_dims.get(key, 0)
        if val < cur:
            val = cur
        self._sticky_dims[key] = val
        return val

    def _stick_flag(self, key: str, val: bool) -> bool:
        cur = self._sticky_flags.get(key, False) or bool(val)
        self._sticky_flags[key] = cur
        return cur

    def _table_lens(self) -> tuple:
        """Sizes of every grow-only interning structure a cached row can
        reference — if any changes while encoding a pod row, the stable-
        side finalize tables need new entries and the delta path must fall
        back to a full encode."""
        return (
            len(self.strings), len(self.resource_names), len(self._exprs_t),
            len(self._reqs_t), len(self._prefs_t), len(self._tols_t),
            len(self._taints_t), len(self._sels_t), len(self._imgsets_t),
            len(self._image_ids), len(self._group_ids), len(self._topo_keys),
        )

    # -- small helpers -----------------------------------------------------

    def _resources_vec(self, req: dict[str, float]) -> np.ndarray:
        idx = self._rn_idx
        for name in req:
            if name not in idx:
                idx[name] = len(self.resource_names)
                self.resource_names.append(name)
        v = np.zeros(len(self.resource_names), np.float32)
        for name, val in req.items():
            v[idx[name]] = val
        return v

    def _native_ctx(self) -> dict:
        """The persistent interning structures handed to the native
        pod_row builder (native/fastassemble.cc) — built once; every
        entry is a live reference to a grow-only table, so the ctx never
        staleness-invalidates."""
        ctx = getattr(self, "_native_ctx_cache", None)
        if ctx is None:
            ctx = {
                "str_ids": self.strings._ids,
                "str_list": self.strings._strs,
                "exprs_idx": self._exprs_t.index,
                "exprs_rows": self._exprs_t.rows,
                "sels_idx": self._sels_t.index,
                "sels_rows": self._sels_t.rows,
                "reqs_idx": self._reqs_t.index,
                "reqs_rows": self._reqs_t.rows,
                "tols_idx": self._tols_t.index,
                "tols_rows": self._tols_t.rows,
                "imgsets_idx": self._imgsets_t.index,
                "imgsets_rows": self._imgsets_t.rows,
                "image_ids": self._image_ids,
                "group_ids": self._group_ids,
                "topo_idx": self._topo_idx,
                "topo_list": self._topo_keys,
                "rn_idx": self._rn_idx,
                "rn_list": self.resource_names,
                "ns_key": NAMESPACE_KEY,
                "pods_name": api.PODS,
                "effect_codes": dict(_EFFECT_CODE),
                "op_in": OP_IN,
                "op_not_in": OP_NOT_IN,
                "op_exists": OP_EXISTS,
                "op_dne": OP_DOES_NOT_EXIST,
                "tol_eq": TOL_OP_EQUAL,
                "tol_exists": TOL_OP_EXISTS,
                "when_dns": WHEN_DO_NOT_SCHEDULE,
                "when_sa": WHEN_SCHEDULE_ANYWAY,
            }
            self._native_ctx_cache = ctx
        return ctx

    def encode(
        self,
        nodes: Sequence[Node],
        pending: Sequence[Pod],
        existing: Sequence[tuple[Pod, str]] = (),
        pod_groups: Sequence[api.PodGroup] = (),
        pvcs: Sequence[api.PersistentVolumeClaim] = (),
        pvs: Sequence[api.PersistentVolume] = (),
        storage_classes: Sequence[api.StorageClass] = (),
        pdbs: Sequence[api.PodDisruptionBudget] = (),
    ) -> ClusterSnapshot:
        """One-shot encode. `existing` is (pod, node_name) for every pod
        already assigned (bound or assumed)."""
        S = self.strings
        rn = self.resource_names
        # Resource-name discovery happens as rows are built (node_rowdata /
        # pod_rowdata call _resources_vec, which appends unseen names), so
        # the R axis is read only AFTER the row walks below; cached rows
        # from earlier encodes may be shorter and are right-padded.

        n_real, p_real, e_real = len(nodes), len(pending), len(existing)
        self._cycle_index += 1
        # hysteresis applies to the DEFAULT pow2 bucketing here; callers
        # that drive pad_pods/pad_nodes themselves (the scheduler's
        # bucketed pads) route their candidates through hysteresis_pad
        # before assigning, so both paths share one held-regime state
        N = self.pad_nodes or self.hysteresis_pad(
            "N", _pow2_bucket(n_real), n_real
        )
        P = self.pad_pods or self.hysteresis_pad(
            "P", _pow2_bucket(p_real), p_real
        )
        # E is STICKY (like MPL/MA): the incremental existing-fold appends
        # bound pods in place, and a completion batch that shrinks e_real
        # must not flip the packed regime; pad_existing pre-sizes it.
        # The pad folds INTO the pow2 bucket (not max'd after) so a
        # non-power-of-two pad can never leave E below the bucket a
        # grown e_real would demand — that would re-flip the regime
        # mid-run, the exact thing pre-sizing exists to prevent.
        E = self._stick(
            "E",
            _pow2_bucket(max(e_real, self.pad_existing or 0))
            if (e_real or self.pad_existing) else 8,
        )

        node_index = {nd.name: i for i, nd in enumerate(nodes)}
        names_now = tuple(nd.name for nd in nodes)
        if names_now != self._node_names:
            self._node_names = names_now
            self._node_epoch += 1

        # ---- persistent tables (ids stable across encodes) ----
        exprs_t = self._exprs_t
        reqs_t = self._reqs_t
        prefs_t = self._prefs_t
        tols_t = self._tols_t
        taints_t = self._taints_t
        sels_t = self._sels_t
        imgsets_t = self._imgsets_t

        def intern_expr(key: int, op: int, vals: tuple[int, ...], num: float) -> int:
            return exprs_t.intern((key, op, vals, num))

        def compile_req(r: NodeSelectorRequirement) -> int:
            op = _OP_CODE[r.operator]
            vals = tuple(sorted(S.intern(v) for v in r.values))
            num = 0.0
            if op in (OP_GT, OP_LT):
                # upstream treats a missing or non-numeric bound as no-match
                try:
                    num = float(r.values[0])
                except (IndexError, ValueError):
                    return intern_expr(0, OP_IMPOSSIBLE, (), 0.0)
                vals = ()
            return intern_expr(S.intern(r.key), op, vals, num)

        def compile_field_req(r: NodeSelectorRequirement) -> int:
            # metadata.name In [names] -> node index set (FIELD_IN); only
            # In/NotIn are defined for matchFields, anything else no-matches
            if r.operator not in (api.OP_IN, api.OP_NOT_IN):
                return intern_expr(0, OP_IMPOSSIBLE, (), 0.0)
            idxs = tuple(
                sorted(node_index[v] for v in r.values if v in node_index)
            )
            # encode NotIn by op FIELD_IN with complement at kernel level is
            # messy; instead resolve the complement here (node set is known).
            if r.operator == api.OP_NOT_IN:
                idxs = tuple(i for i in range(n_real) if i not in set(idxs))
            return intern_expr(0, OP_FIELD_IN, idxs, 0.0)

        def compile_node_affinity_required(terms: Sequence[NodeSelectorTerm]) -> int:
            compiled = []
            for t in terms:
                exprs = [compile_req(e) for e in t.match_expressions]
                exprs += [compile_field_req(e) for e in t.match_fields]
                compiled.append(tuple(exprs))
            if not compiled:
                return -1
            return reqs_t.intern(tuple(compiled))

        def compile_node_affinity_preferred(
            prefs: Sequence[api.PreferredSchedulingTerm],
        ) -> int:
            rows = []
            for p in prefs:
                exprs = [compile_req(e) for e in p.preference.match_expressions]
                exprs += [compile_field_req(e) for e in p.preference.match_fields]
                rows.append((tuple(exprs), float(p.weight)))
            if not rows:
                return -1
            return prefs_t.intern(tuple(rows))

        def compile_tolerations(tols: Sequence[api.Toleration]) -> int:
            rows = []
            for t in tols:
                key = S.intern(t.key) if t.key else -1
                op = TOL_OP_EXISTS if t.operator == "Exists" else TOL_OP_EQUAL
                val = S.intern(t.value)
                eff = _EFFECT_CODE[t.effect] if t.effect else -1
                rows.append((key, op, val, eff))
            return tols_t.intern(tuple(sorted(rows)))

        def compile_taints(taints: Sequence[api.Taint]) -> int:
            return taints_t.intern(
                tuple(
                    sorted(
                        (S.intern(t.key), S.intern(t.value), _EFFECT_CODE[t.effect])
                        for t in taints
                    )
                )
            )

        topo_keys = self._topo_keys
        topo_idx = self._topo_idx

        def topo_key_idx(key: str) -> int:
            i = topo_idx.get(key)
            if i is None:
                i = len(topo_keys)
                topo_idx[key] = i
                topo_keys.append(key)
            return i

        def compile_selector(sel: LabelSelector, namespaces: tuple[str, ...]) -> int:
            exprs = []
            ns_vals = tuple(sorted(S.intern(n) for n in namespaces))
            exprs.append(intern_expr(S.intern(NAMESPACE_KEY), OP_IN, ns_vals, 0.0))
            for k, v in sorted(sel.match_labels.items()):
                exprs.append(
                    intern_expr(S.intern(k), OP_IN, (S.intern(v),), 0.0)
                )
            for e in sel.match_expressions:
                exprs.append(compile_req(e))
            return sels_t.intern(tuple(exprs))

        def compile_aff_terms(
            terms: Sequence[PodAffinityTerm], own_ns: str
        ) -> list[tuple[int, int]]:
            out = []
            for t in terms:
                ns = t.namespaces or (own_ns,)
                out.append(
                    (compile_selector(t.label_selector, tuple(ns)), topo_key_idx(t.topology_key))
                )
            return out

        image_ids = self._image_ids
        image_sizes = self._image_sizes

        def image_id(name: str) -> int:
            i = image_ids.get(name)
            if i is None:
                i = len(image_ids)
                image_ids[name] = i
            return i

        def compile_imageset(images: Sequence[str]) -> int:
            return imgsets_t.intern(tuple(sorted(image_id(i) for i in images)))

        group_ids = self._group_ids
        declared = {g.name: g.min_member for g in pod_groups}

        def group_id(name: str) -> int:
            if not name:
                return -1
            i = group_ids.get(name)
            if i is None:
                i = len(group_ids)
                group_ids[name] = i
            return i

        # ---- volumes (VolumeBinding inputs) ----
        pvc_map = {c.key: c for c in pvcs}
        pv_map = {v.name: v for v in pvs}
        class_map = {s.name: s for s in storage_classes}
        vol_sig = (
            tuple(sorted(
                (c.key, c.volume_name, c.storage_class, c.request)
                for c in pvcs
            )),
            tuple(sorted(
                (v.name, v.claim_ref, v.storage_class, v.capacity,
                 v.node_affinity)
                for v in pvs
            )),
            tuple(sorted(
                (s.name, s.volume_binding_mode, s.provisioner,
                 s.allowed_topologies)
                for s in storage_classes
            )),
        )
        if vol_sig != getattr(self, "_vol_sig", None):
            self._vol_sig = vol_sig
            self._vol_epoch = getattr(self, "_vol_epoch", 0) + 1
        vol_epoch = getattr(self, "_vol_epoch", 0)

        def _terms_use_fields(terms) -> bool:
            return any(t.match_fields for t in terms)

        def compile_pod_vols(p: Pod) -> tuple[list, bool]:
            """((mode, req_id, class_id, size) per mounted PVC, uses_fields)
            — see the ClusterSnapshot field docs for the row encoding.
            uses_fields marks rows whose compiled requirements bake node
            INDICES in (matchFields), which must invalidate on node-set
            changes."""
            rows: list[tuple[int, int, int, float]] = []
            uses_fields = False
            for claim in p.spec.volumes:
                pvc = pvc_map.get(f"{p.namespace}/{claim}")
                if pvc is None:  # missing PVC: unschedulable (upstream
                    rows.append((2, -1, -1, 0.0))  # UnschedulableAndUnresolvable)
                    continue
                if pvc.volume_name:
                    pv = pv_map.get(pvc.volume_name)
                    if pv is None:
                        rows.append((2, -1, -1, 0.0))
                        continue
                    rid = (
                        compile_node_affinity_required(pv.node_affinity)
                        if pv.node_affinity else -1
                    )
                    uses_fields |= _terms_use_fields(pv.node_affinity)
                    rows.append((0, rid, -1, 0.0))
                    continue
                cls = class_map.get(pvc.storage_class)
                if cls is None or (
                    cls.volume_binding_mode != api.VOLUME_BINDING_WAIT
                ):
                    # unbound Immediate-mode PVC: the volume binder owns
                    # it; the pod stays unschedulable until bound
                    rows.append((2, -1, -1, 0.0))
                    continue
                if cls.provisioner:
                    dyn = (
                        compile_node_affinity_required(cls.allowed_topologies)
                        if cls.allowed_topologies else -1
                    )
                    uses_fields |= _terms_use_fields(cls.allowed_topologies)
                else:
                    dyn = -2
                rows.append(
                    (1, dyn, S.intern(pvc.storage_class), float(pvc.request))
                )
            return rows, uses_fields

        # ---- walk nodes (cached per object) ----
        def node_rowdata(nd: Node) -> dict:
            hit = self._node_cache.get(id(nd))
            if hit is not None and hit[0] is nd:
                return hit[1]
            labels = dict(nd.metadata.labels)
            labels.setdefault(HOSTNAME_LABEL, nd.name)
            imgs = []
            for img in nd.status.images:
                for nm in img.names:
                    ii = image_id(nm)
                    imgs.append(ii)
                    image_sizes[ii] = float(img.size_bytes)
            rows = [
                (S.intern(k), S.intern(v), _num_or_nan(v))
                for k, v in sorted(labels.items())
            ]
            data = {
                "alloc": self._resources_vec(nd.status.allocatable),
                "unsched": nd.spec.unschedulable,
                "taintset": compile_taints(nd.spec.taints),
                "lab_k": np.array([k for k, _, _ in rows], np.int32),
                "lab_v": np.array([v for _, v, _ in rows], np.int32),
                "lab_num": np.array([n for _, _, n in rows], np.float32),
                "label_map": {k: S.intern(v) for k, v in labels.items()},
                "images": imgs,
            }
            self._node_cache[id(nd)] = (nd, data)
            return data

        node_rows = [node_rowdata(nd) for nd in nodes]

        # ---- per-pod row data (cached per object) ----
        from .. import native as _native

        native_pod_row = _native.pod_row
        native_ctx = self._native_ctx() if native_pod_row else None

        def pod_rowdata(p: Pod) -> dict:
            hit = self._pod_cache.get(id(p))
            if hit is not None and hit[0] is p:
                data = hit[1]
                if (
                    data["epoch"] is None or data["epoch"] == self._node_epoch
                ) and (
                    data["vol_epoch"] is None
                    or data["vol_epoch"] == vol_epoch
                ):
                    return data
            if native_pod_row is not None:
                # native fast path (~4x the Python walk); returns None
                # for pods with features it does not cover (volumes,
                # real nodeAffinity, exotic selector operators)
                d = native_pod_row(p, native_ctx)
                if d is not None:
                    self._pod_cache[id(p)] = (p, d)
                    return d
            a = _aff(p)
            req_id = -1
            pref_id = -1
            uses_fields = False
            if a.node_affinity and a.node_affinity.required:
                req_id = compile_node_affinity_required(a.node_affinity.required)
                uses_fields = uses_fields or any(
                    t.match_fields for t in a.node_affinity.required
                )
            if a.node_affinity and a.node_affinity.preferred:
                pref_id = compile_node_affinity_preferred(a.node_affinity.preferred)
                uses_fields = uses_fields or any(
                    t.preference.match_fields for t in a.node_affinity.preferred
                )
            sel_req_id = -1
            if p.spec.node_selector:
                term = NodeSelectorTerm(
                    tuple(
                        NodeSelectorRequirement(k, api.OP_IN, (v,))
                        for k, v in sorted(p.spec.node_selector.items())
                    )
                )
                sel_req_id = compile_node_affinity_required([term])
            ns = p.namespace
            aff: list[tuple[int, int]] = []
            anti: list[tuple[int, int]] = []
            prefs: list[tuple[int, int, float]] = []
            if a.pod_affinity:
                aff = compile_aff_terms(a.pod_affinity.required, ns)
                for w in a.pod_affinity.preferred:
                    (s, k) = compile_aff_terms([w.term], ns)[0]
                    prefs.append((s, k, float(w.weight)))
            if a.pod_anti_affinity:
                anti = compile_aff_terms(a.pod_anti_affinity.required, ns)
                for w in a.pod_anti_affinity.preferred:
                    (s, k) = compile_aff_terms([w.term], ns)[0]
                    prefs.append((s, k, -float(w.weight)))
            tsc = []
            for c in p.spec.topology_spread_constraints:
                when = (
                    WHEN_DO_NOT_SCHEDULE
                    if c.when_unsatisfiable == api.DO_NOT_SCHEDULE
                    else WHEN_SCHEDULE_ANYWAY
                )
                tsc.append((
                    topo_key_idx(c.topology_key),
                    compile_selector(c.label_selector, (ns,)),
                    when,
                    c.max_skew,
                ))
            labels = [(S.intern(NAMESPACE_KEY), S.intern(ns))] + [
                (S.intern(k), S.intern(v))
                for k, v in sorted(p.metadata.labels.items())
            ]
            ports = [
                port * 4 + {"TCP": 0, "UDP": 1, "SCTP": 2}.get(proto, 3)
                for (port, proto, _) in p.host_ports()
            ]
            vols, vol_fields = compile_pod_vols(p)
            # rows are PACKED numpy sections: assembly is a native strided
            # scatter (k8s_scheduler_tpu/native) instead of per-pod Python
            # array writes
            data = {
                "reqvec": self._resources_vec(p.resource_requests()),
                "prio": p.spec.priority,
                "creation": p.metadata.creation_timestamp,
                "req_id": req_id,
                "pref_id": pref_id,
                "sel_req_id": sel_req_id,
                "tolset": compile_tolerations(p.spec.tolerations),
                "lab_k": _i32([k for k, _ in labels]),
                "lab_v": _i32([v for _, v in labels]),
                "ports": _i32(ports),
                "aff": _i32([x for t in aff for x in t]),
                "anti": _i32([x for t in anti for x in t]),
                "pref": _i32([x for s, k, _ in prefs for x in (s, k)]),
                "pref_w": _f32([w for _, _, w in prefs]),
                "tsc": _i32([x for k, s, w, _ in tsc for x in (k, s, w)]),
                "tsc_skew": _i32([sk for _, _, _, sk in tsc]),
                "n_aff": max(len(aff), len(anti), len(prefs)),
                "gid": group_id(p.spec.pod_group),
                "imageset": compile_imageset(p.images()),
                "can_preempt": p.spec.preemption_policy != "Never",
                "vol_mode": _i32([m for m, _, _, _ in vols]),
                "vol_req": _i32([r for _, r, _, _ in vols]),
                "vol_cls": _i32([c for _, _, c, _ in vols]),
                "vol_size": _f32([s for _, _, _, s in vols]),
                "vol_epoch": vol_epoch if p.spec.volumes else None,
                "epoch": (
                    self._node_epoch if (uses_fields or vol_fields) else None
                ),
            }
            self._pod_cache[id(p)] = (p, data)
            return data

        pend_rows = [pod_rowdata(p) for p in pending]
        exist_rows = [pod_rowdata(p) for p, _ in existing]
        all_rows = pend_rows + exist_rows

        # mark-and-sweep the caches against the live object set: memory
        # stays bounded by the cluster without the full-recompile cliff a
        # wholesale clear() would cause
        live_pods = {id(p) for p in pending} | {id(p) for p, _ in existing}
        if len(self._pod_cache) > 2 * max(len(live_pods), 1):
            self._pod_cache = {
                k: v for k, v in self._pod_cache.items() if k in live_pods
            }
        live_nodes = {id(nd) for nd in nodes}
        if len(self._node_cache) > 2 * max(len(live_nodes), 1):
            self._node_cache = {
                k: v for k, v in self._node_cache.items() if k in live_nodes
            }

        # the resource-name axis is final only now (row building above
        # discovered every name, including from cached-and-reused rows'
        # earlier encodes — rn is grow-only)
        R = len(rn)

        # ---- dims the pending AND stable sides share (sticky) ----
        MPL = self._stick(
            "MPL", _pad_dim(max([len(d["lab_k"]) for d in all_rows] + [1]), 8)
        )
        MA = self._stick(
            # bucket 2, not 4: real pods rarely carry >2 terms per axis
            # and every per-slot loop in the dyn kernels (W builds,
            # spread-mask HIGH dots, update matmuls, preemption what-if)
            # pays the pad directly; sticky growth keeps recompiles rare.
            # pad_ma folds INTO the max (like pad_existing into E's
            # bucket) so pre-sizing can never leave MA below what a real
            # pod demands
            "MA", _pad_dim(
                max([d["n_aff"] for d in all_rows]
                    + [1, self.pad_ma or 0]), 2
            )
        )

        from .. import native

        # ---- stable-side cache ----
        # Everything derived from nodes/existing/volumes/PDBs alone is
        # cached wholesale, keyed on object identities plus every
        # grow-only interning dimension the arrays bake in: in steady
        # serving only the pending set changes, and re-assembling the
        # cluster side (existing-pod tables, per-node aggregations,
        # domains, expression tables) dominated warm encode time.
        stable_key = (
            tuple(id(nd) for nd in nodes),
            tuple((id(p), nm) for p, nm in existing),
            vol_sig,
            tuple((id(b), b.disruptions_allowed) for b in pdbs),
            self._node_epoch, N, E, R, MPL, MA,
            len(exprs_t.rows), len(reqs_t.rows), len(prefs_t.rows),
            len(tols_t.rows), len(taints_t.rows), len(sels_t.rows),
            len(imgsets_t.rows), len(image_ids), len(group_ids),
            len(topo_keys),
        )
        if getattr(self, "_stable_key", None) == stable_key:
            st = self._stable
        else:
            # ---- assemble node arrays (native strided scatters) ----

            ML = _pad_dim(max([len(d["lab_k"]) for d in node_rows] + [1]), 8)
            node_alloc = np.zeros((N, R), np.float32)
            node_requested = np.zeros((N, R), np.float32)
            node_unsched = np.zeros(N, bool)
            node_taintset = np.zeros(N, np.int32)
            nl_keys = np.full((N, ML), -1, np.int32)
            nl_vals = np.full((N, ML), -1, np.int32)
            nl_num = np.full((N, ML), np.nan, np.float32)
            node_valid = np.zeros(N, bool)
            node_valid[:n_real] = True

            native.scatter_rows(node_alloc, [d["alloc"] for d in node_rows])
            native.fill_scalars(node_unsched, [d["unsched"] for d in node_rows])
            native.fill_scalars(node_taintset, [d["taintset"] for d in node_rows])
            native.scatter_rows(nl_keys, [d["lab_k"] for d in node_rows])
            native.scatter_rows(nl_vals, [d["lab_v"] for d in node_rows])
            native.scatter_rows(nl_num, [d["lab_num"] for d in node_rows])
            node_image_sets = [d["images"] for d in node_rows]


            V = _pad_dim(len(pvs), 4)
            pv_req_arr = np.full(V, -1, np.int32)
            pv_class_arr = np.full(V, -1, np.int32)
            pv_cap_arr = np.zeros(V, np.float32)
            pv_avail_arr = np.zeros(V, bool)
            claimed_pvs = {c.volume_name for c in pvcs if c.volume_name}
            for i, pv in enumerate(pvs):
                pv_req_arr[i] = (
                    compile_node_affinity_required(pv.node_affinity)
                    if pv.node_affinity else -1
                )
                pv_class_arr[i] = S.intern(pv.storage_class)
                pv_cap_arr[i] = pv.capacity
                pv_avail_arr[i] = not pv.claim_ref and pv.name not in claimed_pvs

            # ---- assemble existing-pod arrays ----
            MB = 2  # PDBs tracked per pod (more than 2 selecting one pod is
            # pathological; extras conservatively protect via the first two)
            GP = max(len(pdbs), 1)
            pdb_allowed = np.zeros(GP, np.int32)
            for gi, pdb in enumerate(pdbs):
                pdb_allowed[gi] = pdb.disruptions_allowed
            exist_pdb = np.full((E, MB), -1, np.int32)
            # start times are stored RELATIVE to the oldest existing pod:
            # float32 at Unix-epoch magnitude (~1.7e9) has ~128s resolution,
            # which would collapse the preemption start-time tie-break; only
            # the within-snapshot ORDER matters
            start_base = min(
                (p.metadata.creation_timestamp for p, _ in existing),
                default=0.0,
            )
            exist_start = np.zeros(E, np.float32)

            exist_node = np.full(E, -1, np.int32)
            exist_prio = np.zeros(E, np.int32)
            exist_req = np.zeros((E, R), np.float32)
            el_keys = np.full((E, MPL), -1, np.int32)
            el_vals = np.full((E, MPL), -1, np.int32)
            MEP = self._stick(
                "MEP",
                _pad_dim(max([len(d["ports"]) for d in exist_rows] + [1]),
                         4),
            )
            exist_ports_arr = np.full((E, MEP), -1, np.int32)
            exist_anti = np.full((E, MA, 2), -1, np.int32)
            exist_pref = np.full((E, MA, 2), -1, np.int32)
            exist_pref_w = np.zeros((E, MA), np.float32)
            exist_valid = np.zeros(E, bool)
            exist_valid[:e_real] = True

            used_ports: list[list[int]] = [[] for _ in range(N)]
            # existing pods' own (non-anti) required affinity is not re-checked
            # against incoming pods (upstream symmetry applies to anti-affinity
            # and preferred terms only), so required-affinity terms are dropped

            exist_group = np.full(E, -1, np.int32)
            # absolute creation timestamps (f64) back the incremental
            # existing-fold: exist_start can be re-based exactly when the
            # oldest pod changes
            exist_creation_abs = np.zeros(E, np.float64)
            if e_real:
                exist_creation_abs[:e_real] = [
                    d["creation"] for d in exist_rows
                ]
            native.fill_scalars(exist_prio, [d["prio"] for d in exist_rows])
            native.fill_scalars(exist_group, [d["gid"] for d in exist_rows])
            native.fill_scalars(
                exist_start, [d["creation"] - start_base for d in exist_rows]
            )
            native.fill_scalars(
                exist_node, [node_index.get(nm, -1) for _, nm in existing]
            )
            native.scatter_rows(exist_req, [d["reqvec"] for d in exist_rows])
            native.scatter_rows(el_keys, [d["lab_k"] for d in exist_rows])
            native.scatter_rows(el_vals, [d["lab_v"] for d in exist_rows])
            native.scatter_rows(
                exist_ports_arr, [d["ports"] for d in exist_rows]
            )
            native.scatter_rows(
                exist_anti.reshape(E, MA * 2), [d["anti"] for d in exist_rows]
            )
            native.scatter_rows(
                exist_pref.reshape(E, MA * 2), [d["pref"] for d in exist_rows]
            )
            native.scatter_rows(exist_pref_w, [d["pref_w"] for d in exist_rows])
            if pdbs:
                for i, (p, _nm) in enumerate(existing):
                    b = 0
                    for gi, pdb in enumerate(pdbs):
                        if b >= MB:
                            break
                        if _pdb_matches(pdb, p):
                            exist_pdb[i, b] = gi
                            b += 1

            # per-node aggregation, vectorized: requested sums, the priority-
            # sorted victim table; used ports stay a sparse residue loop
            en = exist_node[:e_real]
            placed_mask = en >= 0
            np.add.at(
                node_requested, en[placed_mask], exist_req[:e_real][placed_mask]
            )
            for i, d in enumerate(exist_rows):
                if len(d["ports"]) and exist_node[i] >= 0:
                    used_ports[int(exist_node[i])].extend(
                        int(x) for x in d["ports"]
                    )

            MUP = self._stick(
                "MUP", _pad_dim(max([len(u) for u in used_ports] + [1]), 4)
            )
            node_used_ports = np.full((N, MUP), -1, np.int32)
            for i, u in enumerate(used_ports):
                if u:
                    node_used_ports[i, : len(u)] = u

            # node_pods [N, MPN]: existing indices per node, ascending priority
            # (ties: higher index first — same key the per-node sort used)
            e_ids = np.flatnonzero(placed_mask)
            if e_ids.size:
                order_v = np.lexsort(
                    (-e_ids, exist_prio[:e_real][e_ids], en[e_ids])
                )
                se = e_ids[order_v].astype(np.int32)
                sn = en[se]
                starts = np.r_[True, sn[1:] != sn[:-1]]
                group_start = np.maximum.accumulate(
                    np.where(starts, np.arange(sn.size), 0)
                )
                col = np.arange(sn.size) - group_start
                # the pad folds INTO the bucket-of-8 (like E into its
                # pow2 bucket): a non-multiple-of-8 pad must not leave
                # MPN below the bucket a grown depth would demand
                MPN = self._stick(
                    "MPN",
                    _pad_dim(
                        max(int(col.max()) + 1,
                            self.pad_pods_per_node or 0), 8
                    ),
                )
                node_pods = np.full((N, MPN), -1, np.int32)
                node_pods[sn, col] = se
            else:
                MPN = self._stick(
                    "MPN",
                    _pad_dim(max(1, self.pad_pods_per_node or 0), 8),
                )
                node_pods = np.full((N, MPN), -1, np.int32)

            # ---- topology domains (flat ids across keys) ----
            K = len(topo_keys)
            domain_map: dict[tuple[int, int], int] = {}
            node_domains = np.full((N, K), -1, np.int32)
            for i, nd in enumerate(nodes):
                labels = dict(nd.metadata.labels)
                labels.setdefault(HOSTNAME_LABEL, nd.name)
                for k, key in enumerate(topo_keys):
                    if key in labels:
                        dk = (k, S.intern(labels[key]))
                        if dk not in domain_map:
                            domain_map[dk] = len(domain_map)
                        node_domains[i, k] = domain_map[dk]
            D = _pad_dim(len(domain_map), 8)
            domain_key = np.full(D, -1, np.int32)
            domain_node_count = np.zeros(D, np.float32)
            for (k, _v), d in domain_map.items():
                domain_key[d] = k
            for i in range(n_real):
                for k in range(K):
                    d = node_domains[i, k]
                    if d >= 0:
                        domain_node_count[d] += 1.0

            # ---- finalize tables ----
            Ex = _pad_dim(len(exprs_t.rows), 8)
            MV = _pad_dim(max([len(v) for _, _, v, _ in exprs_t.rows] + [1]), 4)
            ex_key = np.full(Ex, -1, np.int32)
            ex_op = np.full(Ex, -1, np.int32)
            ex_vals = np.full((Ex, MV), -1, np.int32)
            ex_num = np.zeros(Ex, np.float32)
            for i, (k, op, vals, num) in enumerate(exprs_t.rows):
                ex_key[i] = k
                ex_op[i] = op
                ex_vals[i, : len(vals)] = vals
                ex_num[i] = num

            Rq = _pad_dim(len(reqs_t.rows), 4)
            MT = _pad_dim(max([len(r) for r in reqs_t.rows] + [1]), 2)
            ME = _pad_dim(
                max([len(t) for r in reqs_t.rows for t in r] + [1]), 2
            )
            rq_exprs = np.full((Rq, MT, ME), -1, np.int32)
            for i, terms in enumerate(reqs_t.rows):
                for j, t in enumerate(terms):
                    rq_exprs[i, j, : len(t)] = t

            Pf = _pad_dim(len(prefs_t.rows), 2)
            MPT = _pad_dim(max([len(r) for r in prefs_t.rows] + [1]), 2)
            MPE = _pad_dim(
                max([len(t) for r in prefs_t.rows for (t, _w) in r] + [1]), 2
            )
            pf_exprs = np.full((Pf, MPT, MPE), -1, np.int32)
            pf_weight = np.zeros((Pf, MPT), np.float32)
            for i, row in enumerate(prefs_t.rows):
                for j, (exprs, w) in enumerate(row):
                    pf_exprs[i, j, : len(exprs)] = exprs
                    pf_weight[i, j] = w

            Tl = _pad_dim(len(tols_t.rows), 2)
            MTl = _pad_dim(max([len(r) for r in tols_t.rows] + [1]), 4)
            tl_key = np.full((Tl, MTl), 0, np.int32)
            tl_op = np.zeros((Tl, MTl), np.int32)
            tl_val = np.zeros((Tl, MTl), np.int32)
            tl_effect = np.zeros((Tl, MTl), np.int32)
            tl_valid = np.zeros((Tl, MTl), bool)
            for i, row in enumerate(tols_t.rows):
                for j, (k, op, v, e) in enumerate(row):
                    tl_key[i, j] = k
                    tl_op[i, j] = op
                    tl_val[i, j] = v
                    tl_effect[i, j] = e
                    tl_valid[i, j] = True

            Ts = _pad_dim(len(taints_t.rows), 2)
            MTt = _pad_dim(max([len(r) for r in taints_t.rows] + [1]), 4)
            ts_key = np.full((Ts, MTt), -1, np.int32)
            ts_val = np.zeros((Ts, MTt), np.int32)
            ts_effect = np.zeros((Ts, MTt), np.int32)
            ts_valid = np.zeros((Ts, MTt), bool)
            for i, row in enumerate(taints_t.rows):
                for j, (k, v, e) in enumerate(row):
                    ts_key[i, j] = k
                    ts_val[i, j] = v
                    ts_effect[i, j] = e
                    ts_valid[i, j] = True

            Ssel = _pad_dim(len(sels_t.rows), 4)
            MSE = _pad_dim(max([len(r) for r in sels_t.rows] + [1]), 4)
            sel_exprs = np.full((Ssel, MSE), -1, np.int32)
            for i, row in enumerate(sels_t.rows):
                sel_exprs[i, : len(row)] = row

            I = max(len(image_ids), 1)
            Is = _pad_dim(len(imgsets_t.rows), 2)
            imgset_sizes = np.zeros((Is, I), np.float32)
            for i, row in enumerate(imgsets_t.rows):
                for ii in row:
                    imgset_sizes[i, ii] = image_sizes.get(ii, 0.0)
            node_images = np.zeros((N, I), bool)
            for i, imgs in enumerate(node_image_sets):
                for ii in imgs:
                    node_images[i, ii] = True

            G = max(len(group_ids), 1)
            group_existing_count = np.zeros(G, np.int32)
            for g in exist_group[:e_real]:
                if g >= 0:
                    group_existing_count[g] += 1
            num_domains_val = len(domain_map)
            st = {
                "node_alloc": node_alloc,
                "node_requested": node_requested,
                "node_unsched": node_unsched,
                "node_taintset": node_taintset,
                "nl_keys": nl_keys,
                "nl_vals": nl_vals,
                "nl_num": nl_num,
                "node_valid": node_valid,
                "node_images": node_images,
                "pv_req_arr": pv_req_arr,
                "pv_class_arr": pv_class_arr,
                "pv_cap_arr": pv_cap_arr,
                "pv_avail_arr": pv_avail_arr,
                "exist_node": exist_node,
                "exist_prio": exist_prio,
                "exist_req": exist_req,
                "el_keys": el_keys,
                "el_vals": el_vals,
                "exist_ports": exist_ports_arr,
                "exist_anti": exist_anti,
                "exist_pref": exist_pref,
                "exist_pref_w": exist_pref_w,
                "exist_valid": exist_valid,
                "exist_pdb": exist_pdb,
                "exist_start": exist_start,
                "pdb_allowed": pdb_allowed,
                "node_used_ports": node_used_ports,
                "node_pods": node_pods,
                "node_domains": node_domains,
                "domain_key": domain_key,
                "domain_node_count": domain_node_count,
                "num_domains_val": num_domains_val,
                "ex_key": ex_key,
                "ex_op": ex_op,
                "ex_vals": ex_vals,
                "ex_num": ex_num,
                "rq_exprs": rq_exprs,
                "pf_exprs": pf_exprs,
                "pf_weight": pf_weight,
                "tl_key": tl_key,
                "tl_op": tl_op,
                "tl_val": tl_val,
                "tl_effect": tl_effect,
                "tl_valid": tl_valid,
                "ts_key": ts_key,
                "ts_val": ts_val,
                "ts_effect": ts_effect,
                "ts_valid": ts_valid,
                "sel_exprs": sel_exprs,
                "imgset_sizes": imgset_sizes,
                "group_existing_count": group_existing_count,
                # incremental existing-fold support (_try_fold_existing)
                "exist_group": exist_group,
                "exist_creation_abs": exist_creation_abs,
                "start_base": start_base,
                "e_real": e_real,
            }
            # strong refs keep cached id()s from being reused
            st["__refs"] = (list(nodes), [p for p, _ in existing],
                            list(pvs), list(pvcs), list(storage_classes),
                            list(pdbs))
            self._stable_key = stable_key
            self._stable = st

        # the device-carry regime key: the [P,N] static base + [S,P]
        # matched-pending depend on pod rows x node tables x volumes x
        # interning dims — NOT on the existing-pod set or PDBs (the one
        # existing coupling, NodePorts' used-port mask, is repaired by
        # dirty-marking port-bearing pending pods on every existing-fold).
        # Callers key CarryKeeper on THIS instead of _stable_key so a
        # bound-pod fold does not trigger a full carry rebuild.
        self._carry_key = (stable_key[0], stable_key[2]) + stable_key[4:]

        node_alloc = st["node_alloc"]
        node_requested = st["node_requested"]
        node_unsched = st["node_unsched"]
        node_taintset = st["node_taintset"]
        nl_keys = st["nl_keys"]
        nl_vals = st["nl_vals"]
        nl_num = st["nl_num"]
        node_valid = st["node_valid"]
        node_images = st["node_images"]
        pv_req_arr = st["pv_req_arr"]
        pv_class_arr = st["pv_class_arr"]
        pv_cap_arr = st["pv_cap_arr"]
        pv_avail_arr = st["pv_avail_arr"]
        exist_node = st["exist_node"]
        exist_prio = st["exist_prio"]
        exist_req = st["exist_req"]
        el_keys = st["el_keys"]
        el_vals = st["el_vals"]
        exist_ports_arr = st["exist_ports"]
        exist_anti = st["exist_anti"]
        exist_pref = st["exist_pref"]
        exist_pref_w = st["exist_pref_w"]
        exist_valid = st["exist_valid"]
        exist_pdb = st["exist_pdb"]
        exist_start = st["exist_start"]
        pdb_allowed = st["pdb_allowed"]
        node_used_ports = st["node_used_ports"]
        node_pods = st["node_pods"]
        node_domains = st["node_domains"]
        domain_key = st["domain_key"]
        domain_node_count = st["domain_node_count"]
        num_domains_val = st["num_domains_val"]
        ex_key = st["ex_key"]
        ex_op = st["ex_op"]
        ex_vals = st["ex_vals"]
        ex_num = st["ex_num"]
        rq_exprs = st["rq_exprs"]
        pf_exprs = st["pf_exprs"]
        pf_weight = st["pf_weight"]
        tl_key = st["tl_key"]
        tl_op = st["tl_op"]
        tl_val = st["tl_val"]
        tl_effect = st["tl_effect"]
        tl_valid = st["tl_valid"]
        ts_key = st["ts_key"]
        ts_val = st["ts_val"]
        ts_effect = st["ts_effect"]
        ts_valid = st["ts_valid"]
        sel_exprs = st["sel_exprs"]
        imgset_sizes = st["imgset_sizes"]
        group_existing_count = st["group_existing_count"]

        # group_min_member depends on the per-call pod_groups argument
        G = max(len(group_ids), 1)
        group_min_member = np.zeros(G, np.int32)
        for name, gi in group_ids.items():
            group_min_member[gi] = declared.get(name, 0)

        # ---- assemble pending-pod arrays (native strided scatters) ----
        pod_req = np.zeros((P, R), np.float32)
        pod_prio = np.zeros(P, np.int32)
        pod_node_name = np.full(P, -1, np.int32)
        pod_nominated = np.full(P, -1, np.int32)
        pod_req_id = np.full(P, -1, np.int32)
        pod_sel_req_id = np.full(P, -1, np.int32)
        pod_pref_id = np.full(P, -1, np.int32)
        pod_tolset = np.zeros(P, np.int32)
        pod_group_arr = np.full(P, -1, np.int32)
        pod_imageset = np.zeros(P, np.int32)
        pod_can_preempt = np.zeros(P, bool)
        pod_valid = np.zeros(P, bool)
        pod_valid[:p_real] = True

        pl_keys = np.full((P, MPL), -1, np.int32)
        pl_vals = np.full((P, MPL), -1, np.int32)

        MPorts = self._stick(
            "MPorts",
            _pad_dim(max([len(d["ports"]) for d in pend_rows] + [1]), 4),
        )
        pod_ports = np.full((P, MPorts), -1, np.int32)
        pod_port_ids = np.full((P, MPorts), -1, np.int32)
        port_ids_t = _InternTable()  # distinct (port, proto) among pending

        pod_aff_terms = np.full((P, MA, 2), -1, np.int32)
        pod_anti_terms = np.full((P, MA, 2), -1, np.int32)
        pod_pref_aff = np.full((P, MA, 2), -1, np.int32)
        pod_pref_aff_w = np.zeros((P, MA), np.float32)

        MC = self._stick(
            "MC",  # bucket 2 like MA (same per-slot-loop cost argument);
            # pad_mc pre-sizes like pad_ma above
            _pad_dim(
                max([len(d["tsc_skew"]) for d in pend_rows]
                    + [1, self.pad_mc or 0]), 2
            ),
        )
        pod_tsc = np.full((P, MC, 3), -1, np.int32)
        pod_tsc_skew = np.zeros((P, MC), np.int32)

        MVol = self._stick(
            "MVol",
            _pad_dim(max([len(d["vol_mode"]) for d in pend_rows] + [1]), 2),
        )
        pod_vol_mode = np.full((P, MVol), -1, np.int32)
        pod_vol_req = np.full((P, MVol), -1, np.int32)
        pod_vol_class = np.full((P, MVol), -1, np.int32)
        pod_vol_size = np.zeros((P, MVol), np.float32)


        native.scatter_rows(pod_req, [d["reqvec"] for d in pend_rows])
        native.fill_scalars(pod_prio, [d["prio"] for d in pend_rows])
        native.fill_scalars(pod_req_id, [d["req_id"] for d in pend_rows])
        native.fill_scalars(pod_pref_id, [d["pref_id"] for d in pend_rows])
        native.fill_scalars(
            pod_sel_req_id, [d["sel_req_id"] for d in pend_rows]
        )
        native.fill_scalars(pod_tolset, [d["tolset"] for d in pend_rows])
        native.fill_scalars(pod_group_arr, [d["gid"] for d in pend_rows])
        native.fill_scalars(pod_imageset, [d["imageset"] for d in pend_rows])
        native.fill_scalars(
            pod_can_preempt, [d["can_preempt"] for d in pend_rows]
        )
        native.scatter_rows(pl_keys, [d["lab_k"] for d in pend_rows])
        native.scatter_rows(pl_vals, [d["lab_v"] for d in pend_rows])
        native.scatter_rows(pod_ports, [d["ports"] for d in pend_rows])
        native.scatter_rows(
            pod_aff_terms.reshape(P, MA * 2), [d["aff"] for d in pend_rows]
        )
        native.scatter_rows(
            pod_anti_terms.reshape(P, MA * 2), [d["anti"] for d in pend_rows]
        )
        native.scatter_rows(
            pod_pref_aff.reshape(P, MA * 2), [d["pref"] for d in pend_rows]
        )
        native.scatter_rows(pod_pref_aff_w, [d["pref_w"] for d in pend_rows])
        native.scatter_rows(
            pod_tsc.reshape(P, MC * 3), [d["tsc"] for d in pend_rows]
        )
        native.scatter_rows(pod_tsc_skew, [d["tsc_skew"] for d in pend_rows])
        native.scatter_rows(pod_vol_mode, [d["vol_mode"] for d in pend_rows])
        native.scatter_rows(pod_vol_req, [d["vol_req"] for d in pend_rows])
        native.scatter_rows(pod_vol_class, [d["vol_cls"] for d in pend_rows])
        native.scatter_rows(pod_vol_size, [d["vol_size"] for d in pend_rows])
        # sparse per-pod residue: pinned/nominated nodes and the per-cycle
        # distinct-port interning (pods carrying those are rare)
        for i, (p, d) in enumerate(zip(pending, pend_rows)):
            if p.spec.node_name:
                pod_node_name[i] = node_index.get(p.spec.node_name, -2)
            if p.nominated_node_name:
                pod_nominated[i] = node_index.get(p.nominated_node_name, -1)
            if len(d["ports"]):
                for j, enc_port in enumerate(d["ports"]):
                    pod_port_ids[i, j] = port_ids_t.intern(int(enc_port))

        # Pod ordering rank via the profile's queueSort plugin (default
        # PrioritySort: priority desc, creation ts asc, index).
        pod_order = np.full(P, np.iinfo(np.int32).max, np.int32)
        if p_real:
            creation = np.array(
                [d["creation"] for d in pend_rows], np.float64
            )
            pod_order[:p_real] = self.queue_sort.rank(
                pending, pod_prio[:p_real], creation
            )

        snap = ClusterSnapshot(
            resource_names=tuple(rn),
            num_nodes=np.asarray(n_real, np.int32),
            num_pending=np.asarray(p_real, np.int32),
            num_existing=np.asarray(e_real, np.int32),
            num_domains=np.asarray(num_domains_val, np.int32),
            cycle_index=np.asarray(self._cycle_index, np.int32),
            topology_keys=tuple(topo_keys),
            node_allocatable=node_alloc,
            node_requested=node_requested,
            node_unschedulable=node_unsched,
            node_taintset=node_taintset,
            node_label_keys=nl_keys,
            node_label_vals=nl_vals,
            node_label_num=nl_num,
            node_domains=node_domains,
            node_images=node_images,
            node_used_ports=node_used_ports,
            node_valid=node_valid,
            ex_key=ex_key,
            ex_op=ex_op,
            ex_vals=ex_vals,
            ex_num=ex_num,
            rq_exprs=rq_exprs,
            pf_exprs=pf_exprs,
            pf_weight=pf_weight,
            tl_key=tl_key,
            tl_op=tl_op,
            tl_val=tl_val,
            tl_effect=tl_effect,
            tl_valid=tl_valid,
            ts_key=ts_key,
            ts_val=ts_val,
            ts_effect=ts_effect,
            ts_valid=ts_valid,
            sel_exprs=sel_exprs,
            pod_requested=pod_req,
            pod_priority=pod_prio,
            pod_order=pod_order,
            pod_node_name=pod_node_name,
            pod_nominated=pod_nominated,
            pod_req_id=pod_req_id,
            pod_sel_req_id=pod_sel_req_id,
            pod_pref_id=pod_pref_id,
            pod_tolset=pod_tolset,
            pod_label_keys=pl_keys,
            pod_label_vals=pl_vals,
            pod_ports=pod_ports,
            pod_port_ids=pod_port_ids,
            num_distinct_ports=self._stick(
                "Q", _pad_dim(len(port_ids_t), 4)
            ),
            has_inter_pod_affinity=self._stick_flag(
                "aff",
                bool(
                    (pod_aff_terms >= 0).any()
                    or (pod_anti_terms >= 0).any()
                    or (pod_pref_aff >= 0).any()
                    or (exist_anti >= 0).any()
                    or (exist_pref >= 0).any()
                ),
            ),
            has_topology_spread=self._stick_flag(
                "tsc", bool((pod_tsc >= 0).any())
            ),
            has_volumes=self._stick_flag(
                "vol", bool((pod_vol_mode >= 0).any())
            ),
            has_multi_volume=self._stick_flag(
                "mvol",
                bool(((pod_vol_mode >= 0).sum(axis=1) >= 2).any()),
            ),
            pod_vol_mode=pod_vol_mode,
            pod_vol_req=pod_vol_req,
            pod_vol_class=pod_vol_class,
            pod_vol_size=pod_vol_size,
            pv_req_id=pv_req_arr,
            pv_class=pv_class_arr,
            pv_capacity=pv_cap_arr,
            pv_avail=pv_avail_arr,
            pod_aff_terms=pod_aff_terms,
            pod_anti_terms=pod_anti_terms,
            pod_pref_aff=pod_pref_aff,
            pod_pref_aff_w=pod_pref_aff_w,
            pod_tsc=pod_tsc,
            pod_tsc_skew=pod_tsc_skew,
            pod_group=pod_group_arr,
            pod_imageset=pod_imageset,
            pod_can_preempt=pod_can_preempt,
            pod_valid=pod_valid,
            group_min_member=group_min_member,
            group_existing_count=group_existing_count,
            imgset_sizes=imgset_sizes,
            exist_node=exist_node,
            exist_priority=exist_prio,
            exist_start=exist_start,
            exist_pdb=exist_pdb,
            exist_requested=exist_req,
            pdb_allowed=pdb_allowed,
            exist_label_keys=el_keys,
            exist_label_vals=el_vals,
            exist_ports=exist_ports_arr,
            exist_anti_terms=exist_anti,
            exist_pref_aff=exist_pref,
            exist_pref_aff_w=exist_pref_w,
            exist_valid=exist_valid,
            node_pods=node_pods,
            domain_key=domain_key,
            domain_node_count=domain_node_count,
        )

        # ---- stash everything the delta fast path (encode_packed) needs.
        # The stashed pod_rowdata CLOSURE stays valid exactly while the
        # stable side is unchanged: it captures node_index / the volume
        # maps / vol_epoch, all of which are covered by the delta
        # precheck's object-identity comparisons plus _table_lens.
        creation_full = np.zeros(P, np.float64)
        if p_real:
            creation_full[:p_real] = [d["creation"] for d in pend_rows]
        self._delta_state = {
            "pod_rowdata": pod_rowdata,
            "node_index": node_index,
            "pend_ids": [id(p) for p in pending],
            "pend_refs": list(pending),
            "pend_rows": list(pend_rows),
            # slots whose row carries host ports, maintained
            # incrementally: the delta path's port re-interning only
            # walks THESE instead of scanning all P slots per encode
            "port_set": {
                i for i, d in enumerate(pend_rows) if len(d["ports"])
            },
            "creation": creation_full,
            "p_real": p_real,
            "dims": {"R": R, "MPL": MPL, "MA": MA, "MPorts": MPorts,
                     "MC": MC, "MVol": MVol,
                     "Q": snap.num_distinct_ports},
            "pads": (self.pad_pods, self.pad_nodes, P),
            # stable-side argument identity: the fast path first compares
            # LIST identity (0-cost; the contract is that callers keep one
            # list per stable side and replace it on change), and falls
            # back to element-identity tuples when the list was rebuilt
            "nodes_ids": (id(nodes), len(nodes)),
            "nodes_elems": tuple(id(nd) for nd in nodes),
            "exist_ids": (id(existing), len(existing)),
            "exist_elems": tuple((id(p), nm) for p, nm in existing),
            "vol_ids": (id(pvcs), len(pvcs), id(pvs), len(pvs),
                        id(storage_classes), len(storage_classes)),
            "vol_elems": (tuple(id(c) for c in pvcs),
                          tuple(id(v) for v in pvs),
                          tuple(id(s) for s in storage_classes)),
            "pdb_ids": (id(pdbs), len(pdbs)),
            "pdb_elems": (tuple(id(b) for b in pdbs),
                          tuple(b.disruptions_allowed for b in pdbs)),
            "flags": (snap.has_inter_pod_affinity, snap.has_topology_spread,
                      snap.has_volumes, snap.has_multi_volume),
        }
        # a direct encode() call leaves the arena holding the PREVIOUS
        # snapshot's bytes; mark it stale so the next encode_packed takes
        # the full path (_install_arena rewrites everything and re-syncs)
        self._arena_synced = False
        return snap


    # ------------------------------------------------------------------
    # Packed-arena encode: the steady-serving fast path.
    #
    # encode() rebuilds every pending-side array and repacks ~8MB per
    # cycle even when 80% of the pending set carried over — measured
    # 150-180ms at 10k pods with ZERO churn. encode_packed keeps the
    # packed (wbuf, bbuf) pair as a PERSISTENT ARENA whose per-field
    # numpy views alias the buffers, and rewrites only the rows whose pod
    # object changed. The stable side (nodes / existing pods / volumes /
    # PDBs) is covered by object-identity prechecks; any miss falls back
    # to the full encode, which reinstalls the arena.
    #
    # CONTRACT for delta hits: callers keep ONE list object per stable
    # side and replace the list (not mutate it in place) when membership
    # changes; pod objects are immutable once handed to the encoder,
    # except `nominated_node_name`, whose in-place mutation must be
    # reported via `mutated_ids` (id(pod) set).
    # ------------------------------------------------------------------

    # (field name, rowdata key, pad value) for pending-side 2-D arrays
    _PEND_2D = (
        ("pod_requested", "reqvec", 0.0),
        ("pod_label_keys", "lab_k", -1),
        ("pod_label_vals", "lab_v", -1),
        ("pod_ports", "ports", -1),
        ("pod_pref_aff_w", "pref_w", 0.0),
        ("pod_tsc_skew", "tsc_skew", 0),
        ("pod_vol_mode", "vol_mode", -1),
        ("pod_vol_req", "vol_req", -1),
        ("pod_vol_class", "vol_cls", -1),
        ("pod_vol_size", "vol_size", 0.0),
    )
    # pending-side 3-D arrays, written through a [P, -1] reshaped view
    _PEND_3D = (
        ("pod_aff_terms", "aff", -1),
        ("pod_anti_terms", "anti", -1),
        ("pod_pref_aff", "pref", -1),
        ("pod_tsc", "tsc", -1),
    )
    _PEND_SCALAR = (
        ("pod_priority", "prio"),
        ("pod_req_id", "req_id"),
        ("pod_pref_id", "pref_id"),
        ("pod_sel_req_id", "sel_req_id"),
        ("pod_tolset", "tolset"),
        ("pod_group", "gid"),
        ("pod_imageset", "imageset"),
        ("pod_can_preempt", "can_preempt"),
    )
    # pad value per scalar field (matches the full path's array initials)
    _PEND_SCALAR_PAD = {
        "pod_priority": 0, "pod_req_id": -1, "pod_pref_id": -1,
        "pod_sel_req_id": -1, "pod_tolset": 0, "pod_group": -1,
        "pod_imageset": 0, "pod_can_preempt": False,
        "pod_node_name": -1, "pod_nominated": -1,
    }

    def _apply_specs(self, ds) -> list:
        """The (view, key, pad, mode) spec list for the delta arena's
        pending-side fields — built once per arena; shared by apply_rows
        (dict path) and pod_rows_into (fused path)."""
        specs = ds.get("apply_specs")
        if specs is None:
            A = self._arena
            P = ds["pads"][2]
            specs = (
                [(A[n], k, p, 0) for n, k, p in self._PEND_2D]
                + [(A[n].reshape(P, -1), k, p, 0)
                   for n, k, p in self._PEND_3D]
                + [(A[n], k, self._PEND_SCALAR_PAD[n], 1)
                   for n, k in self._PEND_SCALAR]
            )
            ds["apply_specs"] = specs
        return specs

    def _clear_slots(self, sl) -> None:
        """Reset pending-side arena rows to the full path's pad values —
        applied to slots that stop being backed by a pod (pending-set
        shrink), so a delta arena is byte-identical to a full encode."""
        A = self._arena
        for name, _key, pad in self._PEND_2D:
            A[name][sl] = pad
        for name, _key, pad in self._PEND_3D:
            A[name][sl] = pad
        for name, pad in self._PEND_SCALAR_PAD.items():
            A[name][sl] = pad

    def ingest_pod(self, pod: Pod) -> bool:
        """Admission-time incremental encode: parse `pod`'s arena row
        NOW — in the shadow of the buffer/ack path — so the flush-time
        delta encode finds it staged and skips the parse (the `ingest`
        segment of delta_profile becomes hidden host time and the flush
        is an O(dirty) apply instead of an O(P) parse+apply).

        Interning growth caused by the staging parse is recorded in
        `_staged_grew`: the delta path's table-stability invariant is
        checked against the LAST stash, so the next encode_packed must
        take the full path ONCE to give the stable-side tables their
        new entries — after which every later group in a multi-cycle
        batch deltas against the grown tables instead of triggering the
        whole-batch double re-encode.

        Serve-thread only (the encoder is not thread-safe). Returns
        True if a row was staged."""
        ds = self._delta_state
        if ds is None:
            self.ingest_misses += 1
            return False
        import time as _time

        t0 = _time.perf_counter()
        lens0 = self._table_lens()
        try:
            d = ds["pod_rowdata"](pod)
        except Exception:
            self.ingest_misses += 1
            return False
        if self._table_lens() != lens0:
            self._staged_grew = True
        self._staged[id(pod)] = (pod, d)
        self._ingest_ms += (_time.perf_counter() - t0) * 1e3
        return True

    def clear_ingest(self) -> None:
        """Drop staged rows the flush did not consume (pods dropped or
        shed between buffer and flush). Called at flush end so staging
        memory is bounded by one buffered batch."""
        self._staged.clear()

    def encode_packed(
        self,
        nodes: Sequence[Node],
        pending: Sequence[Pod],
        existing: Sequence[tuple[Pod, str]] = (),
        pod_groups: Sequence[api.PodGroup] = (),
        pvcs: Sequence[api.PersistentVolumeClaim] = (),
        pvs: Sequence[api.PersistentVolume] = (),
        storage_classes: Sequence[api.StorageClass] = (),
        pdbs: Sequence[api.PodDisruptionBudget] = (),
        mutated_ids: frozenset | set = frozenset(),
    ):
        """Encode + pack in one step: returns an EncodedFrame whose
        wbuf/bbuf are the persistent arena buffers (valid until the NEXT
        encode call). Consumers must have FETCHED an in-flight program's
        outputs before the next encode rewrites the arena: jax's CPU
        backend copies a jit's numpy arguments asynchronously on the
        dispatch thread, so a rewrite racing a dispatch can tear the
        copy (reproduced with a 15-line pure-jax loop). The serving
        pipeline provides exactly this ordering — dispatch k+1 is
        refused until cycle k's decisions were fetched
        (ServingPipeline.dispatch). `snap` is a ClusterSnapshot whose
        array fields are views into the buffers, and `dirty` names the
        rewritten pod slots (None = full rebuild)."""
        ds = self._delta_state
        if self._staged_grew:
            # an ingest parse grew an interning table: the stable-side
            # tables need the new entries, so rebuild once (later groups
            # in the same flush delta against the grown tables)
            self._staged_grew = False
            ds = None
        if ds is not None and self._arena_spec is not None:
            ok = self._delta_precheck(
                ds, nodes, existing, pvcs, pvs, storage_classes, pdbs
            )
            if not ok and self._stable_except_existing_ok(
                ds, nodes, pvcs, pvs, storage_classes, pdbs
            ):
                # ONLY the existing set changed — the per-cycle event of
                # real serving (bindings fold in; a completion batch
                # drops the tail). Try the incremental stable fold.
                import time as _time

                _ft = _time.perf_counter()
                ok = self._try_fold_existing(ds, existing)
                if ok:
                    self._fold_ms = (_time.perf_counter() - _ft) * 1e3
            if ok:
                out = self._encode_delta(ds, pending, pod_groups, mutated_ids)
                if out is not None:
                    self.delta_hits += 1
                    return out
        self.full_encodes += 1
        # a bailed delta leaves partial segment marks behind; an empty
        # profile is the "this encode took the full path" signal
        self.delta_profile = {}
        self.last_changed_slots = None  # full path: everything changed
        snap = self.encode(
            nodes, pending, existing, pod_groups, pvcs, pvs,
            storage_classes, pdbs,
        )
        return self._install_arena(snap)

    def _delta_precheck(
        self, ds, nodes, existing, pvcs, pvs, storage_classes, pdbs
    ) -> bool:
        if not self._stable_except_existing_ok(
            ds, nodes, pvcs, pvs, storage_classes, pdbs
        ):
            return False
        if ds["exist_ids"] != (id(existing), len(existing)):
            new = tuple((id(p), nm) for p, nm in existing)
            if new != ds["exist_elems"]:
                # stash for _try_fold_existing so the fold does not
                # rebuild the same O(E) tuple a second time
                self._exist_probe = (id(existing), new)
                return False
        return True

    def _stable_except_existing_ok(
        self, ds, nodes, pvcs, pvs, storage_classes, pdbs
    ) -> bool:
        if not getattr(self, "_arena_synced", False):
            return False  # a direct encode() superseded the arena contents
        if ds["pads"][:2] != (self.pad_pods, self.pad_nodes):
            return False
        if ds["nodes_ids"] != (id(nodes), len(nodes)):
            if tuple(id(nd) for nd in nodes) != ds["nodes_elems"]:
                return False
        if ds["vol_ids"] != (
            id(pvcs), len(pvcs), id(pvs), len(pvs),
            id(storage_classes), len(storage_classes),
        ):
            if ds["vol_elems"] != (
                tuple(id(c) for c in pvcs),
                tuple(id(v) for v in pvs),
                tuple(id(s) for s in storage_classes),
            ):
                return False
        # PDB disruptionsAllowed is status (may be refreshed in place on
        # the same object), so values are compared every cycle
        pdb_vals = tuple(b.disruptions_allowed for b in pdbs)
        if ds["pdb_ids"] != (id(pdbs), len(pdbs)):
            if tuple(id(b) for b in pdbs) != ds["pdb_elems"][0]:
                return False
        if pdb_vals != ds["pdb_elems"][1]:
            return False
        return True

    def _try_fold_existing(self, ds, existing) -> bool:
        """Incremental existing-set fold (SURVEY §4 realism; VERDICT r4
        item 3): bring the cached stable side up to date IN PLACE when the
        existing set changed by a pure APPEND (pods bound since the last
        cycle) or a pure TAIL REMOVAL (un-folding a completion batch of
        recently bound pods). Anything else — middle-of-list removals,
        node/volume/PDB changes, dict growth, arena-dim overflow, pods the
        native parser does not cover — returns False and the caller takes
        the full encode (which rebuilds the stable cache from scratch, so
        partial st mutations on a failed fold are discarded wholesale
        along with the stale _stable_key).

        Exactness contract: after a successful fold, every st array is
        byte-identical to what a from-scratch assembly over the new
        existing list would produce (the packed-encoder differential
        tests drive exactly this equivalence), and _stable_key is updated
        so a later full encode with the same inputs REUSES the folded st.
        The device carry stays valid (keyed on _carry_key, which excludes
        the existing set); the one static coupling — NodePorts' used-port
        mask — is repaired by marking every port-bearing pending slot
        dirty, which the carry-update program then recomputes."""
        from .. import native

        if native.pod_rows_into is None:
            return False
        st = getattr(self, "_stable", None)
        if st is None or "exist_creation_abs" not in st:
            return False
        old = ds["exist_elems"]
        probe = getattr(self, "_exist_probe", None)
        if probe is not None and probe[0] == id(existing):
            new = probe[1]
            self._exist_probe = None
        else:
            new = tuple((id(p), nm) for p, nm in existing)
        if new == old:  # same elements, rebuilt list object
            ds["exist_ids"] = (id(existing), len(existing))
            return True
        n_old, n_new = len(old), len(new)
        if n_new > n_old and new[:n_old] == old:
            pass  # pure append
        elif n_new < n_old and old[:n_new] == new:
            pass  # pure tail removal
        else:
            return False
        L = min(n_old, n_new)
        exist_req = st["exist_req"]
        E = exist_req.shape[0]
        if n_new > E:
            return False  # E pad exhausted: full path grows the regime
        dims = ds["dims"]
        exist_node = st["exist_node"]
        exist_ports = st["exist_ports"]
        exist_group = st["exist_group"]
        ca = st["exist_creation_abs"]
        affected_nodes: set[int] = set()
        port_nodes: set[int] = set()

        if n_new < n_old:  # ---- tail removal ----
            sl = np.arange(L, n_old)
            en = exist_node[sl]
            m = en >= 0
            g = exist_group[sl]
            np.subtract.at(st["group_existing_count"], g[g >= 0], 1)
            affected_nodes.update(int(x) for x in en[m])
            port_nodes.update(
                int(n) for n, p0 in zip(en, exist_ports[sl, 0])
                if n >= 0 and p0 >= 0
            )
            # restore full-path pad values so the arena stays
            # byte-identical to a fresh assembly
            exist_req[sl] = 0.0
            st["el_keys"][sl] = -1
            st["el_vals"][sl] = -1
            exist_ports[sl] = -1
            st["exist_anti"][sl] = -1
            st["exist_pref"][sl] = -1
            st["exist_pref_w"][sl] = 0.0
            st["exist_prio"][sl] = 0
            st["exist_pdb"][sl] = -1
            st["exist_start"][sl] = 0.0
            exist_node[sl] = -1
            exist_group[sl] = -1
            ca[sl] = 0.0
            st["exist_valid"][sl] = False
            # node_requested: f32 subtract is NOT the exact inverse of
            # the full path's slot-ascending add accumulation — recompute
            # the affected nodes' sums from their remaining member rows
            # in the same ascending-slot order, so the result stays
            # bitwise equal to a from-scratch assembly
            if affected_nodes:
                nr = st["node_requested"]
                an0 = np.fromiter(affected_nodes, np.int64)
                nr[an0] = 0.0
                en_rem = exist_node[:n_new]
                sel0 = np.isin(en_rem, an0)
                mem = np.flatnonzero(sel0)  # ascending slots
                if mem.size:
                    np.add.at(nr, en_rem[mem], exist_req[mem])
        else:  # ---- pure append ----
            slots = np.arange(L, n_new, dtype=np.int64)
            app = existing[L:]
            specs = ds.get("exist_specs")
            if specs is None or specs[0][0] is not exist_req:
                specs = [
                    (exist_req, "reqvec", 0.0, 0),
                    (st["el_keys"], "lab_k", -1, 0),
                    (st["el_vals"], "lab_v", -1, 0),
                    (exist_ports, "ports", -1, 0),
                    (st["exist_anti"].reshape(E, -1), "anti", -1, 0),
                    (st["exist_pref"].reshape(E, -1), "pref", -1, 0),
                    (st["exist_pref_w"], "pref_w", 0.0, 0),
                    (st["exist_prio"], "prio", 0, 1),
                    (exist_group, "gid", 0, 1),
                    (ca, "creation", 0.0, 1),
                ]
                ds["exist_specs"] = specs
            flag_aff, flag_tsc, _fv, _fm = ds["flags"]
            limits = {
                "MPL": dims["MPL"], "MA": dims["MA"],
                # MEP (existing-pod port width), not the pending MPorts
                "MPorts": exist_ports.shape[1],
                "MC": 1 << 30,  # exist rows carry no tsc columns
                "R": dims["R"],
                "flag_aff": int(flag_aff),
                # spread counts come from labels, not the existing pod's
                # own constraints — tsc-bearing bound pods are fine
                "flag_tsc": 1,
            }
            lens0 = self._table_lens()
            guard_ok, res = native.pod_rows_into(
                [p for p, _ in app], self._native_ctx(), slots, specs,
                limits,
            )
            if not guard_ok or any(r is None for r in res):
                return False  # dims overflow / unsupported pod
            if self._table_lens() != lens0:
                return False  # interning grew: finalize tables stale
            nidx = ds["node_index"]
            en_new = np.array(
                [nidx.get(nm, -1) for _, nm in app], np.int32
            )
            exist_node[slots] = en_new
            st["exist_valid"][slots] = True
            m = en_new >= 0
            np.add.at(st["node_requested"], en_new[m], exist_req[slots][m])
            g = exist_group[slots]
            np.add.at(st["group_existing_count"], g[g >= 0], 1)
            affected_nodes.update(int(x) for x in en_new[m])
            port_nodes.update(
                int(n) for n, s in zip(en_new, slots)
                if n >= 0 and exist_ports[s, 0] >= 0
            )
            pdbs = st["__refs"][5]
            if pdbs:
                MB = st["exist_pdb"].shape[1]
                for j, (p, _nm) in enumerate(app):
                    b = 0
                    row = st["exist_pdb"][L + j]
                    for gi, pdb in enumerate(pdbs):
                        if b >= MB:
                            break
                        if _pdb_matches(pdb, p):
                            row[b] = gi
                            b += 1

        # ---- used-port lists of affected nodes (rebuilt exactly as the
        # full path builds them: member slots ascending, ports in row
        # order) ----
        if port_nodes:
            if len(port_nodes) > 256:
                return False  # pathological: cheaper as a full encode
            nup = st["node_used_ports"]
            MUP = nup.shape[1]
            en_all = exist_node[:n_new]
            for n in port_nodes:
                members = np.flatnonzero(en_all == n)
                ports_concat = [
                    int(x) for s in members for x in exist_ports[s]
                    if x >= 0
                ]
                if len(ports_concat) > MUP:
                    return False
                nup[n] = -1
                if ports_concat:
                    nup[n, : len(ports_concat)] = ports_concat

        # ---- victim table rows of affected nodes (same lexsort key as
        # the full path, restricted to those nodes) ----
        if affected_nodes:
            npods = st["node_pods"]
            MPN = npods.shape[1]
            an = np.fromiter(affected_nodes, np.int64)
            en_all = exist_node[:n_new]
            sel = np.isin(en_all, an)
            e_ids = np.flatnonzero(sel)
            npods[an] = -1
            if e_ids.size:
                order_v = np.lexsort(
                    (-e_ids, st["exist_prio"][e_ids], en_all[e_ids])
                )
                se = e_ids[order_v].astype(np.int32)
                sn = en_all[se]
                starts = np.r_[True, sn[1:] != sn[:-1]]
                group_start = np.maximum.accumulate(
                    np.where(starts, np.arange(sn.size), 0)
                )
                col = np.arange(sn.size) - group_start
                if int(col.max()) >= MPN:
                    return False  # a node outgrew the victim-table width
                npods[sn, col] = se

        # ---- start times: re-base exactly when the oldest pod changed
        # (full assembly computes base = min over the live set) ----
        newbase = float(ca[:n_new].min()) if n_new else 0.0
        if newbase != st["start_base"]:
            st["exist_start"][:n_new] = (
                ca[:n_new] - newbase
            ).astype(np.float32)
            st["start_base"] = newbase
        elif n_new > n_old:
            sl2 = np.arange(L, n_new)
            st["exist_start"][sl2] = (ca[sl2] - newbase).astype(np.float32)
        st["e_real"] = n_new

        # ---- mirror into the packed arena ----
        A = self._arena
        lo, hi = L, max(n_old, n_new)
        rng = slice(lo, hi)
        for arena_name, st_name in (
            ("exist_requested", "exist_req"),
            ("exist_label_keys", "el_keys"),
            ("exist_label_vals", "el_vals"),
            ("exist_ports", "exist_ports"),
            ("exist_anti_terms", "exist_anti"),
            ("exist_pref_aff", "exist_pref"),
            ("exist_pref_aff_w", "exist_pref_w"),
            ("exist_node", "exist_node"),
            ("exist_priority", "exist_prio"),
            ("exist_pdb", "exist_pdb"),
            ("exist_valid", "exist_valid"),
        ):
            A[arena_name][rng] = st[st_name][rng]
        A["exist_start"][:] = st["exist_start"]
        A["node_requested"][:] = st["node_requested"]
        A["node_pods"][:] = st["node_pods"]
        A["node_used_ports"][:] = st["node_used_ports"]
        A["group_existing_count"][:] = st["group_existing_count"]
        A["num_existing"][...] = n_new

        # ---- commit identity bookkeeping ----
        refs = st["__refs"]
        st["__refs"] = (
            refs[0], [p for p, _ in existing], refs[2], refs[3], refs[4],
            refs[5],
        )
        k = self._stable_key
        self._stable_key = (k[0], new) + k[2:]
        ds["exist_ids"] = (id(existing), len(existing))
        ds["exist_elems"] = new
        # NodePorts static rows read node_used_ports: when the fold
        # actually touched a used-port list, recompute the carry rows of
        # every port-bearing pending slot this cycle
        if port_nodes:
            ds["fold_port_dirty"] = True
        self.fold_hits = getattr(self, "fold_hits", 0) + 1
        return True

    def _encode_delta(self, ds, pending, pod_groups, mutated_ids):
        """The fast path: rewrite only changed pod slots in the arena.
        Returns None to request a full encode (any partial bookkeeping it
        did is simply superseded — the full path rebuilds everything).

        `self.delta_profile` records per-segment milliseconds of the last
        delta encode (detect/rows/ports/apply/order) — the encode-budget
        attribution tool (scripts/profile_encode4.py)."""
        import time as _time

        from .. import native

        _t0 = _time.perf_counter()
        _prof = self.delta_profile = {}
        fold_ms = getattr(self, "_fold_ms", None)
        if fold_ms is not None:
            _prof["fold"] = fold_ms
            self._fold_ms = None
        if self._ingest_ms:
            # staging time already spent in ingest_pod's shadow — kept
            # as its own segment so encode-budget attribution shows the
            # parse cost that the flush no longer pays
            _prof["ingest"] = self._ingest_ms
            self._ingest_ms = 0.0

        def _mark(name):
            nonlocal _t0
            t = _time.perf_counter()
            _prof[name] = _prof.get(name, 0.0) + (t - _t0) * 1e3
            _t0 = t

        dims = ds["dims"]
        P = ds["pads"][2]
        p_real = len(pending)
        if p_real > P:
            return None
        ids = ds["pend_ids"]
        rows = ds["pend_rows"]
        refs = ds["pend_refs"]
        n_prev = len(ids)
        if n_prev < p_real:
            ids += [0] * (p_real - n_prev)
            rows += [None] * (p_real - n_prev)
            refs += [None] * (p_real - n_prev)
        dirty = [
            i for i in range(p_real)
            if ids[i] != id(pending[i]) or ids[i] in mutated_ids
        ]
        # consumers that track POD-CONTENT changes (the extender-verdict
        # carry) read this instead of the returned dirty set, which may
        # be inflated by the port-repair slots below
        self.last_changed_slots = np.asarray(dirty, np.int32)
        if ds.pop("fold_port_dirty", False):
            # an existing-fold changed node_used_ports; NodePorts static
            # rows of port-bearing pending pods must reach the carry
            # update, so their slots join the dirty set (their arena
            # rewrite is a byte-identical no-op)
            extra = [i for i in ds["port_set"] if i < p_real]
            if extra:
                dirty = sorted(set(dirty) | set(extra))
        _mark("detect")
        rowdata = ds["pod_rowdata"]
        lens0 = self._table_lens()
        flag_aff, flag_tsc, flag_vol, flag_mvol = ds["flags"]
        new_rows = []  # dict-interchange rows (fallback pods only)
        fb_slots = []  # their arena slots
        port_set = ds["port_set"]
        creation = ds["creation"]
        # ingest split: dirty slots whose row was staged by ingest_pod
        # skip the flush-time parse entirely — their cached rowdata dict
        # goes through the batched apply below, so the flush pays only
        # the arena write
        staged = self._staged
        ing_slots: set[int] = set()
        if staged:
            for i in dirty:
                p = pending[i]
                ent = staged.get(id(p))
                if ent is not None and ent[0] is p:
                    ing_slots.add(i)
        fd = [i for i in dirty if i not in ing_slots] if ing_slots else dirty
        fused = native.pod_rows_into
        fused_res = None
        if fused is not None and fd:
            # fused fast path (PERF.md round-5): ONE native call parses
            # every dirty pod and writes its arena row + creation column
            # directly — no 26-key rowdata dict, no apply_rows re-read.
            # Pods the native parser does not cover (volumes /
            # nodeAffinity / exotic operators) come back as None and take
            # the dict path below; a guard_ok=False return means a pod
            # overflowed the arena dims, so the whole delta bails to the
            # full encode (partially written rows are rebuilt there).
            specs2 = ds.get("into_specs")
            if specs2 is None:
                specs2 = self._apply_specs(ds) + [
                    (creation, "creation", 0.0, 1)
                ]
                ds["into_specs"] = specs2
            limits = ds.get("into_limits")
            if limits is None:
                limits = {
                    "MPL": dims["MPL"], "MA": dims["MA"],
                    "MPorts": dims["MPorts"], "MC": dims["MC"],
                    "R": dims["R"], "flag_aff": int(flag_aff),
                    "flag_tsc": int(flag_tsc),
                }
                ds["into_limits"] = limits
            guard_ok, fused_res = fused(
                [pending[i] for i in fd], self._native_ctx(),
                np.asarray(fd, np.int64), specs2, limits,
            )
            if not guard_ok:
                return None  # arena dims too small: full re-encode
        fused_map: dict[int, Any] = {}
        if fused_res is not None:
            for j, i in enumerate(fd):
                fused_map[i] = fused_res[j]
        for i in dirty:
            p = pending[i]
            ids[i] = id(p)
            refs[i] = p
            if i in ing_slots:
                # staged at ingest: rowdata is a _pod_cache hit (the
                # parse already ran in the buffer path's shadow)
                staged.pop(id(p), None)
                self.ingest_hits += 1
                r = None
            else:
                r = fused_map.get(i)
            if r is None:  # staged, no native builder, or dict-path pod
                d = rowdata(p)
                new_rows.append(d)
                fb_slots.append(i)
                rows[i] = d
                r = d["ports"]
            else:
                # only "ports" is ever read back from delta rows
                rows[i] = {"ports": r}
            if len(r):
                port_set.add(i)
            else:
                port_set.discard(i)
        _mark("rows")
        if self._table_lens() != lens0:
            return None  # interning grew: stable tables need new entries
        for d in new_rows:
            if (
                len(d["lab_k"]) > dims["MPL"]
                or d["n_aff"] > dims["MA"]
                or len(d["ports"]) > dims["MPorts"]
                or len(d["tsc_skew"]) > dims["MC"]
                or len(d["vol_mode"]) > dims["MVol"]
                or len(d["reqvec"]) > dims["R"]
            ):
                return None
            if not flag_aff and d["n_aff"] > 0:
                return None
            if not flag_tsc and len(d["tsc_skew"]) > 0:
                return None
            if not flag_vol and len(d["vol_mode"]) > 0:
                return None
            if not flag_mvol and len(d["vol_mode"]) >= 2:
                # a first multi-PVC pod flips the joint-admission
                # capability: full path recompiles with the flag on
                return None
        # distinct-port axis: re-intern over every slot that has ports
        # (matches the full path's slot-order interning exactly); the
        # slot set is maintained incrementally, sorted here so interning
        # order equals the full path's slot order
        port_slots = sorted(i for i in port_set if i < p_real)
        port_tab: dict[int, int] = {}
        port_id_rows = []
        for i in port_slots:
            pr = []
            for ep in rows[i]["ports"]:
                ep = int(ep)
                j = port_tab.get(ep)
                if j is None:
                    j = len(port_tab)
                    port_tab[ep] = j
                pr.append(j)
            port_id_rows.append(np.array(pr, np.int32))
        if _pad_dim(len(port_tab), 4) > dims["Q"]:
            return None
        _mark("ports")

        # ---- all checks passed: write the arena ----
        # fused-path rows are already in place; only fallback dict rows
        # need the batched apply + creation write here
        A = self._arena
        if fb_slots:
            idx = np.asarray(fb_slots, np.int64)
            native.apply_rows(self._apply_specs(ds), idx, new_rows)
            creation[idx] = [d["creation"] for d in new_rows]
        if dirty:
            idx = np.asarray(dirty, np.int64)
            nidx = ds["node_index"]
            A["pod_node_name"][idx] = [
                nidx.get(pending[i].spec.node_name, -2)
                if pending[i].spec.node_name else -1
                for i in dirty
            ]
            A["pod_nominated"][idx] = [
                nidx.get(pending[i].nominated_node_name, -1)
                if pending[i].nominated_node_name else -1
                for i in dirty
            ]

        _mark("apply")
        if p_real != ds["p_real"]:
            pv = A["pod_valid"]
            pv[:] = False
            pv[:p_real] = True
            if p_real < ds["p_real"]:
                self._clear_slots(slice(p_real, ds["p_real"]))
                creation[p_real:ds["p_real"]] = 0.0
                for i in range(p_real, ds["p_real"]):
                    port_set.discard(i)
            del ids[p_real:]
            del rows[p_real:]
            del refs[p_real:]
            ds["p_real"] = p_real
            A["num_pending"][...] = p_real

        ppi = A["pod_port_ids"]
        ppi[:] = -1
        if port_slots:
            native.scatter_rows_at(
                ppi, np.asarray(port_slots, np.int64), port_id_rows
            )

        prio = A["pod_priority"]
        po = A["pod_order"]
        po[:] = np.iinfo(np.int32).max
        if p_real:
            po[:p_real] = self.queue_sort.rank(
                pending, prio[:p_real], creation[:p_real]
            )

        gm = A["group_min_member"]
        gm[:] = 0
        if pod_groups or self._group_ids:
            declared = {g.name: g.min_member for g in pod_groups}
            if declared:
                for name, gi in self._group_ids.items():
                    mm = declared.get(name)
                    if mm:
                        gm[gi] = mm

        _mark("order")
        self._cycle_index += 1
        A["cycle_index"][...] = self._cycle_index
        return EncodedFrame(
            self._arena_w, self._arena_b, self._arena_spec,
            self._arena_snap, np.asarray(dirty, np.int32),
        )

    def _install_arena(self, snap: ClusterSnapshot):
        """(Re)build the persistent packed arena from a fully-encoded
        snapshot and return (wbuf, bbuf, spec, view_snapshot)."""
        from . import packing

        spec = packing.make_spec(snap)
        reuse = (
            self._arena_spec is not None
            and spec.key() == self._arena_spec.key()
        )
        if not reuse:
            wbuf = np.empty(spec.n_words, np.uint32)
            bbuf = np.zeros(spec.n_bytes, np.uint8)
            views: dict[str, np.ndarray] = {}
            for name, dt, shape, off in spec.words:
                n = int(np.prod(shape, dtype=np.int64)) if shape else 1
                views[name] = (
                    wbuf[off:off + n]
                    .view(np.int32 if dt == "int32" else np.float32)
                    .reshape(shape)
                )
            for name, shape, off in spec.bools:
                n = int(np.prod(shape, dtype=np.int64)) if shape else 1
                views[name] = bbuf[off:off + n].view(np.bool_).reshape(shape)
            self._arena_spec = spec
            self._arena_w = wbuf
            self._arena_b = bbuf
            self._arena = views
            self._arena_snap = dataclasses.replace(snap, **views)
        for name, v in self._arena.items():
            v[...] = getattr(snap, name)
        self._arena_synced = True
        return EncodedFrame(
            self._arena_w, self._arena_b, self._arena_spec,
            self._arena_snap, None,
        )


def _pdb_matches(pdb: api.PodDisruptionBudget, p: Pod) -> bool:
    """Does `pdb`'s selector cover pod `p`? Shared by the full stable
    assembly and the incremental existing-fold."""
    if p.namespace != pdb.namespace:
        return False
    sel = pdb.selector
    for k, v in sel.match_labels.items():
        if p.metadata.labels.get(k) != v:
            return False
    for e in sel.match_expressions:
        val = p.metadata.labels.get(e.key)
        if e.operator == api.OP_IN and val not in e.values:
            return False
        if e.operator == api.OP_NOT_IN and val in e.values:
            return False
        if e.operator == api.OP_EXISTS and val is None:
            return False
        if e.operator == api.OP_DOES_NOT_EXIST and val is not None:
            return False
    return True


def _aff(p: Pod) -> Affinity:
    return p.spec.affinity or Affinity()


def _pref_count(p: Pod) -> int:
    a = _aff(p)
    n = 0
    if a.pod_affinity:
        n += len(a.pod_affinity.preferred)
    if a.pod_anti_affinity:
        n += len(a.pod_anti_affinity.preferred)
    return n
