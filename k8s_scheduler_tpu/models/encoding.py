"""Snapshot encoding: typed Pod/Node objects -> structure-of-arrays tensors.

This is the TPU-native replacement for the reference's `SchedulerCache`
snapshot (`internal/cache/snapshot.go`, `framework/types.go` NodeInfo —
[UNVERIFIED] locations, mount empty; SURVEY.md §2 C4/C5): instead of a list
of per-node `NodeInfo` structs walked by goroutines, the cluster state is a
set of padded, integer-interned device arrays that one jitted program
consumes.

Encoding strategy (SURVEY.md §7 step 1 + "hard parts" (c)):

- **Interning.** Every string (label keys/values, taint keys, namespaces,
  image names, topology keys) becomes an int32 id via `StringInterner`.
- **Dedup + gather.** Pod-side structures that repeat across pods (node
  affinity requirements, toleration sets, label selectors, image sets) are
  deduplicated into small tables; each pod stores table indices. Kernels
  evaluate the small table against all nodes/pods, then a gather expands to
  the pods axis — O(distinct x N) instead of O(P x N x terms).
- **Padding.** Every ragged axis is padded to a bucketed size with -1
  sentinels so shapes are static across cycles and jit caches stay warm.
- **Label expressions** (`In/NotIn/Exists/DoesNotExist/Gt/Lt`) become rows
  of one expression table usable against node labels and pod labels alike;
  `matchFields` (metadata.name) rows resolve to node-index sets at encode
  time (FIELD_IN).

Namespace scoping of pod-affinity selectors is encoded as an extra implicit
expression on a reserved label key (`__namespace__`), which is injected into
every pod's encoded label list.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from . import api
from .api import (
    Affinity,
    LabelSelector,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinityTerm,
)

# Operator codes for the expression table.
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OP_FIELD_IN = 6  # matchFields metadata.name: values are node indices
OP_IMPOSSIBLE = 7  # never matches (malformed requirement, upstream no-match)

_OP_CODE = {
    api.OP_IN: OP_IN,
    api.OP_NOT_IN: OP_NOT_IN,
    api.OP_EXISTS: OP_EXISTS,
    api.OP_DOES_NOT_EXIST: OP_DOES_NOT_EXIST,
    api.OP_GT: OP_GT,
    api.OP_LT: OP_LT,
}

# Taint effect codes.
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
_EFFECT_CODE = {
    api.NO_SCHEDULE: EFFECT_NO_SCHEDULE,
    api.PREFER_NO_SCHEDULE: EFFECT_PREFER_NO_SCHEDULE,
    api.NO_EXECUTE: EFFECT_NO_EXECUTE,
}

TOL_OP_EQUAL = 0
TOL_OP_EXISTS = 1

WHEN_DO_NOT_SCHEDULE = 0
WHEN_SCHEDULE_ANYWAY = 1

NAMESPACE_KEY = "__namespace__"
HOSTNAME_LABEL = "kubernetes.io/hostname"


class StringInterner:
    """str -> dense int32 id. id 0 is reserved for "" (absent)."""

    def __init__(self) -> None:
        self._ids: dict[str, int] = {"": 0}
        self._strs: list[str] = [""]

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
        return i

    def lookup(self, i: int) -> str:
        return self._strs[i]

    def get(self, s: str) -> int:
        """Like intern but -1 for unknown (no table growth)."""
        return self._ids.get(s, -1)

    def __len__(self) -> int:
        return len(self._strs)


class _InternTable:
    """Dedup table: hashable row -> dense index, rows in insertion order.
    Every pod-side structure that repeats across pods (requirements,
    toleration sets, selectors, image sets...) goes through one of these."""

    def __init__(self) -> None:
        self.index: dict = {}
        self.rows: list = []

    def intern(self, row) -> int:
        i = self.index.get(row)
        if i is None:
            i = len(self.rows)
            self.index[row] = i
            self.rows.append(row)
        return i

    def __len__(self) -> int:
        return len(self.rows)


def _pad_dim(n: int, bucket: int = 8, minimum: int = 1) -> int:
    """Round up to a bucket multiple so shapes are stable across cycles."""
    n = max(n, minimum)
    return ((n + bucket - 1) // bucket) * bucket


def _pow2_bucket(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two (jit-cache-friendly P/N padding)."""
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


def _num_or_nan(s: str) -> float:
    try:
        return float(s)
    except ValueError:
        return float("nan")


@dataclass
class ClusterSnapshot:
    """The device-consumable cluster state. All arrays are numpy on the host;
    `jax.device_put` (or simply passing into a jitted function) moves them.

    Axis glossary: N nodes, P pending pods, E existing (assigned/assumed)
    pods, R resources, Ex label expressions, Rq node-affinity requirement
    sets, Pf preferred-node-affinity sets, Tl toleration sets, Ts taint
    sets, S pod label selectors, D flat topology domains, K topology keys,
    I distinct images, Is distinct image sets, G pod groups, MPN max pods
    per node (preemption table).
    """

    # --- names (static aux data, baked into the compiled program) ---
    resource_names: tuple[str, ...]
    topology_keys: tuple[str, ...]  # interned topology key strings, order = K axis
    # padded count of distinct pending host ports (Q axis of the scan's
    # port-claim bitmap; static because it is a shape, bucketed by 4)
    num_distinct_ports: int
    # capability flags (static): when False, the corresponding plugin
    # contributes nothing and its whole kernel is never traced — a cluster
    # without affinity pays zero for the affinity machinery
    has_inter_pod_affinity: bool
    has_topology_spread: bool

    # --- real (unpadded) counts: 0-d arrays, NOT static — a changed pod
    # count must not recompile the cycle (only padded shapes are static) ---
    num_nodes: np.ndarray
    num_pending: np.ndarray
    num_existing: np.ndarray
    num_domains: np.ndarray

    # --- nodes [N...] ---
    node_allocatable: np.ndarray  # f32 [N, R]
    node_requested: np.ndarray  # f32 [N, R] aggregated from existing pods
    node_unschedulable: np.ndarray  # bool [N]
    node_taintset: np.ndarray  # i32 [N] -> Ts
    node_label_keys: np.ndarray  # i32 [N, ML]
    node_label_vals: np.ndarray  # i32 [N, ML]
    node_label_num: np.ndarray  # f32 [N, ML] numeric parse of value (nan if not)
    node_domains: np.ndarray  # i32 [N, K] flat domain id (-1 = key absent)
    node_images: np.ndarray  # bool [N, I]
    node_used_ports: np.ndarray  # i32 [N, MPorts] encoded host ports (-1 pad)
    node_valid: np.ndarray  # bool [N] (padding rows are False)

    # --- label expression table [Ex...] ---
    ex_key: np.ndarray  # i32 [Ex]
    ex_op: np.ndarray  # i32 [Ex]
    ex_vals: np.ndarray  # i32 [Ex, MV] (-1 pad); node indices for FIELD_IN
    ex_num: np.ndarray  # f32 [Ex] numeric bound for Gt/Lt

    # --- node-affinity requirement sets (OR over terms of AND over exprs) ---
    rq_exprs: np.ndarray  # i32 [Rq, MT, ME] (-1 pad)

    # --- preferred node affinity [Pf...] (flat weighted AND-terms) ---
    pf_exprs: np.ndarray  # i32 [Pf, MPT, ME]
    pf_weight: np.ndarray  # f32 [Pf, MPT] (0 pad)

    # --- toleration / taint set tables ---
    tl_key: np.ndarray  # i32 [Tl, MTl] (-1 = empty key i.e. match-any + Exists)
    tl_op: np.ndarray  # i32 [Tl, MTl]
    tl_val: np.ndarray  # i32 [Tl, MTl]
    tl_effect: np.ndarray  # i32 [Tl, MTl] (-1 = all effects)
    tl_valid: np.ndarray  # bool [Tl, MTl]
    ts_key: np.ndarray  # i32 [Ts, MTt]
    ts_val: np.ndarray  # i32 [Ts, MTt]
    ts_effect: np.ndarray  # i32 [Ts, MTt]
    ts_valid: np.ndarray  # bool [Ts, MTt]

    # --- pod label selectors [S...] (AND of exprs, incl. namespace expr) ---
    sel_exprs: np.ndarray  # i32 [S, MSE] (-1 pad)

    # --- pending pods [P...] ---
    pod_requested: np.ndarray  # f32 [P, R]
    pod_priority: np.ndarray  # i32 [P]
    pod_order: np.ndarray  # i32 [P] rank by (priority desc, creation ts asc)
    pod_node_name: np.ndarray  # i32 [P] node index pin (-1 none)
    pod_nominated: np.ndarray  # i32 [P] node index (-1 none)
    pod_req_id: np.ndarray  # i32 [P] -> Rq (node affinity required; -1 none)
    pod_sel_req_id: np.ndarray  # i32 [P] -> Rq (nodeSelector; -1 none)
    pod_pref_id: np.ndarray  # i32 [P] -> Pf (-1 none)
    pod_tolset: np.ndarray  # i32 [P] -> Tl
    pod_label_keys: np.ndarray  # i32 [P, MPL]
    pod_label_vals: np.ndarray  # i32 [P, MPL]
    pod_ports: np.ndarray  # i32 [P, MPorts] encoded host ports (-1 pad)
    # same ports as indices into the distinct pending-port axis Q — the
    # commit scan tracks intra-batch port claims as a [N, Q] bitmap
    pod_port_ids: np.ndarray  # i32 [P, MPorts] -> Q (-1 pad)
    pod_aff_terms: np.ndarray  # i32 [P, MA, 2] (sel, topo-key idx) (-1 pad)
    pod_anti_terms: np.ndarray  # i32 [P, MA, 2]
    pod_pref_aff: np.ndarray  # i32 [P, MA, 2] preferred affinity terms
    pod_pref_aff_w: np.ndarray  # f32 [P, MA] weights (anti encoded as negative)
    pod_tsc: np.ndarray  # i32 [P, MC, 3] (topo-key idx, sel, when) (-1 pad)
    pod_tsc_skew: np.ndarray  # i32 [P, MC] max_skew (0 pad)
    pod_group: np.ndarray  # i32 [P] -> G (-1 none)
    pod_imageset: np.ndarray  # i32 [P] -> Is
    pod_can_preempt: np.ndarray  # bool [P] (preemptionPolicy != Never)
    pod_valid: np.ndarray  # bool [P]

    # --- pod groups [G] ---
    group_min_member: np.ndarray  # i32 [G]
    group_existing_count: np.ndarray  # i32 [G] members already running

    # --- image sets ---
    imgset_sizes: np.ndarray  # f32 [Is, I] size in bytes of image i if in set

    # --- existing pods [E...] ---
    exist_node: np.ndarray  # i32 [E] node index
    exist_priority: np.ndarray  # i32 [E]
    exist_requested: np.ndarray  # f32 [E, R]
    exist_label_keys: np.ndarray  # i32 [E, MPL]
    exist_label_vals: np.ndarray  # i32 [E, MPL]
    exist_anti_terms: np.ndarray  # i32 [E, MA, 2] their required anti-affinity
    exist_pref_aff: np.ndarray  # i32 [E, MA, 2] their preferred (anti) affinity
    exist_pref_aff_w: np.ndarray  # f32 [E, MA] (anti negative)
    exist_valid: np.ndarray  # bool [E]

    # --- per-node existing-pod table for preemption [N, MPN] ---
    # indices into E, sorted ascending by priority (victims are prefixes)
    node_pods: np.ndarray  # i32 [N, MPN] (-1 pad)

    # --- topology domains ---
    domain_key: np.ndarray  # i32 [D] which topology-key axis each domain is under
    # number of nodes per domain (for spread normalization)
    domain_node_count: np.ndarray  # f32 [D]

    @property
    def P(self) -> int:
        return self.pod_requested.shape[0]

    @property
    def N(self) -> int:
        return self.node_allocatable.shape[0]

    @property
    def E(self) -> int:
        return self.exist_node.shape[0]

    @property
    def R(self) -> int:
        return len(self.resource_names)

    def array_fields(self) -> dict[str, np.ndarray]:
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if isinstance(getattr(self, f.name), np.ndarray)
        }


# Register as a jax pytree with the non-array fields as static aux data, so
# a ClusterSnapshot can be passed straight into jitted kernels.
def _register_pytree() -> None:
    import jax

    data = [f.name for f in dataclasses.fields(ClusterSnapshot)
            if f.type == "np.ndarray"]
    meta = [f.name for f in dataclasses.fields(ClusterSnapshot)
            if f.type != "np.ndarray"]
    jax.tree_util.register_dataclass(
        ClusterSnapshot, data_fields=data, meta_fields=meta
    )


_register_pytree()


class SnapshotEncoder:
    """Builds `ClusterSnapshot`s. Holds interners so ids are stable across
    cycles (incremental cache updates reuse one encoder instance)."""

    def __init__(
        self,
        resource_names: Sequence[str] = api.DEFAULT_RESOURCES,
        pad_pods: int | None = None,
        pad_nodes: int | None = None,
    ) -> None:
        self.strings = StringInterner()
        self.resource_names = list(resource_names)
        self.pad_pods = pad_pods
        self.pad_nodes = pad_nodes

    # -- small helpers -----------------------------------------------------

    def _resources_vec(self, req: dict[str, float]) -> np.ndarray:
        for name in req:
            if name not in self.resource_names:
                self.resource_names.append(name)
        v = np.zeros(len(self.resource_names), np.float32)
        for name, val in req.items():
            v[self.resource_names.index(name)] = val
        return v

    def encode(
        self,
        nodes: Sequence[Node],
        pending: Sequence[Pod],
        existing: Sequence[tuple[Pod, str]] = (),
        pod_groups: Sequence[api.PodGroup] = (),
    ) -> ClusterSnapshot:
        """One-shot encode. `existing` is (pod, node_name) for every pod
        already assigned (bound or assumed)."""
        S = self.strings
        rn = self.resource_names
        # Discover all resource names first so vectors have a single width.
        for nd in nodes:
            self._resources_vec(nd.status.allocatable)
        reqs_pending = [self._resources_vec(p.resource_requests()) for p in pending]
        reqs_exist = [self._resources_vec(p.resource_requests()) for p, _ in existing]
        R = len(rn)

        def vec(x: np.ndarray) -> np.ndarray:
            out = np.zeros(R, np.float32)
            out[: x.shape[0]] = x
            return out

        n_real, p_real, e_real = len(nodes), len(pending), len(existing)
        N = self.pad_nodes or _pow2_bucket(n_real)
        P = self.pad_pods or _pow2_bucket(p_real)
        E = _pow2_bucket(e_real) if e_real else 8

        node_index = {nd.name: i for i, nd in enumerate(nodes)}

        # ---- tables built during the walk ----
        exprs_t = _InternTable()  # rows: (key, op, vals, num)
        reqs_t = _InternTable()  # rows: tuple of terms (each a tuple of expr ids)
        prefs_t = _InternTable()  # rows: tuple of (exprs, weight)
        tols_t = _InternTable()  # rows: sorted (key, op, val, effect)
        taints_t = _InternTable()  # rows: sorted (key, val, effect)
        sels_t = _InternTable()  # rows: tuple of expr ids
        imgsets_t = _InternTable()  # rows: sorted image ids

        def intern_expr(key: int, op: int, vals: tuple[int, ...], num: float) -> int:
            return exprs_t.intern((key, op, vals, num))

        def compile_req(r: NodeSelectorRequirement) -> int:
            op = _OP_CODE[r.operator]
            vals = tuple(sorted(S.intern(v) for v in r.values))
            num = 0.0
            if op in (OP_GT, OP_LT):
                # upstream treats a missing or non-numeric bound as no-match
                try:
                    num = float(r.values[0])
                except (IndexError, ValueError):
                    return intern_expr(0, OP_IMPOSSIBLE, (), 0.0)
                vals = ()
            return intern_expr(S.intern(r.key), op, vals, num)

        def compile_field_req(r: NodeSelectorRequirement) -> int:
            # metadata.name In [names] -> node index set (FIELD_IN); only
            # In/NotIn are defined for matchFields, anything else no-matches
            if r.operator not in (api.OP_IN, api.OP_NOT_IN):
                return intern_expr(0, OP_IMPOSSIBLE, (), 0.0)
            idxs = tuple(
                sorted(node_index[v] for v in r.values if v in node_index)
            )
            # encode NotIn by op FIELD_IN with complement at kernel level is
            # messy; instead resolve the complement here (node set is known).
            if r.operator == api.OP_NOT_IN:
                idxs = tuple(i for i in range(n_real) if i not in set(idxs))
            return intern_expr(0, OP_FIELD_IN, idxs, 0.0)

        def compile_node_affinity_required(terms: Sequence[NodeSelectorTerm]) -> int:
            compiled = []
            for t in terms:
                exprs = [compile_req(e) for e in t.match_expressions]
                exprs += [compile_field_req(e) for e in t.match_fields]
                compiled.append(tuple(exprs))
            if not compiled:
                return -1
            return reqs_t.intern(tuple(compiled))

        def compile_node_affinity_preferred(
            prefs: Sequence[api.PreferredSchedulingTerm],
        ) -> int:
            rows = []
            for p in prefs:
                exprs = [compile_req(e) for e in p.preference.match_expressions]
                exprs += [compile_field_req(e) for e in p.preference.match_fields]
                rows.append((tuple(exprs), float(p.weight)))
            if not rows:
                return -1
            return prefs_t.intern(tuple(rows))

        def compile_tolerations(tols: Sequence[api.Toleration]) -> int:
            rows = []
            for t in tols:
                key = S.intern(t.key) if t.key else -1
                op = TOL_OP_EXISTS if t.operator == "Exists" else TOL_OP_EQUAL
                val = S.intern(t.value)
                eff = _EFFECT_CODE[t.effect] if t.effect else -1
                rows.append((key, op, val, eff))
            return tols_t.intern(tuple(sorted(rows)))

        def compile_taints(taints: Sequence[api.Taint]) -> int:
            return taints_t.intern(
                tuple(
                    sorted(
                        (S.intern(t.key), S.intern(t.value), _EFFECT_CODE[t.effect])
                        for t in taints
                    )
                )
            )

        topo_keys: list[str] = [HOSTNAME_LABEL]

        def topo_key_idx(key: str) -> int:
            if key not in topo_keys:
                topo_keys.append(key)
            return topo_keys.index(key)

        def compile_selector(sel: LabelSelector, namespaces: tuple[str, ...]) -> int:
            exprs = []
            ns_vals = tuple(sorted(S.intern(n) for n in namespaces))
            exprs.append(intern_expr(S.intern(NAMESPACE_KEY), OP_IN, ns_vals, 0.0))
            for k, v in sorted(sel.match_labels.items()):
                exprs.append(
                    intern_expr(S.intern(k), OP_IN, (S.intern(v),), 0.0)
                )
            for e in sel.match_expressions:
                exprs.append(compile_req(e))
            return sels_t.intern(tuple(exprs))

        def compile_aff_terms(
            terms: Sequence[PodAffinityTerm], own_ns: str
        ) -> list[tuple[int, int]]:
            out = []
            for t in terms:
                ns = t.namespaces or (own_ns,)
                out.append(
                    (compile_selector(t.label_selector, tuple(ns)), topo_key_idx(t.topology_key))
                )
            return out

        image_ids: dict[str, int] = {}

        def image_id(name: str) -> int:
            i = image_ids.get(name)
            if i is None:
                i = len(image_ids)
                image_ids[name] = i
            return i

        def compile_imageset(images: Sequence[str]) -> int:
            return imgsets_t.intern(tuple(sorted(image_id(i) for i in images)))

        group_ids: dict[str, int] = {}
        group_min: list[int] = []
        declared = {g.name: g.min_member for g in pod_groups}

        def group_id(name: str) -> int:
            if not name:
                return -1
            i = group_ids.get(name)
            if i is None:
                i = len(group_ids)
                group_ids[name] = i
                group_min.append(declared.get(name, 0))
            return i

        # ---- walk nodes ----
        ML = _pad_dim(
            max((len(nd.metadata.labels) + 1 for nd in nodes), default=1), 8
        )
        node_alloc = np.zeros((N, R), np.float32)
        node_requested = np.zeros((N, R), np.float32)
        node_unsched = np.zeros(N, bool)
        node_taintset = np.zeros(N, np.int32)
        nl_keys = np.full((N, ML), -1, np.int32)
        nl_vals = np.full((N, ML), -1, np.int32)
        nl_num = np.full((N, ML), np.nan, np.float32)
        node_valid = np.zeros(N, bool)
        node_valid[:n_real] = True

        node_image_sets: list[list[int]] = []
        image_sizes: dict[int, float] = {}

        for i, nd in enumerate(nodes):
            node_alloc[i] = vec(self._resources_vec(nd.status.allocatable))
            node_unsched[i] = nd.spec.unschedulable
            node_taintset[i] = compile_taints(nd.spec.taints)
            labels = dict(nd.metadata.labels)
            labels.setdefault(HOSTNAME_LABEL, nd.name)
            for j, (k, v) in enumerate(sorted(labels.items())):
                nl_keys[i, j] = S.intern(k)
                nl_vals[i, j] = S.intern(v)
                nl_num[i, j] = _num_or_nan(v)
            imgs = []
            for img in nd.status.images:
                for nm in img.names:
                    ii = image_id(nm)
                    imgs.append(ii)
                    image_sizes[ii] = float(img.size_bytes)
            node_image_sets.append(imgs)

        # ---- walk pending pods ----
        pod_req = np.zeros((P, R), np.float32)
        pod_prio = np.zeros(P, np.int32)
        pod_node_name = np.full(P, -1, np.int32)
        pod_nominated = np.full(P, -1, np.int32)
        pod_req_id = np.full(P, -1, np.int32)
        pod_sel_req_id = np.full(P, -1, np.int32)
        pod_pref_id = np.full(P, -1, np.int32)
        pod_tolset = np.zeros(P, np.int32)
        pod_group_arr = np.full(P, -1, np.int32)
        pod_imageset = np.zeros(P, np.int32)
        pod_can_preempt = np.zeros(P, bool)
        pod_valid = np.zeros(P, bool)
        pod_valid[:p_real] = True

        MPL = _pad_dim(
            max(
                [len(p.metadata.labels) + 1 for p in pending]
                + [len(p.metadata.labels) + 1 for p, _ in existing]
                + [1]
            ),
            8,
        )
        pl_keys = np.full((P, MPL), -1, np.int32)
        pl_vals = np.full((P, MPL), -1, np.int32)

        MPorts = _pad_dim(
            max(
                [len(p.host_ports()) for p in pending]
                + [1]
            ),
            4,
        )
        pod_ports = np.full((P, MPorts), -1, np.int32)
        pod_port_ids = np.full((P, MPorts), -1, np.int32)
        port_ids_t = _InternTable()  # distinct (port, proto) among pending

        MA = _pad_dim(
            max(
                [
                    max(
                        len(_aff(p).pod_affinity.required) if _aff(p).pod_affinity else 0,
                        len(_aff(p).pod_anti_affinity.required) if _aff(p).pod_anti_affinity else 0,
                        _pref_count(p),
                    )
                    for p in list(pending) + [p for p, _ in existing]
                ]
                + [1]
            ),
            4,
        )
        pod_aff_terms = np.full((P, MA, 2), -1, np.int32)
        pod_anti_terms = np.full((P, MA, 2), -1, np.int32)
        pod_pref_aff = np.full((P, MA, 2), -1, np.int32)
        pod_pref_aff_w = np.zeros((P, MA), np.float32)

        MC = _pad_dim(
            max([len(p.spec.topology_spread_constraints) for p in pending] + [1]), 4
        )
        pod_tsc = np.full((P, MC, 3), -1, np.int32)
        pod_tsc_skew = np.zeros((P, MC), np.int32)

        def encode_pod_labels(p: Pod, keys: np.ndarray, vals: np.ndarray, row: int) -> None:
            keys[row, 0] = S.intern(NAMESPACE_KEY)
            vals[row, 0] = S.intern(p.namespace)
            for j, (k, v) in enumerate(sorted(p.metadata.labels.items()), start=1):
                keys[row, j] = S.intern(k)
                vals[row, j] = S.intern(v)

        def encode_aff(p: Pod, row: int, aff_arr, anti_arr, pref_arr, pref_w) -> None:
            a = _aff(p)
            ns = p.namespace
            if a.pod_affinity:
                for j, t in enumerate(compile_aff_terms(a.pod_affinity.required, ns)):
                    aff_arr[row, j] = t
            if a.pod_anti_affinity:
                for j, t in enumerate(compile_aff_terms(a.pod_anti_affinity.required, ns)):
                    anti_arr[row, j] = t
            prefs: list[tuple[int, int, float]] = []
            if a.pod_affinity:
                for w in a.pod_affinity.preferred:
                    (s, k) = compile_aff_terms([w.term], ns)[0]
                    prefs.append((s, k, float(w.weight)))
            if a.pod_anti_affinity:
                for w in a.pod_anti_affinity.preferred:
                    (s, k) = compile_aff_terms([w.term], ns)[0]
                    prefs.append((s, k, -float(w.weight)))
            for j, (s, k, w) in enumerate(prefs):
                pref_arr[row, j] = (s, k)
                pref_w[row, j] = w

        for i, p in enumerate(pending):
            pod_req[i] = vec(reqs_pending[i])
            pod_prio[i] = p.spec.priority
            if p.spec.node_name:
                pod_node_name[i] = node_index.get(p.spec.node_name, -2)
            if p.nominated_node_name:
                pod_nominated[i] = node_index.get(p.nominated_node_name, -1)
            a = _aff(p)
            if a.node_affinity and a.node_affinity.required:
                pod_req_id[i] = compile_node_affinity_required(a.node_affinity.required)
            if a.node_affinity and a.node_affinity.preferred:
                pod_pref_id[i] = compile_node_affinity_preferred(a.node_affinity.preferred)
            if p.spec.node_selector:
                term = NodeSelectorTerm(
                    tuple(
                        NodeSelectorRequirement(k, api.OP_IN, (v,))
                        for k, v in sorted(p.spec.node_selector.items())
                    )
                )
                pod_sel_req_id[i] = compile_node_affinity_required([term])
            pod_tolset[i] = compile_tolerations(p.spec.tolerations)
            encode_pod_labels(p, pl_keys, pl_vals, i)
            for j, (port, proto, _) in enumerate(p.host_ports()):
                enc_port = port * 4 + {"TCP": 0, "UDP": 1, "SCTP": 2}.get(proto, 3)
                pod_ports[i, j] = enc_port
                pod_port_ids[i, j] = port_ids_t.intern(enc_port)
            encode_aff(p, i, pod_aff_terms, pod_anti_terms, pod_pref_aff, pod_pref_aff_w)
            for j, c in enumerate(p.spec.topology_spread_constraints):
                when = (
                    WHEN_DO_NOT_SCHEDULE
                    if c.when_unsatisfiable == api.DO_NOT_SCHEDULE
                    else WHEN_SCHEDULE_ANYWAY
                )
                pod_tsc[i, j] = (
                    topo_key_idx(c.topology_key),
                    compile_selector(c.label_selector, (p.namespace,)),
                    when,
                )
                pod_tsc_skew[i, j] = c.max_skew
            pod_group_arr[i] = group_id(p.spec.pod_group)
            pod_imageset[i] = compile_imageset(p.images())
            pod_can_preempt[i] = p.spec.preemption_policy != "Never"

        # ---- walk existing pods ----
        exist_node = np.full(E, -1, np.int32)
        exist_prio = np.zeros(E, np.int32)
        exist_req = np.zeros((E, R), np.float32)
        el_keys = np.full((E, MPL), -1, np.int32)
        el_vals = np.full((E, MPL), -1, np.int32)
        exist_anti = np.full((E, MA, 2), -1, np.int32)
        exist_pref = np.full((E, MA, 2), -1, np.int32)
        exist_pref_w = np.zeros((E, MA), np.float32)
        exist_valid = np.zeros(E, bool)
        exist_valid[:e_real] = True

        used_ports: list[list[int]] = [[] for _ in range(N)]
        per_node: list[list[int]] = [[] for _ in range(N)]
        # existing pods' own (non-anti) required affinity is not re-checked
        # against incoming pods (upstream symmetry applies to anti-affinity
        # and preferred terms only), so those terms go to a scratch array
        scratch_aff = np.full((E, MA, 2), -1, np.int32)

        exist_group = np.full(E, -1, np.int32)
        for i, (p, node_name) in enumerate(existing):
            ni = node_index.get(node_name, -1)
            exist_node[i] = ni
            exist_prio[i] = p.spec.priority
            exist_group[i] = group_id(p.spec.pod_group)
            exist_req[i] = vec(reqs_exist[i])
            encode_pod_labels(p, el_keys, el_vals, i)
            encode_aff(p, i, scratch_aff, exist_anti,
                       exist_pref, exist_pref_w)
            if ni >= 0:
                node_requested[ni] += exist_req[i]
                per_node[ni].append(i)
                for (port, proto, _) in p.host_ports():
                    used_ports[ni].append(
                        port * 4 + {"TCP": 0, "UDP": 1, "SCTP": 2}.get(proto, 3)
                    )

        MUP = _pad_dim(max([len(u) for u in used_ports] + [1]), 4)
        node_used_ports = np.full((N, MUP), -1, np.int32)
        for i, u in enumerate(used_ports):
            node_used_ports[i, : len(u)] = u

        MPN = _pad_dim(max([len(x) for x in per_node] + [1]), 8)
        node_pods = np.full((N, MPN), -1, np.int32)
        for i, idxs in enumerate(per_node):
            idxs = sorted(idxs, key=lambda e: (exist_prio[e], -e))
            node_pods[i, : len(idxs)] = idxs

        # ---- topology domains (flat ids across keys) ----
        K = len(topo_keys)
        topo_key_ids = [S.intern(k) for k in topo_keys]
        domain_map: dict[tuple[int, int], int] = {}
        node_domains = np.full((N, K), -1, np.int32)
        for i, nd in enumerate(nodes):
            labels = dict(nd.metadata.labels)
            labels.setdefault(HOSTNAME_LABEL, nd.name)
            for k, key in enumerate(topo_keys):
                if key in labels:
                    dk = (k, S.intern(labels[key]))
                    if dk not in domain_map:
                        domain_map[dk] = len(domain_map)
                    node_domains[i, k] = domain_map[dk]
        D = _pad_dim(len(domain_map), 8)
        domain_key = np.full(D, -1, np.int32)
        domain_node_count = np.zeros(D, np.float32)
        for (k, _v), d in domain_map.items():
            domain_key[d] = k
        for i in range(n_real):
            for k in range(K):
                d = node_domains[i, k]
                if d >= 0:
                    domain_node_count[d] += 1.0

        # ---- finalize tables ----
        Ex = _pad_dim(len(exprs_t.rows), 8)
        MV = _pad_dim(max([len(v) for _, _, v, _ in exprs_t.rows] + [1]), 4)
        ex_key = np.full(Ex, -1, np.int32)
        ex_op = np.full(Ex, -1, np.int32)
        ex_vals = np.full((Ex, MV), -1, np.int32)
        ex_num = np.zeros(Ex, np.float32)
        for i, (k, op, vals, num) in enumerate(exprs_t.rows):
            ex_key[i] = k
            ex_op[i] = op
            ex_vals[i, : len(vals)] = vals
            ex_num[i] = num

        Rq = _pad_dim(len(reqs_t.rows), 4)
        MT = _pad_dim(max([len(r) for r in reqs_t.rows] + [1]), 2)
        ME = _pad_dim(
            max([len(t) for r in reqs_t.rows for t in r] + [1]), 2
        )
        rq_exprs = np.full((Rq, MT, ME), -1, np.int32)
        for i, terms in enumerate(reqs_t.rows):
            for j, t in enumerate(terms):
                rq_exprs[i, j, : len(t)] = t

        Pf = _pad_dim(len(prefs_t.rows), 2)
        MPT = _pad_dim(max([len(r) for r in prefs_t.rows] + [1]), 2)
        MPE = _pad_dim(
            max([len(t) for r in prefs_t.rows for (t, _w) in r] + [1]), 2
        )
        pf_exprs = np.full((Pf, MPT, MPE), -1, np.int32)
        pf_weight = np.zeros((Pf, MPT), np.float32)
        for i, row in enumerate(prefs_t.rows):
            for j, (exprs, w) in enumerate(row):
                pf_exprs[i, j, : len(exprs)] = exprs
                pf_weight[i, j] = w

        Tl = _pad_dim(len(tols_t.rows), 2)
        MTl = _pad_dim(max([len(r) for r in tols_t.rows] + [1]), 4)
        tl_key = np.full((Tl, MTl), 0, np.int32)
        tl_op = np.zeros((Tl, MTl), np.int32)
        tl_val = np.zeros((Tl, MTl), np.int32)
        tl_effect = np.zeros((Tl, MTl), np.int32)
        tl_valid = np.zeros((Tl, MTl), bool)
        for i, row in enumerate(tols_t.rows):
            for j, (k, op, v, e) in enumerate(row):
                tl_key[i, j] = k
                tl_op[i, j] = op
                tl_val[i, j] = v
                tl_effect[i, j] = e
                tl_valid[i, j] = True

        Ts = _pad_dim(len(taints_t.rows), 2)
        MTt = _pad_dim(max([len(r) for r in taints_t.rows] + [1]), 4)
        ts_key = np.full((Ts, MTt), -1, np.int32)
        ts_val = np.zeros((Ts, MTt), np.int32)
        ts_effect = np.zeros((Ts, MTt), np.int32)
        ts_valid = np.zeros((Ts, MTt), bool)
        for i, row in enumerate(taints_t.rows):
            for j, (k, v, e) in enumerate(row):
                ts_key[i, j] = k
                ts_val[i, j] = v
                ts_effect[i, j] = e
                ts_valid[i, j] = True

        Ssel = _pad_dim(len(sels_t.rows), 4)
        MSE = _pad_dim(max([len(r) for r in sels_t.rows] + [1]), 4)
        sel_exprs = np.full((Ssel, MSE), -1, np.int32)
        for i, row in enumerate(sels_t.rows):
            sel_exprs[i, : len(row)] = row

        I = max(len(image_ids), 1)
        Is = _pad_dim(len(imgsets_t.rows), 2)
        imgset_sizes = np.zeros((Is, I), np.float32)
        for i, row in enumerate(imgsets_t.rows):
            for ii in row:
                imgset_sizes[i, ii] = image_sizes.get(ii, 0.0)
        node_images = np.zeros((N, I), bool)
        for i, imgs in enumerate(node_image_sets):
            for ii in imgs:
                node_images[i, ii] = True

        G = max(len(group_ids), 1)
        group_min_member = np.zeros(G, np.int32)
        for name, gi in group_ids.items():
            group_min_member[gi] = declared.get(name, 0)
        group_existing_count = np.zeros(G, np.int32)
        for g in exist_group[:e_real]:
            if g >= 0:
                group_existing_count[g] += 1

        # Pod ordering rank: priority desc, then creation ts asc, then index.
        order_key = sorted(
            range(p_real),
            key=lambda i: (-pending[i].spec.priority,
                           pending[i].metadata.creation_timestamp, i),
        )
        pod_order = np.full(P, np.iinfo(np.int32).max, np.int32)
        for rank, i in enumerate(order_key):
            pod_order[i] = rank

        return ClusterSnapshot(
            resource_names=tuple(rn),
            num_nodes=np.asarray(n_real, np.int32),
            num_pending=np.asarray(p_real, np.int32),
            num_existing=np.asarray(e_real, np.int32),
            num_domains=np.asarray(len(domain_map), np.int32),
            topology_keys=tuple(topo_keys),
            node_allocatable=node_alloc,
            node_requested=node_requested,
            node_unschedulable=node_unsched,
            node_taintset=node_taintset,
            node_label_keys=nl_keys,
            node_label_vals=nl_vals,
            node_label_num=nl_num,
            node_domains=node_domains,
            node_images=node_images,
            node_used_ports=node_used_ports,
            node_valid=node_valid,
            ex_key=ex_key,
            ex_op=ex_op,
            ex_vals=ex_vals,
            ex_num=ex_num,
            rq_exprs=rq_exprs,
            pf_exprs=pf_exprs,
            pf_weight=pf_weight,
            tl_key=tl_key,
            tl_op=tl_op,
            tl_val=tl_val,
            tl_effect=tl_effect,
            tl_valid=tl_valid,
            ts_key=ts_key,
            ts_val=ts_val,
            ts_effect=ts_effect,
            ts_valid=ts_valid,
            sel_exprs=sel_exprs,
            pod_requested=pod_req,
            pod_priority=pod_prio,
            pod_order=pod_order,
            pod_node_name=pod_node_name,
            pod_nominated=pod_nominated,
            pod_req_id=pod_req_id,
            pod_sel_req_id=pod_sel_req_id,
            pod_pref_id=pod_pref_id,
            pod_tolset=pod_tolset,
            pod_label_keys=pl_keys,
            pod_label_vals=pl_vals,
            pod_ports=pod_ports,
            pod_port_ids=pod_port_ids,
            num_distinct_ports=_pad_dim(len(port_ids_t), 4),
            has_inter_pod_affinity=bool(
                (pod_aff_terms >= 0).any()
                or (pod_anti_terms >= 0).any()
                or (pod_pref_aff >= 0).any()
                or (exist_anti >= 0).any()
                or (exist_pref >= 0).any()
            ),
            has_topology_spread=bool((pod_tsc >= 0).any()),
            pod_aff_terms=pod_aff_terms,
            pod_anti_terms=pod_anti_terms,
            pod_pref_aff=pod_pref_aff,
            pod_pref_aff_w=pod_pref_aff_w,
            pod_tsc=pod_tsc,
            pod_tsc_skew=pod_tsc_skew,
            pod_group=pod_group_arr,
            pod_imageset=pod_imageset,
            pod_can_preempt=pod_can_preempt,
            pod_valid=pod_valid,
            group_min_member=group_min_member,
            group_existing_count=group_existing_count,
            imgset_sizes=imgset_sizes,
            exist_node=exist_node,
            exist_priority=exist_prio,
            exist_requested=exist_req,
            exist_label_keys=el_keys,
            exist_label_vals=el_vals,
            exist_anti_terms=exist_anti,
            exist_pref_aff=exist_pref,
            exist_pref_aff_w=exist_pref_w,
            exist_valid=exist_valid,
            node_pods=node_pods,
            domain_key=domain_key,
            domain_node_count=domain_node_count,
        )


def _aff(p: Pod) -> Affinity:
    return p.spec.affinity or Affinity()


def _pref_count(p: Pod) -> int:
    a = _aff(p)
    n = 0
    if a.pod_affinity:
        n += len(a.pod_affinity.preferred)
    if a.pod_anti_affinity:
        n += len(a.pod_anti_affinity.preferred)
    return n
