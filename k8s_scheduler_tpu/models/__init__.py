from .api import (  # noqa: F401
    Affinity,
    Container,
    ContainerImage,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodGroup,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from .builders import MakeNode, MakePod  # noqa: F401
from .encoding import ClusterSnapshot, SnapshotEncoder  # noqa: F401
