"""Fluent pod/node builders for tests and synthetic-cluster generation.

Mirrors the upstream testing wrappers (`MakePod().Name(x).Req(...).Obj()`
style builders in kube-scheduler's `testing` package — expected reference
location [UNVERIFIED], mount empty; SURVEY.md §4 "wrapper builders").
"""

from __future__ import annotations

from typing import Any, Mapping

from . import api
from .api import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)


class MakePod:
    def __init__(self, name: str = "pod", namespace: str = "default"):
        self._pod = Pod(metadata=ObjectMeta(name=name, namespace=namespace), spec=PodSpec())

    def uid(self, uid: str) -> "MakePod":
        self._pod.metadata.uid = uid
        return self

    def labels(self, labels: Mapping[str, str]) -> "MakePod":
        self._pod.metadata.labels.update(labels)
        return self

    def req(self, requests: Mapping[str, Any], image: str = "") -> "MakePod":
        """Add a container with the given resource requests."""
        n = len(self._pod.spec.containers)
        self._pod.spec.containers += (
            Container.make(f"c{n}", image, requests),
        )
        return self

    def image(self, image: str, requests: Mapping[str, Any] | None = None) -> "MakePod":
        return self.req(requests or {}, image=image)

    def host_port(self, port: int, protocol: str = "TCP") -> "MakePod":
        if not self._pod.spec.containers:
            self.req({})
        cs = list(self._pod.spec.containers)
        cs[-1].ports += (ContainerPort(container_port=port, host_port=port, protocol=protocol),)
        self._pod.spec.containers = tuple(cs)
        return self

    def priority(self, p: int) -> "MakePod":
        self._pod.spec.priority = p
        return self

    def preemption_policy(self, policy: str) -> "MakePod":
        self._pod.spec.preemption_policy = policy
        return self

    def created(self, ts: float) -> "MakePod":
        self._pod.metadata.creation_timestamp = ts
        return self

    def node(self, node_name: str) -> "MakePod":
        self._pod.spec.node_name = node_name
        return self

    def node_selector(self, sel: Mapping[str, str]) -> "MakePod":
        self._pod.spec.node_selector.update(sel)
        return self

    def _affinity(self) -> Affinity:
        if self._pod.spec.affinity is None:
            self._pod.spec.affinity = Affinity()
        return self._pod.spec.affinity

    def node_affinity_required(self, *terms: NodeSelectorTerm) -> "MakePod":
        aff = self._affinity()
        na = aff.node_affinity or NodeAffinity()
        aff.node_affinity = NodeAffinity(na.required + terms, na.preferred)
        return self

    def node_affinity_in(self, key: str, values: list[str]) -> "MakePod":
        return self.node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement(key, api.OP_IN, tuple(values)),))
        )

    def node_affinity_preferred(self, weight: int, key: str, values: list[str],
                                op: str = api.OP_IN) -> "MakePod":
        aff = self._affinity()
        na = aff.node_affinity or NodeAffinity()
        term = NodeSelectorTerm((NodeSelectorRequirement(key, op, tuple(values)),))
        aff.node_affinity = NodeAffinity(
            na.required, na.preferred + (PreferredSchedulingTerm(weight, term),)
        )
        return self

    def pod_affinity(self, topology_key: str, match_labels: Mapping[str, str],
                     anti: bool = False, weight: int = 0) -> "MakePod":
        """weight=0 → required term; weight>0 → preferred term."""
        aff = self._affinity()
        term = PodAffinityTerm(
            LabelSelector(match_labels=dict(match_labels)), topology_key
        )
        if anti:
            pa = aff.pod_anti_affinity or PodAntiAffinity()
            if weight:
                pa = PodAntiAffinity(pa.required, pa.preferred + (WeightedPodAffinityTerm(weight, term),))
            else:
                pa = PodAntiAffinity(pa.required + (term,), pa.preferred)
            aff.pod_anti_affinity = pa
        else:
            pb = aff.pod_affinity or PodAffinity()
            if weight:
                pb = PodAffinity(pb.required, pb.preferred + (WeightedPodAffinityTerm(weight, term),))
            else:
                pb = PodAffinity(pb.required + (term,), pb.preferred)
            aff.pod_affinity = pb
        return self

    def toleration(self, key: str, value: str = "", effect: str = "",
                   op: str = "Equal") -> "MakePod":
        self._pod.spec.tolerations += (Toleration(key, op, value, effect),)
        return self

    def spread(self, max_skew: int, topology_key: str,
               match_labels: Mapping[str, str],
               when_unsatisfiable: str = api.DO_NOT_SCHEDULE) -> "MakePod":
        self._pod.spec.topology_spread_constraints += (
            TopologySpreadConstraint(
                max_skew, topology_key, when_unsatisfiable,
                LabelSelector(match_labels=dict(match_labels)),
            ),
        )
        return self

    def volume(self, claim_name: str) -> "MakePod":
        self._pod.spec.volumes = self._pod.spec.volumes + (claim_name,)
        return self

    def group(self, name: str) -> "MakePod":
        self._pod.spec.pod_group = name
        return self

    def scheduler(self, name: str) -> "MakePod":
        self._pod.spec.scheduler_name = name
        return self

    def nominated(self, node_name: str) -> "MakePod":
        self._pod.nominated_node_name = node_name
        return self

    def obj(self) -> Pod:
        return self._pod


class MakeNode:
    def __init__(self, name: str = "node"):
        self._node = Node(metadata=ObjectMeta(name=name))

    def labels(self, labels: Mapping[str, str]) -> "MakeNode":
        self._node.metadata.labels.update(labels)
        return self

    def capacity(self, allocatable: Mapping[str, Any]) -> "MakeNode":
        alloc = dict(self._node.status.allocatable)
        alloc.update(api._req_to_internal(allocatable))
        alloc.setdefault(api.PODS, 110.0)  # upstream default max-pods
        self._node.status.allocatable = alloc
        return self

    def taint(self, key: str, value: str = "", effect: str = api.NO_SCHEDULE) -> "MakeNode":
        self._node.spec.taints += (Taint(key, value, effect),)
        return self

    def unschedulable(self, v: bool = True) -> "MakeNode":
        self._node.spec.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "MakeNode":
        self._node.status.images += (ContainerImage((name,), size_bytes),)
        return self

    def obj(self) -> Node:
        return self._node
