"""Typed object model for the scheduler's API surface.

This is the subset of the Kubernetes Pod/Node API that the scheduler family
consumes (the reference's inputs arrive as client-go informer objects; here
they arrive as these dataclasses, built from dicts/JSON by `from_dict`
constructors or over the gRPC shim).

Expected upstream shapes (reference mount empty — [UNVERIFIED], SURVEY.md
§2 C2/C4): `k8s.io/api/core/v1` types consumed by `framework/types.go`.

Conventions:
- cpu is stored in millicores, memory/storage in bytes (upstream Quantity
  semantics, normalized at parse time — see utils/quantity.py).
- `None` everywhere means "field absent", matching k8s optionality.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Mapping

from ..utils.quantity import parse_quantity

# Resource names get a fixed axis order in the encoded tensors; cpu/memory
# first because every workload has them (upstream: v1.ResourceCPU etc.).
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
DEFAULT_RESOURCES = (CPU, MEMORY, PODS, EPHEMERAL_STORAGE)

# Taint effects (v1.TaintEffect)
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Selector operators (v1.NodeSelectorOperator / metav1.LabelSelectorOperator)
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"

# TopologySpreadConstraint.whenUnsatisfiable
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


def _req_to_internal(requests: Mapping[str, Any]) -> dict[str, float]:
    out: dict[str, float] = {}
    for name, q in requests.items():
        out[name] = parse_quantity(q, as_millis=(name == CPU))
    return out


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple[str, ...] = ()


@dataclass
class NodeSelectorTerm:
    # ANDed requirements; a NodeSelector is an OR over terms.
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()
    match_fields: tuple[NodeSelectorRequirement, ...] = ()  # metadata.name only


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    # requiredDuringSchedulingIgnoredDuringExecution
    required: tuple[NodeSelectorTerm, ...] = ()
    # preferredDuringSchedulingIgnoredDuringExecution
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()

    def empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


@dataclass
class PodAffinityTerm:
    label_selector: LabelSelector
    topology_key: str
    namespaces: tuple[str, ...] = ()  # empty = pod's own namespace


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass
class PodAntiAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass
class Affinity:
    node_affinity: NodeAffinity | None = None
    pod_affinity: PodAffinity | None = None
    pod_anti_affinity: PodAntiAffinity | None = None


@dataclass
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: int | None = None


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: LabelSelector = field(default_factory=LabelSelector)


@dataclass
class ContainerPort:
    container_port: int
    host_port: int = 0  # 0 = no host port claim
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    requests: dict[str, float] = field(default_factory=dict)  # internal units
    ports: tuple[ContainerPort, ...] = ()

    @staticmethod
    def make(name: str, image: str, requests: Mapping[str, Any],
             ports: tuple[ContainerPort, ...] = ()) -> "Container":
        return Container(name, image, _req_to_internal(requests), ports)


@dataclass
class PodSpec:
    containers: tuple[Container, ...] = ()
    node_name: str = ""  # pre-bound / NodeName plugin target
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Affinity | None = None
    tolerations: tuple[Toleration, ...] = ()
    topology_spread_constraints: tuple[TopologySpreadConstraint, ...] = ()
    priority: int = 0
    priority_class_name: str = ""
    # "PreemptLowerPriority" (default) or "Never"
    preemption_policy: str = "PreemptLowerPriority"
    scheduler_name: str = "default-scheduler"
    overhead: dict[str, float] = field(default_factory=dict)
    # Gang scheduling (out-of-tree Coscheduling plugin's PodGroup label):
    pod_group: str = ""
    # PVC names this pod mounts (spec.volumes[].persistentVolumeClaim.
    # claimName) — consumed by the VolumeBinding filter
    volumes: tuple[str, ...] = ()


@dataclass
class Pod:
    metadata: ObjectMeta
    spec: PodSpec
    # status.nominatedNodeName — set by preemption, honored next cycle
    nominated_node_name: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def resource_requests(self) -> dict[str, float]:
        """Effective pod request = sum over containers (+ overhead), plus the
        implicit one-"pods"-slot request (upstream computePodResourceRequest;
        init containers take a max, not modeled yet)."""
        total: dict[str, float] = {}
        for c in self.spec.containers:
            for r, v in c.requests.items():
                total[r] = total.get(r, 0.0) + v
        for r, v in self.spec.overhead.items():
            total[r] = total.get(r, 0.0) + v
        total[PODS] = total.get(PODS, 0.0) + 1.0
        return total

    def host_ports(self) -> list[tuple[int, str, str]]:
        out = []
        for c in self.spec.containers:
            for p in c.ports:
                if p.host_port:
                    out.append((p.host_port, p.protocol, p.host_ip))
        return out

    def images(self) -> list[str]:
        return [c.image for c in self.spec.containers if c.image]


@dataclass
class ContainerImage:
    names: tuple[str, ...]
    size_bytes: int = 0


@dataclass
class NodeStatus:
    allocatable: dict[str, float] = field(default_factory=dict)  # internal units
    images: tuple[ContainerImage, ...] = ()


@dataclass
class NodeSpec:
    taints: tuple[Taint, ...] = ()
    unschedulable: bool = False


@dataclass
class Node:
    metadata: ObjectMeta
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PodGroup:
    """Gang-scheduling group (scheduler-plugins Coscheduling PodGroup CRD
    analogue): schedule min_member members all-or-nothing."""

    name: str
    min_member: int


@dataclass
class PodDisruptionBudget:
    """PDB, as preemption consumes it: how many voluntary disruptions the
    selected pods can absorb right now (status.disruptionsAllowed)."""

    name: str
    namespace: str = "default"
    selector: "LabelSelector" = field(default_factory=lambda: LabelSelector())
    disruptions_allowed: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# Volumes (VolumeBinding filter inputs)
# ---------------------------------------------------------------------------

VOLUME_BINDING_IMMEDIATE = "Immediate"
VOLUME_BINDING_WAIT = "WaitForFirstConsumer"


@dataclass
class StorageClass:
    name: str
    volume_binding_mode: str = VOLUME_BINDING_IMMEDIATE
    # dynamic provisioning available (provisioner != no-provisioner)
    provisioner: bool = True
    # allowedTopologies, compiled like node-affinity terms (OR of terms)
    allowed_topologies: tuple[NodeSelectorTerm, ...] = ()


@dataclass
class PersistentVolume:
    name: str
    capacity: float = 0.0  # storage bytes
    storage_class: str = ""
    # spec.nodeAffinity.required: OR of terms restricting usable nodes
    node_affinity: tuple[NodeSelectorTerm, ...] = ()
    # claimRef: bound to this PVC ("namespace/name"); "" = available
    claim_ref: str = ""


@dataclass
class PersistentVolumeClaim:
    name: str
    namespace: str = "default"
    storage_class: str = ""
    request: float = 0.0  # requested storage bytes
    volume_name: str = ""  # bound PV ("" = unbound)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# dict (JSON) constructors — the wire format of the gRPC shim and test
# fixtures. Accepts the k8s-ish camelCase shapes.
# ---------------------------------------------------------------------------


def _selector_req_from_dict(d: Mapping[str, Any]) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(
        key=d["key"], operator=d["operator"], values=tuple(d.get("values", ()))
    )


def _term_from_dict(d: Mapping[str, Any]) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=tuple(
            _selector_req_from_dict(e) for e in d.get("matchExpressions", ())
        ),
        match_fields=tuple(
            _selector_req_from_dict(e) for e in d.get("matchFields", ())
        ),
    )


def _label_selector_from_dict(d: Mapping[str, Any] | None) -> LabelSelector:
    if not d:
        return LabelSelector()
    return LabelSelector(
        match_labels=dict(d.get("matchLabels", {})),
        match_expressions=tuple(
            _selector_req_from_dict(e) for e in d.get("matchExpressions", ())
        ),
    )


def _pod_affinity_term_from_dict(d: Mapping[str, Any]) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_label_selector_from_dict(d.get("labelSelector")),
        topology_key=d.get("topologyKey", ""),
        namespaces=tuple(d.get("namespaces", ())),
    )


def affinity_from_dict(d: Mapping[str, Any] | None) -> Affinity | None:
    if not d:
        return None
    na = None
    if "nodeAffinity" in d:
        nd = d["nodeAffinity"]
        req = nd.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
        na = NodeAffinity(
            required=tuple(
                _term_from_dict(t) for t in req.get("nodeSelectorTerms", ())
            ),
            preferred=tuple(
                PreferredSchedulingTerm(p["weight"], _term_from_dict(p["preference"]))
                for p in nd.get(
                    "preferredDuringSchedulingIgnoredDuringExecution", ()
                )
            ),
        )
    pa = pan = None
    for key, cls in (("podAffinity", PodAffinity), ("podAntiAffinity", PodAntiAffinity)):
        if key in d:
            pd = d[key]
            obj = cls(
                required=tuple(
                    _pod_affinity_term_from_dict(t)
                    for t in pd.get(
                        "requiredDuringSchedulingIgnoredDuringExecution", ()
                    )
                ),
                preferred=tuple(
                    WeightedPodAffinityTerm(
                        w["weight"],
                        _pod_affinity_term_from_dict(w["podAffinityTerm"]),
                    )
                    for w in pd.get(
                        "preferredDuringSchedulingIgnoredDuringExecution", ()
                    )
                ),
            )
            if key == "podAffinity":
                pa = obj
            else:
                pan = obj
    return Affinity(node_affinity=na, pod_affinity=pa, pod_anti_affinity=pan)


def pod_from_dict(d: Mapping[str, Any]) -> Pod:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    containers = []
    for c in spec.get("containers", ()):
        ports = tuple(
            ContainerPort(
                container_port=p.get("containerPort", 0),
                host_port=p.get("hostPort", 0),
                protocol=p.get("protocol", "TCP"),
                host_ip=p.get("hostIP", ""),
            )
            for p in c.get("ports", ())
        )
        containers.append(
            Container.make(
                c.get("name", "main"),
                c.get("image", ""),
                (c.get("resources", {}) or {}).get("requests", {}),
                ports,
            )
        )
    tolerations = tuple(
        Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Equal"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
            toleration_seconds=t.get("tolerationSeconds"),
        )
        for t in spec.get("tolerations", ())
    )
    tsc = tuple(
        TopologySpreadConstraint(
            max_skew=t["maxSkew"],
            topology_key=t["topologyKey"],
            when_unsatisfiable=t["whenUnsatisfiable"],
            label_selector=_label_selector_from_dict(t.get("labelSelector")),
        )
        for t in spec.get("topologySpreadConstraints", ())
    )
    return Pod(
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            labels=dict(meta.get("labels", {})),
            annotations=dict(meta.get("annotations", {})),
            creation_timestamp=meta.get("creationTimestamp", 0.0),
        ),
        spec=PodSpec(
            containers=tuple(containers),
            node_name=spec.get("nodeName", ""),
            node_selector=dict(spec.get("nodeSelector", {})),
            affinity=affinity_from_dict(spec.get("affinity")),
            tolerations=tolerations,
            topology_spread_constraints=tsc,
            priority=spec.get("priority", 0),
            priority_class_name=spec.get("priorityClassName", ""),
            preemption_policy=spec.get("preemptionPolicy", "PreemptLowerPriority"),
            scheduler_name=spec.get("schedulerName", "default-scheduler"),
            overhead=_req_to_internal(spec.get("overhead", {})),
            pod_group=spec.get("podGroup", "")
            or meta.get("labels", {}).get("pod-group.scheduling.sigs.k8s.io", ""),
        ),
        nominated_node_name=d.get("status", {}).get("nominatedNodeName", ""),
    )


def node_from_dict(d: Mapping[str, Any]) -> Node:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    status = d.get("status", {})
    return Node(
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            labels=dict(meta.get("labels", {})),
            creation_timestamp=meta.get("creationTimestamp", 0.0),
        ),
        spec=NodeSpec(
            taints=tuple(
                Taint(t["key"], t.get("value", ""), t.get("effect", NO_SCHEDULE))
                for t in spec.get("taints", ())
            ),
            unschedulable=bool(spec.get("unschedulable", False)),
        ),
        status=NodeStatus(
            allocatable=_req_to_internal(status.get("allocatable", {})),
            images=tuple(
                ContainerImage(tuple(i.get("names", ())), i.get("sizeBytes", 0))
                for i in status.get("images", ())
            ),
        ),
    )


def pod_to_dict(p: Pod) -> dict[str, Any]:
    """Minimal inverse of pod_from_dict (wire round-trips in tests/shim)."""
    return dataclasses.asdict(p)
