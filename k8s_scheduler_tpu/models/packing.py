"""Snapshot packing: ship the whole ClusterSnapshot to the device as TWO
buffers instead of ~80.

Motivation (measured on the tunneled TPU rig): executing a program whose
input buffers have never been used costs a large per-buffer first-use
overhead — a cycle fed ~80 freshly-assembled numpy arrays spent 300-500ms
more than the same program on warm buffers, even though the total payload
is only ~8MB. Packing all numeric arrays into one u32 word buffer and all
boolean arrays into one u8 buffer makes that per-cycle overhead ~2
buffers' worth; the jitted program unpacks with STATIC slices + bitcasts
that XLA fuses into the consumers.

The PackSpec is static per padded-shape/dictionary-size regime: it pins
every field's (dtype, shape, offset) plus the snapshot's non-array
attributes (python ints/bools/tuples — trace-time constants). When the
encoder's grow-only dimensions change, the spec changes and the packed
program recompiles — same regime-bucketing contract as the unpacked path.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import ClusterSnapshot


@dataclasses.dataclass(frozen=True)
class PackSpec:
    # (name, dtype_str, shape, word_offset) for u32-packed numeric fields
    words: tuple[tuple[str, str, tuple[int, ...], int], ...]
    # (name, shape, byte_offset) for bool fields in the u8 buffer
    bools: tuple[tuple[str, tuple[int, ...], int], ...]
    n_words: int
    n_bytes: int
    # non-array ClusterSnapshot attributes, captured as constants
    aux: tuple[tuple[str, Any], ...]

    def key(self):
        return (self.words, self.bools, self.aux)


# The pad dimensions whose mid-serving flips force a full recompile
# (and have wedged the rig backend — PERF.md "fold-mode rig wedge"):
# name -> (snapshot field, axis) to read the padded size from. P/N are
# the pod/node pads, E the existing-pod pad, MPN the per-node victim
# depth, MA the per-pod (anti-)affinity term pad, MC the per-pod
# topology-spread-constraint pad. core/observe.py diffs consecutive
# signatures to attribute WHICH dimension flipped on a recompile.
SIGNATURE_DIMS = (
    ("P", "pod_valid", 0),
    ("N", "node_valid", 0),
    ("E", "exist_valid", 0),
    ("MPN", "node_pods", 1),
    ("MA", "pod_aff_terms", 1),
    ("MC", "pod_tsc", 1),
)


def shape_signature(spec: PackSpec) -> tuple[tuple[str, int], ...]:
    """Named pad-regime signature of a PackSpec: a stable tuple of
    (dimension, padded size) pairs. Two cycles whose specs differ have
    (at least) one differing signature entry whenever the flip is one
    of the named regime dimensions; dictionary-growth recompiles (spec
    key change with an identical signature) are still visible to the
    observer via the regime_flip count."""
    shapes: dict[str, tuple[int, ...]] = {
        name: shape for name, _dt, shape, _off in spec.words
    }
    shapes.update({name: shape for name, shape, _off in spec.bools})
    out = []
    for dim, field, axis in SIGNATURE_DIMS:
        shp = shapes.get(field)
        if shp is not None and len(shp) > axis:
            out.append((dim, int(shp[axis])))
    return tuple(out)


def respec(spec: PackSpec, dims: "dict[str, int]") -> PackSpec | None:
    """Rewrite a PackSpec's P and/or N pad dimension to an ADJACENT
    regime size without re-encoding — the speculative-precompilation
    path (core/compile_cache.py) predicts the regime churn is about to
    cross a pad-bucket boundary and needs the neighbouring regime's
    exact spec to pre-build its programs off the serve thread.

    The rewrite leans on the encoder's naming contract, verified
    empirically by tests/test_compile_cache.py against real encodes:
    every `pod_*` array field carries P on axis 0, every `node_*` array
    field carries N on axis 0, and no other axis of any field scales
    with P or N — with ONE exception, the extender verdict planes
    (`pod_extender_mask`/`pod_extender_score` are [P, N]). Those are
    array fields only when `has_extender`, a workload speculation does
    not cover, so their presence refuses the rewrite (returns None)
    rather than risking a mis-shaped program. Offsets are recomputed
    from scratch; aux is untouched (P/N are array-derived, not aux)."""
    prefixes = {"P": "pod_", "N": "node_"}
    if not dims or any(d not in prefixes for d in dims):
        return None
    names = {n for n, _dt, _sh, _off in spec.words}
    names.update(n for n, _sh, _off in spec.bools)
    if names & {"pod_extender_mask", "pod_extender_score"}:
        return None  # [P, N] planes: axis-0-only rewrite would be wrong
    old_sizes = dict(shape_signature(spec))

    def rewrite(name: str, shape: tuple) -> tuple:
        for dim, new in dims.items():
            if name.startswith(prefixes[dim]):
                if not shape or shape[0] != old_sizes.get(dim):
                    return shape  # scalar/odd field: leave untouched
                return (int(new),) + tuple(shape[1:])
        return shape

    words = []
    bools = []
    wo = 0
    bo = 0
    for name, dt, shape, _off in spec.words:
        shape = rewrite(name, shape)
        words.append((name, dt, shape, wo))
        wo += int(np.prod(shape, dtype=np.int64)) if shape else 1
    for name, shape, _off in spec.bools:
        shape = rewrite(name, shape)
        bools.append((name, shape, bo))
        bo += int(np.prod(shape, dtype=np.int64)) if shape else 1
    out = PackSpec(
        words=tuple(words),
        bools=tuple(bools),
        n_words=wo,
        n_bytes=max(bo, 1),
        aux=spec.aux,
    )
    got = dict(shape_signature(out))
    for dim, new in dims.items():
        if got.get(dim) != int(new):
            return None  # the naming contract did not hold; refuse
    return out


def make_spec(snap: ClusterSnapshot) -> PackSpec:
    words = []
    bools = []
    aux = []
    wo = 0
    bo = 0
    for f in dataclasses.fields(snap):
        v = getattr(snap, f.name)
        if isinstance(v, np.ndarray) or hasattr(v, "dtype"):
            a = np.asarray(v)
            if a.dtype == np.bool_:
                bools.append((f.name, tuple(a.shape), bo))
                bo += int(a.size)
            elif a.dtype in (np.int32, np.float32):
                words.append((f.name, a.dtype.name, tuple(a.shape), wo))
                wo += int(a.size)
            else:
                raise TypeError(
                    f"unpackable dtype {a.dtype} for field {f.name}"
                )
        else:
            aux.append((f.name, v))
    return PackSpec(
        words=tuple(words),
        bools=tuple(bools),
        n_words=wo,
        n_bytes=max(bo, 1),
        aux=tuple(aux),
    )


def pack(snap: ClusterSnapshot, spec: PackSpec):
    """-> (u32 [n_words], u8 [n_bytes]) numpy buffers."""
    wbuf = np.empty(spec.n_words, np.uint32)
    bbuf = np.zeros(spec.n_bytes, np.uint8)
    for name, _dt, _shape, off in spec.words:
        a = np.ascontiguousarray(np.asarray(getattr(snap, name)))
        wbuf[off:off + a.size] = a.view(np.uint32).ravel()
    for name, _shape, off in spec.bools:
        a = np.ascontiguousarray(np.asarray(getattr(snap, name)))
        bbuf[off:off + a.size] = a.view(np.uint8).ravel()
    return wbuf, bbuf


def unpack(wbuf, bbuf, spec: PackSpec) -> ClusterSnapshot:
    """Rebuild the snapshot inside a trace from the packed buffers."""
    kw = dict(spec.aux)
    for name, dt, shape, off in spec.words:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        sl = jax.lax.slice(wbuf, (off,), (off + n,))
        arr = jax.lax.bitcast_convert_type(
            sl, jnp.int32 if dt == "int32" else jnp.float32
        )
        kw[name] = arr.reshape(shape)
    for name, shape, off in spec.bools:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        sl = jax.lax.slice(bbuf, (off,), (off + n,))
        kw[name] = (sl != 0).reshape(shape)
    return ClusterSnapshot(**kw)
