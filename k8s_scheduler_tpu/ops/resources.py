"""Resource-fit mask and resource-based score kernels.

TPU-native re-design of the reference's `NodeResourcesFit` Filter plugin and
`LeastRequested` / `NodeResourcesBalancedAllocation` Score plugins (expected
upstream locations `framework/plugins/noderesources/*` or
`algorithm/{predicates,priorities}` — [UNVERIFIED], reference mount empty;
SURVEY.md §2 C7/C8): instead of a per-pod, per-node Go loop over 16
goroutines, the whole pods x nodes matrix is computed in one fused XLA
program (the MXU/VPU does the batching; no Parallelizer needed).

Numerics: quantities are float32 (cpu in millicores, memory in bytes).
Upstream uses int64; float32 ulp at 16Gi is 1KiB, far below scheduling
granularity, and all comparisons use a relative epsilon so aggregation
rounding never flips a feasibility bit.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_NODE_SCORE = 100.0
_REL_EPS = 1e-5


def fit_mask(
    pod_requested: jnp.ndarray,  # f32 [P, R]
    node_allocatable: jnp.ndarray,  # f32 [N, R]
    node_requested: jnp.ndarray,  # f32 [N, R]
) -> jnp.ndarray:  # bool [P, N]
    """NodeResourcesFit: pod fits iff for every resource
    requested_pod + requested_node <= allocatable."""
    free = node_allocatable - node_requested  # [N, R]
    slack = _REL_EPS * node_allocatable + _REL_EPS
    return jnp.all(
        pod_requested[:, None, :] <= free[None, :, :] + slack[None, :, :], axis=-1
    )


def fit_mask_single(
    pod_requested: jnp.ndarray,  # f32 [R]
    node_allocatable: jnp.ndarray,  # f32 [N, R]
    node_requested: jnp.ndarray,  # f32 [N, R]
) -> jnp.ndarray:  # bool [N]
    free = node_allocatable - node_requested
    slack = _REL_EPS * node_allocatable + _REL_EPS
    return jnp.all(pod_requested[None, :] <= free + slack, axis=-1)


def _used_fraction(
    pod_requested: jnp.ndarray,  # f32 [R] or [P, R] broadcastable
    node_allocatable: jnp.ndarray,  # f32 [N, R]
    node_requested: jnp.ndarray,  # f32 [N, R]
) -> jnp.ndarray:
    """(node_requested + pod) / allocatable per resource, 1.0 where
    allocatable is 0 (a zero-capacity resource is fully used)."""
    after = node_requested + pod_requested
    return jnp.where(node_allocatable > 0, after / jnp.maximum(node_allocatable, 1e-9), 1.0)


def least_requested_score(
    pod_requested: jnp.ndarray,  # f32 [R] (single pod) or [P, 1, R]
    node_allocatable: jnp.ndarray,  # f32 [N, R]
    node_requested: jnp.ndarray,  # f32 [N, R]
    resource_weights: jnp.ndarray,  # f32 [R] (0 excludes a resource)
) -> jnp.ndarray:  # f32 [N] or [P, N]
    """LeastRequested: mean over weighted resources of
    (allocatable - requested_after) / allocatable * 100.

    Matches upstream leastResourceScorer: per-resource score
    ((capacity - requested) * MaxNodeScore / capacity), combined as a
    weight-weighted average. cpu/memory weight 1 by default."""
    frac = _used_fraction(pod_requested, node_allocatable, node_requested)
    per_res = (1.0 - jnp.clip(frac, 0.0, 1.0)) * MAX_NODE_SCORE
    wsum = jnp.maximum(jnp.sum(resource_weights), 1e-9)
    return jnp.sum(per_res * resource_weights, axis=-1) / wsum


def balanced_allocation_score(
    pod_requested: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    node_requested: jnp.ndarray,
    resource_weights: jnp.ndarray,  # f32 [R] — which resources participate
) -> jnp.ndarray:
    """NodeResourcesBalancedAllocation: (1 - std(fractions)) * 100 over the
    participating resources (upstream balancedResourceScorer, current era:
    standard deviation over resource usage fractions)."""
    frac = jnp.clip(
        _used_fraction(pod_requested, node_allocatable, node_requested), 0.0, 1.0
    )
    w = resource_weights > 0
    n = jnp.maximum(jnp.sum(w), 1)
    mean = jnp.sum(jnp.where(w, frac, 0.0), axis=-1, keepdims=True) / n
    var = jnp.sum(jnp.where(w, (frac - mean) ** 2, 0.0), axis=-1) / n
    return (1.0 - jnp.sqrt(var)) * MAX_NODE_SCORE


def most_requested_score(
    pod_requested: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    node_requested: jnp.ndarray,
    resource_weights: jnp.ndarray,
) -> jnp.ndarray:
    """MostRequested (bin-packing variant of LeastRequested)."""
    frac = _used_fraction(pod_requested, node_allocatable, node_requested)
    per_res = jnp.clip(frac, 0.0, 1.0) * MAX_NODE_SCORE
    wsum = jnp.maximum(jnp.sum(resource_weights), 1e-9)
    return jnp.sum(per_res * resource_weights, axis=-1) / wsum
