"""Preemption as a batched what-if program (SURVEY.md §2 C9, §3.4).

The reference's `DefaultPreemption` PostFilter (expected
`framework/plugins/defaultpreemption/` or `generic_scheduler.go (preempt)`
— [UNVERIFIED], mount empty) runs, per unschedulable pod:

    findCandidates: for each node (parallel goroutines):
        SelectVictimsOnNode: dry-run remove lower-priority pods, re-run
        Filter until the pod fits; re-add highest-priority victims back
        while it still fits (minimize victims)
    pickOneNodeForPreemption: min highest-victim-priority, then min
        priority sum, then fewest victims, then node order
    evict victims, set pod.Status.NominatedNodeName

The TPU-native design exploits the encoder's `node_pods` table: per node,
existing-pod indices sorted ascending by priority, so every candidate
victim set is a PREFIX of that list and the whole
remove/re-add-highest-first minimization collapses to "find the smallest
prefix k whose freed resources make the pod fit" — one cumulative sum plus
a first-true search, vectorized over all nodes at once. Preemptor claims
resolve in two phases: a BATCHED PREFILTER evaluates every budgeted
candidate against the pristine post-cycle state in one [C, N, MPN] pass
and drops those with no feasible preemption node anywhere — exact,
because contention state (`k_claimed` victims already spoken for per
node, `nominated_req` resources nominated pods will consume, spent PDB
budgets) only ever SHRINKS feasibility; then a short `lax.scan` over the
surviving contenders (typically ~the preemptor count, capped at
`scan_budget`)
serializes claims in priority-rank order exactly the way the reference's
one-pod-per-ScheduleOne loop does, so two preemptors never count the
same freed capacity. (A full-budget 256-step scan cost ~50ms on TPU —
one latency-bound step per candidate, mostly no-ops.)

PodDisruptionBudgets: a victim protected by a PDB whose remaining budget
(disruptionsAllowed minus victims already claimed THIS cycle) is exhausted
is evicted only as a LAST RESORT: the per-prefix violation count is the
FIRST node-choice key (upstream pickOneNodeForPreemption criterion #1),
so a zero-violation node always wins, and claimed victims decrement
their PDBs' budgets in the scan carry. Residual vs upstream (PARITY #4):
within one node the victim set stays a priority-ascending PREFIX, while
upstream's two-pass re-add prefers KEEPING a protected pod over an
unprotected higher-priority one — the pod places either way; the victim
identity can differ in mixed protected/unprotected prefixes.

Tie-breaks mirror upstream pickOneNodeForPreemption: min highest-victim
priority, min victim priority sum, min victim count, then LATEST start
time of the highest victim (prefer evicting younger pods), then lowest
node index.

Victim removal relaxes NON-RESOURCE constraints too (upstream re-runs all
filters with victims removed; SURVEY.md §3.4): per candidate (pod, node,
prefix k) the scan phase checks, against the FINAL post-cycle state with
the prefix's victims subtracted —
  - the pod's required anti-affinity (count in the node's key-domain
    minus evicted matching victims must reach zero),
  - the pod's required affinity (must still have a matching pod left, or
    bootstrap on itself),
  - symmetric anti-affinity (every evictable OWNER of an anti term
    matching the pod must be inside the prefix),
  - DoNotSchedule topology spread (post-eviction skew, with the min-over-
    domains recomputed via a min1/argmin/min2 table),
  - hostPorts (every existing holder of a wanted port must be inside the
    prefix; ports held by this cycle's winners or claimed by earlier
    nominations in this pass never clear).
`gate_rows` is accordingly the PURE STATIC candidate gate, computed on
the budgeted candidate view and excluding NodePorts (see
core.cycle._preemption_gate_rows). Remaining deviations: victims are
priority-order PREFIXES per node (upstream's remove/re-add minimization
is prefix-shaped too, except that it can skip PDB-protected pods — see
the PARITY #4 residual above); and within one batch pass, earlier
candidates' victims are
reflected in capacity (k_claimed / nominated_req) but not in the
affinity/spread count tables later candidates read — stale counts are
conservative for anti (never evict where upstream would not) and at
worst waste a nomination elsewhere, which the next cycle's feasibility
check heals (upstream nominates one pod per ScheduleOne iteration and
re-lists, so the same information lag exists across its cycles).
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp

from ..models import encoding as enc
from . import argsel
from . import interpod as interpod_ops

# Production per-cycle latency budgets (the DefaultPreemption plugin's
# defaults; the differential soak imports these so oracle-side truncation
# semantics can never drift from what the kernel actually runs).
DEFAULT_BUDGET = 256
DEFAULT_SCAN_BUDGET = 64

_REL_EPS = 1e-5
_BIG_I32 = jnp.int32(2**31 - 1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PreemptionResult:
    nominated: jnp.ndarray  # i32 [P] node nominated by preemption (-1 none)
    victims: jnp.ndarray  # bool [E] existing pods to evict
    num_preemptors: jnp.ndarray  # i32 [] pods that got a nomination


def run_preemption(
    ctx,
    *,
    assignment: jnp.ndarray,  # i32 [P] from the commit scan (-1 = unsched)
    node_requested: jnp.ndarray,  # f32 [N, R] post-cycle running requests
    gate_rows,  # callable ids i32 [C] -> bool [C, N]: pure-static
    # candidate gate (what eviction can never change), minus NodePorts
    excluded: jnp.ndarray | None = None,  # bool [P] never preempt (e.g.
    # gang-dropped members: they fit without eviction, their group is what
    # failed — upstream never runs PostFilter for Permit rejections)
    budget: int = DEFAULT_BUDGET,  # max preemptor candidates PREFILTERED per cycle:
    # phase 1 evaluates the `budget` lowest-rank unschedulable pods in one
    # batched pass (bounds the [C, N, MPN] table); candidates beyond it
    # stay queued and get their attempt next cycle
    scan_budget: int = DEFAULT_SCAN_BUDGET,  # max NOMINATIONS per cycle: phase 2 scans the
    # `scan_budget` lowest-rank prefilter survivors sequentially (one
    # latency-bound lax.scan step each, ~0.2ms); survivors beyond it defer
    # to the next cycle — upstream nominates ONE pod per ScheduleOne
    # iteration, so 64 per cycle is still generous
) -> PreemptionResult:
    snap = ctx.snap
    P, N = snap.P, snap.N
    E = snap.E
    MPN = snap.node_pods.shape[1]
    K = snap.node_domains.shape[1]

    # ---- final-state affinity/spread tables (what-if baselines) ----
    use_state = snap.has_inter_pod_affinity or snap.has_topology_spread
    if use_state:
        mp = ctx.matched_pending  # [S, P]
        me = ctx.matched_existing  # [S, E]
        state0 = ctx.initial_affinity_state()
        placed = snap.pod_valid & (assignment >= 0)
        node_of_placed = jnp.where(placed, assignment, 0)
        state_f = interpod_ops.affinity_update_batched(
            snap, state0, mp, placed, node_of_placed
        )
        counts_f = state_f.counts  # [S, D]
        total_f = state_f.total  # [S]
        S_, D_ = counts_f.shape
    else:
        placed = snap.pod_valid & (assignment >= 0)
        node_of_placed = jnp.where(placed, assignment, 0)
    if snap.has_inter_pod_affinity:
        anti_cnt_sd = interpod_ops.anti_owner_counts(snap, assignment)
    if snap.has_topology_spread:
        sp_min1, sp_amin, sp_min2 = interpod_ops.spread_min2(
            snap, counts_f
        )
    MA = snap.pod_anti_terms.shape[1]
    MC = snap.pod_tsc.shape[1]
    Q = snap.num_distinct_ports

    # ---- per-node victim tables (shared across all preemptors) ----
    vict_valid = snap.node_pods >= 0  # [N, MPN]
    safe_idx = jnp.clip(snap.node_pods, 0, E - 1)
    vict_prio = jnp.where(
        vict_valid, snap.exist_priority[safe_idx], _BIG_I32
    )  # [N, MPN]
    vict_req = jnp.where(
        vict_valid[:, :, None], snap.exist_requested[safe_idx], 0.0
    )  # [N, MPN, R]
    vict_start = jnp.where(
        vict_valid, snap.exist_start[safe_idx], 0.0
    )  # [N, MPN]
    vict_pdb = jnp.where(
        vict_valid[:, :, None], snap.exist_pdb[safe_idx], -1
    )  # [N, MPN, MB]
    GP = snap.pdb_allowed.shape[0]
    MB = vict_pdb.shape[2]
    # prefix_freed[:, k] = resources freed by evicting the first k victims
    prefix_freed = jnp.concatenate(
        [jnp.zeros_like(vict_req[:, :1]), jnp.cumsum(vict_req, axis=1)], axis=1
    )  # [N, MPN+1, R]
    prio_for_sum = jnp.where(vict_valid, vict_prio, 0)
    prefix_prio = jnp.concatenate(
        [jnp.zeros_like(prio_for_sum[:, :1]), jnp.cumsum(prio_for_sum, axis=1)],
        axis=1,
    )  # [N, MPN+1]
    ks = jnp.arange(MPN + 1, dtype=jnp.int32)[None, :]  # [1, MPN+1]
    slack = _REL_EPS * snap.node_allocatable + _REL_EPS  # [N, R]

    unschedulable = snap.pod_valid & (assignment < 0) & snap.pod_can_preempt
    if excluded is not None:
        unschedulable = unschedulable & ~excluded
    # compact to the budgeted lowest-rank candidates (rank order preserved)
    C = min(P, budget)
    cand_key = jnp.where(unschedulable, snap.pod_order, _BIG_I32)
    cand_ids = jnp.argsort(cand_key)[:C].astype(jnp.int32)
    cand_ok = unschedulable[cand_ids]  # [C]

    # ---- phase 1: batched prefilter (one pass, no contention state) ----
    # A candidate with no feasible preemption node against the PRISTINE
    # post-cycle state never gains one: contention (k_claimed,
    # nominated_req, pdb_used) only shrinks feasibility. Dropping those
    # candidates up front cuts the sequential phase from `budget` steps to
    # the handful of genuine contenders (typically ~the preemptor count).
    prio_c = snap.pod_priority[cand_ids]  # [C]
    req_c = snap.pod_requested[cand_ids]  # [C, R]
    elig_cn = jnp.sum(
        vict_valid[None, :, :] & (vict_prio[None, :, :] < prio_c[:, None, None]),
        axis=2,
    ).astype(jnp.int32)  # [C, N]
    # last-resort eviction (SURVEY §3.4 / PARITY #4): PDB-protected
    # victims no longer truncate the eligible prefix — upstream MAY evict
    # them when nothing else places the pod, preferring nodes with the
    # fewest violations (pickOneNodeForPreemption criterion #1, the
    # scan phase's first lexmin key). The prefilter therefore caps
    # prefixes by priority only.
    elig0 = elig_cn  # [C, N]
    free0 = snap.node_allocatable - node_requested + slack  # [N, R]
    fits0 = jnp.all(
        req_c[:, None, None, :]
        <= free0[None, :, None, :] + prefix_freed[None, :, :, :],
        axis=-1,
    )  # [C, N, MPN+1]
    gate_c = gate_rows(cand_ids)  # [C, N] pure-static candidate gate
    allowed0 = fits0 & (ks[None] >= 1) & (ks[None] <= elig0[:, :, None])
    feasible_any = jnp.any(
        allowed0 & gate_c[:, :, None]
        & snap.node_valid[None, :, None],
        axis=(1, 2),
    ) & cand_ok  # [C]

    C2 = min(C, scan_budget)
    key2 = jnp.where(feasible_any, snap.pod_order[cand_ids], _BIG_I32)
    sel2 = jnp.argsort(key2)[:C2].astype(jnp.int32)
    cand_ids2 = cand_ids[sel2]  # [C2] global pod ids, rank order
    live2 = feasible_any[sel2]
    gate2 = gate_c[sel2]  # [C2, N]

    # ---- batched non-resource what-if over the C2 scan candidates ----
    # Everything here is independent of the scan carry (only claimed
    # ports are not), so it runs ONCE as wide batched ops over
    # [C2, N, MPN+1] instead of per scan step — per-step arbitrary
    # gathers at [N, MPN, MA] scale are pathological on this backend.
    def nonresource_ok_batched(cids):
        """bool [C2, N, MPN+1]: for each scan candidate, node and victim
        prefix — do ALL the candidate's evictable non-resource
        constraints hold once the prefix is gone? (module docstring)"""
        C2 = cids.shape[0]
        ok = jnp.ones((C2, N, MPN + 1), bool)
        s_ids = None

        def cum3(x):  # [C2, N, MPN] f32 -> [C2, N, MPN+1] prefix sums
            c = jnp.cumsum(x, axis=2)
            return jnp.concatenate(
                [jnp.zeros_like(c[:, :, :1]), c], axis=2
            )

        if use_state:
            s_ids = jnp.arange(S_, dtype=jnp.int32)[None, :]
            cbn_f = interpod_ops.counts_by_node(snap, state_f)  # [K*S, N]
            me_vic = (
                me[:, safe_idx.reshape(-1)].reshape(S_, N, MPN)
                & vict_valid[None]
            )
            mvic_f = me_vic.astype(jnp.float32).reshape(S_, N * MPN)

            def term_m_vic(sel_c):  # [C2] -> f32 [C2, N, MPN]
                oh = (
                    jnp.clip(sel_c, 0, S_ - 1)[:, None] == s_ids
                ).astype(jnp.float32)
                return jax.lax.dot(oh, mvic_f).reshape(C2, N, MPN)

            def cnt_at(sel_c, key_c):  # [C2, N]; -1 marks "no domain"
                return interpod_ops._term_pick(
                    snap, cbn_f, sel_c, key_c, exact=True
                )

            if snap.has_inter_pod_affinity:
                for a in range(MA):
                    sel_c = snap.pod_anti_terms[cids, a, 0]  # [C2]
                    key_c = snap.pod_anti_terms[cids, a, 1]
                    cnt = cnt_at(sel_c, key_c)
                    after = cnt[:, :, None] - cum3(term_m_vic(sel_c))
                    ok &= (
                        (sel_c < 0)[:, None, None]
                        | (cnt < -0.5)[:, :, None]
                        | (after <= 0.5)
                    )
                for a in range(MA):
                    sel_c = snap.pod_aff_terms[cids, a, 0]
                    key_c = snap.pod_aff_terms[cids, a, 1]
                    scl = jnp.clip(sel_c, 0, S_ - 1)
                    cnt = cnt_at(sel_c, key_c)
                    cum = cum3(term_m_vic(sel_c))
                    after = cnt[:, :, None] - cum
                    tot_after = total_f[scl][:, None, None] - cum
                    boot = (tot_after <= 0.5) & mp[scl, cids][
                        :, None, None
                    ]
                    ok &= (
                        (sel_c < 0)[:, None, None]
                        | boot
                        | ((cnt > -0.5)[:, :, None] & (after > 0.5))
                    )
                # symmetric: every evictable OWNER of an anti term
                # matching the candidate must fall inside the prefix
                mp_c = mp[:, cids].astype(jnp.float32)  # [S, C2]
                row_d = jax.lax.dot(mp_c.T, anti_cnt_sd)  # [C2, D]
                sym_tot = jnp.zeros((C2, N), jnp.float32)
                for k in range(K):
                    dn = snap.node_domains[:, k]  # [N]
                    g = jnp.take(
                        row_d, jnp.clip(dn, 0, D_ - 1), axis=1
                    )  # [C2, N]
                    sym_tot = sym_tot + jnp.where(dn >= 0, g, 0.0)
                # per-victim owner weight table [S, N*MPN], candidate-
                # independent: victim j on node n owning term (s, key)
                # with a live domain contributes 1 at (s, n*MPN+j)
                sel_v = snap.exist_anti_terms[safe_idx][..., 0]
                key_v = snap.exist_anti_terms[safe_idx][..., 1]
                domk = snap.node_domains[
                    jnp.arange(N)[:, None, None],
                    jnp.clip(key_v, 0, K - 1),
                ]  # [N, MPN, MA]
                valid_v = (
                    (sel_v >= 0) & (domk >= 0) & vict_valid[:, :, None]
                )
                pos = jnp.broadcast_to(
                    (jnp.arange(N)[:, None] * MPN
                     + jnp.arange(MPN)[None, :])[:, :, None],
                    valid_v.shape,
                ).reshape(-1)
                own_f = (
                    jnp.zeros((S_, N * MPN), jnp.float32)
                    .at[
                        jnp.clip(sel_v, 0, S_ - 1).reshape(-1), pos
                    ]
                    .add(valid_v.reshape(-1).astype(jnp.float32))
                )
                w = jax.lax.dot(mp_c.T, own_f).reshape(C2, N, MPN)
                ok &= (sym_tot[:, :, None] - cum3(w)) <= 0.5
            if snap.has_topology_spread:
                for c in range(MC):
                    key_c = snap.pod_tsc[cids, c, 0]
                    sel_c = snap.pod_tsc[cids, c, 1]
                    when_c = snap.pod_tsc[cids, c, 2]
                    skew_c = snap.pod_tsc_skew[cids, c].astype(
                        jnp.float32
                    )
                    hard = (key_c >= 0) & (
                        when_c == enc.WHEN_DO_NOT_SCHEDULE
                    )
                    scl = jnp.clip(sel_c, 0, S_ - 1)
                    cnt = cnt_at(sel_c, key_c)
                    after = cnt[:, :, None] - cum3(term_m_vic(sel_c))
                    row = jnp.clip(key_c, 0, K - 1) * S_ + scl  # [C2]
                    dnc = snap.node_domains.T[
                        jnp.clip(key_c, 0, K - 1)
                    ]  # [C2, N]
                    mexcl = jnp.where(
                        dnc == sp_amin[row][:, None],
                        sp_min2[row][:, None],
                        sp_min1[row][:, None],
                    )
                    min_after = jnp.minimum(mexcl[:, :, None], after)
                    viol = (
                        after + 1.0 - min_after > skew_c[:, None, None]
                    ) | (cnt < -0.5)[:, :, None]
                    ok &= jnp.where(hard[:, None, None], ~viol, True)
        # hostPorts: every existing holder of a wanted port must be in
        # the prefix; ports held by this cycle's winners never clear
        pp_c = snap.pod_ports[cids]  # [C2, MPorts]
        has_p = jnp.any(pp_c >= 0, axis=1)  # [C2]
        vic_ports = snap.exist_ports[safe_idx]  # [N, MPN, MEP]
        conf = (
            (vic_ports[None, :, :, :, None] == pp_c[:, None, None, None])
            & (pp_c >= 0)[:, None, None, None]
        ).any((-2, -1)) & vict_valid[None]  # [C2, N, MPN]
        cum_c = cum3(conf.astype(jnp.float32))
        tot_c = cum_c[:, :, -1:]
        conflict_pw = (
            (snap.pod_ports[None, :, :, None] == pp_c[:, None, None])
            & (pp_c >= 0)[:, None, None]
        ).any((-2, -1)) & placed[None, :]  # [C2, P]
        n_oh = (
            node_of_placed[:, None]
            == jnp.arange(N, dtype=jnp.int32)[None, :]
        ) & placed[:, None]  # [P, N]
        winner_conf = (
            jax.lax.dot(
                conflict_pw.astype(jnp.float32), n_oh.astype(jnp.float32)
            ) > 0.5
        )  # [C2, N]
        ports_ok = (tot_c - cum_c <= 0.5) & ~winner_conf[:, :, None]
        ok &= jnp.where(has_p[:, None, None], ports_ok, True)
        return ok

    ok_nr2 = nonresource_ok_batched(cand_ids2)  # [C2, N, MPN+1]

    def step(carry, rank):
        k_claimed, nominated_req, victim_mask, pdb_used, claimed_q = carry
        p = cand_ids2[rank]
        prio = snap.pod_priority[p]

        # eligible victims: strictly lower priority than the preemptor
        elig = jnp.sum(vict_valid & (vict_prio < prio), axis=1).astype(jnp.int32)
        # PDB protection no longer truncates the prefix: protected
        # victims are evictable as a LAST RESORT, and the per-prefix
        # violation count becomes the first node-choice key below.
        # A victim VIOLATES when its within-group ordinal among the NEW
        # victims (slots >= k_claimed; earlier claims already consumed
        # pdb_used) exceeds the group's remaining budget — upstream's
        # filterPodsWithPDBViolation decrements per victim, so a
        # budget-1 group with two members in one prefix yields exactly
        # one violation, not zero.
        budget_rem = snap.pdb_allowed - pdb_used  # [GP]
        gids = jnp.arange(GP, dtype=vict_pdb.dtype)
        memb = jnp.any(
            vict_pdb[:, :, :, None] == gids[None, None, None, :], axis=2
        ) & vict_valid[:, :, None]  # [N, MPN, GP]
        ordinal = jnp.cumsum(memb.astype(jnp.int32), axis=1)  # inclusive
        pos3 = jnp.arange(MPN, dtype=jnp.int32)[None, :, None]
        claimed_cnt = jnp.sum(
            jnp.where(pos3 < k_claimed[:, None, None], memb, False)
            .astype(jnp.int32),
            axis=1,
        )  # [N, GP] members already claimed by earlier nominations
        prot = jnp.any(
            memb
            & (
                ordinal - claimed_cnt[:, None, :]
                > budget_rem[None, None, :]
            ),
            axis=2,
        ) & vict_valid  # [N, MPN]
        cum_prot = jnp.concatenate(
            [
                jnp.zeros((N, 1), jnp.int32),
                jnp.cumsum(prot.astype(jnp.int32), axis=1),
            ],
            axis=1,
        )  # [N, MPN+1]
        free_base = (
            snap.node_allocatable - node_requested - nominated_req + slack
        )  # [N, R]
        fits = jnp.all(
            snap.pod_requested[p][None, None, :]
            <= free_base[:, None, :] + prefix_freed,
            axis=-1,
        )  # [N, MPN+1]
        # the only carry-dependent non-resource check: ports claimed by
        # earlier nominations in this pass never clear
        qp = snap.pod_port_ids[p]  # [MPorts] -> Q ids
        claimed_conf = jnp.any(
            claimed_q[:, jnp.clip(qp, 0, Q - 1)] & (qp >= 0)[None, :],
            axis=1,
        )  # [N]
        allowed = (
            fits
            & ok_nr2[rank]
            & ~claimed_conf[:, None]
            & (ks >= k_claimed[:, None])
            & (ks <= elig[:, None])
        )
        exists = jnp.any(allowed, axis=1)
        k_min = jnp.argmax(allowed, axis=1).astype(jnp.int32)  # first True  # schedlint: disable=SH001 -- reduce over the MPN+1 victim-prefix axis, an inner pad dimension no mesh axis ever shards; first-True over bool is deterministic per row
        # preemption must actually help: new victims >= 1 (a node feasible
        # with zero evictions would have been chosen by the main cycle)
        candidate = (
            gate2[rank] & snap.node_valid & exists & (k_min > k_claimed)
        )

        # ---- pickOneNodeForPreemption: lexicographic minimization ----
        # row picks via one-hot masked sums, NOT take_along_axis: an
        # arbitrary [N]-gather costs ~50us on this backend and the loop
        # pays it per step x4; the masked reduce over the tiny MPN axis
        # fuses into the surrounding elementwise work
        def pick1(tab, idx):  # tab [N, W], idx [N] -> tab[n, idx[n]]
            pos = jnp.arange(tab.shape[1], dtype=jnp.int32)[None, :]
            return jnp.sum(
                jnp.where(pos == idx[:, None], tab, 0), axis=1
            )

        last = jnp.clip(k_min - 1, 0, MPN - 1)
        max_vict_prio = pick1(vict_prio, last)
        sum_vict_prio = pick1(prefix_prio, k_min) - pick1(
            prefix_prio, k_claimed
        )
        n_vict = k_min - k_claimed
        # NEW victims' PDB violations (upstream pickOneNodeForPreemption
        # criterion #1): nodes needing no violation always win over
        # last-resort nodes
        viol = pick1(cum_prot, k_min) - pick1(cum_prot, k_claimed)

        def lexmin(cand, key, big=_BIG_I32):
            key = jnp.where(cand, key, big)
            return cand & (key == jnp.min(key))

        best = lexmin(candidate, viol)
        best = lexmin(best, max_vict_prio)
        best = lexmin(best, sum_vict_prio)
        best = lexmin(best, n_vict)
        # upstream: prefer the node whose highest victim started LATEST
        # (evict younger pods); minimize the negated start time
        hi_start = pick1(vict_start, last)
        best = lexmin(best, -hi_start, big=jnp.float32(jnp.inf))
        # lowest node index among ties — shard-invariant over a sharded
        # nodes axis (ops/argsel.py; plain argmax merges shard-locally)
        b = argsel.argmax_first(best, axis=0)

        do = live2[rank] & jnp.any(candidate)
        nominated_p = jnp.where(do, b, jnp.int32(-1))

        # claim victims node_pods[b, k_claimed[b]:k_min[b]]
        pos1 = jnp.arange(MPN, dtype=jnp.int32)
        newly = do & (pos1 >= k_claimed[b]) & (pos1 < k_min[b]) & vict_valid[b]
        victim_mask = victim_mask.at[safe_idx[b]].max(newly)
        # newly-claimed victims consume their PDBs' budgets
        for bb in range(MB):
            g = vict_pdb[b, :, bb]  # [MPN]
            pdb_used = pdb_used.at[jnp.clip(g, 0, GP - 1)].add(
                jnp.where(newly & (g >= 0), 1, 0)
            )
        k_claimed = k_claimed.at[b].set(
            jnp.where(do, k_min[b], k_claimed[b])
        )
        nominated_req = nominated_req.at[b].add(
            jnp.where(do, snap.pod_requested[p], 0.0)
        )
        # ports this nomination will occupy: later candidates in this
        # pass must not count on evicting their way onto them
        qp2 = snap.pod_port_ids[p]
        claimed_q = claimed_q.at[b, jnp.clip(qp2, 0, Q - 1)].max(
            do & (qp2 >= 0)
        )
        return (
            (k_claimed, nominated_req, victim_mask, pdb_used, claimed_q),
            (p, nominated_p),
        )

    init = (
        jnp.zeros(N, jnp.int32),
        jnp.zeros_like(node_requested),
        jnp.zeros(E, bool),
        jnp.zeros(GP, jnp.int32),
        jnp.zeros((N, Q), bool),
    )
    # the serialization loop runs only over LIVE candidates: sel2 sorts
    # feasible candidates first (infeasible keys are _BIG_I32), so ranks
    # >= n_live are guaranteed no-ops (live2 False -> no claim, no
    # nomination) and a while_loop bounded by n_live skips them. At
    # config #4 that is ~19 latency-bound steps instead of scan_budget
    # (64) — each dead step cost ~0.2 ms on TPU.
    n_live = jnp.sum(live2).astype(jnp.int32)
    if os.environ.get("K8S_TPU_PREEMPT_FIXED_LOOP") == "1":
        # debug/workaround knob: run every budgeted rank (dead ranks are
        # no-ops) instead of the data-dependent live bound — isolates
        # rig issues with dynamic-trip while loops at ~0.2 ms per dead
        # step
        n_live = jnp.int32(C2)
    pods0 = cand_ids2  # rank -> pod id is static; dead ranks emit -1
    noms0 = jnp.full(C2, -1, jnp.int32)

    def w_cond(st):
        return st[0] < n_live

    def w_body(st):
        rank, carry, noms_acc = st
        carry, (_p, nom_p) = step(carry, rank)
        return rank + 1, carry, noms_acc.at[rank].set(nom_p)

    _, (_, _, victims, _, _), noms = jax.lax.while_loop(
        w_cond, w_body, (jnp.int32(0), init, noms0)
    )
    nominated = jnp.full(P, -1, jnp.int32).at[pods0].max(noms)
    return PreemptionResult(
        nominated=nominated,
        victims=victims & snap.exist_valid,
        num_preemptors=jnp.sum(nominated >= 0).astype(jnp.int32),
    )
