"""Preemption as a batched what-if program (SURVEY.md §2 C9, §3.4).

The reference's `DefaultPreemption` PostFilter (expected
`framework/plugins/defaultpreemption/` or `generic_scheduler.go (preempt)`
— [UNVERIFIED], mount empty) runs, per unschedulable pod:

    findCandidates: for each node (parallel goroutines):
        SelectVictimsOnNode: dry-run remove lower-priority pods, re-run
        Filter until the pod fits; re-add highest-priority victims back
        while it still fits (minimize victims)
    pickOneNodeForPreemption: min highest-victim-priority, then min
        priority sum, then fewest victims, then node order
    evict victims, set pod.Status.NominatedNodeName

The TPU-native design exploits the encoder's `node_pods` table: per node,
existing-pod indices sorted ascending by priority, so every candidate
victim set is a PREFIX of that list and the whole
remove/re-add-highest-first minimization collapses to "find the smallest
prefix k whose freed resources make the pod fit" — one cumulative sum plus
a first-true search, vectorized over all nodes at once. Preemptor claims
resolve in two phases: a BATCHED PREFILTER evaluates every budgeted
candidate against the pristine post-cycle state in one [C, N, MPN] pass
and drops those with no feasible preemption node anywhere — exact,
because contention state (`k_claimed` victims already spoken for per
node, `nominated_req` resources nominated pods will consume, spent PDB
budgets) only ever SHRINKS feasibility; then a short `lax.scan` over the
surviving contenders (typically ~the preemptor count, capped at
`scan_budget`)
serializes claims in priority-rank order exactly the way the reference's
one-pod-per-ScheduleOne loop does, so two preemptors never count the
same freed capacity. (A full-budget 256-step scan cost ~50ms on TPU —
one latency-bound step per candidate, mostly no-ops.)

PodDisruptionBudgets: a victim protected by a PDB whose remaining budget
(disruptionsAllowed minus victims already claimed THIS cycle) is exhausted
truncates the node's eligible prefix — no prefix reaching past it is
considered, and claimed victims decrement their PDBs' budgets in the scan
carry so one cycle never over-disrupts a budget. (Upstream prefers
PDB-violating victims last but may still evict them; this kernel never
does — strictly conservative.)

Tie-breaks mirror upstream pickOneNodeForPreemption: min highest-victim
priority, min victim priority sum, min victim count, then LATEST start
time of the highest victim (prefer evicting younger pods), then lowest
node index.

Documented deviation from upstream: victim removal only relaxes RESOURCE
constraints here. Upstream re-runs all filters with victims removed, so a
pod blocked by (say) anti-affinity toward a victim can preempt it; this
kernel requires `candidate_mask` (static + non-resource dynamic filters
against the post-cycle state — CycleResult.preempt_gate) to pass with the
victims still present — strictly conservative (never evicts where
upstream would not).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..models.encoding import ClusterSnapshot

_REL_EPS = 1e-5
_BIG_I32 = jnp.int32(2**31 - 1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PreemptionResult:
    nominated: jnp.ndarray  # i32 [P] node nominated by preemption (-1 none)
    victims: jnp.ndarray  # bool [E] existing pods to evict
    num_preemptors: jnp.ndarray  # i32 [] pods that got a nomination


def run_preemption(
    snap: ClusterSnapshot,
    *,
    assignment: jnp.ndarray,  # i32 [P] from the commit scan (-1 = unsched)
    node_requested: jnp.ndarray,  # f32 [N, R] post-cycle running requests
    static_mask: jnp.ndarray,  # bool [P, N] candidate gate: static + non-
    # resource dynamic feasibility vs the final state (preempt_gate)
    excluded: jnp.ndarray | None = None,  # bool [P] never preempt (e.g.
    # gang-dropped members: they fit without eviction, their group is what
    # failed — upstream never runs PostFilter for Permit rejections)
    budget: int = 256,  # max preemptor candidates PREFILTERED per cycle:
    # phase 1 evaluates the `budget` lowest-rank unschedulable pods in one
    # batched pass (bounds the [C, N, MPN] table); candidates beyond it
    # stay queued and get their attempt next cycle
    scan_budget: int = 64,  # max NOMINATIONS per cycle: phase 2 scans the
    # `scan_budget` lowest-rank prefilter survivors sequentially (one
    # latency-bound lax.scan step each, ~0.2ms); survivors beyond it defer
    # to the next cycle — upstream nominates ONE pod per ScheduleOne
    # iteration, so 64 per cycle is still generous
) -> PreemptionResult:
    P, N = static_mask.shape
    E = snap.E
    MPN = snap.node_pods.shape[1]

    # ---- per-node victim tables (shared across all preemptors) ----
    vict_valid = snap.node_pods >= 0  # [N, MPN]
    safe_idx = jnp.clip(snap.node_pods, 0, E - 1)
    vict_prio = jnp.where(
        vict_valid, snap.exist_priority[safe_idx], _BIG_I32
    )  # [N, MPN]
    vict_req = jnp.where(
        vict_valid[:, :, None], snap.exist_requested[safe_idx], 0.0
    )  # [N, MPN, R]
    vict_start = jnp.where(
        vict_valid, snap.exist_start[safe_idx], 0.0
    )  # [N, MPN]
    vict_pdb = jnp.where(
        vict_valid[:, :, None], snap.exist_pdb[safe_idx], -1
    )  # [N, MPN, MB]
    GP = snap.pdb_allowed.shape[0]
    MB = vict_pdb.shape[2]
    # prefix_freed[:, k] = resources freed by evicting the first k victims
    prefix_freed = jnp.concatenate(
        [jnp.zeros_like(vict_req[:, :1]), jnp.cumsum(vict_req, axis=1)], axis=1
    )  # [N, MPN+1, R]
    prio_for_sum = jnp.where(vict_valid, vict_prio, 0)
    prefix_prio = jnp.concatenate(
        [jnp.zeros_like(prio_for_sum[:, :1]), jnp.cumsum(prio_for_sum, axis=1)],
        axis=1,
    )  # [N, MPN+1]
    ks = jnp.arange(MPN + 1, dtype=jnp.int32)[None, :]  # [1, MPN+1]
    slack = _REL_EPS * snap.node_allocatable + _REL_EPS  # [N, R]

    unschedulable = snap.pod_valid & (assignment < 0) & snap.pod_can_preempt
    if excluded is not None:
        unschedulable = unschedulable & ~excluded
    # compact to the budgeted lowest-rank candidates (rank order preserved)
    C = min(P, budget)
    cand_key = jnp.where(unschedulable, snap.pod_order, _BIG_I32)
    cand_ids = jnp.argsort(cand_key)[:C].astype(jnp.int32)
    cand_ok = unschedulable[cand_ids]  # [C]

    # ---- phase 1: batched prefilter (one pass, no contention state) ----
    # A candidate with no feasible preemption node against the PRISTINE
    # post-cycle state never gains one: contention (k_claimed,
    # nominated_req, pdb_used) only shrinks feasibility. Dropping those
    # candidates up front cuts the sequential phase from `budget` steps to
    # the handful of genuine contenders (typically ~the preemptor count).
    prio_c = snap.pod_priority[cand_ids]  # [C]
    req_c = snap.pod_requested[cand_ids]  # [C, R]
    elig_cn = jnp.sum(
        vict_valid[None, :, :] & (vict_prio[None, :, :] < prio_c[:, None, None]),
        axis=2,
    ).astype(jnp.int32)  # [C, N]
    prot0 = jnp.zeros(vict_valid.shape, bool)
    for b in range(MB):
        g = vict_pdb[:, :, b]
        prot0 |= (g >= 0) & (snap.pdb_allowed[jnp.clip(g, 0, GP - 1)] <= 0)
    prot0 &= vict_valid
    pos_row = jnp.arange(MPN, dtype=jnp.int32)[None, :]
    first_prot0 = jnp.min(
        jnp.where(prot0, pos_row, MPN), axis=1
    ).astype(jnp.int32)  # [N]
    elig0 = jnp.minimum(elig_cn, first_prot0[None, :])  # [C, N]
    free0 = snap.node_allocatable - node_requested + slack  # [N, R]
    fits0 = jnp.all(
        req_c[:, None, None, :]
        <= free0[None, :, None, :] + prefix_freed[None, :, :, :],
        axis=-1,
    )  # [C, N, MPN+1]
    allowed0 = fits0 & (ks[None] >= 1) & (ks[None] <= elig0[:, :, None])
    feasible_any = jnp.any(
        allowed0 & static_mask[cand_ids][:, :, None]
        & snap.node_valid[None, :, None],
        axis=(1, 2),
    ) & cand_ok  # [C]

    C2 = min(C, scan_budget)
    key2 = jnp.where(feasible_any, snap.pod_order[cand_ids], _BIG_I32)
    sel2 = jnp.argsort(key2)[:C2].astype(jnp.int32)
    cand_ids2 = cand_ids[sel2]  # [C2] global pod ids, rank order
    live2 = feasible_any[sel2]

    # ---- phase 2: exact rank-sequential claims over the survivors ----
    def step(carry, rank):
        k_claimed, nominated_req, victim_mask, pdb_used = carry
        p = cand_ids2[rank]
        prio = snap.pod_priority[p]

        # eligible victims: strictly lower priority than the preemptor
        elig = jnp.sum(vict_valid & (vict_prio < prio), axis=1).astype(jnp.int32)
        # PDB truncation: a victim whose remaining budget is exhausted
        # caps the usable prefix at its position (prefixes never skip)
        budget_rem = snap.pdb_allowed - pdb_used  # [GP]
        prot = jnp.zeros(vict_valid.shape, bool)
        for b in range(MB):
            g = vict_pdb[:, :, b]
            prot |= (g >= 0) & (budget_rem[jnp.clip(g, 0, GP - 1)] <= 0)
        prot &= vict_valid
        first_prot = jnp.min(
            jnp.where(prot, pos_row, MPN), axis=1
        ).astype(jnp.int32)  # [N]
        elig = jnp.minimum(elig, first_prot)
        free_base = (
            snap.node_allocatable - node_requested - nominated_req + slack
        )  # [N, R]
        fits = jnp.all(
            snap.pod_requested[p][None, None, :]
            <= free_base[:, None, :] + prefix_freed,
            axis=-1,
        )  # [N, MPN+1]
        allowed = fits & (ks >= k_claimed[:, None]) & (ks <= elig[:, None])
        exists = jnp.any(allowed, axis=1)
        k_min = jnp.argmax(allowed, axis=1).astype(jnp.int32)  # first True
        # preemption must actually help: new victims >= 1 (a node feasible
        # with zero evictions would have been chosen by the main cycle)
        candidate = (
            static_mask[p] & snap.node_valid & exists & (k_min > k_claimed)
        )

        # ---- pickOneNodeForPreemption: lexicographic minimization ----
        last = jnp.clip(k_min - 1, 0, MPN - 1)
        max_vict_prio = jnp.take_along_axis(
            vict_prio, last[:, None], axis=1
        )[:, 0]  # priority of the highest (last-in-prefix) victim
        sum_vict_prio = (
            jnp.take_along_axis(prefix_prio, k_min[:, None], axis=1)[:, 0]
            - jnp.take_along_axis(prefix_prio, k_claimed[:, None], axis=1)[:, 0]
        )
        n_vict = k_min - k_claimed

        def lexmin(cand, key, big=_BIG_I32):
            key = jnp.where(cand, key, big)
            return cand & (key == jnp.min(key))

        best = lexmin(candidate, max_vict_prio)
        best = lexmin(best, sum_vict_prio)
        best = lexmin(best, n_vict)
        # upstream: prefer the node whose highest victim started LATEST
        # (evict younger pods); minimize the negated start time
        hi_start = jnp.take_along_axis(vict_start, last[:, None], axis=1)[:, 0]
        best = lexmin(best, -hi_start, big=jnp.float32(jnp.inf))
        b = jnp.argmax(best).astype(jnp.int32)  # lowest node index among ties

        do = live2[rank] & jnp.any(candidate)
        nominated_p = jnp.where(do, b, jnp.int32(-1))

        # claim victims node_pods[b, k_claimed[b]:k_min[b]]
        pos1 = jnp.arange(MPN, dtype=jnp.int32)
        newly = do & (pos1 >= k_claimed[b]) & (pos1 < k_min[b]) & vict_valid[b]
        victim_mask = victim_mask.at[safe_idx[b]].max(newly)
        # newly-claimed victims consume their PDBs' budgets
        for bb in range(MB):
            g = vict_pdb[b, :, bb]  # [MPN]
            pdb_used = pdb_used.at[jnp.clip(g, 0, GP - 1)].add(
                jnp.where(newly & (g >= 0), 1, 0)
            )
        k_claimed = k_claimed.at[b].set(
            jnp.where(do, k_min[b], k_claimed[b])
        )
        nominated_req = nominated_req.at[b].add(
            jnp.where(do, snap.pod_requested[p], 0.0)
        )
        return (
            (k_claimed, nominated_req, victim_mask, pdb_used),
            (p, nominated_p),
        )

    init = (
        jnp.zeros(N, jnp.int32),
        jnp.zeros_like(node_requested),
        jnp.zeros(E, bool),
        jnp.zeros(GP, jnp.int32),
    )
    (_, _, victims, _), (pods, noms) = jax.lax.scan(
        step, init, jnp.arange(C2, dtype=jnp.int32)
    )
    nominated = jnp.full(P, -1, jnp.int32).at[pods].max(noms)
    return PreemptionResult(
        nominated=nominated,
        victims=victims & snap.exist_valid,
        num_preemptors=jnp.sum(nominated >= 0).astype(jnp.int32),
    )
