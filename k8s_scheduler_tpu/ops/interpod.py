"""Inter-pod affinity / topology-spread kernels — the quadratic hot path.

The reference's InterPodAffinity plugin is its worst-case cost center:
O(nodes x existing-pods-with-affinity) per pod (SURVEY.md §3.5, benchmark
config #3; expected `framework/plugins/interpodaffinity/` — [UNVERIFIED],
mount empty). The TPU-native design never materializes pods x nodes x pods:

1. Label selectors are deduplicated ([S] distinct selectors, each an AND of
   expression-table rows incl. an implicit namespace expression).
2. ONE batched pass computes matched_pending [S, P] and matched_existing
   [S, E] via the shared expression kernel.
3. Affinity state collapses to per-(selector, topology-domain) COUNTS
   [S, D] (plus per-selector node tables [S, N] for the symmetric checks) —
   segment-sums over existing pods, not pairwise comparisons.
4. The commit scan carries these counts and updates them as pods place, so
   in-cycle affinity among pending pods resolves exactly like the
   reference's sequential NodeInfo mutation. Per-step cost is O(S*N + MA*N).

Semantics parity notes:
- Required affinity: >=1 matching pod in the node's domain, with the
  upstream bootstrap rule (a pod matching its own selector may place when
  NO pod in the cluster matches it — the first pod of a self-affine group).
- Required anti-affinity: zero matching pods in the domain; symmetric
  anti-affinity of existing AND in-cycle pods is enforced via the [S, N]
  presence table.
- Preferred terms score both directions (incoming pod's preferences against
  placed pods, placed pods' preferences against the incoming pod),
  normalized by max |raw| over feasible nodes like the oracle.
- A node missing the topology key cannot satisfy required affinity, cannot
  violate anti-affinity, and fails DoNotSchedule spread constraints.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..models import encoding as enc
from . import labels as labels_ops


def selector_match(snap, label_keys, label_vals) -> jnp.ndarray:  # [S, X]
    """Every deduplicated selector against every labeled subject."""
    em = labels_ops.expr_pod_mask(snap, label_keys, label_vals)  # [Ex, X]
    g = labels_ops._gather_expr(em, snap.sel_exprs, fill=True)  # [S, MSE, X]
    return g.all(axis=1)


def matched_pending(snap) -> jnp.ndarray:  # bool [S, P]
    return selector_match(snap, snap.pod_label_keys, snap.pod_label_vals) & (
        snap.pod_valid[None, :]
    )


def matched_existing(snap) -> jnp.ndarray:  # bool [S, E]
    return selector_match(snap, snap.exist_label_keys, snap.exist_label_vals) & (
        snap.exist_valid[None, :]
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AffinityState:
    """Scan-carried affinity state (see module docstring)."""

    counts: jnp.ndarray  # f32 [S, D] matching pods per (selector, domain)
    total: jnp.ndarray  # f32 [S] matching pods anywhere (bootstrap rule)
    anti_presence: jnp.ndarray  # bool [S, N] node blocked-by-anti(sel) table
    pref_sym: jnp.ndarray  # f32 [S, N] symmetric preferred-term weights


def _exist_domains(snap) -> jnp.ndarray:  # i32 [E, K]
    safe_node = jnp.clip(snap.exist_node, 0, snap.N - 1)
    dom = snap.node_domains[safe_node]  # [E, K]
    return jnp.where((snap.exist_node >= 0)[:, None], dom, -1)


def initial_state(snap, m_exist: jnp.ndarray) -> AffinityState:
    """Aggregate existing pods into the four state tables."""
    S, E = m_exist.shape
    D = snap.domain_key.shape[0]
    K = snap.node_domains.shape[1]
    dom = _exist_domains(snap)  # [E, K]

    # counts[s, d] = number of existing pods matching s whose node is in d
    # — [S,E] @ [E,D] one-hot matmul per key on the MXU (3x faster than
    # the per-selector scatter-add at 16k x 5k; 0/1 operands are exact at
    # any matmul precision, accumulation is f32). A -1 domain (node
    # missing the key) produces an all-zero one-hot row.
    counts = jnp.zeros((S, D), jnp.float32)
    mb = m_exist.astype(jnp.float32)
    d_ids = jnp.arange(D, dtype=jnp.int32)[None, :]
    for k in range(K):  # K is tiny (distinct topology keys)
        oh = (dom[:, k][:, None] == d_ids).astype(jnp.float32)  # [E, D]
        counts = counts + jax.lax.dot(mb, oh)
    total = jnp.sum(m_exist.astype(jnp.float32), axis=1)  # [S]

    # anti_presence[s, n] = some placed pod with required anti-term (s, k)
    # shares node n's k-domain. Built as ONE scatter into a flat [S, D]
    # table (flat domain ids are globally unique, so no key collisions),
    # then expanded to nodes with K gathers. Gated on the static capability
    # flag: a spread-only cluster never traces the affinity tables.
    if snap.has_inter_pod_affinity:
        anti = _flat_to_node(
            snap, _flat_table(snap.exist_anti_terms, None, dom, S, D), True
        )
        pref = _flat_to_node(
            snap,
            _flat_table(snap.exist_pref_aff, snap.exist_pref_aff_w, dom, S, D),
            False,
        )
    else:
        anti = jnp.zeros((S, snap.N), bool)
        pref = jnp.zeros((S, snap.N), jnp.float32)
    return AffinityState(counts, total, anti, pref)


def _flat_table(terms, weights, owner_dom, S, D):
    """Scatter every term (sel, k) of every owner into [S, D] at the
    owner's k-domain. terms [X, MA, 2], owner_dom [X, K]; weights None ->
    bool OR table, else f32 sum table."""
    X, MA, _ = terms.shape
    K = owner_dom.shape[1]
    sel = terms[..., 0].reshape(-1)  # [X*MA]
    k = jnp.clip(terms[..., 1].reshape(-1), 0, K - 1)
    xi = jnp.repeat(jnp.arange(X), MA)
    d = owner_dom[xi, k]
    valid = (sel >= 0) & (d >= 0)
    si = jnp.clip(sel, 0, S - 1)
    di = jnp.clip(d, 0, D - 1)
    if weights is None:
        return jnp.zeros((S, D), bool).at[si, di].max(valid)
    w = jnp.where(valid, weights.reshape(-1), 0.0)
    return jnp.zeros((S, D), jnp.float32).at[si, di].add(w)


def _flat_to_node(snap, flat, bool_mode: bool):
    """[S, D] per-domain table -> [S, N] per-node table (a node is in one
    domain per topology key; flat ids are unique across keys)."""
    out = jnp.zeros((flat.shape[0], snap.N), bool if bool_mode else jnp.float32)
    for k in range(snap.node_domains.shape[1]):
        nd = snap.node_domains[:, k]  # [N]
        g = flat[:, jnp.clip(nd, 0, flat.shape[1] - 1)]  # [S, N]
        m = (nd >= 0)[None, :]
        out = (out | (g & m)) if bool_mode else (out + jnp.where(m, g, 0.0))
    return out


def _node_domain_match(snap, k, d):  # bool [N]: nodes whose k-domain == d
    nd = jnp.take(snap.node_domains, jnp.clip(k, 0, snap.node_domains.shape[1] - 1),
                  axis=1)  # [N]
    return (nd == d) & (d >= 0)


# --------------------------------------------------------------------------
# per-step (inside the commit scan)
# --------------------------------------------------------------------------


def _counts_at_nodes(snap, state: AffinityState, sel, k) -> jnp.ndarray:
    """counts[sel, domain(n, k)] for all nodes n; -1 domains -> -1."""
    D = state.counts.shape[1]
    nd = jnp.take(
        snap.node_domains, jnp.clip(k, 0, snap.node_domains.shape[1] - 1), axis=1
    )  # [N]
    row = state.counts[jnp.clip(sel, 0, state.counts.shape[0] - 1)]  # [D]
    c = row[jnp.clip(nd, 0, D - 1)]
    return jnp.where(nd >= 0, c, -1.0)  # -1 marks "no such domain"


def affinity_dyn_mask(snap, state: AffinityState, m_pending, p) -> jnp.ndarray:
    """Required affinity + anti-affinity + symmetric anti for pod p: [N]."""
    N = snap.N
    ok = jnp.ones((N,), bool)
    MA = snap.pod_aff_terms.shape[1]
    aff = snap.pod_aff_terms[p]  # [MA, 2]
    anti = snap.pod_anti_terms[p]
    for a in range(MA):
        sel, k = aff[a, 0], aff[a, 1]
        c = _counts_at_nodes(snap, state, sel, k)
        # bootstrap: nothing matches the selector anywhere AND the pod
        # matches its own selector -> term ignored
        boot = (state.total[jnp.clip(sel, 0, state.total.shape[0] - 1)] == 0) & (
            m_pending[jnp.clip(sel, 0, m_pending.shape[0] - 1), p]
        )
        term_ok = jnp.where(sel >= 0, boot | (c > 0), True)
        ok &= term_ok
    for a in range(MA):
        sel, k = anti[a, 0], anti[a, 1]
        c = _counts_at_nodes(snap, state, sel, k)
        # c == -1 (key absent) cannot be violated; c == 0 is fine
        term_ok = jnp.where(sel >= 0, c <= 0, True)
        ok &= term_ok
    # symmetric: placed pods' anti terms whose selector matches p
    mp = m_pending[:, p]  # [S]
    viol = jnp.any(mp[:, None] & state.anti_presence, axis=0)  # [N]
    return ok & ~viol


def affinity_dyn_score(snap, state: AffinityState, m_pending, p,
                       feasible) -> jnp.ndarray:
    """Preferred-term score for pod p, normalized to [-100, 100] by the max
    |raw| over feasible nodes (both sides of the symmetry)."""
    N = snap.N
    raw = jnp.zeros((N,), jnp.float32)
    MA = snap.pod_pref_aff.shape[1]
    pref = snap.pod_pref_aff[p]
    w = snap.pod_pref_aff_w[p]
    for a in range(MA):
        sel, k = pref[a, 0], pref[a, 1]
        c = _counts_at_nodes(snap, state, sel, k)
        raw += jnp.where((sel >= 0) & (c > 0), w[a] * jnp.maximum(c, 0.0), 0.0)
    mp = m_pending[:, p].astype(jnp.float32)  # [S]
    raw += mp @ state.pref_sym  # symmetric direction, [S]x[S,N]
    hi = jnp.max(jnp.where(feasible, jnp.abs(raw), 0.0))
    return jnp.where(hi > 0, raw / hi * 100.0, 0.0)


def affinity_update(snap, state: AffinityState, m_pending, p, node,
                    committed) -> AffinityState:
    """Pod p committed to `node`: fold it into counts/total/anti/pref."""
    K = snap.node_domains.shape[1]
    S, D = state.counts.shape
    mp = jnp.where(committed, m_pending[:, p].astype(jnp.float32), 0.0)  # [S]
    counts = state.counts
    node_dom = snap.node_domains[node]  # [K]
    for k in range(K):
        d = node_dom[k]
        add = jnp.where(d >= 0, mp, 0.0)
        counts = counts.at[:, jnp.clip(d, 0, D - 1)].add(add)
    total = state.total + mp

    # fold p's own anti/preferred terms into the node tables (unrolled over
    # the tiny MA axis; each slot is one [N]-row mask + scatter); statically
    # skipped when the cluster has no affinity terms at all
    anti = state.anti_presence
    pref = state.pref_sym
    if not snap.has_inter_pod_affinity:
        return AffinityState(counts, total, anti, pref)
    MA = snap.pod_anti_terms.shape[1]
    anti_terms = snap.pod_anti_terms[p]
    pref_terms = snap.pod_pref_aff[p]
    pref_w = snap.pod_pref_aff_w[p]
    for a in range(MA):
        sel, k = anti_terms[a, 0], anti_terms[a, 1]
        d = node_dom[jnp.clip(k, 0, K - 1)]
        row = _node_domain_match(snap, k, d) & (sel >= 0) & committed
        anti = anti.at[jnp.clip(sel, 0, S - 1)].max(row)

        sel2, k2 = pref_terms[a, 0], pref_terms[a, 1]
        d2 = node_dom[jnp.clip(k2, 0, K - 1)]
        row2 = _node_domain_match(snap, k2, d2) & (sel2 >= 0) & committed
        pref = pref.at[jnp.clip(sel2, 0, S - 1)].add(
            jnp.where(row2, pref_w[a], 0.0)
        )
    return AffinityState(counts, total, anti, pref)


# --------------------------------------------------------------------------
# topology spread
# --------------------------------------------------------------------------


def spread_dyn_mask(snap, state: AffinityState, p) -> jnp.ndarray:
    """DoNotSchedule constraints: count(dom) + 1 - min(dom counts of the
    key) <= maxSkew; nodes missing the key fail."""
    N = snap.N
    ok = jnp.ones((N,), bool)
    MC = snap.pod_tsc.shape[1]
    tsc = snap.pod_tsc[p]  # [MC, 3]
    skews = snap.pod_tsc_skew[p]
    D = state.counts.shape[1]
    for c in range(MC):
        k, sel, when = tsc[c, 0], tsc[c, 1], tsc[c, 2]
        cnt = _counts_at_nodes(snap, state, sel, k)  # [N], -1 = no key
        row = state.counts[jnp.clip(sel, 0, state.counts.shape[0] - 1)]  # [D]
        eligible = (snap.domain_key == k) & (snap.domain_node_count > 0)
        minc = jnp.min(jnp.where(eligible, row, jnp.inf))
        minc = jnp.where(jnp.isfinite(minc), minc, 0.0)
        viol = (cnt + 1.0 - minc > skews[c].astype(jnp.float32)) | (cnt < 0)
        hard = (k >= 0) & (when == enc.WHEN_DO_NOT_SCHEDULE)
        ok &= jnp.where(hard, ~viol, True)
    return ok


def spread_dyn_score(snap, state: AffinityState, p, feasible) -> jnp.ndarray:
    """ScheduleAnyway constraints: fewer matching pods in the node's domain
    is better; raw = sum of counts, normalized reverse over feasible nodes
    (both sides use this simplified form of upstream's two-pass score)."""
    N = snap.N
    raw = jnp.zeros((N,), jnp.float32)
    MC = snap.pod_tsc.shape[1]
    tsc = snap.pod_tsc[p]
    for c in range(MC):
        k, sel, when = tsc[c, 0], tsc[c, 1], tsc[c, 2]
        cnt = _counts_at_nodes(snap, state, sel, k)
        soft = (k >= 0) & (when == enc.WHEN_SCHEDULE_ANYWAY)
        raw += jnp.where(soft, jnp.maximum(cnt, 0.0), 0.0)
    hi = jnp.max(jnp.where(feasible, raw, 0.0))
    return jnp.where(hi > 0, (1.0 - raw / hi) * 100.0, 100.0)


# ==========================================================================
# Batched (whole-pending-set) variants — the round-based commit's kernels.
#
# The per-pod functions above run inside the sequential commit scan: one
# [N]-row at a time, P scan steps. On TPU that is latency-bound (~100us+
# per scan step through the sequencer), so the round-based commit
# (ops/rounds.py) evaluates ALL pods against the current state at once:
# count lookups become row-gathers from a [K*S, N] table and the symmetric
# terms become [P,S]x[S,N] matmuls on the MXU.
# ==========================================================================


def counts_by_node(snap, state: AffinityState) -> jnp.ndarray:
    """[K*S, N] table: counts[s, domain(n, k)] for every (k, s, n); -1
    where node n has no domain for key k."""
    K = snap.node_domains.shape[1]
    S, D = state.counts.shape
    rows = []
    for k in range(K):
        nd = snap.node_domains[:, k]  # [N]
        g = state.counts[:, jnp.clip(nd, 0, D - 1)]  # [S, N]
        rows.append(jnp.where((nd >= 0)[None, :], g, -1.0))
    return jnp.concatenate(rows, axis=0)  # [K*S, N]  # schedlint: disable=SH002 -- 2-D selector-table rows stacked on the K*S axis, which is never mesh-sharded (the PR 9 miscompile needs sharded 1-D operands)


def _row_onehot(snap, sel, k) -> jnp.ndarray:  # f32 [P, K*S]
    """One-hot row selector for per-pod (selector, key) terms."""
    S = snap.sel_exprs.shape[0]
    K = snap.node_domains.shape[1]
    row = jnp.clip(k, 0, K - 1) * S + jnp.clip(sel, 0, S - 1)
    ks = jnp.arange(K * S, dtype=row.dtype)[None, :]
    return (row[:, None] == ks).astype(jnp.float32)


def _term_pick(snap, table, sel, k, exact: bool) -> jnp.ndarray:
    """table[row(sel, k)] for every pod as a one-hot [P, K*S] @ [K*S, N]
    matmul on the MXU — ~5x faster than the arbitrary-row gather at
    10k x 5k. With `exact`, bf16_3x precision keeps integer-valued f32
    table entries exact through the matmul (each f32 splits into three
    bf16 terms exactly; the single nonzero per one-hot row sums them back
    in f32); without it, entries must already be bf16-exact (0/1 presence
    bits, small sentinels)."""
    oh = _row_onehot(snap, sel, k)
    prec = jax.lax.Precision.HIGH if exact else jax.lax.Precision.DEFAULT
    return jax.lax.dot(oh, table, precision=prec)


def _term_counts(snap, cbn, sel, k):  # sel,k: i32 [P] -> f32 [P, N]
    """Exact counts-at-node pick for per-pod terms (spread skew and
    preference scores compare/weight true counts)."""
    return _term_pick(snap, cbn, sel, k, exact=True)


def _multi_hot(snap, sel, k, w) -> jnp.ndarray:  # [P, A] each -> f32 [P, K*S]
    """Weighted MULTI-hot term matrix: row (k, sel) accumulates w[:, a]
    over the term axis. Collapses A per-slot `one-hot @ table` dots into
    ONE dot — the term-compaction lever from PERF.md item 4. Callers
    zero w for invalid slots; duplicate (sel, k) slots sum, which every
    consumer's algebra wants (satisfied-term counts, additive weights)."""
    S = snap.sel_exprs.shape[0]
    K = snap.node_domains.shape[1]
    W = jnp.zeros((sel.shape[0], K * S), jnp.float32)
    ks = jnp.arange(K * S, dtype=jnp.int32)[None, :]
    for a in range(sel.shape[1]):  # A is tiny/static; fuses to one pass
        row = jnp.clip(k[:, a], 0, K - 1) * S + jnp.clip(sel[:, a], 0, S - 1)
        W = W + jnp.where(row[:, None] == ks, w[:, a][:, None], 0.0)
    return W


def affinity_mask_batched(snap, state: AffinityState, m_pending,
                          cbn) -> jnp.ndarray:  # bool [P, N]
    """Required affinity + anti-affinity + symmetric anti for ALL pods.

    Only the SIGN of the domain counts matters here (c > 0 / c <= 0), so
    the picks run over a shared 0/1 presence table — bf16-exact at any
    matmul precision; the -1 no-domain sentinel lands in the 'not
    positive' bucket both checks want.

    Term-compacted (PERF item 4): instead of one [P,K*S]@[K*S,N] dot per
    term slot (2*MA dots), a multi-hot count matrix per direction gives
    TWO dots total. Required terms: a valid non-boot term is satisfied
    iff its row is positive, so satisfied-count == required-count iff
    every term holds (counts are small ints — bf16-exact, f32 accum).
    Anti terms: violated iff the multi-hot dot against positivity is
    nonzero."""
    P, N = m_pending.shape[1], snap.N
    S = state.total.shape[0]
    pid = jnp.arange(P, dtype=jnp.int32)
    pos = (cbn > 0).astype(jnp.float32)  # [K*S, N]

    sel = snap.pod_aff_terms[..., 0]  # [P, MA]
    k = snap.pod_aff_terms[..., 1]
    scl = jnp.clip(sel, 0, S - 1)
    boot = (state.total[scl] == 0) & m_pending[scl, pid[:, None]]  # [P, MA]
    need = (sel >= 0) & ~boot
    W = _multi_hot(snap, sel, k, need.astype(jnp.float32))
    n_req = jnp.sum(need, axis=1).astype(jnp.float32)  # [P]
    ok = jax.lax.dot(W, pos) >= n_req[:, None] - 0.5

    a_sel = snap.pod_anti_terms[..., 0]
    a_k = snap.pod_anti_terms[..., 1]
    Wa = _multi_hot(snap, a_sel, a_k, (a_sel >= 0).astype(jnp.float32))
    ok &= jax.lax.dot(Wa, pos) < 0.5
    # symmetric: any placed pod's anti term whose selector matches p —
    # [P,S]x[S,N] matmul on the MXU instead of a per-pod [S,N] reduction
    viol = (
        m_pending.T.astype(jnp.float32) @ state.anti_presence.astype(jnp.float32)
    ) > 0.0
    return ok & ~viol


def affinity_score_batched(snap, state: AffinityState, m_pending, cbn,
                           feasible) -> jnp.ndarray:  # f32 [P, N]
    """Preferred-term score for ALL pods, normalized per pod to
    [-100, 100] by max |raw| over that pod's feasible nodes.

    Term-compacted: per-slot contribution w * max(c, 0) * (c > 0) equals
    w * relu(c) (the -1 no-domain sentinel relus to 0), which is LINEAR
    in the table — so all MA exact picks collapse to one weighted
    multi-hot dot against relu(cbn) at HIGH precision (counts exceed
    bf16's integer range; bf16_3x keeps the products exact)."""
    sel = snap.pod_pref_aff[..., 0]  # [P, MA]
    k = snap.pod_pref_aff[..., 1]
    w = jnp.where(sel >= 0, snap.pod_pref_aff_w, 0.0)
    Ww = _multi_hot(snap, sel, k, w)
    raw = jax.lax.dot(Ww, jnp.maximum(cbn, 0.0),
                      precision=jax.lax.Precision.HIGH)
    raw += m_pending.T.astype(jnp.float32) @ state.pref_sym  # [P, N]
    hi = jnp.max(jnp.where(feasible, jnp.abs(raw), 0.0), axis=1, keepdims=True)
    return jnp.where(hi > 0, raw / hi * 100.0, 0.0)


def spread_minc(snap, state: AffinityState) -> jnp.ndarray:  # f32 [K*S]
    """min matching-pod count over eligible domains, per (key, selector) —
    the `minc` of the spread rule, shared by all pods."""
    K = snap.node_domains.shape[1]
    S, D = state.counts.shape
    outs = []
    for k in range(K):
        eligible = (snap.domain_key == k) & (snap.domain_node_count > 0)  # [D]
        m = jnp.min(
            jnp.where(eligible[None, :], state.counts, jnp.inf), axis=1
        )  # [S]
        outs.append(jnp.where(jnp.isfinite(m), m, 0.0))
    return jnp.concatenate(outs, axis=0)  # schedlint: disable=SH002 -- per-key [S] minima on the replicated selector axis; never pods-sharded


def spread_mask_batched(snap, state: AffinityState, cbn,
                        minc) -> jnp.ndarray:  # bool [P, N]
    P, N = snap.P, snap.N
    ok = jnp.ones((P, N), bool)
    MC = snap.pod_tsc.shape[1]
    S = state.counts.shape[0]
    K = snap.node_domains.shape[1]
    for c in range(MC):
        k = snap.pod_tsc[:, c, 0]
        sel = snap.pod_tsc[:, c, 1]
        when = snap.pod_tsc[:, c, 2]
        cnt = _term_counts(snap, cbn, sel, k)  # [P, N]
        row = jnp.clip(k, 0, K - 1) * S + jnp.clip(sel, 0, S - 1)
        mc = minc[row]  # [P]
        skew = snap.pod_tsc_skew[:, c].astype(jnp.float32)
        viol = (cnt + 1.0 - mc[:, None] > skew[:, None]) | (cnt < 0)
        hard = (k >= 0) & (when == enc.WHEN_DO_NOT_SCHEDULE)
        ok &= jnp.where(hard[:, None], ~viol, True)
    return ok


def spread_score_batched(snap, state: AffinityState, cbn,
                         feasible) -> jnp.ndarray:  # f32 [P, N]
    # Term-compacted like affinity_score_batched: soft-slot contribution
    # max(cnt, 0) is relu-linear in the table, so MC exact picks become
    # one multi-hot dot against relu(cbn).
    k = snap.pod_tsc[..., 0]  # [P, MC]
    sel = snap.pod_tsc[..., 1]
    when = snap.pod_tsc[..., 2]
    soft = (k >= 0) & (when == enc.WHEN_SCHEDULE_ANYWAY)
    Ws = _multi_hot(snap, sel, k, soft.astype(jnp.float32))
    raw = jax.lax.dot(Ws, jnp.maximum(cbn, 0.0),
                      precision=jax.lax.Precision.HIGH)
    hi = jnp.max(jnp.where(feasible, raw, 0.0), axis=1, keepdims=True)
    return jnp.where(hi > 0, (1.0 - raw / hi) * 100.0, 100.0)


def affinity_update_batched(snap, state: AffinityState, m_pending,
                            accepted, node_of) -> AffinityState:
    """Fold a whole round's accepted placements (accepted bool [P],
    node_of i32 [P]) into the state tables in one batched pass.

    Every table update is an MXU matmul instead of a scatter (profiled:
    one [S, N] scatter-max cost ~7ms per round at 10k x 5k; the
    equivalent [S, P] @ [P, N] matmul is ~0.2ms). Exactness: counts/anti
    matmuls have 0/1 operands (exact at any matmul precision, f32
    accumulation); pref weights go through f32 dots at HIGH precision,
    which represents the inputs exactly."""
    K = snap.node_domains.shape[1]
    S, D = state.counts.shape
    N = snap.N
    P = accepted.shape[0]
    acc_f = accepted.astype(jnp.float32)
    mp_acc = m_pending.astype(jnp.float32) * acc_f[None, :]  # [S, P]
    nsafe = jnp.clip(node_of, 0, N - 1)
    node_dom = snap.node_domains[nsafe]  # [P, K]

    counts = state.counts
    d_ids = jnp.arange(D, dtype=jnp.int32)[None, :]
    for k in range(K):
        d = jnp.where(accepted, node_dom[:, k], -1)  # [P]
        oh_d = (d[:, None] == d_ids).astype(jnp.float32)  # [P, D]
        counts = counts + jax.lax.dot(mp_acc, oh_d)
    total = state.total + jnp.sum(mp_acc, axis=1)

    anti = state.anti_presence
    pref = state.pref_sym
    if not snap.has_inter_pod_affinity:
        return AffinityState(counts, total, anti, pref)
    MA = snap.pod_anti_terms.shape[1]
    s_ids = jnp.arange(S, dtype=jnp.int32)[None, :]
    for a in range(MA):
        sel = snap.pod_anti_terms[:, a, 0]  # [P]
        k = jnp.clip(snap.pod_anti_terms[:, a, 1], 0, K - 1)
        d = jnp.take_along_axis(node_dom, k[:, None], axis=1)[:, 0]  # [P]
        nd_k = snap.node_domains.T[k]  # [P, N] domain of every node under k
        row = (nd_k == d[:, None]) & (d >= 0)[:, None] & (
            sel >= 0
        )[:, None] & accepted[:, None]  # [P, N]
        oh_s = (sel[:, None] == s_ids).astype(jnp.float32)  # [P, S]
        hits = jax.lax.dot(oh_s.T, row.astype(jnp.float32))  # [S, N]
        anti = anti | (hits > 0.0)

        sel2 = snap.pod_pref_aff[:, a, 0]
        k2 = jnp.clip(snap.pod_pref_aff[:, a, 1], 0, K - 1)
        d2 = jnp.take_along_axis(node_dom, k2[:, None], axis=1)[:, 0]
        nd_k2 = snap.node_domains.T[k2]  # [P, N]
        row2 = (nd_k2 == d2[:, None]) & (d2 >= 0)[:, None] & (
            sel2 >= 0
        )[:, None] & accepted[:, None]
        w2 = snap.pod_pref_aff_w[:, a]  # [P]
        oh_w = jnp.where(sel2[:, None] == s_ids, w2[:, None], 0.0)  # [P, S]
        pref = pref + jax.lax.dot(
            oh_w.T, row2.astype(jnp.float32),
            precision=jax.lax.Precision.HIGH,
        )  # [S, N]
    return AffinityState(counts, total, anti, pref)


def spread_min2(snap, counts):
    """Per (key, selector): (min1, argmin-domain, min2) of the matching-
    pod counts over eligible domains — each f32/i32 [K*S].

    Preemption's what-if needs "min over domains EXCLUDING d" for the
    candidate node's domain d (evicting on one node only lowers that
    domain's count): min_excl(d) = min2 if argmin == d else min1. A
    (key, selector) with a single eligible domain gets min2 = 1e9 so
    min_after collapses to the domain's own post-eviction count."""
    K = snap.node_domains.shape[1]
    S, D = counts.shape
    d_ids = jnp.arange(D, dtype=jnp.int32)[None, :]
    m1s, aas, m2s = [], [], []
    for k in range(K):
        eligible = (snap.domain_key == k) & (snap.domain_node_count > 0)
        vals = jnp.where(eligible[None, :], counts, jnp.inf)  # [S, D]
        a1 = jnp.argmin(vals, axis=1).astype(jnp.int32)  # [S]  # schedlint: disable=SH001 -- reduce over the domain axis D, which is never mesh-sharded (MESH_AXES is pods/nodes); counts ties are broken identically on every replica
        m1 = jnp.min(vals, axis=1)
        vals2 = jnp.where(d_ids == a1[:, None], jnp.inf, vals)
        m2 = jnp.min(vals2, axis=1)
        m1s.append(jnp.where(jnp.isfinite(m1), m1, 0.0))
        aas.append(a1)
        m2s.append(jnp.where(jnp.isfinite(m2), m2, 1e9))
    return (
        jnp.concatenate(m1s), jnp.concatenate(aas), jnp.concatenate(m2s)  # schedlint: disable=SH002 -- [S] per-key vectors on the replicated selector axis; never pods-sharded
    )


def anti_owner_counts(snap, assignment) -> jnp.ndarray:
    """f32 [S, D]: how many pods (existing + placed-this-cycle) OWN a
    required anti-affinity term (sel, key) whose key-domain is d — the
    COUNT version of AffinityState.anti_presence, which preemption needs
    to know whether evicting a node's victim prefix removes the last
    owner blocking a symmetric-anti candidate."""
    S = snap.sel_exprs.shape[0]
    D = snap.domain_key.shape[0]
    dom_e = _exist_domains(snap)  # [E, K]
    onesE = jnp.ones(snap.exist_anti_terms.shape[:2], jnp.float32)
    cnt = _flat_table(snap.exist_anti_terms, onesE, dom_e, S, D)
    placed = snap.pod_valid & (assignment >= 0)
    node_dom = snap.node_domains[jnp.clip(assignment, 0, snap.N - 1)]
    terms_p = jnp.where(
        placed[:, None, None], snap.pod_anti_terms, -1
    )
    onesP = jnp.ones(terms_p.shape[:2], jnp.float32)
    return cnt + _flat_table(terms_p, onesP, node_dom, S, D)


def selector_activity(snap) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(anti_active [S], spread_active [S]): selectors referenced by any
    required anti-affinity term (pending or existing pods) / any topology
    spread constraint — the selectors whose MATCHERS matter for the
    round-commit interaction guards."""
    S = snap.sel_exprs.shape[0]

    def mark(terms_sel):  # i32 [..] selector ids (-1 pad) -> bool [S]
        flat = terms_sel.reshape(-1)
        return (
            jnp.zeros((S,), bool)
            .at[jnp.clip(flat, 0, S - 1)]
            .max(flat >= 0)
        )

    anti_active = mark(snap.pod_anti_terms[..., 0]) | mark(
        snap.exist_anti_terms[..., 0]
    )
    spread_active = mark(snap.pod_tsc[..., 1])
    return anti_active, spread_active
