"""Label-expression kernels: the device-side selector/affinity machinery.

The reference evaluates label selectors per pod per node in Go
(`NodeAffinity`/`nodeaffinity.Filter`, upstream
`component-helpers/scheduling/corev1/nodeaffinity` — [UNVERIFIED], mount
empty; SURVEY.md §2 C7). Here, every distinct match expression in the
cluster is one row of a deduplicated expression table (models/encoding.py),
and ONE kernel evaluates the whole table against every node (or every pod)
at once:

    expr_node_mask: [Ex] exprs x [N] nodes  -> bool [Ex, N]
    requirement_mask: OR-of-terms(AND-of-exprs) gather -> bool [Rq, N]
    per-pod masks are then a single int gather: mask[pod_req_id[p]]

so the per-cycle cost is O(Ex*N*ML*MV) elementwise (tiny: Ex is the number
of DISTINCT expressions, not pods) plus O(P) gathers, instead of the
reference's O(P*N*terms) interpreted walk.

Semantics parity (labels.Requirement): NotIn and DoesNotExist match when
the key is absent; Gt/Lt require a numerically-parsable label value.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models import encoding as enc


def take_rows(table: jnp.ndarray, ids: jnp.ndarray, fill) -> jnp.ndarray:
    """table[ids] for a [X, N] table and [P] ids (-1 -> `fill`), as a
    one-hot [P, X] @ [X, N] matmul on the MXU.

    Arbitrary-row gathers at [P, N] scale cost ~2.5ms each on this
    backend where the equivalent one-hot matmul costs ~0.3ms (see the
    measured numbers in ops/rounds.py's guard-table notes); X (distinct
    table rows) is always small. Bool tables ride a DEFAULT-precision dot
    (0/1 exact in bf16); f32 tables use Precision.HIGH (bf16_3x splits
    represent any f32 exactly, and each one-hot row has a single nonzero,
    so there is no accumulation error)."""
    X = table.shape[0]
    oh = (
        jnp.clip(ids, 0, X - 1)[:, None]
        == jnp.arange(X, dtype=ids.dtype)[None, :]
    )
    if table.dtype == jnp.bool_:
        out = jax.lax.dot(
            oh.astype(jnp.float32), table.astype(jnp.float32),
            precision=jax.lax.Precision.DEFAULT,
        ) > 0.5
    else:
        out = jax.lax.dot(
            oh.astype(jnp.float32), table, precision=jax.lax.Precision.HIGH
        )
    return jnp.where((ids >= 0)[:, None], out, fill)


def expr_match(
    ex_key: jnp.ndarray,  # i32 [Ex]
    ex_op: jnp.ndarray,  # i32 [Ex]
    ex_vals: jnp.ndarray,  # i32 [Ex, MV] (-1 pad)
    ex_num: jnp.ndarray,  # f32 [Ex]
    label_keys: jnp.ndarray,  # i32 [X, ML] (-1 pad)
    label_vals: jnp.ndarray,  # i32 [X, ML]
    label_num: jnp.ndarray | None = None,  # f32 [X, ML] (nan if not numeric)
    subject_index: jnp.ndarray | None = None,  # i32 [X] for FIELD_IN
) -> jnp.ndarray:  # bool [Ex, X]
    """Evaluate every expression against every labeled subject (node or
    pod). X is the subject axis."""
    key_eq = label_keys[None, :, :] == ex_key[:, None, None]  # [Ex, X, ML]
    key_eq &= label_keys[None, :, :] >= 0
    has_key = key_eq.any(-1)  # [Ex, X]
    # value-in-set per label slot: [Ex, X, ML, MV] -> [Ex, X, ML]
    val_in = (
        (label_vals[None, :, :, None] == ex_vals[:, None, None, :])
        & (ex_vals >= 0)[:, None, None, :]
    ).any(-1)
    key_and_val = (key_eq & val_in).any(-1)  # [Ex, X]

    if label_num is not None:
        # nan compares False, so non-numeric labels never satisfy Gt/Lt
        gt = (key_eq & (label_num[None, :, :] > ex_num[:, None, None])).any(-1)
        lt = (key_eq & (label_num[None, :, :] < ex_num[:, None, None])).any(-1)
    else:
        gt = lt = jnp.zeros_like(has_key)

    if subject_index is not None:
        field_in = (
            (subject_index[None, :, None] == ex_vals[:, None, :])
            & (ex_vals >= 0)[:, None, :]
        ).any(-1)
    else:
        field_in = jnp.zeros_like(has_key)

    op = ex_op[:, None]
    return jnp.select(
        [
            op == enc.OP_IN,
            op == enc.OP_NOT_IN,
            op == enc.OP_EXISTS,
            op == enc.OP_DOES_NOT_EXIST,
            op == enc.OP_GT,
            op == enc.OP_LT,
            op == enc.OP_FIELD_IN,
        ],
        [
            key_and_val,
            ~key_and_val,  # absent key matches NotIn
            has_key,
            ~has_key,
            gt,
            lt,
            field_in,
        ],
        default=jnp.zeros_like(has_key),  # OP_IMPOSSIBLE / padding
    )


def expr_node_mask(snap) -> jnp.ndarray:  # bool [Ex, N]
    return expr_match(
        snap.ex_key,
        snap.ex_op,
        snap.ex_vals,
        snap.ex_num,
        snap.node_label_keys,
        snap.node_label_vals,
        snap.node_label_num,
        subject_index=jnp.arange(snap.N, dtype=jnp.int32),
    )


def expr_pod_mask(snap, label_keys, label_vals) -> jnp.ndarray:  # [Ex, X]
    """Expressions against pod labels (selectors). Gt/Lt on pod labels is
    legal in k8s only for node selectors, so no numeric axis here."""
    return expr_match(
        snap.ex_key, snap.ex_op, snap.ex_vals, snap.ex_num,
        label_keys, label_vals,
    )


def _gather_expr(expr_mask: jnp.ndarray, ids: jnp.ndarray,
                 fill: bool) -> jnp.ndarray:
    """expr_mask [Ex, X] gathered by ids [...] with -1 -> `fill`."""
    safe = jnp.clip(ids, 0, expr_mask.shape[0] - 1)
    out = expr_mask[safe]  # [..., X]
    return jnp.where((ids >= 0)[..., None], out, fill)


def requirement_mask(rq_exprs: jnp.ndarray, expr_mask: jnp.ndarray) -> jnp.ndarray:
    """[Rq, MT, ME] requirement table -> bool [Rq, X]: OR over terms of
    AND over expressions (nodeSelectorTerms semantics; an all-padding term
    is ignored)."""
    g = _gather_expr(expr_mask, rq_exprs, fill=True)  # [Rq, MT, ME, X]
    term_ok = g.all(axis=2)  # [Rq, MT, X]
    term_valid = (rq_exprs >= 0).any(axis=2)  # [Rq, MT]
    return (term_ok & term_valid[:, :, None]).any(axis=1)


def pod_requirement_mask(snap, expr_mask: jnp.ndarray) -> jnp.ndarray:
    """Per-pod node-affinity + nodeSelector feasibility: bool [P, N].
    (NodeAffinity Filter + the separate nodeSelector field are ANDed,
    matching upstream.)"""
    req = requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]
    return take_rows(req, snap.pod_req_id, True) & take_rows(
        req, snap.pod_sel_req_id, True
    )


def preferred_score(snap, expr_mask: jnp.ndarray) -> jnp.ndarray:
    """NodeAffinity preferred terms -> score [P, N] in [0, 100].

    Deviation from upstream (documented): upstream NormalizeScore divides
    by the max score across *feasible* nodes, which couples a pod's score
    on one node to the whole node set; we normalize by the pod's total
    preferred weight instead (score = matched_weight / total_weight * 100),
    which is node-local and identical in ranking for a single pod. The
    oracle uses the same rule, so differential tests are exact."""
    g = _gather_expr(expr_mask, snap.pf_exprs, fill=True)  # [Pf, MPT, ME, N]
    term_ok = g.all(axis=2)  # [Pf, MPT, N]
    term_valid = (snap.pf_exprs >= 0).any(axis=2)  # [Pf, MPT]
    w = snap.pf_weight * term_valid  # [Pf, MPT]
    matched = jnp.sum(w[:, :, None] * term_ok, axis=1)  # [Pf, N]
    total = jnp.maximum(jnp.sum(w, axis=1), 1e-9)[:, None]  # [Pf, 1]
    table = matched / total * 100.0  # [Pf, N]
    return take_rows(table, snap.pod_pref_id, 0.0)  # [P, N]
