"""Round-based batched commit: the TPU-first ScheduleOne batching.

The sequential commit scan (ops/commit.py) preserves exact one-pod-at-a-
time semantics but costs one `lax.scan` step per pod — and a TPU scan step
is latency-bound (~100us+ through the sequencer), so 10k pods cost seconds
regardless of how little work each step does. This module replaces the
per-pod loop with a small number of ROUNDS; each round is a handful of
large batched ops (matmuls, row-gathers, sorts, segmented scans) that use
the MXU/VPU at full width:

  1. CLAIM   — every still-pending pod evaluates all plugin masks/scores
               against the current state (exactly: the same kernels the
               scan uses, batched over [B, N]) and claims its best node
               (nominated node first, then argmax with a deterministic
               hash tie-break — the analogue of upstream selectHost's
               random tie-break, which also prevents herding).
  2. ACCEPT  — a number of cheap acceptance PASSES (waterfall): in each
               pass, every still-unaccepted pod claims its best node among
               choices not yet known-dead, with the capacity-sensitive
               node-local score component RE-ANCHORED to the in-round
               node_req (a filling node loses attractiveness immediately —
               the spread mechanism sequential scheduling gets from score
               freshness); capacity losers fall to their next-best node in
               the next pass, reusing the round's masks (no dyn
               recompute). At round end, ONE guard sweep checks all
               capacity-accepted claims for mutual consistency and revokes
               violators (they retry next round against refreshed masks).
               Within a pass, claims resolve in `pod_order` rank without
               any sequential host loop:
               a. per-node capacity: sort claims by (node, rank), then a
                  segmented exclusive prefix-sum of requests admits each
                  claimant iff it still fits (earlier-rank claimants of
                  the same node are charged first);
               b. interaction guards: claims that could invalidate one
                  another within the round (required anti-affinity,
                  DoNotSchedule spread skew, affinity bootstrap, hostPort
                  exclusivity) are resolved by a participant table — one
                  row per (claimant, constraint-role) — sorted by
                  (group, rank) and swept with segmented exclusive scans.
                  Rank order within a group decides, exactly like the
                  sequential scan would have.
  3. UPDATE  — accepted placements fold into the running state in one
               batched pass (segment-adds into domain counts, scatter
               rows into the symmetric tables, port-bitmap scatter).

Rounds repeat (lax.while_loop) until no claim is accepted or `max_rounds`
is hit; leftover pods are unschedulable this cycle. Round 1 runs over the
full pending set; subsequent rounds run over a COMPACTED view — the
lowest-rank `P/compact` still-active pods, re-gathered each round — since
round 1 typically places the large majority, and [B, N] work shrinks
proportionally. The compacted view is a real ClusterSnapshot whose
pod-axis arrays are gathered at the active ids, so every plugin kernel
runs unchanged.

Semantics contract (documented deviation from the strict scan):
  - Every accepted placement satisfies every filter against the state at
    the start of its round, and the guards make same-round acceptances
    mutually consistent, so the FINAL assignment is valid under the final
    state — same validity invariant the sequential scan provides
    (oracle.validate_rounds_assignment checks it).
  - Guards count REJECTED claimants too (conservative): a claim that lost
    capacity can still hold an anti-affinity slot for its round; the loser
    simply retries next round against the true state. This only delays
    placements, never invalidates them.
  - Outcomes can differ from the strict scan where in-cycle contention
    exists (scores against a slightly older state, hash tie-break); the
    strict scan remains available as commit_mode="scan".

A pod that matches more than MS_MATCH guard-active selectors overflows the
matcher table; overflow claimants are deferred while any normal claimant
exists and then accepted one per round (exact, since they run alone).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec

from ..models import encoding as enc
from ..parallel.mesh import MESH_AXES, mesh_pin
from . import argsel
from . import interpod as interpod_ops

NEG_INF = -1e9
_REL_EPS = 1e-5  # mirrors ops/resources.py fit slack
MS_MATCH = 4  # guard-active selectors tracked per pod (overflow = defer)
# Claim scores are rounded to INTEGERS before the hash tie-break — the
# upstream scheduler's own granularity (plugin Score returns int64 in
# [0, 100]; selectHost random-tie-breaks across the whole max class).
# Keeping f32 score sums un-rounded created artificial total orders that
# herded every pod's claim onto the same argmax node; integer classes let
# the per-pod hash spread contending claims across all equally-good nodes.
TIE_EPS = 0.9375  # hash spread, strictly below the integer quantum
_PR1 = jnp.uint32(2654435761)
_PR2 = jnp.uint32(40503)
_BIG = jnp.int32(2**31 - 1)

# participant role bits (packed into one sort operand)
_RB_MATCH = 1
_RB_ANTI = 2
_RB_BOOT = 4
_RB_GMATCH = 8
_RB_SPREAD = 16
_RB_PORT = 32
_RB_PV = 64  # static-PV exclusivity: one claimant per PV per cycle


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RoundsResult:
    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-commit
    extra: Any  # final plugin state
    rounds_used: jnp.ndarray  # i32 []
    accepted_per_round: jnp.ndarray  # i32 [max_rounds] acceptance counts
    diag_per_round: jnp.ndarray  # i32 [max_rounds, 3] summed over passes:
    # (live claims, capacity rejections, guard rejections) — convergence
    # diagnostics, negligible cost


def compact_window(P: int, compact: int = 8) -> int:
    """Row count of the compacted per-round view (also used by the
    cycle's final attribution/preemption-gate view): the `P/compact`
    lowest-rank actives, padded to a lane multiple."""
    return min(P, max(256, -(-P // compact) // 128 * 128))


def _tie_break(gid: jnp.ndarray, N: int) -> jnp.ndarray:
    """f32 [B, N] in [0, TIE_EPS), keyed on GLOBAL pod id so compaction
    does not change a pod's tie-break row."""
    p = gid.astype(jnp.uint32)[:, None]
    n = jax.lax.broadcasted_iota(jnp.uint32, (1, N), 1)
    h = (p * _PR1 + n * _PR2) & jnp.uint32(0xFFFF)
    return h.astype(jnp.float32) * (TIE_EPS / 65536.0)


def _matched_active(m_pending, active_sel, ms: int):
    """Per-pod list of up to `ms` guard-active selectors it matches.

    Returns (sels i32 [P, ms] (-1 pad), overflow bool [P]). Selector ids
    ascending (deterministic). Implemented as `ms` masked argmin passes —
    a lax.top_k here would sort the whole [P, S] table, which costs
    hundreds of ms at 10k pods for a table that is almost entirely
    False."""
    S, P = m_pending.shape
    m = (m_pending & active_sel[:, None]).T  # [P, S]
    sel_ids = jnp.arange(S, dtype=jnp.int32)[None, :]
    cols = []
    remaining = m
    for _ in range(ms):
        # lowest matching selector id still unclaimed
        cand = jnp.where(remaining, sel_ids, S)
        nxt = jnp.min(cand, axis=1).astype(jnp.int32)  # [P]
        cols.append(jnp.where(nxt < S, nxt, -1))
        remaining = remaining & (sel_ids != nxt[:, None])
    overflow = jnp.any(remaining, axis=1)
    return jnp.stack(cols, axis=1), overflow


def _pod_view(snap, gid: jnp.ndarray):
    """A ClusterSnapshot whose pod-axis arrays are gathered at `gid` —
    plugin kernels run on it unchanged with P = len(gid)."""
    updates = {
        f.name: getattr(snap, f.name)[gid]
        for f in dataclasses.fields(snap)
        # extender verdicts (None unless configured) are pre-folded into
        # the static mask/score, so views never need them
        if f.name.startswith("pod_") and getattr(snap, f.name) is not None
    }
    return dataclasses.replace(snap, **updates)


def _seg_scan_tables(keys, pods, counts):
    """Entries sorted by (key, rank): for each 0/1 indicator column,
    return the in-segment count strictly before each entry's POD (one
    pod's own entries never block each other).

    All indicator columns ride ONE stacked [L, C] cumsum and TWO stacked
    row-gathers — per-column 1-D gathers are pathologically slow on this
    backend (~2ms each at L=283k; 12 of them dominated the sweep)."""
    L = keys.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    seg_start = jnp.concatenate(  # schedlint: disable=SH002 -- the [L] sorted entries axis is replicated (lax.sort all-gathers its operands; the audit suite bounds exactly that payload), so no operand here is sharded
        [jnp.ones((1,), bool), keys[1:] != keys[:-1]]
    )
    run_start = seg_start | jnp.concatenate(  # schedlint: disable=SH002 -- same replicated [L] axis as the line above
        [jnp.ones((1,), bool), pods[1:] != pods[:-1]]
    )
    seg_first = jax.lax.cummax(jnp.where(seg_start, i, -1))
    run_first = jax.lax.cummax(jnp.where(run_start, i, -1))
    names = list(counts.keys())
    x = jnp.stack([counts[n] for n in names], axis=1)  # [L, C]
    before = jnp.cumsum(x, axis=0) - x  # strictly before index j
    delta = before[run_first] - before[seg_first]  # [L, C]
    return {n: delta[:, c] for c, n in enumerate(names)}


def _owner_state(ext_state):
    for v in ext_state.values():
        if isinstance(v, interpod_ops.AffinityState):
            return v
    return None


def rounds_commit(
    *,
    snap,
    static_mask: jnp.ndarray = None,  # bool [P, N]
    static_score: jnp.ndarray = None,  # f32 [P, N]
    sbase: jnp.ndarray = None,  # f32 [P, N] pre-combined static score
    # (NEG_INF where infeasible) — the carry path passes this directly
    # instead of (static_mask, static_score)
    m_pending: jnp.ndarray,  # bool [S, P]
    dyn_batched_view_fn: Callable,  # (vsnap, vmp, node_req, ext, vsmask)
    #   -> (mask [B,N], score [B,N], per_filter)
    update_batched_view_fn: Callable,  # (vsnap, vmp, ext, accepted, node_of)
    extra: Any,
    max_rounds: int = 64,
    compact: int = 8,
    passes: int = 6,  # device-time flat across 4..10 at config-#4 scale;
    passes_round0: int = 10,  # smaller counts compile ~30% faster
    shortlist: int = 0,  # >0: acceptance passes run on a per-pod top-k
    # candidate shortlist [B, shortlist] instead of [B, N], with a
    # rescue pass preserving the "unplaced => infeasible vs final
    # state" invariant (see one_round). MEASURED (sweep_shortlist4, real
    # TPU, config #4 10k x 5k): the shortlist LOSES at this geometry —
    # 212 ms vs 158 ms wide — because the per-pass saving (~0.5 ms; the
    # [B,N] pass chain is bandwidth-cheap at N=5k) is smaller than the
    # added per-round top_k (~6.5 ms at [10k,5k]) and per-pass [B,k]
    # anchor-delta gathers (~1.7 ms). Default therefore 0 (wide). The
    # path is kept, tested, for geometries where N dwarfs the pass
    # count's bandwidth economics (N >> 5k). ROUNDING CAVEAT (advisor
    # r4): the shortlist scores round(base)+tie+round(delta) while the
    # wide path scores round(base+delta)+tie — the two roundings can
    # differ by 1, so node CHOICES may diverge from the wide engine
    # beyond the top-k approximation itself (heuristic-only; the
    # unplaced=>infeasible invariant is unaffected).
    anchor_stride: int = 1,  # re-anchor every pass (the spread signal
    # is load-bearing: stride 2 cost ~19% of round-0 acceptance in the
    # same sweep)
    compact_gather: str = "rows",  # how compacted rounds fetch the
    # active rows of the [P, N] static base: "rows" = row-gather (fast
    # single-chip; under GSPMD it makes XLA all-gather the FULL [P, N]
    # sbase per round — 200 MB at config #4); "onehot" = one-hot [B, P]
    # matmul (exact: one 1.0 per row, f32) whose contraction runs over
    # the sharded pods axis, so the mesh path pays one small [B, N]
    # all-reduce instead. The sharded build selects "onehot".
    score_anchor_fn: Callable | None = None,  # node_requested -> f32 [N]
    # capacity-sensitive node-local score component (Framework.score_anchor)
    pv_choice_fn: Callable | None = None,  # (vsnap, node_of, live, ext)
    # -> i32 [B, MVol] chosen static PV per claimant/slot (-1 none): the
    # guard arbitrates same-round claimants of one PV by rank
    mesh=None,  # jax.sharding.Mesh | None — the collective-payload
    # diet's sharding hint: with a mesh, the compacted per-round [B, N]
    # views carry an explicit with_sharding_constraint over the mesh
    # axes (parallel/mesh.MESH_AXES), so the one-hot compaction's psum
    # lowers to a reduce-scatter of the PARTITIONED view instead of
    # all-reducing a replicated [B, N] (the single largest collective in
    # AUDIT_SHARDED_r05: 23.6 MB of 43.2 MB total). None (the default,
    # and every single-device build) changes nothing.
) -> RoundsResult:
    P, N = (sbase if sbase is not None else static_mask).shape
    S = m_pending.shape[0]
    D = snap.domain_key.shape[0]
    K = snap.node_domains.shape[1]
    MA = snap.pod_anti_terms.shape[1]
    MC = snap.pod_tsc.shape[1]
    Q = snap.num_distinct_ports
    MPorts = snap.pod_port_ids.shape[1]

    rank_g = snap.pod_order.astype(jnp.int32)  # [P] lower = earlier

    # guard-active selectors (static per cycle)
    anti_active, spread_active = interpod_ops.selector_activity(snap)
    aff_used = (
        jnp.zeros((S,), bool)
        .at[jnp.clip(snap.pod_aff_terms[..., 0].reshape(-1), 0, S - 1)]
        .max(snap.pod_aff_terms[..., 0].reshape(-1) >= 0)
    )
    active_sel = anti_active | spread_active | aff_used
    matched_sels_g, overflow_g = _matched_active(
        m_pending, active_sel, MS_MATCH
    )

    has_guards = bool(snap.has_inter_pod_affinity or snap.has_topology_spread)
    has_port_guards = bool(Q > 0)

    # group-key space: domain groups, per-selector global groups,
    # (node, port) groups, static-PV groups, invalid
    GK_GLOBAL = S * (D + 1)
    GK_PORT = GK_GLOBAL + S
    GK_PV = GK_PORT + N * Q
    V = snap.pv_avail.shape[0]
    GK_INVALID = GK_PV + V + 1
    has_pv_guards = bool(snap.has_volumes and pv_choice_fn is not None)

    def shard_view(arr):
        """Constrain a compacted [B, ...] view onto the mesh axes
        (row dim on 'pods', a second dim on 'nodes' when present and
        divisible — parallel/mesh.mesh_pin owns the rule). Identity
        without a mesh."""
        if mesh is None:
            return arr
        return mesh_pin(arr, mesh, MESH_AXES)

    def local_update_fn(fn):
        """Force the per-round plugin-state update to run device-LOCAL
        on a mesh (identity without one). The update contracts [B, S]/
        [B, D] one-hots over the claims axis; left to GSPMD those dots
        get contraction-sharded — each device computes a partial and
        all-reduces the FULL [S, N]/[S, D] count tables, 58 MB/cycle at
        the audit shape even with every input pinned replicated (the
        partitioner trades our per-cycle payload for FLOP spread).
        shard_map admits no such choice: inputs arrive replicated
        (kilobyte-scale [B, ...] vectors — shard_map inserts the tiny
        gathers itself), every device computes the identical full
        update, zero collectives inside."""
        if mesh is None:
            return fn
        return shard_map(
            fn, mesh=mesh,
            in_specs=(PartitionSpec(),) * 5,  # schedlint: disable=SH003 -- shard_map plumbing: the EMPTY spec (replicated) carries no layout rule, it marks these inputs as not-mesh_pin's-business
            out_specs=PartitionSpec(),  # schedlint: disable=SH003 -- same replicated shard_map plumbing as the line above
            check_rep=False,
        )

    slack = _REL_EPS * snap.node_allocatable + _REL_EPS  # [N, R]
    # static mask+score pre-combined; scores clamp to +-1e6 (far above any
    # plugin-weight scale, far below |NEG_INF|/2) so an extreme extender
    # score can never push a feasible node across the infeasible threshold
    # the compacted rounds reconstruct the mask with (vsbase > NEG_INF/2)
    if sbase is None:
        sbase = jnp.where(
            static_mask, jnp.clip(static_score, -1e6, 1e6), NEG_INF
        )  # [P, N]

    def guards_ok(vsnap, vrank, vsels, choice, live, ext_state):
        """Participant-table sweep over the round's accepted claims;
        ok bool [B]. Within a (selector/port, domain/node) group, entries
        resolve in rank order — the same outcome a sequential pass over
        the claims would produce."""
        B = vrank.shape[0]
        state = _owner_state(ext_state) if has_guards else None
        if state is None and not has_port_guards and not has_pv_guards:
            return jnp.ones((B,), bool)
        nsafe = jnp.clip(choice, 0, N - 1)

        keys, role_ids, caps = [], [], []

        def emit(key, valid, role, cap=None):
            keys.append(jnp.where(valid & live, key, GK_INVALID))
            role_ids.append(role)
            caps.append(cap)

        if state is not None:
            # each capability pays only for its own machinery: affinity-
            # only clusters never trace the spread sections and vice versa
            # (the encoder's capability-flag convention)
            node_dom = snap.node_domains[nsafe]  # [B, K]
            boot_active = state.total == 0  # [S]
            if snap.has_inter_pod_affinity:
                for a in range(MA):
                    sel = vsnap.pod_anti_terms[:, a, 0]
                    k = jnp.clip(vsnap.pod_anti_terms[:, a, 1], 0, K - 1)
                    d = jnp.take_along_axis(node_dom, k[:, None], 1)[:, 0]
                    key = jnp.clip(sel, 0, S - 1) * (D + 1) + (d + 1)
                    emit(key, (sel >= 0) & (d >= 0), _RB_ANTI)
                for a in range(MA):
                    sel = vsnap.pod_aff_terms[:, a, 0]
                    scl = jnp.clip(sel, 0, S - 1)
                    emit(GK_GLOBAL + scl, (sel >= 0) & boot_active[scl],
                         _RB_BOOT)
            if snap.has_topology_spread:
                minc = interpod_ops.spread_minc(snap, state)  # [K*S]
                for c in range(MC):
                    k = vsnap.pod_tsc[:, c, 0]
                    sel = vsnap.pod_tsc[:, c, 1]
                    when = vsnap.pod_tsc[:, c, 2]
                    kcl = jnp.clip(k, 0, K - 1)
                    d = jnp.take_along_axis(node_dom, kcl[:, None], 1)[:, 0]
                    scl = jnp.clip(sel, 0, S - 1)
                    hard = (k >= 0) & (when == enc.WHEN_DO_NOT_SCHEDULE) & (
                        d >= 0
                    )
                    cnt = state.counts[scl, jnp.clip(d, 0, D - 1)]  # [B]
                    mc = minc[kcl * S + scl]
                    cap = (
                        vsnap.pod_tsc_skew[:, c].astype(jnp.float32)
                        - cnt + mc
                    ).astype(jnp.int32)
                    emit(scl * (D + 1) + (d + 1), hard, _RB_SPREAD,
                         cap=jnp.maximum(cap, 1))
            # matchers feed the anti guard AND the spread arrival counts —
            # needed whenever either capability is on
            for m in range(MS_MATCH):
                sel = vsels[:, m]
                scl = jnp.clip(sel, 0, S - 1)
                for k in range(K):
                    d = node_dom[:, k]
                    emit(scl * (D + 1) + (d + 1), (sel >= 0) & (d >= 0),
                         _RB_MATCH)
                if snap.has_inter_pod_affinity:
                    emit(GK_GLOBAL + scl, (sel >= 0) & boot_active[scl],
                         _RB_GMATCH)
        if has_port_guards:
            for j in range(MPorts):
                ids = vsnap.pod_port_ids[:, j]
                key = GK_PORT + nsafe * Q + jnp.clip(ids, 0, Q - 1)
                emit(key, ids >= 0, _RB_PORT)
        if has_pv_guards:
            # one entry per (claimant, volume slot) naming the static PV
            # the claim would bind; first rank per PV survives
            pvc = pv_choice_fn(vsnap, nsafe, live, ext_state)  # [B, MVol]
            for j in range(pvc.shape[1]):
                ids = pvc[:, j]
                emit(GK_PV + jnp.clip(ids, 0, V - 1), ids >= 0, _RB_PV)

        # stack+reshape, NOT jnp.concatenate: on a multi-axis mesh this
        # jaxlib's SPMD partitioner miscompiles an axis-0 concatenate of
        # 1-D pods-sharded integer vectors — the partially-replicated
        # operands are summed over the free ('nodes') axis, so every
        # value comes back multiplied by that axis size (minimal repro:
        # tests/test_shard_invariance.py::test_sharded_concat_workaround
        # — THIS, not reduce tie order, was the real source of the 2-D
        # mesh guard divergence behind the old dryrun_multichip_8 xfail;
        # stack+reshape takes the safe partitioner path and is the same
        # piece-major layout)
        keys_c = jnp.stack(keys, axis=0).reshape(-1)
        n_emit = len(keys)
        ranks_c = jnp.tile(vrank, n_emit)
        # Collective-payload diet: the claimant id, role, and cap of
        # table entry j are all FUNCTIONS of position (claimant j % B of
        # emit slot j // B; roles are per-slot trace constants), so the
        # sweep gathers NONE of them through the sort — the permutation
        # alone reconstructs pods/roles, and the caps column is gathered
        # only when a spread emit actually produced one. (The old
        # stacked [L, 3] payload gather was the audit's s32[283136,3]
        # all-reduce — 3.4 MB at the P=10112 shape — for data the sort
        # result already encodes.)
        role_tab = jnp.asarray(role_ids, jnp.int32)  # [n_emit] constant
        needs_caps = any(c is not None for c in caps)
        if needs_caps:
            caps_c = jnp.stack([  # stack, not concatenate (see keys_c)
                c if c is not None else jnp.full((B,), 2**30, jnp.int32)
                for c in caps
            ], axis=0).reshape(-1)

        # The participant-table sort dominates the sweep. When (key, rank)
        # fits one u32 word, sort a SINGLE packed operand plus an iota
        # permutation — a multi-key sort costs ~2x the packed one at
        # L≈290k, and per-column 1-D gathers are ~2ms each on this backend.
        rank_space = 1 << int(P - 1).bit_length()  # active ranks are < P
        # minimal index width for the sort's permutation operand (the
        # compacted table fits i16; round 0's P-scale table takes i32)
        iota = jnp.arange(
            keys_c.shape[0], dtype=argsel.index_dtype(keys_c.shape[0])
        )
        if (GK_INVALID + 1) * rank_space <= 2**32:
            # padded/inactive rows carry rank INT32_MAX (pod_order pad);
            # clamp so they cannot wrap the key bits (their key is
            # GK_INVALID, so relative order among them is irrelevant)
            packed = (
                keys_c.astype(jnp.uint32) * jnp.uint32(rank_space)
                + jnp.minimum(ranks_c, rank_space - 1).astype(jnp.uint32)
            )
            packed_s, perm = jax.lax.sort((packed, iota), num_keys=1)
            keys_s = (packed_s // jnp.uint32(rank_space)).astype(jnp.int32)
        else:
            keys_s, _ranks_s, perm = jax.lax.sort(
                (keys_c, ranks_c, iota), num_keys=2
            )
        slot = perm // B
        pods_s = perm - slot * B
        role_s = role_tab[slot]
        cap_s = caps_c[perm] if needs_caps else None
        before = _seg_scan_tables(
            keys_s, pods_s,
            {
                "match": (role_s == _RB_MATCH).astype(jnp.int32),
                "anti": (role_s == _RB_ANTI).astype(jnp.int32),
                "boot": (role_s == _RB_BOOT).astype(jnp.int32),
                "gmatch": (role_s == _RB_GMATCH).astype(jnp.int32),
                "port": (role_s == _RB_PORT).astype(jnp.int32),
                "pv": (role_s == _RB_PV).astype(jnp.int32),
                "arrive": ((role_s == _RB_MATCH) | (role_s == _RB_SPREAD))
                .astype(jnp.int32),
            },
        )
        ok_e = jnp.ones(keys_s.shape, bool)
        ok_e &= jnp.where(role_s == _RB_ANTI, before["match"] == 0, True)
        ok_e &= jnp.where(role_s == _RB_MATCH, before["anti"] == 0, True)
        ok_e &= jnp.where(
            role_s == _RB_BOOT,
            (before["boot"] == 0) & (before["gmatch"] == 0),
            True,
        )
        if needs_caps:
            # only spread emits carry a cap, and they exist iff a cap
            # column was built — without one no row has _RB_SPREAD
            ok_e &= jnp.where(
                role_s == _RB_SPREAD, before["arrive"] < cap_s, True
            )
        ok_e &= jnp.where(role_s == _RB_PORT, before["port"] == 0, True)
        ok_e &= jnp.where(role_s == _RB_PV, before["pv"] == 0, True)
        ok_e |= keys_s == GK_INVALID
        ok_pod = (
            jnp.ones((B,), jnp.int32).at[pods_s].min(ok_e.astype(jnp.int32))
        )
        return ok_pod > 0

    def one_round(gid, act_v, node_req, ext, passes: int,
                  identity_gid: bool = False):
        """One round over the pods in `gid` (global ids; `act_v` marks
        which rows are genuinely active).

        The round computes plugin masks/scores ONCE, then runs `passes`
        CAPACITY-ONLY acceptance passes: in each pass every
        still-unaccepted pod claims its best node (score re-anchored to
        the in-round node_req) among choices not yet known-dead, claims
        resolve by a (node, rank) capacity prefix, and losers that no
        longer fit the node alone mark the choice dead and fall to their
        next-best node next pass — without waiting a full dyn recompute.
        ONE guard sweep at round end checks every capacity-accepted claim
        for mutual consistency (original ranks decide within a group) and
        REVOKES violators, who retry next round against refreshed
        masks.

        With `shortlist` > 0 the passes run over a per-pod top-k
        candidate SHORTLIST of the round-start scores ([B, k] — top_k is
        one bandwidth-bound read of the scored array, while each wide
        pass re-materialized several [B, N] arrays plus a [B, N]
        dead-scatter). A pod whose entire shortlist dies in-round waits
        for the RESCUE pass: one wide pass, entered via lax.cond only
        when some active pod is mask-feasible but shortlist-exhausted,
        which restores the engine's invariant that a round accepts at
        least one claim whenever any active pod is feasible — so loop
        termination still implies every unplaced pod is infeasible
        against the final state (oracle.validate_rounds_assignment)."""
        B = gid.shape[0]
        if identity_gid:
            # round 0: gid is the identity permutation — indexing with
            # it is not always elided by XLA, and under GSPMD the
            # residual gather all-gathers the full sharded [P, N] base
            vsnap, vmp, vsbase = snap, m_pending, sbase
            vrank, vsels, vovf = rank_g, matched_sels_g, overflow_g
        else:
            vsnap = _pod_view(snap, gid)
            vmp = m_pending[:, gid]
            # static mask+score travel as ONE pre-combined f32 array
            # (score where feasible, NEG_INF where not): compacted
            # rounds pay a single [B, N] row-gather instead of two
            # (~2ms each at 10k x 5k)
            if compact_gather == "onehot":
                oh = jax.nn.one_hot(gid, P, dtype=jnp.float32)  # [B, P]
                vsbase = jnp.matmul(
                    oh, sbase, precision=jax.lax.Precision.HIGHEST
                )
                # with a mesh, pin the compacted view SHARDED: the
                # contraction over the pods axis then lowers to a
                # reduce-scatter of the partitioned [B, N] view instead
                # of all-reducing a replicated one — at the audit shape
                # that single collective was 23.6 MB of the 43.2 MB
                # per-cycle total (AUDIT_SHARDED_r05)
                vsbase = shard_view(vsbase)
            else:
                vsbase = sbase[gid]
            vrank = rank_g[gid]
            vsels = matched_sels_g[gid]
            vovf = overflow_g[gid]
        vsmask = vsbase > NEG_INF * 0.5

        mask, score, _pf = dyn_batched_view_fn(
            vsnap, vmp, node_req, ext, vsmask
        )
        mask = mask & vsmask & act_v[:, None]
        base = vsbase + score  # un-rounded; claim ranking re-rounds with
        # the per-pass anchor delta applied (see score_node_anchor)
        tie = _tie_break(gid, N)
        anchor0 = (
            score_anchor_fn(node_req) if score_anchor_fn is not None else None
        )
        pid = jnp.arange(B, dtype=jnp.int32)
        i = jnp.arange(B, dtype=jnp.int32)
        nom = jnp.clip(vsnap.pod_nominated, 0, N - 1)
        has_nom = vsnap.pod_nominated >= 0

        def resolve_capacity(live, best, node_req):
            """Rank-ordered capacity resolution of one pass's claims
            (sorted segmented prefix vs in-round state): returns
            (accepted bool [B], node_req'). Passes accept on capacity
            ONLY; the guard sweep runs once at round end over all
            capacity-accepted claims and revokes violators — guards are
            ~5% of rejections but the table sort is the dominant
            per-pass cost, so it must not run per pass.

            The (node, rank) sort key is PACKED into one u32 when it
            fits (N+1 node values x a pow2 rank space) — the sorted key
            then carries s_node/s_live for free, so the sort's
            partitioned all-gather moves (key, iota) instead of the old
            (key, iota) + two post-sort [B] row-gathers. Beyond u32
            range (the 100k-pod x 50k-node bench grid: the old
            `best * P + vrank` i32 key silently WRAPPED there) a 2-key
            sort keeps exact lexicographic order at any scale."""
            rank_space = 1 << int(P - 1).bit_length()  # ranks are < P
            nkey = jnp.where(live, best, N).astype(jnp.uint32)
            rkey = jnp.minimum(vrank, rank_space - 1).astype(jnp.uint32)
            # minimal index width: the permutation operand rides the
            # sort's partitioned all-gather (i16 halves it when B fits)
            bidx = jnp.arange(B, dtype=argsel.index_dtype(B))
            if (N + 1) * rank_space <= 2**32:
                packed = nkey * jnp.uint32(rank_space) + rkey
                packed_s, order = jax.lax.sort(
                    (packed, bidx), num_keys=1
                )
                s_node = (packed_s // jnp.uint32(rank_space)).astype(
                    jnp.int32
                )
            else:
                s_nkey, _s_rkey, order = jax.lax.sort(
                    (nkey, rkey, bidx), num_keys=2
                )
                s_node = s_nkey.astype(jnp.int32)
            s_live = s_node < N  # live claims carry a real node id
            s_req = jnp.where(
                s_live[:, None], vsnap.pod_requested[order], 0.0
            )
            cum = jnp.cumsum(s_req, axis=0)
            before = cum - s_req
            seg_start = jnp.concatenate(  # schedlint: disable=SH002 -- s_node is lax.sort output, which GSPMD materializes replicated here (the sort's all-gather is the audited claim_sort payload); the shard-invariance suite pins this bit-exact at devices 1-8
                [jnp.ones((1,), bool), s_node[1:] != s_node[:-1]]
            )
            seg_first = jax.lax.cummax(jnp.where(seg_start, i, -1))
            seg_before = before - before[seg_first]
            nsafe = jnp.clip(s_node, 0, N - 1)
            free = (
                snap.node_allocatable[nsafe] - node_req[nsafe]
                + slack[nsafe]
            )
            fits = jnp.all(seg_before + s_req <= free, axis=1) & s_live
            accepted_t = jnp.zeros((B,), bool).at[order].set(fits)
            node_of_t = jnp.where(accepted_t, best, 0)
            req_add = jnp.where(
                accepted_t[:, None], vsnap.pod_requested, 0.0
            )
            # one-hot matmul instead of scatter-add: 0.27 vs 1.14 ms at
            # B=10k (probe_shortlist_prims) and this runs once per pass;
            # 0/1 x f32 products are exact, accumulation order differs
            # from a sequential scatter only in fp summation order
            oh = jax.nn.one_hot(node_of_t, N, dtype=jnp.float32)
            node_req = node_req + jnp.matmul(
                oh.T, req_add, precision=jax.lax.Precision.HIGHEST
            )
            return accepted_t, node_req

        def fits_alone_at(best, node_req):
            # A capacity loser keeps the node alive if it still fits
            # ALONE in the node's post-pass free space: the segmented
            # prefix charges REJECTED earlier-rank claims too (a huge
            # non-fitting claim shadows smaller ones behind it), so such
            # losers retry next pass once the contenders settle.
            bsafe = jnp.clip(best, 0, N - 1)
            return jnp.all(
                vsnap.pod_requested
                <= snap.node_allocatable[bsafe] - node_req[bsafe]
                + slack[bsafe],
                axis=1,
            )

        def pick_overflow(has, acc, normal):
            # Overflow claimants (matching more guard-active selectors
            # than the MS_MATCH table tracks) are invisible to other
            # claims' guard checks, so one may only be accepted in a
            # round that accepts NOTHING else: lowest rank, alone, iff
            # the round is still empty-handed.
            allow_ovf = ~jnp.any(acc) & ~jnp.any(normal)
            ovf_rank = jnp.min(jnp.where(has & vovf, vrank, _BIG))
            return has & vovf & (vrank == ovf_rank) & allow_ovf

        acc = jnp.zeros((B,), bool)
        acc_node = jnp.full((B,), -1, jnp.int32)
        diag = jnp.zeros((3,), jnp.int32)
        use_sl = 0 < shortlist < N

        if use_sl:
            k = shortlist
            scored0 = jnp.where(mask, jnp.round(base) + tie, NEG_INF)
            # shard-invariant top_k (ops/argsel.py): equal-score entries
            # keep the lowest-index-first order at ANY device count —
            # lax.top_k's partitioned form merges ties shard-locally
            vals, sl = argsel.top_k_first(scored0, k)  # [B, k]
            # the nominated node (post-preemption) must be claimable even
            # when outside the top-k: force it into the last column (and
            # NEG_INF any earlier duplicate so a dead node is not offered
            # twice)
            nom_val = jnp.take_along_axis(scored0, nom[:, None], 1)[:, 0]
            vals = jnp.where(
                has_nom[:, None] & (sl == nom[:, None]), NEG_INF, vals
            )
            sl = sl.at[:, k - 1].set(jnp.where(has_nom, nom, sl[:, k - 1]))
            vals = vals.at[:, k - 1].set(
                jnp.where(has_nom, nom_val, vals[:, k - 1])
            )
            sl_ok = vals > NEG_INF * 0.5
            dead = jnp.zeros((B, k), bool)
            # the [B*k] anchor-delta gather is ~1.7 ms at B=10k;
            # anchor_stride > 1 trades acceptance for that gather (one
            # pass of staleness ages the spread signal — measured -19%
            # round-0 acceptance at stride 2)
            delta_stride = max(1, anchor_stride)
            dsl = jnp.zeros((B, k), jnp.float32)
            for t in range(passes):
                avail = sl_ok & ~dead & ~acc[:, None]
                if anchor0 is not None and t > 0:
                    # nodes that filled this round lose attractiveness
                    # NOW — the spread mechanism sequential scheduling
                    # gets from per-pod score freshness; the delta rides
                    # a [B*k] gather from the [N] anchor vector
                    if (t - 1) % delta_stride == 0:
                        delta = jnp.round(
                            score_anchor_fn(node_req) - anchor0
                        )
                        dsl = delta[sl.reshape(-1)].reshape(B, k)
                    eff = jnp.where(avail, vals + dsl, NEG_INF)
                else:
                    eff = jnp.where(avail, vals, NEG_INF)
                bj = argsel.argmax_first(eff, axis=1)
                nom_ok = has_nom & avail[:, k - 1]
                bj = jnp.where(nom_ok, k - 1, bj)
                best = jnp.take_along_axis(sl, bj[:, None], 1)[:, 0]
                has = (
                    jnp.take_along_axis(avail, bj[:, None], 1)[:, 0]
                    & act_v & vsnap.pod_valid & ~acc
                )
                normal = has & ~vovf
                ovf_pick = (
                    pick_overflow(has, acc, normal)
                    if t == passes - 1
                    else jnp.zeros_like(normal)
                )
                live = normal | ovf_pick
                accepted_t, node_req = resolve_capacity(live, best,
                                                        node_req)
                acc = acc | accepted_t
                acc_node = jnp.where(accepted_t, best, acc_node)
                dead = dead.at[pid, bj].max(
                    live & ~accepted_t & ~fits_alone_at(best, node_req)
                )
                diag = diag + jnp.stack([
                    jnp.sum(live, dtype=jnp.int32),
                    jnp.sum(live & ~accepted_t, dtype=jnp.int32),
                    jnp.zeros((), jnp.int32),
                ])

            # ---- rescue pass (shortlist-exhaustion escape hatch) ----
            # Runs only when some active pod is feasible by this round's
            # mask yet has no live shortlist entry left; one wide pass
            # over the full mask for exactly those pods. Guarantees a
            # zero-accept round implies every active pod's mask was
            # empty — the invariant the validity checker relies on.
            feas0 = jnp.any(mask, axis=1)
            exhausted = (
                act_v & vsnap.pod_valid & ~acc & feas0
                & ~jnp.any(sl_ok & ~dead, axis=1)
            )

            def rescue(op):
                acc, acc_node, node_req, diag = op
                if anchor0 is not None:
                    delta = score_anchor_fn(node_req) - anchor0
                    scored = jnp.round(base + delta[None, :]) + tie
                else:
                    scored = jnp.round(base) + tie
                avail = mask & ~acc[:, None]
                eff = jnp.where(avail, scored, NEG_INF)
                best = argsel.argmax_first(eff, axis=1)
                r_nom_ok = has_nom & avail[pid, nom]
                best = jnp.where(r_nom_ok, nom, best)
                has = avail[pid, best] & exhausted
                normal = has & ~vovf
                ovf_pick = pick_overflow(has, acc, normal)
                live = normal | ovf_pick
                accepted_t, node_req = resolve_capacity(live, best,
                                                        node_req)
                acc = acc | accepted_t
                acc_node = jnp.where(accepted_t, best, acc_node)
                diag = diag + jnp.stack([
                    jnp.sum(live, dtype=jnp.int32),
                    jnp.sum(live & ~accepted_t, dtype=jnp.int32),
                    jnp.zeros((), jnp.int32),
                ])
                return acc, acc_node, node_req, diag

            acc, acc_node, node_req, diag = jax.lax.cond(
                jnp.any(exhausted), rescue, lambda op: op,
                (acc, acc_node, node_req, diag),
            )
        else:
            dead = jnp.zeros((B, N), bool)
            for t in range(passes):
                avail = mask & ~dead & ~acc[:, None]
                if anchor0 is not None and t > 0:
                    # nodes that filled this round lose attractiveness
                    # NOW — the spread mechanism sequential scheduling
                    # gets from per-pod score freshness
                    delta = score_anchor_fn(node_req) - anchor0  # [N]
                    scored = jnp.round(base + delta[None, :]) + tie
                else:
                    scored = jnp.round(base) + tie
                eff_t = jnp.where(avail, scored, NEG_INF)
                nom_ok = has_nom & avail[pid, nom]
                # argmax_first (ops/argsel.py): lowest-index tie-break
                # survives a sharded nodes axis — the shard-exactness
                # contract (sharded == replicated placements bit-
                # identically, test_dryrun_multichip_8)
                best = jnp.where(
                    nom_ok, nom, argsel.argmax_first(eff_t, axis=1)
                ).astype(jnp.int32)
                has = avail[pid, best] & act_v & vsnap.pod_valid & ~acc
                normal = has & ~vovf
                ovf_pick = (
                    pick_overflow(has, acc, normal)
                    if t == passes - 1
                    else jnp.zeros_like(normal)
                )
                live = normal | ovf_pick
                accepted_t, node_req = resolve_capacity(live, best,
                                                        node_req)
                acc = acc | accepted_t
                acc_node = jnp.where(accepted_t, best, acc_node)
                dead = dead.at[pid, best].max(
                    live & ~accepted_t & ~fits_alone_at(best, node_req)
                )
                diag = diag + jnp.stack([
                    jnp.sum(live, dtype=jnp.int32),
                    jnp.sum(live & ~accepted_t, dtype=jnp.int32),
                    jnp.zeros((), jnp.int32),
                ])

        # ---- round-end guard sweep over ALL capacity-accepted claims ----
        # Revoking a violator leaves node_req slightly over-charged for
        # claims accepted after it this round — those stay valid (the node
        # is merely LESS full than they assumed). Revoked pods retry next
        # round; persistent violations (anti slot held by the winner) are
        # then excluded by the refreshed dyn masks.
        g_ok = guards_ok(vsnap, vrank, vsels, acc_node, acc, ext)
        revoked = acc & ~g_ok
        node_req = node_req.at[jnp.where(revoked, acc_node, 0)].add(
            jnp.where(revoked[:, None], -vsnap.pod_requested, 0.0)
        )
        acc = acc & g_ok
        acc_node = jnp.where(acc, acc_node, -1)
        diag = diag + jnp.stack([
            jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.int32),
            jnp.sum(revoked, dtype=jnp.int32),
        ])

        ext = local_update_fn(update_batched_view_fn)(
            vsnap, vmp, ext, acc, jnp.where(acc, acc_node, 0)
        )
        return acc, acc_node, node_req, ext, diag

    # ---- round 1: full pending set ----
    gid0 = jnp.arange(P, dtype=jnp.int32)
    acc0, node0, node_req, extra, diag0 = one_round(
        gid0, snap.pod_valid, snap.node_requested, extra, passes_round0,
        identity_gid=True,
    )
    placed = jnp.where(acc0, node0, -1)
    active = snap.pod_valid & ~acc0
    acc_hist = jnp.zeros((max_rounds,), jnp.int32).at[0].set(
        jnp.sum(acc0, dtype=jnp.int32)
    )
    diag_hist = jnp.zeros((max_rounds, 3), jnp.int32).at[0].set(diag0)

    # ---- rounds 2+: compacted to the lowest-rank actives ----
    # The window holds the B lowest-rank actives. A zero-accept round
    # must NOT terminate the loop while actives remain beyond the
    # window (they may be feasible — the windowed pods can all be
    # stuck on constraints while a higher-rank pod would place; caught
    # by the 500x100 mid-size differential, invisible to <=B-pod toy
    # cases): instead the window ADVANCES by B over the rank order
    # (`skip`). State provably does not change during a zero-accept
    # round (no accepts => no node_req/extra updates, and revocations
    # only touch same-round accepts), so a full zero-accept sweep gives
    # every active pod a genuine full-mask check against what is then
    # the final state — the validity invariant "unplaced => infeasible"
    # holds exactly. Any acceptance resets the sweep to the lowest
    # ranks.
    B = compact_window(P, compact)

    def body(carry):
        node_req, ext, placed, active, rnd, skip, hist, dhist = carry
        key = jnp.where(active, rank_g, _BIG)
        order = jnp.argsort(key).astype(jnp.int32)
        start = jnp.minimum(skip, jnp.maximum(P - B, 0))
        gid = jax.lax.dynamic_slice(order, (start,), (B,))
        act_v = active[gid]
        accepted, node_of, node_req, ext, diag = one_round(
            gid, act_v, node_req, ext, passes
        )
        placed = placed.at[gid].set(jnp.where(accepted, node_of, placed[gid]))
        active = active.at[gid].set(act_v & ~accepted)
        n_acc = jnp.sum(accepted, dtype=jnp.int32)
        hist = hist.at[jnp.minimum(rnd, max_rounds - 1)].set(n_acc)
        dhist = dhist.at[jnp.minimum(rnd, max_rounds - 1)].set(diag)
        skip = jnp.where(n_acc > 0, jnp.int32(0), skip + jnp.int32(B))
        return (node_req, ext, placed, active, rnd + 1, skip, hist,
                dhist)

    def cond(carry):
        _, _, _, active, rnd, skip, _, _ = carry
        n_act = jnp.sum(active, dtype=jnp.int32)
        return (skip < n_act) & (rnd < max_rounds)

    # round 0 was full-width: if it accepted nothing, every pod already
    # had its full-mask check and the sweep is complete (skip = P)
    skip0 = jnp.where(jnp.any(acc0), jnp.int32(0), jnp.int32(P))
    node_req, extra, placed, active, rounds_used, _, acc_hist, diag_hist = (
        jax.lax.while_loop(
            cond, body,
            (node_req, extra, placed, active, jnp.int32(1), skip0,
             acc_hist, diag_hist),
        )
    )

    return RoundsResult(
        assignment=placed,
        node_requested=node_req,
        extra=extra,
        rounds_used=rounds_used,
        accepted_per_round=acc_hist,
        diag_per_round=diag_hist,
    )
