"""NodePorts filter kernel.

Reference: `framework/plugins/nodeports/` ([UNVERIFIED], mount empty) —
a pod requesting hostPorts is infeasible on nodes where any requested
(port, protocol) is already in use. Ports are encoded as port*4+protocol
ints (models/encoding.py), so the check is set-disjointness of small padded
int lists, evaluated blockwise over the pods axis to bound the [P, N,
MPp, MUP] intermediate.

This mask covers ports used by EXISTING pods; pods claiming the same host
port within one pending batch are handled exactly by the commit scan's
[N, Q] port-claim bitmap (framework/plugins.py NodePorts.extra_*), matching
the reference's sequential NodeInfo updates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ports_conflict_mask(
    pod_ports: jnp.ndarray,  # i32 [P, MPp] (-1 pad)
    node_used_ports: jnp.ndarray,  # i32 [N, MUP] (-1 pad)
    block: int = 512,
) -> jnp.ndarray:  # bool [P, N] — True = conflict (infeasible)
    P = pod_ports.shape[0]
    nblocks = max(P // block, 1)
    if P % block != 0:
        # padded P is a power of two / 128-multiple; fall back to one block
        nblocks, block_ = 1, P
    else:
        block_ = block

    blocks = pod_ports.reshape(nblocks, block_, -1)

    def one(pp):  # [B, MPp]
        eq = (
            (pp[:, None, :, None] == node_used_ports[None, :, None, :])
            & (pp >= 0)[:, None, :, None]
            & (node_used_ports >= 0)[None, :, None, :]
        )
        return eq.any((2, 3))  # [B, N]

    return jax.lax.map(one, blocks).reshape(P, -1)
