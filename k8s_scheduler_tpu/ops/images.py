"""ImageLocality score kernel.

Reference: `framework/plugins/imagelocality/` ([UNVERIFIED], mount empty) —
nodes already holding a pod's container images score higher, scaled by image
size (ramp between 23MB and 1GB) and by how widely the image is spread
across nodes.

TPU-native design: pods' image sets are deduplicated ([Is] distinct sets);
the per-(imageset, node) total-present-bytes matrix is ONE matmul
node_images[N, I] @ weighted_sizes[Is, I]^T — an MXU op — followed by the
ramp and a per-pod gather.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import labels as labels_ops

_MIN_IMG = 23.0 * 2**20  # minThreshold: images below this don't move the score
_MAX_IMG = 1.0 * 2**30  # maxThreshold: cap per upstream maxContainerThreshold


def image_locality_score(snap) -> jnp.ndarray:  # f32 [P, N] in [0, 100]
    node_imgs = snap.node_images.astype(jnp.float32)  # [N, I]
    # spread factor: fraction of (real) nodes having each image — an image
    # everywhere contributes fully, a rare image is discounted (upstream
    # scaledImageScore), preventing stampedes onto one warm node.
    n_real = jnp.maximum(
        jnp.sum(snap.node_valid.astype(jnp.float32)), 1.0
    )
    spread = jnp.sum(
        node_imgs * snap.node_valid[:, None].astype(jnp.float32), axis=0
    ) / n_real  # [I]
    weighted = snap.imgset_sizes * spread[None, :]  # [Is, I]
    have = node_imgs @ weighted.T  # [N, Is]  (MXU)
    clipped = jnp.clip(have, _MIN_IMG, _MAX_IMG)
    table = (clipped - _MIN_IMG) / (_MAX_IMG - _MIN_IMG) * 100.0  # [N, Is]
    # per-pod pick as a one-hot MXU matmul (row-gathers are slow here)
    return labels_ops.take_rows(table.T, snap.pod_imageset, 0.0)  # [P, N]
