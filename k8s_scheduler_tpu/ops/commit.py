"""Greedy sequential-commit pass: the batched equivalent of ScheduleOne.

The reference schedules ONE pod per `ScheduleOne` iteration: pop the
highest-priority pod, filter+score nodes, pick the max, assume it in the
cache so the next pod sees reduced capacity (SURVEY.md §3.2 — expected
`schedule_one.go`/`generic_scheduler.go`, [UNVERIFIED], mount empty). The
TPU design batches a whole pending set per cycle but must preserve those
sequential-commit semantics: pods earlier in priority order constrain later
ones (SURVEY.md §7 "hard parts" (a)).

This is a `lax.scan` over the priority-ordered pending set. Everything that
does NOT depend on in-cycle commitments (label/taint/affinity-vs-existing
masks, static scores) is precomputed batched [P, N] outside the scan; the
scan body only evaluates the dynamic residue — resource fit against the
running allocatable matrix plus caller-provided hooks (running
topology-domain counts for inter-pod affinity / topology spread arrive via
`dyn_fn`/`update_fn`). Each step is O(N) vector work, so the whole commit is
O(P*N) — the same work one Filter pass does in the reference, but fused into
one XLA while-loop on device.

Tie-breaking: upstream `selectHost` breaks score ties with reservoir
sampling; we take the lowest node index (deterministic — the differential
oracle does the same).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from . import argsel

NEG_INF = -1e9

# dyn_fn(pod_idx, node_requested [N,R], extra, static_row [N] bool)
#   -> (full feasibility mask [N] bool, score [N] f32)
#   or (mask, score, aux) — aux is any pytree emitted per step (e.g.
#   per-filter reject counts for failure attribution); stacked over the
#   pod axis into CommitResult.dyn_aux
# The static row is passed IN so score hooks that normalize across nodes
# (inter-pod affinity, topology spread) can normalize over feasible nodes
# only, like upstream NormalizeScore running after Filter.
DynFn = Callable[
    [jnp.ndarray, jnp.ndarray, Any, jnp.ndarray],
    tuple[jnp.ndarray, jnp.ndarray],
]
# update_fn(extra, pod_idx, node_idx, committed) -> extra
UpdateFn = Callable[[Any, jnp.ndarray, jnp.ndarray, jnp.ndarray], Any]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CommitResult:
    assignment: jnp.ndarray  # i32 [P] node index or -1
    node_requested: jnp.ndarray  # f32 [N, R] post-commit
    extra: Any  # final hook state (e.g. running domain counts)
    dyn_aux: Any = None  # per-pod stacked dyn_fn aux (None w/ 2-tuple dyn_fn)


def greedy_commit(
    *,
    order: jnp.ndarray,  # i32 [P]: pod index scheduled at each rank
    static_mask: jnp.ndarray,  # bool [P, N]
    static_score: jnp.ndarray,  # f32 [P, N]
    pod_requested: jnp.ndarray,  # f32 [P, R]
    pod_valid: jnp.ndarray,  # bool [P]
    pod_nominated: jnp.ndarray,  # i32 [P] node index (-1 none)
    node_allocatable: jnp.ndarray,  # f32 [N, R]
    node_requested: jnp.ndarray,  # f32 [N, R] at cycle start
    dyn_fn: DynFn,
    extra: Any = None,
    update_fn: UpdateFn | None = None,
) -> CommitResult:
    P, N = static_mask.shape

    def step(carry, rank):
        node_req, ext = carry
        p = order[rank]
        out = dyn_fn(p, node_req, ext, static_mask[p])
        feasible, dyn_score = out[0], out[1]
        aux = out[2] if len(out) > 2 else jnp.int32(0)
        # dyn_fn is expected to fold the static row in (it needs it for
        # normalize-over-feasible scoring); AND it again here so a dyn_fn
        # that ignores its 4th arg can never bypass static filters
        feasible = feasible & static_mask[p]
        score = jnp.where(feasible, static_score[p] + dyn_score, NEG_INF)
        # A nominated node (set by a previous preemption) is honored when
        # feasible, regardless of score — upstream evaluates the nominated
        # node first and keeps it if it passes filters.
        nom = jnp.clip(pod_nominated[p], 0, N - 1)
        nom_ok = (pod_nominated[p] >= 0) & feasible[nom]
        # lowest-index tie-break that survives a sharded nodes axis
        # (ops/argsel.py) — identical to argmax on a single device
        best = jnp.where(
            nom_ok, nom, argsel.argmax_first(score, axis=0)
        ).astype(jnp.int32)
        ok = feasible[best] & pod_valid[p]
        node = jnp.where(ok, best, jnp.int32(-1))
        node_req = node_req.at[best].add(
            jnp.where(ok, pod_requested[p], 0.0)
        )
        if update_fn is not None:
            ext = update_fn(ext, p, best, ok)
        return (node_req, ext), (p, node, aux)

    (node_req_final, extra_final), (pods, assigned, auxs) = jax.lax.scan(
        step, (node_requested, extra), jnp.arange(P, dtype=jnp.int32)
    )
    assignment = jnp.zeros(P, jnp.int32).at[pods].set(assigned)
    # ys arrive in rank order; re-scatter to pod order like `assignment`
    dyn_aux = jax.tree_util.tree_map(
        lambda a: jnp.zeros_like(a).at[pods].set(a), auxs
    )
    return CommitResult(assignment, node_req_final, extra_final, dyn_aux)


def unwind_assignments(
    result: CommitResult,
    drop: jnp.ndarray,  # bool [P] — assignments to roll back (e.g. gang fail)
    pod_requested: jnp.ndarray,  # f32 [P, R]
) -> CommitResult:
    """Roll back a subset of commitments (all-or-nothing gang semantics:
    a group that did not fully place releases its members' capacity and the
    pods go back to the queue — upstream Permit-timeout behaviour)."""
    P, _ = pod_requested.shape
    assigned = result.assignment >= 0
    undo = drop & assigned
    node_req = result.node_requested
    # scatter-subtract each dropped pod's request from its node
    idx = jnp.clip(result.assignment, 0, node_req.shape[0] - 1)
    node_req = node_req.at[idx].add(
        jnp.where(undo[:, None], -pod_requested, 0.0)
    )
    assignment = jnp.where(undo, -1, result.assignment)
    return CommitResult(assignment, node_req, result.extra, result.dyn_aux)
