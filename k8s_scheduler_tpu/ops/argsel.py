"""Shard-invariant selection primitives (ISSUE 10 / ROADMAP item 3).

`jnp.argmax`/`jax.lax.top_k` break score ties by LOWEST INDEX on a
single device, but under GSPMD a reduce over a *sharded* axis lowers to
a per-shard partial reduce plus a cross-shard (value, index) combiner
whose tie order is an implementation detail of the chosen partitioning
strategy — equal-valued entries can merge in shard-local order, so the
same program picks DIFFERENT (equally good) nodes at different device
counts (`test_dryrun_multichip_8`'s historical divergence: every
divergent pod landed on an equal-score node).

The fix is structural, not a tweak to the combiner: never present a tie
to a partitioned reduce. Each helper here decomposes the selection into
reductions that are order-invariant by algebra (max, min over distinct
integers) or into a comparator that is already a total order (a 2-key
sort whose second key is the index), so the result is bit-identical at
ANY device count — and identical to the single-device numpy semantics
("first occurrence of the max"), which is why swapping these in changes
nothing on the replicated path.

Every partitioned claim-path reduce in ops/rounds.py, ops/commit.py and
ops/preemption.py routes through this module; a new argmax/top_k over a
potentially-sharded axis should too.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def argmax_first(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Index of the FIRST maximum along `axis` (i32), shard-invariant.

    max() is order-invariant (no rounding, associative+commutative), and
    the follow-up min() runs over distinct integer indices — so neither
    reduce can merge ties shard-locally. Bit-identical to jnp.argmax on
    one device (numpy's first-occurrence rule) and at every shard count.
    Two cheap reduces replace one (value, index) tuple-reduce; under a
    sharded axis the cross-shard payload is a scalar-per-row f32 + s32
    instead of the tuple combiner's pairs.
    """
    ax = axis if axis >= 0 else x.ndim + axis
    n = x.shape[ax]
    m = jnp.max(x, axis=ax, keepdims=True)
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, ax)
    return jnp.min(jnp.where(x == m, idx, jnp.int32(n)), axis=ax)


def top_k_first(x: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """`jax.lax.top_k` over the last axis with shard-invariant tie
    order: (values [., k], indices [., k]), ties resolved lowest-index
    first — exactly top_k's documented single-device order.

    Implemented as a 2-key `lax.sort` (descending value, ascending
    index): the comparator is a TOTAL order, so the sorted sequence is
    unique regardless of how XLA partitions the sort. Costs a full sort
    of the axis instead of a selection — acceptable for the shortlist
    path, whose per-round top_k was already the dominant term at the
    geometry where it is enabled (see ops/rounds.py `shortlist`). The
    index operand rides the sort at the minimal width the axis extent
    allows (the collective-payload diet: a partitioned sort all-gathers
    its operands); the returned indices are widened back to i32.
    """
    n = x.shape[-1]
    iota = jax.lax.broadcasted_iota(index_dtype(n), x.shape, x.ndim - 1)
    neg, idx = jax.lax.sort((-x, iota), dimension=x.ndim - 1, num_keys=2)
    take = (slice(None),) * (x.ndim - 1) + (slice(0, k),)
    return -neg[take], idx[take].astype(jnp.int32)


def index_dtype(n: int):
    """Minimal sortable index dtype addressing `n` values — the
    collective-payload diet's "claim-sort index width": a sorted-iota
    permutation operand rides every partitioned sort's all-gather, and
    half-width indices halve that payload where the extent allows."""
    return jnp.int16 if n <= 2**15 - 1 else jnp.int32
