"""VolumeBinding filter kernel (SURVEY.md §2 C7).

The reference's VolumeBinding plugin (expected
`framework/plugins/volumebinding/` — [UNVERIFIED], mount empty) decides,
per pod per node, whether the pod's PVCs can be satisfied there:

  - a BOUND PVC restricts the pod to nodes satisfying its PV's
    nodeAffinity (zone/hostname-restricted volumes);
  - an UNBOUND WaitForFirstConsumer PVC needs either an available static
    PV (class + capacity + nodeAffinity match) or dynamic provisioning
    whose storage-class allowedTopologies admit the node;
  - a missing PVC or an unbound Immediate-mode PVC makes the pod
    unschedulable (upstream UnschedulableAndUnresolvable).

TPU-native shape: PV nodeAffinity terms compile through the SAME
requirement machinery as pod node-affinity (encoder interns them into
`rq_exprs`), so the per-PV node masks are rows of the shared [Rq, N]
requirement table. The static-candidate test batches into one
[P*MVol, V] x [V, N] matmul; everything is gated on the `has_volumes`
capability flag, so volume-free clusters never trace any of it.

Same-cycle contention for one static PV IS arbitrated in-cycle
(VERDICT r2 item 8): the VolumeBinding plugin carries a `pv_claimed`
bitmap through the commit engines' extra state — a placed pod claims its
chosen PV (lowest-index compatible, upstream's deterministic binder
choice), later pods in the cycle see the PV as unavailable, and the
rounds engine's participant table additionally resolves SAME-ROUND
claimants of one PV by rank (`_RB_PV`). Dynamic provisioning is
unlimited and needs no arbitration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import labels as labels_ops

_CAP_EPS = 1e-3


def pv_node_table(snap, expr_mask):  # bool [V, N]
    """Per-PV node admissibility (nodeAffinity through the shared
    requirement table) AND pre-cycle availability."""
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]
    return (
        labels_ops.take_rows(req, snap.pv_req_id, True)
        & snap.pv_avail[:, None]
    )


def pod_pv_cand(snap, j):  # bool [P, V] class+size candidacy for slot j
    cls = snap.pod_vol_class[:, j]
    size = snap.pod_vol_size[:, j]
    return (
        (snap.pv_class[None, :] == cls[:, None])
        & (snap.pv_capacity[None, :] + _CAP_EPS >= size[:, None])
        & (snap.pod_vol_mode[:, j] == 1)[:, None]
    )


def volume_mask(snap, expr_mask: jnp.ndarray,
                pv_claimed: jnp.ndarray | None = None) -> jnp.ndarray:
    """Conjunction over each pod's PVC constraints -> bool [P, N].
    `pv_claimed` (bool [V]) marks static PVs already claimed by this
    cycle's placements; None = pre-cycle availability only (the static
    phase — the commit engines re-run the unbound-slot part per round
    with the live bitmap via VolumeBinding.dyn_mask*)."""
    P, N = snap.P, snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]

    def req_rows(ids):  # i32 [X] -> bool [X, N]; id < 0 -> all-True
        return labels_ops.take_rows(req, ids, True)

    pv_ok = req_rows(snap.pv_req_id) & snap.pv_avail[:, None]  # [V, N]
    if pv_claimed is not None:
        pv_ok = pv_ok & ~pv_claimed[:, None]
    MVol = snap.pod_vol_mode.shape[1]

    ok = jnp.ones((P, N), bool)
    for j in range(MVol):
        mode = snap.pod_vol_mode[:, j]  # [P]
        rid = snap.pod_vol_req[:, j]

        rid_rows = req_rows(rid)  # [P, N] (bound PV affinity / dyn topology)

        # static candidates: available PVs of the right class and size,
        # usable on the node
        cand = pod_pv_cand(snap, j)  # [P, V]
        static_ok = (
            cand.astype(jnp.float32) @ pv_ok.astype(jnp.float32)
        ) > 0.0  # [P, N]

        dyn_ok = jnp.where(
            (rid == -2)[:, None], False, rid_rows
        )  # -1 folds to all-True via req_rows
        row_ok = jnp.where(
            (mode == 0)[:, None],
            rid_rows,
            jnp.where((mode == 1)[:, None], static_ok | dyn_ok, False),
        )
        ok &= jnp.where((mode >= 0)[:, None], row_ok, True)
    return ok


def volume_mask_unbound(snap, expr_mask, pv_claimed) -> jnp.ndarray:
    """The CLAIM-dependent residue of volume_mask: only unbound
    WaitForFirstConsumer slots (mode==1) re-evaluate against the live
    `pv_claimed` bitmap; everything else (bound-PV affinity, missing
    PVCs) is claim-independent and already in the static mask."""
    P, N = snap.P, snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)
    pv_ok = (
        labels_ops.take_rows(req, snap.pv_req_id, True)
        & snap.pv_avail[:, None]
        & ~pv_claimed[:, None]
    )  # [V, N]
    MVol = snap.pod_vol_mode.shape[1]
    ok = jnp.ones((P, N), bool)
    for j in range(MVol):
        mode = snap.pod_vol_mode[:, j]
        rid = snap.pod_vol_req[:, j]
        static_ok = (
            pod_pv_cand(snap, j).astype(jnp.float32)
            @ pv_ok.astype(jnp.float32)
        ) > 0.0
        dyn_ok = jnp.where(
            (rid == -2)[:, None], False,
            labels_ops.take_rows(req, rid, True),
        )
        ok &= jnp.where((mode == 1)[:, None], static_ok | dyn_ok, True)
    return ok


def volume_mask_unbound_row(snap, expr_mask, pv_claimed, p):
    """Single-pod row of volume_mask_unbound (bool [N]) — the scan
    engine's per-step hook; the batched form would redo [P, N] work at
    every one of P scan steps."""
    N = snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)
    pv_ok = (
        labels_ops.take_rows(req, snap.pv_req_id, True)
        & snap.pv_avail[:, None]
        & ~pv_claimed[:, None]
    )  # [V, N]
    Rq = req.shape[0]
    MVol = snap.pod_vol_mode.shape[1]
    ok = jnp.ones((N,), bool)
    for j in range(MVol):
        mode = snap.pod_vol_mode[p, j]
        rid = snap.pod_vol_req[p, j]
        cand = (
            (snap.pv_class == snap.pod_vol_class[p, j])
            & (snap.pv_capacity + _CAP_EPS >= snap.pod_vol_size[p, j])
            & (mode == 1)
        )  # [V]
        static_ok = jnp.any(cand[:, None] & pv_ok, axis=0)  # [N]
        rid_row = jnp.where(
            rid >= 0, req[jnp.clip(rid, 0, Rq - 1)], True
        )
        dyn_ok = jnp.where(rid == -2, False, rid_row)
        ok &= jnp.where(mode == 1, static_ok | dyn_ok, True)
    return ok


def chosen_pv_row(snap, expr_mask, pv_claimed, node, p, j):
    """Scalar chosen_pv for one pod at one node (the scan engine's
    per-step claim): i32 [] PV index or -1."""
    V = snap.pv_avail.shape[0]
    pv_ok_n = (
        pv_node_table(snap, expr_mask)[:, jnp.clip(node, 0, snap.N - 1)]
        & ~pv_claimed
    )  # [V]
    cand = (
        (snap.pv_class == snap.pod_vol_class[p, j])
        & (snap.pv_capacity + _CAP_EPS >= snap.pod_vol_size[p, j])
        & (snap.pod_vol_mode[p, j] == 1)
        & pv_ok_n
    )
    idx = jnp.where(cand, jnp.arange(V, dtype=jnp.int32), V)
    best = jnp.min(idx).astype(jnp.int32)
    return jnp.where(best < V, best, -1)


def fold_pv_claims(snap, expr_mask, pv_claimed, accepted, node_of,
                   rank):
    """Fold a BATCH of placements' static-PV claims into `pv_claimed`
    exactly as a rank-ordered sequential pass would: iterate — each pass
    every unresolved claimant picks its lowest-index compatible
    unclaimed PV, and only the LOWEST-RANK claimant per contended PV
    claims it; losers retry against the updated bitmap. Terminates in at
    most V passes (each pass claims >= 1 PV or nothing changes); when
    the batch is known claim-disjoint (the rounds engine's _RB_PV guard
    guarantees it) the loop exits after one pass."""
    V = snap.pv_avail.shape[0]
    P = accepted.shape[0]
    MVol = snap.pod_vol_mode.shape[1]
    big = jnp.int32(2**31 - 1)

    def body(carry):
        claimed, pending_slots, _progress = carry
        progress = jnp.zeros((), bool)
        for j in range(MVol):
            ch = chosen_pv(
                snap, expr_mask, claimed, node_of,
                pending_slots[:, j], j,
            )  # [P]
            has = ch >= 0
            chc = jnp.clip(ch, 0, V - 1)
            # lowest rank per chosen PV wins this pass
            winner_rank = (
                jnp.full((V,), big).at[chc].min(
                    jnp.where(has, rank, big)
                )
            )
            won = has & (rank == winner_rank[chc])
            claimed = claimed.at[chc].max(won)
            # winners' slots resolve; losers retry next pass
            pending_slots = pending_slots.at[:, j].set(
                pending_slots[:, j] & ~won & has
            )
            progress = progress | jnp.any(won)
        return claimed, pending_slots, progress

    def cond(carry):
        _, pending_slots, progress = carry
        return progress & jnp.any(pending_slots)

    init_slots = jnp.broadcast_to(accepted[:, None], (P, MVol)) & (
        snap.pod_vol_mode == 1
    )
    claimed, _, _ = jax.lax.while_loop(
        cond,
        body,
        body((pv_claimed, init_slots, jnp.ones((), bool))),
    )
    return claimed


def chosen_pv(snap, expr_mask, pv_claimed, node_of, active, j):
    """i32 [P]: the PV each active pod would claim for volume slot j at
    node `node_of` — the LOWEST-INDEX compatible available unclaimed PV
    admissible on that node (the deterministic binder choice both
    engines and the oracle share); -1 when the slot is not an unbound
    static claim (incl. pods whose slot rides dynamic provisioning
    because no static PV fits)."""
    V = snap.pv_avail.shape[0]
    pv_ok = (
        pv_node_table(snap, expr_mask) & ~pv_claimed[:, None]
    )  # [V, N]
    nsafe = jnp.clip(node_of, 0, snap.N - 1)
    at_node = pv_ok[:, nsafe].T  # [P, V]
    cand = pod_pv_cand(snap, j) & at_node & active[:, None]
    idx = jnp.where(cand, jnp.arange(V, dtype=jnp.int32)[None, :], V)
    best = jnp.min(idx, axis=1).astype(jnp.int32)
    return jnp.where(best < V, best, -1)
