"""VolumeBinding filter kernel (SURVEY.md §2 C7).

The reference's VolumeBinding plugin (expected
`framework/plugins/volumebinding/` — [UNVERIFIED], mount empty) decides,
per pod per node, whether the pod's PVCs can be satisfied there:

  - a BOUND PVC restricts the pod to nodes satisfying its PV's
    nodeAffinity (zone/hostname-restricted volumes);
  - an UNBOUND WaitForFirstConsumer PVC needs either an available static
    PV (class + capacity + nodeAffinity match) or dynamic provisioning
    whose storage-class allowedTopologies admit the node;
  - a missing PVC or an unbound Immediate-mode PVC makes the pod
    unschedulable (upstream UnschedulableAndUnresolvable).

TPU-native shape: PV nodeAffinity terms compile through the SAME
requirement machinery as pod node-affinity (encoder interns them into
`rq_exprs`), so the per-PV node masks are rows of the shared [Rq, N]
requirement table. The static-candidate test batches into one
[P*MVol, V] x [V, N] matmul; everything is gated on the `has_volumes`
capability flag, so volume-free clusters never trace any of it.

Same-cycle contention for one static PV (two pods, one volume) is NOT
arbitrated in-cycle: upstream binds volumes in PreBind and relies on
bind-failure retry for the loser, and this kernel inherits that contract
(the agent reports the failed bind; the pod requeues).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import labels as labels_ops

_CAP_EPS = 1e-3


def volume_mask(snap, expr_mask: jnp.ndarray) -> jnp.ndarray:  # bool [P, N]
    """Conjunction over each pod's PVC constraints (module docstring)."""
    P, N = snap.P, snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]
    Rq = req.shape[0]
    MVol = snap.pod_vol_mode.shape[1]

    def req_rows(ids):  # i32 [X] -> bool [X, N]; id < 0 -> all-True
        return labels_ops.take_rows(req, ids, True)

    pv_node_ok = req_rows(snap.pv_req_id) & snap.pv_avail[:, None]  # [V, N]

    ok = jnp.ones((P, N), bool)
    for j in range(MVol):
        mode = snap.pod_vol_mode[:, j]  # [P]
        rid = snap.pod_vol_req[:, j]
        cls = snap.pod_vol_class[:, j]
        size = snap.pod_vol_size[:, j]

        rid_rows = req_rows(rid)  # [P, N] (bound PV affinity / dyn topology)

        # static candidates: available PVs of the right class and size,
        # usable on the node
        cand = (
            (snap.pv_class[None, :] == cls[:, None])
            & (snap.pv_capacity[None, :] + _CAP_EPS >= size[:, None])
        )  # [P, V] (availability folded into pv_node_ok)
        static_ok = (
            cand.astype(jnp.float32) @ pv_node_ok.astype(jnp.float32)
        ) > 0.0  # [P, N]

        dyn_ok = jnp.where(
            (rid == -2)[:, None], False, rid_rows
        )  # -1 folds to all-True via req_rows
        row_ok = jnp.where(
            (mode == 0)[:, None],
            rid_rows,
            jnp.where((mode == 1)[:, None], static_ok | dyn_ok, False),
        )
        ok &= jnp.where((mode >= 0)[:, None], row_ok, True)
    return ok
